# Build/test/bench entry points (the reference's Makefile builds its JNI
# native layer, Makefile:66-110; here the native layer is two small ctypes
# libraries that also self-build lazily on first import — `make native`
# just builds them eagerly).

PY ?= python

.PHONY: all native test lint audit audit-smoke check check-smoke race race-smoke verify-fast telemetry-smoke autotune-smoke kernel-search-smoke plan-smoke precision-smoke chaos-smoke health-smoke serve-smoke serve-chaos-smoke fleet-smoke ingest-smoke obs-smoke bench bench-cached bench-smoke cpu-baseline flagship clean

all: native test

native: keystone_tpu/native/_ingest.so keystone_tpu/native/_ngram.so

keystone_tpu/native/_ingest.so: keystone_tpu/native/ingest.cpp
	$(PY) -c "from keystone_tpu.native import ingest; ingest.ensure_built()"
	@touch $@

keystone_tpu/native/_ngram.so: keystone_tpu/native/ngram.cpp
	$(PY) -c "from keystone_tpu.native import ngram; ngram.ensure_built()"
	@touch $@

test:
	$(PY) -m pytest tests/ -q

# Static analysis (keystone_tpu/analysis): rules R1-R5 over the package +
# bench + scripts. Exit is non-zero ONLY for findings not in the ratcheted
# lint_baseline.json — pre-existing debt can't grow, fixed debt is
# reported as stale. Seconds, no backend init.
lint:
	JAX_PLATFORMS=cpu $(PY) -m keystone_tpu.analysis

# IR-level static analysis (keystone_tpu/analysis/ir_audit.py): lower the
# registered entry points (overlap schedulers, solver rungs, Pallas
# kernels + XLA twins, fused DAG segment) to jaxpr + compiled HLO and run
# rules A1-A5. Non-zero exit ONLY for findings not in the ratcheted
# ir_baseline.json. Seconds on the 8-device CPU sim.
audit:
	JAX_PLATFORMS=cpu $(PY) -m keystone_tpu.cli audit

# Two-target audit smoke (<20 s): zero new findings + the JSON output
# schema, the contract `make verify-fast` rides (scripts/audit_smoke.py).
audit-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/audit_smoke.py

# Construction-time pipeline contract checker (keystone_tpu/analysis/
# check.py): propagate (shape, dtype, PartitionSpec) through the
# registered pipeline graphs — no data, no compiles — and run rules
# C1-C5. Non-zero exit ONLY for findings not in the ratcheted
# check_baseline.json. Seconds.
check:
	JAX_PLATFORMS=cpu $(PY) -m keystone_tpu.cli check

# All-pipeline check smoke (<20 s): every registered target clean + the
# JSON schema + the mis-chained-pipeline construction rejection, the
# contract `make verify-fast` rides (scripts/check_smoke.py).
check-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/check_smoke.py

# Lock-discipline static analysis (keystone_tpu/analysis/concurrency.py):
# model every lock creation site, `with <lock>:` span and thread/atexit
# entry point into an acquisition graph and run rules T1-T5 (inversions,
# blocking-under-lock, unguarded shared state, thread lifecycles,
# unlocked read-merge-replace). Non-zero exit ONLY for findings not in
# the ratcheted race_baseline.json. Seconds, no backend init.
race:
	JAX_PLATFORMS=cpu $(PY) -m keystone_tpu.cli race

# Lock-discipline smoke (<20 s): seeded bad fixtures fire every T rule,
# the real tree sweeps clean against the committed baseline with the JSON
# schema intact, and the KEYSTONE_LOCK_WITNESS runtime sanitizer catches
# a replayed PR-15 deadlock while the unset-knob path returns locks
# unchanged (scripts/race_smoke.py).
race-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/race_smoke.py

# Lint + tier-1 + the BENCH_SMOKE bench contract + the telemetry smoke in
# ONE command — the pre-merge loop: the static pass first (it is the
# cheapest failure), then the full (non-slow) test suite on the 8-device
# CPU mesh, a tiny-shape end-to-end bench pass that exercises the
# compact-line / budget-skip / incremental-flush machinery (exactly what
# tests/test_bench_contract.py pins, but visible in your terminal), and a
# tiny traced pipeline run asserting the telemetry contract end to end.
verify-fast: lint
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' -p no:cacheprovider
	BENCH_SMOKE=1 KEYSTONE_BENCH_BUDGET_S=180 $(PY) bench.py
	JAX_PLATFORMS=cpu $(PY) scripts/telemetry_smoke.py
	JAX_PLATFORMS=cpu $(PY) scripts/autotune_smoke.py
	JAX_PLATFORMS=cpu $(PY) scripts/kernel_search_smoke.py
	JAX_PLATFORMS=cpu $(PY) scripts/plan_smoke.py
	JAX_PLATFORMS=cpu $(PY) scripts/audit_smoke.py
	JAX_PLATFORMS=cpu $(PY) scripts/check_smoke.py
	JAX_PLATFORMS=cpu $(PY) scripts/precision_smoke.py
	JAX_PLATFORMS=cpu $(PY) scripts/chaos_smoke.py
	JAX_PLATFORMS=cpu $(PY) scripts/health_smoke.py
	JAX_PLATFORMS=cpu $(PY) scripts/serve_smoke.py
	JAX_PLATFORMS=cpu $(PY) scripts/fleet_smoke.py
	JAX_PLATFORMS=cpu $(PY) scripts/ingest_smoke.py
	JAX_PLATFORMS=cpu $(PY) scripts/obs_smoke.py
	JAX_PLATFORMS=cpu $(PY) scripts/race_smoke.py

# Fleet-observability contract (<20 s): 2 replica workers + driver each
# write a pid+role-unique telemetry shard, merged counter totals exactly
# equal the per-shard sums, a client-minted trace id rides the unix-socket
# frame and stitches into ONE Perfetto trace spanning >= 2 OS processes
# with flow arrows, and the `keystone-tpu obs` CLI renders the dir with
# rc=0 (scripts/obs_smoke.py).
obs-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/obs_smoke.py

# Streaming-ingest contract (<20 s): overlap-on <= overlap-off on a
# calibrated progressive-JPEG tar set, the ring bounds live decoded
# batches (gauge pin) with every buffer recycled, native-vs-fallback
# parity, an injected bad JPEG costing one image not the stream, and a
# worker death whose archive the survivors re-run (scripts/
# ingest_smoke.py).
ingest-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/ingest_smoke.py

# Numerical-health contract (<20 s): KEYSTONE_HEALTH=0 byte-identical to
# the prior program, sentinel trips on an injected NaN block, on-device
# quarantine (warn) and the self-healing escalation ladder (heal) landing
# inside the clean twin's error envelope, malformed KEYSTONE_FAULTS plans
# rejected eagerly (scripts/health_smoke.py).
health-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/health_smoke.py

# Chaos-ladder contract (<20 s): a streaming weighted fit killed
# mid-schedule by an injected KEYSTONE_FAULTS device error resumes from
# its checkpoint on a RESHAPED (8 -> 4 device) CPU-sim mesh and matches
# the uninterrupted twin; truncated checkpoints raise the named
# CheckpointCorruptError (scripts/chaos_smoke.py).
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/chaos_smoke.py

# Serving-gateway contract (<20 s): admission accept/reject at the gate,
# bit-parity vs the unbatched apply with zero steady-state recompiles,
# overload shedding with retry-after while admitted work still serves, a
# poisoned dispatch tripping the breaker and a half-open probe recovering
# it, and a graceful drain (scripts/serve_smoke.py).
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/serve_smoke.py

# Serve chaos ladder (<30 s): KEYSTONE_FAULTS firing at all three serve
# sites under sustained synthetic load plus a mid-run SIGKILL/restart —
# every request gets a response or a structured shed, the breaker
# round-trips open -> half-open -> closed, and the restarted gateway
# serves steady state with zero recompiles (scripts/serve_chaos_smoke.py).
serve-chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/serve_chaos_smoke.py

# Fleet-serving contract (<20 s): 2 replica worker processes x 2 tenants
# — fleet predictions match a locally built deterministic twin (the
# coalesced cross-process batch path vs the single-request apply), a
# concurrent multi-tenant burst serves with zero steady-state recompiles
# summed across the fleet, and both tenants land on the shared stats
# view (scripts/fleet_smoke.py).
fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/fleet_smoke.py

# Precision-tier contract (<20 s): f32 tier byte-identical to the prior
# program, bf16 parity within the documented envelope, and the bf16-sketch
# -> f32-CG composition restoring accuracy (scripts/precision_smoke.py).
precision-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/precision_smoke.py

# Tiny traced pipeline -> counters non-zero, Chrome trace well-formed,
# telemetry-report renders (scripts/telemetry_smoke.py); CPU, seconds.
telemetry-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/telemetry_smoke.py

# Whole-pipeline-optimizer contract end to end: plan a tiny DAG under a
# small binding HBM budget -> fits + planned < hand default, zero re-plans
# on repeat (memo + persisted KEYSTONE_PLAN_CACHE), zero recompiles on the
# planned pipeline's repeat run (scripts/plan_smoke.py); CPU, seconds.
plan-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/plan_smoke.py

# Tile-autotuner contract end to end: tiny interpret-mode sweep -> persisted
# device-keyed cache -> reload with zero re-sweeps -> _pick_tiles consumes
# the winner (scripts/autotune_smoke.py); CPU, seconds.
autotune-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/autotune_smoke.py

# Kernel variant search end to end: tiny interpret-mode sweep of the fused
# conv.pool span's variant space against a throwaway cache -> persisted
# bare + #variant entries -> reload with zero re-sweeps -> fused parity vs
# the split pair (scripts/kernel_search_smoke.py); CPU, <20 s.
kernel-search-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/kernel_search_smoke.py

bench:
	$(PY) bench.py

# Cache/prefetch evidence only: the primary metric plus the cached-vs-cold
# and prefetch-on/off rows (core/cache.py, core/prefetch.py); every other
# secondary block is switched off for a fast loop.
bench-cached:
	BENCH_EXTRAS=0 BENCH_FLAGSHIP=0 BENCH_VOC_REFDIM=0 BENCH_TIMIT_FULL=0 \
	BENCH_MOMENTS=0 BENCH_CONSTANTS=0 BENCH_SERVE=0 BENCH_SERVE_LATENCY=0 \
	BENCH_STAGES=0 \
	$(PY) bench.py

# Tiny-shape end-to-end smoke of the bench contract itself: every shape
# shrunk to CPU scale (BENCH_SMOKE=1), heavy sections off, 180 s budget —
# exercises the incremental-flush / budget-skip / compact-line machinery in
# seconds. The bench-contract tier-1 test runs exactly this.
bench-smoke:
	BENCH_SMOKE=1 KEYSTONE_BENCH_BUDGET_S=180 $(PY) bench.py

cpu-baseline:
	JAX_PLATFORMS=cpu $(PY) scripts/cpu_baseline.py

flagship:
	$(PY) scripts/flagship_imagenet.py --warm

clean:
	rm -f keystone_tpu/native/_ingest.so keystone_tpu/native/_ngram.so \
	      keystone_tpu/native/*.srchash
