"""End-to-end mini runs of the CIFAR and TIMIT pipelines on the CPU mesh."""

import jax.numpy as jnp
import numpy as np

from keystone_tpu.loaders.cifar import load_cifar_binary, synthetic_cifar
from keystone_tpu.pipelines.linear_pixels import LinearPixelsConfig
from keystone_tpu.pipelines.linear_pixels import run as run_linear_pixels
from keystone_tpu.pipelines.random_cifar import RandomCifarConfig
from keystone_tpu.pipelines.random_cifar import run as run_random_cifar
from keystone_tpu.pipelines.random_patch_cifar import RandomPatchCifarConfig
from keystone_tpu.pipelines.random_patch_cifar import run as run_random_patch
from keystone_tpu.pipelines.timit import TimitConfig
from keystone_tpu.pipelines.timit import run as run_timit


def test_cifar_binary_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    n = 5
    records = np.zeros((n, 3073), np.uint8)
    records[:, 0] = np.arange(n)
    records[:, 1:] = rng.integers(0, 256, size=(n, 3072))
    p = tmp_path / "batch.bin"
    p.write_bytes(records.tobytes())
    imgs, labels = load_cifar_binary(str(p))
    assert imgs.shape == (5, 32, 32, 3)
    assert labels.tolist() == [0, 1, 2, 3, 4]
    # channel planes: record layout R plane then G then B, row-major
    assert imgs[0, 0, 0, 0] == float(records[0, 1])  # R(0,0)
    assert imgs[0, 0, 0, 1] == float(records[0, 1 + 1024])  # G(0,0)
    assert imgs[0, 0, 1, 0] == float(records[0, 2])  # R(0,1)


def test_linear_pixels_end_to_end():
    res = run_linear_pixels(
        LinearPixelsConfig(synthetic_train=800, synthetic_test=200)
    )
    assert res["test_error"] < 30.0  # synthetic prototypes are separable


def test_random_cifar_end_to_end():
    res = run_random_cifar(
        RandomCifarConfig(
            num_filters=16, synthetic_train=400, synthetic_test=120, lam=10.0
        )
    )
    assert res["test_error"] < 25.0


def test_random_patch_cifar_end_to_end():
    res = run_random_patch(
        RandomPatchCifarConfig(
            num_filters=16,
            whitener_size=2000,
            synthetic_train=400,
            synthetic_test=120,
            lam=10.0,
        )
    )
    assert res["test_error"] < 25.0


def test_timit_end_to_end_streaming():
    res = run_timit(
        TimitConfig(
            num_cosines=3,
            num_cosine_features=256,
            num_epochs=2,
            lam=10.0,
            gamma=0.02,  # bandwidth matched to the synthetic prototype task
            synthetic_train=3000,
            synthetic_test=400,
        )
    )
    assert res["test_error"] < 15.0
