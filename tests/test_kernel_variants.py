"""Generated kernel variants vs the XLA twins, and the search protocol
(``ops/pallas/variants.py`` + ``ops/pallas/extraction.py``).

Three contracts pinned here:

- PARITY: every variant in every kernel's declared space matches the
  untouched XLA twin on odd / tile-straddling shapes, at BOTH precision
  tiers (f32 bit-envelope; bf16 within the storage-rounding envelope) —
  a generated kernel may win on measured speed, never on wrong answers.
- CACHE MIGRATION: pre-variant tile-only cache entries keep serving as
  the default variant's winners (bare bucket = default variant), while
  entries naming an UNKNOWN ``#variant`` are pruned on load and never
  shadow a real winner.
- WINNER SELECTION: a challenger variant serves only when both it and
  the default carry a persisted measured latency and the challenger's is
  strictly smaller; a variant failing the validation gate is never
  swept, never recorded, never served; after one full sweep a reload
  performs ZERO re-sweeps (the contract ``tests/test_autotune.py`` pins
  for tiles, extended across the variant axis).

Counter assertions are DELTAS against the shared process registry, same
discipline as ``tests/test_autotune.py``.
"""

import json

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.learning.gmm import GaussianMixtureModel
from keystone_tpu.ops.images import fisher_vector as FV
from keystone_tpu.ops.images.convolver import Convolver
from keystone_tpu.ops.images.pooler import Pooler
from keystone_tpu.ops.images.sift import _dsift_single_scale
from keystone_tpu.ops.pallas import autotune, variants
from keystone_tpu.ops.pallas import extraction as E
from keystone_tpu.telemetry import get_registry

TIERS = ("f32", "bf16")


def _count(name: str) -> float:
    return sum(get_registry().counters(name).values())


def _rel_close(a, b, tol):
    a, b = np.asarray(a), np.asarray(b)
    denom = np.max(np.abs(b)) + 1e-9
    np.testing.assert_allclose(a / denom, b / denom, atol=tol)


def _tol(tier: str) -> float:
    return variants.PARITY_TOL[tier]


@pytest.fixture()
def tuner_cache(tmp_path, monkeypatch):
    path = tmp_path / "autotune_cache.json"
    monkeypatch.setenv("KEYSTONE_AUTOTUNE_CACHE", str(path))
    autotune.clear_memory_cache()
    yield path
    autotune.clear_memory_cache()


# --------------------------------------------------------------------------
# key composition
# --------------------------------------------------------------------------


def test_variant_bucket_composition():
    """``"<shape>[@tier][#variant]"``: default variants keep the bare
    bucket (pre-variant entries stay valid winners); the variant suffix
    joins LAST, after the precision tier; typos raise instead of minting
    a cache partition nobody will ever serve."""
    for kernel, space in variants.VARIANT_SPACES.items():
        assert variants.known_variants(kernel) == space
        assert variants.default_variant(kernel) == space[0]
        assert variants.variant_bucket("64x64", kernel, space[0]) == "64x64"
    assert variants.variant_bucket("64x64", "conv.norm", "xy") == "64x64#xy"
    assert (
        variants.variant_bucket("32x32@bf16", "conv.pool", "fused.yx")
        == "32x32@bf16#fused.yx"
    )
    with pytest.raises(ValueError):
        variants.variant_bucket("b", "conv.norm", "zz")
    with pytest.raises(ValueError):
        variants.known_variants("no.such.kernel")


# --------------------------------------------------------------------------
# parity: every variant vs the XLA twin, odd shapes, both tiers
# --------------------------------------------------------------------------


@pytest.mark.parametrize("tier", TIERS)
def test_sift_stack_variant_matches_matmul_twin(tier):
    rng = np.random.default_rng(20)
    imgs = jnp.asarray(rng.uniform(0, 1, (2, 37, 53)).astype(np.float32))
    args = (3, 4, 9, 37, 53)
    d_ref, m_ref = _dsift_single_scale(imgs, *args, "matmul")
    d_out, m_out = _dsift_single_scale(imgs, *args, "pallas", 16, tier,
                                       "stack")
    _rel_close(d_out, d_ref, _tol(tier))
    _rel_close(m_out, m_ref, _tol(tier))


@pytest.mark.parametrize("tier", TIERS)
def test_fv_joint_variant_matches_f32_twin(tier, monkeypatch):
    """The joint (Kp, 2d) moment matmul through the full FV dispatch path
    (plan monkeypatched to force the variant; the lazy import inside
    ``_fv_cols_batch_pallas`` re-reads the extraction module attribute)."""
    rng = np.random.default_rng(21)
    k, d, nd = 8, 12, 37  # nd indivisible by every tile candidate
    gmm = GaussianMixtureModel(
        means=jnp.asarray(rng.normal(size=(k, d)).astype(np.float32)),
        variances=jnp.asarray(
            rng.uniform(0.5, 2.0, (k, d)).astype(np.float32)
        ),
        weights=jnp.asarray(rng.dirichlet(np.ones(k)).astype(np.float32)),
    )
    x = jnp.asarray(rng.normal(size=(3, nd, d)).astype(np.float32))
    ref = FV._fv_cols_batch_f32(x, gmm, 0, 2 * k)
    monkeypatch.setenv("KEYSTONE_PRECISION_TIER", tier)
    monkeypatch.setattr(E, "fv_encode_plan", lambda *a, **kw: ("joint", 16))
    out = FV._fv_cols_batch_pallas(x, gmm, 0, 2 * k)
    assert out.shape == ref.shape
    _rel_close(out, ref, _tol(tier))


@pytest.mark.parametrize("tier", TIERS)
def test_conv_xy_variant_matches_xla_twin(tier):
    rng = np.random.default_rng(22)
    k, c, nf = 5, 3, 7  # odd nf -> filter-tile padding engages
    imgs = jnp.asarray(rng.uniform(0, 1, (2, 17, 19, c)).astype(np.float32))
    filters = jnp.asarray(
        rng.normal(size=(nf, k * k * c)).astype(np.float32)
    )
    conv = Convolver(filters=filters, num_channels=c, normalize_patches=True)
    ref = conv._apply_batch_xla(imgs)
    out = E.conv_norm(
        imgs, filters, num_channels=c, normalize=True, var_constant=10.0,
        tile_f=64, interpret=True, tier=tier, variant="xy",
    )
    assert out.shape == ref.shape
    _rel_close(out, ref, _tol(tier))


@pytest.mark.parametrize("tier", TIERS)
def test_pool_wh_variant_matches_xla_twin(tier, monkeypatch):
    rng = np.random.default_rng(23)
    imgs = jnp.asarray(rng.normal(size=(2, 13, 11, 5)).astype(np.float32))
    pool = Pooler(stride=3, pool_size=5, pool="sum")
    monkeypatch.delenv("KEYSTONE_PALLAS", raising=False)
    ref = pool.apply_batch(imgs)  # the XLA twin (kernel is explicit-only)
    out = E.pool_sum(imgs, 3, 5, None, tile_c=8, interpret=True, tier=tier,
                     variant="wh")
    assert out.shape == ref.shape
    _rel_close(out, ref, _tol(tier))


@pytest.mark.parametrize("variant", ["split", "fused.yx", "fused.xy"])
@pytest.mark.parametrize("tier", TIERS)
def test_conv_pool_variants_match_xla_twin_pair(tier, variant, monkeypatch):
    """The fusion span vs the untouched two-stage XLA reference (conv twin
    through HBM, then pool twin), with the filter axis STRADDLING two
    64-wide tiles (nf=70) and odd image geometry — ragged tiles, lane
    padding and the pooled-block trim all engage."""
    rng = np.random.default_rng(24)
    k, c, nf = 3, 3, 70
    imgs = jnp.asarray(rng.uniform(0, 1, (2, 11, 13, c)).astype(np.float32))
    filters = jnp.asarray(
        rng.normal(size=(nf, k * k * c)).astype(np.float32)
    )
    conv = Convolver(filters=filters, num_channels=c, normalize_patches=True)
    pool = Pooler(stride=2, pool_size=3, pool="sum")
    monkeypatch.delenv("KEYSTONE_PALLAS", raising=False)
    ref = pool.apply_batch(conv._apply_batch_xla(imgs))
    out = E.conv_norm_pool(
        imgs, filters, num_channels=c, normalize=True, var_constant=10.0,
        stride=2, pool_size=3, tile_f=64, interpret=True, tier=tier,
        variant=variant,
    )
    assert out.shape == ref.shape
    _rel_close(out, ref, _tol(tier))


def test_conv_pool_fused_equals_split_bit_envelope():
    """The acceptance headline: at f32 the fused kernel is bit-envelope
    equivalent to the split pair (same arithmetic, same order — only the
    HBM round-trip is removed)."""
    rng = np.random.default_rng(25)
    imgs = jnp.asarray(rng.uniform(0, 1, (2, 14, 14, 3)).astype(np.float32))
    filters = jnp.asarray(rng.normal(size=(7, 75)).astype(np.float32))
    kw = dict(num_channels=3, normalize=True, var_constant=10.0, stride=2,
              pool_size=3, tile_f=64, interpret=True)
    split = E.conv_norm_pool(imgs, filters, variant="split", **kw)
    for variant in ("fused.yx", "fused.xy"):
        fused = E.conv_norm_pool(imgs, filters, variant=variant, **kw)
        _rel_close(fused, split, 2e-5)


# --------------------------------------------------------------------------
# cache migration: pre-variant entries serve, unknown variants are pruned
# --------------------------------------------------------------------------


def test_pre_variant_tile_only_entry_still_serves_default(
    tuner_cache, monkeypatch
):
    """A cache written BEFORE the variant search existed (bare bucket,
    tile winner only) must keep serving — as the default variant, with
    zero sweeps and zero validation."""
    monkeypatch.delenv("KEYSTONE_AUTOTUNE", raising=False)
    bucket = autotune.precision_bucket(autotune.shape_bucket(16, 16, 7),
                                      "f32")
    tuner_cache.write_text(json.dumps({
        "version": 1,
        "devices": {autotune.device_key(): {
            "conv.norm": {bucket: {"value": 64, "us": 10.0, "swept": 2}},
        }},
    }))
    autotune.clear_memory_cache()
    s0 = _count("autotune.sweep")
    variant, tile = E.conv_norm_plan(16, 16, 3, 3, 7, allow_sweep=False)
    assert (variant, tile) == ("yx", 64)
    assert _count("autotune.sweep") == s0


def test_unknown_variant_and_tier_entries_pruned_known_survive(tuner_cache):
    dev = autotune.device_key()
    tuner_cache.write_text(json.dumps({
        "version": 1,
        "devices": {dev: {"conv.norm": {
            "64x64": {"value": 128, "us": 5.0},
            "64x64#xy": {"value": 64, "us": 4.0},
            "64x64@bf16#xy": {"value": 64, "us": 3.0},
            "64x64#bogus": {"value": 8, "us": 0.1},      # unknown variant
            "64x64@f16": {"value": 8, "us": 0.1},        # unknown tier
            "64x64@f16#xy": {"value": 8, "us": 0.1},
        }, "made.up.kernel": {
            "8x8#xy": {"value": 8, "us": 0.1},           # no declared space
        }}},
    }))
    autotune.clear_memory_cache()
    assert autotune.lookup("conv.norm", "64x64") == 128
    assert autotune.lookup("conv.norm", "64x64#xy") == 64
    assert autotune.lookup("conv.norm", "64x64@bf16#xy") == 64
    assert autotune.lookup("conv.norm", "64x64#bogus") is None
    assert autotune.lookup("conv.norm", "64x64@f16") is None
    assert autotune.lookup("conv.norm", "64x64@f16#xy") is None
    assert autotune.lookup("made.up.kernel", "8x8#xy") is None
    # a pruned phantom cannot shadow: search at this bucket arbitrates
    # over the surviving entries only
    variant, value = variants.search("conv.norm", "64x64", (64, 128), 128)
    assert (variant, value) == ("xy", 64)


# --------------------------------------------------------------------------
# winner selection: measured-winner protocol across variants
# --------------------------------------------------------------------------


def test_challenger_needs_strictly_smaller_measured_us(
    tuner_cache, monkeypatch
):
    monkeypatch.delenv("KEYSTONE_AUTOTUNE", raising=False)
    autotune.record("pool.sum", "64x64", 128, micros=100.0, swept=2)
    # challenger without a measured us: the default serves
    autotune.record("pool.sum", "64x64#wh", 64, micros=None, swept=1)
    assert variants.search("pool.sum", "64x64", (64, 128), 128) \
        == ("hw", 128)
    # slower challenger: the default serves
    autotune.record("pool.sum", "64x64#wh", 64, micros=150.0, swept=1)
    assert variants.search("pool.sum", "64x64", (64, 128), 128) \
        == ("hw", 128)
    # strictly faster challenger: it serves
    autotune.record("pool.sum", "64x64#wh", 64, micros=50.0, swept=1)
    assert variants.search("pool.sum", "64x64", (64, 128), 128) \
        == ("wh", 64)
    # ... but an out-of-candidates winner value is skipped (same guard as
    # resolve: a tile swept at the small end of the bucket may not fit)
    assert variants.search("pool.sum", "64x64", (128,), 128) == ("hw", 128)


def test_unmeasured_default_serves_even_against_measured_challenger(
    tuner_cache, monkeypatch
):
    """No measured incumbent -> nothing to beat: a challenger may only win
    a MEASURED comparison, never by default."""
    monkeypatch.delenv("KEYSTONE_AUTOTUNE", raising=False)
    autotune.record("pool.sum", "32x32", 128, swept=0)  # no us
    autotune.record("pool.sum", "32x32#wh", 64, micros=5.0, swept=1)
    assert variants.search("pool.sum", "32x32", (64, 128), 128) \
        == ("hw", 128)


def test_rejected_variant_never_swept_recorded_or_served(
    tuner_cache, monkeypatch
):
    monkeypatch.setenv("KEYSTONE_AUTOTUNE", "1")
    measured = []

    def measure_for(name):
        def measure(cand, reps):
            measured.append((name, cand))
            return 0.01 * reps
        return measure

    s0 = _count("autotune.sweep")
    variant, value = variants.search(
        "pool.sum", "8x8", (8, 16), 8,
        measure_for=measure_for, validate_for=lambda name: False,
    )
    assert variant == "hw"
    assert all(name == "hw" for name, _ in measured)  # default swept only
    assert autotune.peek_entry("pool.sum", "8x8#wh") is None
    assert _count("autotune.sweep") == s0 + 1


def test_validate_variant_counts_and_gates():
    reg = get_registry()
    v0 = sum(reg.counters("variants.validated").values())
    r0 = sum(reg.counters("variants.rejected").values())
    ok = lambda: jnp.ones((3,))
    assert variants.validate_variant("pool.sum", "wh", ok, ok, tol=1e-6)
    assert sum(reg.counters("variants.validated").values()) == v0 + 1
    # parity failure
    assert not variants.validate_variant(
        "pool.sum", "wh", lambda: 2.0 * ok(), ok, tol=1e-6
    )
    # NaN is a failure, not a vacuous pass
    assert not variants.validate_variant(
        "pool.sum", "wh", lambda: jnp.full((3,), jnp.nan), ok, tol=1e-6
    )
    # a variant that cannot even run is rejected, not fatal
    def boom():
        raise RuntimeError("unlowerable")
    assert not variants.validate_variant("pool.sum", "wh", boom, ok,
                                         tol=1e-6)
    assert sum(reg.counters("variants.rejected").values()) == r0 + 3


def test_variants_knob_off_restricts_sweep_to_default_grid(
    tuner_cache, monkeypatch
):
    """KEYSTONE_AUTOTUNE_VARIANTS=0 under KEYSTONE_AUTOTUNE=1: only the
    default variant's tile grid sweeps — but a PERSISTED variant winner
    still serves (the knob gates sweeping, not serving)."""
    monkeypatch.setenv("KEYSTONE_AUTOTUNE", "1")
    monkeypatch.setenv("KEYSTONE_AUTOTUNE_VARIANTS", "0")
    measured = []

    def measure_for(name):
        def measure(cand, reps):
            measured.append((name, cand))
            return (0.01 if name == "hw" else 0.001) * reps
        return measure

    def never(name):
        raise AssertionError("validated a variant with the knob off")

    variant, value = variants.search(
        "pool.sum", "4x4", (8, 16), 8,
        measure_for=measure_for, validate_for=never,
    )
    assert variant == "hw"
    assert all(name == "hw" for name, _ in measured)
    assert autotune.peek_entry("pool.sum", "4x4#wh") is None
    # persisted challenger from a prior full sweep still serves
    autotune.record("pool.sum", "4x4#wh", 16, micros=1.0, swept=2)
    assert variants.search(
        "pool.sum", "4x4", (8, 16), 8,
        measure_for=measure_for, validate_for=never,
    ) == ("wh", 16)


def test_full_search_persists_then_reload_zero_resweeps(
    tuner_cache, monkeypatch
):
    """The zero-re-sweeps contract across the variant axis: one full sweep
    (default + challenger), then a fresh process against the persisted
    file serves the measured winner with no measurement at all."""
    monkeypatch.setenv("KEYSTONE_AUTOTUNE", "1")
    measured = []

    def measure_for(name):
        def measure(cand, reps):
            measured.append((name, cand))
            base = {"hw": 0.02, "wh": 0.005}[name]
            return base * reps
        return measure

    s0 = _count("autotune.sweep")
    variant, value = variants.search(
        "pool.sum", "16x16", (8, 16), 8,
        measure_for=measure_for, validate_for=lambda name: True,
    )
    assert variant == "wh"  # the measured winner
    assert _count("autotune.sweep") == s0 + 2  # bare + #wh, once each
    assert {n for n, _ in measured} == {"hw", "wh"}

    measured.clear()
    autotune.clear_memory_cache()  # the fresh-process case
    assert variants.search(
        "pool.sum", "16x16", (8, 16), 8,
        measure_for=measure_for, validate_for=lambda name: True,
    ) == (variant, value)
    assert not measured, "a persisted variant winner was re-swept"
    assert _count("autotune.sweep") == s0 + 2
