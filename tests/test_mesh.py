import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.dataset import pad_rows
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.parallel import (
    data_axis_size,
    distribute,
    make_mesh,
    replicate,
    shard_rows,
    use_mesh,
)


def test_make_mesh_shapes(devices):
    mesh = make_mesh()
    assert mesh.shape == {"data": 8, "model": 1}
    mesh2 = make_mesh(data=4, model=2)
    assert mesh2.shape == {"data": 4, "model": 2}


def test_pad_rows():
    x = jnp.ones((10, 3))
    padded, mask = pad_rows(x, 8)
    assert padded.shape == (16, 3)
    assert float(mask.sum()) == 10.0


def test_distribute_shards_rows(devices):
    mesh = make_mesh()
    with use_mesh(mesh):
        ds = distribute(jnp.arange(20.0).reshape(10, 2))
        assert ds.num_items == 16
        assert ds.num_valid == 10
        shard_shapes = {s.data.shape for s in ds.data.addressable_shards}
        assert shard_shapes == {(2, 2)}


def test_sharded_scaler_matches_local(devices, rng):
    """Masked, mesh-sharded moments == local numpy moments: the treeAggregate
    replacement is exact."""
    x = rng.normal(size=(21, 4)).astype(np.float32)
    mesh = make_mesh()
    with use_mesh(mesh):
        ds = distribute(jnp.asarray(x))
        model = StandardScaler().fit(ds)
    np.testing.assert_allclose(np.asarray(model.mean), x.mean(axis=0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(model.std), x.std(axis=0, ddof=1), rtol=1e-4)


def test_replicate(devices):
    mesh = make_mesh()
    with use_mesh(mesh):
        w = replicate(jnp.ones((4, 4)))
    assert w.sharding.is_fully_replicated
