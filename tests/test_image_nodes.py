"""Image node tests: naive im2col implementations of the reference semantics
(Convolver.scala makePatches, Pooler.scala, Windower.scala) vs the XLA ops."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.learning import ZCAWhitenerEstimator
from keystone_tpu.ops.images import (
    Convolver,
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
    Pooler,
    SymmetricRectifier,
    Windower,
)


def naive_patches(img, k):
    """All k×k patches in the reference layout: rows indexed (x + y*resW),
    patch vector ordered (poy, pox, chan) channel-fastest.
    With our (H=y, W=x, C) arrays: row index = x + y*resW, vector =
    img[y+poy, x+pox, c] flattened (poy, pox, c)."""
    h, w, c = img.shape
    rh, rw = h - k + 1, w - k + 1
    rows = np.zeros((rh * rw, k * k * c), np.float64)
    for y in range(rh):
        for x in range(rw):
            rows[x + y * rw] = img[y : y + k, x : x + k, :].reshape(-1)
    return rows, rh, rw


def naive_normalize_rows(mat, alpha):
    mu = mat.mean(axis=1, keepdims=True)
    var = ((mat - mu) ** 2).sum(axis=1, keepdims=True) / (mat.shape[1] - 1)
    return (mat - mu) / np.sqrt(var + alpha)


@pytest.mark.parametrize("normalize", [False, True])
def test_convolver_matches_naive_im2col(rng, normalize):
    img = rng.normal(size=(8, 8, 3)).astype(np.float32)
    filters = rng.normal(size=(5, 4 * 4 * 3)).astype(np.float32)
    conv = Convolver(
        filters=jnp.asarray(filters),
        num_channels=3,
        normalize_patches=normalize,
        var_constant=10.0,
    )
    out = np.asarray(conv.serve(jnp.asarray(img)))  # (resH, resW, nF)
    patches, rh, rw = naive_patches(img.astype(np.float64), 4)
    if normalize:
        patches = naive_normalize_rows(patches, 10.0)
    expected = patches @ filters.astype(np.float64).T  # (rh*rw, nF)
    # our (resH, resW) layout: row index x + y*rw
    got = out.reshape(rh * rw, -1)
    np.testing.assert_allclose(got, expected, atol=1e-3)


def test_convolver_whitener_mean_subtraction(rng):
    img = rng.normal(size=(6, 6, 2)).astype(np.float32)
    filters = rng.normal(size=(3, 3 * 3 * 2)).astype(np.float32)
    sample = rng.normal(size=(50, 18)).astype(np.float32)
    whitener = ZCAWhitenerEstimator(eps=0.1).fit_single(jnp.asarray(sample))
    conv = Convolver(
        filters=jnp.asarray(filters),
        whitener=whitener,
        num_channels=2,
        normalize_patches=True,
    )
    out = np.asarray(conv.serve(jnp.asarray(img)))
    patches, rh, rw = naive_patches(img.astype(np.float64), 3)
    patches = naive_normalize_rows(patches, 10.0)
    patches = patches - np.asarray(whitener.means)
    expected = (patches @ filters.astype(np.float64).T).reshape(rh, rw, -1)
    np.testing.assert_allclose(out, expected, atol=1e-3)


def test_convolver_batch_matches_single(rng):
    imgs = rng.normal(size=(4, 8, 8, 3)).astype(np.float32)
    filters = rng.normal(size=(2, 2 * 2 * 3)).astype(np.float32)
    conv = Convolver(filters=jnp.asarray(filters), num_channels=3)
    batch = np.asarray(conv(jnp.asarray(imgs)))
    single = np.asarray(conv.serve(jnp.asarray(imgs[1])))
    np.testing.assert_allclose(batch[1], single, atol=1e-5)


def test_pooler_sum_hand_computed():
    """4×4 image, poolSize=2, stride=2 -> strideStart=1, pools at 1,3 clamped.
    Reference PoolingSuite.scala:11-30 analog."""
    img = jnp.arange(16.0).reshape(4, 4, 1)
    out = np.asarray(Pooler(stride=2, pool_size=2, pool="sum").serve(img))
    # pools: windows starting at 0 and 2 (stride 2, pad right 0): [0:2], [2:4]
    expected = np.array(
        [
            [img[0:2, 0:2, 0].sum(), img[0:2, 2:4, 0].sum()],
            [img[2:4, 0:2, 0].sum(), img[2:4, 2:4, 0].sum()],
        ]
    )
    np.testing.assert_allclose(out[:, :, 0], expected)


def test_pooler_clamped_edge_window():
    """27×27 (CIFAR post-conv), poolSize=14, stride=13: 2 pools per dim, the
    second window [13:27) is clamped — matches reference geometry."""
    img = jnp.ones((27, 27, 2))
    out = np.asarray(Pooler(stride=13, pool_size=14, pool="sum").serve(img))
    assert out.shape == (2, 2, 2)
    np.testing.assert_allclose(out[0, 0], 14 * 14)
    np.testing.assert_allclose(out[1, 1], 14 * 14)  # pad contributes 0 to sum


def test_pooler_max_with_pixel_function():
    img = jnp.array([[-5.0, 2.0], [3.0, -1.0]]).reshape(2, 2, 1)
    out = Pooler(stride=1, pool_size=2, pixel_function=jnp.abs, pool="max").serve(img)
    assert float(out[0, 0, 0]) == 5.0


def test_windower_matches_naive(rng):
    imgs = rng.normal(size=(2, 5, 5, 3)).astype(np.float32)
    w = Windower(stride=2, window_size=3)
    out = np.asarray(w(jnp.asarray(imgs)))
    assert out.shape == (2 * 2 * 2, 3, 3, 3)
    # first image, window (y=0,x=0); ordering row-major over (ny, nx)
    np.testing.assert_allclose(out[0], imgs[0, 0:3, 0:3, :])
    np.testing.assert_allclose(out[1], imgs[0, 0:3, 2:5, :])
    np.testing.assert_allclose(out[4], imgs[1, 0:3, 0:3, :])


def test_symmetric_rectifier_doubles_channels():
    img = jnp.array([[[1.0, -2.0]]])
    out = np.asarray(SymmetricRectifier(alpha=0.25).serve(img))
    np.testing.assert_allclose(out, [[[0.75, 0.0, 0.0, 1.75]]])


def test_grayscaler_ntsc():
    img = jnp.array([[[1.0, 0.5, 0.25]]])  # R, G, B
    out = float(GrayScaler().serve(img)[0, 0, 0])
    assert abs(out - (0.2989 * 1.0 + 0.587 * 0.5 + 0.114 * 0.25)) < 1e-6
    out_bgr = float(GrayScaler(channel_order="bgr").serve(img)[0, 0, 0])
    assert abs(out_bgr - (0.114 * 1.0 + 0.587 * 0.5 + 0.2989 * 0.25)) < 1e-6


def test_pixel_scaler_and_vectorizer():
    img = jnp.full((2, 2, 3), 255.0)
    assert float(PixelScaler().serve(img).max()) == 1.0
    v = ImageVectorizer().serve(img)
    assert v.shape == (12,)


def test_zca_whitened_covariance_is_identity(rng):
    """Reference ZCAWhiteningSuite.scala:16-33: whitened covariance ≈ I."""
    x = rng.normal(size=(500, 8)).astype(np.float32) @ rng.normal(
        size=(8, 8)
    ).astype(np.float32)
    zca = ZCAWhitenerEstimator(eps=1e-6).fit_single(jnp.asarray(x))
    white = np.asarray(zca(jnp.asarray(x)))
    cov = white.T @ white / (x.shape[0] - 1)
    np.testing.assert_allclose(cov, np.eye(8), atol=5e-2)


def test_image_utils_functional_layer():
    """ImageUtils equivalents: split/combine/map round-trips and grayscale.

    Reference: ``utils/images/ImageUtils.scala`` splitChannels (:282-303),
    pixelCombine (:127-151), mapPixels (:97-116), toGrayScale (:55-87).
    """
    from keystone_tpu.ops.images import (
        map_pixels,
        pixel_combine,
        split_channels,
        to_grayscale,
    )

    rng = np.random.default_rng(0)
    img = jnp.asarray(rng.uniform(size=(8, 6, 3)).astype(np.float32))

    chans = split_channels(img)
    assert len(chans) == 3 and chans[0].shape == (8, 6, 1)
    resum = pixel_combine(pixel_combine(chans[0], chans[1]), chans[2])
    np.testing.assert_allclose(
        np.asarray(resum)[..., 0], np.asarray(img).sum(-1), rtol=1e-6
    )

    doubled = map_pixels(img, lambda p: p * 2.0)
    np.testing.assert_allclose(np.asarray(doubled), 2 * np.asarray(img), rtol=1e-6)

    gray = to_grayscale(img)
    assert gray.shape == (8, 6, 1)
    expect = np.asarray(img) @ np.array([0.2989, 0.5870, 0.1140], np.float32)
    np.testing.assert_allclose(np.asarray(gray)[..., 0], expect, rtol=1e-5)


def test_classification_error_matches_err_percent():
    from keystone_tpu.utils import classification_error, get_err_percent

    pred = np.array([0, 1, 2, 1])
    act = np.array([0, 1, 1, 1])
    assert classification_error(pred, act) == pytest.approx(0.25)
    assert get_err_percent(pred, act) == pytest.approx(25.0)
