"""Pallas fused GMM-moments kernel vs the XLA formulation.

The kernel (``ops/pallas/moments.py``) runs in interpreter mode on the CPU
test mesh; on TPU the same code path compiles. Tolerances are loose-ish
(2e-3 relative) because the kernel evaluates the log-density in its expanded
affine form ``x@A + x²@B + c`` (MXU-shaped) which loses a few digits to
cancellation vs the direct ``(x-μ)²`` form — the same trade the float C++
enceval EM made (reference ``src/main/cpp/EncEval.cxx:122-180``).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from keystone_tpu.learning.gmm import GaussianMixtureModelEstimator
from keystone_tpu.ops.pallas.moments import gmm_moments, gmm_moments_xla


def _random_gmm(rng, k, d):
    means = rng.normal(size=(k, d)).astype(np.float32)
    variances = rng.uniform(0.5, 2.0, size=(k, d)).astype(np.float32)
    weights = rng.dirichlet(np.ones(k)).astype(np.float32)
    return means, variances, weights


def _assert_close(a, b, rtol=2e-3):
    a, b = np.asarray(a), np.asarray(b)
    denom = np.max(np.abs(b)) + 1e-9
    np.testing.assert_allclose(a / denom, b / denom, atol=rtol)


@pytest.mark.parametrize("n,d,k", [(700, 37, 10), (513, 64, 16), (100, 5, 3)])
def test_moments_match_xla(n, d, k):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    means, variances, weights = _random_gmm(rng, k, d)
    w = rng.uniform(0.0, 1.0, size=(n,)).astype(np.float32)

    ref = gmm_moments_xla(x, means, variances, weights, w)
    out = gmm_moments(x, means, variances, weights, w)
    for a, b in zip(out, ref):
        assert a.shape == b.shape
        _assert_close(a, b)


def test_moments_unweighted_qsum_totals_n():
    rng = np.random.default_rng(1)
    n, d, k = 300, 16, 4
    x = rng.normal(size=(n, d)).astype(np.float32)
    means, variances, weights = _random_gmm(rng, k, d)
    qsum, _, _ = gmm_moments(x, means, variances, weights)
    # posteriors sum to one per row; qsum totals the (unpadded) row count
    assert abs(float(jnp.sum(qsum)) - n) < 1e-2


def test_moments_mask_excludes_rows():
    rng = np.random.default_rng(2)
    n, d, k = 200, 8, 5
    x = rng.normal(size=(n, d)).astype(np.float32)
    means, variances, weights = _random_gmm(rng, k, d)
    mask = (np.arange(n) < 120).astype(np.float32)

    masked = gmm_moments(x, means, variances, weights, mask)
    truncated = gmm_moments(x[:120], means, variances, weights)
    for a, b in zip(masked, truncated):
        _assert_close(a, b)


def test_moments_far_from_origin_precision():
    """SIFT-scale uncentered data (values ~100±small): the centered affine
    form must match a float64 direct-Mahalanobis oracle — the regime where
    the uncentered x@A + x²@B expansion loses whole digits to cancellation."""
    rng = np.random.default_rng(7)
    n, d, k = 600, 32, 8
    means = (rng.normal(size=(k, d)) * 3.0 + 100.0).astype(np.float32)
    variances = rng.uniform(0.05, 0.5, size=(k, d)).astype(np.float32)
    weights = rng.dirichlet(np.ones(k)).astype(np.float32)
    comp = rng.integers(0, k, size=n)
    x = (means[comp] + np.sqrt(variances[comp]) * rng.normal(size=(n, d))).astype(
        np.float32
    )

    # float64 oracle, direct (x-mu)^2 form
    x64, m64, v64 = x.astype(np.float64), means.astype(np.float64), variances.astype(np.float64)
    mahal = ((x64[:, None, :] - m64[None]) ** 2 / v64[None]).sum(2)
    ll = (
        np.log(weights.astype(np.float64))[None]
        - 0.5 * (d * np.log(2 * np.pi) + np.log(v64).sum(1))[None]
        - 0.5 * mahal
    )
    q = np.exp(ll - ll.max(1, keepdims=True))
    q /= q.sum(1, keepdims=True)
    oracle = (q.sum(0), q.T @ x64, q.T @ (x64 * x64))

    for impl, out in [
        ("pallas", gmm_moments(x, means, variances, weights)),
        ("xla", gmm_moments_xla(x, means, variances, weights)),
    ]:
        for a, b, nm in zip(out, oracle, ("qsum", "qx", "qx2")):
            denom = np.max(np.abs(b)) + 1e-9
            np.testing.assert_allclose(
                np.asarray(a) / denom, b / denom, atol=2e-3,
                err_msg=f"{impl}:{nm}",
            )


def test_moments_auto_chunked_matches_single(monkeypatch):
    """The lax.scan chunked path (large-n branch of gmm_moments_auto) equals
    the single-program path."""
    import keystone_tpu.ops.pallas.moments as M

    rng = np.random.default_rng(4)
    n, d, k = 1000, 12, 6
    x = rng.normal(size=(n, d)).astype(np.float32)
    means, variances, weights = _random_gmm(rng, k, d)
    w = rng.uniform(0.0, 1.0, size=(n,)).astype(np.float32)

    single = M.gmm_moments_xla(x, means, variances, weights, w)
    monkeypatch.setattr(M, "_CHUNK_ROWS", 256)  # force chunking (n=1000 -> 4 chunks)
    chunked = M.gmm_moments_auto(x, means, variances, weights, w)
    for a, b in zip(chunked, single):
        _assert_close(a, b, rtol=1e-5)


def test_gmm_estimator_pallas_matches_xla_fit():
    """Planted two-component mixture: both implementations recover it."""
    rng = np.random.default_rng(3)
    c0 = rng.normal(loc=-3.0, scale=0.5, size=(400, 6))
    c1 = rng.normal(loc=+3.0, scale=0.5, size=(400, 6))
    x = np.concatenate([c0, c1]).astype(np.float32)

    fits = {}
    for impl in ("xla", "pallas"):
        gmm = GaussianMixtureModelEstimator(
            k=2, num_iter=20, implementation=impl
        ).fit(x)
        order = np.argsort(np.asarray(gmm.means)[:, 0])
        fits[impl] = np.asarray(gmm.means)[order]
        np.testing.assert_allclose(
            fits[impl], [[-3.0] * 6, [3.0] * 6], atol=0.15
        )
    np.testing.assert_allclose(fits["pallas"], fits["xla"], atol=0.02)


def test_sep_kernel_matches_xla(rng):
    """The copy-free separate-input kernel (the auto path's large-n TPU arm)
    must agree with the XLA formulation, weighted and unweighted, including
    ragged row counts (tile padding)."""
    from keystone_tpu.ops.pallas.moments import gmm_moments_sep, gmm_moments_xla

    for n, d, k in ((700, 13, 5), (1030, 64, 16)):
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32) * 3 + 1)
        means = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
        var = jnp.asarray(rng.random((k, d)).astype(np.float32) + 0.3)
        w = jnp.asarray(rng.random(k).astype(np.float32))
        w = w / w.sum()
        rw = jnp.asarray(rng.random(n).astype(np.float32))
        for row_w in (None, rw):
            ref = gmm_moments_xla(x, means, var, w, row_w)
            got = gmm_moments_sep(x, means, var, w, row_w)
            for a, b in zip(got, ref):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
                )
