"""End-to-end mini-pipeline test (the reference's StupidBackoffSuite-style
full-fit-path category, SURVEY.md §4.6) on the 8-device CPU mesh."""

import numpy as np

from keystone_tpu.loaders.mnist import load_mnist_csv, synthetic_mnist
from keystone_tpu.pipelines.mnist_random_fft import MnistRandomFFTConfig, run


def test_mnist_random_fft_end_to_end():
    cfg = MnistRandomFFTConfig(
        num_ffts=2,
        block_size=512,
        lam=10.0,
        synthetic_train=600,
        synthetic_test=200,
    )
    results = run(cfg)
    # learnable synthetic data: near-zero train error, strong generalization
    assert results["train_error"] < 5.0
    assert results["test_error"] < 10.0


def test_config_validation():
    import pytest

    with pytest.raises(ValueError):
        MnistRandomFFTConfig(block_size=1000).validate()


def test_synthetic_mnist_split_consistency():
    x1, y1 = synthetic_mnist(100, seed=1)
    x2, y2 = synthetic_mnist(100, seed=2)
    assert not np.allclose(x1, x2)  # different samples
    # same class structure: per-class means correlate across splits
    m1 = np.stack([x1[y1 == c].mean(0) for c in range(10) if (y1 == c).any()])
    m2 = np.stack([x2[y2 == c].mean(0) for c in range(10) if (y2 == c).any()])
    # prototypes shared -> means of same class are close
    assert np.corrcoef(m1[0], m2[0])[0, 1] > 0.5


def test_mnist_csv_loader(tmp_path):
    rows = ["3," + ",".join(["0.5"] * 784), "1," + ",".join(["0.25"] * 784)]
    p = tmp_path / "mnist.csv"
    p.write_text("\n".join(rows))
    x, y = load_mnist_csv(str(p))
    assert x.shape == (2, 784)
    assert y.tolist() == [2, 0]  # 1-indexed in file -> 0-indexed
