import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.stats import (
    CosineRandomFeatures,
    LinearRectifier,
    NormalizeRows,
    PaddedFFT,
    RandomSignNode,
    SignedHellingerMapper,
    StandardScaler,
)
from keystone_tpu.utils.stats import normalize_rows


def test_linear_rectifier():
    node = LinearRectifier(max_val=0.0, alpha=1.0)
    out = node(jnp.array([[0.5, 2.0, -3.0]]))
    np.testing.assert_allclose(np.asarray(out), [[0.0, 1.0, 0.0]])


def test_random_sign_node(rng):
    node = RandomSignNode.create(16, jax.random.key(0))
    signs = np.asarray(node.signs)
    assert set(np.unique(signs)) <= {-1.0, 1.0}
    x = jnp.ones((3, 16))
    np.testing.assert_allclose(np.asarray(node(x)), np.tile(signs, (3, 1)))


def test_normalize_rows_node():
    x = jnp.array([[3.0, 4.0], [0.0, 0.0]])
    out = np.asarray(NormalizeRows()(x))
    np.testing.assert_allclose(out[0], [0.6, 0.8], rtol=1e-6)
    np.testing.assert_allclose(out[1], [0.0, 0.0])


def test_signed_hellinger():
    out = SignedHellingerMapper()(jnp.array([[-4.0, 9.0]]))
    np.testing.assert_allclose(np.asarray(out), [[-2.0, 3.0]])


def test_padded_fft_matches_numpy():
    rng = np.random.default_rng(0)
    x = rng.normal(size=784).astype(np.float32)
    out = np.asarray(PaddedFFT()(jnp.asarray(x)[None, :]))[0]
    assert out.shape == (512,)
    expected = np.fft.fft(x, n=1024).real[:512]
    np.testing.assert_allclose(out, expected, rtol=1e-3, atol=1e-3)


def test_cosine_random_features_moments():
    """Statistical moment checks, like CosineRandomFeaturesSuite.scala:16,36."""
    key = jax.random.key(1)
    node = CosineRandomFeatures.create(8, 4096, gamma=1.0, key=key)
    x = jax.random.normal(jax.random.key(2), (4, 8))
    feats = np.asarray(node(x))
    assert feats.shape == (4, 4096)
    assert np.all(feats >= -1) and np.all(feats <= 1)
    # E[cos(w·x + b)] = 0 when b ~ U[0, 2pi)
    assert abs(feats.mean()) < 0.05
    # direct computation agrees
    direct = np.cos(np.asarray(x) @ np.asarray(node.w).T + np.asarray(node.b))
    np.testing.assert_allclose(feats, direct, atol=1e-5)


def test_cauchy_random_features():
    node = CosineRandomFeatures.create(8, 64, gamma=0.5, key=jax.random.key(3), distribution="cauchy")
    assert np.asarray(node.w).shape == (64, 8)


def test_standard_scaler_unbiased(rng):
    x = rng.normal(loc=3.0, scale=2.0, size=(64, 5)).astype(np.float32)
    model = StandardScaler().fit(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(model.mean), x.mean(axis=0), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(model.std), x.std(axis=0, ddof=1), rtol=1e-4
    )
    out = np.asarray(model(jnp.asarray(x)))
    np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-5)
    np.testing.assert_allclose(out.std(axis=0, ddof=1), 1.0, rtol=1e-4)


def test_standard_scaler_masked_ignores_padding(rng):
    x = rng.normal(size=(10, 3)).astype(np.float32)
    padded = np.concatenate([x, np.full((6, 3), 1e6, np.float32)])
    mask = np.concatenate([np.ones(10, np.float32), np.zeros(6, np.float32)])
    model = StandardScaler().fit(jnp.asarray(padded), mask=jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(model.mean), x.mean(axis=0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(model.std), x.std(axis=0, ddof=1), rtol=1e-4)


def test_scaler_constant_feature_guard():
    x = jnp.ones((8, 2))
    model = StandardScaler().fit(x)
    out = np.asarray(model(x))
    assert np.all(np.isfinite(out))
    np.testing.assert_allclose(out, 0.0, atol=1e-6)


def test_normalize_rows_util():
    rng = np.random.default_rng(5)
    m = rng.normal(size=(4, 10))
    out = np.asarray(normalize_rows(jnp.asarray(m), alpha=1.0))
    expected = (m - m.mean(axis=1, keepdims=True)) / np.sqrt(
        m.var(axis=1, ddof=1, keepdims=True) + 1.0
    )
    np.testing.assert_allclose(out, expected, rtol=1e-5)
