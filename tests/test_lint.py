"""keystone-lint (keystone_tpu/analysis): rule fixtures R1-R5, the
baseline ratchet, pragma handling, the knob registry, the lint CLI, and
the KEYSTONE_GUARD runtime sentinel.

Rule tests run the real engine over tiny fixture trees written to
``tmp_path`` — one positive (must flag) and one negative (must stay
silent) per rule family, plus the repo-wide invariant that the shipped
tree itself lints clean against its committed baseline.
"""

import json
import os
import textwrap

import numpy as np
import pytest

from keystone_tpu.analysis.engine import (
    LintEngine,
    apply_baseline,
    load_baseline,
    run_lint,
    save_baseline,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_tree(tmp_path, files):
    """Write {relpath: source} under tmp_path and run the engine on it."""
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return LintEngine(str(tmp_path), sorted(files)).run()


def rules_of(result):
    return sorted({f.rule for f in result.findings})


# ---------------------------------------------------------------------------
# R1: host syncs in jit/shard_map hot paths
# ---------------------------------------------------------------------------

R1_POSITIVE = """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np


    @jax.jit
    def hot(x):
        v = float(x[0])
        y = np.asarray(x)
        x.block_until_ready()
        t = time.time()
        return x * v + t


    def helper(x):
        return x.item()


    @jax.jit
    def hot_via_call(x):
        return helper(x)
"""


def test_r1_flags_host_syncs_in_hot_paths(tmp_path):
    res = lint_tree(tmp_path, {"pkg/mod.py": R1_POSITIVE})
    r1 = [f for f in res.findings if f.rule == "R1"]
    msgs = " | ".join(f.message for f in r1)
    assert "float" in msgs
    assert "asarray" in msgs
    assert "block_until_ready" in msgs
    assert "time.time" in msgs
    # call-graph propagation: helper() is hot because hot_via_call jits it
    assert any("helper" in f.message for f in r1), msgs
    # findings carry the clickable anchor + a hint
    assert all(f.line > 0 and f.hint for f in r1)


def test_r1_silent_outside_hot_paths_and_on_static_args(tmp_path):
    res = lint_tree(tmp_path, {"pkg/mod.py": """
        import time

        import jax
        import numpy as np


        def cold(x):
            # identical syncs, but nothing jits this function
            v = float(x[0])
            x.block_until_ready()
            return np.asarray(x) * v


        @jax.jit
        def hot(x):
            # shape reads are trace-time python ints: not syncs
            scale = float(x.shape[0])
            return x * scale
    """})
    assert [f for f in res.findings if f.rule == "R1"] == []


def test_r1_wrap_call_marks_function_hot(tmp_path):
    res = lint_tree(tmp_path, {"pkg/mod.py": """
        import jax


        def body(x):
            return x.item()


        fast = jax.jit(body)
    """})
    assert any(f.rule == "R1" and "item" in f.message for f in res.findings)


# ---------------------------------------------------------------------------
# R2: recompile hazards
# ---------------------------------------------------------------------------

def test_r2_jit_in_loop_and_immediate_call(tmp_path):
    res = lint_tree(tmp_path, {"pkg/mod.py": """
        import functools

        import jax


        def per_batch(batches):
            out = []
            for b in batches:
                f = jax.jit(lambda a: a + 1)
                out.append(f(b))
            return out


        def per_call(x):
            return jax.jit(lambda a: a * 2)(x)
    """})
    syms = [f.symbol for f in res.findings if f.rule == "R2"]
    assert "jit-in-loop" in syms
    assert "jit-immediate-call" in syms


def test_r2_static_arg_unhashable_default(tmp_path):
    res = lint_tree(tmp_path, {"pkg/mod.py": """
        import functools

        import jax


        @functools.partial(jax.jit, static_argnums=(1,))
        def solve(x, opts=[]):
            return x
    """})
    assert any(
        f.rule == "R2" and "unhashable" in f.message for f in res.findings
    )


def test_r2_silent_on_construct_once_idioms(tmp_path):
    res = lint_tree(tmp_path, {"pkg/mod.py": """
        import functools

        import jax


        @jax.jit
        def decorated(x):
            return x + 1


        @functools.partial(jax.jit, static_argnums=(1,))
        def decorated_static(x, n=3):
            return x * n


        _cached = jax.jit(lambda a: a - 1)


        def user(x):
            return _cached(x)
    """})
    assert [f for f in res.findings if f.rule == "R2"] == []


# ---------------------------------------------------------------------------
# R3: collective safety
# ---------------------------------------------------------------------------

def test_r3_axis_not_bound_by_shard_map_spec(tmp_path):
    res = lint_tree(tmp_path, {"pkg/mod.py": """
        import jax
        from jax.sharding import PartitionSpec as P


        def outer(x, mesh):
            def local(xj):
                return jax.lax.psum(xj, "model")

            spec = P("data")
            return jax.shard_map(
                local, mesh=mesh, in_specs=spec, out_specs=spec
            )(x)
    """})
    r3 = [f for f in res.findings if f.rule == "R3"]
    assert any("'model'" in f.message and "not bound" in f.message
               for f in r3), [f.message for f in r3]


def test_r3_bound_axis_and_param_default_resolution(tmp_path):
    res = lint_tree(tmp_path, {"pkg/mod.py": """
        import jax
        from jax.sharding import PartitionSpec as P


        def outer(x, mesh, axis="data"):
            def local(xj):
                return jax.lax.psum(xj, axis)

            spec = P(None, axis)
            return jax.shard_map(
                local, mesh=mesh, in_specs=spec, out_specs=spec
            )(x)
    """})
    assert [f for f in res.findings if f.rule == "R3"] == []


def test_r3_unpaired_ppermute(tmp_path):
    pos = lint_tree(tmp_path, {"pkg/fold.py": """
        import jax

        from keystone_tpu.parallel.ring import paired_ring_perms


        def one_directional_fold(x, axis, k):
            fwd, bwd = paired_ring_perms(k)
            for _ in range(k - 1):
                x = jax.lax.ppermute(x, axis, fwd)
            return x
    """})
    assert any(f.rule == "R3" and "one-directionally" in f.message
               for f in pos.findings)

    neg = lint_tree(tmp_path / "neg", {"pkg/fold.py": """
        import jax

        from keystone_tpu.parallel.ring import paired_ring_perms


        def paired_fold(x, y, axis, k):
            fwd, bwd = paired_ring_perms(k)
            for _ in range((k - 1) // 2):
                x = jax.lax.ppermute(x, axis, fwd)
                y = jax.lax.ppermute(y, axis, bwd)
            return x, y
    """})
    assert [f for f in neg.findings if f.rule == "R3"] == []


# ---------------------------------------------------------------------------
# R4: knob hygiene
# ---------------------------------------------------------------------------

def test_r4_raw_env_reads_flagged(tmp_path):
    res = lint_tree(tmp_path, {"pkg/mod.py": """
        import os

        _ENV = "KEYSTONE_INDIRECT"


        def reads():
            a = os.environ.get("KEYSTONE_FOO", "0")
            b = os.environ["BENCH_BAR"]
            c = os.getenv("BENCH_BAZ")
            d = os.environ.get(_ENV)
            return a, b, c, d
    """})
    syms = {f.symbol for f in res.findings if f.rule == "R4"}
    assert {"KEYSTONE_FOO", "BENCH_BAR", "BENCH_BAZ",
            "KEYSTONE_INDIRECT"} <= syms


def test_r4_writes_and_foreign_vars_allowed(tmp_path):
    res = lint_tree(tmp_path, {"pkg/mod.py": """
        import os


        def writes():
            os.environ["KEYSTONE_FOO"] = "1"        # knob production
            os.environ.pop("KEYSTONE_FOO", None)
            return os.environ.get("XLA_FLAGS", "")  # not a keystone knob
    """})
    assert [f for f in res.findings if f.rule == "R4"] == []


def test_r4_undeclared_knobs_get(tmp_path):
    res = lint_tree(tmp_path, {"pkg/mod.py": """
        from keystone_tpu.utils import knobs


        def read():
            ok = knobs.get("KEYSTONE_OVERLAP")       # declared
            bad = knobs.get("KEYSTONE_NOT_A_KNOB")   # undeclared
            return ok, bad
    """})
    r4 = [f for f in res.findings if f.rule == "R4"]
    assert any("KEYSTONE_NOT_A_KNOB" in f.message for f in r4)
    assert not any("KEYSTONE_OVERLAP" in f.message for f in r4)


# ---------------------------------------------------------------------------
# R5: shared-state locks
# ---------------------------------------------------------------------------

R5_SRC = """
    import threading

    _STATE = {}
    _ORDER = []
    _lock = threading.Lock()


    def unlocked(k, v):
        _STATE[k] = v
        _ORDER.append(k)


    def locked(k, v):
        with _lock:
            _STATE[k] = v
            _ORDER.append(k)


    class Registry:
        table = {}

        @classmethod
        def bad(cls, k):
            Registry.table.pop(k, None)

        @classmethod
        def good(cls, k):
            with _lock:
                Registry.table.pop(k, None)
"""


def test_r5_unlocked_mutations_in_scope_modules(tmp_path):
    res = lint_tree(tmp_path, {"pkg/core/cache.py": R5_SRC})
    r5 = [f for f in res.findings if f.rule == "R5"]
    syms = sorted(f.symbol for f in r5)
    assert "_STATE" in syms and "_ORDER" in syms
    assert any("Registry.table" in s for s in syms)
    # exactly the three unlocked mutations — the with-lock ones pass
    assert len(r5) == 3, [(f.line, f.symbol) for f in r5]


def test_r5_out_of_scope_module_silent(tmp_path):
    res = lint_tree(tmp_path, {"pkg/ops/stuff.py": R5_SRC})
    assert [f for f in res.findings if f.rule == "R5"] == []


# ---------------------------------------------------------------------------
# R7: dead knobs (declared but never read — the inverse of R4)
# ---------------------------------------------------------------------------

R7_KNOBS = """
    def declare(name, type, default, doc, **kw):
        pass


    declare("KEYSTONE_LIVE", "bool", False, "read below")
    declare("KEYSTONE_DEAD", "bool", False, "nobody reads this")
    declare("BENCH_PRODUCED", "bool", True, "only written, still alive")
"""

R7_CONSUMER = """
    import os

    from keystone_tpu.utils import knobs


    def f(env):
        # a knobs.get read keeps a knob alive...
        live = knobs.get("KEYSTONE_LIVE")
        # ...and so does env *production* (the bench's subprocess control:
        # a knob exists for its writers too)
        env["BENCH_PRODUCED"] = "0"
        return live
"""


def test_r7_flags_declared_knob_nobody_reads(tmp_path):
    res = lint_tree(tmp_path, {
        "keystone_tpu/utils/knobs.py": R7_KNOBS,
        "keystone_tpu/mod.py": R7_CONSUMER,
    })
    r7 = [f for f in res.findings if f.rule == "R7"]
    assert len(r7) == 1, [(f.symbol, f.message) for f in r7]
    assert r7[0].symbol == "dead:KEYSTONE_DEAD"
    assert "never read" in r7[0].message
    # anchored at the declaration line in knobs.py
    assert r7[0].path.endswith(os.path.join("utils", "knobs.py"))
    assert 'KEYSTONE_DEAD' in (tmp_path / r7[0].path).read_text(
    ).splitlines()[r7[0].line - 1]


def test_r7_silent_without_registry_in_scope(tmp_path):
    """Fixture trees without knobs.py (every other rule's fixtures) must
    not drown in dead-knob findings for the installed registry."""
    res = lint_tree(tmp_path, {"keystone_tpu/mod.py": R7_CONSUMER})
    assert [f for f in res.findings if f.rule == "R7"] == []


# ---------------------------------------------------------------------------
# R6: hand-set solver block sizes in pipelines (unbounded peak-HBM)
# ---------------------------------------------------------------------------

R6_POSITIVE = """
    def run(config):
        est = BlockWeightedLeastSquaresEstimator(
            config.block_size, 1, 0.1, 0.25
        )
        est2 = BlockLeastSquaresEstimator(block_size=4096, num_iter=1)
        return est, est2
"""


def test_r6_flags_hand_set_pipeline_block_sizes(tmp_path):
    res = lint_tree(
        tmp_path, {"keystone_tpu/pipelines/mod.py": R6_POSITIVE}
    )
    r6 = [f for f in res.findings if f.rule == "R6"]
    assert len(r6) == 2
    msgs = " | ".join(f.message for f in r6)
    assert "config.block_size" in msgs and "4096" in msgs
    assert "peak-HBM" in msgs


def test_r6_covers_bcd_method_and_skips_blockless_overloads(tmp_path):
    """BlockCoordinateDescent passes its block via
    solve_least_squares_with_l2 (kw or 5th positional), not the
    constructor; the NormalEquations overload takes no block and must not
    be misread."""
    res = lint_tree(tmp_path, {"keystone_tpu/pipelines/mod.py": """
        def run(config, A, b):
            bcd = BlockCoordinateDescent()
            m1 = bcd.solve_least_squares_with_l2(
                A, b, 0.1, block_size=config.block_size
            )
            m2 = NormalEquations().solve_least_squares_with_l2(A, b, 0.1)
            return m1, m2
    """})
    r6 = [f for f in res.findings if f.rule == "R6"]
    assert len(r6) == 1
    assert "config.block_size" in r6[0].message


def test_r6_silent_when_module_resolves_and_outside_pipelines(tmp_path):
    # a module that routes through plan.resolve_block_size is clean
    res = lint_tree(tmp_path, {"keystone_tpu/pipelines/mod.py": """
        from keystone_tpu.core import plan


        def run(config, n):
            block = plan.resolve_block_size(
                "x", explicit=config.block_size or None, n_rows=n,
                num_classes=10, default=4096,
            )
            return BlockWeightedLeastSquaresEstimator(block, 1, 0.1, 0.25)
    """})
    assert [f for f in res.findings if f.rule == "R6"] == []
    # bench/scripts/solver microbenches are out of scope
    res = lint_tree(tmp_path, {"keystone_tpu/linalg/mod.py": R6_POSITIVE})
    assert [f for f in res.findings if f.rule == "R6"] == []


# ---------------------------------------------------------------------------
# Pragmas
# ---------------------------------------------------------------------------

def test_pragma_trailing_and_block(tmp_path):
    res = lint_tree(tmp_path, {"pkg/core/cache.py": """
        _STATE = {}


        def f(k, v):
            _STATE[k] = v  # lint: disable=R5 (single-threaded by contract)


        def g(k, v):
            # lint: disable=R5 (the justification paragraph form:
            # the pragma covers this whole comment block plus the
            # mutation line below)
            _STATE[k] = v


        def h(k, v):
            _STATE[k] = v  # lint: disable=R1 (wrong rule: must NOT suppress)
    """})
    r5 = [f for f in res.findings if f.rule == "R5"]
    assert len(r5) == 1 and "def h" not in ""  # only h's mutation survives
    assert res.suppressed == 2


def test_pragma_bare_disable_suppresses_all(tmp_path):
    res = lint_tree(tmp_path, {"pkg/mod.py": """
        import os

        x = os.environ.get("KEYSTONE_FOO")  # lint: disable
    """})
    assert res.findings == [] and res.suppressed == 1


def test_stale_pragma_reported(tmp_path):
    """A pragma that suppresses ZERO findings is itself reported (the
    unused-noqa analog) — but a pragma for a rule family this engine does
    not run (the audit's A-rules) is NOT stale just because R1-R6 ran."""
    res = lint_tree(tmp_path, {"pkg/core/cache.py": """
        _STATE = {}


        def f(k, v):
            _STATE[k] = v  # lint: disable=R5 (fires -> credited, not stale)


        def g(v):
            return v + 1  # lint: disable=R5 (suppresses nothing -> stale)


        def h(v):
            return v + 2  # lint: disable=A3 (audit-family rule: not ours)


        def i(v):
            return v + 3  # lint: disable (bare: stale when nothing fired)
    """})
    assert res.suppressed == 1
    # sorted by line: g's unused R5 first, then i's unused bare disable
    assert [r for _p, _l, r in res.stale_pragmas] == ["R5", "*"]


def test_stale_pragma_block_form_counts_once(tmp_path):
    """The justification-paragraph pragma (comment block + first code
    line) is ONE site: credited once when its line fires, stale once when
    nothing does."""
    res = lint_tree(tmp_path, {"pkg/core/cache.py": """
        _STATE = {}


        def f(k, v):
            # lint: disable=R5 (covers this block and the
            # mutation line below)
            _STATE[k] = v
    """})
    assert res.suppressed == 1 and res.stale_pragmas == []


# ---------------------------------------------------------------------------
# Baseline ratchet
# ---------------------------------------------------------------------------

def test_baseline_ratchet(tmp_path):
    src_one = """
        import os

        a = os.environ.get("KEYSTONE_FOO")
    """
    src_two = src_one + "    b = os.environ.get(\"KEYSTONE_FOO\")\n"
    baseline_path = str(tmp_path / "baseline.json")

    # 1. baseline the single pre-existing finding
    res = lint_tree(tmp_path, {"pkg/mod.py": src_one})
    assert len(res.findings) == 1
    save_baseline(baseline_path, res.findings)

    # 2. unchanged tree: baselined finding passes
    res = run_lint(str(tmp_path), ["pkg/mod.py"], baseline_path=baseline_path)
    assert res.findings == [] and len(res.baselined) == 1

    # 3. line drift must not churn the ratchet (fingerprints have no lines)
    (tmp_path / "pkg" / "mod.py").write_text(
        "# a new leading comment\n" + textwrap.dedent(src_one)
    )
    res = run_lint(str(tmp_path), ["pkg/mod.py"], baseline_path=baseline_path)
    assert res.findings == [] and len(res.baselined) == 1

    # 4. a second occurrence of the same fingerprint IS new -> fails
    (tmp_path / "pkg" / "mod.py").write_text(textwrap.dedent(src_two))
    res = run_lint(str(tmp_path), ["pkg/mod.py"], baseline_path=baseline_path)
    assert len(res.findings) == 1 and len(res.baselined) == 1

    # 5. fixing everything surfaces the stale entry (ratchet down)
    (tmp_path / "pkg" / "mod.py").write_text("x = 1\n")
    res = run_lint(str(tmp_path), ["pkg/mod.py"], baseline_path=baseline_path)
    assert res.findings == [] and res.stale


def test_baseline_roundtrip_format(tmp_path):
    path = str(tmp_path / "b.json")
    res = lint_tree(tmp_path, {"pkg/mod.py": """
        import os

        a = os.environ.get("KEYSTONE_FOO")
    """})
    save_baseline(path, res.findings)
    data = json.load(open(path))
    assert "findings" in data and all(
        isinstance(v, int) for v in data["findings"].values()
    )
    assert load_baseline(path) == data["findings"]


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_exit_codes_and_clickable_triple(tmp_path, capsys):
    from keystone_tpu.analysis.cli import main as lint_main

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'import os\na = os.environ.get("KEYSTONE_FOO")\n'
    )
    # new finding -> exit 1, path:line:col: RULE message triple on stdout
    rc = lint_main(["pkg", "--root", str(tmp_path), "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1
    assert any(
        line.startswith(f"pkg{os.sep}mod.py:2:4: R4")
        for line in out.splitlines()
    ), out

    # --update-baseline ratchets -> exit 0 afterwards
    rc = lint_main(["pkg", "--root", str(tmp_path), "--update-baseline"])
    assert rc == 0
    rc = lint_main(["pkg", "--root", str(tmp_path)])
    assert rc == 0
    capsys.readouterr()

    # json format carries the same data machine-readably
    rc = lint_main(["pkg", "--root", str(tmp_path), "--no-baseline",
                    "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1 and payload["total"] == 1


def test_cli_show_stale_pragmas(tmp_path, capsys):
    from keystone_tpu.analysis.cli import main as lint_main

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("x = 1  # lint: disable=R4 (nothing here)\n")
    rc = lint_main(["pkg", "--root", str(tmp_path), "--no-baseline",
                    "--show-stale-pragmas"])
    out = capsys.readouterr().out
    assert rc == 0  # stale pragmas report, they do not fail the build
    assert "stale pragmas" in out
    assert f"pkg{os.sep}mod.py:1: lint: disable=R4" in out


def test_update_baseline_prunes_stale_fingerprints(tmp_path, capsys):
    """--update-baseline must PRUNE fingerprints whose findings were fixed
    (not keep them as dead allowance): the rewritten file holds exactly
    the surviving findings."""
    from keystone_tpu.analysis.cli import main as lint_main

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'import os\na = os.environ.get("KEYSTONE_FOO")\n'
    )
    baseline = tmp_path / "lint_baseline.json"
    stale_fp = "pkg/gone.py::R4::KEYSTONE_GONE"
    baseline.write_text(json.dumps({
        "findings": {stale_fp: 2},
    }))
    rc = lint_main(["pkg", "--root", str(tmp_path), "--update-baseline"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "1 stale fingerprint(s) pruned" in out
    kept = load_baseline(str(baseline))
    assert stale_fp not in kept
    assert len(kept) == 1 and all("mod.py" in fp for fp in kept)


def test_update_baseline_keeps_out_of_scope_debt(tmp_path, capsys):
    """A subset run (`lint pkg --update-baseline`) must not prune the
    debt of still-existing files it never linted."""
    from keystone_tpu.analysis.cli import main as lint_main

    for sub in ("pkg", "other"):
        d = tmp_path / sub
        d.mkdir()
        (d / "mod.py").write_text(
            'import os\na = os.environ.get("KEYSTONE_FOO")\n'
        )
    rc = lint_main(["pkg", "other", "--root", str(tmp_path),
                    "--update-baseline"])
    assert rc == 0
    baseline = load_baseline(str(tmp_path / "lint_baseline.json"))
    assert len(baseline) == 2
    # fix pkg's finding, update ONLY pkg: pkg's fp pruned, other's kept
    (tmp_path / "pkg" / "mod.py").write_text("x = 1\n")
    rc = lint_main(["pkg", "--root", str(tmp_path), "--update-baseline"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "1 stale fingerprint(s) pruned" in out
    assert "1 out-of-scope kept" in out
    kept = load_baseline(str(tmp_path / "lint_baseline.json"))
    assert len(kept) == 1 and all("other" in fp for fp in kept)


def test_repo_lints_clean_against_committed_baseline():
    """The acceptance invariant: the shipped tree has no findings beyond
    its committed (empty-or-justified) baseline — and no stale pragmas
    (every suppression in the tree suppresses something)."""
    res = run_lint(
        REPO_ROOT, ["keystone_tpu", "bench.py", "scripts"],
        baseline_path=os.path.join(REPO_ROOT, "lint_baseline.json"),
    )
    assert res.errors == []
    assert res.findings == [], "\n".join(f.format() for f in res.findings)
    assert res.stale_pragmas == [], res.stale_pragmas


# ---------------------------------------------------------------------------
# Knob registry
# ---------------------------------------------------------------------------

def test_knobs_defaults_and_parsing(monkeypatch):
    from keystone_tpu.utils import knobs

    monkeypatch.delenv("KEYSTONE_OVERLAP", raising=False)
    assert knobs.get("KEYSTONE_OVERLAP") is False
    monkeypatch.setenv("KEYSTONE_OVERLAP", "1")
    assert knobs.get("KEYSTONE_OVERLAP") is True
    monkeypatch.setenv("KEYSTONE_OVERLAP", "maybe")
    with pytest.raises(ValueError, match="KEYSTONE_OVERLAP"):
        knobs.get("KEYSTONE_OVERLAP")

    monkeypatch.setenv("KEYSTONE_CACHE_DEVICE_MB", "2048.0")
    assert knobs.get("KEYSTONE_CACHE_DEVICE_MB") == 2048

    # lenient knobs fall back instead of raising (pinned elsewhere too)
    monkeypatch.setenv("KEYSTONE_PREFETCH", "junk")
    assert knobs.get("KEYSTONE_PREFETCH", default=2) == 2

    with pytest.raises(KeyError, match="not a declared knob"):
        knobs.get("KEYSTONE_NOPE")


def test_knobs_validators(monkeypatch):
    from keystone_tpu.utils import knobs

    monkeypatch.setenv("KEYSTONE_OVERLAP_TILES", "0,9")
    with pytest.raises(ValueError, match="KEYSTONE_OVERLAP_TILES"):
        knobs.get("KEYSTONE_OVERLAP_TILES")
    # normalizing validator: reads yield the parsed tuple (one parse site)
    monkeypatch.setenv("KEYSTONE_OVERLAP_TILES", "8,2")
    assert knobs.get("KEYSTONE_OVERLAP_TILES") == (8, 2)
    monkeypatch.setenv("KEYSTONE_OVERLAP_TILES", "4")
    assert knobs.get("KEYSTONE_OVERLAP_TILES") == (4, None)

    monkeypatch.setenv("KEYSTONE_FV_IMPL", "weird")  # lenient choice knob
    assert knobs.get("KEYSTONE_FV_IMPL") == "auto"


def test_knobs_validate_environment(monkeypatch):
    from keystone_tpu.utils import knobs

    knobs.validate_environment()  # clean env passes
    monkeypatch.setenv("BENCH_MOMENTS", "yes")
    with pytest.raises(ValueError, match="BENCH_MOMENTS"):
        knobs.validate_environment()


def test_knobs_readme_table_lists_every_knob():
    from keystone_tpu.utils import knobs

    table = knobs.readme_table()
    for name in knobs.all_knobs():
        assert f"`{name}`" in table
    readme = open(os.path.join(REPO_ROOT, "README.md")).read()
    for name in knobs.all_knobs():
        assert name in readme, f"knob {name} missing from README"


# ---------------------------------------------------------------------------
# Runtime guard (KEYSTONE_GUARD)
# ---------------------------------------------------------------------------

@pytest.fixture
def guard_registry():
    from keystone_tpu.telemetry.registry import MetricsRegistry

    return MetricsRegistry()


def test_guard_chain_solver_smoke_zero_violations(guard_registry):
    """Acceptance fixture: a warmed Chain + block-solver run under the
    armed guard reports ZERO transfer and ZERO recompile violations —
    the runtime verification of the R1/R2 static pass over the solver
    hot paths."""
    import jax
    import jax.numpy as jnp

    from keystone_tpu.analysis.guard import guard, violations
    from keystone_tpu.core.pipeline import Transformer
    from keystone_tpu.learning import BlockLeastSquaresEstimator

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.normal(size=(64, 16)).astype(np.float32))
    Y = jnp.asarray(rng.normal(size=(64, 3)).astype(np.float32))
    pipe = Transformer.from_fn(lambda x: jnp.tanh(x)).then(
        BlockLeastSquaresEstimator(block_size=8, num_iter=2, lam=0.5)
    )

    def run_once():
        model = pipe.fit(X, Y)
        preds = model(X)
        jax.block_until_ready(preds)

    run_once()  # warm: compile everything outside the guard
    with guard(registry=guard_registry):
        run_once()
    v = violations(guard_registry)
    assert v["guard.transfer"] == 0, guard_registry.as_dict()["counters"]
    assert v["guard.recompile"] == 0, guard_registry.as_dict()["counters"]


def test_guard_weighted_bcd_zero_transfers(guard_registry):
    """The flagship weighted solver's fit loop is transfer-clean (this PR
    removed 31 implicit per-fit uploads: lam/w scalars, eager zeros,
    per-block slice starts, bucket tables, the eager bucket gather)."""
    import jax
    import jax.numpy as jnp

    from keystone_tpu.analysis.guard import guard, violations
    from keystone_tpu.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )
    from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels

    rng = np.random.default_rng(1)
    X = jnp.asarray(rng.normal(size=(64, 16)).astype(np.float32))
    lab = ClassLabelIndicatorsFromIntLabels(3)(
        jnp.asarray(rng.integers(0, 3, 64))
    )
    est = BlockWeightedLeastSquaresEstimator(8, 2, 0.1, 0.25)

    def fit():
        jax.block_until_ready(est.fit(X, lab).w)

    fit()
    with guard(registry=guard_registry):
        fit()
    assert violations(guard_registry)["guard.transfer"] == 0, \
        guard_registry.as_dict()["counters"]


def test_guard_counts_transfer_violation(guard_registry):
    import jax.numpy as jnp

    from keystone_tpu.analysis.guard import guard

    x = jnp.arange(8.0)
    with guard(registry=guard_registry):
        # a numpy operand in an eager op is an implicit h2d upload every
        # call (small-int constants can be cached; arrays are not)
        jnp.add(x, np.arange(8.0, dtype=np.float32))
    assert guard_registry.sum_counters("guard.transfer") >= 1


def test_guard_counts_recompile(guard_registry):
    import jax
    import jax.numpy as jnp

    from keystone_tpu.analysis.guard import guard

    x = jnp.arange(4.0)
    with guard(registry=guard_registry):
        # the R2 hazard shape: a fresh function object (and jit wrapper)
        # per iteration defeats the executable cache — same name, same
        # signature, compiled twice
        for _ in range(2):
            def body(a):
                return a * 3.0

            jax.jit(body)(x)
    assert guard_registry.sum_counters("guard.recompile") >= 1


def test_guard_disallow_mode_counts_and_raises(guard_registry):
    import jax.numpy as jnp

    from keystone_tpu.analysis.guard import guard

    x = jnp.arange(8.0)
    with pytest.raises(Exception, match="[Dd]isallow"):
        with guard(registry=guard_registry, transfer_mode="disallow"):
            float(x[3])
    assert guard_registry.sum_counters("guard.transfer") >= 1


def test_maybe_guard_is_opt_in(monkeypatch, guard_registry):
    import contextlib

    from keystone_tpu.analysis import guard as guard_mod

    monkeypatch.delenv("KEYSTONE_GUARD", raising=False)
    ctx = guard_mod.maybe_guard()
    assert isinstance(ctx, contextlib.nullcontext)
    monkeypatch.setenv("KEYSTONE_GUARD", "1")
    ctx = guard_mod.maybe_guard(registry=guard_registry)
    with ctx:
        pass  # arms and disarms cleanly
