"""Checkpoint/resume tests: fitted nodes round-trip through save/load and
load_or_fit skips refitting (SURVEY.md §5 rebuild implication)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.core import chain, load_node, load_or_fit, save_node
from keystone_tpu.learning import GaussianMixtureModel, PCAEstimator
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.utils import annotate, trace


def test_fitted_pca_round_trip(tmp_path, rng):
    x = rng.normal(size=(200, 12)).astype(np.float32)
    fitted = PCAEstimator(4).fit(x)
    path = str(tmp_path / "pca.ckpt")
    save_node(fitted, path)
    loaded = load_node(path)
    np.testing.assert_allclose(
        np.asarray(fitted(x)), np.asarray(loaded(x)), rtol=1e-6
    )


def test_fitted_chain_round_trip(tmp_path, rng):
    x = rng.normal(size=(100, 8)).astype(np.float32) * 3 + 1
    fitted = chain(StandardScaler().fit(x), PCAEstimator(3).fit(x))
    path = str(tmp_path / "chain.ckpt")
    save_node(fitted, path)
    loaded = load_node(path)
    np.testing.assert_allclose(
        np.asarray(fitted(x)), np.asarray(loaded(x)), rtol=1e-5
    )


def test_gmm_round_trip(tmp_path, rng):
    k, d = 3, 5
    gmm = GaussianMixtureModel(
        means=rng.normal(size=(k, d)).astype(np.float32),
        variances=rng.uniform(0.5, 2.0, (k, d)).astype(np.float32),
        weights=np.full(k, 1 / 3, np.float32),
    )
    path = str(tmp_path / "gmm.ckpt")
    save_node(gmm, path)
    loaded = load_node(path)
    x = rng.normal(size=(20, d)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(gmm.apply_batch(x)), np.asarray(loaded.apply_batch(x)), rtol=1e-5
    )


def test_load_or_fit_switch(tmp_path, rng):
    x = rng.normal(size=(80, 6)).astype(np.float32)
    path = str(tmp_path / "node.ckpt")
    calls = []

    def fit():
        calls.append(1)
        return PCAEstimator(2).fit(x)

    first = load_or_fit(path, fit)
    second = load_or_fit(path, fit)  # must load, not refit
    assert len(calls) == 1
    np.testing.assert_allclose(np.asarray(first(x)), np.asarray(second(x)), rtol=1e-6)


def test_load_or_fit_empty_path_always_fits(rng):
    x = rng.normal(size=(40, 4)).astype(np.float32)
    calls = []

    def fit():
        calls.append(1)
        return PCAEstimator(2).fit(x)

    load_or_fit("", fit)
    load_or_fit("", fit)
    assert len(calls) == 2


def test_reject_garbage(tmp_path):
    p = tmp_path / "bad.ckpt"
    import pickle

    p.write_bytes(pickle.dumps({"not": "a checkpoint"}))
    with pytest.raises(ValueError):
        load_node(str(p))


def test_text_pipeline_checkpointable(tmp_path):
    """The fitted newsgroups-style predictor (TermFrequency + sparse
    vectorizer + NB) must round-trip — regression for the lambda-default
    TermFrequency that broke pickling."""
    import numpy as np

    from keystone_tpu.learning.naive_bayes import NaiveBayesEstimator
    from keystone_tpu.ops.nlp import NGramsFeaturizer, Tokenizer
    from keystone_tpu.ops.util.sparse import (
        CommonSparseFeatures,
        TermFrequency,
        binary_weight,
    )
    from keystone_tpu.ops.util import MaxClassifier

    docs = ["cat dog cat", "dog dog fish", "fish cat fish", "dog cat dog"]
    labels = np.array([0, 1, 0, 1], np.int32)
    feats = chain(Tokenizer(), NGramsFeaturizer(orders=(1,)), TermFrequency(fn=binary_weight))
    predictor = (
        feats.then(CommonSparseFeatures(10)).fit(docs)
        .then(NaiveBayesEstimator(2)).fit(docs, labels)
        .then(MaxClassifier())
    )
    path = str(tmp_path / "predictor.ckpt")
    save_node(predictor, path)
    loaded = load_node(path)
    np.testing.assert_array_equal(
        np.asarray(predictor(docs)), np.asarray(loaded(docs))
    )


def test_profiling_hooks_are_noops_without_dir(rng):
    import jax.numpy as jnp

    with trace():  # no env var, no dir: must be free
        with annotate("stage"):
            _ = jnp.sum(jnp.ones(8)).block_until_ready()


def test_lambda_statics_fail_loudly(tmp_path):
    """Nodes carrying lambdas cannot round-trip through pickle; save_node
    must raise a ValueError naming the culprit, not pickle's opaque error
    (VERDICT round-1 weak #8)."""
    from keystone_tpu.core.pipeline import LambdaTransformer

    node = LambdaTransformer(fn=lambda x: x + 1, name="inc")
    with pytest.raises(ValueError, match="lambda"):
        save_node(node, str(tmp_path / "bad.ckpt"))


def _double(x):
    return x * 2.0


def test_module_level_fn_statics_round_trip(tmp_path):
    from keystone_tpu.core.pipeline import LambdaTransformer

    node = LambdaTransformer(fn=_double, name="double")
    p = str(tmp_path / "ok.ckpt")
    save_node(node, p)
    back = load_node(p)
    x = jnp.arange(4.0)
    np.testing.assert_allclose(np.asarray(back(x[None])), np.asarray(node(x[None])))


def test_fitted_fisher_pipeline_round_trip(tmp_path, rng):
    """Whole fitted VOC-style featurizer (SIFT -> PCA -> GMM -> FV chain) +
    linear model round-trips through one checkpoint and reproduces
    predictions exactly (VERDICT round-1 item 8)."""
    from keystone_tpu.learning import BlockLeastSquaresEstimator
    from keystone_tpu.ops.images import SIFTExtractor
    from keystone_tpu.pipelines._fisher import fit_fisher_branch

    imgs = jnp.asarray(rng.random((6, 48, 48)).astype(np.float32))
    featurizer, feats = fit_fisher_branch(
        SIFTExtractor(scales=2), imgs, pca_dims=8, vocab_size=2,
        num_pca_samples=2000, num_gmm_samples=2000,
    )
    labels = jnp.asarray(np.eye(3, dtype=np.float32)[[0, 1, 2, 0, 1, 2]] * 2 - 1)
    model = BlockLeastSquaresEstimator(block_size=16, lam=1.0).fit(feats, labels)
    pipeline = featurizer.then(model)

    p = str(tmp_path / "voc_pipeline.ckpt")
    save_node(pipeline, p)
    back = load_node(p)
    np.testing.assert_allclose(
        np.asarray(back(imgs)), np.asarray(pipeline(imgs)), atol=1e-6
    )


# ---------------------------------------------------------------------------
# Durability + mesh-portability contract (PR 12): checksummed v2 payloads,
# crash-atomic writes, manifests, named errors
# ---------------------------------------------------------------------------

def test_truncated_checkpoint_raises_named_error(tmp_path):
    """A truncated file must raise CheckpointCorruptError BEFORE any state
    is unpickled — loaded whole or not at all, never garbage."""
    from keystone_tpu.core.checkpoint import (
        CheckpointCorruptError,
        load_node,
        save_node,
    )

    p = str(tmp_path / "t.ckpt")
    save_node({"w": np.arange(4096, dtype=np.float32)}, p)
    blob = open(p, "rb").read()
    for cut in (len(blob) // 2, 10, 1):
        with open(p, "wb") as f:
            f.write(blob[:cut])
        with pytest.raises(CheckpointCorruptError):
            load_node(p)


def test_bitflip_fails_checksum(tmp_path):
    """Corruption anywhere in the payload fails the SHA-256 check with the
    named error (bit-rot is detected, not silently deserialized)."""
    from keystone_tpu.core.checkpoint import (
        CheckpointCorruptError,
        load_node,
        save_node,
    )

    p = str(tmp_path / "b.ckpt")
    save_node({"w": np.arange(4096, dtype=np.float32)}, p)
    blob = bytearray(open(p, "rb").read())
    blob[len(blob) - 100] ^= 0xFF  # flip a byte inside the array payload
    with open(p, "wb") as f:
        f.write(bytes(blob))
    with pytest.raises(CheckpointCorruptError, match="checksum"):
        load_node(p)


def test_legacy_v1_checkpoint_still_loads(tmp_path):
    """Pre-checksum (v1) files written by earlier builds keep loading —
    format migration must not strand existing checkpoints."""
    import pickle

    import jax

    from keystone_tpu.core.checkpoint import load_checkpoint

    value = {"w": np.arange(16, dtype=np.float32)}
    leaves, treedef = jax.tree.flatten(value)
    p = tmp_path / "v1.ckpt"
    p.write_bytes(pickle.dumps({
        "magic": "keystone-tpu-node-v1",
        "treedef": treedef,
        "leaves": [np.asarray(l) for l in leaves],
    }))
    node, manifest = load_checkpoint(str(p))
    np.testing.assert_array_equal(node["w"], value["w"])
    assert manifest is None


def test_manifest_round_trip_and_validation(tmp_path):
    from keystone_tpu.analysis.contracts import validate_manifest
    from keystone_tpu.core.checkpoint import (
        CheckpointError,
        build_manifest,
        load_checkpoint,
        load_manifest,
        save_node,
    )

    state = {"R": np.zeros((8, 3), np.float32),
             "models": [np.zeros((4, 3), np.float32)]}
    manifest = build_manifest(
        state, mesh_shape={"data": 8, "model": 1}, mesh_devices=8,
        block_order=[0, 1], pos=3,
    )
    assert validate_manifest(manifest) == []
    # per-array logical shapes recorded for every leaf
    assert any("R" in k for k in manifest["arrays"])
    assert manifest["arrays"]["['R']"] == {"shape": [8, 3],
                                           "dtype": "float32"}
    p = str(tmp_path / "m.ckpt")
    save_node(state, p, manifest=manifest)
    node, back = load_checkpoint(p)
    assert back == manifest
    assert load_manifest(p) == manifest
    np.testing.assert_array_equal(node["R"], state["R"])

    # the contract rejects malformed manifests on BOTH sides
    assert validate_manifest({"format": 2}) != []          # arrays missing
    assert validate_manifest({"arrays": {}}) != []         # format missing
    assert validate_manifest(
        {"format": 2, "arrays": {"x": {"shape": "nope", "dtype": "f"}}}
    ) != []
    assert validate_manifest(
        {"format": 2, "arrays": {}, "mesh_shape": {"data": 0}}
    ) != []
    with pytest.raises(CheckpointError, match="contract"):
        build_manifest(state, mesh_shape={"data": 0})  # writer-side catch


def test_restore_onto_reshards_and_rejects_mismatch(devices):
    """restore_onto re-device_puts host state onto the LIVE sharding (the
    mesh-portable resume step) and raises the named mismatch error when
    logical shapes genuinely disagree."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.core.checkpoint import (
        CheckpointMismatchError,
        mesh_shape_of,
        restore_onto,
    )
    from keystone_tpu.parallel import make_mesh

    mesh4 = make_mesh(data=4, model=1, devices=devices[:4])
    live = jax.device_put(
        jnp.zeros((16, 3)), NamedSharding(mesh4, P("data", None))
    )
    host = np.arange(48, dtype=np.float32).reshape(16, 3)
    out = restore_onto(host, live)
    assert out.sharding == live.sharding
    np.testing.assert_array_equal(np.asarray(out), host)
    assert mesh_shape_of(live) == {"data": 4, "model": 1}
    assert mesh_shape_of(np.zeros(3)) is None
    with pytest.raises(CheckpointMismatchError, match="shape"):
        restore_onto(np.zeros((8, 3), np.float32), live)


def test_save_is_crash_atomic(tmp_path, monkeypatch):
    """A crash mid-write leaves the PREVIOUS checkpoint intact: the payload
    goes to a temp file and only an atomic rename publishes it."""
    import os

    from keystone_tpu.core.checkpoint import load_node, save_node

    p = str(tmp_path / "a.ckpt")
    save_node({"v": np.float32(1.0)}, p)

    real_replace = os.replace

    def crashing_replace(src, dst):
        raise OSError("simulated crash at publish time")

    monkeypatch.setattr(os, "replace", crashing_replace)
    with pytest.raises(OSError):
        save_node({"v": np.float32(2.0)}, p)
    monkeypatch.setattr(os, "replace", real_replace)
    assert float(load_node(p)["v"]) == 1.0  # old checkpoint intact
    assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp")] == []


def test_checkpoint_telemetry_histograms(tmp_path):
    from keystone_tpu.core.checkpoint import load_node, save_node
    from keystone_tpu.telemetry import get_registry

    reg = get_registry()

    def count(name):
        h = reg.get_histogram(name)
        return (h or {}).get("count", 0)

    s0, l0 = count("checkpoint.save_s"), count("checkpoint.load_s")
    p = str(tmp_path / "t.ckpt")
    save_node({"v": np.zeros(8, np.float32)}, p)
    load_node(p)
    assert count("checkpoint.save_s") == s0 + 1
    assert count("checkpoint.load_s") == l0 + 1


def test_v1_magic_missing_fields_is_named_corruption(tmp_path):
    """A v1-magic dict missing treedef/leaves must raise the NAMED
    corruption error, not a KeyError that escapes the elastic recovery
    path's except-CheckpointError handler."""
    import pickle

    from keystone_tpu.core.checkpoint import (
        CheckpointCorruptError,
        load_node,
    )

    p = tmp_path / "v1bad.ckpt"
    p.write_bytes(pickle.dumps({"magic": "keystone-tpu-node-v1"}))
    with pytest.raises(CheckpointCorruptError, match="v1"):
        load_node(str(p))


def test_writer_side_manifest_bug_is_distinct_from_corruption():
    """build_manifest failures are CheckpointWriteError — a code bug in
    the writer, deliberately NOT a subclass match for the discard-and-
    refit handler's unusable-file class."""
    from keystone_tpu.core.checkpoint import (
        CheckpointCorruptError,
        CheckpointWriteError,
        build_manifest,
    )

    with pytest.raises(CheckpointWriteError):
        build_manifest({"x": np.zeros(2)}, mesh_shape={"data": 0})
    assert not issubclass(CheckpointWriteError, CheckpointCorruptError)
