"""Checkpoint/resume tests: fitted nodes round-trip through save/load and
load_or_fit skips refitting (SURVEY.md §5 rebuild implication)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.core import chain, load_node, load_or_fit, save_node
from keystone_tpu.learning import GaussianMixtureModel, PCAEstimator
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.utils import annotate, trace


def test_fitted_pca_round_trip(tmp_path, rng):
    x = rng.normal(size=(200, 12)).astype(np.float32)
    fitted = PCAEstimator(4).fit(x)
    path = str(tmp_path / "pca.ckpt")
    save_node(fitted, path)
    loaded = load_node(path)
    np.testing.assert_allclose(
        np.asarray(fitted(x)), np.asarray(loaded(x)), rtol=1e-6
    )


def test_fitted_chain_round_trip(tmp_path, rng):
    x = rng.normal(size=(100, 8)).astype(np.float32) * 3 + 1
    fitted = chain(StandardScaler().fit(x), PCAEstimator(3).fit(x))
    path = str(tmp_path / "chain.ckpt")
    save_node(fitted, path)
    loaded = load_node(path)
    np.testing.assert_allclose(
        np.asarray(fitted(x)), np.asarray(loaded(x)), rtol=1e-5
    )


def test_gmm_round_trip(tmp_path, rng):
    k, d = 3, 5
    gmm = GaussianMixtureModel(
        means=rng.normal(size=(k, d)).astype(np.float32),
        variances=rng.uniform(0.5, 2.0, (k, d)).astype(np.float32),
        weights=np.full(k, 1 / 3, np.float32),
    )
    path = str(tmp_path / "gmm.ckpt")
    save_node(gmm, path)
    loaded = load_node(path)
    x = rng.normal(size=(20, d)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(gmm.apply_batch(x)), np.asarray(loaded.apply_batch(x)), rtol=1e-5
    )


def test_load_or_fit_switch(tmp_path, rng):
    x = rng.normal(size=(80, 6)).astype(np.float32)
    path = str(tmp_path / "node.ckpt")
    calls = []

    def fit():
        calls.append(1)
        return PCAEstimator(2).fit(x)

    first = load_or_fit(path, fit)
    second = load_or_fit(path, fit)  # must load, not refit
    assert len(calls) == 1
    np.testing.assert_allclose(np.asarray(first(x)), np.asarray(second(x)), rtol=1e-6)


def test_load_or_fit_empty_path_always_fits(rng):
    x = rng.normal(size=(40, 4)).astype(np.float32)
    calls = []

    def fit():
        calls.append(1)
        return PCAEstimator(2).fit(x)

    load_or_fit("", fit)
    load_or_fit("", fit)
    assert len(calls) == 2


def test_reject_garbage(tmp_path):
    p = tmp_path / "bad.ckpt"
    import pickle

    p.write_bytes(pickle.dumps({"not": "a checkpoint"}))
    with pytest.raises(ValueError):
        load_node(str(p))


def test_text_pipeline_checkpointable(tmp_path):
    """The fitted newsgroups-style predictor (TermFrequency + sparse
    vectorizer + NB) must round-trip — regression for the lambda-default
    TermFrequency that broke pickling."""
    import numpy as np

    from keystone_tpu.learning.naive_bayes import NaiveBayesEstimator
    from keystone_tpu.ops.nlp import NGramsFeaturizer, Tokenizer
    from keystone_tpu.ops.util.sparse import (
        CommonSparseFeatures,
        TermFrequency,
        binary_weight,
    )
    from keystone_tpu.ops.util import MaxClassifier

    docs = ["cat dog cat", "dog dog fish", "fish cat fish", "dog cat dog"]
    labels = np.array([0, 1, 0, 1], np.int32)
    feats = chain(Tokenizer(), NGramsFeaturizer(orders=(1,)), TermFrequency(fn=binary_weight))
    predictor = (
        feats.then(CommonSparseFeatures(10)).fit(docs)
        .then(NaiveBayesEstimator(2)).fit(docs, labels)
        .then(MaxClassifier())
    )
    path = str(tmp_path / "predictor.ckpt")
    save_node(predictor, path)
    loaded = load_node(path)
    np.testing.assert_array_equal(
        np.asarray(predictor(docs)), np.asarray(loaded(docs))
    )


def test_profiling_hooks_are_noops_without_dir(rng):
    import jax.numpy as jnp

    with trace():  # no env var, no dir: must be free
        with annotate("stage"):
            _ = jnp.sum(jnp.ones(8)).block_until_ready()


def test_lambda_statics_fail_loudly(tmp_path):
    """Nodes carrying lambdas cannot round-trip through pickle; save_node
    must raise a ValueError naming the culprit, not pickle's opaque error
    (VERDICT round-1 weak #8)."""
    from keystone_tpu.core.pipeline import LambdaTransformer

    node = LambdaTransformer(fn=lambda x: x + 1, name="inc")
    with pytest.raises(ValueError, match="lambda"):
        save_node(node, str(tmp_path / "bad.ckpt"))


def _double(x):
    return x * 2.0


def test_module_level_fn_statics_round_trip(tmp_path):
    from keystone_tpu.core.pipeline import LambdaTransformer

    node = LambdaTransformer(fn=_double, name="double")
    p = str(tmp_path / "ok.ckpt")
    save_node(node, p)
    back = load_node(p)
    x = jnp.arange(4.0)
    np.testing.assert_allclose(np.asarray(back(x[None])), np.asarray(node(x[None])))


def test_fitted_fisher_pipeline_round_trip(tmp_path, rng):
    """Whole fitted VOC-style featurizer (SIFT -> PCA -> GMM -> FV chain) +
    linear model round-trips through one checkpoint and reproduces
    predictions exactly (VERDICT round-1 item 8)."""
    from keystone_tpu.learning import BlockLeastSquaresEstimator
    from keystone_tpu.ops.images import SIFTExtractor
    from keystone_tpu.pipelines._fisher import fit_fisher_branch

    imgs = jnp.asarray(rng.random((6, 48, 48)).astype(np.float32))
    featurizer, feats = fit_fisher_branch(
        SIFTExtractor(scales=2), imgs, pca_dims=8, vocab_size=2,
        num_pca_samples=2000, num_gmm_samples=2000,
    )
    labels = jnp.asarray(np.eye(3, dtype=np.float32)[[0, 1, 2, 0, 1, 2]] * 2 - 1)
    model = BlockLeastSquaresEstimator(block_size=16, lam=1.0).fit(feats, labels)
    pipeline = featurizer.then(model)

    p = str(tmp_path / "voc_pipeline.ckpt")
    save_node(pipeline, p)
    back = load_node(p)
    np.testing.assert_allclose(
        np.asarray(back(imgs)), np.asarray(pipeline(imgs)), atol=1e-6
    )
