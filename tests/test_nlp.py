"""NLP node tests.

Mirrors the reference suites: ``nodes/nlp/NGramIndexerSuite.scala`` (bit-pack
round trips), ``pipelines/nlp/StupidBackoffSuite.scala`` (end-to-end toy-corpus
scores checked against hand-computed backoff values).
"""

import numpy as np
import pytest

from keystone_tpu.ops.nlp import (
    CoreNLPFeatureExtractor,
    LowerCase,
    NGramIndexerImpl,
    NGramsCounts,
    NGramsCountsMode,
    NGramsFeaturizer,
    NaiveBitPackIndexer,
    PackedNGramIndexer,
    StupidBackoffEstimator,
    Tokenizer,
    Trim,
    WordFrequencyEncoder,
    encoded_ngrams,
    lemmatize,
)


class TestStrings:
    def test_tokenizer_java_split_semantics(self):
        t = Tokenizer("[\\s]+")
        assert t.apply("a b  c") == ["a", "b", "c"]
        # Java split keeps a leading empty, drops trailing empties
        assert t.apply(" a b ") == ["", "a", "b"]
        assert t.apply_batch(["x y", "z"]) == [["x", "y"], ["z"]]

    def test_trim_lowercase(self):
        assert Trim()(["  A  ", "b "]) == ["A", "b"]
        assert LowerCase()(["AbC"]) == ["abc"]


class TestNGrams:
    def test_featurizer_orders(self):
        f = NGramsFeaturizer(orders=(1, 2))
        out = f.apply(["a", "b", "c"])
        assert out == [("a",), ("b",), ("c",), ("a", "b"), ("b", "c")]

    def test_featurizer_short_doc(self):
        f = NGramsFeaturizer(orders=(2, 3))
        assert f.apply(["x"]) == []

    def test_counts_default_sorted(self):
        docs = [[("a",), ("b",), ("a",)], [("a",)]]
        counts = NGramsCounts(mode=NGramsCountsMode.DEFAULT)(docs)
        assert counts[0] == (("a",), 3)
        assert dict(counts)[("b",)] == 1

    def test_encoded_ngrams_matches_naive(self, rng):
        ids = rng.integers(0, 50, size=(6, 12)).astype(np.int32)
        lengths = rng.integers(2, 13, size=6).astype(np.int32)
        for i, l in enumerate(lengths):
            ids[i, l:] = -1
        for order in (2, 3):
            got = encoded_ngrams(ids, lengths, order)
            expected = []
            for i in range(6):
                row = ids[i, : lengths[i]]
                for j in range(len(row) - order + 1):
                    expected.append(row[j : j + order])
            np.testing.assert_array_equal(got, np.array(expected))


class TestIndexers:
    def test_bitpack_round_trip(self):
        idx = NaiveBitPackIndexer()
        for ngram in [(7,), (1, 2), (3, 4, 5), (0, 0, 0), ((1 << 20) - 1, 9)]:
            key = idx.pack(ngram)
            assert idx.unpack(key) == tuple(ngram)
            assert idx.ngram_order(key) == len(ngram)

    def test_bitpack_shortening(self):
        idx = NaiveBitPackIndexer()
        key = idx.pack((3, 4, 5))
        assert idx.unpack(idx.remove_farthest_word(key)) == (4, 5)
        assert idx.unpack(idx.remove_current_word(key)) == (3, 4)
        with pytest.raises(ValueError):
            idx.remove_current_word(idx.pack((1,)))

    def test_seq_indexer(self):
        idx = NGramIndexerImpl()
        key = idx.pack((9, 8, 7, 6, 5))
        assert idx.ngram_order(key) == 5
        assert idx.remove_farthest_word(key) == (8, 7, 6, 5)
        assert idx.remove_current_word(key) == (9, 8, 7, 6)

    def test_packed_batch_lexicographic(self):
        idx = PackedNGramIndexer(vocab_size=1000, max_order=3)
        ngrams = np.array([[1, 2, 3], [1, 2, 4], [2, 0, 0]], dtype=np.int64)
        keys = idx.pack_batch(ngrams)
        assert keys[0] < keys[1] < keys[2]  # lexicographic order preserved
        np.testing.assert_array_equal(
            idx.drop_current_batch(keys), idx.pack_batch(ngrams[:, :2])
        )
        np.testing.assert_array_equal(
            idx.drop_farthest_batch(keys, 3), idx.pack_batch(ngrams[:, 1:])
        )

    def test_packed_rejects_overflow(self):
        with pytest.raises(ValueError):
            PackedNGramIndexer(vocab_size=1 << 25, max_order=5)


class TestWordFrequencyEncoder:
    def test_rank_and_oov(self):
        docs = [["b", "a", "b"], ["b", "c"]]
        enc = WordFrequencyEncoder().fit(docs)
        assert enc.word_index["b"] == 0  # most frequent -> id 0
        assert enc.apply(["b", "zzz"]) == [0, -1]
        assert enc.unigram_counts[0] == 3
        ids, lengths = enc.encode_padded([["a"], ["b", "c"]])
        assert ids.shape == (2, 2)
        assert ids[0, 1] == -1 and list(lengths) == [1, 2]


class TestStupidBackoff:
    """Hand-computed backoff scores on a toy corpus
    (StupidBackoffSuite.scala:48-70 analog)."""

    @pytest.fixture()
    def model(self):
        corpus = [["a", "b", "c"], ["a", "b", "d"], ["b", "c"]]
        enc = WordFrequencyEncoder().fit(corpus)
        encoded = enc.apply_batch(corpus)
        ngrams = NGramsFeaturizer(orders=(2, 3))(encoded)
        counts = NGramsCounts(mode=NGramsCountsMode.NO_ADD)(ngrams)
        model = StupidBackoffEstimator(enc.unigram_counts, alpha=0.4).fit(counts)
        return enc, model

    def test_seen_bigram(self, model):
        enc, m = model
        a, b = enc.word_index["a"], enc.word_index["b"]
        # S(b|a) = c(ab)/c(a) = 2/2
        assert m.apply((a, b)) == pytest.approx(1.0)

    def test_seen_trigram(self, model):
        enc, m = model
        a, b, c = (enc.word_index[w] for w in "abc")
        # S(c|ab) = c(abc)/c(ab) = 1/2
        assert m.apply((a, b, c)) == pytest.approx(0.5)

    def test_backoff_to_bigram(self, model):
        enc, m = model
        a, b, c, d = (enc.word_index[w] for w in "abcd")
        # (c,b,d) unseen -> 0.4 * S(d|b); (b,d) seen: c(bd)/c(b) = 1/3
        assert m.apply((c, b, d)) == pytest.approx(0.4 * (1.0 / 3.0))

    def test_backoff_to_unigram(self, model):
        enc, m = model
        c, d = enc.word_index["c"], enc.word_index["d"]
        # (d,c) unseen -> 0.4 * S(c) = 0.4 * c(c)/N; N=8 tokens, c(c)=2
        assert m.apply((d, c)) == pytest.approx(0.4 * 2.0 / 8.0)

    def test_unigram_score(self, model):
        enc, m = model
        b = enc.word_index["b"]
        assert m.apply((b,)) == pytest.approx(3.0 / 8.0)

    def test_oov_scores_zero_base(self, model):
        enc, m = model
        b = enc.word_index["b"]
        # (-1, b) backs off to unigram b
        assert m.apply((-1, b)) == pytest.approx(0.4 * 3.0 / 8.0)

    def test_batch_matches_single(self, model):
        enc, m = model
        a, b, c = (enc.word_index[w] for w in "abc")
        batch = np.array([[a, b], [b, c], [c, a]], dtype=np.int32)
        got = m.score_batch(batch)
        for row, s in zip(batch, got):
            assert m.apply(tuple(row)) == pytest.approx(float(s))

    def test_scores_enumeration(self, model):
        enc, m = model
        scores = dict(m.scores())
        a, b = enc.word_index["a"], enc.word_index["b"]
        assert scores[(a, b)] == pytest.approx(1.0)
        # every trained ngram present (3 unique bigrams + 2 trigrams)
        assert len(scores) == 5

    def test_wide_vocab_keys_survive_device(self):
        """Packed keys wider than 31 bits must not be truncated (x64 path)."""
        big = 1 << 18
        uni = {0: 5, 1: 3, big: 2}
        counts = [((big, 1), 2), ((big, 0), 1)]
        m = StupidBackoffEstimator(uni, alpha=0.4).fit(counts)
        assert m.apply((big, 1)) == pytest.approx(2.0 / 2.0)
        assert m.apply((big, 0)) == pytest.approx(1.0 / 2.0)
        # unseen pair with wide ids backs off cleanly
        assert m.apply((1, big)) == pytest.approx(0.4 * 2.0 / 10.0)

    def test_host_fallback_for_overwide_configs(self):
        """vocab × order beyond 63 bits must fall back to host tables with
        identical scoring semantics."""
        big = (1 << 20) - 1  # 20-bit ids × order 4 = 80 bits > 63
        uni = {0: 4, 1: 3, 2: 2, big: 1}
        counts = [
            ((0, 1, 2, big), 2),
            ((0, 1, 2), 3),
            ((1, 2), 4),
            ((0, 1), 5),
        ]
        m = StupidBackoffEstimator(uni, alpha=0.4).fit(counts)
        assert m.host_tables is not None
        # seen 4-gram: c(0,1,2,big)/c(0,1,2) = 2/3
        assert m.apply((0, 1, 2, big)) == pytest.approx(2.0 / 3.0)
        # unseen 4-gram backs off: (2,1,2,0) -> a*( (1,2,0)? unseen ->
        # a*( (2,0)? unseen -> a * c(0)/N ) )
        n = 10.0
        assert m.apply((2, 1, 2, 0)) == pytest.approx(0.4 * 0.4 * 0.4 * 4.0 / n)
        # scores() enumerates all trained ngrams on the host path too
        scores = dict(m.scores())
        assert scores[(0, 1)] == pytest.approx(5.0 / 4.0)  # c(0,1)/c(0), c(0)=4
        assert len(scores) == 4


class TestCoreNLP:
    def test_lemmatize(self):
        assert lemmatize("running") == "run"
        assert lemmatize("cities") == "city"
        assert lemmatize("stopped") == "stop"
        assert lemmatize("children") == "child"
        assert lemmatize("cats") == "cat"
        # e-restoration (Porter *o / at-bl-iz) and irregulars
        assert lemmatize("loved") == "love"
        assert lemmatize("making") == "make"
        assert lemmatize("locating") == "locate"
        assert lemmatize("took") == "take"
        assert lemmatize("wives") == "wife"
        assert lemmatize("falling") == "fall"  # ll not undoubled

    def test_entity_substitution(self):
        ext = CoreNLPFeatureExtractor(orders=(1,))
        grams = ext.apply("The cats saw Paris in 1990.")
        toks = [g[0] for g in grams]
        # typed mentions, like the reference's CoreNLP entity-class strings
        assert "<DATE>" in toks  # 1990
        assert "<LOCATION>" in toks  # Paris
        assert "cat" in toks  # lemmatized
        assert toks[0] == "the"  # sentence-initial capital not an entity

    def test_entity_types_and_run_merging(self):
        ext = CoreNLPFeatureExtractor(orders=(1,))
        toks = [g[0] for g in ext.apply(
            "We met John Smith at Acme Corp near Boston on Monday, "
            "paying 42 dollars."
        )]
        assert "<PERSON>" in toks  # John Smith -> one person mention
        assert "<ORGANIZATION>" in toks  # Acme Corp
        assert "<LOCATION>" in toks  # Boston
        assert "<DATE>" in toks  # Monday
        assert "<NUM>" in toks  # 42
        # John Smith merged into ONE token, not two
        assert toks.count("<PERSON>") == 1

    def test_unknown_capitalized_stays_generic_ent(self):
        ext = CoreNLPFeatureExtractor(orders=(1,))
        toks = [g[0] for g in ext.apply("We visited Xyzzy yesterday.")]
        assert "<ENT>" in toks

    def test_bigrams(self):
        ext = CoreNLPFeatureExtractor(orders=(1, 2))
        grams = ext.apply("dogs run")
        assert ("dog", "run") in grams

    def test_sentence_boundaries_reset_entity_detection(self):
        # 'The' after a period is sentence-initial, not an entity
        ext = CoreNLPFeatureExtractor(orders=(1,))
        toks = [g[0] for g in ext.apply("Dogs bark. The cat saw Berlin. It ran.")]
        assert toks.count("<LOCATION>") == 1  # only mid-sentence Berlin
        assert "the" in toks and "it" in toks

    def test_lowercase_may_is_not_a_date(self):
        ext = CoreNLPFeatureExtractor(orders=(1,))
        toks = [g[0] for g in ext.apply("You may go if they march in May.")]
        assert toks.count("<DATE>") == 1  # only capitalized May
        assert "may" in toks and "march" in toks

    def test_newline_separates_mentions(self):
        # a paragraph break must end the 'Mary' mention (no merge with the
        # next line's leading capital, which becomes sentence-initial)
        ext = CoreNLPFeatureExtractor(orders=(1,))
        toks = [g[0] for g in ext.apply("He met Mary\n\nParis is big")]
        assert "<PERSON>" in toks  # Mary alone, not merged across the break
        assert "paris" in toks  # next line's first token = sentence-initial
        # and mid-sentence mentions after a newline still type correctly
        toks2 = [g[0] for g in ext.apply("He met Mary\nthen saw Paris")]
        assert "<PERSON>" in toks2 and "<LOCATION>" in toks2


class TestFitEncodedEquivalence:
    """fit_encoded (vectorized windows + packed keys + native count_by_key)
    must build the same model as fit over the tuple-based NGrams chain."""

    def _both_models(self, docs, orders, alpha=0.4):
        enc = WordFrequencyEncoder().fit(docs)
        est = StupidBackoffEstimator(enc.unigram_counts, alpha=alpha)
        encoded = enc.apply_batch(docs)
        ngrams = NGramsFeaturizer(orders=orders)(encoded)
        counts = NGramsCounts(mode=NGramsCountsMode.NO_ADD)(ngrams)
        ref = est.fit(counts)
        ids, lengths = enc.encode_padded(docs)
        fast = est.fit_encoded(ids, lengths, orders)
        return ref, fast

    @staticmethod
    def _assert_same_tables(ref, fast):
        assert ref.max_order == fast.max_order
        assert ref.word_bits == fast.word_bits
        assert len(ref.table_keys) == len(fast.table_keys)
        for rk, fk, rc, fc in zip(
            ref.table_keys, fast.table_keys, ref.table_counts, fast.table_counts
        ):
            np.testing.assert_array_equal(np.asarray(rk), np.asarray(fk))
            np.testing.assert_allclose(np.asarray(rc), np.asarray(fc))
        np.testing.assert_allclose(
            np.asarray(ref.unigram_counts), np.asarray(fast.unigram_counts)
        )

    def test_toy_corpus(self):
        docs = [["a", "b", "c"], ["a", "b", "d"], ["b", "c"], ["a"]]
        ref, fast = self._both_models(docs, (2, 3))
        self._assert_same_tables(ref, fast)

    def test_zipf_corpus_with_short_docs(self):
        rng = np.random.default_rng(5)
        vocab = [f"w{i}" for i in range(80)]
        probs = 1.0 / np.arange(1, 81)
        probs /= probs.sum()
        docs = [
            [vocab[i] for i in rng.choice(80, size=int(rng.integers(1, 12)), p=probs)]
            for _ in range(150)
        ]
        ref, fast = self._both_models(docs, (2, 3, 4))
        self._assert_same_tables(ref, fast)
        # and the served scores agree
        q = np.array([[0, 1, 2, 3], [3, 2, 1, 0], [0, 0, 0, 0]], np.int32)
        np.testing.assert_allclose(ref.score_batch(q), fast.score_batch(q), rtol=1e-6)

    def test_oov_windows_dropped(self):
        # encode test-side docs against a vocab missing some words: windows
        # containing OOV (-1) must not enter the tables on either path
        train = [["a", "b"], ["b", "c"]]
        enc = WordFrequencyEncoder().fit(train)
        est = StupidBackoffEstimator(enc.unigram_counts)
        other = [["a", "zz", "b"], ["b", "c", "a"]]
        encoded = enc.apply_batch(other)
        counts = NGramsCounts(mode=NGramsCountsMode.NO_ADD)(
            NGramsFeaturizer(orders=(2,))(encoded)
        )
        ref = est.fit(counts)
        ids, lengths = enc.encode_padded(other)
        fast = est.fit_encoded(ids, lengths, (2,))
        self._assert_same_tables(ref, fast)

    def test_scores_arrays_matches_scores(self):
        docs = [["a", "b", "c"], ["b", "c", "a", "b"]]
        ref, fast = self._both_models(docs, (2, 3))
        flat = [
            (tuple(map(int, ng)), float(s))
            for ngrams, scores in fast.scores_arrays()
            for ng, s in zip(ngrams, scores)
        ]
        assert flat == fast.scores()

    def test_pipeline_both_host_paths_agree(self):
        from keystone_tpu.pipelines.stupid_backoff import StupidBackoffConfig, run

        fast = run(
            StupidBackoffConfig(
                synthetic_docs=300, fast_host_path=True, device_path=False
            )
        )
        slow = run(
            StupidBackoffConfig(
                synthetic_docs=300, fast_host_path=False, device_path=False
            )
        )
        assert fast["num_scored"] == slow["num_scored"]
        assert fast["sample_scores"] == slow["sample_scores"]

    def test_max_order_follows_data_not_request(self):
        # every doc shorter than 3: both paths must produce a max_order-2
        # model (fit derives order from the data; fit_encoded must match)
        docs = [["a", "b"], ["b", "c"], ["a"]]
        ref, fast = self._both_models(docs, (2, 3))
        assert ref.max_order == fast.max_order == 2
        self._assert_same_tables(ref, fast)


class TestFitDeviceEquivalence:
    """fit_device (on-chip sort + segment-reduce counting,
    ops/nlp/device_count.py) must build the same model as fit_encoded —
    table keys/counts, unigrams, and served scores — for both int32-packed
    and int64-packed key widths."""

    def _models(self, docs, orders):
        enc = WordFrequencyEncoder().fit(docs)
        est = StupidBackoffEstimator(enc.unigram_counts, alpha=0.4)
        ids, lengths = enc.encode_padded(docs)
        host = est.fit_encoded(ids, lengths, orders)
        dev = est.fit_device(ids, lengths, orders, enc.vocab_size)
        return host, dev

    @staticmethod
    def _assert_same(host, dev):
        assert dev.table_sizes is not None
        for hk, dk, hc, dc in zip(
            host.table_keys, dev.table_keys, host.table_counts, dev.table_counts
        ):
            np.testing.assert_array_equal(np.asarray(hk), np.asarray(dk))
            np.testing.assert_allclose(np.asarray(hc), np.asarray(dc))
        np.testing.assert_allclose(
            np.asarray(host.unigram_counts), np.asarray(dev.unigram_counts)
        )
        assert float(host.num_tokens) == float(dev.num_tokens)

    def test_toy_corpus(self):
        docs = [["a", "b", "c"], ["a", "b", "d"], ["b", "c"], ["a"]]
        host, dev = self._models(docs, (2, 3))
        self._assert_same(host, dev)
        for (hng, hs), (dng, ds) in zip(host.scores_arrays(), dev.scores_arrays()):
            np.testing.assert_array_equal(hng, dng)
            np.testing.assert_allclose(hs, ds, rtol=1e-6)

    def test_zipf_corpus_and_served_scores(self):
        rng = np.random.default_rng(7)
        vocab = [f"w{i}" for i in range(90)]
        probs = 1.0 / np.arange(1, 91)
        probs /= probs.sum()
        docs = [
            [vocab[i] for i in rng.choice(90, size=int(rng.integers(1, 14)), p=probs)]
            for _ in range(200)
        ]
        host, dev = self._models(docs, (2, 3))
        self._assert_same(host, dev)
        q = np.array([[0, 1, 2], [3, 2, 1], [89, 0, 5], [-1, 0, 1]], np.int32)
        np.testing.assert_allclose(
            host.score_batch(q), dev.score_batch(q), rtol=1e-6
        )
        # scores_device (the self-aligned table fold the pipeline reports)
        # must agree with the host model's scores over the same sorted keys
        host_arrays = host.scores_arrays()
        for (order, keys, s, size), (hng, hs) in zip(
            dev.scores_device(), host_arrays
        ):
            assert size == hng.shape[0]
            np.testing.assert_allclose(np.asarray(s)[:size], hs, rtol=1e-6)

    def test_oov_windows_dropped_on_device(self):
        train = [["a", "b"], ["b", "c"]]
        enc = WordFrequencyEncoder().fit(train)
        est = StupidBackoffEstimator(enc.unigram_counts)
        ids, lengths = enc.encode_padded(enc_docs := [["a", "zz", "b"], ["b", "c", "a"]])
        host = est.fit_encoded(ids, lengths, (2,))
        dev = est.fit_device(ids, lengths, (2,), enc.vocab_size)
        self._assert_same(host, dev)

    def test_int64_key_path(self):
        # vocab wide enough that order-3 keys exceed 30 bits -> int64 sort
        rng = np.random.default_rng(3)
        ids = rng.integers(0, 70000, size=(60, 10)).astype(np.int32)
        lengths = rng.integers(3, 11, size=(60,)).astype(np.int32)
        uni = {}
        for row, n in zip(ids, lengths):
            for w in row[:n]:
                uni[int(w)] = uni.get(int(w), 0) + 1
        est = StupidBackoffEstimator(uni, 0.4)
        host = est.fit_encoded(ids, lengths, (2, 3))
        dev = est.fit_device(ids, lengths, (2, 3))  # vocab from the dict
        assert dev.table_keys[1].dtype.name == "int64"
        self._assert_same(host, dev)
        for (hng, hs), (dng, ds) in zip(host.scores_arrays(), dev.scores_arrays()):
            np.testing.assert_array_equal(hng, dng)
            np.testing.assert_allclose(hs, ds, rtol=1e-6)

    def test_pipeline_device_synthetic_runs(self):
        from keystone_tpu.pipelines.stupid_backoff import StupidBackoffConfig, run

        r = run(StupidBackoffConfig(synthetic_docs=400, device_path=True))
        assert r["num_ngrams"] > 0 and r["num_scored"] == r["num_ngrams"]
        assert len(r["sample_scores"]) > 0
        assert all(s["score"] > 0 for s in r["sample_scores"])
        assert np.isfinite(r["score_checksum"])

    def test_sum_by_key_matches_numpy_unique(self):
        import jax.numpy as jnp

        from keystone_tpu.ops.nlp.device_count import sum_by_key

        rng = np.random.default_rng(11)
        keys = rng.integers(0, 50, size=300).astype(np.int32)
        valid = rng.random(300) < 0.8
        uniq, totals, n = sum_by_key(jnp.asarray(keys), jnp.asarray(valid))
        n = int(n)
        ref_k, ref_c = np.unique(keys[valid], return_counts=True)
        np.testing.assert_array_equal(np.asarray(uniq)[:n], ref_k)
        np.testing.assert_allclose(np.asarray(totals)[:n], ref_c)
        # weighted variant
        w = rng.random(300).astype(np.float32)
        uniq2, totals2, n2 = sum_by_key(
            jnp.asarray(keys), jnp.asarray(valid), jnp.asarray(w)
        )
        ref = {}
        for k, ww in zip(keys[valid], w[valid]):
            ref[int(k)] = ref.get(int(k), 0.0) + float(ww)
        np.testing.assert_allclose(
            np.asarray(totals2)[: int(n2)],
            [ref[int(k)] for k in np.asarray(uniq2)[: int(n2)]],
            rtol=1e-5,
        )


class TestDeviceModelCheckpointing:
    def test_device_fit_backoff_model_roundtrip(self, tmp_path):
        """A device-fit StupidBackoffModel (sentinel-trimmed tables +
        static table_sizes) must checkpoint and reload bit-exactly through
        core.checkpoint — the serving-side artifact of the device path."""
        from keystone_tpu.core.checkpoint import load_node, save_node

        docs = [["a", "b", "c"], ["b", "c", "a", "b"], ["c", "a"]] * 4
        enc = WordFrequencyEncoder().fit(docs)
        ids, lengths = enc.encode_padded(docs)
        est = StupidBackoffEstimator(enc.unigram_counts, 0.4)
        model = est.fit_device(ids, lengths, (2, 3), enc.vocab_size)
        path = str(tmp_path / "backoff.ckpt")
        save_node(model, path)
        loaded = load_node(path)
        assert loaded.table_sizes == model.table_sizes
        q = np.array([[0, 1, 2], [2, 1, 0], [-1, 0, 1]], np.int32)
        np.testing.assert_allclose(
            loaded.score_batch(q), model.score_batch(q)
        )

    def test_device_vectorizer_roundtrip(self, tmp_path):
        from keystone_tpu.core.checkpoint import load_node, save_node
        from keystone_tpu.ops.nlp.device_text import DeviceCommonSparseFeatures

        rng = np.random.default_rng(2)
        ids = rng.integers(0, 50, size=(30, 8)).astype(np.int32)
        lengths = rng.integers(2, 9, size=(30,)).astype(np.int32)
        vec = DeviceCommonSparseFeatures(base=51, orders=(1, 2)).fit(ids, lengths)
        path = str(tmp_path / "vec.ckpt")
        save_node(vec, path)
        loaded = load_node(path)
        a = np.asarray(vec.apply_encoded(ids, lengths).to_dense())
        b = np.asarray(loaded.apply_encoded(ids, lengths).to_dense())
        np.testing.assert_allclose(a, b)


def test_sum_by_key_fuzz_matches_numpy(rng):
    """Randomized sweep of the device reduceByKey primitive across sizes,
    dtypes, validity densities, and weighted/unweighted modes."""
    import jax
    import jax.numpy as jnp

    from keystone_tpu.ops.nlp.device_count import sum_by_key

    for trial in range(12):
        n = int(rng.integers(1, 400))
        hi = int(rng.integers(2, 1000))
        dt = np.int32 if trial % 2 else np.int64
        keys = rng.integers(0, hi, size=n).astype(dt)
        valid = rng.random(n) < rng.random()  # varying density incl. ~0
        weights = rng.random(n).astype(np.float32) if trial % 3 == 0 else None
        with jax.enable_x64():
            uniq, totals, cnt = sum_by_key(
                jnp.asarray(keys), jnp.asarray(valid),
                None if weights is None else jnp.asarray(weights),
            )
        cnt = int(cnt)
        ref_k = np.unique(keys[valid])
        assert cnt == len(ref_k), (trial, cnt, len(ref_k))
        np.testing.assert_array_equal(np.asarray(uniq)[:cnt], ref_k)
        ref_tot = {}
        for k, v, w in zip(
            keys, valid, weights if weights is not None else np.ones(n)
        ):
            if v:
                ref_tot[int(k)] = ref_tot.get(int(k), 0.0) + float(w)
        np.testing.assert_allclose(
            np.asarray(totals)[:cnt],
            [ref_tot[int(k)] for k in ref_k],
            rtol=1e-5, atol=1e-5,
        )


class TestTrimlessDeviceFit:
    """trim=False (the pipeline's single-round-trip path) must expose the
    SAME model behavior as the trimmed fit through every public surface —
    padded tables are an internal layout, never a semantic difference."""

    def test_padded_model_matches_trimmed_everywhere(self):
        docs = [["a", "b", "c"], ["a", "b", "d"], ["b", "c"], ["c", "a", "b"]] * 3
        enc = WordFrequencyEncoder().fit(docs)
        ids, lengths = enc.encode_padded(docs)
        est = StupidBackoffEstimator(enc.unigram_counts, 0.4)
        trimmed = est.fit_device(ids, lengths, (2, 3), enc.vocab_size)
        padded = est.fit_device(
            ids, lengths, (2, 3), enc.vocab_size, trim=False
        )
        assert padded.table_sizes is None
        assert padded.table_sizes_dev is not None
        # scores_arrays pulls the device sizes itself and must trim
        sa_t, sa_p = trimmed.scores_arrays(), padded.scores_arrays()
        assert len(sa_t) == len(sa_p)
        for (ng_t, s_t), (ng_p, s_p) in zip(sa_t, sa_p):
            np.testing.assert_array_equal(ng_t, ng_p)
            np.testing.assert_allclose(s_t, s_p, rtol=1e-6)
        # scores_device sizes (device scalars) match the trimmed statics
        for (o_t, k_t, s_t, sz_t), (o_p, k_p, s_p, sz_p) in zip(
            trimmed.scores_device(), padded.scores_device()
        ):
            assert o_t == o_p
            assert sz_t == int(sz_p)
            np.testing.assert_allclose(
                np.asarray(s_p)[: int(sz_p)], np.asarray(s_t)[:sz_t], rtol=1e-6
            )
        # query scoring identical
        q = np.array([[0, 1, 2], [2, 1, 0], [-1, 0, 1]], np.int32)
        np.testing.assert_allclose(
            trimmed.score_batch(q), padded.score_batch(q), rtol=1e-6
        )

    def test_pipeline_reports_match_across_trim_modes(self, monkeypatch):
        from keystone_tpu.pipelines import stupid_backoff as sb

        r_dev = sb.run(sb.StupidBackoffConfig(synthetic_docs=250))
        # force the trimmed path by making the trimless predicate false
        monkeypatch.setattr(
            sb.StupidBackoffEstimator, "fit_device",
            lambda self, ids, lengths, orders, vocab=None, trim=True,
            _orig=sb.StupidBackoffEstimator.fit_device:
            _orig(self, ids, lengths, orders, vocab, trim=True),
        )
        r_trim = sb.run(sb.StupidBackoffConfig(synthetic_docs=250))
        assert r_dev["num_ngrams"] == r_trim["num_ngrams"]
        assert r_dev["sample_scores"] == r_trim["sample_scores"]
        np.testing.assert_allclose(
            r_dev["score_checksum"], r_trim["score_checksum"], rtol=1e-5
        )
