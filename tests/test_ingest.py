"""Streaming out-of-core ingest (``core/ingest.py`` + the loaders growth).

The tier's contracts, each pinned through the real entry points:

- ``prefetch_map`` consumes LAZILY through a windowed deque — an unbounded
  iterable flows through without materializing (the old ``list(items)``
  defeated out-of-core streaming), with the error-at-own-yield semantics
  intact on the windowed path.
- The native loader's name plumbing survives GNU long names: batches
  refill instead of silently truncating the tail of the name list.
- Native and pure-Python fallback paths agree on a synthetic tar set.
- ``BucketedImageLoader`` bucket selection (exact fit stays in its bucket,
  partial per-bucket batches flush at end of input) and
  ``_threaded_image_iter`` abandoned-generator cleanup.
- ``KEYSTONE_INGEST_BUFFERS`` provably bounds live decoded batches (the
  ``ingest.buffers_live`` gauge family), every buffer recycles, and an
  abandoned stream leaks neither threads nor leases.
- ``stream_batches`` always yields the FULL fixed ring shape (zero-padded
  final batch): one compile, zero steady-state recompiles.
- Fault surface: an undecodable JPEG costs one image, a corrupt archive
  costs one archive — the stream completes either way.
- ``TarIngestNode`` is a declared host stage the checker/planner pass can
  cost (no C5 un-evaluable hole).
"""

import gc
import io
import os
import tarfile
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.core.ingest import (
    HostBufferRing,
    StreamingTarIngest,
    TarIngestNode,
    frame_into,
    stream_batches,
)
from keystone_tpu.core.prefetch import prefetch_map
from keystone_tpu.telemetry import get_registry


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _jpeg_bytes(arr: np.ndarray) -> bytes:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, "JPEG", quality=92)
    return buf.getvalue()


def _write_tar(path, entries, fmt=tarfile.USTAR_FORMAT):
    with tarfile.open(path, "w", format=fmt) as tf:
        for name, payload in entries:
            ti = tarfile.TarInfo(name)
            ti.size = len(payload)
            tf.addfile(ti, io.BytesIO(payload))


def _make_tarset(tmp_path, num_tars=2, per_tar=8, hw=48, seed=3):
    rng = np.random.default_rng(seed)
    paths = []
    for t in range(num_tars):
        entries = []
        for i in range(per_tar):
            arr = (rng.uniform(0, 1, size=(hw, hw, 3)) * 255).astype(np.uint8)
            entries.append((f"cls{i % 2}/im_{t}_{i}.jpg", _jpeg_bytes(arr)))
        p = tmp_path / f"part{t}.tar"
        _write_tar(p, entries)
        paths.append(str(p))
    return paths


def _native_lib_or_none():
    from keystone_tpu.native.ingest import _get_lib

    return _get_lib()


# ---------------------------------------------------------------------------
# prefetch_map: windowed, streaming-safe (satellite 1)
# ---------------------------------------------------------------------------


def test_prefetch_map_streams_lazy_infinite_iterator():
    """The old ``items = list(items)`` hung forever here: an UNBOUNDED
    iterator must flow through with at most depth+1 items pulled ahead of
    the yield cursor."""
    pulled = []

    def infinite():
        i = 0
        while True:
            pulled.append(i)
            yield i
            i += 1

    depth = 2
    gen = prefetch_map(lambda i: i * 10, infinite(), depth=depth)
    got = [next(gen) for _ in range(7)]
    assert got == [i * 10 for i in range(7)]
    # windowed laziness: never more than depth+1 raw items ahead of the
    # yield cursor (7 yielded, so at most 7 + depth + 1 ever pulled)
    assert len(pulled) <= 7 + depth + 1
    gen.close()


def test_prefetch_map_window_bound_holds_at_every_yield():
    """The run-ahead window stays bounded THROUGHOUT a long lazy stream,
    not just at the end — the peak-memory contract streaming ingest
    rides."""
    n_pulled = 0

    def lazy(n):
        nonlocal n_pulled
        for i in range(n):
            n_pulled += 1
            yield i

    depth = 3
    worst = 0
    n_yielded = 0
    for v in prefetch_map(lambda i: i + 1, lazy(60), depth=depth):
        n_yielded += 1
        worst = max(worst, n_pulled - n_yielded)
    assert n_yielded == 60
    assert worst <= depth + 1, worst


def test_prefetch_map_error_at_own_yield_on_lazy_stream():
    """Windowed mode keeps the error-at-own-yield contract: values before
    a mid-stream producer failure are all served first, and the failure
    surfaces exactly at its own position — on a GENERATOR input."""

    def produce(i):
        if i == 4:
            raise RuntimeError("boom at 4")
        return i

    got = []
    gen = prefetch_map(produce, iter(range(100)), depth=3)
    with pytest.raises(RuntimeError, match="boom at 4"):
        for v in gen:
            got.append(v)
    assert got == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# native loader name plumbing (satellite 2)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(_native_lib_or_none() is None,
                    reason="native ingest library unavailable")
def test_native_loader_long_names_no_tail_truncation(tmp_path):
    """GNU long names near the walker's 4096-char cap round-trip through
    the batched native loader with names and images ALIGNED — the old
    fixed per-call name buffer silently truncated the tail of the name
    list instead of refilling."""
    from keystone_tpu.native import PrefetchImageLoader

    hw = 40
    entries = []
    imgs = {}
    for i in range(6):
        name = f"cls{i}/" + "x" * 3800 + f"_{i}.jpg"
        # solid colors survive JPEG almost losslessly, so a shifted
        # name->image pairing is unambiguous (noise would drown in
        # lossy-codec error)
        arr = np.full((hw, hw, 3), 30 + 30 * i, np.uint8)
        entries.append((name, _jpeg_bytes(arr)))
        imgs[name] = arr
    _write_tar(tmp_path / "long.tar", entries, fmt=tarfile.GNU_FORMAT)

    loader = PrefetchImageLoader([str(tmp_path / "long.tar")], hw, hw,
                                 num_threads=2)
    seen = {}
    for batch, names in loader.batches(6):
        assert batch.shape[0] == len(names)
        for j, n in enumerate(names):
            seen[n] = batch[j]
    assert set(seen) == set(imgs), "tail of the long-name list lost"
    # alignment: each name's frame matches ITS image (not a shifted one)
    for name, arr in imgs.items():
        expect = arr.astype(np.float32) / 255.0
        assert float(np.abs(seen[name] - expect).mean()) < 0.02, name


# ---------------------------------------------------------------------------
# native vs pure-Python fallback parity (satellite 3)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(_native_lib_or_none() is None,
                    reason="native ingest library unavailable")
def test_native_vs_python_fallback_batch_parity(tmp_path, monkeypatch):
    """The two PrefetchImageLoader paths agree on a synthetic tar set:
    same entry names, same image count, pixels within JPEG-decoder
    tolerance."""
    from keystone_tpu.native import ingest as native_ingest
    from keystone_tpu.native.ingest import PrefetchImageLoader

    tars = _make_tarset(tmp_path, num_tars=2, per_tar=6)

    def collect():
        out = {}
        loader = PrefetchImageLoader(tars, 48, 48, num_threads=2)
        for batch, names in loader.batches(4):
            for j, n in enumerate(names):
                out[n] = batch[j].copy()
        return out

    native = collect()
    monkeypatch.setattr(native_ingest, "_lib", None)
    monkeypatch.setattr(native_ingest, "_build_attempted", True)
    fallback = collect()
    assert set(native) == set(fallback) and len(native) == 12
    worst = max(
        float(np.abs(native[k] - fallback[k]).mean()) for k in native
    )
    assert worst <= 2.0 / 255.0, worst


def test_streaming_ingest_frames_match_center_frame(tmp_path):
    """``frame_into`` (the in-place ring-slot form) must produce exactly
    the loaders' ``_center_frame`` result — including re-zeroed padding on
    a recycled buffer — for undersize, exact and oversize images."""
    from keystone_tpu.native.ingest import _center_frame

    rng = np.random.default_rng(11)
    out = np.empty((64, 64, 3), np.float32)
    out[:] = 7.0  # dirty recycled-slot contents
    for shape in [(40, 50), (64, 64), (100, 80)]:
        img = (rng.uniform(0, 1, size=(*shape, 3)) * 255).astype(np.uint8)
        frame_into(img, out)
        np.testing.assert_array_equal(out, _center_frame(img, 64, 64))
        out[:] = 7.0


# ---------------------------------------------------------------------------
# BucketedImageLoader selection + _threaded_image_iter cleanup (satellite 3)
# ---------------------------------------------------------------------------


def test_bucketed_loader_exact_fit_and_partial_flush(tmp_path):
    """An image exactly matching a bucket lands in THAT bucket (not a
    larger one), and partial per-bucket batches flush at end of input."""
    from keystone_tpu.native import BucketedImageLoader

    rng = np.random.default_rng(4)

    def img(h, w):
        return (rng.uniform(0, 1, size=(h, w, 3)) * 255).astype(np.uint8)

    entries = [
        ("a/exact.jpg", _jpeg_bytes(img(64, 64))),       # exact fit
        ("a/small.jpg", _jpeg_bytes(img(40, 40))),       # pads into (64,64)
        ("a/mid.jpg", _jpeg_bytes(img(90, 90))),         # pads into (128,128)
    ]
    _write_tar(tmp_path / "b.tar", entries)
    loader = BucketedImageLoader(
        [str(tmp_path / "b.tar")], buckets=[(64, 64), (128, 128)],
        num_threads=1,
    )
    got = {}
    for hw, imgs, names in loader.batches(batch_size=8):
        assert imgs.shape[1:] == (*hw, 3)
        got.setdefault(hw, []).extend(n.split("/")[-1] for n in names)
    # batch_size 8 was never reached: BOTH buckets flushed partial batches
    assert sorted(got[(64, 64)]) == ["exact.jpg", "small.jpg"]
    assert got[(128, 128)] == ["mid.jpg"]


def test_threaded_image_iter_abandoned_early_break_no_leaked_threads(
        tmp_path):
    """Abandoning ``_threaded_image_iter`` (early break) must stop its
    worker threads — no thread pinned on a full queue after the consumer
    walks away."""
    from keystone_tpu.native.ingest import _threaded_image_iter

    tars = _make_tarset(tmp_path, num_tars=2, per_tar=10)
    before = threading.active_count()
    it = _threaded_image_iter(tars, num_threads=3)
    next(it)
    it.close()  # runs the generator's finally: stop + drain + join
    gc.collect()
    deadline = time.monotonic() + 5.0
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before


# ---------------------------------------------------------------------------
# ring bound + recycle (acceptance: KEYSTONE_INGEST_BUFFERS bounds memory)
# ---------------------------------------------------------------------------


def test_ingest_buffers_knob_bounds_live_batches(tmp_path, monkeypatch):
    """The acceptance gauge pin: with KEYSTONE_INGEST_BUFFERS=2 the
    ``ingest.buffers_live_peak`` gauge never exceeds 2 across a stream of
    many more batches than buffers, and every lease is recycled by stream
    end (live == 0)."""
    monkeypatch.setenv("KEYSTONE_INGEST_BUFFERS", "2")
    tars = _make_tarset(tmp_path, num_tars=2, per_tar=12)
    ingest = StreamingTarIngest(tars, (48, 48), batch_size=4, num_threads=2)
    assert ingest.num_buffers == 2  # the knob resolved
    reg = get_registry()
    n_batches = 0
    for batch in ingest.batches():
        n_batches += 1
        assert reg.get_gauge("ingest.buffers_live") <= 2
        batch.release()
    assert n_batches >= 6  # many more batches than buffers: recycling real
    assert reg.get_gauge("ingest.buffers_live_peak") <= 2
    assert reg.get_gauge("ingest.buffers_live") == 0


def test_ring_acquire_blocks_until_release():
    """``HostBufferRing.acquire`` IS the memory bound: with every buffer
    leased the next acquire blocks until a release."""
    ring = HostBufferRing(2, (1, 4, 4, 3))
    a = ring.acquire()
    b = ring.acquire()
    assert {a, b} == {0, 1}
    got = []

    def blocked():
        got.append(ring.acquire())

    t = threading.Thread(target=blocked, daemon=True)
    t.start()
    time.sleep(0.2)
    assert got == []  # still blocked: the ring is the bound
    ring.release(a)
    t.join(timeout=5.0)
    assert got == [a]
    ring.release(b)
    ring.release(got[0])


def test_abandoned_stream_stops_workers_and_recycles(tmp_path):
    """Early break out of ``StreamingTarIngest.batches`` stops the decode
    workers and recycles every lease — no thread or buffer leaks (the
    wedge class an abandoned consumer used to risk)."""
    tars = _make_tarset(tmp_path, num_tars=2, per_tar=12)
    before = threading.active_count()
    reg = get_registry()
    ingest = StreamingTarIngest(tars, (48, 48), batch_size=4,
                                num_threads=2, num_buffers=2)
    for batch in ingest.batches():
        break  # abandon mid-stream, lease not even released
    gc.collect()
    deadline = time.monotonic() + 10.0
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before
    assert reg.get_gauge("ingest.buffers_live") == 0


# ---------------------------------------------------------------------------
# stream_batches: fixed shape, zero recompiles, padded tail
# ---------------------------------------------------------------------------


def test_stream_batches_fixed_shape_zero_recompiles(tmp_path):
    """Steady-state streaming consumers compile EXACTLY once: every
    yielded device batch has the full fixed ring shape, the final partial
    batch is zero-padded (not shape-changed), and the jitted per-batch
    program's cache holds one entry after the whole stream."""
    tars = _make_tarset(tmp_path, num_tars=1, per_tar=10)

    @jax.jit
    def consume(x):
        return x.sum(axis=(1, 2, 3))

    bs = 4  # 10 images -> 2 full batches + 1 padded partial
    totals = []
    for arr, names, n in stream_batches(
        StreamingTarIngest(tars, (48, 48), bs, num_threads=2,
                           num_buffers=2)
    ):
        assert arr.shape == (bs, 48, 48, 3)
        if n < bs:  # the padded tail: zeroed, not stale recycled pixels
            assert float(jnp.abs(arr[n:]).max()) == 0.0
        totals.append(int(n))
        consume(arr).block_until_ready()
    assert sum(totals) == 10 and totals[-1] == 2
    assert consume._cache_size() == 1


# ---------------------------------------------------------------------------
# fault surface: bad JPEG, corrupt archive
# ---------------------------------------------------------------------------


def test_undecodable_entry_costs_one_image_not_the_stream(tmp_path):
    """A garbage JPEG payload is skipped with the ``ingest.bad_images``
    counter — the stream completes with every other image."""
    rng = np.random.default_rng(6)
    hw = 48
    entries = []
    for i in range(5):
        arr = (rng.uniform(0, 1, size=(hw, hw, 3)) * 255).astype(np.uint8)
        entries.append((f"a/ok_{i}.jpg", _jpeg_bytes(arr)))
    entries.insert(2, ("a/garbage.jpg", b"\xff\xd8 not a real jpeg"))
    _write_tar(tmp_path / "bad.tar", entries)
    reg = get_registry()
    bad0 = reg.get_counter("ingest.bad_images")
    names = []
    for arr, batch_names, n in stream_batches(
        StreamingTarIngest([str(tmp_path / "bad.tar")], (hw, hw), 2)
    ):
        names.extend(batch_names[:n])
    assert sorted(names) == [f"a/ok_{i}.jpg" for i in range(5)]
    assert reg.get_counter("ingest.bad_images") - bad0 >= 1


def test_corrupt_archive_costs_one_archive_not_the_stream(tmp_path):
    """A non-tar file in the set charges ``ingest.tar_errors`` and the
    OTHER archive's images all arrive — one bad archive never wedges the
    pool."""
    tars = _make_tarset(tmp_path, num_tars=1, per_tar=6)
    junk = tmp_path / "junk.tar"
    junk.write_bytes(b"this is not a tar archive at all" * 8)
    reg = get_registry()
    e0 = reg.get_counter("ingest.tar_errors")
    n_tot = sum(
        n for _, _, n in stream_batches(
            StreamingTarIngest([tars[0], str(junk)], (48, 48), 4,
                               num_threads=2, num_buffers=2)
        )
    )
    assert n_tot == 6
    assert reg.get_counter("ingest.tar_errors") - e0 >= 1


# ---------------------------------------------------------------------------
# planner/checker integration: ingest as a declared host stage
# ---------------------------------------------------------------------------


def test_tar_ingest_node_is_declared_host_stage(tmp_path):
    """``TarIngestNode`` declares its C5 ``__contract__`` transfer: the
    shared propagation pass sees ONE bounded ring batch (no un-evaluable
    hole), and the planner cost table prices the stage instead of
    degrading to an unbounded plan."""
    from keystone_tpu.analysis import contracts
    from keystone_tpu.core.pipeline import chain
    from keystone_tpu.core.plan import pipeline_costs
    from keystone_tpu.ops.images import GrayScaler

    tars = _make_tarset(tmp_path, num_tars=1, per_tar=4)
    node = TarIngestNode.create(tars, (48, 48), batch_size=4)
    assert node.jittable is False and node.memoizable is False
    pipe = chain(node, GrayScaler())
    records = contracts.propagate_pipeline(
        pipe, contracts.spec_struct(1)
    )
    assert records[0].declared is True
    assert records[0].issue is None
    lead = contracts.leading_leaf(records[0].out_aval)
    assert tuple(lead.shape) == (4, 48, 48, 3)
    # downstream stages see the declared batch (the checker can propagate
    # THROUGH ingest), and the planner prices every stage: bounded peaks
    costs = pipeline_costs(pipe, contracts.spec_struct(1),
                           with_flops=False)
    assert costs[0].jittable is False  # host stage = boundary
    assert all(c.peak_hbm_bytes is not None for c in costs)
    assert costs[0].out_bytes == 4 * 48 * 48 * 3 * 4


def test_tar_ingest_node_apply_batch_probe(tmp_path):
    """``apply_batch`` is the sampling probe: it materializes the FIRST
    decoded batch only (seeding PCA/GMM fits), releasing its lease."""
    tars = _make_tarset(tmp_path, num_tars=1, per_tar=6)
    node = TarIngestNode.create(tars, (48, 48), batch_size=4)
    out = node.apply_batch()
    assert out.shape == (4, 48, 48, 3)
    assert get_registry().get_gauge("ingest.buffers_live") == 0


# ---------------------------------------------------------------------------
# review-pass regressions: claim/flush deadlock, last-worker death, native
# mid-payload truncation
# ---------------------------------------------------------------------------


def test_exhausted_ring_with_slow_consumer_no_deadlock(tmp_path):
    """A worker must never block on the ring while holding the claim lock:
    with every buffer live and a sealed batch still missing a peer's
    ``_finish_fill``, that flush needs the same lock — the old in-lock
    ``ring.acquire`` wedged the stream. One buffer, several workers, and a
    slow consumer drive exactly that contention; the stream must still
    deliver every image."""
    tars = _make_tarset(tmp_path, num_tars=2, per_tar=12, seed=21)
    done = {}

    def consume():
        total = 0
        for batch in StreamingTarIngest(
            tars, (48, 48), 4, num_threads=4, num_buffers=1
        ).batches():
            time.sleep(0.02)  # slow consumer: workers pile up on the ring
            total += batch.n_valid
            batch.release()
        done["total"] = total

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    t.join(timeout=60.0)
    assert not t.is_alive(), "streaming ingest deadlocked on the ring"
    assert done["total"] == 24


def test_last_worker_death_respawns_no_data_loss(tmp_path, monkeypatch):
    """A single-worker pool whose worker dies has no survivors to re-run
    the re-queued archive — the dying LAST worker must respawn a
    replacement instead of shipping the done sentinel over pending work
    (the old path completed cleanly with the tail of the dataset silently
    missing)."""
    from keystone_tpu.utils import faults

    tars = _make_tarset(tmp_path, num_tars=3, per_tar=5, seed=22)
    monkeypatch.setenv("KEYSTONE_FAULTS", "ingest.worker@1")
    faults.reset()
    reg = get_registry()
    d0 = reg.get_counter("ingest.worker_deaths")
    r0 = reg.get_counter("ingest.worker_respawns")
    try:
        names = []
        for _, batch_names, n in stream_batches(
            StreamingTarIngest(tars, (48, 48), 4,
                               num_threads=1, num_buffers=2)
        ):
            names.extend(batch_names[:n])
    finally:
        monkeypatch.delenv("KEYSTONE_FAULTS")
        faults.reset()
    assert len(names) == 15 and len(set(names)) == 15
    assert reg.get_counter("ingest.worker_deaths") - d0 >= 1
    assert reg.get_counter("ingest.worker_respawns") - r0 >= 1


@pytest.mark.skipif(_native_lib_or_none() is None,
                    reason="native ingest library unavailable")
def test_native_mid_payload_truncation_raises_like_fallback(tmp_path):
    """A tar cut mid-payload must raise ``tarfile.ReadError`` on the
    NATIVE walker too — the old path yielded the short entry as if whole
    and ended the archive as a clean EOF, diverging from the fallback and
    from the truncated-tar fault accounting."""
    from keystone_tpu.native.ingest import iter_tar_entries

    rng = np.random.default_rng(23)
    arr = (rng.uniform(0, 1, size=(64, 64, 3)) * 255).astype(np.uint8)
    whole = tmp_path / "whole.tar"
    _write_tar(whole, [("a/one.jpg", _jpeg_bytes(arr)),
                       ("a/two.jpg", _jpeg_bytes(arr))])
    blob = whole.read_bytes()
    with tarfile.open(whole) as tf:
        two = tf.getmembers()[1]
        cut_at = two.offset_data + two.size // 2  # mid two.jpg's payload
    cut = tmp_path / "cut.tar"
    cut.write_bytes(blob[:cut_at])
    with pytest.raises(tarfile.ReadError):
        list(iter_tar_entries(str(cut)))
    # and the streaming tier charges it as ONE bad archive, no wedge
    reg = get_registry()
    e0 = reg.get_counter("ingest.tar_errors")
    n_tot = sum(
        n for _, _, n in stream_batches(
            StreamingTarIngest([str(cut)], (48, 48), 2, num_threads=1)
        )
    )
    assert n_tot >= 1  # the whole leading entry still arrives
    assert reg.get_counter("ingest.tar_errors") - e0 == 1


def test_transfer_survives_ring_buffer_mutation(tmp_path):
    """The ring slot is recycled only after the transfer COMPLETES —
    PJRT host-buffer semantics are backend-dependent (a device DMA may
    still be reading the numpy buffer when ``device_put`` returns), so
    ``stream_batches`` must block on transfer readiness before release.
    Pin it end to end: overwrite every ring buffer the moment each batch
    is yielded; the already-yielded device arrays must keep their
    pixels."""
    tars = _make_tarset(tmp_path, num_tars=1, per_tar=6, seed=24)
    ingest = StreamingTarIngest(tars, (48, 48), 2, num_threads=1,
                                num_buffers=2)
    arrs = []
    for arr, _, n in stream_batches(ingest, depth=1):
        host = np.array(arr)  # snapshot before poisoning the ring
        for i in range(ingest.ring.num_buffers):
            ingest.ring.buffer(i)[:] = -7.0  # stomp every slot
        arrs.append((arr, host, n))
    assert len(arrs) == 3
    for arr, host, _ in arrs:
        np.testing.assert_array_equal(np.array(arr), host)


def test_abandoned_stream_with_dead_workers_recycles_queued_leases(tmp_path):
    """Abandoning the generator AFTER the workers already exited (their
    final batches flushed and queued) must still recycle every queued
    lease — the drain loop used to stop at 'no thread alive' and leak
    them, leaving ``ingest.buffers_live`` pinned above zero."""
    tars = _make_tarset(tmp_path, num_tars=1, per_tar=8, seed=25)
    ingest = StreamingTarIngest(tars, (48, 48), 2, num_threads=1,
                                num_buffers=4)
    gen = ingest.batches()
    first = next(gen)
    first.release()
    # let the single worker decode the whole tiny set and exit: the
    # remaining batches now sit flushed in the ready queue, workers gone
    deadline = time.monotonic() + 10.0
    while any(t.is_alive() for t in ingest._last_state["threads"]):
        if time.monotonic() > deadline:
            raise AssertionError("worker did not finish the tiny tar set")
        time.sleep(0.02)
    gen.close()  # abandon with queued batches and no live workers
    assert get_registry().get_gauge("ingest.buffers_live") == 0
