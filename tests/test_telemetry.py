"""Structured telemetry: registry semantics (concurrent, resettable,
exportable), Chrome-trace span schema, the instrumented layers' counters
(overlap engagement/fallback asserted from the REGISTRY, not log text),
Timer thread-safety + registry routing, the ``KEYSTONE_SYNC_TIMERS``
failure-visibility satellite, and the ``telemetry-report`` CLI."""

import json
import logging
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu import telemetry
from keystone_tpu.telemetry.registry import MetricsRegistry


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_counter_gauge_histogram_roundtrip():
    reg = MetricsRegistry()
    reg.inc("requests", 2, site="a")
    reg.inc("requests", site="a")
    reg.inc("requests", site="b")
    reg.set_gauge("depth", 3)
    for v in (0.5, 1.5, 2.5):
        reg.observe("latency", v)

    assert reg.get_counter("requests", site="a") == 3
    assert reg.get_counter("requests", site="b") == 1
    assert reg.get_counter("requests", site="missing") == 0
    assert reg.get_gauge("depth") == 3
    h = reg.get_histogram("latency")
    assert h["count"] == 3 and h["min"] == 0.5 and h["max"] == 2.5
    assert h["sum"] == pytest.approx(4.5)

    d = reg.as_dict()
    assert d["counters"]["requests{site=a}"] == 3
    assert d["gauges"]["depth"] == 3
    assert d["histograms"]["latency"]["count"] == 3
    # label-order independence: same series either way
    reg.inc("multi", x="1", y="2")
    reg2 = MetricsRegistry()
    reg2.inc("multi", y="2", x="1")
    assert (
        list(reg.counters("multi")) == list(reg2.counters("multi"))
    )


def test_registry_prefix_sums_and_reset():
    reg = MetricsRegistry()
    reg.inc("overlap.fallback", 2, site="x")
    reg.inc("overlap.fallback", 1, site="y")
    reg.inc("overlap.engaged", site="x")
    assert reg.sum_counters("overlap.fallback") == 3
    assert set(reg.counters("overlap.")) == {
        "overlap.fallback{site=x}", "overlap.fallback{site=y}",
        "overlap.engaged{site=x}",
    }
    reg.reset()
    assert reg.as_dict() == {"counters": {}, "gauges": {}, "histograms": {}}


def test_registry_concurrent_writers_exact_totals():
    """8 writer threads × 500 ops each, with a reader exporting mid-flight:
    no op may be lost or double-counted, and exports must never crash."""
    reg = MetricsRegistry()
    threads, errors = [], []

    def writer(tid: int):
        try:
            for i in range(500):
                reg.inc("work", thread=tid % 2)
                reg.observe("obs", float(i))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader():
        try:
            for _ in range(50):
                reg.as_dict()
                reg.to_jsonl()
                reg.to_prometheus()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    for t in range(8):
        threads.append(threading.Thread(target=writer, args=(t,)))
    threads.append(threading.Thread(target=reader))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert reg.get_counter("work", thread=0) + reg.get_counter(
        "work", thread=1
    ) == 8 * 500
    assert reg.get_histogram("obs")["count"] == 8 * 500


def test_registry_jsonl_and_prometheus_export():
    reg = MetricsRegistry()
    reg.inc("cache.hit", 4, tier="device")
    reg.set_gauge("prefetch.depth", 2)
    reg.observe("timer.fit", 0.25)

    lines = [json.loads(l) for l in reg.to_jsonl().strip().splitlines()]
    by_name = {(l["type"], l["name"]): l for l in lines}
    assert by_name[("counter", "cache.hit")]["value"] == 4
    assert by_name[("counter", "cache.hit")]["labels"] == {"tier": "device"}
    assert by_name[("gauge", "prefetch.depth")]["value"] == 2
    assert by_name[("histogram", "timer.fit")]["count"] == 1

    prom = reg.to_prometheus()
    assert "# TYPE keystone_cache_hit counter" in prom
    assert 'keystone_cache_hit{tier="device"} 4' in prom
    assert "# TYPE keystone_timer_fit histogram" in prom
    assert "keystone_timer_fit_count 1" in prom
    assert 'le="+Inf"' in prom


# ---------------------------------------------------------------------------
# spans / Chrome trace schema
# ---------------------------------------------------------------------------

def test_span_noop_when_tracing_off():
    tracer = telemetry.get_tracer()
    before = len(tracer)
    with tracer.span("invisible") as sp:
        assert sp.track("value") == "value"
        sp.set(anything=1)
    assert len(tracer) == before
    assert not telemetry.tracing_enabled()


def test_chrome_trace_schema_and_nesting(tmp_path):
    tracer = telemetry.get_tracer()
    with telemetry.use_tracing(True):
        with tracer.span("outer", sync=False) as sp:
            sp.set(flops=2e9)
            with tracer.span("child_a", sync=False):
                pass
            with tracer.span("child_b", sync=False):
                pass

    path = tmp_path / "trace.json"
    tracer.export_chrome_trace(str(path))
    trace = json.loads(path.read_text())  # valid JSON
    events = trace["traceEvents"]
    assert len(events) == 3
    for ev in events:
        for field in ("name", "ph", "ts", "dur", "pid", "tid", "args"):
            assert field in ev, (field, ev)
        assert ev["ph"] == "X"
        assert ev["dur"] > 0
    by_name = {e["name"]: e for e in events}
    outer, a, b = by_name["outer"], by_name["child_a"], by_name["child_b"]
    # children nest strictly inside the parent interval, siblings disjoint
    for child in (a, b):
        assert child["ts"] >= outer["ts"]
        assert child["ts"] + child["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert a["ts"] + a["dur"] <= b["ts"] + 1e-3
    # flops -> achieved GFLOPs derived at export
    assert outer["args"]["achieved_gflops"] > 0
    # dispatch-vs-synced: both recorded, dispatch <= total
    spans = tracer.spans_as_dicts()
    for s in spans:
        assert s["dispatch_us"] <= s["dur_us"] + 1e-3
    depths = {s["name"]: s["depth"] for s in spans}
    assert depths == {"outer": 0, "child_a": 1, "child_b": 1}


def test_chain_run_produces_perfetto_loadable_trace(tmp_path):
    """Acceptance: a Chain run under the tracer yields per-stage spans
    (keyed by structural fingerprint) and a loadable Chrome trace."""
    from keystone_tpu.core.pipeline import Cacher, Transformer, chain

    class Add(Transformer):
        def apply(self, x):
            return x + 1.0

    class Scale(Transformer):
        def apply(self, x):
            return x * 2.0

    c = chain(Add(), Cacher(), Scale())
    with telemetry.use_tracing(True):
        out = c(jnp.ones((16, 4)))
    assert float(out[0, 0]) == 4.0

    spans = telemetry.get_tracer().spans_as_dicts()
    stage_spans = [s for s in spans if s["name"].startswith("stage:")]
    assert {s["name"] for s in stage_spans} == {
        "stage:Add", "stage:Cacher", "stage:Scale"
    }
    for s in stage_spans:
        assert s["args"]["fingerprint"]
        assert s["args"]["in_shapes"] and s["args"]["out_shapes"]
        assert s["args"]["in_bytes"] > 0
    # chain-level parent span encloses the stages
    assert any(s["name"].startswith("chain:") for s in spans)

    path = tmp_path / "chain_trace.json"
    telemetry.get_tracer().export_chrome_trace(str(path))
    trace = json.loads(path.read_text())
    assert len(trace["traceEvents"]) == len(spans)
    # same fingerprint on a refit-equivalent node, different on a new shape
    from keystone_tpu.telemetry import stage_fingerprint

    assert stage_fingerprint(Add()) == stage_fingerprint(Add())
    assert stage_fingerprint(jnp.ones((4,))) != stage_fingerprint(
        jnp.ones((5,))
    )


# ---------------------------------------------------------------------------
# instrumented layers
# ---------------------------------------------------------------------------

def test_overlap_counters_from_registry_no_log_scraping(devices):
    """Engagement and fallback asserted straight off the registry — the
    bench/test contract the once-per-shape log cannot provide."""
    from keystone_tpu.parallel import overlap as ov
    from keystone_tpu.parallel.mesh import make_mesh

    reg = telemetry.get_registry()
    mesh = make_mesh()
    x = np.asarray(
        np.random.default_rng(0).normal(size=(64, 16)), np.float32
    )

    ov.maybe_tiled_transpose_matmul(jnp.asarray(x), None, mesh)
    assert reg.get_counter(
        "overlap.engaged", site="tiled_transpose_matmul",
        schedule="single_tier",
    ) == 1
    assert reg.sum_counters("overlap.fallback") == 0
    h = reg.get_histogram("overlap.tiles", site="tiled_psum_dot")
    assert h is not None and h["count"] >= 1
    assert reg.sum_counters("overlap.reduce_scatter_rounds") >= 1

    # shape-driven fallback: counted per decision, with the site label
    ov._FALLBACK_LOGGED.clear()
    ov.maybe_tiled_transpose_matmul(jnp.asarray(x[:63]), None, mesh)
    ov.maybe_tiled_transpose_matmul(jnp.asarray(x[:63]), None, mesh)
    assert reg.get_counter(
        "overlap.fallback", site="maybe_tiled_transpose_matmul"
    ) == 2  # NOT rate-limited like the log

    # ring TSQR engagement + ppermute round count
    telemetry.reset()
    from keystone_tpu.linalg.solvers import tsqr_solve

    b = np.asarray(np.random.default_rng(1).normal(size=(64, 3)), np.float32)
    tsqr_solve(jnp.asarray(x), jnp.asarray(b), lam=0.1, mesh=mesh,
               overlap=True)
    assert reg.get_counter("overlap.engaged", site="ring_tsqr_fold") >= 1
    assert reg.get_counter(
        "overlap.ppermute_rounds", site="ring_tsqr_fold"
    ) >= 7  # k=8: 2*ceil(7/2) paired + 1 middle hop
    assert reg.get_counter("solver.calls", solver="tsqr") == 1


def test_cache_counters_per_tier():
    from keystone_tpu.core.cache import IntermediateCache

    reg = telemetry.get_registry()
    cache = IntermediateCache(device_bytes=1 << 20, host_bytes=1 << 20)
    calls = []
    value = jnp.arange(8.0)

    def compute():
        calls.append(1)
        return value

    cache.memoize("k1", compute)  # miss -> compute -> put
    cache.memoize("k1", compute)  # device hit
    assert len(calls) == 1
    assert reg.get_counter("cache.miss") == 1
    assert reg.get_counter("cache.compute") == 1
    assert reg.get_counter("cache.put") == 1
    assert reg.get_counter("cache.hit", tier="device") == 1
    # mirror of the CacheStats view
    assert cache.stats.hits == 1 and cache.stats.misses == 1


def test_prefetch_counters():
    from keystone_tpu.core.prefetch import prefetch_map

    reg = telemetry.get_registry()
    out = list(prefetch_map(lambda x: x * 2, range(5), depth=2))
    assert out == [0, 2, 4, 6, 8]
    assert reg.get_gauge("prefetch.depth") == 2
    # item 0 stalls (nothing produced yet), the rest were run ahead
    assert reg.get_counter("prefetch.stall") == 1
    assert reg.get_counter("prefetch.ready") == 4
    assert reg.get_counter("prefetch.produced_ahead") == 4
    assert reg.get_counter("prefetch.stall_s") >= 0

    telemetry.reset()
    # a gate that forbids crossing parity boundaries blocks run-ahead
    list(prefetch_map(
        lambda x: x, [0, 0, 1, 1], depth=3,
        gate=lambda a, b: a == b,
    ))
    assert reg.get_counter("prefetch.gate_blocked") >= 1


def test_bcd_residual_trajectory_and_unchanged_result():
    from keystone_tpu.linalg.bcd import block_coordinate_descent_l2

    rng = np.random.default_rng(3)
    A = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(64, 3)), jnp.float32)

    W_plain = block_coordinate_descent_l2(A, b, 1.0, 8, num_iter=2)
    reg = telemetry.get_registry()
    assert reg.get_counter("solver.calls", solver="bcd") == 1
    assert reg.get_counter("solver.bcd.gram_flops") > 0
    assert reg.get_histogram("solver.bcd.residual_fro") is None  # off: none

    with telemetry.use_tracing(True):
        W_traced = block_coordinate_descent_l2(A, b, 1.0, 8, num_iter=2)
    h = reg.get_histogram("solver.bcd.residual_fro")
    assert h["count"] == 4  # 2 blocks x 2 iterations
    # BCD monotonically non-increases the residual; final <= first step
    assert h["min"] <= h["max"]
    assert reg.get_gauge("solver.bcd.final_residual_fro") == pytest.approx(
        h["min"], rel=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(W_plain), np.asarray(W_traced), rtol=1e-6
    )
    span_names = [s["name"] for s in telemetry.get_tracer().spans_as_dicts()]
    assert "solver.bcd" in span_names


# ---------------------------------------------------------------------------
# Timer satellites
# ---------------------------------------------------------------------------

def test_timer_thread_safety_reset_summary_and_registry_routing():
    from keystone_tpu.utils import Timer

    Timer.reset()
    errors = []

    def worker():
        try:
            for _ in range(50):
                with Timer("tele.test.concurrent", log=False, block=False):
                    pass
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(Timer.registry["tele.test.concurrent"]) == 400
    s = Timer.summary()["tele.test.concurrent"]
    assert s["count"] == 400 and s["total"] >= 0 and s["min"] <= s["max"]
    # routed into the structured registry as a histogram
    h = telemetry.get_registry().get_histogram("timer.tele.test.concurrent")
    assert h["count"] == 400
    Timer.reset()
    assert "tele.test.concurrent" not in Timer.registry


def test_sync_timers_marker_failure_logged_once(monkeypatch, caplog):
    """The KEYSTONE_SYNC_TIMERS marker path must not swallow failures
    silently: one warning for the process, and the timing still records."""
    from keystone_tpu.utils import Timer
    from keystone_tpu.utils import logging as klog

    monkeypatch.setenv("KEYSTONE_SYNC_TIMERS", "1")
    monkeypatch.setattr(
        klog.jax, "local_devices",
        lambda: (_ for _ in ()).throw(RuntimeError("devices gone")),
    )
    monkeypatch.setattr(Timer, "_sync_marker_warned", False)
    Timer.reset()
    with caplog.at_level(logging.WARNING, logger="keystone_tpu.timing"):
        with Timer("tele.test.sync_fail", log=False, block=False) as t1:
            pass
        with Timer("tele.test.sync_fail", log=False, block=False):
            pass
    warnings = [
        r for r in caplog.records
        if "KEYSTONE_SYNC_TIMERS" in r.getMessage()
    ]
    assert len(warnings) == 1  # once per process, not per Timer
    assert "devices gone" in warnings[0].getMessage()
    assert t1.elapsed is not None  # timing survived the failed barrier
    assert len(Timer.registry["tele.test.sync_fail"]) == 2
    Timer.reset()


def test_sync_timers_marker_path_works(monkeypatch):
    """Knob coverage: with the env set and healthy devices the marker
    barrier runs and the timer records normally."""
    from keystone_tpu.utils import Timer

    monkeypatch.setenv("KEYSTONE_SYNC_TIMERS", "1")
    monkeypatch.setattr(Timer, "_sync_marker_warned", False)
    Timer.reset()
    with Timer("tele.test.sync_ok", log=False) as t:
        jnp.ones((8,)).sum()
    assert t.elapsed is not None and t.elapsed >= 0
    assert Timer._sync_marker_warned is False  # no failure, no warning
    Timer.reset()


# ---------------------------------------------------------------------------
# report CLI
# ---------------------------------------------------------------------------

def test_telemetry_report_cli(tmp_path, capsys):
    from keystone_tpu.cli import main as cli_main

    reg = MetricsRegistry()
    reg.inc("overlap.engaged", 3, site="tiled_psum_dot")
    reg.observe("timer.fit", 1.25)
    artifact = {
        "metrics": reg.as_dict(),
        "spans": [{
            "name": "solver.bcd", "ts_us": 0.0, "dispatch_us": 10.0,
            "dur_us": 1000.0, "depth": 0, "tid": 1,
            "args": {"achieved_gflops": 42.0},
        }],
    }
    path = tmp_path / "bench_telemetry.json"
    path.write_text(json.dumps(artifact))

    assert cli_main(["telemetry-report", str(path)]) == 0
    out = capsys.readouterr().out
    assert "overlap.engaged{site=tiled_psum_dot}" in out
    assert "timer.fit" in out
    assert "solver.bcd" in out and "42.0" in out

    assert cli_main(["telemetry-report", str(tmp_path / "missing.json")]) == 2


def test_export_dir_writes_all_artifacts(tmp_path):
    reg = telemetry.get_registry()
    reg.inc("x")
    with telemetry.use_tracing(True):
        with telemetry.get_tracer().span("s", sync=False):
            pass
    paths = telemetry.export_dir(str(tmp_path))
    metrics = json.loads((tmp_path / "telemetry_metrics.json").read_text())
    assert metrics["counters"]["x"] == 1
    trace = json.loads((tmp_path / "telemetry_trace.json").read_text())
    assert trace["traceEvents"][0]["name"] == "s"
    assert "keystone_x" in (tmp_path / "telemetry_metrics.prom").read_text()
    jsonl = [
        json.loads(l)
        for l in (tmp_path / "telemetry_metrics.jsonl").read_text().splitlines()
    ]
    assert any(l["name"] == "x" and l["value"] == 1 for l in jsonl)
    assert set(paths) == {"metrics", "jsonl", "prometheus", "trace"}
