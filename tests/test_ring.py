"""Ring / all-to-all sequence-context parallelism (parallel/ring.py) on the
8-device CPU mesh: sharded programs must match the unsharded oracle exactly
(same math, different schedule)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.parallel import make_mesh, use_mesh
from keystone_tpu.parallel.ring import (
    attention_reference,
    ring_attention,
    ring_gram,
    ulysses_attention,
)


@pytest.fixture()
def mesh(devices):
    m = make_mesh(data=8, model=1, devices=devices)
    with use_mesh(m):
        yield m


def _qkv(shape=(2, 32, 8, 4)):
    ks = jax.random.split(jax.random.key(0), 3)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def test_ring_gram_matches_dense(devices, rng):
    m = make_mesh(data=1, model=8, devices=devices)
    x = rng.normal(size=(40, 16)).astype(np.float32)
    with use_mesh(m):
        g = ring_gram(jnp.asarray(x), m, axis="model")
    np.testing.assert_allclose(np.asarray(g), x.T @ x, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("k", [2, 5, 7, 8])
def test_ring_gram_bidirectional_matches_unidirectional(devices, rng, k):
    """Bidirectional-vs-unidirectional parity across odd and even ring
    sizes: every tile is the same matmul on the same operands, so the
    results must be IDENTICAL (not merely close), and both must match the
    dense oracle."""
    m = make_mesh(data=1, model=k, devices=devices[:k])
    x = rng.normal(size=(24, 8 * k)).astype(np.float32)
    with use_mesh(m):
        uni = np.asarray(ring_gram(jnp.asarray(x), m, axis="model",
                                   bidirectional=False))
        bi = np.asarray(ring_gram(jnp.asarray(x), m, axis="model",
                                  bidirectional=True))
    np.testing.assert_array_equal(bi, uni)
    np.testing.assert_allclose(bi, x.T @ x, rtol=1e-4, atol=1e-4)


def test_ring_gram_overlap_knob_routes_bidirectional(devices, rng):
    from keystone_tpu.parallel.overlap import use_overlap

    m = make_mesh(data=1, model=8, devices=devices)
    x = rng.normal(size=(24, 32)).astype(np.float32)
    with use_mesh(m):
        explicit = np.asarray(
            ring_gram(jnp.asarray(x), m, axis="model", bidirectional=True)
        )
        with use_overlap(True):  # bidirectional=None resolves the knob
            via_knob = np.asarray(ring_gram(jnp.asarray(x), m, axis="model"))
    np.testing.assert_array_equal(via_knob, explicit)


def test_ring_gram_rejects_indivisible_feature_axis(devices, rng):
    m = make_mesh(data=1, model=8, devices=devices)
    x = jnp.asarray(rng.normal(size=(24, 30)).astype(np.float32))
    with use_mesh(m):
        with pytest.raises(ValueError, match="divisible"):
            ring_gram(x, m, axis="model", bidirectional=False)
        with pytest.raises(ValueError, match="divisible"):
            ring_gram(x, m, axis="model", bidirectional=True)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(mesh, causal):
    q, k, v = _qkv()
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(mesh, causal):
    q, k, v = _qkv()
    out = ulysses_attention(q, k, v, mesh, causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_ring_attention_rejects_indivisible_sequence_axis(mesh):
    q, k, v = _qkv((2, 30, 8, 4))  # 30 % 8 != 0
    with pytest.raises(ValueError, match="sequence length"):
        ring_attention(q, k, v, mesh)


def test_ulysses_rejects_indivisible_head_axis(mesh):
    q, k, v = _qkv((2, 32, 6, 4))  # 6 heads % 8 devices != 0
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, k, v, mesh)


def test_ring_attention_long_sequence_streams(mesh):
    # 8k tokens over 8 devices: per-chip score tile is (1k, 1k), never (8k, 8k).
    q, k, v = _qkv((1, 8192, 2, 8))
    out = ring_attention(q, k, v, mesh)
    assert out.shape == (1, 8192, 2, 8)
    assert bool(jnp.isfinite(out).all())
