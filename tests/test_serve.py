"""Serving gateway (keystone_tpu/serve/gateway.py): admission control,
deadline-aware shedding, coalescing parity, the circuit breaker, cache-tier
degradation, and the zero-recompile steady-state pin.

The admission fixtures reuse the contracts C1/C4 cases (tests/test_check.py):
the same mis-composed SIFT->vectorize->FV chain the checker rejects is
rejected by ``serve()`` at registration time, and the C4 family (an f64
item under the compiled f32 ladder) is rejected AT THE GATE — never
discovered inside a donated-buffer dispatch.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import keystone_tpu._compat  # noqa: F401
from keystone_tpu.analysis.contracts import ContractViolation
from keystone_tpu.core.pipeline import Transformer, chain
from keystone_tpu.serve import Gateway, ServeRejected, serve
from keystone_tpu.serve.gateway import DEFAULT_SHAPES, _jit_apply_batch
from keystone_tpu.telemetry import get_registry
from keystone_tpu.utils import faults, knobs


class Doubler(Transformer):
    def apply(self, x):
        return x * 2


class AddOne(Transformer):
    def apply(self, x):
        return x + 1


class PoisonOnMarker(Transformer):
    """NaNs its whole output when any element exceeds the marker — the
    deterministic stand-in for a numerically poisoned model (PR-13's
    sentinel family, serving form)."""

    def apply(self, x):
        bad = jnp.max(x) > 1e9
        return jnp.where(bad, jnp.full_like(x, jnp.nan), x * 2)


D = 4


def _spec(d=D, dtype=np.float32):
    return jax.ShapeDtypeStruct((d,), dtype)


def _item(i=0.0, d=D):
    return np.arange(d, dtype=np.float32) + np.float32(i)


@pytest.fixture()
def gw():
    """A started gateway over a tiny elementwise chain; always closed."""
    g = serve(chain(Doubler(), AddOne()), item_spec=_spec())
    yield g
    g.close(drain=False)


# ---------------------------------------------------------------------------
# admission control (the PR-10 follow-on)
# ---------------------------------------------------------------------------

def test_admission_accepts_and_serves(gw):
    out = gw.predict(_item())
    np.testing.assert_array_equal(np.asarray(out), _item() * 2 + 1)


def test_admission_rejects_dtype_at_the_gate(gw):
    # the C4 family at the gate: an f64 item under the compiled f32
    # ladder is structured-rejected pre-dispatch, never silently cast
    with pytest.raises(ServeRejected) as e:
        gw.predict(_item().astype(np.float64))
    r = e.value.response
    assert (r.code, r.kind) == ("rejected", "dtype")
    assert "float64" in r.error


def test_admission_rejects_rank_and_dim(gw):
    with pytest.raises(ServeRejected) as e:
        gw.predict(np.zeros((D, 2), np.float32))
    assert e.value.response.kind == "rank"
    with pytest.raises(ServeRejected) as e:
        gw.predict(np.zeros((D + 1,), np.float32))
    assert e.value.response.kind == "dim"
    # structured responses carry the code the chaos driver counts
    assert e.value.response.code == "rejected"


def test_admission_rejects_unknown_model(gw):
    resp = gw.submit(_item(), model="nope").result(1)
    assert (resp.code, resp.kind) == ("rejected", "model")


def test_serve_rejects_c1_broken_chain(monkeypatch):
    """The contracts C1 fixture: the mis-composed SIFT -> vectorize -> FV
    chain (rank mismatch) is rejected by serve() at registration, with
    the stages named — the same pass `keystone-tpu check` runs."""
    from keystone_tpu.learning.gmm import GaussianMixtureModel
    from keystone_tpu.ops.images import SIFTExtractor
    from keystone_tpu.ops.images.fisher_vector import FisherVector
    from keystone_tpu.ops.util import MatrixVectorizer

    monkeypatch.setenv("KEYSTONE_CHECK", "0")
    gmm = GaussianMixtureModel(
        means=jnp.zeros((4, 16)), variances=jnp.ones((4, 16)),
        weights=jnp.full((4,), 0.25),
    )
    bad = chain(SIFTExtractor(), MatrixVectorizer(), FisherVector(gmm=gmm))
    with pytest.raises(ContractViolation) as e:
        serve(bad, item_spec=jax.ShapeDtypeStruct((64, 64), np.float32),
              warm=False, start=False)
    assert "FisherVector" in str(e.value)


def test_serve_rejects_host_stage():
    class HostNode(Transformer):
        jittable = False

        def apply(self, x):
            return np.asarray(x)

    with pytest.raises(TypeError, match="host node"):
        serve(chain(Doubler(), HostNode()), item_spec=_spec(),
              warm=False, start=False)


def test_item_spec_required_without_contract():
    with pytest.raises(ValueError, match="item spec"):
        serve(chain(Doubler()), warm=False, start=False)


# ---------------------------------------------------------------------------
# coalescing + dispatch parity
# ---------------------------------------------------------------------------

def test_coalesced_burst_bit_parity_vs_unbatched(gw):
    """A burst coalesced through the padded shape ladder returns, for
    every item, EXACTLY what the unbatched apply returns — padding rows
    never leak into real rows."""
    items = [_item(i) for i in range(20)]  # 20 -> one padded 32-rung
    pend = [gw.submit(x) for x in items]
    rs = [p.result(10) for p in pend]
    assert all(r.ok for r in rs), [r.code for r in rs]
    pipe = chain(Doubler(), AddOne())
    for x, r in zip(items, rs):
        np.testing.assert_array_equal(
            np.asarray(r.value), np.asarray(pipe.serve(jnp.asarray(x)))
        )
        assert r.latency_ms is not None and r.latency_ms >= 0


def test_single_item_equals_batch_row():
    """Rung-1 single dispatch vs a row of a coalesced padded dispatch:
    identical results on a matmul-bearing chain (allclose; the reduction
    geometry per row is the same program)."""
    w = np.asarray(
        np.random.default_rng(3).normal(size=(D, 8)), np.float32
    )
    mat = Transformer.from_fn(lambda x: x @ jnp.asarray(w))
    # SLO effectively off: this test pins PARITY, not shedding — in a
    # contended suite process the cold first dispatch can push the p99
    # window over the default SLO and legitimately shed the burst
    # (same rationale as test_zero_recompile_steady_state below).
    g = serve(chain(mat), item_spec=_spec(), slo_ms=10_000.0)
    try:
        single = np.asarray(g.predict(_item(1.0)))
        pend = [g.submit(_item(i)) for i in [0.0, 1.0, 2.0]]
        rs = [p.result(10) for p in pend]
        assert all(r.ok for r in rs), [r.code for r in rs]
        rows = [np.asarray(r.value) for r in rs]
        np.testing.assert_allclose(rows[1], single, rtol=1e-6)
    finally:
        g.close(drain=False)


def test_zero_recompile_steady_state():
    """The zero-recompile pin: after warmup, serving any burst size holds
    the shared dispatch compile cache CONSTANT.  SLO effectively off: in
    a contended suite process a cold first dispatch can push the 5 s p99
    window over the default 50 ms SLO and legitimately shed — this test
    pins recompiles, not shedding (test_p99_over_slo_sheds_new_arrivals
    covers the shed signal)."""
    g = serve(chain(Doubler(), AddOne()), item_spec=_spec(),
              slo_ms=10_000.0)
    try:
        size0 = g.compile_cache_size()
        for burst in (1, 3, 20, 32):
            pend = [g.submit(_item(i)) for i in range(burst)]
            assert all(p.result(10).ok for p in pend)
        assert g.compile_cache_size() == size0
        assert _jit_apply_batch._cache_size() == size0
    finally:
        g.close(drain=False)


# ---------------------------------------------------------------------------
# deadline-aware shedding + overload
# ---------------------------------------------------------------------------

def test_deadline_expired_is_shed():
    g = serve(chain(Doubler()), item_spec=_spec(), start=False)
    try:
        p = g.submit(_item(), deadline_ms=0.0)
        time.sleep(0.01)  # the deadline passes while queued
        g.start()
        r = p.result(10)
        assert r.code == "deadline", r
        assert get_registry().get_counter(
            "serve.shed_total", reason="deadline") >= 1
    finally:
        g.close(drain=False)


def test_unmeetable_deadline_is_shed_pre_dispatch(gw):
    # per-shape estimate is recorded by warmup; a 1000x tighter deadline
    # is provably unmeetable and dropped before wasting device time
    est = gw._estimate_ms(gw.default_model, 1)
    assert est > 0
    r = gw.submit(_item(), deadline_ms=est / 1000.0).result(10)
    assert r.code == "deadline"
    assert "deadline" in r.error


def test_queue_depth_shed_with_retry_after():
    g = serve(chain(Doubler()), item_spec=_spec(), queue_depth=4,
              start=False)
    try:
        pend = [g.submit(_item(i)) for i in range(6)]
        shed = [p.result(0.1) for p in pend[4:]]
        assert all(r.code == "shed" for r in shed), [r.code for r in shed]
        assert all(r.retry_after_s and r.retry_after_s > 0 for r in shed)
        g.start()
        served = [p.result(10) for p in pend[:4]]
        assert all(r.ok for r in served)
    finally:
        g.close(drain=False)


def test_p99_over_slo_sheds_new_arrivals():
    g = serve(chain(Doubler()), item_spec=_spec(), slo_ms=50.0,
              start=False)
    try:
        g.submit(_item())           # one queued
        g._p99_ms = 500.0           # observed p99 10x over the SLO
        r = g.submit(_item()).result(0.1)
        assert r.code == "shed"
        assert "SLO" in r.error
        assert r.retry_after_s >= 0.05
    finally:
        g.close(drain=False)


def test_close_drain_false_sheds_backlog_structured():
    g = serve(chain(Doubler()), item_spec=_spec(), start=False)
    pend = [g.submit(_item(i)) for i in range(3)]
    g.close(drain=False)
    rs = [p.result(1) for p in pend]
    assert all(r.code == "shutdown" for r in rs)
    # post-close submissions get a structured shutdown response too
    assert g.submit(_item()).result(1).code == "shutdown"


# ---------------------------------------------------------------------------
# circuit breaker (PR-13 health sentinels, serving form)
# ---------------------------------------------------------------------------

def _poison_gateway(**kw):
    kw.setdefault("breaker_threshold", 2)
    kw.setdefault("breaker_cooldown_s", 0.05)
    return serve(chain(PoisonOnMarker()), item_spec=_spec(), **kw)


POISON = np.full((D,), 2e9, np.float32)


def test_sentinel_trips_on_nan_output():
    g = _poison_gateway()
    try:
        r = g.submit(POISON).result(10)
        assert r.code == "sentinel"
        assert "non-finite" in r.error
        assert g.breaker_state() == "closed"  # one trip, threshold 2
        # a healthy dispatch resets the consecutive-trip count
        assert g.submit(_item()).result(10).ok
    finally:
        g.close(drain=False)


def test_breaker_open_half_open_close_roundtrip():
    g = _poison_gateway()
    reg = get_registry()
    try:
        # two CONSECUTIVE sentinel trips open the breaker
        for _ in range(2):
            assert g.submit(POISON).result(10).code == "sentinel"
        assert g.breaker_state() == "open"
        assert reg.get_gauge(
            "serve.breaker_state", model=g.default_model) == 1.0
        # open = fail fast with retry_after, no dispatch
        r = g.submit(_item()).result(1)
        assert r.code == "breaker_open"
        assert r.retry_after_s is not None
        # after the cooldown the next request is the half-open probe;
        # it serves healthy and CLOSES the breaker
        time.sleep(0.06)
        r = g.submit(_item()).result(10)
        assert r.ok, r
        assert g.breaker_state() == "closed"
        assert reg.get_gauge(
            "serve.breaker_state", model=g.default_model) == 0.0
        assert g.submit(_item()).result(10).ok
    finally:
        g.close(drain=False)


def test_failed_probe_reopens_breaker():
    g = _poison_gateway()
    try:
        for _ in range(2):
            g.submit(POISON).result(10)
        assert g.breaker_state() == "open"
        time.sleep(0.06)
        # the probe itself is poisoned -> straight back to open
        assert g.submit(POISON).result(10).code == "sentinel"
        assert g.breaker_state() == "open"
        # ... and a later healthy probe still recovers it
        time.sleep(0.06)
        assert g.submit(_item()).result(10).ok
        assert g.breaker_state() == "closed"
    finally:
        g.close(drain=False)


def test_breaker_disabled_never_opens():
    g = _poison_gateway(breaker_threshold=0)
    try:
        for _ in range(4):
            assert g.submit(POISON).result(10).code == "sentinel"
        assert g.breaker_state() == "closed"
        assert g.submit(_item()).result(10).ok
    finally:
        g.close(drain=False)


# ---------------------------------------------------------------------------
# degradation ladder: cache tiers + ladder shrink
# ---------------------------------------------------------------------------

def test_overload_demotes_cold_models_tiny_budget(monkeypatch):
    """Under a tiny KEYSTONE_CACHE_*_MB budget, queue-pressure sheds
    demote COLD models' pool entries to the host tier; the hot model
    stays device-resident, and a later request to the demoted model
    still serves (lookup promotes it back — the PR-1 tier mechanics)."""
    from keystone_tpu.core.cache import _DEVICE, _HOST

    monkeypatch.setenv("KEYSTONE_CACHE_DEVICE_MB", "1")
    monkeypatch.setenv("KEYSTONE_CACHE_HOST_MB", "64")
    g = serve(chain(Doubler()), item_spec=_spec(), name="hot",
              queue_depth=2, start=False)
    try:
        g.add_model("cold", chain(AddOne()), item_spec=_spec())
        tiers = {n: g._pool._entries[g._pool_key(n)].tier
                 for n in ("hot", "cold")}
        assert tiers == {"hot": _DEVICE, "cold": _DEVICE}
        # overflow the bounded queue with hot-model requests: the shed
        # path demotes every model but the hot one
        backlog = [g.submit(_item(i), model="hot") for i in range(3)]
        assert g._pool._entries[g._pool_key("cold")].tier == _HOST
        assert g._pool._entries[g._pool_key("hot")].tier == _DEVICE
        assert get_registry().get_counter("serve.model_demotions") >= 1
        g.start()
        for p in backlog:  # drain the hot backlog before the cold request
            p.result(10)
        # the demoted model still serves: lookup promotes it back
        out = g.predict(_item(), model="cold")
        np.testing.assert_array_equal(np.asarray(out), _item() + 1)
    finally:
        g.close(drain=False)


def test_oom_retry_hook_shrinks_ladder_and_demotes():
    g = serve(chain(Doubler()), item_spec=_spec(), name="hot",
              start=False)
    try:
        g.add_model("cold", chain(AddOne()), item_spec=_spec())
        reg = get_registry()
        deg0 = reg.get_counter("serve.degraded")
        assert g._ladder == DEFAULT_SHAPES
        g._on_dispatch_retry(
            1, RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        )
        assert g._ladder == DEFAULT_SHAPES[:-1]  # largest rung dropped
        assert reg.get_counter("serve.degraded") == deg0 + 1
        from keystone_tpu.core.cache import _HOST

        assert g._pool._entries[g._pool_key("cold")].tier == _HOST
        # a non-OOM error does NOT degrade
        g._on_dispatch_retry(1, RuntimeError("INTERNAL: transient"))
        assert g._ladder == DEFAULT_SHAPES[:-1]
        # the floor: the ladder never shrinks below one rung
        for _ in range(4):
            g._on_dispatch_retry(
                1, RuntimeError("RESOURCE_EXHAUSTED: out of memory")
            )
        assert g._ladder == DEFAULT_SHAPES[:1]
    finally:
        g.close(drain=False)


# ---------------------------------------------------------------------------
# chaos sites (KEYSTONE_FAULTS serve.admit / serve.dispatch / serve.respond)
# ---------------------------------------------------------------------------

@pytest.fixture()
def clean_faults(monkeypatch):
    faults.reset()
    yield monkeypatch
    monkeypatch.delenv("KEYSTONE_FAULTS", raising=False)
    faults.reset()


def test_injected_admit_fault_is_structured(clean_faults, gw):
    clean_faults.setenv("KEYSTONE_FAULTS", "serve.admit@0:xla")
    r = gw.submit(_item()).result(5)
    assert r.code == "error"
    assert "injected fault" in r.error
    # the next request (occurrence past the plan) serves normally
    assert gw.submit(_item()).result(10).ok


def test_injected_dispatch_fault_is_retried(clean_faults, gw):
    reg = get_registry()
    a0 = reg.get_counter("retry.attempt")
    clean_faults.setenv("KEYSTONE_FAULTS", "serve.dispatch@0:xla")
    r = gw.submit(_item()).result(15)
    assert r.ok, r  # the retry loop absorbed the transient fault
    assert reg.get_counter("retry.attempt") > a0


def test_injected_dispatch_nan_trips_sentinel(clean_faults):
    g = _poison_gateway()
    try:
        clean_faults.setenv("KEYSTONE_FAULTS", "serve.dispatch@0:nan")
        r = g.submit(_item()).result(10)  # a HEALTHY item, poisoned batch
        assert r.code == "sentinel"
        assert get_registry().get_counter(
            "serve.sentinel_trips", model=g.default_model) >= 1
    finally:
        g.close(drain=False)


def test_injected_respond_fault_is_structured(clean_faults, gw):
    clean_faults.setenv("KEYSTONE_FAULTS", "serve.respond@0:xla")
    r = gw.submit(_item()).result(10)
    assert r.code == "error"
    assert "respond failure" in r.error
    assert gw.submit(_item()).result(10).ok


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def test_serve_shapes_knob_parses_and_validates(monkeypatch):
    monkeypatch.setenv("KEYSTONE_SERVE_SHAPES", "16, 2,2, 4")
    assert knobs.get("KEYSTONE_SERVE_SHAPES") == (2, 4, 16)
    monkeypatch.setenv("KEYSTONE_SERVE_SHAPES", "8,frogs")
    with pytest.raises(ValueError, match="KEYSTONE_SERVE_SHAPES"):
        knobs.get("KEYSTONE_SERVE_SHAPES")
    monkeypatch.setenv("KEYSTONE_SERVE_SHAPES", "0,4")
    with pytest.raises(ValueError, match="positive"):
        knobs.validate_environment()


def test_gateway_honors_shape_ladder_knob(monkeypatch):
    monkeypatch.setenv("KEYSTONE_SERVE_SHAPES", "2,4")
    g = serve(chain(Doubler()), item_spec=_spec(), start=False,
              warm=False)
    try:
        assert g._ladder == (2, 4)
        assert g._pick_shape(1) == 2
        assert g._pick_shape(3) == 4
        assert g._pick_shape(9) == 4  # above the ladder: chunked at max
    finally:
        g.close(drain=False)


def test_serve_knobs_validated(monkeypatch):
    monkeypatch.setenv("KEYSTONE_SERVE_SLO_MS", "-1")
    with pytest.raises(ValueError, match="KEYSTONE_SERVE_SLO_MS"):
        knobs.validate_environment()
    monkeypatch.setenv("KEYSTONE_SERVE_SLO_MS", "25")
    monkeypatch.setenv("KEYSTONE_SERVE_QUEUE_DEPTH", "7")
    g = serve(chain(Doubler()), item_spec=_spec(), start=False,
              warm=False)
    try:
        assert g.slo_ms == 25.0 and g.queue_depth == 7
    finally:
        g.close(drain=False)


# ---------------------------------------------------------------------------
# stats surface
# ---------------------------------------------------------------------------

def test_stats_surface(gw):
    assert gw.predict(_item()) is not None
    s = gw.stats()
    assert s["queue_bound"] == gw.queue_depth
    assert s["ladder"] == list(DEFAULT_SHAPES)
    assert s["breakers"] == {"default": "closed"}
    assert s["p50_ms"] >= 0.0
