"""Numerical health sentinels + self-healing escalation (PR 13,
``utils/health.py``): mode resolution, the deterministic escalation
ladder, on-device quarantine gating in the streaming weighted loop and
the BCD scan, the guarded one-shot solver ladder, numeric fault kinds,
checkpoint replay of quarantine/heal decisions, and the off-mode
byte-identity pin."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.learning.block_weighted import (
    BlockWeightedLeastSquaresEstimator,
)
from keystone_tpu.telemetry import get_registry
from keystone_tpu.utils import faults, health, knobs


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for k in ("KEYSTONE_FAULTS", "KEYSTONE_HEALTH", "KEYSTONE_HEALTH_GROWTH"):
        monkeypatch.delenv(k, raising=False)
    faults.reset()
    yield
    faults.reset()


def _counter_sum(name):
    return get_registry().counter_family_total(name)


class _Slice:
    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    def apply_batch(self, raw):
        return raw["x"][:, self.lo : self.hi]


def _task(n=192, d=32, c=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=(d, c)).astype(np.float32)
    cls = np.argmax(x @ w_true, axis=1)
    lbl = np.eye(c, dtype=np.float32)[cls] * 2.0 - 1.0
    return x, lbl, cls


def _streaming_fit(x, lbl, bs=8, num_iter=2, **kw):
    d = x.shape[1]
    nodes = [_Slice(k * bs, (k + 1) * bs) for k in range(d // bs)]
    est = BlockWeightedLeastSquaresEstimator(bs, num_iter, 0.1, 0.25)
    m = est.fit_streaming(nodes, {"x": jnp.asarray(x)}, jnp.asarray(lbl), **kw)
    jax.block_until_ready(m.w)
    return m


# ---------------------------------------------------------------------------
# Mode + ladder resolution
# ---------------------------------------------------------------------------

def test_mode_resolution(monkeypatch):
    assert health.resolve_health_mode() == "0"
    monkeypatch.setenv("KEYSTONE_HEALTH", "warn")
    assert health.resolve_health_mode() == "warn"
    assert health.resolve_health_mode("heal") == "heal"  # per-call wins
    with pytest.raises(ValueError, match="KEYSTONE_HEALTH"):
        monkeypatch.setenv("KEYSTONE_HEALTH", "loud")
        knobs.get("KEYSTONE_HEALTH")
    with pytest.raises(ValueError, match="health mode"):
        health.resolve_health_mode("bogus")


def test_escalation_sequence_is_deterministic():
    # storage first (bf16 -> f32, same rung), then the rungs above, f32
    assert health.escalation_sequence("sketch", "bf16") == [
        ("sketch", "f32"), ("tsqr", "f32"), ("normal_equations", "f32"),
    ]
    assert health.escalation_sequence("sketch", "f32") == [
        ("tsqr", "f32"), ("normal_equations", "f32"),
    ]
    assert health.escalation_sequence("tsqr", "f32") == [
        ("normal_equations", "f32"),
    ]
    assert health.escalation_sequence("normal_equations", "f32") == []
    # a rung outside the ladder (the block loops) escalates storage only
    assert health.escalation_sequence("weighted_block", "bf16") == [
        ("weighted_block", "f32"),
    ]
    assert health.escalation_sequence("weighted_block", "f32") == []


# ---------------------------------------------------------------------------
# The guarded block update (traced sentinels + on-device gate)
# ---------------------------------------------------------------------------

def _update_args(seed=3, n=32, bs=8, c=3):
    rng = np.random.default_rng(seed)
    R = jnp.asarray(rng.normal(size=(n, c)).astype(np.float32))
    Xb = jnp.asarray(rng.normal(size=(n, bs)).astype(np.float32))
    dW = jnp.asarray(0.01 * rng.normal(size=(bs, c)).astype(np.float32))
    valid = jnp.ones((n,), jnp.float32)
    gram = jnp.asarray(np.eye(bs, dtype=np.float32))
    cross = jnp.asarray(rng.normal(size=(bs, c)).astype(np.float32))
    nrm = jnp.linalg.norm(R)
    return R, Xb, dW, valid, gram, cross, nrm


def test_guarded_update_healthy_is_bit_exact_passthrough():
    R, Xb, dW, valid, gram, cross, nrm = _update_args()
    expected = np.asarray(R - (Xb * valid[:, None]) @ dW)
    R_out, dW_eff, nrm_out, rec = health.guarded_block_update(
        R, Xb, dW, valid, gram, cross, nrm, jnp.float32(10.0), "highest"
    )
    rec = np.asarray(rec)
    assert rec[0] == 1.0  # healthy
    assert np.array_equal(np.asarray(dW_eff), np.asarray(dW))
    np.testing.assert_allclose(np.asarray(R_out), expected, rtol=1e-6)
    assert float(nrm_out) == pytest.approx(
        float(np.linalg.norm(expected)), rel=1e-5
    )


@pytest.mark.parametrize("poison_target,reason", [
    ("gram", "gram_diag"),
    ("cross", "nonfinite_cross"),
    ("dW", "nonfinite_update"),
])
def test_guarded_update_rejects_nonfinite_on_device(poison_target, reason):
    R, Xb, dW, valid, gram, cross, nrm = _update_args()
    bad = {
        "gram": gram.at[0, 0].set(jnp.inf),
        "cross": cross.at[0, 0].set(jnp.nan),
        "dW": dW.at[0, 0].set(jnp.nan),
    }[poison_target]
    args = dict(gram=gram, cross=cross, dW=dW)
    args[poison_target] = bad
    R_host = np.asarray(R)  # R is DONATED below — snapshot first
    R_out, dW_eff, nrm_out, rec = health.guarded_block_update(
        R, Xb, args["dW"], valid, args["gram"], args["cross"], nrm,
        jnp.float32(10.0), "highest",
    )
    assert np.asarray(rec)[0] == 0.0
    assert health.trip_reason(rec) == reason
    # the carry never sees the poison: R unchanged, update zeroed, norm kept
    assert np.array_equal(np.asarray(R_out), R_host)
    assert np.all(np.asarray(dW_eff) == 0.0)
    assert float(nrm_out) == float(nrm)


def test_guarded_update_growth_sentinel_catches_finite_garbage():
    # a FINITE but exploding update: every flag is clean except growth
    R, Xb, dW, valid, gram, cross, nrm = _update_args()
    huge = dW + 1e6
    R_host = np.asarray(R)  # R is DONATED below — snapshot first
    R_out, dW_eff, _, rec = health.guarded_block_update(
        R, Xb, huge, valid, gram, cross, nrm, jnp.float32(10.0), "highest"
    )
    rec = np.asarray(rec)
    assert rec[0] == 0.0 and rec[3] == 1.0  # unhealthy, but update finite
    assert health.trip_reason(rec) == "residual_growth"
    assert np.array_equal(np.asarray(R_out), R_host)
    assert np.all(np.asarray(dW_eff) == 0.0)


# ---------------------------------------------------------------------------
# Streaming weighted loop: byte-identity, quarantine, heal
# ---------------------------------------------------------------------------

def test_streaming_off_mode_is_byte_identical(monkeypatch):
    x, lbl, _ = _task()
    ref = _streaming_fit(x, lbl)
    monkeypatch.setenv("KEYSTONE_HEALTH", "0")
    m0 = _streaming_fit(x, lbl)
    assert np.array_equal(np.asarray(ref.w), np.asarray(m0.w))
    assert np.array_equal(np.asarray(ref.b), np.asarray(m0.b))
    # a no-trip guarded fit is a bit-exact pass-through too (the gate
    # selects the identical R_cand when healthy)
    monkeypatch.setenv("KEYSTONE_HEALTH", "warn")
    t0 = _counter_sum("health.tripped")
    mw = _streaming_fit(x, lbl)
    assert np.array_equal(np.asarray(ref.w), np.asarray(mw.w))
    assert _counter_sum("health.tripped") == t0  # no new trips


def test_streaming_warn_quarantines_poisoned_block(monkeypatch):
    x, lbl, _ = _task()
    monkeypatch.setenv("KEYSTONE_HEALTH", "warn")
    q0, t0 = _counter_sum("health.quarantined"), _counter_sum(
        "health.tripped"
    )
    faults.reset()
    monkeypatch.setenv("KEYSTONE_FAULTS", "block@2:nan")
    m = _streaming_fit(x, lbl)
    monkeypatch.delenv("KEYSTONE_FAULTS")
    assert _counter_sum("health.tripped") > t0
    assert _counter_sum("health.quarantined") == q0 + 1
    w = np.asarray(m.w)
    assert np.all(np.isfinite(w)) and np.all(np.isfinite(np.asarray(m.b)))
    # the poisoned block (schedule pos 2 = block 2, sequential order)
    # contributed nothing: its weights are exactly zero
    assert np.all(w[2 * 8 : 3 * 8] == 0.0)
    assert np.any(w[:8] != 0.0)


@pytest.mark.parametrize("kind", ["inf", "saturate"])
def test_streaming_sentinels_trip_on_every_numeric_kind(monkeypatch, kind):
    x, lbl, _ = _task(seed=4)
    monkeypatch.setenv("KEYSTONE_HEALTH", "warn")
    t0 = _counter_sum("health.tripped")
    faults.reset()
    monkeypatch.setenv("KEYSTONE_FAULTS", f"block@1:{kind}")
    m = _streaming_fit(x, lbl)
    monkeypatch.delenv("KEYSTONE_FAULTS")
    assert _counter_sum("health.tripped") > t0
    assert np.all(np.isfinite(np.asarray(m.w)))


def test_streaming_heal_escalates_and_matches_envelope(monkeypatch):
    x, lbl, cls = _task(seed=5)

    def err(m):
        pred = np.argmax(
            x @ np.asarray(m.w) + np.asarray(m.b)[None, :], axis=1
        )
        return float(np.mean(pred != cls))

    clean = _streaming_fit(x, lbl)
    monkeypatch.setenv("KEYSTONE_HEALTH", "heal")
    e0, h0 = _counter_sum("health.escalations"), _counter_sum("health.healed")
    faults.reset()
    monkeypatch.setenv("KEYSTONE_FAULTS", "block@2:nan")
    healed = _streaming_fit(x, lbl)
    monkeypatch.delenv("KEYSTONE_FAULTS")
    assert _counter_sum("health.escalations") > e0
    assert _counter_sum("health.healed") > h0
    # the healed block genuinely contributes (not a silent quarantine)
    assert np.any(np.asarray(healed.w)[2 * 8 : 3 * 8] != 0.0)
    assert err(healed) <= err(clean) + 0.02


def test_streaming_unguarded_poison_is_the_hazard(monkeypatch):
    # the contrast case: KEYSTONE_HEALTH=0 lets the NaN block poison the
    # whole model — exactly what the sentinels exist to prevent
    x, lbl, _ = _task(seed=6)
    faults.reset()
    monkeypatch.setenv("KEYSTONE_FAULTS", "block@2:nan")
    m = _streaming_fit(x, lbl)
    monkeypatch.delenv("KEYSTONE_FAULTS")
    assert not np.all(np.isfinite(np.asarray(m.w)))


# ---------------------------------------------------------------------------
# Checkpoint replay: kill mid-fit, resume, same decisions
# ---------------------------------------------------------------------------

def test_poisoned_kill_and_resume_replays_heal_bit_exact(
    tmp_path, monkeypatch
):
    x, lbl, _ = _task(seed=7)
    monkeypatch.setenv("KEYSTONE_HEALTH", "heal")

    # uninterrupted poisoned twin (same injection, no kill)
    faults.reset()
    monkeypatch.setenv("KEYSTONE_FAULTS", "block@2:nan")
    twin = _streaming_fit(x, lbl)
    monkeypatch.delenv("KEYSTONE_FAULTS")
    faults.reset()

    # poisoned + killed at pos 5, then resumed from the checkpoint
    ckpt = str(tmp_path / "fit.ckpt")
    monkeypatch.setenv("KEYSTONE_FAULTS", "block@2:nan,block@5:xla")
    with pytest.raises(Exception, match="injected fault"):
        _streaming_fit(x, lbl, checkpoint_path=ckpt, checkpoint_every=1)
    monkeypatch.delenv("KEYSTONE_FAULTS")
    faults.reset()
    assert os.path.exists(ckpt)

    from keystone_tpu.core.checkpoint import load_manifest

    man = load_manifest(ckpt)
    assert man["health_mode"] == "heal"
    assert 2 in man["health_tripped"]

    resumed = _streaming_fit(
        x, lbl, checkpoint_path=ckpt, checkpoint_every=1
    )
    assert not os.path.exists(ckpt)
    # the restored sentinel records + deterministic heal pass make the
    # resumed fit BIT-EXACT vs the uninterrupted poisoned twin
    assert np.array_equal(np.asarray(twin.w), np.asarray(resumed.w))
    assert np.array_equal(np.asarray(twin.b), np.asarray(resumed.b))


def test_resume_under_flipped_health_mode_is_loud(tmp_path, monkeypatch):
    from keystone_tpu.core.checkpoint import CheckpointMismatchError

    x, lbl, _ = _task(seed=8)
    ckpt = str(tmp_path / "fit.ckpt")
    monkeypatch.setenv("KEYSTONE_HEALTH", "heal")
    faults.reset()
    monkeypatch.setenv("KEYSTONE_FAULTS", "block@2:nan,block@5:xla")
    with pytest.raises(Exception, match="injected fault"):
        _streaming_fit(x, lbl, checkpoint_path=ckpt, checkpoint_every=1)
    monkeypatch.delenv("KEYSTONE_FAULTS")
    faults.reset()
    monkeypatch.setenv("KEYSTONE_HEALTH", "0")
    with pytest.raises(CheckpointMismatchError, match="KEYSTONE_HEALTH"):
        _streaming_fit(x, lbl, checkpoint_path=ckpt, checkpoint_every=1)


# ---------------------------------------------------------------------------
# BCD scan sentinels
# ---------------------------------------------------------------------------

def _bcd_system(seed=9, n=128, d=32, c=3):
    rng = np.random.default_rng(seed)
    A = rng.normal(size=(n, d)).astype(np.float32)
    Wt = rng.normal(size=(d, c)).astype(np.float32)
    return jnp.asarray(A), jnp.asarray(A @ Wt)


def test_bcd_warn_no_trip_is_bit_identical(monkeypatch):
    from keystone_tpu.linalg.bcd import block_coordinate_descent_l2

    A, b = _bcd_system()
    ref = block_coordinate_descent_l2(A, b, 1e-3, 8, num_iter=2)
    monkeypatch.setenv("KEYSTONE_HEALTH", "warn")
    w = block_coordinate_descent_l2(A, b, 1e-3, 8, num_iter=2)
    assert np.array_equal(np.asarray(ref), np.asarray(w))


def test_bcd_poisoned_entry_quarantines_and_stays_finite(monkeypatch):
    from keystone_tpu.linalg.bcd import block_coordinate_descent_l2

    A, b = _bcd_system()
    monkeypatch.setenv("KEYSTONE_HEALTH", "warn")
    q0 = _counter_sum("health.quarantined")
    faults.reset()
    monkeypatch.setenv("KEYSTONE_FAULTS", "bcd@0:nan")
    w = block_coordinate_descent_l2(A, b, 1e-3, 8, num_iter=2)
    monkeypatch.delenv("KEYSTONE_FAULTS")
    assert _counter_sum("health.quarantined") > q0
    assert np.all(np.isfinite(np.asarray(w)))


def test_bcd_heal_escalates_bf16_to_f32(monkeypatch):
    from keystone_tpu.linalg.bcd import block_coordinate_descent_l2

    A, b = _bcd_system(seed=10)
    monkeypatch.setenv("KEYSTONE_HEALTH", "heal")
    monkeypatch.setenv("KEYSTONE_PRECISION_TIER", "bf16")
    e0 = _counter_sum("health.escalations")
    faults.reset()
    monkeypatch.setenv("KEYSTONE_FAULTS", "bcd@0:nan")
    w = block_coordinate_descent_l2(A, b, 1e-3, 8, num_iter=2)
    monkeypatch.delenv("KEYSTONE_FAULTS")
    # the storage escalation fired (bf16 -> f32 re-run); the poison is
    # in-call permanent, so the f32 run's own gate still quarantines —
    # loud, finite, never wedged
    assert _counter_sum("health.escalations") > e0
    assert np.all(np.isfinite(np.asarray(w)))


# ---------------------------------------------------------------------------
# One-shot guarded solver ladder
# ---------------------------------------------------------------------------

def _lstsq_system(seed=11, n=256, d=16, c=2):
    rng = np.random.default_rng(seed)
    A = rng.normal(size=(n, d)).astype(np.float32)
    Wt = rng.normal(size=(d, c)).astype(np.float32)
    return jnp.asarray(A), jnp.asarray(A @ Wt), Wt


def test_guarded_lstsq_escalates_failed_sketch_to_tsqr(monkeypatch):
    A, b, Wt = _lstsq_system()
    monkeypatch.setenv("KEYSTONE_HEALTH", "heal")
    nan_W = jnp.full((A.shape[1], b.shape[1]), jnp.nan)
    monkeypatch.setitem(
        health._RUNGS, "sketch",
        lambda *a, **k: (nan_W, jnp.float32(jnp.nan)),
    )
    e0, h0 = _counter_sum("health.escalations"), _counter_sum("health.healed")
    W = health.guarded_lstsq(A, b, lam=1e-4, rung="sketch")
    assert _counter_sum("health.escalations") > e0
    assert _counter_sum("health.healed") > h0
    assert np.linalg.norm(np.asarray(W) - Wt) / np.linalg.norm(Wt) < 1e-3


def test_guarded_lstsq_warn_returns_first_attempt_loudly(monkeypatch):
    A, b, _ = _lstsq_system(seed=12)
    monkeypatch.setenv("KEYSTONE_HEALTH", "warn")
    nan_W = jnp.full((A.shape[1], b.shape[1]), jnp.nan)
    monkeypatch.setitem(
        health._RUNGS, "sketch",
        lambda *a, **k: (nan_W, jnp.float32(jnp.nan)),
    )
    t0 = _counter_sum("health.tripped")
    W = health.guarded_lstsq(A, b, lam=1e-4, rung="sketch")
    assert _counter_sum("health.tripped") > t0
    assert not np.all(np.isfinite(np.asarray(W)))  # warn never substitutes


def test_guarded_lstsq_exhaustion_is_loud_not_wedged(monkeypatch):
    A, b, _ = _lstsq_system(seed=13)
    monkeypatch.setenv("KEYSTONE_HEALTH", "heal")
    nan_W = jnp.full((A.shape[1], b.shape[1]), jnp.nan)
    for rung in health.RUNG_LADDER:
        fail = (
            (lambda *a, **k: (nan_W, jnp.float32(jnp.nan)))
            if rung == "sketch" else (lambda *a, **k: nan_W)
        )
        monkeypatch.setitem(health._RUNGS, rung, fail)
    x0 = _counter_sum("health.exhausted")
    W = health.guarded_lstsq(A, b, lam=1e-4, rung="sketch")
    assert _counter_sum("health.exhausted") > x0
    assert W is not None


def test_guarded_lstsq_rung_error_escalates(monkeypatch):
    A, b, Wt = _lstsq_system(seed=14)
    monkeypatch.setenv("KEYSTONE_HEALTH", "heal")

    def boom(*a, **k):
        raise RuntimeError("synthetic rung failure")

    monkeypatch.setitem(health._RUNGS, "sketch", boom)
    W = health.guarded_lstsq(A, b, lam=1e-4, rung="sketch")
    assert np.linalg.norm(np.asarray(W) - Wt) / np.linalg.norm(Wt) < 1e-3


def test_solver_classes_route_through_guard_only_when_armed(monkeypatch):
    from keystone_tpu.linalg.distributed import TSQR

    A, b, _ = _lstsq_system(seed=15)
    ref = TSQR().solve_least_squares(A, b, lam=1e-4)
    monkeypatch.setenv("KEYSTONE_HEALTH", "0")
    off = TSQR().solve_least_squares(A, b, lam=1e-4)
    assert np.array_equal(np.asarray(ref), np.asarray(off))
    # armed: the guarded path certifies the clean system and returns the
    # same rung's answer
    monkeypatch.setenv("KEYSTONE_HEALTH", "warn")
    guarded = TSQR().solve_least_squares(A, b, lam=1e-4)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(guarded), rtol=1e-5, atol=1e-5
    )


def test_sketch_certificate_is_returned_and_small():
    from keystone_tpu.linalg.sketch import sketched_lstsq_solve

    A, b, Wt = _lstsq_system(seed=16)
    x, cert = sketched_lstsq_solve(A, b, lam=1e-4, with_certificate=True)
    assert np.asarray(cert).shape == ()
    assert float(cert) < health._sketch_cert_limit()
    assert np.linalg.norm(np.asarray(x) - Wt) / np.linalg.norm(Wt) < 1e-3


# ---------------------------------------------------------------------------
# Numeric fault kinds: grammar + poison + eager validation
# ---------------------------------------------------------------------------

def test_numeric_kinds_parse_and_return_spec(monkeypatch):
    monkeypatch.setenv("KEYSTONE_FAULTS", "block@1:nan, bcd@0:saturate")
    plan = knobs.get("KEYSTONE_FAULTS")
    assert plan == (
        faults.FaultSpec("block", 1, "nan", 1),
        faults.FaultSpec("bcd", 0, "saturate", 1),
    )
    faults.reset()
    assert faults.check("bcd") == faults.FaultSpec("bcd", 0, "saturate", 1)
    assert faults.check("block") is None   # occurrence 0: clean
    assert faults.check("block") == faults.FaultSpec("block", 1, "nan", 1)


@pytest.mark.parametrize("bad", [
    "segment@1:nan", "bench_section@0:inf", "segment@2:saturate",
])
def test_numeric_kind_at_non_data_site_fails_eagerly(monkeypatch, bad):
    # satellite pin: a malformed plan fails at validate_environment()
    # (the CLI/bench fail-fast), never deep inside a fit
    monkeypatch.setenv("KEYSTONE_FAULTS", bad)
    with pytest.raises(ValueError, match="numeric kind"):
        knobs.validate_environment()


def test_poison_kinds_overwrite_first_row():
    x = jnp.asarray(np.ones((4, 3), np.float32))
    assert np.all(np.isnan(np.asarray(faults.poison(x, "nan"))[0]))
    assert np.all(np.isinf(np.asarray(faults.poison(x, "inf"))[0]))
    sat = np.asarray(faults.poison(x, "saturate"))
    assert np.all(sat[0] >= 1e38) and np.all(np.isfinite(sat[0]))
    # rows past the first are untouched
    for kind in ("nan", "inf", "saturate"):
        assert np.all(np.asarray(faults.poison(x, kind))[1:] == 1.0)
    with pytest.raises(ValueError, match="poison kind"):
        faults.poison(x, "xla")


# ---------------------------------------------------------------------------
# A1 sentinel allowance + the guarded audit entry
# ---------------------------------------------------------------------------

def test_sentinel_all_reduce_check_budget():
    from keystone_tpu.analysis.ir_rules import check_sentinel_all_reduces

    scalar = "  %ar = f32[] all-reduce(f32[] %x), replica_groups={}\n"
    bulk = (
        "  %ar2 = f32[128,128]{1,0} all-reduce(f32[128,128]{1,0} %y)\n"
    )
    assert check_sentinel_all_reduces(scalar, 2) == []
    assert any(
        "bulk all-reduce" in p
        for p in check_sentinel_all_reduces(bulk, 2)
    )
    # budget overflow: three scalars against a budget of two
    assert any(
        "scalar all-reduces" in p
        for p in check_sentinel_all_reduces(scalar * 3, 2)
    )
    # tuple result shapes sum their members
    tup = "  %ar3 = (f32[], f32[4]) all-reduce(f32[] %a, f32[4] %b)\n"
    assert check_sentinel_all_reduces(tup, 1) == []


def test_guarded_block_step_audits_clean(devices):
    from keystone_tpu.analysis.ir_audit import (
        INTENDED_PRECISION,
        run_audit,
    )

    assert INTENDED_PRECISION["solver.block_step_guarded"] == ("f32", "f32")
    res = run_audit(["solver.block_step_guarded"], baseline_path=None)
    assert not res.errors and not res.skipped
    assert res.findings == []
