"""Randomized solver tier (``linalg/sketch.py``): subspace-embedding
statistics for CountSketch/SRHT, sketch-and-precondition correctness against
dense oracles at odd shard counts and indivisible d, the convergence-
tolerance contract of the preconditioned iteration, leverage-score block
scheduling, the ``KEYSTONE_SOLVER`` tier routing, and the zero-transfer
guard fixture for the sketched hot loop."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu import telemetry
from keystone_tpu.core.dataset import pad_rows
from keystone_tpu.linalg import (
    SketchedLeastSquares,
    TSQR,
    block_coordinate_descent_l2,
    leverage_block_order,
    normal_equations_solve,
    sketch_matrix,
    sketch_rows,
    sketched_lstsq_solve,
)
from keystone_tpu.parallel import distribute, make_mesh, use_mesh


def _planted(rng, n=256, d=24, c=3, noise=0.0):
    A = rng.normal(size=(n, d)).astype(np.float32)
    W = rng.normal(size=(d, c)).astype(np.float32)
    b = A @ W + noise * rng.normal(size=(n, c)).astype(np.float32)
    return A, W, b


# -- sketch operators -------------------------------------------------------


@pytest.mark.parametrize("kind", ["countsketch", "srht"])
def test_sketch_subspace_embedding_statistics(rng, kind):
    """The property the whole tier rests on: every singular value of S·A is
    within a constant band of A's (a subspace embedding), so the sketched
    R preconditions the full system to O(1) conditioning. Deterministic
    seeds; the ±0.5 band is loose for m = 8·d."""
    A = jnp.asarray(rng.normal(size=(512, 16)).astype(np.float32))
    m = sketch_rows(512, 16, factor=8.0)
    SA, _ = sketch_matrix(A, m, 0, kind=kind)
    assert SA.shape == (m, 16)
    s_a = np.linalg.svd(np.asarray(A), compute_uv=False)
    s_sa = np.linalg.svd(np.asarray(SA), compute_uv=False)
    ratios = s_sa / s_a
    assert ratios.max() < 1.5 and ratios.min() > 0.5, ratios


@pytest.mark.parametrize("kind", ["countsketch", "srht"])
def test_sketch_preconditioner_conditioning(rng, kind):
    """κ(A R⁻¹) after the sketched QR must be O(1) even when A itself is
    badly conditioned — the measurable form of the embedding guarantee."""
    A = rng.normal(size=(512, 12)).astype(np.float32)
    A[:, 0] *= 1e3  # κ(A) ~ 1e3
    m = sketch_rows(512, 12, factor=8.0)
    SA, _ = sketch_matrix(jnp.asarray(A), m, 0, kind=kind)
    R = np.linalg.qr(np.asarray(SA), mode="r")
    precond = A @ np.linalg.inv(R)
    s = np.linalg.svd(precond, compute_uv=False)
    assert s[0] / s[-1] < 4.0, s[0] / s[-1]


def test_sketch_matrix_sharded_replicated_pair(devices, rng):
    """Sharded sketch contract: (S·A, S·b) from ONE operator, replicated,
    at both an even and an odd shard count."""
    for nk in (8, 5):
        mesh = make_mesh(data=nk, model=1, devices=devices[:nk])
        n = 40 * nk
        A = jnp.asarray(rng.normal(size=(n, 12)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32))
        with use_mesh(mesh):
            m = sketch_rows(n, 12, k=nk)
            SA, Sb = sketch_matrix(A, m, 0, y=b, mesh=mesh)
        assert SA.shape == (m, 12) and Sb.shape == (m, 3)
        # the pair is consistent: lstsq on the sketch ≈ lstsq on the data
        # (sketch-and-solve, the warm start the preconditioned CG refines)
        w_sk = np.linalg.lstsq(np.asarray(SA), np.asarray(Sb), rcond=None)[0]
        w_ref = np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]
        assert np.abs(w_sk - w_ref).max() < 0.5


def test_srht_sketch_rows_divisibility_error(devices, rng):
    mesh = make_mesh(data=8, model=1, devices=devices)
    A = jnp.asarray(rng.normal(size=(64, 4)).astype(np.float32))
    with pytest.raises(ValueError, match="per-shard sample"):
        sketch_matrix(A, 12, 0, kind="srht", mesh=mesh)  # 12 % 16 != 0


# -- sketched solve vs dense oracles ----------------------------------------


def test_sketched_solve_matches_lstsq_oracle_odd_shards(devices, rng):
    """Dense-oracle equivalence at the shapes the tiled paths cannot touch:
    odd shard counts and an indivisible d (the ring-fold test's regime),
    with A genuinely row-sharded (the committed-sharding gate routes
    uncommitted arrays to the single-program form), plus that no-mesh
    single-program form itself."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    d, c = 10, 3
    for nk in (1, 5, 8):
        mesh = make_mesh(data=nk, model=1, devices=devices[:nk])
        n = 30 * nk
        A = rng.normal(size=(n, d)).astype(np.float32)
        b = rng.normal(size=(n, c)).astype(np.float32)
        with use_mesh(mesh):
            Aj = jax.device_put(
                jnp.asarray(A), NamedSharding(mesh, P("data", None))
            )
            bj = jax.device_put(
                jnp.asarray(b), NamedSharding(mesh, P("data", None))
            )
            w0 = np.asarray(sketched_lstsq_solve(Aj, bj, mesh=mesh, tol=1e-8))
            w2 = np.asarray(
                sketched_lstsq_solve(Aj, bj, lam=1.5, mesh=mesh, tol=1e-8)
            )
        w_ref = np.linalg.lstsq(A, b, rcond=None)[0]
        np.testing.assert_allclose(w0, w_ref, rtol=1e-3, atol=1e-4)
        w_ridge = np.asarray(normal_equations_solve(A, b, lam=1.5))
        np.testing.assert_allclose(w2, w_ridge, rtol=1e-3, atol=1e-4)


def test_sketched_solve_masked_rows_ignored(rng):
    A, _, b = _planted(rng, n=100, d=12, noise=0.2)
    w_full = np.asarray(sketched_lstsq_solve(A, b, lam=1.0, tol=1e-8))
    Ap, mask = pad_rows(jnp.asarray(A), 16)
    bp, _ = pad_rows(jnp.asarray(b), 16)
    Ap = Ap.at[100:].set(99.0)  # poison the padding; mask must hide it
    bp = bp.at[100:].set(-99.0)
    w_masked = np.asarray(
        sketched_lstsq_solve(Ap, bp, lam=1.0, mask=mask, tol=1e-8)
    )
    np.testing.assert_allclose(w_masked, w_full, atol=1e-4)


def test_sketched_solve_overlap_matches(devices, rng):
    """Overlap knob on (tiled reduce-scatter sketch reduction + tiled CG
    AᵀAp): same solution as the monolithic path, and the tiled-psum
    schedule actually engaged (counters, not logs)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(data=8, model=1, devices=devices)
    A, _, b = _planted(rng, n=128, d=16, noise=0.3)
    with use_mesh(mesh):
        Aj = jax.device_put(
            jnp.asarray(A), NamedSharding(mesh, P("data", None))
        )
        bj = jax.device_put(
            jnp.asarray(b), NamedSharding(mesh, P("data", None))
        )
        w_off = np.asarray(
            sketched_lstsq_solve(Aj, bj, lam=0.5, mesh=mesh, tol=1e-8)
        )
        telemetry.reset()
        # overlap=True is a different static config, so this traces fresh
        # programs — the engaged counters (trace-time) must fire
        w_on = np.asarray(
            sketched_lstsq_solve(
                Aj, bj, lam=0.5, mesh=mesh, tol=1e-8, overlap=True
            )
        )
    np.testing.assert_allclose(w_on, w_off, rtol=1e-3, atol=1e-4)
    reg = telemetry.get_registry()
    assert reg.get_counter(
        "overlap.engaged", site="tiled_psum", schedule="single_tier"
    ) >= 1, reg.as_dict()["counters"]
    telemetry.reset()


def test_countsketch_reduction_hlo_pins_tiled_schedule(devices, rng):
    """Structure pin via the auditor's own helpers (ir_rules.py): the
    committed-mesh CountSketch (S·A, S·b) reduction lowers to >= k
    per-tile reduce-scatters, at most two trailing all-gathers (one per
    pair member), and NO all-reduce — exactly the program
    `keystone-tpu audit solver.countsketch_reduce` checks, so the test
    and the auditor cannot drift apart."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.analysis.ir_rules import (
        assert_pipelined_reduce_scatter,
    )

    mesh = make_mesh(data=8, model=1, devices=devices)
    k = mesh.shape["data"]
    A = jax.device_put(
        jnp.asarray(rng.normal(size=(16 * k, 16)).astype(np.float32)),
        NamedSharding(mesh, P("data", None)),
    )
    b = jax.device_put(
        jnp.asarray(rng.normal(size=(16 * k, 3)).astype(np.float32)),
        NamedSharding(mesh, P("data", None)),
    )
    f = jax.jit(lambda A_, b_: sketch_matrix(
        A_, 8 * k, 7, y=b_, kind="countsketch", mesh=mesh, omesh=mesh,
    ))
    hlo = f.lower(A, b).compile().as_text()
    assert_pipelined_reduce_scatter(hlo, k, all_gather_max=2)


# -- convergence-tolerance contract -----------------------------------------


def test_sketched_solve_tolerance_pin(rng):
    """Tighter tol ⇒ at least as many CG iterations and a smaller final
    relative residual; tol=0 pins the iteration count to max_iters exactly
    (the bench's fixed-work form). Counters ride the telemetry registry
    under tracing, the bcd residual-trajectory precedent."""
    A, _, b = _planted(rng, n=200, d=16, noise=0.5)

    def run(tol, max_iters=50):
        telemetry.reset()
        with telemetry.use_tracing(True):
            sketched_lstsq_solve(A, b, lam=1.0, tol=tol, max_iters=max_iters)
        reg = telemetry.get_registry()
        return (
            reg.get_counter("solver.sketch.iterations"),
            reg.get_gauge("solver.sketch.final_residual_rel"),
        )

    it_loose, res_loose = run(1e-1)
    it_tight, res_tight = run(1e-7)
    assert it_tight >= it_loose >= 1
    assert res_tight < res_loose
    assert res_tight < 1e-6
    it_fixed, _ = run(0.0, max_iters=3)
    assert it_fixed == 3
    telemetry.reset()


def test_sketch_phase_spans_and_flops(rng):
    """The sketch/QR/iterate phases land as spans with analytic-FLOP
    counters — the tier's telemetry contract."""
    A, _, b = _planted(rng, n=128, d=8, noise=0.2)
    telemetry.reset()
    with telemetry.use_tracing(True):
        sketched_lstsq_solve(A, b, lam=1.0, tol=1e-6)
    reg = telemetry.get_registry()
    assert reg.get_counter("solver.calls", solver="sketch") == 1
    assert reg.get_counter("solver.sketch.sketch_flops") > 0
    assert reg.get_counter("solver.sketch.qr_flops") > 0
    assert reg.get_counter("solver.sketch.iter_flops") > 0
    h = reg.get_histogram("solver.sketch.residual_rel")
    assert h is not None and h["count"] >= 1
    names = {s["name"] for s in telemetry.get_tracer().spans_as_dicts()}
    assert {"solver.sketch", "solver.sketch.sketch_qr",
            "solver.sketch.iterate"} <= names
    telemetry.reset()


# -- leverage-score block scheduling ----------------------------------------


def test_leverage_block_order_prioritizes_energy(rng):
    A = rng.normal(size=(256, 32)).astype(np.float32)
    A[:, 16:24] *= 50.0  # block 2 of 4 (bs=8) carries the spectrum
    order = np.asarray(leverage_block_order(jnp.asarray(A), 8))
    assert order[0] == 2, order
    assert sorted(order.tolist()) == [0, 1, 2, 3]


def test_bcd_leverage_schedule_converges_to_same_solution(rng):
    """At convergence the leverage visit order reaches the same ridge
    solution as sequential (Gauss–Seidel order only changes the path)."""
    A, _, b = _planted(rng, n=200, d=30, noise=0.5)
    lam = 4.0
    w_seq = np.asarray(
        block_coordinate_descent_l2(A, b, lam, block_size=8, num_iter=25)
    )
    w_lev = np.asarray(
        block_coordinate_descent_l2(
            A, b, lam, block_size=8, num_iter=25, block_schedule="leverage"
        )
    )
    np.testing.assert_allclose(w_lev, w_seq, atol=1e-3)
    grad = A.T @ (A @ w_lev - b) + lam * w_lev
    assert np.abs(grad).max() < 1e-2


def test_bcd_rejects_unknown_schedule(rng):
    A, _, b = _planted(rng, d=16)
    with pytest.raises(ValueError, match="block_schedule"):
        block_coordinate_descent_l2(A, b, 1.0, 8, block_schedule="random")


# -- KEYSTONE_SOLVER tier routing -------------------------------------------


def test_solver_tier_knob_routes_estimator_classes(monkeypatch, rng):
    from keystone_tpu.learning import LinearMapEstimator

    A, _, b = _planted(rng, n=128, d=16, noise=0.2)
    w_ref = np.linalg.lstsq(A, b, rcond=None)[0]
    monkeypatch.setenv("KEYSTONE_SOLVER", "sketch")
    telemetry.reset()
    w = np.asarray(TSQR().solve_least_squares(A, b))
    np.testing.assert_allclose(w, w_ref, rtol=1e-3, atol=5e-4)
    # the sketch tier actually ran (not the exact path under a new name)
    reg = telemetry.get_registry()
    assert reg.get_counter("solver.calls", solver="sketch") == 1
    assert reg.get_counter("solver.calls", solver="tsqr") == 0
    # noiseless planted data: the routed estimator must still recover it
    A0, _, b0 = _planted(rng, noise=0.0)
    model = LinearMapEstimator(lam=0.01).fit(jnp.asarray(A0), jnp.asarray(b0))
    pred = np.asarray(model(jnp.asarray(A0)))
    np.testing.assert_allclose(pred, b0, atol=5e-2)
    assert reg.get_counter("solver.calls", solver="sketch") == 2
    monkeypatch.setenv("KEYSTONE_SOLVER", "junk")
    with pytest.raises(ValueError, match="KEYSTONE_SOLVER"):
        TSQR().solve_least_squares(A, b)
    telemetry.reset()


def test_sketched_least_squares_class(rng):
    A, _, b = _planted(rng, n=128, d=16, noise=0.1)
    w = np.asarray(
        SketchedLeastSquares(tol=1e-8).solve_least_squares(A, b)
    )
    np.testing.assert_allclose(
        w, np.linalg.lstsq(A, b, rcond=None)[0], rtol=1e-3, atol=1e-4
    )


def test_sketch_knob_validation(monkeypatch):
    from keystone_tpu.utils import knobs

    monkeypatch.setenv("KEYSTONE_SKETCH_FACTOR", "0.5")
    with pytest.raises(ValueError, match="KEYSTONE_SKETCH_FACTOR"):
        knobs.get("KEYSTONE_SKETCH_FACTOR")
    monkeypatch.setenv("KEYSTONE_SKETCH_KIND", "gaussian")
    with pytest.raises(ValueError, match="KEYSTONE_SKETCH_KIND"):
        knobs.get("KEYSTONE_SKETCH_KIND")


def test_weighted_bcd_sketch_tier_leverage_order(monkeypatch, rng):
    """KEYSTONE_SOLVER=sketch orders the weighted-BCD block visits by
    sketched leverage; at multiple passes the fit stays close to the
    sequential fit (same fixed point)."""
    from keystone_tpu.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )
    from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels

    X = jnp.asarray(rng.normal(size=(96, 24)).astype(np.float32))
    lab = ClassLabelIndicatorsFromIntLabels(3)(
        jnp.asarray(rng.integers(0, 3, 96))
    )
    est = BlockWeightedLeastSquaresEstimator(8, 6, 0.5, 0.25)
    m_seq = est.fit(X, lab)
    monkeypatch.setenv("KEYSTONE_SOLVER", "sketch")
    m_lev = est.fit(X, lab)
    np.testing.assert_allclose(
        np.asarray(m_lev.w), np.asarray(m_seq.w), atol=5e-2
    )


def test_weighted_bcd_checkpoint_rejects_changed_order(rng, tmp_path):
    """A checkpoint written under one visit order must not resume under
    another — the cursor is a schedule position, and silently mixing
    orders would corrupt the Gauss–Seidel pass. A mid-fit kill (simulated
    by a failing block featurizer) leaves the checkpoint behind; the
    resume under a permuted order must fail loudly."""
    import os

    from keystone_tpu.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )
    from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels

    X = jnp.asarray(rng.normal(size=(64, 24)).astype(np.float32))
    lab = ClassLabelIndicatorsFromIntLabels(3)(
        jnp.asarray(rng.integers(0, 3, 64))
    )
    path = str(tmp_path / "wbcd.ckpt")
    est = BlockWeightedLeastSquaresEstimator(8, 2, 0.5, 0.25)

    calls = []

    def get_block(b):
        if len(calls) == 4:
            raise RuntimeError("simulated mid-fit crash")
        calls.append(b)
        return jax.lax.dynamic_slice_in_dim(X, b * 8, 8, 1)

    with pytest.raises(RuntimeError, match="mid-fit crash"):
        est._run(get_block, 3, lab, None, "high",
                 checkpoint_path=path, checkpoint_every=1)
    assert os.path.exists(path), "mid-fit crash should leave the checkpoint"
    with pytest.raises(ValueError, match="block order"):
        est._run(get_block, 3, lab, None, "high",
                 checkpoint_path=path, checkpoint_every=1,
                 block_order=[2, 0, 1])


# -- zero-transfer guard fixture --------------------------------------------


def test_sketched_hot_loop_zero_transfers():
    """The sketched solve's warmed fit loop is transfer-guard-clean: no
    implicit host↔device uploads in sketch/QR/iterate (lam, tol, seed all
    ride device_scalar; the sketch draws its randomness in-program)."""
    from keystone_tpu.analysis.guard import guard, violations
    from keystone_tpu.telemetry.registry import MetricsRegistry

    rng = np.random.default_rng(3)
    A = jnp.asarray(rng.normal(size=(96, 16)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(96, 3)).astype(np.float32))

    def solve():
        jax.block_until_ready(
            sketched_lstsq_solve(A, b, lam=0.5, tol=1e-6)
        )

    solve()  # warm: compile everything outside the guard
    reg = MetricsRegistry()
    with guard(registry=reg):
        solve()
    v = violations(reg)
    assert v["guard.transfer"] == 0, reg.as_dict()["counters"]
    assert v["guard.recompile"] == 0, reg.as_dict()["counters"]


def test_srht_short_input_clamps_and_pads(rng):
    """n < factor·d (the short-input regime): each shard samples only the
    rows it holds and zero-pads to the requested sketch height — shapes
    stay the contract's (m, d) and the solve still matches the oracle."""
    A = rng.normal(size=(100, 64)).astype(np.float32)
    b = rng.normal(size=(100, 3)).astype(np.float32)
    m = sketch_rows(100, 64)
    assert m > 100  # the regime under test: sketch taller than the data
    SA, _ = sketch_matrix(jnp.asarray(A), m, 0, kind="srht")
    assert SA.shape == (m, 64)
    w = np.asarray(
        sketched_lstsq_solve(A, b, lam=1.0, kind="srht", tol=1e-8)
    )
    w_ref = np.asarray(normal_equations_solve(A, b, lam=1.0))
    np.testing.assert_allclose(w, w_ref, rtol=1e-3, atol=1e-3)


def test_committed_gate_rejects_column_sharded(devices, rng):
    """P('data','model') operands must NOT take the shard_map sketch path:
    the P('data', None) in_specs would all-gather the model axis of the
    full matrix — the implicit transfer (and at FV scale, OOM) the
    committed-sharding gate exists to prevent."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.linalg.sketch import _committed_sketch_mesh

    mesh = make_mesh(data=4, model=2, devices=devices)
    x = jnp.asarray(rng.normal(size=(64, 16)).astype(np.float32))
    with use_mesh(mesh):
        rowed = jax.device_put(x, NamedSharding(mesh, P("data", None)))
        both = jax.device_put(x, NamedSharding(mesh, P("data", "model")))
        assert _committed_sketch_mesh(rowed, mesh, "data") is mesh
        assert _committed_sketch_mesh(both, mesh, "data") is None
        assert _committed_sketch_mesh(x, mesh, "data") is None  # uncommitted
        # the solve still WORKS on the column-sharded operand — it just
        # takes the single-program form (XLA SPMD partitions it)
        b = jnp.asarray(rng.normal(size=(64, 3)).astype(np.float32))
        w = np.asarray(sketched_lstsq_solve(both, b, lam=1.0, tol=1e-8))
        w_ref = np.asarray(normal_equations_solve(x, b, lam=1.0))
        np.testing.assert_allclose(w, w_ref, rtol=1e-3, atol=1e-3)
