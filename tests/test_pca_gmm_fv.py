"""PCA, GMM-EM, and Fisher Vector tests, mirroring the reference's
property/statistical suites (PCASuite, EncEvalSuite planted-Gaussian
recovery) plus an autodiff oracle for the FV encoding."""

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.learning import (
    GaussianMixtureModel,
    GaussianMixtureModelEstimator,
    PCAEstimator,
)
from keystone_tpu.ops.images import FisherVector
from keystone_tpu.parallel import distribute, make_mesh, use_mesh


def _correlated_data(rng, n=400, d=10):
    basis = rng.normal(size=(d, d))
    z = rng.normal(size=(n, 4)) * np.array([5.0, 3.0, 1.0, 0.5])
    return (z @ basis[:4] + 0.05 * rng.normal(size=(n, d))).astype(np.float32)


def test_pca_reduced_covariance_is_diagonal(rng):
    """PCASuite.scala:51-78: covariance of the projected data is diagonal."""
    x = _correlated_data(rng)
    pca = PCAEstimator(dims=4, method="svd").fit(jnp.asarray(x))
    out = np.asarray(pca(jnp.asarray(x - x.mean(0))))
    cov = out.T @ out / (out.shape[0] - 1)
    off = cov - np.diag(np.diag(cov))
    assert np.abs(off).max() < 1e-2 * np.abs(np.diag(cov)).max()
    # variance ordering: descending
    dvar = np.diag(cov)
    assert np.all(dvar[:-1] >= dvar[1:] - 1e-5)


def test_pca_gram_matches_svd(rng):
    x = _correlated_data(rng, n=800)
    p_svd = np.asarray(PCAEstimator(4, "svd").fit(jnp.asarray(x)).pca_mat)
    p_gram = np.asarray(PCAEstimator(4, "gram").fit(jnp.asarray(x)).pca_mat)
    # same subspace and same sign convention -> same matrix (up to fp noise)
    np.testing.assert_allclose(np.abs(p_svd), np.abs(p_gram), atol=1e-2)


def test_pca_randomized_matches_exact_subspace(rng):
    """Randomized range-finder PCA ("Panther" RRF + power iterations)
    recovers the exact SVD components on a low-rank-plus-noise sample —
    the exact path stays the pinned twin."""
    x = _correlated_data(rng, n=800)
    p_svd = np.asarray(PCAEstimator(4, "svd").fit(jnp.asarray(x)).pca_mat)
    p_rrf = np.asarray(
        PCAEstimator(4, "randomized").fit(jnp.asarray(x)).pca_mat
    )
    # same subspace, same sign convention -> same matrix (up to fp noise
    # in the trailing near-degenerate direction)
    np.testing.assert_allclose(np.abs(p_svd), np.abs(p_rrf), atol=2e-2)
    # projector distance pins the subspace itself, not just magnitudes
    proj = lambda p: p @ p.T  # noqa: E731
    assert np.linalg.norm(proj(p_svd) - proj(p_rrf)) < 1e-2
    # sign convention holds on the randomized path too
    for j in range(4):
        col = p_rrf[:, j]
        assert col[np.argmax(np.abs(col))] >= 0


def test_pca_knob_routes_auto_only(rng, monkeypatch):
    """KEYSTONE_PCA=randomized reroutes method='auto'; an explicit method
    argument still wins (the knob-precedence contract)."""
    x = _correlated_data(rng, n=800)
    monkeypatch.setenv("KEYSTONE_PCA", "randomized")
    p_auto = np.asarray(PCAEstimator(4).fit(jnp.asarray(x)).pca_mat)
    p_rrf = np.asarray(
        PCAEstimator(4, "randomized").fit(jnp.asarray(x)).pca_mat
    )
    np.testing.assert_array_equal(p_auto, p_rrf)  # auto took the RRF path
    p_svd_explicit = np.asarray(
        PCAEstimator(4, "svd").fit(jnp.asarray(x)).pca_mat
    )
    monkeypatch.delenv("KEYSTONE_PCA")
    p_svd = np.asarray(PCAEstimator(4, "svd").fit(jnp.asarray(x)).pca_mat)
    np.testing.assert_array_equal(p_svd_explicit, p_svd)  # knob ignored


def test_pca_randomized_masked_rows_ignored(rng):
    """Mask semantics match the exact path: padding rows do not move the
    components."""
    x = _correlated_data(rng, n=400)
    pad = np.concatenate([x, 1e3 * np.ones((64, x.shape[1]), np.float32)])
    mask = jnp.asarray(np.r_[np.ones(400), np.zeros(64)].astype(np.float32))
    p_plain = np.asarray(
        PCAEstimator(4, "randomized").fit(jnp.asarray(x)).pca_mat
    )
    p_masked = np.asarray(
        PCAEstimator(4, "randomized").fit(jnp.asarray(pad), mask=mask).pca_mat
    )
    np.testing.assert_allclose(np.abs(p_plain), np.abs(p_masked), atol=2e-2)


def test_pca_sign_convention(rng):
    x = _correlated_data(rng)
    p = np.asarray(PCAEstimator(4, "svd").fit(jnp.asarray(x)).pca_mat)
    for j in range(4):
        col = p[:, j]
        assert col[np.argmax(np.abs(col))] >= 0


def test_pca_distributed_fit(rng, devices):
    x = _correlated_data(rng, n=804)
    with use_mesh(make_mesh()):
        ds = distribute(jnp.asarray(x))
        p = PCAEstimator(4, "gram").fit(ds)
    out = np.asarray(p(jnp.asarray(x - x.mean(0))))
    cov = out.T @ out / (out.shape[0] - 1)
    off = cov - np.diag(np.diag(cov))
    assert np.abs(off).max() < 2e-2 * np.abs(np.diag(cov)).max()


def _planted_gmm(rng, n=2000):
    """Two well-separated planted Gaussians (EncEvalSuite.scala:42-64)."""
    means = np.array([[-5.0, 0.0, 2.0], [5.0, 3.0, -2.0]])
    stds = np.array([[1.0, 0.5, 0.8], [0.7, 1.2, 0.6]])
    labels = rng.integers(0, 2, size=n)
    x = means[labels] + stds[labels] * rng.normal(size=(n, 3))
    return x.astype(np.float32), means, stds


def test_gmm_recovers_planted_gaussians(rng):
    x, means, stds = _planted_gmm(rng)
    gmm = GaussianMixtureModelEstimator(k=2, num_iter=40).fit(jnp.asarray(x))
    got_means = np.asarray(gmm.means)
    # match centers up to permutation
    order = np.argsort(got_means[:, 0])
    np.testing.assert_allclose(got_means[order], means[np.argsort(means[:, 0])], atol=0.2)
    got_vars = np.asarray(gmm.variances)[order]
    np.testing.assert_allclose(
        got_vars, (stds**2)[np.argsort(means[:, 0])], rtol=0.3
    )
    np.testing.assert_allclose(np.asarray(gmm.weights).sum(), 1.0, atol=1e-5)


def test_gmm_posteriors_sum_to_one(rng):
    x, *_ = _planted_gmm(rng, n=100)
    gmm = GaussianMixtureModelEstimator(k=2, num_iter=10).fit(jnp.asarray(x))
    post = np.asarray(gmm(jnp.asarray(x)))
    np.testing.assert_allclose(post.sum(axis=1), 1.0, atol=1e-5)
    one = np.asarray(gmm.serve(jnp.asarray(x[0])))
    np.testing.assert_allclose(one, post[0], atol=1e-5)


def test_gmm_masked_fit_ignores_padding(rng):
    x, *_ = _planted_gmm(rng, n=500)
    xp = np.concatenate([x, np.full((12, 3), 1e4, np.float32)])
    mask = np.concatenate([np.ones(500, np.float32), np.zeros(12, np.float32)])
    g1 = GaussianMixtureModelEstimator(k=2, num_iter=20).fit(jnp.asarray(x))
    g2 = GaussianMixtureModelEstimator(k=2, num_iter=20).fit(
        jnp.asarray(xp), mask=jnp.asarray(mask)
    )
    np.testing.assert_allclose(
        np.sort(np.asarray(g1.means), 0), np.sort(np.asarray(g2.means), 0), atol=0.3
    )


def test_gmm_csv_roundtrip(tmp_path):
    means = np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])  # (dim=3, k=2) ref layout
    np.savetxt(tmp_path / "m.csv", means, delimiter=",")
    np.savetxt(tmp_path / "v.csv", np.ones((3, 2)), delimiter=",")
    np.savetxt(tmp_path / "w.csv", np.array([0.4, 0.6]), delimiter=",")
    gmm = GaussianMixtureModel.load(
        str(tmp_path / "m.csv"), str(tmp_path / "v.csv"), str(tmp_path / "w.csv")
    )
    assert gmm.means.shape == (2, 3)  # transposed to (k, dim)
    np.testing.assert_allclose(np.asarray(gmm.means)[0], [1.0, 3.0, 5.0])


def test_fisher_vector_matches_autodiff_gradient(rng):
    """FV is the Fisher-normalized gradient of the mean log-likelihood:
    verify against jax.grad — an oracle independent of the encoder code."""
    k, d, n = 3, 4, 50
    x = rng.normal(size=(n, d)).astype(np.float32)
    gmm = GaussianMixtureModelEstimator(k=k, num_iter=15, seed=1).fit(
        jnp.asarray(rng.normal(size=(200, d)).astype(np.float32) * 2)
    )
    fv = np.asarray(FisherVector(gmm=gmm).serve(jnp.asarray(x)))  # (d, 2k)
    assert fv.shape == (d, 2 * k)

    def mean_ll(means, variances):
        g = GaussianMixtureModel(means=means, variances=variances, weights=gmm.weights)
        ll = g.log_likelihoods(jnp.asarray(x))
        return jnp.mean(jax.scipy.special.logsumexp(ll, axis=1))

    g_mu, g_var = jax.grad(mean_ll, argnums=(0, 1))(gmm.means, gmm.variances)
    sigma = np.sqrt(np.asarray(gmm.variances))
    w = np.asarray(gmm.weights)
    # dL/dμ = Σ q (x-μ)/σ² / n  ->  FV_μ = σ·dL/dμ / √w
    expect_mu = np.asarray(g_mu) * sigma / np.sqrt(w)[:, None]
    np.testing.assert_allclose(fv[:, :k], expect_mu.T, atol=1e-4)
    # dL/dσ² = Σ q[(x-μ)²/σ⁴ - 1/σ²]/2n  ->  FV_σ = 2σ²·dL/dσ² / √(2w)
    expect_sig = 2.0 * np.asarray(g_var) * np.asarray(gmm.variances) / np.sqrt(2 * w)[:, None]
    np.testing.assert_allclose(fv[:, k:], expect_sig.T, atol=1e-4)


def test_fisher_vector_batch(rng):
    gmm = GaussianMixtureModelEstimator(k=2, num_iter=5).fit(
        jnp.asarray(rng.normal(size=(100, 4)).astype(np.float32))
    )
    descs = jnp.asarray(rng.normal(size=(3, 20, 4)).astype(np.float32))
    out = np.asarray(FisherVector(gmm=gmm)(descs))
    assert out.shape == (3, 4, 4)
    one = np.asarray(FisherVector(gmm=gmm).serve(descs[1]))
    np.testing.assert_allclose(out[1], one, atol=1e-5)


def test_fisher_slice_normalized_matches_dense_chain(rng, monkeypatch):
    """Concatenated FisherVectorSliceNormalized blocks must equal the dense
    FV → vectorize → L2 → Hellinger → L2 chain (the two L2 norms cancel into
    one per-image L1 scalar — see ops/images/fisher_vector.py)."""
    # pin the exact-f32 FV path: on TPU hosts the auto dispatch takes the
    # bf16 MXU form, whose rounding breaks this test's atol=1e-5 pin (the
    # cross-path agreement has its own test with bf16-sized tolerances)
    monkeypatch.setenv("KEYSTONE_FV_IMPL", "f32")
    from keystone_tpu.ops.images.fisher_vector import (
        fisher_l1_norms,
        make_fisher_block_nodes,
    )
    from keystone_tpu.pipelines._fisher import fisher_featurizer

    k, d = 4, 8
    gmm = GaussianMixtureModelEstimator(k=k, num_iter=10).fit(
        jnp.asarray(rng.normal(size=(200, d)).astype(np.float32))
    )
    descs = jnp.asarray(rng.normal(size=(6, 20, d)).astype(np.float32))
    dense = np.asarray(fisher_featurizer(gmm)(descs))  # (6, d*2k)

    l1 = fisher_l1_norms(descs, gmm, chunk=4)
    raw = {"descs": descs, "l1": l1}
    for row_chunk in (0, 4):  # one-shot and dynamic_slice-chunked (ragged n)
        blocks = make_fisher_block_nodes(gmm, block_size=2 * d, row_chunk=row_chunk)
        assert len(blocks) == k
        stream = np.concatenate(
            [np.asarray(b.apply_batch(raw)) for b in blocks], axis=1
        )
        assert stream.shape == dense.shape
        np.testing.assert_allclose(stream, dense, atol=1e-5)


def test_fisher_block_cache_groups_match_ungrouped(rng):
    """cache_blocks grouping must be a pure featurization refactor: grouped
    nodes (slices of one shared-posterior group pass) emit exactly what the
    per-block nodes emit, for every group size incl. ragged last groups."""
    from keystone_tpu.learning.block_linear import grouped_block_getter
    from keystone_tpu.ops.images.fisher_vector import (
        fisher_l1_norms,
        make_fisher_block_nodes,
    )

    k, d = 4, 8
    gmm = GaussianMixtureModelEstimator(k=k, num_iter=10).fit(
        jnp.asarray(rng.normal(size=(200, d)).astype(np.float32))
    )
    descs = jnp.asarray(rng.normal(size=(6, 20, d)).astype(np.float32))
    raw = {"descs": descs, "l1": fisher_l1_norms(descs, gmm, chunk=4)}
    plain = make_fisher_block_nodes(gmm, block_size=2 * d)
    ref = [np.asarray(b.apply_batch(raw)) for b in plain]
    for cache_blocks in (1, 2, 3, 4):
        nodes = make_fisher_block_nodes(
            gmm, block_size=2 * d, cache_blocks=cache_blocks
        )
        get, clear = grouped_block_getter(nodes, raw)
        for b in range(len(nodes)):
            np.testing.assert_allclose(
                np.asarray(get(b)), ref[b], atol=1e-6,
                err_msg=f"cache_blocks={cache_blocks} block={b}",
            )
        clear()
    # group metadata sanity: cache_blocks=1 and full-width groups disable
    # caching (group == block / group == everything is still one pass each)
    solo = make_fisher_block_nodes(gmm, block_size=2 * d, cache_blocks=1)
    assert all(n.cache_group is None for n in solo)
    grouped = make_fisher_block_nodes(gmm, block_size=2 * d, cache_blocks=2)
    assert grouped[0].cache_group == grouped[1].cache_group is not None
    assert grouped[2].cache_group == grouped[3].cache_group != grouped[0].cache_group


def test_grouped_getter_caches_once_per_group(rng):
    """The one-slot cache computes each group exactly once for in-order
    access and serves slices from it."""
    from keystone_tpu.learning.block_linear import grouped_block_getter

    calls = []

    class _Node:
        def __init__(self, i):
            self.i = i
            self.cache_group = ("g", i // 2)

        def group_node(self):
            node = self

            class _G:
                def apply_batch(self, raw):
                    calls.append(node.cache_group)
                    return raw["x"][:, (node.i // 2) * 4 : (node.i // 2) * 4 + 4]

            return _G()

        def slice_cached(self, out):
            lo = (self.i % 2) * 2
            return out[:, lo : lo + 2]

        def apply_batch(self, raw):
            raise AssertionError("grouped node must be served from the cache")

    raw = {"x": jnp.asarray(rng.normal(size=(5, 8)).astype(np.float32))}
    nodes = [_Node(i) for i in range(4)]
    get, clear = grouped_block_getter(nodes, raw)
    out = [np.asarray(get(b)) for b in range(4)]
    assert calls == [("g", 0), ("g", 1)]  # one featurization per group
    full = np.asarray(raw["x"])
    np.testing.assert_allclose(np.concatenate(out, axis=1), full)
    clear()


def test_fv_cols_batch_matches_per_image(rng, monkeypatch):
    """The flat-gemm batched FV (_fv_cols_batch, global affine params) must
    agree with the per-image centered path (_fv_cols) — same math, different
    schedule — across column ranges and descriptor scales."""
    # pin the exact-f32 FV path: the rtol=4e-4 below is an f32-schedule
    # bound; the TPU auto dispatch would take the bf16 MXU form and fail it
    monkeypatch.setenv("KEYSTONE_FV_IMPL", "f32")
    from keystone_tpu.ops.images.fisher_vector import _fv_cols, _fv_cols_batch

    k, d = 8, 16
    gmm = GaussianMixtureModelEstimator(k=k, num_iter=15).fit(
        jnp.asarray(rng.normal(size=(400, d)).astype(np.float32))
    )
    for scale in (1.0, 8.0):
        descs = jnp.asarray(
            scale * rng.normal(size=(5, 30, d)).astype(np.float32)
        )
        for lo, hi in ((0, 2 * k), (0, 4), (6, 12), (k, 2 * k)):
            ref = np.stack(
                [np.asarray(_fv_cols(D, gmm, lo, hi)) for D in descs]
            )
            got = np.asarray(_fv_cols_batch(descs, gmm, lo, hi))
            np.testing.assert_allclose(
                got, ref, rtol=4e-4, atol=4e-5,
                err_msg=f"scale={scale} cols=[{lo},{hi})",
            )


def test_fv_cols_batch_mxu_matches_f32(rng, monkeypatch):
    """The TPU MXU moment form (one [x|x²]@[A;B] posterior gemm + bf16
    moment einsums, _fv_cols_batch_mxu) must agree with the exact f32 path
    within bf16 rounding, across one-sided, straddling, coinciding and
    full column ranges. On CPU the f32 path is the default; the mxu form
    is what the flagship featurize runs on the chip, so this is the
    cross-path pin (the _conv1d_same impl-forcing pattern)."""
    from keystone_tpu.ops.images import fisher_vector as fv

    k, d = 8, 16
    gmm = GaussianMixtureModelEstimator(k=k, num_iter=15).fit(
        jnp.asarray(rng.normal(size=(400, d)).astype(np.float32))
    )
    descs = jnp.asarray(rng.normal(size=(6, 30, d)).astype(np.float32))
    for lo, hi in ((0, 2 * k), (0, 4), (6, 12), (k, 2 * k), (4, k + 4)):
        monkeypatch.setenv("KEYSTONE_FV_IMPL", "f32")
        ref = np.asarray(fv._fv_cols_batch(descs, gmm, lo, hi))
        monkeypatch.setenv("KEYSTONE_FV_IMPL", "mxu")
        got = np.asarray(fv._fv_cols_batch(descs, gmm, lo, hi))
        # bf16 inputs to the moment einsums: ~8-bit mantissa on the
        # contributions; f32 accumulation keeps the error at rounding
        # scale, not growth scale
        scale = np.abs(ref).max()
        np.testing.assert_allclose(
            got, ref, atol=2e-2 * scale, rtol=2e-2,
            err_msg=f"cols=[{lo},{hi})",
        )


def test_gmm_n_init_picks_best_likelihood(rng):
    """Best-of-n restarts must return the candidate with the highest data
    log-likelihood — and on a well-separated planted mixture that candidate
    recovers the truth at least as well as any single draw."""
    from keystone_tpu.learning.gmm import (
        GaussianMixtureModelEstimator,
        _mean_loglik,
    )

    k, d = 6, 8
    protos = 12.0 * rng.normal(size=(k, d)).astype(np.float32)
    x = jnp.asarray(
        (protos[rng.integers(0, k, 3000)]
         + rng.normal(size=(3000, d))).astype(np.float32)
    )
    w_row = jnp.ones((3000,), jnp.float32)
    best = GaussianMixtureModelEstimator(k, num_iter=15, n_init=4).fit(x)
    ll_best = float(_mean_loglik(
        x, w_row, best.means, best.variances, best.weights
    ))
    # the selected model's likelihood must be >= a single fit's
    single = GaussianMixtureModelEstimator(k, num_iter=15, n_init=1).fit(x)
    ll_single = float(_mean_loglik(
        x, w_row, single.means, single.variances, single.weights
    ))
    assert ll_best >= ll_single - 1e-3, (ll_best, ll_single)


def test_bucketed_streaming_blocks_match_dense_fit(rng):
    """BucketConcatNode blocks (per-bucket descriptor tensors with different
    per-image descriptor counts, row-concatenated per column block) must
    reproduce the dense featurizer exactly — raw, through the grouped cache,
    and through the full streaming weighted fit."""
    import jax.numpy as jnp

    from keystone_tpu.learning.block_linear import grouped_block_getter
    from keystone_tpu.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )
    from keystone_tpu.ops.images.fisher_vector import (
        fisher_l1_norms,
        make_bucketed_fisher_block_nodes,
    )
    from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels
    from keystone_tpu.pipelines._fisher import fisher_featurizer

    k, d = 4, 8
    gmm = GaussianMixtureModelEstimator(k=k, num_iter=10).fit(
        jnp.asarray(rng.normal(size=(300, d)).astype(np.float32))
    )
    d0 = jnp.asarray(rng.normal(size=(7, 12, d)).astype(np.float32))
    d1 = jnp.asarray(rng.normal(size=(5, 20, d)).astype(np.float32))
    dense = jnp.concatenate(
        [fisher_featurizer(gmm)(d0), fisher_featurizer(gmm)(d1)], axis=0
    )
    labels = np.array([0, 1, 2, 0, 1, 2, 0, 1, 2, 0, 1, 2], np.int32)
    ind = np.asarray(ClassLabelIndicatorsFromIntLabels(3)(jnp.asarray(labels)))
    bs = 2 * d  # 4 blocks over the 2k*d = 64 feature columns
    raw = {
        "b0": d0, "l1_b0": fisher_l1_norms(d0, gmm, chunk=4),
        "b1": d1, "l1_b1": fisher_l1_norms(d1, gmm, chunk=4),
    }
    nodes = make_bucketed_fisher_block_nodes(
        gmm, bs, [("b0", "l1_b0"), ("b1", "l1_b1")], cache_blocks=2
    )
    assert nodes[0].cache_group is not None  # grouping active across buckets
    feats = jnp.concatenate([n.apply_batch(raw) for n in nodes], axis=1)
    np.testing.assert_allclose(
        np.asarray(feats), np.asarray(dense), atol=5e-6
    )
    get, clear = grouped_block_getter(nodes, raw, None)
    cached = jnp.concatenate([get(b) for b in range(len(nodes))], axis=1)
    clear()
    np.testing.assert_allclose(
        np.asarray(cached), np.asarray(dense), atol=5e-6
    )
    est = BlockWeightedLeastSquaresEstimator(bs, 1, 0.05, 0.25)
    m_ref = est.fit(dense, jnp.asarray(ind))
    m_st = est.fit_streaming(nodes, raw, jnp.asarray(ind))
    np.testing.assert_allclose(
        np.asarray(m_st.w), np.asarray(m_ref.w), atol=1e-5
    )
