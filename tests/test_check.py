"""keystone-check (keystone_tpu/analysis/check.py + contracts.py): the
construction-time pipeline contract checker.

Covers: per-rule positive/negative fixtures (C1–C5), construction-site
line anchoring, the KEYSTONE_CHECK fail-fast wiring (the acceptance
scenario: a rank mismatch inserted between SIFT extraction and FV encode
is rejected at ``chain()`` time with both stages named — zero data, zero
compiles), pragma + baseline ratchet round trip, CLI exit codes/JSON, the
all-five-pipelines-check-clean invariant against the committed (empty)
``check_baseline.json``, and the checker-vs-planner propagation-parity
pin (``core/plan.py::pipeline_costs`` consumes the SAME pass).
"""

import inspect
import io
import json
import logging
import os
import sys
from contextlib import redirect_stdout

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import keystone_tpu._compat  # noqa: F401  (jax.enable_x64 shim)
from keystone_tpu.analysis import check as checkmod
from keystone_tpu.analysis.check import (
    CheckEntry,
    FitApply,
    PipelineContract,
    check_pipeline,
    fit_apply_findings,
    run_check,
)
from keystone_tpu.analysis.contracts import (
    ContractViolation,
    NodeContract,
    propagate_pipeline,
)
from keystone_tpu.analysis.engine import save_baseline
from keystone_tpu.core.pipeline import FunctionNode, Transformer, chain
from keystone_tpu.learning.gmm import GaussianMixtureModel
from keystone_tpu.learning.pca import BatchPCATransformer
from keystone_tpu.ops.images import SIFTExtractor
from keystone_tpu.ops.images.fisher_vector import FisherVector
from keystone_tpu.ops.util import MatrixVectorizer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
THIS_FILE = os.path.abspath(__file__)


def _gmm(k=4, d=16):
    return GaussianMixtureModel(
        means=jnp.zeros((k, d), jnp.float32),
        variances=jnp.ones((k, d), jnp.float32),
        weights=jnp.ones((k,), jnp.float32) / k,
    )


@pytest.fixture
def no_construction_check(monkeypatch):
    """Build deliberately-broken pipelines without tripping the fail-fast
    wiring (the unit tests exercise the checker on the finished graph)."""
    monkeypatch.setenv("KEYSTONE_CHECK", "0")


# ---------------------------------------------------------------------------
# C1: chain mismatch, named stages, construction-site anchoring
# ---------------------------------------------------------------------------

def test_c1_rank_mismatch_names_both_stages(no_construction_check):
    site_line = inspect.currentframe().f_lineno + 1
    pipe = chain(SIFTExtractor(), MatrixVectorizer(), FisherVector(gmm=_gmm()))
    findings = check_pipeline(PipelineContract(
        name="fx", pipe=pipe,
        sample=jax.ShapeDtypeStruct((2, 64, 64), jnp.float32),
    ))
    c1 = [f for f in findings if f.rule == "C1"]
    assert len(c1) == 1, findings
    # BOTH stages named: the producer and the rejecting consumer
    assert "MatrixVectorizer" in c1[0].message
    assert "FisherVector" in c1[0].message
    assert "rank" in c1[0].message
    # anchored at the chain() construction site in THIS file
    assert c1[0].path == THIS_FILE
    assert c1[0].line == site_line
    # line-drift-immune fingerprint names both stages too
    assert "MatrixVectorizer>FisherVector" in c1[0].fingerprint


def test_c1_dim_mismatch_flagged_and_good_chain_clean(no_construction_check):
    # wrong PCA width into FV (dim-kind mismatch: definite under a REAL
    # sample spec)
    bad = chain(
        SIFTExtractor(),
        BatchPCATransformer(pca_mat=jnp.zeros((128, 8), jnp.float32)),
        FisherVector(gmm=_gmm(d=16)),
    )
    findings = check_pipeline(PipelineContract(
        name="fx", pipe=bad,
        sample=jax.ShapeDtypeStruct((2, 64, 64), jnp.float32),
    ))
    assert [f.rule for f in findings] == ["C1"]
    assert "last dim 16" in findings[0].message
    good = chain(
        SIFTExtractor(),
        BatchPCATransformer(pca_mat=jnp.zeros((128, 16), jnp.float32)),
        FisherVector(gmm=_gmm(d=16)),
    )
    assert check_pipeline(PipelineContract(
        name="fx", pipe=good,
        sample=jax.ShapeDtypeStruct((2, 64, 64), jnp.float32),
    )) == []


def test_c1_blocked_downstream_reported_once(no_construction_check):
    """A failure is reported at its source; stages downstream of it are
    blocked, not separately flagged."""
    pipe = chain(
        SIFTExtractor(), MatrixVectorizer(), FisherVector(gmm=_gmm()),
        MatrixVectorizer(),
    )
    findings = check_pipeline(PipelineContract(
        name="fx", pipe=pipe,
        sample=jax.ShapeDtypeStruct((2, 64, 64), jnp.float32),
    ))
    assert len([f for f in findings if f.rule == "C1"]) == 1


# ---------------------------------------------------------------------------
# The fail-fast wiring (the acceptance scenario)
# ---------------------------------------------------------------------------

def test_mischained_pipeline_rejected_at_construction(monkeypatch, caplog):
    """THE acceptance pin: a rank mismatch inserted between SIFT
    extraction and FV encode raises at ``chain()`` time under the default
    KEYSTONE_CHECK=auto — both stages named, zero compiles (the abstract
    trace never lowers), zero data loaded (only zero-weight nodes
    exist)."""
    monkeypatch.delenv("KEYSTONE_CHECK", raising=False)
    jax.config.update("jax_log_compiles", True)
    try:
        with caplog.at_level(logging.DEBUG, logger="jax"):
            with pytest.raises(ContractViolation) as e:
                chain(
                    SIFTExtractor(), MatrixVectorizer(),
                    FisherVector(gmm=_gmm()),
                )
    finally:
        jax.config.update("jax_log_compiles", False)
    msg = str(e.value)
    assert "MatrixVectorizer" in msg and "FisherVector" in msg
    assert e.value.findings[0].rule == "C1"
    # the construction site is THIS file (the finding anchor)
    assert e.value.findings[0].path == THIS_FILE
    # zero compiles: nothing was lowered to the backend
    compiled = [r for r in caplog.records if "compil" in r.message.lower()]
    assert compiled == [], compiled


def test_check_off_and_good_chains_unaffected(monkeypatch):
    monkeypatch.setenv("KEYSTONE_CHECK", "0")
    pipe = chain(SIFTExtractor(), MatrixVectorizer(), FisherVector(gmm=_gmm()))
    assert pipe is not None  # no raise with checking off
    monkeypatch.delenv("KEYSTONE_CHECK")
    # a well-typed chain constructs fine under auto
    good = chain(
        SIFTExtractor(),
        BatchPCATransformer(pca_mat=jnp.zeros((128, 16), jnp.float32)),
        FisherVector(gmm=_gmm(d=16)),
    )
    assert good is not None


def test_strict_mode_raises_on_template_dim_mismatch(monkeypatch):
    """auto tolerates exact-dim mismatches at construction (the template's
    absolute dims are made up); KEYSTONE_CHECK=1 is the strict opt-in."""
    monkeypatch.setenv("KEYSTONE_CHECK", "auto")
    pipe = chain(
        SIFTExtractor(),
        BatchPCATransformer(pca_mat=jnp.zeros((64, 8), jnp.float32)),
    )  # SIFT descriptors are 128-wide: a dim mismatch, not rank
    assert pipe is not None
    monkeypatch.setenv("KEYSTONE_CHECK", "1")
    with pytest.raises(ContractViolation):
        chain(
            SIFTExtractor(),
            BatchPCATransformer(pca_mat=jnp.zeros((64, 8), jnp.float32)),
        )


# ---------------------------------------------------------------------------
# C2: declared input-spec conflicts with the committed spec
# ---------------------------------------------------------------------------

class _RowShardedOnly(Transformer):
    """Test node requiring row-sharded P('data', None) input."""

    def __contract__(self):
        from jax.sharding import PartitionSpec as P

        return NodeContract(in_spec=P("data", None))

    def apply(self, x):
        return x


def test_c2_spec_conflict_flagged_and_match_clean(no_construction_check):
    from jax.sharding import PartitionSpec as P

    pipe = chain(_RowShardedOnly())
    sample = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    bad = check_pipeline(PipelineContract(
        name="fx", pipe=pipe, sample=sample, spec=P(None, "model"),
    ))
    assert [f.rule for f in bad] == ["C2"]
    assert "all-gather" in bad[0].message
    ok = check_pipeline(PipelineContract(
        name="fx", pipe=pipe, sample=sample, spec=P("data", None),
    ))
    assert ok == []
    # trailing Nones are implicit (JAX semantics): P('data') satisfies a
    # declared P('data', None) requirement — no false C2
    assert check_pipeline(PipelineContract(
        name="fx", pipe=pipe, sample=sample, spec=P("data"),
    )) == []
    # ...and a LONGER committed spec carried through a rank-dropping stage
    # still matches on the named axes
    assert check_pipeline(PipelineContract(
        name="fx", pipe=pipe, sample=sample,
        spec=P("data", None, None, None),
    )) == []
    # an uncommitted input (spec=None) cannot conflict
    assert check_pipeline(PipelineContract(
        name="fx", pipe=pipe, sample=sample,
    )) == []


def test_c2_spec_propagates_through_row_preserving_stages(
    no_construction_check,
):
    """The committed spec flows through row-preserving stages and reaches
    a deep requirement; a row-count-changing stage drops it (no false
    positive past a reduction)."""
    from jax.sharding import PartitionSpec as P

    double = Transformer.from_fn(lambda x: x * 2.0, name="double")
    pipe = chain(double, _RowShardedOnly())
    sample = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    bad = check_pipeline(PipelineContract(
        name="fx", pipe=pipe, sample=sample, spec=P(None, "model"),
    ))
    assert [f.rule for f in bad] == ["C2"]

    class _Pool(Transformer):
        def apply_batch(self, xs):
            return xs.sum(axis=0, keepdims=True)

        def apply(self, x):
            return x

    pooled = chain(_Pool(), _RowShardedOnly())
    assert check_pipeline(PipelineContract(
        name="fx", pipe=pooled, sample=sample, spec=P(None, "model"),
    )) == []


# ---------------------------------------------------------------------------
# C3: estimator fit/apply asymmetry
# ---------------------------------------------------------------------------

def test_c3_fit_apply_asymmetry():
    pairs = [FitApply(
        "solver",
        fit_aval=jax.ShapeDtypeStruct((64, 1024), jnp.float32),
        apply_aval=jax.ShapeDtypeStruct((32, 512), jnp.float32),
    )]
    findings = fit_apply_findings(pairs, "fx")
    assert [f.rule for f in findings] == ["C3"]
    assert "solver" in findings[0].message
    assert "(1024,)" in findings[0].message and "(512,)" in findings[0].message
    # dtype asymmetry is C3 too
    dt = fit_apply_findings([FitApply(
        "solver",
        fit_aval=jax.ShapeDtypeStruct((64, 512), jnp.float32),
        apply_aval=jax.ShapeDtypeStruct((32, 512), jnp.bfloat16),
    )], "fx")
    assert [f.rule for f in dt] == ["C3"]
    # symmetric layouts (any leading batch) are clean
    assert fit_apply_findings([FitApply(
        "solver",
        fit_aval=jax.ShapeDtypeStruct((64, 512), jnp.float32),
        apply_aval=jax.ShapeDtypeStruct((7, 512), jnp.float32),
    )], "fx") == []


# ---------------------------------------------------------------------------
# C4: pre-dispatch f64 leaks
# ---------------------------------------------------------------------------

class _Widens(Transformer):
    def apply(self, x):
        return x.astype(jnp.float64)


class _WidensAllowed(_Widens):
    def __contract__(self):
        return NodeContract(allow_f64=True)


def test_c4_f64_leak_fires_pre_dispatch(no_construction_check):
    sample = jax.ShapeDtypeStruct((4, 8), jnp.float32)
    with jax.enable_x64():
        bad = check_pipeline(PipelineContract(
            name="fx", pipe=chain(_Widens()), sample=sample,
        ))
        allowed = check_pipeline(PipelineContract(
            name="fx", pipe=chain(_WidensAllowed()), sample=sample,
        ))
    assert [f.rule for f in bad] == ["C4"]
    assert "float64" in bad[0].message
    assert allowed == []
    # one leak = ONE finding, at the stage that INTRODUCES the wide dtype
    # — downstream carriers are not re-flagged (report-once-at-source)
    carry = Transformer.from_fn(lambda x: x * 1, name="carry")
    with jax.enable_x64():
        flood = check_pipeline(PipelineContract(
            name="fx", pipe=chain(_Widens(), carry, carry),
            sample=jax.ShapeDtypeStruct((4, 8), jnp.float32),
        ))
    assert len(flood) == 1, [f.message for f in flood]
    assert "_Widens" in flood[0].message
    # with x64 off the widening never happens — clean (the dtype the
    # dispatch would actually see)
    assert check_pipeline(PipelineContract(
        name="fx", pipe=chain(_Widens()), sample=sample,
    )) == []


# ---------------------------------------------------------------------------
# C5: un-evaluable stages (and the planner parity)
# ---------------------------------------------------------------------------

class _DataDependent(FunctionNode):
    """Host node whose output shape depends on VALUES — abstractly
    un-evaluable, and nobody declared a contract."""

    jittable = False

    def apply_batch(self, xs):
        return xs[np.asarray(xs[:, 0]) > 0]


def test_c5_unevaluable_stage_flagged_declared_host_clean(
    no_construction_check,
):
    from keystone_tpu.ops.stats import ColumnSampler

    sample = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    bad = check_pipeline(PipelineContract(
        name="fx", pipe=chain(_DataDependent()), sample=sample,
    ))
    assert [f.rule for f in bad] == ["C5"]
    assert "_DataDependent" in bad[0].message
    assert "bounded=False" in bad[0].message
    # a host node WITH a declared contract (ColumnSampler) is evaluable
    descs = jax.ShapeDtypeStruct((4, 6, 8), jnp.float32)
    recs = propagate_pipeline(chain(ColumnSampler(num_samples=10)), descs)
    assert recs[0].issue is None
    assert tuple(recs[0].out_aval.shape) == (10, 8)
    assert check_pipeline(PipelineContract(
        name="fx", pipe=chain(ColumnSampler(num_samples=10)), sample=descs,
    )) == []


def test_checker_planner_propagation_parity(no_construction_check):
    """THE parity pin: ``pipeline_costs`` consumes the checker's
    propagation pass, so for every stage the cost table's abstract output
    bytes equal the checker's, and an un-evaluable stage is EXACTLY the
    planner's unbounded stage (plan.bounded=False <-> a C5 finding)."""
    from keystone_tpu.core import plan
    from keystone_tpu.core.plan import _tree_bytes

    pipe = chain(
        SIFTExtractor(),
        BatchPCATransformer(pca_mat=jnp.zeros((128, 16), jnp.float32)),
        _DataDependent(),
        MatrixVectorizer(),
    )
    sample = jax.ShapeDtypeStruct((2, 64, 64), jnp.float32)
    records = propagate_pipeline(pipe, sample)
    costs = plan.pipeline_costs(pipe, sample, mode="estimate",
                                with_flops=False)
    assert len(costs) == len(records)
    for cost, rec in zip(costs, records):
        if rec.out_aval is None:
            assert cost.peak_hbm_bytes is None
            assert cost.out_bytes == 0
        else:
            assert cost.out_bytes == _tree_bytes(rec.out_aval)
    # the un-evaluable stage degrades the plan AND is the C5 finding
    p = plan._decide(costs, "estimate", None, [], {}, "fp")
    assert p.bounded is False
    findings = check_pipeline(PipelineContract(
        name="fx", pipe=pipe, sample=sample,
    ))
    assert [f.rule for f in findings] == ["C5"]


# ---------------------------------------------------------------------------
# Pragma + baseline ratchet round trip
# ---------------------------------------------------------------------------

_FIXTURE_SRC = """\
import jax.numpy as jnp

from keystone_tpu.core.pipeline import chain
from keystone_tpu.learning.gmm import GaussianMixtureModel
from keystone_tpu.ops.images import SIFTExtractor
from keystone_tpu.ops.images.fisher_vector import FisherVector
from keystone_tpu.ops.util import MatrixVectorizer

gmm = GaussianMixtureModel(
    means=jnp.zeros((4, 16), jnp.float32),
    variances=jnp.ones((4, 16), jnp.float32),
    weights=jnp.ones((4,), jnp.float32) / 4,
)
pipe = chain(SIFTExtractor(), MatrixVectorizer(), FisherVector(gmm=gmm)){pragma}
"""


def _fixture_registry(tmp_path, pragma=""):
    """Exec a mis-chained fixture module from tmp_path (construction sites
    anchor THERE) and wrap it as a one-target check registry."""
    import jax as _jax

    path = tmp_path / "fixture_pipe.py"
    src = _FIXTURE_SRC.format(pragma=pragma)
    path.write_text(src)
    ns: dict = {}
    exec(compile(src, str(path), "exec"), ns)
    sample = _jax.ShapeDtypeStruct((2, 64, 64), jnp.float32)
    entry = CheckEntry(
        name="fx",
        builder=lambda: [PipelineContract(
            name="fx", pipe=ns["pipe"], sample=sample,
        )],
        path="fixture_pipe.py", line=1, doc="",
    )
    return {"fx": entry}


def test_pragma_suppresses_at_construction_site(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_CHECK", "0")
    reg = _fixture_registry(
        tmp_path, pragma="  # lint: disable=C1 (fixture debt)"
    )
    result = run_check(registry=reg, root=str(tmp_path))
    assert result.findings == []
    assert result.suppressed == 1
    assert result.stale_pragmas == []
    # the same pragma for a rule that never fires there IS stale
    reg2 = _fixture_registry(
        tmp_path, pragma="  # lint: disable=C4 (wrong rule)"
    )
    result2 = run_check(registry=reg2, root=str(tmp_path))
    assert [f.rule for f in result2.findings] == ["C1"]
    assert result2.suppressed == 0
    assert [(l, r) for _, l, r in result2.stale_pragmas]


def test_stale_pragma_reported_after_finding_fixed(tmp_path, monkeypatch):
    """The steady-state stale case: a C-pragma at a construction site whose
    mis-composition got FIXED must still be reported (anchor files are
    scanned for pragmas whether or not they produced findings)."""
    monkeypatch.setenv("KEYSTONE_CHECK", "0")
    import jax as _jax

    src = """\
import jax.numpy as jnp

from keystone_tpu.core.pipeline import chain
from keystone_tpu.learning.gmm import GaussianMixtureModel
from keystone_tpu.learning.pca import BatchPCATransformer
from keystone_tpu.ops.images import SIFTExtractor
from keystone_tpu.ops.images.fisher_vector import FisherVector

gmm = GaussianMixtureModel(
    means=jnp.zeros((4, 16), jnp.float32),
    variances=jnp.ones((4, 16), jnp.float32),
    weights=jnp.ones((4,), jnp.float32) / 4,
)
pipe = chain(
    SIFTExtractor(),
    BatchPCATransformer(pca_mat=jnp.zeros((128, 16), jnp.float32)),
    FisherVector(gmm=gmm),
)  # lint: disable=C1 (was a mis-chain once; fixed since)
"""
    path = tmp_path / "fixture_fixed.py"
    path.write_text(src)
    ns: dict = {}
    exec(compile(src, str(path), "exec"), ns)
    reg = {"fx": CheckEntry(
        name="fx",
        builder=lambda: [PipelineContract(
            name="fx", pipe=ns["pipe"],
            sample=_jax.ShapeDtypeStruct((2, 64, 64), jnp.float32),
        )],
        path="fixture_fixed.py", line=1, doc="",
    )}
    result = run_check(registry=reg, root=str(tmp_path))
    assert result.findings == [] and result.suppressed == 0
    assert len(result.stale_pragmas) == 1, result.stale_pragmas
    assert result.stale_pragmas[0][2] == "C1"


def test_baseline_ratchet_round_trip(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_CHECK", "0")
    reg = _fixture_registry(tmp_path)
    baseline = tmp_path / "check_baseline.json"
    first = run_check(registry=reg, root=str(tmp_path))
    assert [f.rule for f in first.findings] == ["C1"]
    save_baseline(str(baseline), first.findings, tool="check")
    # baselined now: known debt, nothing new, line drift immune
    again = run_check(registry=reg, root=str(tmp_path),
                      baseline_path=str(baseline))
    assert again.findings == []
    assert [f.rule for f in again.baselined] == ["C1"]
    # fixing the debt surfaces the fingerprint as stale (ratchet down)
    fixed = _fixture_registry(
        tmp_path, pragma="  # lint: disable=C1 (fixture debt)"
    )
    stale = run_check(registry=fixed, root=str(tmp_path),
                      baseline_path=str(baseline))
    assert stale.findings == [] and stale.baselined == []
    assert len(stale.stale) == 1


# ---------------------------------------------------------------------------
# CLI + the shipped-pipelines invariant
# ---------------------------------------------------------------------------

def _cli(argv):
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = checkmod.main(argv)
    return rc, buf.getvalue()


def test_cli_json_exit_codes_and_list():
    rc, out = _cli(["--format", "json", "--root", REPO_ROOT])
    assert rc == 0, out
    payload = json.loads(out)
    assert payload["new"] == []
    assert payload["errors"] == []
    assert set(payload["targets"]) >= {
        "mnist", "cifar", "timit", "voc", "imagenet"
    }
    rc, out = _cli(["--list"])
    assert rc == 0 and "imagenet" in out
    rc, _ = _cli(["--target", "nosuch", "--root", REPO_ROOT])
    assert rc == 2


def test_cli_update_baseline_prunes_fixed_debt(tmp_path, monkeypatch):
    """--update-baseline must prune in-scope stale fingerprints (the
    fingerprint embeds the CONTRACT name, not the registry target name)
    and must not inflate persisting counts across repeated updates."""
    monkeypatch.setenv("KEYSTONE_CHECK", "0")
    baseline = tmp_path / "check_baseline.json"
    reg = _fixture_registry(tmp_path)
    monkeypatch.setattr(checkmod, "CHECK_TARGETS", reg)
    rc, _ = _cli(["--update-baseline", "--root", str(tmp_path),
                  "--baseline", str(baseline)])
    assert rc == 0
    first = json.load(open(baseline))["findings"]
    assert len(first) == 1 and list(first.values()) == [1]
    # a second update of the SAME debt keeps the count at 1 (no
    # keep+re-add double counting)
    rc, _ = _cli(["--update-baseline", "--root", str(tmp_path),
                  "--baseline", str(baseline)])
    assert rc == 0
    assert json.load(open(baseline))["findings"] == first
    # fix the mis-chain -> the fingerprint is IN scope and prunes
    fixed = _fixture_registry(
        tmp_path, pragma="  # lint: disable=C1 (fixture debt)"
    )
    monkeypatch.setattr(checkmod, "CHECK_TARGETS", fixed)
    rc, _ = _cli(["--update-baseline", "--root", str(tmp_path),
                  "--baseline", str(baseline)])
    assert rc == 0
    assert json.load(open(baseline))["findings"] == {}


def test_cli_exits_one_on_new_findings(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_CHECK", "0")
    reg = _fixture_registry(tmp_path)
    monkeypatch.setattr(checkmod, "CHECK_TARGETS", reg)
    rc, out = _cli(["--no-baseline", "--format", "json",
                    "--root", str(tmp_path)])
    assert rc == 1
    payload = json.loads(out)
    assert payload["new"][0]["rule"] == "C1"


def test_all_five_pipelines_check_clean_against_committed_baseline():
    """The registry-acceptance + hygiene invariant: every shipped pipeline
    has a registered contract target, and the whole registry checks clean
    against the committed (EMPTY) check_baseline.json — the checker ships
    with zero debt."""
    baseline_path = os.path.join(REPO_ROOT, "check_baseline.json")
    assert os.path.exists(baseline_path)
    committed = json.load(open(baseline_path))
    assert committed["findings"] == {}  # committed EMPTY: zero debt
    assert set(checkmod.CHECK_TARGETS) >= {
        "mnist", "cifar", "timit", "voc", "imagenet"
    }
    result = run_check(root=REPO_ROOT, baseline_path=baseline_path)
    assert result.errors == []
    assert result.findings == [], [f.format() for f in result.findings]
    assert result.files == len(result.targets)
