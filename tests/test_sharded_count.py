"""Mesh-sharded keyed aggregation (round-4 VERDICT item 2).

The cluster-wide ``reduceByKey``: per-shard sort+segment combine, compacted
per-shard tables all-gathered over the mesh, one merge reduce — exactness
pinned against the single-device path, capacity overflow pinned to report
(never undercount), and the comm pattern pinned in HLO: the only all-gather
is of the COMPACTED tables (at the capacity budget), never of the raw
window keys. Reference: ``ngrams.scala:150-183``,
``StupidBackoff.scala:25-57,156-159``; SURVEY §2.13 calls keyed shuffle
"the one genuinely non-dense pattern".
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from keystone_tpu.ops.nlp.device_count import (
    count_ngrams_device,
    count_ngrams_sharded,
    sum_by_key,
    sum_by_key_sharded,
    unigram_table_device,
    unigram_table_sharded,
)


@pytest.fixture()
def mesh():
    return Mesh(np.array(jax.devices()[:8]), ("data",))


def _trimmed(uniq, totals, n):
    n = int(n)
    return np.asarray(uniq[:n]), np.asarray(totals[:n])


def test_sum_by_key_sharded_matches_single_device(mesh, rng):
    n = 8 * 512
    keys = jnp.asarray(rng.integers(0, 1000, n), jnp.int32)
    valid = jnp.asarray(rng.random(n) < 0.9)
    w = jnp.asarray(rng.integers(1, 5, n), jnp.float32)

    for weights in (None, w):
        uniq_s, tot_s, nu_s, over = sum_by_key_sharded(
            keys, valid, mesh=mesh, weights=weights
        )
        uniq_1, tot_1, nu_1 = sum_by_key(keys, valid, weights)
        assert int(over) == 0
        ks, ts = _trimmed(uniq_s, tot_s, nu_s)
        k1, t1 = _trimmed(uniq_1, tot_1, nu_1)
        np.testing.assert_array_equal(ks, k1)
        # integer-valued f32 sums are exact -> bitwise equality
        np.testing.assert_array_equal(ts, t1)


def test_sum_by_key_sharded_capacity_overflow_reported(mesh, rng):
    n = 8 * 128
    # every key distinct -> per-shard distinct count = 128 > capacity 64
    keys = jnp.asarray(np.arange(n), jnp.int32)
    valid = jnp.ones((n,), bool)
    *_, over = sum_by_key_sharded(keys, valid, mesh=mesh, capacity=64)
    assert int(over) == 1
    # ample capacity: exact and unflagged
    uniq, tot, nu, over = sum_by_key_sharded(
        keys, valid, mesh=mesh, capacity=128
    )
    assert int(over) == 0
    assert int(nu) == n
    np.testing.assert_array_equal(np.asarray(uniq[:n]), np.arange(n))


def _corpus(rng, d=64, L=24, vocab=50):
    ids = rng.integers(0, vocab, (d, L)).astype(np.int32)
    lengths = rng.integers(3, L + 1, d).astype(np.int32)
    # sprinkle OOV
    ids[rng.random((d, L)) < 0.05] = -1
    return jnp.asarray(ids), jnp.asarray(lengths)


def test_count_ngrams_sharded_matches_single_device(mesh, rng):
    ids, lengths = _corpus(rng)
    for order, word_bits in ((2, 6), (3, 6)):
        uniq_s, tot_s, nu_s, over = count_ngrams_sharded(
            ids, lengths, order, word_bits, mesh=mesh
        )
        uniq_1, tot_1, nu_1 = count_ngrams_device(ids, lengths, order, word_bits)
        assert int(over) == 0
        ks, ts = _trimmed(uniq_s, tot_s, nu_s)
        k1, t1 = _trimmed(uniq_1, tot_1, nu_1)
        np.testing.assert_array_equal(ks, k1)
        np.testing.assert_array_equal(ts, t1)


def test_unigram_table_sharded_matches_single_device(mesh, rng):
    ids, lengths = _corpus(rng)
    got = unigram_table_sharded(ids, 50, lengths, mesh=mesh)
    want = unigram_table_device(ids, 50, lengths)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_stupid_backoff_fit_device_sharded_matches(mesh, rng):
    """fit_device(mesh=...) produces the same trimmed model tables as the
    single-device fit — device ≡ host pinned transitively through the
    existing fit_device ≡ fit_encoded pin in test_nlp.py."""
    from keystone_tpu.ops.nlp.stupid_backoff import StupidBackoffEstimator

    ids, lengths = _corpus(rng, d=60, L=20, vocab=40)  # 60: exercises padding
    est = StupidBackoffEstimator(unigram_counts={})
    m1 = est.fit_device(ids, lengths, orders=(2, 3), vocab_size=40)
    ms = est.fit_device(
        ids, lengths, orders=(2, 3), vocab_size=40, mesh=mesh
    )
    assert ms.table_sizes == m1.table_sizes
    for a, b in zip(ms.table_keys, m1.table_keys):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(ms.table_counts, m1.table_counts):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(
        np.asarray(ms.unigram_counts), np.asarray(m1.unigram_counts)
    )
    # undersized capacity must raise, not undercount
    with pytest.raises(RuntimeError, match="undersizes"):
        est.fit_device(
            ids, lengths, orders=(2, 3), vocab_size=40, mesh=mesh,
            shard_capacity=4,
        )


def test_newsgroups_featurizer_sharded_matches(mesh, rng):
    """DeviceCommonSparseFeatures with a mesh fits the identical vocabulary
    table (integer totals -> bitwise-equal merge -> identical top-k)."""
    from keystone_tpu.ops.nlp.device_text import DeviceCommonSparseFeatures

    ids, lengths = _corpus(rng, d=48, L=16, vocab=30)
    kw = dict(base=31, orders=(1, 2), num_features=64, weight="binary")
    v1 = DeviceCommonSparseFeatures(**kw).fit(ids, lengths)
    vs = DeviceCommonSparseFeatures(**kw, mesh=mesh).fit(ids, lengths)
    np.testing.assert_array_equal(
        np.asarray(vs.keys_sorted), np.asarray(v1.keys_sorted)
    )
    np.testing.assert_array_equal(
        np.asarray(vs.feat_of_pos), np.asarray(v1.feat_of_pos)
    )
    # and the vectorized output rides the same table
    b1 = v1.apply_encoded(ids, lengths)
    bs = vs.apply_encoded(ids, lengths)
    np.testing.assert_array_equal(np.asarray(bs.indices), np.asarray(b1.indices))
    np.testing.assert_array_equal(np.asarray(bs.values), np.asarray(b1.values))


def _all_gather_sizes(hlo_text: str):
    """Total element count of every all-gather result in the HLO."""
    sizes = []
    for m in re.finditer(
        r"=\s+(?:\([^)]*\)\s+)?[a-z0-9]+\[([\d,]*)\][^=]*?all-gather", hlo_text
    ):
        dims = [int(x) for x in m.group(1).split(",") if x]
        n = 1
        for x in dims:
            n *= x
        sizes.append(n)
    return sizes


def test_sharded_count_hlo_gathers_compacted_tables_only(mesh):
    """Comm-pattern pin: with capacity C < n_local the program's all-gathers
    move P*C-element compacted tables; nothing at the raw window size
    (P*n_local) is ever gathered, and no all-to-all appears (the exchange
    is the compacted all-gather by design — see device_count.py)."""
    n = 8 * 1024
    cap = 256  # < n_local = 1024
    keys = jnp.zeros((n,), jnp.int32)
    valid = jnp.ones((n,), bool)

    fn = jax.jit(
        lambda k, v: sum_by_key_sharded(k, v, mesh=mesh, capacity=cap)
    )
    txt = fn.lower(
        jax.device_put(keys, NamedSharding(mesh, P("data"))),
        jax.device_put(valid, NamedSharding(mesh, P("data"))),
    ).compile().as_text()

    sizes = _all_gather_sizes(txt)
    assert sizes, "expected all-gathers of the compacted tables"
    assert all(s <= 8 * cap for s in sizes), sizes  # never the raw 8*1024
    assert "all-to-all" not in txt
