"""CLI launcher tests: `bin/run-pipeline` / `keystone_tpu.cli`.

Reference surface: ``bin/run-pipeline.sh`` (class + flags dispatch,
``run-pipeline.sh:9-28``); the cluster-launch flags map to
``jax.distributed.initialize`` (multi-process execution itself is covered
by ``tests/test_multihost.py``).
"""

import io
import sys

import pytest

from keystone_tpu import cli


def _run_capture(argv):
    out, err = io.StringIO(), io.StringIO()
    old = sys.stdout, sys.stderr
    sys.stdout, sys.stderr = out, err
    try:
        rc = cli.main(argv)
    finally:
        sys.stdout, sys.stderr = old
    return rc, out.getvalue(), err.getvalue()


def test_help_lists_every_pipeline():
    rc, out, _ = _run_capture(["--help"])
    assert rc == 0
    for name in cli.PIPELINES:
        assert name in out


def test_every_pipeline_parses_help():
    """Each registered pipeline must import and expose a parseable config
    (argparse --help exits 0) — catches registry typos and import rot."""
    import importlib

    for name, module in cli.PIPELINES.items():
        mod = importlib.import_module(module)
        with pytest.raises(SystemExit) as e:
            _run_capture_help = io.StringIO()
            old = sys.stdout
            sys.stdout = _run_capture_help
            try:
                mod.main(["--help"])
            finally:
                sys.stdout = old
        assert e.value.code == 0, name


def test_empty_and_unknown_names_error_cleanly():
    rc, out, _ = _run_capture([])
    assert rc == 2
    # unknown name reports an error instead of raising
    rc, _, err = _run_capture(["NoSuchPipeline"])
    assert rc == 2 and "unknown pipeline" in err


def test_case_insensitive_name_resolves(monkeypatch):
    import importlib

    called = {}
    mod = importlib.import_module(cli.PIPELINES["MnistRandomFFT"])
    monkeypatch.setattr(mod, "main", lambda rest: called.setdefault("argv", rest))
    rc, _, _ = _run_capture(["MNISTRANDOMFFT"])
    assert rc == 0 and called["argv"] == []


def test_partial_distributed_flags_refused():
    rc, _, err = _run_capture(
        ["--num-processes", "2", "MnistRandomFFT"]
    )
    assert rc == 2
    assert "require --coordinator" in err


def test_mesh_model_must_divide_devices():
    rc, _, err = _run_capture(["--mesh-model", "7", "MnistRandomFFT"])
    assert rc == 2
    assert "does not divide" in err


def test_snake_case_resolves(monkeypatch):
    """mnist_random_fft resolves to MnistRandomFFT and runs its main."""
    import importlib

    called = {}
    mod = importlib.import_module(cli.PIPELINES["MnistRandomFFT"])
    monkeypatch.setattr(mod, "main", lambda rest: called.setdefault("argv", rest))
    rc, _, _ = _run_capture(["mnist_random_fft", "--num-ffts", "2"])
    assert rc == 0
    assert called["argv"] == ["--num-ffts", "2"]
