"""CLI launcher tests: `bin/run-pipeline` / `keystone_tpu.cli`.

Reference surface: ``bin/run-pipeline.sh`` (class + flags dispatch,
``run-pipeline.sh:9-28``); the cluster-launch flags map to
``jax.distributed.initialize`` (multi-process execution itself is covered
by ``tests/test_multihost.py``).
"""

import io
import sys

import pytest

from keystone_tpu import cli


def _run_capture(argv):
    out, err = io.StringIO(), io.StringIO()
    old = sys.stdout, sys.stderr
    sys.stdout, sys.stderr = out, err
    try:
        rc = cli.main(argv)
    finally:
        sys.stdout, sys.stderr = old
    return rc, out.getvalue(), err.getvalue()


def test_help_lists_every_pipeline():
    rc, out, _ = _run_capture(["--help"])
    assert rc == 0
    for name in cli.PIPELINES:
        assert name in out


def test_every_pipeline_parses_help():
    """Each registered pipeline must import and expose a parseable config
    (argparse --help exits 0) — catches registry typos and import rot."""
    import importlib

    for name, module in cli.PIPELINES.items():
        mod = importlib.import_module(module)
        with pytest.raises(SystemExit) as e:
            _run_capture_help = io.StringIO()
            old = sys.stdout
            sys.stdout = _run_capture_help
            try:
                mod.main(["--help"])
            finally:
                sys.stdout = old
        assert e.value.code == 0, name


def test_empty_and_unknown_names_error_cleanly():
    rc, out, _ = _run_capture([])
    assert rc == 2
    # unknown name reports an error instead of raising
    rc, _, err = _run_capture(["NoSuchPipeline"])
    assert rc == 2 and "unknown pipeline" in err


def test_cli_validates_environment_fail_fast(monkeypatch):
    """A typo'd KEYSTONE_* value dies AT DISPATCH with the knob-named
    message (rc=2) — every subcommand shares the gate, so a bad knob can
    never be silently ignored mid-run."""
    monkeypatch.setenv("KEYSTONE_OVERLAP", "yes")  # bools take '0'/'1'
    rc, _, err = _run_capture(["--help"])
    assert rc == 2
    assert "KEYSTONE_OVERLAP" in err and "invalid environment" in err
    # lint rides the same dispatch gate
    rc, _, err = _run_capture(["lint", "--help"])
    assert rc == 2 and "KEYSTONE_OVERLAP" in err
    monkeypatch.delenv("KEYSTONE_OVERLAP")
    rc, _, _ = _run_capture(["--help"])
    assert rc == 0


def test_bench_regime_validates_environment_fail_fast(monkeypatch):
    """scripts/bench_regime.py shares the same fail-fast contract: an
    invalid knob value exits 2 with the knob named, before any regime
    imports jax or touches devices."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_regime_under_test",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "bench_regime.py",
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setenv("KEYSTONE_SKETCH_FACTOR", "0.5")  # must be > 1
    monkeypatch.setattr(sys, "argv", ["bench_regime.py", "flagship"])
    err = io.StringIO()
    old = sys.stderr
    sys.stderr = err
    try:
        rc = mod.main()
    finally:
        sys.stderr = old
    assert rc == 2
    assert "KEYSTONE_SKETCH_FACTOR" in err.getvalue()


def test_case_insensitive_name_resolves(monkeypatch):
    import importlib

    called = {}
    mod = importlib.import_module(cli.PIPELINES["MnistRandomFFT"])
    monkeypatch.setattr(mod, "main", lambda rest: called.setdefault("argv", rest))
    rc, _, _ = _run_capture(["MNISTRANDOMFFT"])
    assert rc == 0 and called["argv"] == []


def test_partial_distributed_flags_refused():
    rc, _, err = _run_capture(
        ["--num-processes", "2", "MnistRandomFFT"]
    )
    assert rc == 2
    assert "require --coordinator" in err


def test_mesh_model_must_divide_devices():
    rc, _, err = _run_capture(["--mesh-model", "7", "MnistRandomFFT"])
    assert rc == 2
    assert "does not divide" in err


def test_snake_case_resolves(monkeypatch):
    """mnist_random_fft resolves to MnistRandomFFT and runs its main."""
    import importlib

    called = {}
    mod = importlib.import_module(cli.PIPELINES["MnistRandomFFT"])
    monkeypatch.setattr(mod, "main", lambda rest: called.setdefault("argv", rest))
    rc, _, _ = _run_capture(["mnist_random_fft", "--num-ffts", "2"])
    assert rc == 0
    assert called["argv"] == ["--num-ffts", "2"]


def test_hosts_emits_per_host_commands(capsys):
    from keystone_tpu.cli import main

    rc = main(["--hosts", "h0,h1,h2", "--mesh-model", "2",
               "--devices-per-host", "4", "Timit", "--num-epochs", "5"])
    assert rc == 0
    out = capsys.readouterr().out
    lines = [l for l in out.splitlines() if l and not l.startswith("#")]
    assert len(lines) == 3
    for i, line in enumerate(lines):
        assert f"--process-id {i}" in line
        assert "--coordinator h0:8476" in line  # first host elected
        assert "--num-processes 3" in line
        assert "--mesh-model 2" in line
        assert "Timit --num-epochs 5" in line
    assert "12 devices -> (data=6, model=2)" in out


def test_hosts_rejects_indivisible_mesh(capsys):
    from keystone_tpu.cli import main

    rc = main(["--hosts", "h0,h1", "--mesh-model", "3", "Timit"])
    assert rc == 2
    assert "does not divide" in capsys.readouterr().err


def test_emit_host_commands_unit():
    from keystone_tpu.cli import emit_host_commands

    lines, note = emit_host_commands(["a", " b "], ["MnistRandomFFT"],
                                     devices_per_host=8, port=9000)
    assert lines[0][0] == "a" and lines[1][0] == "b"
    assert "--coordinator a:9000" in lines[1][1]
    assert "16 devices" in note
