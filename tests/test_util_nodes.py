import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.util import (
    ClassLabelIndicatorsFromIntLabels,
    ClassLabelIndicatorsFromIntArrayLabels,
    MatrixVectorizer,
    MaxClassifier,
    TopKClassifier,
    VectorSplitter,
    ZipVectors,
)


def test_class_label_indicators():
    node = ClassLabelIndicatorsFromIntLabels(num_classes=4)
    out = node(jnp.array([0, 2]))
    np.testing.assert_allclose(
        np.asarray(out), [[1, -1, -1, -1], [-1, -1, 1, -1]]
    )


def test_multilabel_indicators_with_padding():
    node = ClassLabelIndicatorsFromIntArrayLabels(num_classes=5)
    labels = jnp.array([[0, 3, -1], [2, -1, -1]])
    out = node(labels)
    np.testing.assert_allclose(
        np.asarray(out), [[1, -1, -1, 1, -1], [-1, -1, 1, -1, -1]]
    )


def test_max_and_topk_classifier():
    scores = jnp.array([[0.1, 0.9, 0.0], [0.5, 0.2, 0.3]])
    assert np.asarray(MaxClassifier()(scores)).tolist() == [1, 0]
    topk = TopKClassifier(k=2)(scores)
    assert np.asarray(topk).tolist() == [[1, 0], [0, 2]]


def test_vector_splitter_zip_roundtrip():
    x = jnp.arange(24.0).reshape(4, 6)
    blocks = VectorSplitter(block_size=4)(x)
    assert [b.shape for b in blocks] == [(4, 4), (4, 2)]
    back = ZipVectors()(blocks)
    np.testing.assert_allclose(np.asarray(back), np.asarray(x))


def test_matrix_vectorizer_column_major():
    m = jnp.array([[1.0, 2.0], [3.0, 4.0]])
    out = MatrixVectorizer().serve(m)
    # Breeze toDenseVector is column-major
    np.testing.assert_allclose(np.asarray(out), [1.0, 3.0, 2.0, 4.0])
