"""Deterministic fault injection (``utils/faults.py``): the KEYSTONE_FAULTS
plan grammar, the per-site occurrence counters, each wired injection site
(streaming block loop, BCD entry, pipeline segment boundary), and the
off-by-default contract — unset knob means no counting, no behavior change,
bit-identical results."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.utils import faults, knobs


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("KEYSTONE_FAULTS", raising=False)
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# Plan grammar
# ---------------------------------------------------------------------------

def test_plan_parses_through_knob_registry(monkeypatch):
    monkeypatch.setenv(
        "KEYSTONE_FAULTS", "block@7, bcd@0:oom, segment@2:xla*3"
    )
    plan = knobs.get("KEYSTONE_FAULTS")
    assert plan == (
        faults.FaultSpec("block", 7, "xla", 1),
        faults.FaultSpec("bcd", 0, "oom", 1),
        faults.FaultSpec("segment", 2, "xla", 3),
    )


@pytest.mark.parametrize("bad", [
    "block",            # no occurrence
    "block@x",          # non-integer occurrence
    "nope@1",           # unknown site
    "block@1:zap",      # unknown kind
    "block@1*0",        # repeat < 1
    "block@-1",         # negative occurrence
])
def test_malformed_plan_is_a_knob_error(monkeypatch, bad):
    monkeypatch.setenv("KEYSTONE_FAULTS", bad)
    with pytest.raises(ValueError, match="KEYSTONE_FAULTS"):
        knobs.get("KEYSTONE_FAULTS")
    # and validate_environment (the bench's fail-fast) rejects it too
    with pytest.raises(ValueError):
        knobs.validate_environment()


def test_repeat_fires_consecutive_occurrences(monkeypatch):
    monkeypatch.setenv("KEYSTONE_FAULTS", "bcd@1:xla*2")
    faults.check("bcd")  # occurrence 0: clean
    for _ in range(2):   # occurrences 1, 2: both fire
        with pytest.raises(Exception, match="injected fault"):
            faults.check("bcd")
    faults.check("bcd")  # occurrence 3: clean again


# ---------------------------------------------------------------------------
# Off-by-default contract
# ---------------------------------------------------------------------------

def test_unset_knob_counts_nothing_and_changes_nothing(rng):
    from keystone_tpu.linalg.bcd import block_coordinate_descent_l2

    A = jnp.asarray(rng.normal(size=(64, 16)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(64, 3)).astype(np.float32))
    w_ref = np.asarray(block_coordinate_descent_l2(A, b, 1.0, 8))
    # the armed-plan crossings of other tests were reset by the fixture;
    # unarmed crossings must not count at all
    assert faults.counters() == {}
    w_again = np.asarray(block_coordinate_descent_l2(A, b, 1.0, 8))
    np.testing.assert_array_equal(w_ref, w_again)
    assert faults.counters() == {}


def test_injected_error_is_retriable_and_counted(monkeypatch):
    """The default kind raises the SAME XlaRuntimeError type the retry
    wrapper treats as retriable — injection exercises the production
    recovery path, not a parallel test-only one."""
    import jaxlib.xla_extension as xe

    from keystone_tpu.telemetry import get_registry

    reg = get_registry()
    before = reg.get_counter("faults.injected", site="bcd", kind="xla")
    monkeypatch.setenv("KEYSTONE_FAULTS", "bcd@0")
    with pytest.raises(xe.XlaRuntimeError, match="INTERNAL: injected"):
        faults.check("bcd")
    assert reg.get_counter(
        "faults.injected", site="bcd", kind="xla"
    ) == before + 1


def test_oom_kind_has_resource_exhausted_flavor(monkeypatch):
    monkeypatch.setenv("KEYSTONE_FAULTS", "segment@0:oom")
    with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
        faults.check("segment")


def test_unknown_site_crossing_is_a_bug(monkeypatch):
    monkeypatch.setenv("KEYSTONE_FAULTS", "block@99")
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.check("typo_site")


# ---------------------------------------------------------------------------
# Wired sites
# ---------------------------------------------------------------------------

def test_bcd_entry_site_fires(monkeypatch, rng):
    from keystone_tpu.linalg.bcd import block_coordinate_descent_l2

    A = jnp.asarray(rng.normal(size=(64, 16)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(64, 3)).astype(np.float32))
    monkeypatch.setenv("KEYSTONE_FAULTS", "bcd@1")
    w0 = block_coordinate_descent_l2(A, b, 1.0, 8)  # occurrence 0: clean
    with pytest.raises(Exception, match="injected fault"):
        block_coordinate_descent_l2(A, b, 1.0, 8)   # occurrence 1: fires
    assert w0.shape == (16, 3)


def test_segment_boundary_site_fires(monkeypatch, rng):
    from keystone_tpu.core.pipeline import chain
    from keystone_tpu.ops.stats import LinearRectifier

    pipe = chain(LinearRectifier(), LinearRectifier())
    x = jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))
    np.testing.assert_array_equal(  # unarmed: the fused segment runs clean
        np.asarray(pipe(x)), np.maximum(np.asarray(x), 0.0)
    )
    monkeypatch.setenv("KEYSTONE_FAULTS", "segment@0")
    with pytest.raises(Exception, match="injected fault"):
        pipe(x)


def test_streaming_block_site_kills_mid_schedule_and_resumes(
    monkeypatch, rng, tmp_path
):
    """The chaos-ladder core on one mesh: an injected device error at a
    mid-schedule block boundary leaves the checkpoint behind; the
    production elastic retry resumes from it and the result equals the
    uninterrupted fit bit-exactly (same mesh, same reduction geometry)."""
    from keystone_tpu.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )
    from keystone_tpu.telemetry import get_registry
    from keystone_tpu.utils import fit_streaming_elastic

    n, d, c, bs = 96, 32, 4, 8
    x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    lbl = jnp.asarray(
        np.eye(c, dtype=np.float32)[np.arange(n) % c] * 2.0 - 1.0
    )

    class Slice:
        def __init__(self, lo, hi):
            self.lo, self.hi = lo, hi

        def apply_batch(self, raw):
            return raw["x"][:, self.lo : self.hi]

    nodes = [Slice(k * bs, (k + 1) * bs) for k in range(d // bs)]
    est = BlockWeightedLeastSquaresEstimator(bs, 1, 0.1, 0.25)
    ref = est.fit_streaming(nodes, {"x": x}, lbl)

    reg = get_registry()
    resumed0 = reg.get_counter("retry.resumed")
    ckpt = str(tmp_path / "chaos.ckpt")
    monkeypatch.setenv("KEYSTONE_FAULTS", "block@2:xla")
    m = fit_streaming_elastic(
        est, nodes, {"x": x}, lbl,
        checkpoint_path=ckpt, checkpoint_every=1,
        retries=2, backoff_s=0.0,
    )
    np.testing.assert_array_equal(np.asarray(m.w), np.asarray(ref.w))
    np.testing.assert_array_equal(np.asarray(m.b), np.asarray(ref.b))
    assert reg.get_counter("retry.resumed") == resumed0 + 1
    assert not os.path.exists(ckpt)  # completed fit cleans up


def test_kill_kind_sigkills_the_process(tmp_path):
    """The 'kill' kind is a real SIGKILL (the preemption only a checkpoint
    survives) — exercised in a subprocess so this test outlives it."""
    import signal
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ['KEYSTONE_FAULTS'] = 'segment@0:kill'\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "from keystone_tpu.utils import faults\n"
        "faults.check('segment')\n"
        "print('survived')\n"
    )
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=240, env=env,
    )
    assert proc.returncode == -signal.SIGKILL, (
        proc.returncode, proc.stdout, proc.stderr[-500:]
    )
    assert "survived" not in proc.stdout
