"""Core pipeline API tests (reference behavior: ``pipelines/Transformer.scala``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu import (
    Cacher,
    Chain,
    Estimator,
    Identity,
    LabelEstimator,
    Transformer,
    chain,
)
from keystone_tpu.core.pipeline import LambdaTransformer


class Doubler(Transformer):
    def apply(self, x):
        return x * 2


class AddOne(Transformer):
    def apply(self, x):
        return x + 1


def test_single_and_batch_paths_agree():
    t = Doubler()
    x = jnp.arange(4.0)
    batch = jnp.stack([x, x + 1])
    assert np.allclose(t.serve(x), x * 2)
    assert np.allclose(t(batch), batch * 2)


def test_then_composition_and_flattening():
    p = Doubler() >> AddOne() >> Doubler()
    assert isinstance(p, Chain)
    assert len(p.stages) == 3
    q = p >> AddOne()
    assert len(q.stages) == 4  # nested chains flatten
    x = jnp.array([1.0, 2.0])
    np.testing.assert_allclose(np.asarray(q.serve(x)), (np.array([1.0, 2.0]) * 2 + 1) * 2 + 1)


def test_chain_batch_with_cacher_boundary():
    p = Doubler() >> Cacher(name="mid") >> AddOne()
    batch = jnp.ones((8, 3))
    out = p(batch)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 3), 3.0))


def test_lambda_transformer():
    t = Transformer.from_fn(lambda x: x - 5)
    assert isinstance(t, LambdaTransformer)
    np.testing.assert_allclose(np.asarray(t(jnp.zeros((2, 2)))), -5 * np.ones((2, 2)))


def test_then_estimator_defers_fit():
    """`pre.then(est)`: est fits on pre-transformed data (Transformer.scala:37)."""

    class MeanShift(Estimator):
        def fit(self, data):
            mu = jnp.mean(data, axis=0)
            return Transformer.from_fn(lambda x: x - mu)

    pre = Doubler()
    pipe_est = pre.then(MeanShift())
    data = jnp.array([[1.0, 2.0], [3.0, 4.0]])
    fitted = pipe_est.fit(data)
    out = fitted(data)
    # doubled data is [[2,4],[6,8]], mean [4,6] -> centered
    np.testing.assert_allclose(np.asarray(out), [[-2.0, -2.0], [2.0, 2.0]])


def test_then_label_estimator_defers_fit():
    class LabelMean(LabelEstimator):
        def fit(self, data, labels):
            mu = jnp.mean(labels)
            return Transformer.from_fn(lambda x: x + mu)

    pipe_est = Identity().then(LabelMean())
    data = jnp.zeros((3, 2))
    labels = jnp.array([1.0, 2.0, 3.0])
    fitted = pipe_est.fit(data, labels)
    np.testing.assert_allclose(np.asarray(fitted(data)), np.full((3, 2), 2.0))


def test_fitted_chain_is_pytree():
    p = Doubler() >> AddOne()
    leaves = jax.tree_util.tree_leaves(p)
    assert leaves == []  # stateless nodes: all config static
    # a chain with state exposes its leaves

    class Affine(Transformer):
        w: jax.Array

        def apply(self, x):
            return x * self.w

    q = Affine(w=jnp.array(3.0)) >> AddOne()
    assert len(jax.tree_util.tree_leaves(q)) == 1


def test_jit_cache_reuse_across_refit():
    class Affine(Transformer):
        w: jax.Array

        def apply(self, x):
            return x * self.w

    batch = jnp.ones((4, 2))
    t1 = Affine(w=jnp.array(2.0))
    t2 = Affine(w=jnp.array(5.0))
    np.testing.assert_allclose(np.asarray(t1(batch)), 2 * np.ones((4, 2)))
    np.testing.assert_allclose(np.asarray(t2(batch)), 5 * np.ones((4, 2)))
