import jax.numpy as jnp
import numpy as np

from keystone_tpu.evaluation import (
    BinaryClassifierEvaluator,
    MeanAveragePrecisionEvaluator,
    MulticlassClassifierEvaluator,
)


def test_multiclass_confusion_and_accuracy():
    preds = jnp.array([0, 1, 2, 1, 0])
    actuals = jnp.array([0, 1, 1, 1, 2])
    m = MulticlassClassifierEvaluator(num_classes=3)(preds, actuals)
    # rows = actual, cols = predicted
    expected = np.array([[1, 0, 0], [0, 2, 1], [1, 0, 0]], dtype=float)
    np.testing.assert_allclose(m.confusion_matrix, expected)
    assert abs(m.total_accuracy - 3 / 5) < 1e-9
    assert m.micro_precision == m.total_accuracy
    assert "Accuracy" in m.summary()


def test_multiclass_masked():
    preds = jnp.array([0, 1, 0, 0])
    actuals = jnp.array([0, 1, 1, 1])
    mask = jnp.array([1.0, 1.0, 1.0, 0.0])
    m = MulticlassClassifierEvaluator(num_classes=2)(preds, actuals, mask)
    assert m.total == 3
    assert abs(m.total_accuracy - 2 / 3) < 1e-9


def test_binary_metrics():
    preds = jnp.array([1, 1, 0, 0, 1])
    actuals = jnp.array([1, 0, 0, 1, 1])
    m = BinaryClassifierEvaluator()(preds, actuals)
    assert (m.tp, m.fp, m.fn, m.tn) == (2, 1, 1, 1)
    assert abs(m.precision - 2 / 3) < 1e-9
    assert abs(m.recall - 2 / 3) < 1e-9
    assert abs(m.fscore() - 2 / 3) < 1e-9


def test_mean_average_precision_perfect_ranking():
    # class 0 relevant items ranked first -> AP = 1
    actuals = jnp.array([[0], [0], [1]])
    scores = jnp.array([[0.9, 0.1], [0.8, 0.3], [0.1, 0.7]])
    ev = MeanAveragePrecisionEvaluator(num_classes=2)
    aps = ev(actuals, scores)
    np.testing.assert_allclose(aps, [1.0, 1.0], atol=1e-6)


def test_mean_average_precision_voc_11pt():
    # One relevant item ranked second of three: precision@match = 1/2.
    # 11-pt interpolated AP = mean over t of max precision with recall>=t = 0.5
    actuals = jnp.array([[1], [0], [1]])
    scores = jnp.array([[0.9], [0.8], [0.1]])[:, :1]
    ev = MeanAveragePrecisionEvaluator(num_classes=1)
    ap = ev(jnp.array([[0], [-1], [-1]]), jnp.array([[0.5], [0.9], [0.1]]))
    np.testing.assert_allclose(ap, [0.5], atol=1e-6)
