"""Retry-on-device-error wrapper (SURVEY.md §5 failure-detection bullet:
what Spark's task retry gave the reference for free, scoped to the
transient single-process failures a JAX runtime actually sees)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops.stats import LinearRectifier
from keystone_tpu.utils import Retry, call_with_device_retries


class _FakeDeviceError(RuntimeError):
    pass


def test_retries_then_succeeds():
    calls = []

    def flaky(x):
        calls.append(1)
        if len(calls) < 3:
            raise _FakeDeviceError("transport hiccup")
        return x + 1

    out = call_with_device_retries(
        flaky, 41, retries=2, backoff_s=0.0, retriable=(_FakeDeviceError,)
    )
    assert out == 42 and len(calls) == 3


def test_exhausted_retries_raise():
    def always_fails():
        raise _FakeDeviceError("down")

    with pytest.raises(_FakeDeviceError):
        call_with_device_retries(
            always_fails, retries=1, backoff_s=0.0,
            retriable=(_FakeDeviceError,),
        )


def test_non_retriable_propagates_immediately():
    calls = []

    def typo():
        calls.append(1)
        raise ValueError("not a device error")

    with pytest.raises(ValueError):
        call_with_device_retries(typo, retries=5, backoff_s=0.0)
    assert len(calls) == 1


def test_retry_node_wraps_pipeline_stage():
    node = Retry(node=LinearRectifier(), retries=1)
    x = jnp.asarray(np.array([[-1.0, 2.0]], np.float32))
    out = node(x)
    np.testing.assert_allclose(np.asarray(out), [[0.0, 2.0]])
    one = node.apply(x[0])
    np.testing.assert_allclose(np.asarray(one), [0.0, 2.0])


def test_fit_streaming_elastic_resumes_not_restarts(tmp_path):
    """Elastic streaming fit (retry x mid-fit checkpoint): a device error
    mid-solve must cost only the blocks since the last checkpoint, and the
    final model must equal the uninterrupted fit bit-exactly (SURVEY §5
    failure-recovery — the lineage-recompute analog for the solver)."""
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )
    from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels
    from keystone_tpu.utils import fit_streaming_elastic

    rng = np.random.default_rng(3)
    n, d, c, bs = 120, 32, 4, 8
    x = rng.normal(size=(n, d)).astype(np.float32)
    labels = (np.arange(n) % c).astype(np.int32)
    ind = np.asarray(ClassLabelIndicatorsFromIntLabels(c)(jnp.asarray(labels)))
    nblocks = d // bs

    calls = {"n": 0}

    class FlakyNode:
        """Fails with a 'device error' on its 3rd block visit, once."""

        def __init__(self, lo, hi):
            self.lo, self.hi = lo, hi
            self.failed = False

        def apply_batch(self, raw):
            calls["n"] += 1
            if calls["n"] == 3 and not FlakyNode.tripped:
                FlakyNode.tripped = True
                raise RuntimeError("transient device error (injected)")
            return raw["x"][:, self.lo : self.hi]

    FlakyNode.tripped = False
    class SliceNode:
        def __init__(self, lo, hi):
            self.lo, self.hi = lo, hi

        def apply_batch(self, raw):
            return raw["x"][:, self.lo : self.hi]

    est = BlockWeightedLeastSquaresEstimator(bs, 1, 0.1, 0.25)
    ref = est.fit_streaming(
        [SliceNode(k * bs, (k + 1) * bs) for k in range(nblocks)],
        {"x": jnp.asarray(x)}, jnp.asarray(ind),
    )

    nodes = [FlakyNode(k * bs, (k + 1) * bs) for k in range(nblocks)]
    ckpt = str(tmp_path / "elastic.ckpt")
    m = fit_streaming_elastic(
        est, nodes, {"x": jnp.asarray(x)}, jnp.asarray(ind),
        checkpoint_path=ckpt, checkpoint_every=1,
        retries=2, backoff_s=0.0, retriable=(RuntimeError,),
    )
    np.testing.assert_array_equal(np.asarray(m.w), np.asarray(ref.w))
    np.testing.assert_array_equal(np.asarray(m.b), np.asarray(ref.b))
    # progress preserved: 2 completed calls before the crash + the crashing
    # call + only the remaining blocks on resume (not a from-scratch rerun)
    assert calls["n"] == 3 + (nblocks - 2)
    # completed elastic fit cleans its checkpoint (path reusable)
    assert not (tmp_path / "elastic.ckpt").exists()
