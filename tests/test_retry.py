"""Retry-on-device-error wrapper (SURVEY.md §5 failure-detection bullet:
what Spark's task retry gave the reference for free, scoped to the
transient single-process failures a JAX runtime actually sees)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops.stats import LinearRectifier
from keystone_tpu.utils import Retry, call_with_device_retries


class _FakeDeviceError(RuntimeError):
    pass


def test_retries_then_succeeds():
    calls = []

    def flaky(x):
        calls.append(1)
        if len(calls) < 3:
            raise _FakeDeviceError("transport hiccup")
        return x + 1

    out = call_with_device_retries(
        flaky, 41, retries=2, backoff_s=0.0, retriable=(_FakeDeviceError,)
    )
    assert out == 42 and len(calls) == 3


def test_exhausted_retries_raise():
    def always_fails():
        raise _FakeDeviceError("down")

    with pytest.raises(_FakeDeviceError):
        call_with_device_retries(
            always_fails, retries=1, backoff_s=0.0,
            retriable=(_FakeDeviceError,),
        )


def test_non_retriable_propagates_immediately():
    calls = []

    def typo():
        calls.append(1)
        raise ValueError("not a device error")

    with pytest.raises(ValueError):
        call_with_device_retries(typo, retries=5, backoff_s=0.0)
    assert len(calls) == 1


def test_retry_node_wraps_pipeline_stage():
    node = Retry(node=LinearRectifier(), retries=1)
    x = jnp.asarray(np.array([[-1.0, 2.0]], np.float32))
    out = node(x)
    np.testing.assert_allclose(np.asarray(out), [[0.0, 2.0]])
    one = node.apply(x[0])
    np.testing.assert_allclose(np.asarray(one), [0.0, 2.0])
