"""Retry-on-device-error wrapper (SURVEY.md §5 failure-detection bullet:
what Spark's task retry gave the reference for free, scoped to the
transient single-process failures a JAX runtime actually sees)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops.stats import LinearRectifier
from keystone_tpu.utils import Retry, call_with_device_retries


class _FakeDeviceError(RuntimeError):
    pass


def test_retries_then_succeeds():
    calls = []

    def flaky(x):
        calls.append(1)
        if len(calls) < 3:
            raise _FakeDeviceError("transport hiccup")
        return x + 1

    out = call_with_device_retries(
        flaky, 41, retries=2, backoff_s=0.0, retriable=(_FakeDeviceError,)
    )
    assert out == 42 and len(calls) == 3


def test_exhausted_retries_raise():
    def always_fails():
        raise _FakeDeviceError("down")

    with pytest.raises(_FakeDeviceError):
        call_with_device_retries(
            always_fails, retries=1, backoff_s=0.0,
            retriable=(_FakeDeviceError,),
        )


def test_non_retriable_propagates_immediately():
    calls = []

    def typo():
        calls.append(1)
        raise ValueError("not a device error")

    with pytest.raises(ValueError):
        call_with_device_retries(typo, retries=5, backoff_s=0.0)
    assert len(calls) == 1


def test_retry_node_wraps_pipeline_stage():
    node = Retry(node=LinearRectifier(), retries=1)
    x = jnp.asarray(np.array([[-1.0, 2.0]], np.float32))
    out = node(x)
    np.testing.assert_allclose(np.asarray(out), [[0.0, 2.0]])
    one = node.apply(x[0])
    np.testing.assert_allclose(np.asarray(one), [0.0, 2.0])


def test_fit_streaming_elastic_resumes_not_restarts(tmp_path):
    """Elastic streaming fit (retry x mid-fit checkpoint): a device error
    mid-solve must cost only the blocks since the last checkpoint, and the
    final model must equal the uninterrupted fit bit-exactly (SURVEY §5
    failure-recovery — the lineage-recompute analog for the solver)."""
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )
    from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels
    from keystone_tpu.utils import fit_streaming_elastic

    rng = np.random.default_rng(3)
    n, d, c, bs = 120, 32, 4, 8
    x = rng.normal(size=(n, d)).astype(np.float32)
    labels = (np.arange(n) % c).astype(np.int32)
    ind = np.asarray(ClassLabelIndicatorsFromIntLabels(c)(jnp.asarray(labels)))
    nblocks = d // bs

    calls = {"n": 0}

    class FlakyNode:
        """Fails with a 'device error' on its 3rd block visit, once."""

        def __init__(self, lo, hi):
            self.lo, self.hi = lo, hi
            self.failed = False

        def apply_batch(self, raw):
            calls["n"] += 1
            if calls["n"] == 3 and not FlakyNode.tripped:
                FlakyNode.tripped = True
                raise RuntimeError("transient device error (injected)")
            return raw["x"][:, self.lo : self.hi]

    FlakyNode.tripped = False
    class SliceNode:
        def __init__(self, lo, hi):
            self.lo, self.hi = lo, hi

        def apply_batch(self, raw):
            return raw["x"][:, self.lo : self.hi]

    est = BlockWeightedLeastSquaresEstimator(bs, 1, 0.1, 0.25)
    ref = est.fit_streaming(
        [SliceNode(k * bs, (k + 1) * bs) for k in range(nblocks)],
        {"x": jnp.asarray(x)}, jnp.asarray(ind),
    )

    nodes = [FlakyNode(k * bs, (k + 1) * bs) for k in range(nblocks)]
    ckpt = str(tmp_path / "elastic.ckpt")
    m = fit_streaming_elastic(
        est, nodes, {"x": jnp.asarray(x)}, jnp.asarray(ind),
        checkpoint_path=ckpt, checkpoint_every=1,
        retries=2, backoff_s=0.0, retriable=(RuntimeError,),
    )
    np.testing.assert_array_equal(np.asarray(m.w), np.asarray(ref.w))
    np.testing.assert_array_equal(np.asarray(m.b), np.asarray(ref.b))
    # progress preserved: 2 completed calls before the crash + the crashing
    # call + only the remaining blocks on resume (not a from-scratch rerun)
    assert calls["n"] == 3 + (nblocks - 2)
    # completed elastic fit cleans its checkpoint (path reusable)
    assert not (tmp_path / "elastic.ckpt").exists()


# ---------------------------------------------------------------------------
# Hardened retry (PR 12): budget knob, deterministic jitter, on-retry hook,
# exhaustion message
# ---------------------------------------------------------------------------

def test_retry_budget_knob_governs_default(monkeypatch):
    """retries=None takes KEYSTONE_RETRY_BUDGET; explicit retries= wins."""
    monkeypatch.setenv("KEYSTONE_RETRY_BUDGET", "0")
    calls = []

    def flaky():
        calls.append(1)
        raise _FakeDeviceError("down")

    with pytest.raises(_FakeDeviceError):
        call_with_device_retries(
            flaky, backoff_s=0.0, retriable=(_FakeDeviceError,)
        )
    assert len(calls) == 1  # budget 0: no re-attempts
    calls.clear()
    with pytest.raises(_FakeDeviceError):
        call_with_device_retries(  # explicit beats the knob
            flaky, retries=2, backoff_s=0.0, retriable=(_FakeDeviceError,)
        )
    assert len(calls) == 3


def test_exhaustion_surfaces_original_type_with_attempt_count():
    def always_fails():
        raise _FakeDeviceError("device gone")

    with pytest.raises(_FakeDeviceError) as ei:
        call_with_device_retries(
            always_fails, retries=2, backoff_s=0.0,
            retriable=(_FakeDeviceError,),
        )
    msg = str(ei.value)
    assert "device gone" in msg and "3 attempt" in msg, msg


def test_exhaustion_preserves_constructor_set_attributes():
    """The attempt count is amended IN PLACE (string first-arg) or skipped
    (non-string first-arg) — never a type(e)(msg) rebuild that would drop
    multi-arg state like OSError.errno, breaking upstream handlers that
    inspect it."""
    import errno as _errno

    def fails_with_errno():
        raise OSError(_errno.ENOSPC, "No space left on device")

    with pytest.raises(OSError) as ei:
        call_with_device_retries(
            fails_with_errno, retries=1, backoff_s=0.0, retriable=(OSError,)
        )
    assert ei.value.errno == _errno.ENOSPC  # handler-visible state intact


def test_backoff_is_deterministic_jittered_and_capped(monkeypatch):
    """Waits are exponential with a deterministic jitter in [0, 25%) and a
    hard cap — two identical runs sleep the exact same schedule."""
    from keystone_tpu.utils.retry import _jitter_frac

    for token in ("a", "b"):
        for attempt in range(1, 6):
            f = _jitter_frac(token, attempt)
            assert 0.0 <= f < 0.25
            assert f == _jitter_frac(token, attempt)  # deterministic

    waits = []
    monkeypatch.setattr("time.sleep", lambda s: waits.append(s))
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 4:
            raise _FakeDeviceError("hiccup")
        return "ok"

    assert call_with_device_retries(
        flaky, retries=3, backoff_s=1.0, max_backoff_s=2.0,
        retriable=(_FakeDeviceError,),
    ) == "ok"
    assert len(waits) == 3
    assert 1.0 <= waits[0] < 1.25      # base * jitter
    assert 2.0 <= waits[1] < 2.5       # doubled
    assert 2.0 <= waits[2] < 2.5       # capped at max_backoff_s (pre-jitter)

    waits2 = []
    calls.clear()
    monkeypatch.setattr("time.sleep", lambda s: waits2.append(s))
    call_with_device_retries(
        flaky, retries=3, backoff_s=1.0, max_backoff_s=2.0,
        retriable=(_FakeDeviceError,),
    )
    assert waits2 == waits  # reproducible schedule


def test_on_retry_hook_runs_and_its_failure_never_masks_the_retry():
    seen = []

    def hook(attempt, exc):
        seen.append((attempt, str(exc)))
        raise RuntimeError("hook bug")  # must not break the retry loop

    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise _FakeDeviceError("hiccup")
        return 7

    assert call_with_device_retries(
        flaky, retries=2, backoff_s=0.0, retriable=(_FakeDeviceError,),
        on_retry=hook,
    ) == 7
    assert seen == [(1, "hiccup")]


def test_default_hook_frees_device_cache_tier_on_oom():
    """The OOM-survives-smaller-retry case: RESOURCE_EXHAUSTED errors free
    the intermediate cache's device tier before the re-dispatch."""
    import jax

    from keystone_tpu.core.cache import IntermediateCache, use_cache

    cache = IntermediateCache(device_bytes=1 << 20, host_bytes=1 << 20)
    with use_cache(cache):
        cache.memoize("k1", lambda: jax.numpy.ones((128,)))
        assert cache._tier_bytes["device"] > 0
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 2:
                raise _FakeDeviceError("RESOURCE_EXHAUSTED: out of memory")
            return "ok"

        assert call_with_device_retries(
            flaky, retries=1, backoff_s=0.0, retriable=(_FakeDeviceError,)
        ) == "ok"
        # the entry survived but left HBM (demoted to the host tier)
        assert cache._tier_bytes["device"] == 0
        hit, val = cache.lookup("k1")
        assert hit and val.shape == (128,)


def test_retry_telemetry_counters():
    from keystone_tpu.telemetry import get_registry

    reg = get_registry()
    a0, r0, e0 = (reg.get_counter("retry.attempt"),
                  reg.get_counter("retry.resumed"),
                  reg.get_counter("retry.exhausted"))
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise _FakeDeviceError("hiccup")
        return 1

    call_with_device_retries(
        flaky, retries=2, backoff_s=0.0, retriable=(_FakeDeviceError,)
    )
    assert reg.get_counter("retry.attempt") == a0 + 1
    assert reg.get_counter("retry.resumed") == r0 + 1
    with pytest.raises(_FakeDeviceError):
        call_with_device_retries(
            lambda: (_ for _ in ()).throw(_FakeDeviceError("down")),
            retries=0, backoff_s=0.0, retriable=(_FakeDeviceError,),
        )
    assert reg.get_counter("retry.exhausted") == e0 + 1


# ---------------------------------------------------------------------------
# fit_streaming_elastic edge cases (PR 12 satellite): final-block resume,
# foreign block order, corrupt checkpoint, checkpoint-dir knob
# ---------------------------------------------------------------------------

def _elastic_fixture(rng_seed=3, n=96, d=32, c=4, bs=8):
    import numpy as np

    from keystone_tpu.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )

    rng = np.random.default_rng(rng_seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    lbl = np.eye(c, dtype=np.float32)[np.arange(n) % c] * 2.0 - 1.0

    class Slice:
        calls = 0

        def __init__(self, lo, hi):
            self.lo, self.hi = lo, hi

        def apply_batch(self, raw):
            Slice.calls += 1
            return raw["x"][:, self.lo : self.hi]

    nodes = [Slice(k * bs, (k + 1) * bs) for k in range(d // bs)]
    est = BlockWeightedLeastSquaresEstimator(bs, 1, 0.1, 0.25)
    return est, nodes, Slice, {"x": jnp.asarray(x)}, jnp.asarray(lbl)


def test_elastic_resume_after_final_block_is_noop_completion(
    tmp_path, monkeypatch
):
    """A checkpoint whose cursor sits past the final block resumes as a
    NO-OP: zero blocks re-featurized, the checkpointed model returned
    bit-exactly, and the file cleaned up — the crash-after-last-save
    window."""
    import os

    est, nodes, Slice, raw, lbl = _elastic_fixture()
    ckpt = str(tmp_path / "final.ckpt")

    # capture the final checkpoint by disabling the completion-time removal
    removed = []
    real_remove = os.remove
    monkeypatch.setattr(os, "remove", lambda p: removed.append(p))
    ref = est.fit_streaming(
        nodes, raw, lbl, checkpoint_path=ckpt, checkpoint_every=1
    )
    monkeypatch.setattr(os, "remove", real_remove)
    assert removed == [ckpt] and os.path.exists(ckpt)

    from keystone_tpu.core.checkpoint import load_manifest

    assert load_manifest(ckpt)["pos"] == len(nodes)  # cursor past the end
    Slice.calls = 0
    m = est.fit_streaming(
        nodes, raw, lbl, checkpoint_path=ckpt, checkpoint_every=1
    )
    assert Slice.calls == 0  # no block revisited
    np.testing.assert_array_equal(np.asarray(m.w), np.asarray(ref.w))
    np.testing.assert_array_equal(np.asarray(m.b), np.asarray(ref.b))
    assert not os.path.exists(ckpt)  # completion still cleans up


def test_elastic_rejects_checkpoint_under_different_block_order(tmp_path):
    """A checkpoint written under a foreign visit schedule must fail
    LOUDLY (silently interleaving two orders would corrupt the
    Gauss-Seidel pass), and the non-retriable error must escape the
    elastic retry loop immediately."""
    from keystone_tpu.core.checkpoint import load_checkpoint, save_node
    from keystone_tpu.utils import fit_streaming_elastic

    est, nodes, Slice, raw, lbl = _elastic_fixture()
    ckpt = str(tmp_path / "order.ckpt")
    import os

    # write a genuine mid-fit checkpoint, then forge its block order
    os.environ["KEYSTONE_FAULTS"] = "block@2:xla"
    from keystone_tpu.utils import faults

    faults.reset()
    try:
        with pytest.raises(Exception, match="injected fault"):
            est.fit_streaming(
                nodes, raw, lbl, checkpoint_path=ckpt, checkpoint_every=1
            )
    finally:
        os.environ.pop("KEYSTONE_FAULTS", None)
        faults.reset()
    state, _ = load_checkpoint(ckpt)
    state["block_order"] = list(reversed(state["block_order"]))
    save_node(state, ckpt)

    calls = {"n": 0}

    def count_retry(attempt, exc):
        calls["n"] += 1

    with pytest.raises(ValueError, match="block order|order"):
        fit_streaming_elastic(
            est, nodes, raw, lbl, checkpoint_path=ckpt,
            checkpoint_every=1, retries=3, backoff_s=0.0,
            on_retry=count_retry,
        )
    assert calls["n"] == 0  # a schedule mismatch is not retriable


def test_elastic_discards_corrupt_checkpoint_and_refits(tmp_path):
    """A checkpoint that fails its checksum must not wedge the elastic
    path: the file is discarded (counted) and the fit restarts from
    scratch with zero manual intervention."""
    import os

    from keystone_tpu.telemetry import get_registry
    from keystone_tpu.utils import fit_streaming_elastic

    est, nodes, Slice, raw, lbl = _elastic_fixture()
    ref = est.fit_streaming(nodes, raw, lbl)

    ckpt = str(tmp_path / "corrupt.ckpt")
    from keystone_tpu.core.checkpoint import save_node

    save_node({"junk": np.arange(4096, dtype=np.float32)}, ckpt)
    blob = open(ckpt, "rb").read()
    with open(ckpt, "wb") as f:
        f.write(blob[: len(blob) // 2])  # truncate: checksum now fails

    reg = get_registry()
    d0 = reg.get_counter("checkpoint.corrupt_discarded")
    m = fit_streaming_elastic(
        est, nodes, raw, lbl, checkpoint_path=ckpt, checkpoint_every=1,
        retries=0, backoff_s=0.0,
    )
    assert reg.get_counter("checkpoint.corrupt_discarded") == d0 + 1
    np.testing.assert_array_equal(np.asarray(m.w), np.asarray(ref.w))
    assert not os.path.exists(ckpt)


def test_elastic_checkpoint_dir_knob_derives_path(tmp_path, monkeypatch):
    """checkpoint_path=None + KEYSTONE_CHECKPOINT_DIR derives a
    per-configuration file; without either, the call fails loudly (an
    elastic fit without a checkpoint cannot resume)."""
    from keystone_tpu.utils import fit_streaming_elastic

    est, nodes, Slice, raw, lbl = _elastic_fixture()
    with pytest.raises(ValueError, match="KEYSTONE_CHECKPOINT_DIR"):
        fit_streaming_elastic(est, nodes, raw, lbl)

    monkeypatch.setenv("KEYSTONE_CHECKPOINT_DIR", str(tmp_path))
    ref = est.fit_streaming(nodes, raw, lbl)
    m = fit_streaming_elastic(est, nodes, raw, lbl, backoff_s=0.0)
    np.testing.assert_array_equal(np.asarray(m.w), np.asarray(ref.w))
    # completed fit cleaned its auto-derived checkpoint out of the dir
    assert not any(p.suffix == ".ckpt" for p in tmp_path.iterdir())


def test_elastic_discards_non_checkpoint_garbage_too(tmp_path):
    """A pickle-loadable file that is NOT a checkpoint (leftover artifact
    at the path) raises plain CheckpointError, which must also be
    discarded-and-refit — only the intact-but-mismatched checkpoint class
    stays loud (deleting it could destroy another run's progress)."""
    import os
    import pickle

    from keystone_tpu.utils import fit_streaming_elastic

    est, nodes, Slice, raw, lbl = _elastic_fixture()
    ref = est.fit_streaming(nodes, raw, lbl)
    ckpt = str(tmp_path / "garbage.ckpt")
    with open(ckpt, "wb") as f:
        pickle.dump({"not": "a checkpoint"}, f)
    m = fit_streaming_elastic(
        est, nodes, raw, lbl, checkpoint_path=ckpt, checkpoint_every=1,
        retries=0, backoff_s=0.0,
    )
    np.testing.assert_array_equal(np.asarray(m.w), np.asarray(ref.w))
    assert not os.path.exists(ckpt)
