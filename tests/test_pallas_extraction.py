"""Pallas extraction-kernel family vs the XLA twins
(``ops/pallas/extraction.py``; interpreter mode on the CPU test mesh).

Every kernel is pinned against the UNTOUCHED prior XLA path on odd /
indivisible shapes (ragged tiles + lane padding + mask poison all engage),
at f32 tolerances. Knob semantics are pinned too: ``KEYSTONE_PALLAS=0``
must reproduce the exact prior program (selection resolves identically to
the knob-unset default on CPU), and ``=1`` must force every kernel on.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from keystone_tpu.learning.gmm import GaussianMixtureModel
from keystone_tpu.ops.images import fisher_vector as FV
from keystone_tpu.ops.images.convolver import Convolver
from keystone_tpu.ops.images.pooler import Pooler
from keystone_tpu.ops.images.sift import (
    SIFTExtractor,
    _dsift_single_scale,
    _resolve_impl_and_tile,
)
from keystone_tpu.ops.pallas import extraction as E


def _rel_close(a, b, tol=2e-5):
    a, b = np.asarray(a), np.asarray(b)
    denom = np.max(np.abs(b)) + 1e-9
    np.testing.assert_allclose(a / denom, b / denom, atol=tol)


def _gmm(rng, k, d):
    return GaussianMixtureModel(
        means=jnp.asarray(rng.normal(size=(k, d)).astype(np.float32)),
        variances=jnp.asarray(
            rng.uniform(0.5, 2.0, (k, d)).astype(np.float32)
        ),
        weights=jnp.asarray(rng.dirichlet(np.ones(k)).astype(np.float32)),
    )


# --------------------------------------------------------------------------
# knob semantics
# --------------------------------------------------------------------------


def test_knob_zero_is_the_exact_prior_path(monkeypatch):
    """KEYSTONE_PALLAS=0 and unset must resolve to the IDENTICAL static
    selection (and therefore the identical jit cache entry / HLO) on CPU —
    the HLO-level-no-op acceptance. =1 must force the kernels on."""
    node = SIFTExtractor()
    img = jnp.zeros((32, 32), jnp.float32)
    monkeypatch.delenv("KEYSTONE_PALLAS", raising=False)
    assert _resolve_impl_and_tile(node, img) == ("auto", 0, "f32", "unroll")
    assert FV._fv_moment_impl() == "f32"  # CPU default, prior behavior
    monkeypatch.setenv("KEYSTONE_PALLAS", "0")
    assert _resolve_impl_and_tile(node, img) == ("auto", 0, "f32", "unroll")
    assert FV._fv_moment_impl() == "f32"
    assert not E.pallas_enabled()
    assert not E.pallas_enabled(auto_ok=False)
    monkeypatch.setenv("KEYSTONE_PALLAS", "1")
    assert _resolve_impl_and_tile(node, img)[0] == "pallas"
    assert FV._fv_moment_impl() == "pallas"
    assert E.pallas_enabled() and E.pallas_enabled(auto_ok=False)
    # KEYSTONE_FV_IMPL stays the stronger force
    monkeypatch.setenv("KEYSTONE_FV_IMPL", "f32")
    assert FV._fv_moment_impl() == "f32"


def test_knob_validates():
    from keystone_tpu.utils import knobs

    import os

    os.environ["KEYSTONE_PALLAS"] = "yes"
    try:
        with pytest.raises(ValueError):
            knobs.get("KEYSTONE_PALLAS")
    finally:
        del os.environ["KEYSTONE_PALLAS"]


# --------------------------------------------------------------------------
# SIFT: fused binning × selection matmul
# --------------------------------------------------------------------------


@pytest.mark.parametrize("h,w", [(37, 53), (48, 48)])
def test_sift_pallas_matches_both_twins(h, w):
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.uniform(0, 1, (2, h, w)).astype(np.float32))
    args = (3, 4, 9, h, w)  # step, bin, min_bound at scale-0 geometry
    d_pl, m_pl = _dsift_single_scale(imgs, *args, "pallas", 16)
    d_mm, m_mm = _dsift_single_scale(imgs, *args, "matmul")
    d_wd, m_wd = _dsift_single_scale(imgs, *args, "window")
    _rel_close(d_pl, d_mm)
    _rel_close(m_pl, m_mm)
    _rel_close(d_pl, d_wd, tol=2e-4)  # window form sums in another order
    _rel_close(m_pl, m_wd, tol=2e-4)


def test_sift_extractor_end_to_end_knob(monkeypatch):
    """Whole extractor (all scales, layout, quantization) under the knob:
    quantized descriptors may differ by at most one 512x-floor step."""
    rng = np.random.default_rng(1)
    img = jnp.asarray(rng.uniform(0, 1, (47, 61)).astype(np.float32))
    node = SIFTExtractor()
    monkeypatch.delenv("KEYSTONE_PALLAS", raising=False)
    ref = np.asarray(node.apply(img))
    monkeypatch.setenv("KEYSTONE_PALLAS", "1")
    out = np.asarray(node.apply(img))
    assert out.shape == ref.shape == (node.num_descriptors(47, 61), 128)
    assert np.max(np.abs(out - ref)) <= 1.0


def test_sift_pallas_tile_independence():
    """The autotuned tile is a schedule choice, not a semantics choice."""
    rng = np.random.default_rng(2)
    imgs = jnp.asarray(rng.uniform(0, 1, (1, 41, 33)).astype(np.float32))
    a = _dsift_single_scale(imgs, 3, 4, 9, 41, 33, "pallas", 8)[0]
    b = _dsift_single_scale(imgs, 3, 4, 9, 41, 33, "pallas", 64)[0]
    _rel_close(a, b, tol=1e-6)


# --------------------------------------------------------------------------
# Fisher vector: fused posterior × moments
# --------------------------------------------------------------------------


@pytest.mark.parametrize(
    "lo_hi", [(0, 16), (1, 3), (9, 11), (7, 10)],
    ids=["full", "mean-only", "var-only", "straddle"],
)
def test_fv_pallas_matches_f32_twin(lo_hi):
    rng = np.random.default_rng(3)
    k, d, nd = 8, 12, 37  # nd indivisible by every tile candidate
    gmm = _gmm(rng, k, d)
    x = jnp.asarray(rng.normal(size=(3, nd, d)).astype(np.float32))
    lo, hi = lo_hi
    out = FV._fv_cols_batch_pallas(x, gmm, lo, hi)
    ref = FV._fv_cols_batch_f32(x, gmm, lo, hi)
    assert out.shape == ref.shape == (3, (hi - lo) * d)
    _rel_close(out, ref)


def test_fv_pallas_zero_rows():
    rng = np.random.default_rng(4)
    gmm = _gmm(rng, 4, 6)
    out = FV._fv_cols_batch_pallas(jnp.zeros((0, 9, 6)), gmm, 0, 8)
    assert out.shape == (0, 48)


def test_fv_dispatch_under_knob(monkeypatch):
    """_fv_cols_batch routes through the kernel under the knob and the
    result matches the default dispatch to f32 rounding — including the
    streaming L1-norm prepass built on top of it."""
    rng = np.random.default_rng(5)
    gmm = _gmm(rng, 6, 8)
    x = jnp.asarray(rng.normal(size=(4, 21, 8)).astype(np.float32))
    monkeypatch.delenv("KEYSTONE_PALLAS", raising=False)
    ref = FV._fv_cols_batch(x, gmm, 0, 12)
    l1_ref = FV.fisher_l1_norms(x, gmm, chunk=0)
    monkeypatch.setenv("KEYSTONE_PALLAS", "1")
    out = FV._fv_cols_batch(x, gmm, 0, 12)
    l1_out = FV.fisher_l1_norms(x, gmm, chunk=0)
    _rel_close(out, ref)
    _rel_close(l1_out, l1_ref)


# --------------------------------------------------------------------------
# Convolver: fused im2col + patch normalization
# --------------------------------------------------------------------------


@pytest.mark.parametrize("normalize", [True, False])
def test_conv_pallas_matches_xla_twin(normalize):
    rng = np.random.default_rng(6)
    k, c, nf = 5, 3, 7  # odd nf -> filter-tile padding engages
    imgs = jnp.asarray(rng.uniform(0, 1, (2, 17, 19, c)).astype(np.float32))
    filters = jnp.asarray(rng.normal(size=(nf, k * k * c)).astype(np.float32))
    conv = Convolver(
        filters=filters, num_channels=c, normalize_patches=normalize
    )
    ref = conv._apply_batch_xla(imgs)
    out = E.conv_norm(
        imgs, filters, num_channels=c, normalize=normalize,
        var_constant=10.0, tile_f=64, interpret=True,
    )
    assert out.shape == ref.shape
    _rel_close(out, ref)


def test_conv_pallas_with_whitener_and_knob(monkeypatch):
    from keystone_tpu.learning.zca import ZCAWhitener

    rng = np.random.default_rng(7)
    k, c, nf = 3, 3, 5
    imgs = jnp.asarray(rng.uniform(0, 1, (2, 11, 13, c)).astype(np.float32))
    filters = jnp.asarray(rng.normal(size=(nf, k * k * c)).astype(np.float32))
    wh = ZCAWhitener(
        means=jnp.asarray(rng.normal(size=(k * k * c,)).astype(np.float32)),
        whitener=jnp.eye(k * k * c),
    )
    conv = Convolver(filters=filters, whitener=wh, num_channels=c)
    monkeypatch.delenv("KEYSTONE_PALLAS", raising=False)
    ref = conv.apply_batch(imgs)
    monkeypatch.setenv("KEYSTONE_PALLAS", "1")
    out = conv.apply_batch(imgs)
    _rel_close(out, ref)
    # auto grade does NOT engage the conv kernel (explicit-only)
    monkeypatch.setenv("KEYSTONE_PALLAS", "auto")
    assert conv._pallas_plan(imgs) is None


def test_conv_pallas_vmem_fallback(monkeypatch):
    """An image too large for any filter tile falls back to the XLA twin
    instead of overcommitting VMEM."""
    monkeypatch.setenv("KEYSTONE_PALLAS", "1")
    rng = np.random.default_rng(8)
    conv = Convolver(
        filters=jnp.asarray(rng.normal(size=(4, 27)).astype(np.float32)),
        num_channels=3,
    )
    big = jnp.zeros((1, 1300, 1300, 3), jnp.float32)
    assert conv._pallas_plan(big) is None
    small = jnp.zeros((1, 16, 16, 3), jnp.float32)
    assert conv._pallas_plan(small) is not None


# --------------------------------------------------------------------------
# Pooler: fused pixel-fn + separable sum pooling
# --------------------------------------------------------------------------


def test_pool_pallas_matches_xla_twin_clamped_edges(monkeypatch):
    rng = np.random.default_rng(9)
    img = jnp.asarray(rng.normal(size=(27, 27, 5)).astype(np.float32))
    pool = Pooler(stride=13, pool_size=14, pool="sum")  # clamped windows
    monkeypatch.delenv("KEYSTONE_PALLAS", raising=False)
    ref = pool.apply(img)
    monkeypatch.setenv("KEYSTONE_PALLAS", "1")
    out = pool.apply(img)
    assert out.shape == ref.shape
    _rel_close(out, ref)


def test_pool_pallas_pixel_fn_and_batch(monkeypatch):
    rng = np.random.default_rng(10)
    imgs = jnp.asarray(rng.normal(size=(3, 13, 11, 5)).astype(np.float32))
    pool = Pooler(stride=3, pool_size=6, pixel_function=jnp.abs, pool="sum")
    monkeypatch.delenv("KEYSTONE_PALLAS", raising=False)
    ref = pool.apply_batch(imgs)
    monkeypatch.setenv("KEYSTONE_PALLAS", "1")
    out = pool.apply_batch(imgs)
    assert out.shape == ref.shape
    _rel_close(out, ref)


def test_pool_channel_mixing_pixel_fn_stays_correct(monkeypatch):
    """A shape-preserving but channel-MIXING pixel function must still be
    exact: the kernel hands it the full channel block (no tiling)."""
    rng = np.random.default_rng(11)
    imgs = jnp.asarray(rng.normal(size=(2, 9, 9, 4)).astype(np.float32))
    mix = lambda im: im[..., ::-1] + im.mean(axis=-1, keepdims=True)
    pool = Pooler(stride=2, pool_size=4, pixel_function=mix, pool="sum")
    monkeypatch.delenv("KEYSTONE_PALLAS", raising=False)
    ref = pool.apply_batch(imgs)
    monkeypatch.setenv("KEYSTONE_PALLAS", "1")
    out = pool.apply_batch(imgs)
    _rel_close(out, ref)


def test_pool_max_stays_on_xla_twin(monkeypatch):
    rng = np.random.default_rng(12)
    img = jnp.asarray(rng.normal(size=(12, 12, 3)).astype(np.float32))
    pool = Pooler(stride=2, pool_size=4, pool="max")
    monkeypatch.setenv("KEYSTONE_PALLAS", "1")
    assert not pool._pallas_ok(img)
    monkeypatch.delenv("KEYSTONE_PALLAS", raising=False)
    ref = pool.apply(img)
    monkeypatch.setenv("KEYSTONE_PALLAS", "1")
    np.testing.assert_array_equal(np.asarray(pool.apply(img)), np.asarray(ref))


def test_pool_shape_changing_pixel_fn_rejected(monkeypatch):
    """A pixel function that changes the output shape fails the eval_shape
    probe, so the kernel never engages for it (the XLA twin itself has
    never supported shape-changing pixel functions — its output assert
    predates this PR)."""
    monkeypatch.setenv("KEYSTONE_PALLAS", "1")
    rng = np.random.default_rng(13)
    img = jnp.asarray(rng.normal(size=(8, 8, 2)).astype(np.float32))
    doubler = lambda im: jnp.concatenate([im, im], axis=-1)
    pool = Pooler(stride=2, pool_size=4, pixel_function=doubler, pool="sum")
    assert not pool._pallas_ok(img)
