"""Row-chunked streaming BlockLeastSquares: exact equivalence pins.

The chunked path (``fit_streaming(row_chunk=...)`` +
``fit_node_scaler_chunked``) is what runs the FULL reference TIMIT config
(2.2M frames; ``TimitPipeline.scala:23-34``) on one chip — no (n, 4096)
feature block ever materializes. Centering is affine, so the chunked
closed-form gram/cross must match the in-core formulation to float
tolerance across masking, multiple epochs, and the gram-cache switch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.core.dataset import pad_rows
from keystone_tpu.core.pipeline import chain
from keystone_tpu.learning import BlockLeastSquaresEstimator
from keystone_tpu.ops.stats import CosineRandomFeatures, StandardScaler
from keystone_tpu.ops.stats.scaler import fit_node_scaler_chunked


def _nodes_and_data(rng, n=200, d=12, b=16, nblocks=3, mask_tail=0):
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n, 5)).astype(np.float32)
    mask = None
    if mask_tail:
        x, _ = (np.asarray(a) for a in pad_rows(jnp.asarray(x), n + mask_tail))
        y, _ = (np.asarray(a) for a in pad_rows(jnp.asarray(y), n + mask_tail))
        mask = np.zeros(n + mask_tail, np.float32)
        mask[:n] = 1.0
    keys = jax.random.split(jax.random.key(0), nblocks)
    nodes = []
    for k in range(nblocks):
        rf = CosineRandomFeatures.create(d, b, 0.1, keys[k])
        scaler = StandardScaler().fit(
            rf(jnp.asarray(x)),
            mask=None if mask is None else jnp.asarray(mask),
        )
        nodes.append(chain(rf, scaler))
    return nodes, jnp.asarray(x), jnp.asarray(y), (
        None if mask is None else jnp.asarray(mask)
    )


@pytest.mark.parametrize("num_iter,cache_grams", [(1, True), (3, True), (3, False)])
@pytest.mark.parametrize("mask_tail", [0, 7])
def test_chunked_matches_unchunked(rng, num_iter, cache_grams, mask_tail):
    nodes, x, y, mask = _nodes_and_data(rng, mask_tail=mask_tail)
    est = BlockLeastSquaresEstimator(16, num_iter, 0.1, cache_grams=cache_grams)
    ref = est.fit_streaming(nodes, x, y, mask=mask)
    # chunk 64 does not divide 200/207: the ragged tail path runs too
    got = est.fit_streaming(nodes, x, y, mask=mask, row_chunk=64)
    scale = np.abs(np.asarray(ref.w)).max()
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), atol=5e-5 * scale + 1e-6
    )
    np.testing.assert_allclose(
        np.asarray(got.feature_means), np.asarray(ref.feature_means),
        atol=1e-5,
    )
    np.testing.assert_allclose(np.asarray(got.b), np.asarray(ref.b), atol=1e-6)


@pytest.mark.parametrize("mask_tail", [0, 5])
@pytest.mark.parametrize("normalize", [True, False])
def test_chunked_scaler_matches_incore(rng, mask_tail, normalize):
    x = rng.normal(size=(150, 10)).astype(np.float32)
    mask = None
    if mask_tail:
        x = np.concatenate([x, 99.0 * np.ones((mask_tail, 10), np.float32)])
        mask = np.concatenate(
            [np.ones(150, np.float32), np.zeros(mask_tail, np.float32)]
        )
    rf = CosineRandomFeatures.create(10, 24, 0.2, jax.random.key(1))
    ref = StandardScaler(normalize_std_dev=normalize).fit(
        rf(jnp.asarray(x)), mask=None if mask is None else jnp.asarray(mask)
    )
    got = fit_node_scaler_chunked(
        rf, jnp.asarray(x), None if mask is None else jnp.asarray(mask),
        chunk=64, normalize_std_dev=normalize,
    )
    np.testing.assert_allclose(
        np.asarray(got.mean), np.asarray(ref.mean), rtol=1e-5, atol=1e-6
    )
    if normalize:
        np.testing.assert_allclose(
            np.asarray(got.std), np.asarray(ref.std), rtol=1e-4, atol=1e-6
        )
    else:
        assert got.std is None and ref.std is None


def test_timit_pipeline_chunked_matches_unchunked(rng):
    """End-to-end: the TIMIT pipeline with row_chunk on vs off must reach
    the same test error (same math, different tiling)."""
    from keystone_tpu.pipelines.timit import TimitConfig, run

    base = dict(
        synthetic_train=600, synthetic_test=200, num_cosines=3,
        num_cosine_features=32, num_epochs=2,
    )
    ref = run(TimitConfig(**base))
    got = run(TimitConfig(**base, row_chunk=128))
    assert abs(ref["test_error"] - got["test_error"]) < 0.51  # same up to ties
