"""keystone-race (keystone_tpu/analysis/concurrency.py): rule fixtures
T1-T5 over the lockgraph model, the R5 -> T3 pragma alias, stale-pragma
scoping, the baseline ratchet, the CLI exit contract, and the repo-wide
invariant that the shipped tree sweeps clean against its committed
``race_baseline.json``.

Rule tests run the real engine over tiny fixture trees written to
``tmp_path`` — one positive (must flag) and one negative (must stay
silent) per rule family — mirroring tests/test_lint.py.
"""

import io
import json
import os
import textwrap
from contextlib import redirect_stdout

from keystone_tpu.analysis.concurrency import (
    ALL_RACE_RULES,
    RaceEngine,
    default_paths,
    run_race,
)
from keystone_tpu.analysis.concurrency import main as race_main
from keystone_tpu.analysis.engine import (
    apply_baseline,
    load_baseline,
    save_baseline,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def race_tree(tmp_path, files):
    """Write {relpath: source} under tmp_path and run the engine on it."""
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return RaceEngine(str(tmp_path), sorted(files)).run()


def rules_of(result):
    return sorted({f.rule for f in result.findings})


# ---------------------------------------------------------------------------
# T1: lock-order inversion
# ---------------------------------------------------------------------------

T1_POSITIVE = """
    import threading

    a_lock = threading.Lock()
    b_lock = threading.Lock()


    def forward():
        with a_lock:
            with b_lock:
                return 1


    def backward():
        with b_lock:
            with a_lock:
                return 2
"""


def test_t1_flags_inversion(tmp_path):
    res = race_tree(tmp_path, {"pkg/mod.py": T1_POSITIVE})
    t1 = [f for f in res.findings if f.rule == "T1"]
    assert t1, rules_of(res)
    assert "a_lock" in t1[0].message and "b_lock" in t1[0].message


def test_t1_silent_on_consistent_order(tmp_path):
    res = race_tree(tmp_path, {"pkg/mod.py": """
        import threading

        a_lock = threading.Lock()
        b_lock = threading.Lock()


        def one():
            with a_lock:
                with b_lock:
                    return 1


        def two():
            with a_lock:
                with b_lock:
                    return 2
    """})
    assert not [f for f in res.findings if f.rule == "T1"], rules_of(res)


def test_t1_inversion_through_called_function(tmp_path):
    """The acquisition graph follows resolvable calls: holding ``a`` and
    calling a function that takes ``b`` is an a->b edge even with no
    lexically nested ``with``."""
    res = race_tree(tmp_path, {"pkg/mod.py": """
        import threading

        a_lock = threading.Lock()
        b_lock = threading.Lock()


        def take_b():
            with b_lock:
                return 1


        def forward():
            with a_lock:
                return take_b()


        def backward():
            with b_lock:
                with a_lock:
                    return 2
    """})
    assert [f for f in res.findings if f.rule == "T1"], rules_of(res)


# ---------------------------------------------------------------------------
# T2: blocking call while holding a lock
# ---------------------------------------------------------------------------

def test_t2_flags_blocking_under_lock(tmp_path):
    res = race_tree(tmp_path, {"pkg/mod.py": """
        import queue
        import threading
        import time

        work_lock = threading.Lock()
        q = queue.Queue()


        def bad_get():
            with work_lock:
                return q.get()


        def bad_sleep():
            with work_lock:
                time.sleep(5)


        def bad_send(sock, frame):
            with work_lock:
                sock.sendall(frame)
    """})
    t2 = [f for f in res.findings if f.rule == "T2"]
    tails = {f.symbol.split("->")[-1] for f in t2}
    assert tails == {"get", "sleep", "sendall"}, t2


def test_t2_silent_on_bounded_and_lookalike_calls(tmp_path):
    """timeout= kwargs, dict.get(key), str.join(iterable), and a
    Condition.wait on the HELD condition (which releases it) are all
    exempt — the PR-15 class is the indefinite wait only."""
    res = race_tree(tmp_path, {"pkg/mod.py": """
        import queue
        import threading

        cond = threading.Condition()
        work_lock = threading.Lock()
        q = queue.Queue()
        TABLE = {}


        def ok_bounded():
            with work_lock:
                return q.get(timeout=0.5)


        def ok_dict_get(key):
            with work_lock:
                return TABLE.get(key, None)


        def ok_join(parts):
            with work_lock:
                return ",".join(parts)


        def ok_cond_wait():
            with cond:
                cond.wait()
    """})
    assert not [f for f in res.findings if f.rule == "T2"], res.findings


# ---------------------------------------------------------------------------
# T3: unguarded shared state (generalizes + subsumes lint R5)
# ---------------------------------------------------------------------------

T3_POSITIVE = """
    import threading

    state_lock = threading.Lock()
    RESULTS = []


    def publish(x):
        RESULTS.append(x)
"""


def test_t3_flags_unguarded_mutation_in_concurrent_module(tmp_path):
    res = race_tree(tmp_path, {"pkg/mod.py": T3_POSITIVE})
    t3 = [f for f in res.findings if f.rule == "T3"]
    assert t3 and "RESULTS" in t3[0].message


def test_t3_silent_under_lock_and_out_of_scope(tmp_path):
    res = race_tree(tmp_path, {
        # guarded mutation: silent
        "pkg/guarded.py": """
            import threading

            state_lock = threading.Lock()
            RESULTS = []


            def publish(x):
                with state_lock:
                    RESULTS.append(x)
        """,
        # no entry point, no module-level lock: out of scope, silent
        # even though the mutation is bare
        "pkg/sequential.py": """
            CACHE = []


            def remember(x):
                CACHE.append(x)
        """,
    })
    assert not [f for f in res.findings if f.rule == "T3"], res.findings


def test_t3_honors_existing_r5_pragma(tmp_path):
    """The R5 -> T3 alias: a ``# lint: disable=R5`` pragma written for
    lint keeps suppressing at the same site under race — existing
    justifications carry over without a rewrite — and the R5-only pragma
    is NOT race's stale-pragma business."""
    src = T3_POSITIVE.replace(
        "RESULTS.append(x)",
        "RESULTS.append(x)  # lint: disable=R5 (single-writer by design)",
    )
    res = race_tree(tmp_path, {"pkg/mod.py": src})
    assert not [f for f in res.findings if f.rule == "T3"], res.findings
    assert res.suppressed == 1
    assert res.stale_pragmas == []


def test_t3_native_pragma_and_stale_scoping(tmp_path):
    """A ``disable=T3`` pragma suppresses like any lint pragma; one that
    suppresses nothing IS reported stale (T rules are race's scope),
    while a bare ``disable`` is left to lint to police."""
    res = race_tree(tmp_path, {"pkg/mod.py": """
        import threading

        state_lock = threading.Lock()
        RESULTS = []


        def publish(x):
            RESULTS.append(x)  # lint: disable=T3 (single writer)


        def quiet(x):
            return x  # lint: disable=T2 (nothing blocks here)


        def also_quiet(x):
            return x  # lint: disable
    """})
    assert not [f for f in res.findings if f.rule == "T3"], res.findings
    assert res.suppressed == 1
    assert len(res.stale_pragmas) == 1
    assert res.stale_pragmas[0][2] == "T2"


# ---------------------------------------------------------------------------
# T4: thread lifecycles
# ---------------------------------------------------------------------------

def test_t4_flags_spawn_under_lock_and_unjoined_thread(tmp_path):
    res = race_tree(tmp_path, {"pkg/mod.py": """
        import subprocess
        import threading

        spawn_lock = threading.Lock()


        def launch():
            t = threading.Thread(target=print)
            t.start()
            with spawn_lock:
                subprocess.run(["true"])
    """})
    t4 = [f for f in res.findings if f.rule == "T4"]
    symbols = {f.symbol for f in t4}
    assert "spawn_lock->spawn" in symbols, t4
    assert "thread@t" in symbols, t4


def test_t4_silent_on_daemon_joined_and_pool_joined(tmp_path):
    res = race_tree(tmp_path, {"pkg/mod.py": """
        import subprocess
        import threading


        def ok():
            d = threading.Thread(target=print, daemon=True)
            d.start()
            j = threading.Thread(target=print)
            j.start()
            j.join()
            pool = [threading.Thread(target=print) for _ in range(3)]
            for t in pool:
                t.start()
            for t in pool:
                t.join(30)
            subprocess.run(["true"])
    """})
    assert not [f for f in res.findings if f.rule == "T4"], res.findings


# ---------------------------------------------------------------------------
# T5: unlocked read-merge-replace
# ---------------------------------------------------------------------------

def test_t5_flags_read_merge_replace_without_flock(tmp_path):
    res = race_tree(tmp_path, {"pkg/mod.py": """
        import json
        import os


        def bump(path):
            try:
                with open(path) as f:
                    data = json.load(f)
            except OSError:
                data = {}
            data["n"] = data.get("n", 0) + 1
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, path)
    """})
    t5 = [f for f in res.findings if f.rule == "T5"]
    assert t5 and t5[0].symbol == "bump"


def test_t5_silent_with_flock_sidecar(tmp_path):
    res = race_tree(tmp_path, {"pkg/mod.py": """
        import json
        import os


        def bump(path):
            import fcntl

            lockf = open(path + ".lock", "w")
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                with open(path) as f:
                    data = json.load(f)
            except OSError:
                data = {}
            data["n"] = data.get("n", 0) + 1
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, path)
            lockf.close()
    """})
    assert not [f for f in res.findings if f.rule == "T5"], res.findings


def test_bench_cursor_rotation_is_flocked():
    """Regression for the genuine T5 finding this pass surfaced: the
    bench secondary-section cursor read->increment->replace now runs
    under the flock sidecar, so the sweep must stay silent on bench.py."""
    res = RaceEngine(REPO_ROOT, ["bench.py"]).run()
    t5 = [f for f in res.findings if f.rule == "T5"]
    assert not t5, t5


# ---------------------------------------------------------------------------
# Baseline ratchet + CLI
# ---------------------------------------------------------------------------

def test_baseline_ratchet(tmp_path):
    res = race_tree(tmp_path, {"pkg/mod.py": T1_POSITIVE})
    assert res.findings
    baseline_path = tmp_path / "race_baseline.json"
    save_baseline(str(baseline_path), res.findings, tool="race")
    baseline = json.loads(baseline_path.read_text())
    assert baseline["findings"]

    # same tree re-swept: everything baselined, nothing new
    res2 = race_tree(tmp_path, {"pkg/mod.py": T1_POSITIVE})
    new, known, stale = apply_baseline(
        res2.findings, load_baseline(str(baseline_path))
    )
    assert new == [] and known and not stale

    # fixed tree (both sites order a -> b): nothing new, the old
    # fingerprints count as stale
    res3 = race_tree(tmp_path, {"pkg/mod.py": """
        import threading

        a_lock = threading.Lock()
        b_lock = threading.Lock()


        def forward():
            with a_lock:
                with b_lock:
                    return 1


        def backward():
            with a_lock:
                with b_lock:
                    return 2
    """})
    assert res3.findings == []
    new, known, stale = apply_baseline(
        res3.findings, load_baseline(str(baseline_path))
    )
    assert new == [] and stale


def test_cli_exit_codes(tmp_path, capsys):
    (tmp_path / "mod.py").write_text(textwrap.dedent(T1_POSITIVE))

    # findings, no baseline: rc=1 with the clickable triple
    rc = race_main(["--root", str(tmp_path), "mod.py"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "mod.py:" in out and "T1" in out

    # ratchet reset: rc=0, baseline written
    rc = race_main(["--root", str(tmp_path), "--update-baseline", "mod.py"])
    assert rc == 0
    assert (tmp_path / "race_baseline.json").exists()
    capsys.readouterr()

    # same debt, now baselined: rc=0
    rc = race_main(["--root", str(tmp_path), "mod.py"])
    assert rc == 0
    capsys.readouterr()

    # JSON format carries the schema
    rc = race_main(["--root", str(tmp_path), "--format", "json", "mod.py"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    for key in ("new", "baselined", "stale", "suppressed", "files",
                "errors", "total"):
        assert key in payload

    # a file that does not parse: rc=2
    (tmp_path / "broken.py").write_text("def f(:\n")
    rc = race_main(["--root", str(tmp_path), "broken.py"])
    assert rc == 2
    capsys.readouterr()


# ---------------------------------------------------------------------------
# The shipped tree
# ---------------------------------------------------------------------------

def test_repo_sweeps_clean_against_committed_baseline():
    """The tier-1 invariant `make race` enforces: zero new findings over
    the real tree vs the committed (empty) race_baseline.json."""
    baseline = os.path.join(REPO_ROOT, "race_baseline.json")
    assert os.path.exists(baseline), "race_baseline.json must be committed"
    result = run_race(REPO_ROOT, default_paths(REPO_ROOT),
                      baseline_path=baseline)
    assert result.errors == [], result.errors
    assert result.findings == [], [
        f"{f.path}:{f.line} {f.rule} {f.message}" for f in result.findings
    ]
    # the ratchet starts EMPTY: the tree carries no baselined race debt
    assert result.baselined == []


def test_committed_fixtures_fire_every_rule():
    """The detectors cannot silently rot: each committed bad fixture in
    tests/fixtures/race/ keeps firing its rule."""
    res = RaceEngine(REPO_ROOT, ["tests/fixtures/race"]).run()
    assert not res.errors, res.errors
    assert {f.rule for f in res.findings} == set(ALL_RACE_RULES)
