import jax


def test_entry_compiles_and_runs():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)


def test_dryrun_multichip_8(capsys):
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)
    assert "mesh={'data': 4, 'model': 2}" in capsys.readouterr().out
