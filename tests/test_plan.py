"""Cost-based whole-pipeline planner (core/plan.py) + the DAG
generalization of Chain (core/pipeline.py).

Pins the ISSUE-8 acceptance surface: estimate-vs-profile plan parity on a
toy DAG, the HBM budget as a binding (and exactly computed) constraint,
plan-off => the prior program untouched (no plan consulted, hand segment
boundaries, hand block sizes), and explicit knobs beating planned values.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.core import plan
from keystone_tpu.core.cache import IntermediateCache, use_cache
from keystone_tpu.core.pipeline import (
    Cacher,
    Chain,
    ConcatFeatures,
    Transformer,
    chain,
    chain_to_dag,
    dag,
)
from keystone_tpu.learning.pca import PCATransformer
from keystone_tpu.telemetry import get_registry, get_tracer, use_tracing


class Affine(Transformer):
    w: jax.Array

    def apply(self, x):
        return x @ self.w

    apply_batch = apply


class Host(Transformer):
    jittable = False

    def apply(self, x):
        return x

    def apply_batch(self, xs):
        return jax.block_until_ready(xs)


def _mats(d=256, k=64):
    rng = np.random.default_rng(0)
    return (
        jnp.asarray(rng.normal(size=(d, k)).astype(np.float32)),
        jnp.asarray(rng.normal(size=(d, k)).astype(np.float32)),
    )


def _toy_dag(n=512, d=256, k=64):
    w1, w2 = _mats(d, k)
    pipe = dag(
        [Affine(w=w1), Affine(w=w2), ConcatFeatures()],
        [(-1,), (-1,), (0, 1)],
    )
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    return pipe, x


# ---------------------------------------------------------------------------
# DAG execution semantics
# ---------------------------------------------------------------------------

def test_dag_matches_eager_composition():
    pipe, x = _toy_dag()
    w1, w2 = pipe.nodes[0].w, pipe.nodes[1].w
    expect = jnp.concatenate([x @ w1, x @ w2], axis=-1)
    np.testing.assert_allclose(np.asarray(pipe(x)), np.asarray(expect),
                               rtol=1e-6)
    # single-item serving path agrees with the bulk path
    np.testing.assert_allclose(
        np.asarray(pipe.serve(x[0])), np.asarray(expect[0]), rtol=1e-6
    )


def test_dag_fan_out_and_host_boundary_segmentation():
    """A host node is a materialization boundary; jittable runs on either
    side fuse. Observed through the span names (one span per segment)."""
    w1, w2 = _mats()
    pipe = dag(
        [Affine(w=w1), Host(), Affine(w=w2.T), ConcatFeatures()],
        [(-1,), (0,), (1,), (1, 2)],
    )
    x = jnp.ones((32, 256), jnp.float32)
    get_tracer().reset()
    with use_tracing(True):
        out = pipe(x)
    assert out.shape == (32, 256 + 64)
    names = [s["name"] for s in get_tracer().spans_as_dicts()
             if s["name"].startswith("stage:")]
    # Affine | Host boundary | Affine+Concat fused into ONE program
    assert names == ["stage:Affine", "stage:Host",
                     "stage:Affine+ConcatFeatures"]


def test_dag_validation_errors():
    w1, _ = _mats()
    with pytest.raises(ValueError, match="topological"):
        dag([Affine(w=w1)], [(1,)])
    with pytest.raises(TypeError, match="Merge"):
        dag([Affine(w=w1), Affine(w=w1)], [(-1,), (-1, 0)])
    with pytest.raises(ValueError, match="dependency lists"):
        dag([Affine(w=w1)], [])


def test_dag_cache_point_memoizes_and_skips_producer():
    """A cache_after point stores the intermediate; the repeat call serves
    it and never re-executes the producing subgraph (fewer stage spans)."""
    pipe, x = _toy_dag()
    pipe = pipe.replace(cache_after=(0,))
    cache = IntermediateCache(cache_dir=None)
    with use_cache(cache):
        out1 = pipe(x)
        first_hits = cache.stats.hits
        out2 = pipe(x)  # whole-output hit
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert cache.stats.hits > first_hits
    assert cache.stats.computes == 1
    # drop the whole-output entry, keep the node-0 intermediate: the rerun
    # must resume from it (node 0 skipped) and still be exact
    with use_cache(cache):
        whole_key = pipe._prefix_key(2, __import__(
            "keystone_tpu.core.cache", fromlist=["fingerprint"]
        ).fingerprint(x))
        e = cache._entries.pop(whole_key)
        cache._tier_bytes[e.tier] -= e.nbytes
        get_tracer().reset()
        with use_tracing(True):
            out3 = pipe(x)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out3))
    names = [s["name"] for s in get_tracer().spans_as_dicts()
             if s["name"].startswith("stage:")]
    assert "stage:Affine+ConcatFeatures" in names[-1]
    assert not any(n == "stage:Affine" for n in names)  # node 0 skipped


def test_chain_to_dag_preserves_semantics():
    w1, w2 = _mats()
    c = chain(Affine(w=w1), Cacher(), Affine(w=w2.T))
    d = chain_to_dag(c)
    assert d.cache_after == (0,)
    x = jnp.ones((16, 256), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(c(x)), np.asarray(d(x)), rtol=1e-6
    )


# ---------------------------------------------------------------------------
# Cost table + decisions
# ---------------------------------------------------------------------------

def test_cost_table_shapes_consumers_and_bounds():
    pipe, x = _toy_dag(n=512, d=256, k=64)
    costs = plan.pipeline_costs(pipe, x, mode="estimate")
    assert [c.consumers for c in costs] == [1, 1, 1]
    # fan-out counted
    pipe2 = dag(
        [pipe.nodes[0], pipe.nodes[1], ConcatFeatures(), ConcatFeatures()],
        [(-1,), (-1,), (0, 1), (0, 2)],
    )
    costs2 = plan.pipeline_costs(pipe2, x, mode="estimate")
    assert costs2[0].consumers == 2
    c0 = costs[0]
    assert c0.out_bytes == 512 * 64 * 4
    assert c0.in_bytes == 512 * 256 * 4
    assert c0.peak_hbm_bytes is not None and c0.peak_hbm_bytes >= (
        c0.in_bytes + c0.out_bytes
    )
    assert all(c.source == "estimate" for c in costs)


def test_unbounded_stage_is_reported_not_fatal():
    class Weird(Transformer):
        jittable = False

        def apply_batch(self, xs):
            # data-dependent shape: abstract evaluation cannot bound it
            return xs[: int(np.asarray(xs)[0, 0]) + 1]

        def apply(self, x):
            return x

    w1, _ = _mats()
    c = chain(Weird(), Affine(w=w1))
    p = plan.plan_pipeline(
        c, jnp.ones((8, 256), jnp.float32), mode="estimate",
        budget_bytes=1 << 30,
    )
    assert p.bounded is False
    assert p.fits is False  # an unbounded stage can never prove fit
    assert p.stages[0].peak_hbm_bytes is None


def test_estimate_vs_profile_plan_parity_on_toy_dag():
    """After a traced run, profile mode replans from measured spans and
    lands on the SAME decisions (segments, cache tiers, shardings, block
    sizes) as estimate mode — the cost source changes, the plan does not."""
    pipe, x = _toy_dag(n=2048, d=512, k=256)
    sites = [dict(site="s", n_rows=2048, num_classes=16, default=512,
                  quantum=64, ceiling=1024)]
    budget = 64 << 20
    est = plan.plan_pipeline(pipe, x, mode="estimate", budget_bytes=budget,
                             block_sites=sites)
    get_tracer().reset()
    with use_tracing(True):
        pipe(x)
    prof = plan.plan_pipeline(pipe, x, mode="profile", budget_bytes=budget,
                              block_sites=sites)
    assert any(s.source == "profile" for s in prof.stages)
    assert [s.segment for s in prof.stages] == [s.segment for s in est.stages]
    assert [s.cache_tier for s in prof.stages] == [
        s.cache_tier for s in est.stages
    ]
    assert [s.sharding for s in prof.stages] == [
        s.sharding for s in est.stages
    ]
    assert prof.block_sizes == est.block_sizes


def test_cache_decision_and_apply_plan_round_trip():
    """A reused expensive intermediate gets a device-tier cache decision;
    apply_plan materializes it as a cache point that actually hits."""
    w1, w2 = _mats(1024, 512)
    pipe = dag(
        [Affine(w=w1), ConcatFeatures(), ConcatFeatures()],
        [(-1,), (0, 0), (0, 1)],
    )
    x = jnp.ones((4096, 1024), jnp.float32)
    p = plan.plan_pipeline(pipe, x, mode="estimate", budget_bytes=8 << 30)
    assert p.stages[0].cache_tier == "device"
    planned = plan.apply_plan(pipe, p)
    assert 0 in planned.cache_after
    cache = IntermediateCache(cache_dir=None)
    with use_cache(cache):
        planned(x)
        planned(x)
    assert cache.stats.hits >= 1


def test_apply_plan_replaces_hand_cachers_from_cost():
    """The headline KeystoneML semantic: hand cache points are re-decided.
    A Cacher after a CHEAP stage is declined (gone from the planned
    chain); one after an expensive stage survives as a planned point."""
    w_cheap, _ = _mats(8, 4)
    c = chain(Affine(w=w_cheap), Cacher(), Affine(w=w_cheap.T))
    x = jnp.ones((16, 8), jnp.float32)
    p = plan.plan_pipeline(c, x, mode="estimate", budget_bytes=1 << 30)
    assert len(p.stages) == 2  # Cacher stripped from the cost table
    assert all(s.cache_tier is None for s in p.stages)  # declined
    planned = plan.apply_plan(c, p)
    assert not any(isinstance(s, Cacher) for s in planned.stages)
    assert len(planned.stages) == 2
    # expensive + re-consumed: the hand point is re-confirmed by cost
    w_big, _ = _mats(1024, 1024)
    c2 = chain(Affine(w=w_big), Cacher(), Affine(w=w_big))
    x2 = jnp.ones((8192, 1024), jnp.float32)
    p2 = plan.plan_pipeline(c2, x2, mode="estimate", budget_bytes=8 << 30)
    assert p2.stages[0].cache_tier == "device"
    planned2 = plan.apply_plan(c2, p2)
    assert any(isinstance(s, Cacher) for s in planned2.stages)


def test_apply_plan_dag_materializes_segment_splits():
    """A budget-forced segment split must survive apply_plan on a DAG:
    the executed program materializes at the planned boundary instead of
    fusing past the peak the plan was scored on."""
    w1, _ = _mats(1024, 1024)
    pipe = dag(
        [Affine(w=w1), Affine(w=w1), Affine(w=w1)],
        [(-1,), (0,), (1,)],
    )
    x = jnp.ones((8192, 1024), jnp.float32)
    budget = 80 << 20  # three 32 MB intermediates cannot stay fused
    p = plan.plan_pipeline(pipe, x, mode="estimate", budget_bytes=budget)
    assert p.num_segments > 1
    planned = plan.apply_plan(pipe, p)
    assert planned.cache_after  # the split is a materialization point
    get_tracer().reset()
    with use_tracing(True):
        out = planned(x)
    assert out.shape == (8192, 1024)
    seg_spans = [s["name"] for s in get_tracer().spans_as_dicts()
                 if s["name"].startswith("stage:")]
    assert len(seg_spans) == p.num_segments  # executed as planned


def test_sharding_boundary_flips_at_wide_feature_stage():
    """The first stage whose 2-D feature output is wider than tall (the
    d >= n solver regime) flips the plan to 'model' sharding onward —
    the data->model boundary."""
    w_small = jnp.zeros((2048, 256), jnp.float32)
    w_big = jnp.zeros((256, 16384), jnp.float32)
    c = chain(Affine(w=w_small), Affine(w=w_big))
    x = jnp.ones((512, 2048), jnp.float32)
    p = plan.plan_pipeline(c, x, mode="estimate", budget_bytes=8 << 30)
    assert p.stages[0].sharding == "data"  # (512, 256): rows dominate
    assert p.stages[1].sharding == "model"  # (512, 16384): d >= n


# ---------------------------------------------------------------------------
# HBM budget: binding constraint, exact arithmetic
# ---------------------------------------------------------------------------

def test_hbm_budget_is_binding_and_exactly_computed():
    n, classes, quantum, default = 8192, 64, 64, 4096
    budget = 48 << 20

    def peak(b):
        return plan.block_solve_peak_bytes(b, n_rows=n, num_classes=classes)

    chosen = plan.hbm_safe_block_size(
        n_rows=n, num_classes=classes, budget_bytes=budget,
        default=default, quantum=quantum,
    )
    assert chosen < default  # binding
    assert peak(chosen) <= budget  # provably fits
    assert peak(chosen + quantum) > budget  # and is maximal
    # no budget -> the hand default stands
    assert plan.hbm_safe_block_size(
        n_rows=n, num_classes=classes, budget_bytes=None,
        default=default, quantum=quantum,
    ) == default
    # impossible budget -> the quantum floor, never a wedge
    assert plan.hbm_safe_block_size(
        n_rows=n, num_classes=classes, budget_bytes=1024,
        default=default, quantum=quantum,
    ) == quantum


def test_plan_fits_flag_tracks_budget():
    pipe, x = _toy_dag(n=4096, d=1024, k=512)
    small = plan.plan_pipeline(pipe, x, mode="estimate",
                               budget_bytes=1 << 20)
    big = plan.plan_pipeline(pipe, x, mode="estimate",
                             budget_bytes=8 << 30)
    assert not small.fits
    assert big.fits
    # the tight budget forces more materialization boundaries (segment
    # splitting at the largest intermediates), never a wedge
    assert small.num_segments >= big.num_segments
    assert small.est_peak_hbm_bytes <= big.est_peak_hbm_bytes


# ---------------------------------------------------------------------------
# Knob precedence: explicit > env > planned > default; off == prior program
# ---------------------------------------------------------------------------

def test_resolve_block_size_precedence(monkeypatch):
    kw = dict(n_rows=100_000, num_classes=100, default=4096)
    monkeypatch.delenv("KEYSTONE_OPTIMIZER", raising=False)
    monkeypatch.delenv("KEYSTONE_BLOCK_SIZE", raising=False)
    # off -> hand default, no plan consulted
    assert plan.resolve_block_size("t", **kw) == 4096
    monkeypatch.setenv("KEYSTONE_OPTIMIZER", "estimate")
    monkeypatch.setenv("KEYSTONE_HBM_BUDGET", "64")
    planned = plan.resolve_block_size("t", **kw)
    assert planned != 4096  # the budget binds at these dims
    monkeypatch.setenv("KEYSTONE_BLOCK_SIZE", "512")
    assert plan.resolve_block_size("t", **kw) == 512  # env beats planned
    assert plan.resolve_block_size("t", explicit=777, **kw) == 777


def test_resolve_cache_blocks_precedence(monkeypatch):
    kw = dict(n_rows=100_000, block_size=4096, itemsize=2, default=2)
    monkeypatch.delenv("KEYSTONE_OPTIMIZER", raising=False)
    assert plan.resolve_cache_blocks("t", **kw) == 2
    assert plan.resolve_cache_blocks("t", explicit=0, **kw) == 0
    monkeypatch.setenv("KEYSTONE_OPTIMIZER", "estimate")
    monkeypatch.setenv("KEYSTONE_HBM_BUDGET", "16384")
    v = plan.resolve_cache_blocks("t", **kw)
    assert 0 <= v <= 8
    assert plan.resolve_cache_blocks("t", explicit=4, **kw) == 4


def test_optimizer_off_is_the_prior_program(monkeypatch):
    """KEYSTONE_OPTIMIZER=0: maybe_plan returns None, the hand Cacher
    segmentation stands untouched, and the lowered segment HLO is the
    plain Chain program (no planner artifacts)."""
    monkeypatch.delenv("KEYSTONE_OPTIMIZER", raising=False)
    assert plan.enabled() is False
    w1, w2 = _mats()
    c = chain(Affine(w=w1), Cacher(), Affine(w=w2.T))
    x = jnp.ones((16, 256), jnp.float32)
    assert plan.maybe_plan(c, x) is None
    get_tracer().reset()
    with use_tracing(True):
        c(x)
    names = [s["name"] for s in get_tracer().spans_as_dicts()
             if s["name"].startswith("stage:")]
    # the PRIOR segmentation: jit segment | hand Cacher | jit segment
    assert names == ["stage:Affine", "stage:Cacher", "stage:Affine"]
    from keystone_tpu.core.pipeline import _jit_apply_batch

    hlo = _jit_apply_batch.lower(
        Chain(stages=(Affine(w=w1),)), x
    ).as_text()
    assert "dot" in hlo  # the same single-matmul program as ever


def test_knob_wins_over_plan_in_pipeline_config(monkeypatch):
    """The migrated pipelines: explicit config block size is passed through
    verbatim even with the optimizer on (documented precedence)."""
    from keystone_tpu.pipelines.mnist_random_fft import MnistRandomFFTConfig

    monkeypatch.setenv("KEYSTONE_OPTIMIZER", "estimate")
    monkeypatch.setenv("KEYSTONE_HBM_BUDGET", "8")
    cfg = MnistRandomFFTConfig(block_size=1024)
    assert cfg.resolved_block_size(60000) == 1024
    auto = MnistRandomFFTConfig()
    planned = auto.resolved_block_size(60000)
    assert planned % 512 == 0  # the FFT-width quantum is honored
    monkeypatch.delenv("KEYSTONE_OPTIMIZER")
    monkeypatch.delenv("KEYSTONE_HBM_BUDGET")
    assert auto.resolved_block_size(60000) == 2048  # prior hand value


# ---------------------------------------------------------------------------
# Plan cache + export + CLI
# ---------------------------------------------------------------------------

def test_plan_cache_zero_replans(tmp_path, monkeypatch):
    pipe, x = _toy_dag()
    cache_path = str(tmp_path / "plan_cache.json")
    reg = get_registry()
    before = reg.get_counter("plan.computed")
    kw = dict(mode="estimate", budget_bytes=1 << 30, cache_path=cache_path)
    p1 = plan.plan_pipeline(pipe, x, **kw)
    assert reg.get_counter("plan.computed") == before + 1
    p2 = plan.plan_pipeline(pipe, x, **kw)
    assert reg.get_counter("plan.computed") == before + 1  # memo hit
    assert p2.fingerprint == p1.fingerprint
    # fresh-process simulation: memo cleared, disk cache serves
    with plan._PLAN_LOCK:
        plan._PLAN_MEMO.clear()
    disk_hits = reg.get_counter("plan.cache_hit", tier="disk")
    p3 = plan.plan_pipeline(pipe, x, **kw)
    assert reg.get_counter("plan.computed") == before + 1
    assert reg.get_counter("plan.cache_hit", tier="disk") == disk_hits + 1
    assert p3.to_json() == p1.to_json()


def test_plan_json_round_trip(tmp_path):
    pipe, x = _toy_dag()
    p = plan.plan_pipeline(pipe, x, mode="estimate", budget_bytes=1 << 30)
    path = str(tmp_path / "plan.json")
    p.save(path)
    with open(path) as f:
        loaded = plan.Plan.from_json(json.load(f))
    assert loaded.to_json() == p.to_json()
    assert "segments" in p.summary() or p.num_segments >= 1


def test_plan_cli_toy(tmp_path, capsys):
    out_json = str(tmp_path / "p.json")
    rc = plan.main(["toy", "--smoke", "--budget-mb", "64",
                    "--json", out_json])
    assert rc == 0
    text = capsys.readouterr().out
    assert "block_size[toy.solver]" in text
    with open(out_json) as f:
        artifact = json.load(f)
    assert artifact["fits"] is True
    assert artifact["block_sizes"]["toy.solver"] > 0
