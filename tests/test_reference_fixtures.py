"""Parity tests against the reference's own test fixtures.

The reference repo ships miniature real datasets and solver matrices under
``src/test/resources`` (SURVEY.md §4); these tests run the *same assertions
its suites make* — exact loader counts/labels (``VOCLoaderSuite.scala:10-33``,
``ImageNetLoaderSuite.scala:10-27``), the weighted-solver zero-gradient
invariant on the same aMat/bMat matrices
(``BlockWeightedLeastSquaresSuite.scala:63-95``), and the VOC codebook GMM
load (``EncEvalSuite.scala:17-23``) — through this framework's loaders and
solvers. Skipped when the reference checkout isn't mounted.
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

_RES = "/root/reference/src/test/resources"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(_RES), reason="reference fixtures not mounted"
)


def test_voc_loader_parity():
    """VOCLoaderSuite.scala:18-32: 10 images; 000104.jpg has labels {14,19};
    13 labels total, 9 distinct."""
    from keystone_tpu.loaders.voc import load_voc_labels
    from keystone_tpu.native import PrefetchImageLoader

    labels_map = load_voc_labels(os.path.join(_RES, "images/voclabels.csv"))
    loader = PrefetchImageLoader(
        [os.path.join(_RES, "images/voc/voctest.tar")], 128, 128, 2
    )
    seen = {}
    for imgs, names in loader.batches(64):
        for i, name in enumerate(names):
            if name.startswith("VOCdevkit/VOC2007/JPEGImages/") and name in labels_map:
                seen[name.split("/")[-1]] = (imgs[i], labels_map[name])

    assert len(seen) == 10
    assert "000104.jpg" in seen
    img, labels = seen["000104.jpg"]
    assert img.shape == (128, 128, 3) and np.isfinite(img).all()
    assert set(labels) == {14, 19}
    all_labels = [l for _, ls in seen.values() for l in ls]
    assert len(all_labels) == 13
    assert len(set(all_labels)) == 9


def test_imagenet_loader_parity():
    """ImageNetLoaderSuite.scala:12-26: 5 images, every label 12, filenames
    under n15075141."""
    from keystone_tpu.loaders.imagenet import load_imagenet

    imgs, labels = load_imagenet(
        os.path.join(_RES, "images/imagenet"),
        os.path.join(_RES, "images/imagenet-test-labels"),
        target_hw=(128, 128),
        num_threads=2,
    )
    assert imgs.shape == (5, 128, 128, 3)
    assert np.isfinite(imgs).all()
    assert (labels == 12).all()


def test_jpeg_decode_matches_pil():
    """The native libjpeg decode and PIL agree on the fixture photo (the two
    ingest paths must be interchangeable downstream)."""
    from keystone_tpu.native import ingest

    with open(os.path.join(_RES, "images/000012.jpg"), "rb") as f:
        raw = f.read()
    if ingest._get_lib() is None:
        pytest.skip("native ingest unavailable; PIL fallback is the path")
    via_native = ingest.decode_jpeg(raw)  # native path (lib present)
    from PIL import Image
    import io

    via_pil = np.asarray(Image.open(io.BytesIO(raw)).convert("RGB"))
    assert via_native is not None
    assert via_native.shape == via_pil.shape
    # both are IDCT'd JPEG pixels; small per-pixel rounding differences only
    assert np.mean(np.abs(via_native.astype(int) - via_pil.astype(int))) < 2.0


def _load_voc_codebook():
    from keystone_tpu.learning.gmm import GaussianMixtureModel

    return GaussianMixtureModel.load(
        os.path.join(_RES, "images/voc_codebook/means.csv"),
        os.path.join(_RES, "images/voc_codebook/variances.csv"),
        os.path.join(_RES, "images/voc_codebook/priors"),
    )


def test_voc_codebook_gmm_and_fisher_vector():
    """EncEvalSuite.scala:17-38 against the one reference-blessed numeric
    artifact in the checkout (the pretrained 256x80 VOC codebook): the FV
    encoding must EQUAL the ``jax.grad`` Fisher-score oracle value-by-value
    (not just in shape) — any change to the FV math fails this."""
    import jax

    from keystone_tpu.learning.gmm import GaussianMixtureModel
    from keystone_tpu.ops.images.fisher_vector import FisherVector

    gmm = _load_voc_codebook()
    assert gmm.means.shape == (256, 80)
    assert gmm.variances.shape == (256, 80)
    assert gmm.weights.shape == (256,)
    assert float(jnp.sum(gmm.weights)) == pytest.approx(1.0, abs=1e-3)
    assert float(jnp.min(gmm.variances)) > 0.0

    # descriptors in the codebook's own operating range: perturbations of
    # its centers (pure noise at offset 100 sits in no component's support)
    rng = np.random.default_rng(0)
    comp = rng.choice(256, 500)  # one draw: center AND noise from the
    descs = jnp.asarray(         # same component, so samples stay in-support
        np.asarray(gmm.means)[comp]
        + rng.normal(size=(500, 80)) * np.sqrt(np.asarray(gmm.variances)[comp])
    ).astype(jnp.float32)

    fv = np.asarray(FisherVector(gmm=gmm).apply(descs))
    assert fv.shape == (80, 512)
    assert bool(np.isfinite(fv).all())

    def mean_ll(means, variances):
        g = GaussianMixtureModel(
            means=means, variances=variances, weights=gmm.weights
        )
        ll = g.log_likelihoods(descs)
        return jnp.mean(jax.scipy.special.logsumexp(ll, axis=1))

    g_mu, g_var = jax.grad(mean_ll, argnums=(0, 1))(gmm.means, gmm.variances)
    sigma = np.sqrt(np.asarray(gmm.variances))
    w = np.asarray(gmm.weights)
    expect_mu = (np.asarray(g_mu) * sigma / np.sqrt(w)[:, None]).T
    expect_sig = (
        2.0 * np.asarray(g_var) * np.asarray(gmm.variances)
        / np.sqrt(2.0 * w)[:, None]
    ).T
    # scale-relative tolerance: the oracle differentiates the raw (not
    # centered-affine) log-density, so agreement is to f32 conditioning
    scale = max(np.abs(expect_mu).max(), np.abs(expect_sig).max())
    np.testing.assert_allclose(fv[:, :256], expect_mu, atol=2e-4 * scale)
    np.testing.assert_allclose(fv[:, 256:], expect_sig, atol=2e-4 * scale)


def test_voc_codebook_posteriors_match_sklearn():
    """Posterior responsibilities under the pretrained codebook cross-checked
    against ``sklearn.mixture.GaussianMixture.predict_proba`` carrying the
    SAME Gaussians — an implementation-independent E-step oracle."""
    from sklearn.mixture import GaussianMixture

    gmm = _load_voc_codebook()
    rng = np.random.default_rng(1)
    centers = np.asarray(gmm.means)[rng.choice(256, 300)]
    descs = (centers + rng.normal(size=(300, 80)) * 3.0).astype(np.float32)

    sk = GaussianMixture(256, covariance_type="diag")
    sk.means_ = np.asarray(gmm.means, np.float64)
    sk.covariances_ = np.asarray(gmm.variances, np.float64)
    sk.weights_ = np.asarray(gmm.weights, np.float64)
    from sklearn.mixture._gaussian_mixture import _compute_precision_cholesky

    sk.precisions_cholesky_ = _compute_precision_cholesky(
        sk.covariances_, "diag"
    )
    want = sk.predict_proba(descs)
    got = np.asarray(gmm.apply_batch(jnp.asarray(descs)))
    np.testing.assert_allclose(got, want, atol=1e-4)


def _load_fixture_mats():
    a = np.loadtxt(os.path.join(_RES, "aMat.csv"), delimiter=",")
    b = np.loadtxt(os.path.join(_RES, "bMat.csv"), delimiter=",")
    return a.astype(np.float32), b.astype(np.float32)


def test_block_weighted_zero_gradient_on_fixture():
    """BlockWeightedLeastSquaresSuite.scala:71-95 with the same matrices and
    config (blockSize=4, numIter=10, lambda=0.1, mixtureWeight=0.3): the
    fitted model's weighted-least-squares gradient has ~zero norm.
    """
    from keystone_tpu.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )

    A, B = _load_fixture_mats()
    lam, mw = 0.1, 0.3
    n, d = A.shape
    c = B.shape[1]

    model = BlockWeightedLeastSquaresEstimator(
        block_size=4, num_iter=10, lam=lam, mixture_weight=mw
    ).fit(jnp.asarray(A), jnp.asarray(B))
    W = np.asarray(model.w)
    b0 = np.asarray(model.b)

    # independent gradient recomputation (computeGradient, suite lines 18-55)
    cls = B.argmax(1)
    counts = np.bincount(cls, minlength=c)
    wts = np.full((n, c), (1.0 - mw) / n)
    for i in range(n):
        wts[i, cls[i]] += mw / counts[cls[i]]
    resid = (A @ W + b0) - B
    grad = A.T @ (resid * wts) + lam * W
    assert np.linalg.norm(grad) < 1e-2


def test_least_squares_fixture_recovery():
    """Ridge regression on the same fixture matrices agrees with an
    independent numpy solve (LinearMapperSuite-style check on real data)."""
    from keystone_tpu.linalg.solvers import normal_equations_solve

    A, B = _load_fixture_mats()
    lam = 0.01
    w_ne = np.asarray(normal_equations_solve(jnp.asarray(A), jnp.asarray(B), lam=lam))
    w_np = np.linalg.solve(A.T @ A + lam * np.eye(A.shape[1]), A.T @ B)
    np.testing.assert_allclose(w_ne, w_np, rtol=0, atol=5e-3 * np.abs(w_np).max())


def test_solver_precision_parity_on_fixture():
    """The default solver precision (bf16x3) against the 6-pass
    f32-equivalent on the reference's real aMat/bMat matrices (round-1
    ADVICE: synthetic parity tests can't see the bf16x3 gram error). On CPU
    backends the MXU pass count is moot (all matmuls are f32) so this pins
    the plumbing; the same check run on a real v5e chip measures ~1.1e-4
    max relative weight deviation at lam∈{0.01, 1e-5} (recorded in
    BASELINE.md)."""
    from keystone_tpu.linalg.solvers import (
        get_solver_precision,
        normal_equations_solve,
        set_solver_precision,
    )

    A, B = _load_fixture_mats()
    lam = 0.01
    prev = get_solver_precision()
    try:
        set_solver_precision("highest")
        w_hi = np.asarray(normal_equations_solve(jnp.asarray(A), jnp.asarray(B), lam=lam))
        set_solver_precision("high")
        w_def = np.asarray(normal_equations_solve(jnp.asarray(A), jnp.asarray(B), lam=lam))
    finally:
        set_solver_precision(prev)
    rel = np.abs(w_def - w_hi).max() / np.abs(w_hi).max()
    assert rel < 1e-3, f"bf16x3 vs highest relative deviation {rel:.2e}"


def test_lda_on_iris_fixture():
    """LinearDiscriminantAnalysisSuite used iris.data; class separation in
    the discriminant space must be near-perfect for the two separable pairs."""
    from keystone_tpu.learning.lda import LinearDiscriminantAnalysis

    rows, labels = [], []
    name_to_id: dict = {}
    with open(os.path.join(_RES, "iris.data")) as f:
        for line in f:
            parts = line.strip().split(",")
            if len(parts) != 5:
                continue
            rows.append([float(v) for v in parts[:4]])
            labels.append(name_to_id.setdefault(parts[4], len(name_to_id)))
    x = jnp.asarray(np.asarray(rows, np.float32))
    y = jnp.asarray(np.asarray(labels, np.int32))

    mapper = LinearDiscriminantAnalysis(num_dims=2).fit(x, y)
    z = np.asarray(mapper(x))
    # class centroids well-separated relative to within-class scatter
    cents = np.stack([z[np.asarray(y) == k].mean(0) for k in range(3)])
    within = np.mean([z[np.asarray(y) == k].std(0).mean() for k in range(3)])
    d01 = np.linalg.norm(cents[0] - cents[1])
    assert d01 / within > 5.0


def test_voc_pipeline_end_to_end_on_reference_tar():
    """Full VOCSIFTFisher on the reference's own miniature VOC archive
    (VOCSIFTFisher.scala:21-104): real JPEG decode → SIFT → PCA → GMM → FV →
    BlockLeastSquares → MeanAveragePrecision, no synthetic anywhere in the
    path (VERDICT round-1 item 3)."""
    from keystone_tpu.pipelines.voc_sift_fisher import (
        VOCSIFTFisherConfig,
        run as run_voc,
    )

    cfg = VOCSIFTFisherConfig(
        train_location=os.path.join(_RES, "images/voc/voctest.tar"),
        train_labels=os.path.join(_RES, "images/voclabels.csv"),
        test_location=os.path.join(_RES, "images/voc/voctest.tar"),
        test_labels=os.path.join(_RES, "images/voclabels.csv"),
        desc_dim=16,
        vocab_size=4,
        num_pca_samples=4000,
        num_gmm_samples=4000,
        sift_scales=2,
        image_hw=128,
        lam=0.5,
        block_size=256,
    )
    res = run_voc(cfg)
    # 10 real images, train==test; the fixture covers 9 of 20 VOC classes
    # (VOCLoaderSuite.scala:18-32) and absent classes contribute AP=0, so a
    # perfectly-ranking model scores exactly 9/20 = 0.45 mean AP. Measured:
    # 0.45 — at ceiling. Assert ≥89% of ceiling (real ranking signal; a
    # random scorer sits far below).
    assert np.isfinite(res["test_map"])
    assert 0.0 <= res["test_map"] <= 1.0
    assert res["test_map"] > 0.4


def test_imagenet_pipeline_end_to_end_on_reference_tar():
    """Full ImageNetSiftLcsFV (both branches + weighted BCD) on the
    reference's miniature ImageNet archive (ImageNetSiftLcsFV.scala:150-196):
    real JPEGs end to end, evaluator output asserted."""
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        run as run_imagenet,
    )

    cfg = ImageNetSiftLcsFVConfig(
        train_location=os.path.join(_RES, "images/imagenet"),
        train_labels=os.path.join(_RES, "images/imagenet-test-labels"),
        test_location=os.path.join(_RES, "images/imagenet"),
        test_labels=os.path.join(_RES, "images/imagenet-test-labels"),
        sift_pca_dim=16,
        lcs_pca_dim=16,
        vocab_size=4,
        num_pca_samples=4000,
        num_gmm_samples=4000,
        image_hw=128,
        lam=1e-3,
        block_size=256,
    )
    res = run_imagenet(cfg)
    # Single-synset archive (label 12 for every image): a fitted model must
    # rank the true class in its top-5 on the training images themselves.
    assert res["test_top5_error"] == 0.0
    assert np.isfinite(res["test_top1_error"])


def test_imagenet_streaming_pipeline_on_reference_tar():
    """The flagship out-of-core path on REAL data: the reference's miniature
    ImageNet archive through chunked JPEG ingest → SIFT+LCS → PCA/GMM →
    Fisher cache-grouped block nodes → Woodbury weighted BCD → streaming
    eval. Same archive as the in-core test above; this pins that streaming
    mode (fit_streaming + grouped FisherVectorSliceNormalized) is not a
    synthetic-only configuration."""
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        run as run_imagenet,
    )

    cfg = ImageNetSiftLcsFVConfig(
        train_location=os.path.join(_RES, "images/imagenet"),
        train_labels=os.path.join(_RES, "images/imagenet-test-labels"),
        test_location=os.path.join(_RES, "images/imagenet"),
        test_labels=os.path.join(_RES, "images/imagenet-test-labels"),
        sift_pca_dim=16,
        lcs_pca_dim=16,
        vocab_size=4,
        num_pca_samples=4000,
        num_gmm_samples=4000,
        image_hw=128,
        lam=1e-3,
        block_size=32,
        streaming=True,
        extract_chunk=4,
        sample_images=8,
        fv_row_chunk=4,
        fv_cache_blocks=2,
        desc_dtype="float32",
    )
    res = run_imagenet(cfg)
    assert res["feature_dim"] == 2 * (16 + 16) * 4
    assert res["test_top5_error"] == 0.0
    assert np.isfinite(res["test_top1_error"])


def test_voc_bucketed_pipeline_on_reference_tar():
    """VOCSIFTFisher through size-bucketed variable-shape ingest (>=2
    buckets, no global resize): per-bucket static shapes through SIFT with
    descriptor counts exactly ``SIFTExtractor.num_descriptors(bh, bw)``, one
    PCA/GMM pooled across buckets, FV rows concatenated — the wiring of
    ``native.BucketedImageLoader`` into the pipeline (VERDICT round-2 weak
    #2 / next #2; reference native-size processing:
    ``loaders/ImageLoaderUtils.scala:47-93``)."""
    from keystone_tpu.loaders.voc import load_voc_bucketed
    from keystone_tpu.ops.images import SIFTExtractor
    from keystone_tpu.pipelines.voc_sift_fisher import (
        VOCSIFTFisherConfig,
        run as run_voc,
    )

    buckets = "340x500,400x500"
    groups = load_voc_bucketed(
        os.path.join(_RES, "images/voc/voctest.tar"),
        os.path.join(_RES, "images/voclabels.csv"),
        [(340, 500), (400, 500)],
    )
    # the fixture archive must genuinely exercise BOTH buckets
    assert len(groups) == 2, [hw for hw, _, _ in groups]
    assert sum(imgs.shape[0] for _, imgs, _ in groups) == 10

    cfg = VOCSIFTFisherConfig(
        train_location=os.path.join(_RES, "images/voc/voctest.tar"),
        train_labels=os.path.join(_RES, "images/voclabels.csv"),
        test_location=os.path.join(_RES, "images/voc/voctest.tar"),
        test_labels=os.path.join(_RES, "images/voclabels.csv"),
        desc_dim=16,
        vocab_size=4,
        num_pca_samples=4000,
        num_gmm_samples=4000,
        sift_scales=2,
        buckets=buckets,
        lam=0.5,
        block_size=256,
    )
    res = run_voc(cfg)
    assert np.isfinite(res["test_map"])
    assert res["test_map"] > 0.4  # same ranking bar as the single-frame e2e
    ext = SIFTExtractor(scales=2)
    assert set(res["buckets"]) == {"340x500", "400x500"}
    for key, info in res["buckets"].items():
        bh, bw = map(int, key.split("x"))
        assert info["descriptors"] == ext.num_descriptors(bh, bw)
        assert info["images"] > 0


def test_imagenet_bucketed_pipeline_on_reference_tar():
    """ImageNetSiftLcsFV (both branches) through >=2 size buckets on the
    reference archive — no global resize, per-bucket descriptor counts
    asserted for SIFT and LCS."""
    from keystone_tpu.ops.images import LCSExtractor, SIFTExtractor
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        run as run_imagenet,
    )

    cfg = ImageNetSiftLcsFVConfig(
        train_location=os.path.join(_RES, "images/imagenet"),
        train_labels=os.path.join(_RES, "images/imagenet-test-labels"),
        test_location=os.path.join(_RES, "images/imagenet"),
        test_labels=os.path.join(_RES, "images/imagenet-test-labels"),
        sift_pca_dim=16,
        lcs_pca_dim=16,
        vocab_size=4,
        num_pca_samples=4000,
        num_gmm_samples=4000,
        buckets="400x500,500x500",
        lam=1e-3,
        block_size=256,
    )
    res = run_imagenet(cfg)
    assert res["test_top5_error"] == 0.0  # single-synset archive, as in-core
    assert len(res["buckets"]) == 2, res["buckets"]
    sift = SIFTExtractor()
    lcs = LCSExtractor(cfg.lcs_stride, cfg.lcs_border, cfg.lcs_patch)
    for key, info in res["buckets"].items():
        bh, bw = map(int, key.split("x"))
        assert info["sift_descriptors"] == sift.num_descriptors(bh, bw)
        assert info["lcs_descriptors"] == lcs.num_keypoints(bh, bw)
        assert info["images"] > 0


def test_imagenet_bucketed_streaming_pipeline_on_reference_tar():
    """Bucketed ingest THROUGH the streaming (out-of-core) solver on the
    reference archive: per-bucket resident descriptors + BucketConcatNode
    blocks through fit_streaming — variable-size real data and the flagship
    solver path in one configuration (closes the 'bucketed is in-core only'
    limitation)."""
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        run as run_imagenet,
    )

    cfg = ImageNetSiftLcsFVConfig(
        train_location=os.path.join(_RES, "images/imagenet"),
        train_labels=os.path.join(_RES, "images/imagenet-test-labels"),
        test_location=os.path.join(_RES, "images/imagenet"),
        test_labels=os.path.join(_RES, "images/imagenet-test-labels"),
        sift_pca_dim=16,
        lcs_pca_dim=16,
        vocab_size=4,
        num_pca_samples=4000,
        num_gmm_samples=4000,
        # three-bucket ladder whose FIRST bucket no fixture image fits:
        # ladder alignment must carry the empty bucket through extraction,
        # reduction, nodes, and eval without a row/label mismatch
        buckets="120x120,400x500,500x500",
        streaming=True,
        extract_chunk=4,
        fv_row_chunk=2,
        fv_cache_blocks=2,
        lam=1e-3,
        block_size=128,  # = one branch width (2*4*16): one block per branch
    )
    res = run_imagenet(cfg)
    assert res["buckets"]["120x120"] == 0  # empty ladder bucket carried
    assert res["buckets"]["400x500"] + res["buckets"]["500x500"] == 5
    # single-synset archive: the fitted model must put the true class in
    # its top-5 on the training images themselves (as the in-core e2e does)
    assert res["test_top5_error"] == 0.0
    assert np.isfinite(res["test_top1_error"])
    assert res["feature_dim"] == 2 * (16 + 16) * 4
