"""Sparse featurization + Naive Bayes + Newsgroups pipeline tests.

Reference suites: ``nodes/misc/TermFrequencySuite.scala``,
``nodes/util/CommonSparseFeaturesSuite`` analogs, and the canonical
composition chain of ``pipelines/text/NewsgroupsPipeline.scala:24-32``.
"""

import numpy as np
import pytest

from keystone_tpu.learning.naive_bayes import NaiveBayesEstimator
from keystone_tpu.loaders.newsgroups import load_newsgroups, synthetic_newsgroups
from keystone_tpu.ops.util.sparse import (
    AllSparseFeatures,
    CommonSparseFeatures,
    SparseBatch,
    TermFrequency,
)
from keystone_tpu.pipelines.newsgroups import NewsgroupsConfig, run


class TestTermFrequency:
    def test_counts(self):
        tf = TermFrequency()
        out = dict(tf.apply(["a", "b", "a", "a"]))
        assert out == {"a": 3.0, "b": 1.0}

    def test_weight_fn(self):
        tf = TermFrequency(fn=lambda c: 1.0)
        out = dict(tf.apply(["a", "a", "b"]))
        assert out == {"a": 1.0, "b": 1.0}


class TestSparseFeatures:
    def test_common_top_k(self):
        docs = [[("a", 5.0), ("b", 1.0)], [("a", 2.0), ("c", 3.0)], [("b", 1.0)]]
        vec = CommonSparseFeatures(2).fit(docs)
        assert set(vec.feature_index) == {"a", "c"}  # totals: a=7, c=3, b=2
        assert vec.feature_index["a"] == 0

    def test_all_features(self):
        docs = [[("x", 1.0)], [("y", 2.0), ("x", 1.0)]]
        vec = AllSparseFeatures().fit(docs)
        assert set(vec.feature_index) == {"x", "y"}

    def test_vectorize_roundtrip(self):
        docs = [[("a", 2.0), ("c", 1.0)], [("b", 4.0)], []]
        vec = AllSparseFeatures().fit(docs)
        batch = vec(docs)
        assert isinstance(batch, SparseBatch)
        dense = np.asarray(batch.to_dense())
        expected = np.zeros((3, 3), np.float32)
        expected[0, vec.feature_index["a"]] = 2.0
        expected[0, vec.feature_index["c"]] = 1.0
        expected[1, vec.feature_index["b"]] = 4.0
        np.testing.assert_allclose(dense, expected)

    def test_unknown_terms_dropped(self):
        vec = CommonSparseFeatures(1).fit([[("a", 5.0)], [("b", 1.0)]])
        batch = vec([[("b", 3.0), ("a", 1.0)]])
        dense = np.asarray(batch.to_dense())
        assert dense.shape == (1, 1)
        assert dense[0, 0] == 1.0  # only 'a' survives


class TestNaiveBayes:
    def test_matches_hand_computation(self):
        # 2 classes, 3 features, lambda=1 — compute theta/pi by hand
        X = np.array([[2.0, 0.0, 1.0], [1.0, 1.0, 0.0], [0.0, 3.0, 1.0]], np.float32)
        y = np.array([0, 0, 1])
        model = NaiveBayesEstimator(2, lam=1.0).fit(X, y)
        T = np.array([[3.0, 1.0, 1.0], [0.0, 3.0, 1.0]])
        theta = np.log(T + 1) - np.log(T.sum(1, keepdims=True) + 3)
        pi = np.log(np.array([2.0, 1.0]) + 1) - np.log(3.0 + 2.0)
        np.testing.assert_allclose(np.asarray(model.theta), theta, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(model.pi), pi, rtol=1e-5)
        # scoring: log pi + theta.x
        scores = np.asarray(model.apply_batch(X))
        np.testing.assert_allclose(scores, pi[None] + X @ theta.T, rtol=1e-5)

    def test_sparse_matches_dense(self, rng):
        n, v, c = 40, 12, 3
        dense = (rng.random((n, v)) < 0.3) * rng.integers(1, 4, (n, v))
        dense = dense.astype(np.float32)
        y = rng.integers(0, c, n).astype(np.int32)
        docs = [
            [(j, float(dense[i, j])) for j in range(v) if dense[i, j] > 0]
            for i in range(n)
        ]
        vec_fit = AllSparseFeatures().fit(docs)
        batch = vec_fit(docs)
        # remap dense columns into the fitted feature order
        perm = [vec_fit.feature_index[j] for j in range(v)]
        dense_perm = np.zeros_like(dense)
        dense_perm[:, perm] = dense
        m_sparse = NaiveBayesEstimator(c).fit(batch, y)
        m_dense = NaiveBayesEstimator(c).fit(dense_perm, y)
        np.testing.assert_allclose(
            np.asarray(m_sparse.theta), np.asarray(m_dense.theta), rtol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(m_sparse.apply_batch(batch)),
            np.asarray(m_dense.apply_batch(dense_perm)),
            rtol=1e-4,
        )


class TestLoader:
    def test_directory_loader(self, tmp_path):
        for cls, texts in [("rec.autos", ["car fast", "wheel"]), ("sci.med", ["doc"])]:
            d = tmp_path / cls
            d.mkdir()
            for i, t in enumerate(texts):
                (d / f"{i}.txt").write_text(t)
        docs, labels, names = load_newsgroups(str(tmp_path))
        assert names == ["rec.autos", "sci.med"]
        assert len(docs) == 3
        assert labels.tolist() == [0, 0, 1]

    def test_synthetic_separable(self):
        docs, labels, names = synthetic_newsgroups(50, num_classes=4)
        assert len(docs) == 50 and len(names) == 4
        assert set(labels) <= set(range(4))


def test_newsgroups_pipeline_end_to_end():
    res = run(
        NewsgroupsConfig(
            synthetic_train=400,
            synthetic_test=100,
            synthetic_classes=5,
            common_features=5000,
        )
    )
    assert res["test_error"] < 10.0  # synthetic topics are separable
    assert res["macro_f1"] > 0.9


class TestFastTextEquivalence:
    """The fused integer-key path (ops/nlp/fast_text.py) must produce the
    same features as the reference-shaped tuple chain."""

    def _tuple_chain(self, docs, orders, k):
        from keystone_tpu.core.pipeline import chain
        from keystone_tpu.ops.nlp import LowerCase, NGramsFeaturizer, Tokenizer, Trim
        from keystone_tpu.ops.util.sparse import binary_weight

        feat = chain(
            Trim(),
            LowerCase(),
            Tokenizer("[\\s]+"),
            NGramsFeaturizer(orders=orders),
            TermFrequency(fn=binary_weight),
        )
        tf = feat(docs)
        vec = CommonSparseFeatures(k).fit(tf)
        return feat, vec, vec(tf)

    @staticmethod
    def _row_sets(batch):
        """Per-doc {feature-column-fingerprint: weight} with columns identified
        by their (sorted) per-corpus value pattern, not by id."""
        dense = np.asarray(batch.to_dense())
        cols = [tuple(dense[:, j]) for j in range(dense.shape[1])]
        return sorted(cols)

    def test_matches_tuple_chain_untruncated(self):
        from keystone_tpu.ops.nlp import EncodedCommonSparseFeatures

        docs, labels, _ = synthetic_newsgroups(120, num_classes=4, seed=7)
        docs = list(docs) + ["", "   ", "one", "repeat repeat repeat"]
        orders = (1, 2)
        _, _, ref_batch = self._tuple_chain(docs, orders, 10**6)
        vec, fast_batch = EncodedCommonSparseFeatures(
            orders=orders, num_features=10**6, weight="binary"
        ).fit_transform(docs)
        assert fast_batch.num_features == ref_batch.num_features
        assert self._row_sets(fast_batch) == self._row_sets(ref_batch)

    def test_matches_tuple_chain_on_test_docs_with_oov(self):
        from keystone_tpu.ops.nlp import EncodedCommonSparseFeatures

        train, _, _ = synthetic_newsgroups(100, num_classes=3, seed=1)
        test, _, _ = synthetic_newsgroups(30, num_classes=3, seed=2)
        test = list(test) + ["totally unseen words xyzzy", ""]
        orders = (1, 2, 3)
        feat, ref_vec, _ = self._tuple_chain(train, orders, 10**6)
        fast_vec = EncodedCommonSparseFeatures(
            orders=orders, num_features=10**6, weight="binary"
        ).fit(train)
        ref_batch = ref_vec(feat(test))
        fast_batch = fast_vec(test)
        assert self._row_sets(fast_batch) == self._row_sets(ref_batch)

    def test_topk_truncation_totals_match(self):
        from keystone_tpu.ops.nlp import EncodedCommonSparseFeatures

        docs, _, _ = synthetic_newsgroups(80, num_classes=4, seed=3)
        k = 50
        _, ref_vec, ref_batch = self._tuple_chain(docs, (1, 2), k)
        _, fast_batch = EncodedCommonSparseFeatures(
            orders=(1, 2), num_features=k, weight="binary"
        ).fit_transform(docs)
        assert fast_batch.num_features == ref_batch.num_features == k
        # selected features' doc-frequency multisets agree (ties at the cut
        # may pick different-but-equal-count terms)
        ref_tot = sorted(np.asarray(ref_batch.to_dense()).sum(0))
        fast_tot = sorted(np.asarray(fast_batch.to_dense()).sum(0))
        np.testing.assert_allclose(fast_tot, ref_tot)

    def test_count_weighting(self):
        from keystone_tpu.ops.nlp import EncodedCommonSparseFeatures

        docs = ["a a a b", "a b b", "c"]
        vec, batch = EncodedCommonSparseFeatures(
            orders=(1,), num_features=100, weight="count"
        ).fit_transform(docs)
        dense = np.asarray(batch.to_dense())
        # totals: a=4, b=3, c=1 -> ids 0,1,2 by descending total
        np.testing.assert_allclose(dense[:, 0], [3.0, 1.0, 0.0])  # 'a'
        np.testing.assert_allclose(dense[:, 1], [1.0, 2.0, 0.0])  # 'b'
        np.testing.assert_allclose(dense[:, 2], [0.0, 0.0, 1.0])  # 'c'

    def test_pipeline_both_host_paths_agree(self):
        # common_features above the distinct-n-gram count: no truncation cut,
        # so both paths select identical feature sets and the comparison is
        # tie-free (at a truncating cut the two paths break count ties among
        # different-but-equal-frequency n-grams, legitimately).
        cfg = dict(
            synthetic_train=200,
            synthetic_test=60,
            synthetic_classes=4,
            common_features=10**6,
        )
        fast = run(NewsgroupsConfig(fast_host_path=True, device_path=False, **cfg))
        slow = run(NewsgroupsConfig(fast_host_path=False, device_path=False, **cfg))
        assert fast["test_error"] == slow["test_error"]
        assert fast["train_error"] == slow["train_error"]

    def test_overflow_guard(self):
        from keystone_tpu.ops.nlp.fast_text import _ngram_keys

        ids = np.arange(10, dtype=np.int64)
        doc_of = np.zeros(10, np.int64)
        with pytest.raises(OverflowError):
            _ngram_keys(ids, doc_of, (1, 2, 3, 4, 5, 6, 7), base=2**10)

    def test_empty_docs_batch(self):
        from keystone_tpu.ops.nlp import EncodedCommonSparseFeatures

        vec = EncodedCommonSparseFeatures(orders=(1, 2)).fit(["a b", "b c"])
        batch = vec([])
        assert batch.indices.shape[0] == 0
        assert batch.num_features == vec.num_features


class TestDeviceTextEquivalence:
    """The on-device featurizer (ops/nlp/device_text.py) must produce the
    same features as the host fused path when fed the same id encoding."""

    @staticmethod
    def _encode_padded(docs, vocab=None):
        """Tokenize/encode with the host fast path's vocabulary (first-seen
        order) and pad to [D, L] — so device keys are bit-identical."""
        from keystone_tpu.ops.nlp.fast_text import _tokenize_encode

        grow = vocab is None
        if vocab is None:
            vocab = {}
        ids, doc_of = _tokenize_encode(docs, "[\\s]+", vocab, grow=grow)
        n_docs = len(docs)
        lengths = np.bincount(doc_of, minlength=n_docs).astype(np.int32)
        max_len = max(1, int(lengths.max(initial=0)))
        out = np.full((n_docs, max_len), -1, np.int32)
        starts = np.cumsum(lengths) - lengths
        col = np.arange(len(ids)) - starts[doc_of]
        out[doc_of, col] = ids
        return out, lengths, vocab

    def _both(self, docs, orders, k, weight="binary"):
        from keystone_tpu.ops.nlp import EncodedCommonSparseFeatures
        from keystone_tpu.ops.nlp.device_text import DeviceCommonSparseFeatures

        host_vec, host_batch = EncodedCommonSparseFeatures(
            orders=orders, num_features=k, weight=weight
        ).fit_transform(docs)
        ids, lengths, vocab = self._encode_padded(docs)
        dev_vec, dev_batch = DeviceCommonSparseFeatures(
            base=len(vocab) + 1, orders=orders, num_features=k, weight=weight
        ).fit_transform(ids, lengths)
        return host_vec, host_batch, dev_vec, dev_batch, vocab

    def test_untruncated_exact_match(self):
        docs, _, _ = synthetic_newsgroups(100, num_classes=4, seed=9)
        docs = list(docs) + ["", "   ", "one", "repeat repeat repeat"]
        hv, hb, dv, db, _ = self._both(docs, (1, 2), 10**6)
        assert dv.num_features == hv.num_features
        np.testing.assert_array_equal(
            np.sort(np.asarray(dv.keys_sorted)), hv.keys_sorted
        )
        np.testing.assert_allclose(
            np.asarray(db.to_dense()), np.asarray(hb.to_dense())
        )

    def test_oov_test_docs_exact_match(self):
        from keystone_tpu.ops.nlp import EncodedCommonSparseFeatures
        from keystone_tpu.ops.nlp.device_text import DeviceCommonSparseFeatures

        train, _, _ = synthetic_newsgroups(80, num_classes=3, seed=4)
        test, _, _ = synthetic_newsgroups(25, num_classes=3, seed=5)
        test = list(test) + ["totally unseen xyzzy words", ""]
        orders = (1, 2, 3)
        host_vec = EncodedCommonSparseFeatures(
            orders=orders, num_features=10**6, weight="binary"
        ).fit(train)
        ids, lengths, vocab = self._encode_padded(train)
        dev_vec = DeviceCommonSparseFeatures(
            base=len(vocab) + 1, orders=orders, num_features=10**6
        ).fit(ids, lengths)
        t_ids, t_lengths, _ = self._encode_padded(test, vocab)
        np.testing.assert_allclose(
            np.asarray(dev_vec.apply_encoded(t_ids, t_lengths).to_dense()),
            np.asarray(host_vec.apply_batch(test).to_dense()),
        )

    def test_count_weighting_exact(self):
        docs = ["a a a b", "a b b", "c"]
        hv, hb, dv, db, _ = self._both(docs, (1,), 100, weight="count")
        np.testing.assert_allclose(
            np.asarray(db.to_dense()), np.asarray(hb.to_dense())
        )

    def test_truncation_totals_match(self):
        docs, _, _ = synthetic_newsgroups(60, num_classes=4, seed=6)
        k = 40
        hv, hb, dv, db, _ = self._both(docs, (1, 2), k)
        assert db.num_features == hb.num_features == k
        ref_tot = sorted(np.asarray(hb.to_dense()).sum(0))
        dev_tot = sorted(np.asarray(db.to_dense()).sum(0))
        np.testing.assert_allclose(dev_tot, ref_tot)

    def test_pipeline_device_matches_host_errors(self):
        cfg = dict(
            synthetic_train=300,
            synthetic_test=80,
            synthetic_classes=4,
            common_features=10**6,
        )
        dev = run(NewsgroupsConfig(device_path=True, **cfg))
        host = run(NewsgroupsConfig(device_path=False, **cfg))
        # different corpora realizations (device ids vs host strings of the
        # same distribution) — both must separate the synthetic topics
        assert dev["test_error"] < 10.0 and host["test_error"] < 10.0
        assert dev["macro_f1"] > 0.9 and host["macro_f1"] > 0.9

    def test_device_synthetic_generator_shapes(self):
        from keystone_tpu.loaders.newsgroups import synthetic_newsgroups_device

        ids, lengths, labels, vocab = synthetic_newsgroups_device(50, 6, seed=0)
        assert ids.shape[0] == 50 and labels.shape == (50,)
        assert int(lengths.min()) >= 30 and int(lengths.max()) < 120
        assert vocab == 200 + 6 * 30
        assert int(ids.max()) < vocab and int(ids.min()) >= 0


def test_device_text_int64_key_path(rng):
    """A packing base wide enough that order-2 keys exceed int32 must still
    produce correct features (the int64 programs run under enable_x64 —
    without it jax silently canonicalizes the keys to int32 and distinct
    n-grams collide). Features must be identical — up to feature-id
    permutation from tie-breaks — to the same corpus packed with a small
    base, since keys are only identifiers."""
    from keystone_tpu.ops.nlp.device_text import (
        DeviceCommonSparseFeatures,
        _key_dtype,
    )
    import jax.numpy as jnp

    ids = rng.integers(0, 500, size=(40, 12)).astype(np.int32)
    lengths = rng.integers(3, 13, size=(40,)).astype(np.int32)
    small = DeviceCommonSparseFeatures(base=501, orders=(1, 2), num_features=10**6)
    big = DeviceCommonSparseFeatures(base=70001, orders=(1, 2), num_features=10**6)
    assert _key_dtype(70001, (1, 2)) == jnp.int64
    _, b_small = small.fit_transform(ids, lengths)
    _, b_big = big.fit_transform(ids, lengths)
    assert b_small.num_features == b_big.num_features

    def col_fingerprints(batch):
        dense = np.asarray(batch.to_dense())
        return sorted(tuple(dense[:, j]) for j in range(dense.shape[1]))

    assert col_fingerprints(b_small) == col_fingerprints(b_big)
