"""Sparse featurization + Naive Bayes + Newsgroups pipeline tests.

Reference suites: ``nodes/misc/TermFrequencySuite.scala``,
``nodes/util/CommonSparseFeaturesSuite`` analogs, and the canonical
composition chain of ``pipelines/text/NewsgroupsPipeline.scala:24-32``.
"""

import numpy as np
import pytest

from keystone_tpu.learning.naive_bayes import NaiveBayesEstimator
from keystone_tpu.loaders.newsgroups import load_newsgroups, synthetic_newsgroups
from keystone_tpu.ops.util.sparse import (
    AllSparseFeatures,
    CommonSparseFeatures,
    SparseBatch,
    TermFrequency,
)
from keystone_tpu.pipelines.newsgroups import NewsgroupsConfig, run


class TestTermFrequency:
    def test_counts(self):
        tf = TermFrequency()
        out = dict(tf.apply(["a", "b", "a", "a"]))
        assert out == {"a": 3.0, "b": 1.0}

    def test_weight_fn(self):
        tf = TermFrequency(fn=lambda c: 1.0)
        out = dict(tf.apply(["a", "a", "b"]))
        assert out == {"a": 1.0, "b": 1.0}


class TestSparseFeatures:
    def test_common_top_k(self):
        docs = [[("a", 5.0), ("b", 1.0)], [("a", 2.0), ("c", 3.0)], [("b", 1.0)]]
        vec = CommonSparseFeatures(2).fit(docs)
        assert set(vec.feature_index) == {"a", "c"}  # totals: a=7, c=3, b=2
        assert vec.feature_index["a"] == 0

    def test_all_features(self):
        docs = [[("x", 1.0)], [("y", 2.0), ("x", 1.0)]]
        vec = AllSparseFeatures().fit(docs)
        assert set(vec.feature_index) == {"x", "y"}

    def test_vectorize_roundtrip(self):
        docs = [[("a", 2.0), ("c", 1.0)], [("b", 4.0)], []]
        vec = AllSparseFeatures().fit(docs)
        batch = vec(docs)
        assert isinstance(batch, SparseBatch)
        dense = np.asarray(batch.to_dense())
        expected = np.zeros((3, 3), np.float32)
        expected[0, vec.feature_index["a"]] = 2.0
        expected[0, vec.feature_index["c"]] = 1.0
        expected[1, vec.feature_index["b"]] = 4.0
        np.testing.assert_allclose(dense, expected)

    def test_unknown_terms_dropped(self):
        vec = CommonSparseFeatures(1).fit([[("a", 5.0)], [("b", 1.0)]])
        batch = vec([[("b", 3.0), ("a", 1.0)]])
        dense = np.asarray(batch.to_dense())
        assert dense.shape == (1, 1)
        assert dense[0, 0] == 1.0  # only 'a' survives


class TestNaiveBayes:
    def test_matches_hand_computation(self):
        # 2 classes, 3 features, lambda=1 — compute theta/pi by hand
        X = np.array([[2.0, 0.0, 1.0], [1.0, 1.0, 0.0], [0.0, 3.0, 1.0]], np.float32)
        y = np.array([0, 0, 1])
        model = NaiveBayesEstimator(2, lam=1.0).fit(X, y)
        T = np.array([[3.0, 1.0, 1.0], [0.0, 3.0, 1.0]])
        theta = np.log(T + 1) - np.log(T.sum(1, keepdims=True) + 3)
        pi = np.log(np.array([2.0, 1.0]) + 1) - np.log(3.0 + 2.0)
        np.testing.assert_allclose(np.asarray(model.theta), theta, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(model.pi), pi, rtol=1e-5)
        # scoring: log pi + theta.x
        scores = np.asarray(model.apply_batch(X))
        np.testing.assert_allclose(scores, pi[None] + X @ theta.T, rtol=1e-5)

    def test_sparse_matches_dense(self, rng):
        n, v, c = 40, 12, 3
        dense = (rng.random((n, v)) < 0.3) * rng.integers(1, 4, (n, v))
        dense = dense.astype(np.float32)
        y = rng.integers(0, c, n).astype(np.int32)
        docs = [
            [(j, float(dense[i, j])) for j in range(v) if dense[i, j] > 0]
            for i in range(n)
        ]
        vec_fit = AllSparseFeatures().fit(docs)
        batch = vec_fit(docs)
        # remap dense columns into the fitted feature order
        perm = [vec_fit.feature_index[j] for j in range(v)]
        dense_perm = np.zeros_like(dense)
        dense_perm[:, perm] = dense
        m_sparse = NaiveBayesEstimator(c).fit(batch, y)
        m_dense = NaiveBayesEstimator(c).fit(dense_perm, y)
        np.testing.assert_allclose(
            np.asarray(m_sparse.theta), np.asarray(m_dense.theta), rtol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(m_sparse.apply_batch(batch)),
            np.asarray(m_dense.apply_batch(dense_perm)),
            rtol=1e-4,
        )


class TestLoader:
    def test_directory_loader(self, tmp_path):
        for cls, texts in [("rec.autos", ["car fast", "wheel"]), ("sci.med", ["doc"])]:
            d = tmp_path / cls
            d.mkdir()
            for i, t in enumerate(texts):
                (d / f"{i}.txt").write_text(t)
        docs, labels, names = load_newsgroups(str(tmp_path))
        assert names == ["rec.autos", "sci.med"]
        assert len(docs) == 3
        assert labels.tolist() == [0, 0, 1]

    def test_synthetic_separable(self):
        docs, labels, names = synthetic_newsgroups(50, num_classes=4)
        assert len(docs) == 50 and len(names) == 4
        assert set(labels) <= set(range(4))


def test_newsgroups_pipeline_end_to_end():
    res = run(
        NewsgroupsConfig(
            synthetic_train=400,
            synthetic_test=100,
            synthetic_classes=5,
            common_features=5000,
        )
    )
    assert res["test_error"] < 10.0  # synthetic topics are separable
    assert res["macro_f1"] > 0.9
