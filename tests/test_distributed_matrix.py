"""RowShardedMatrix / NormalEquations / BlockCoordinateDescent / TSQR —
the mlmatrix surface rebuilt (SURVEY.md §2.2). Invariant style mirrors the
reference suites: planted-model recovery (``LinearMapperSuite.scala:11-34``),
block ≡ dense (``BlockLinearMapperSuite.scala:17-54``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.linalg import (
    BlockCoordinateDescent,
    NormalEquations,
    RowShardedMatrix,
    TSQR,
)
from keystone_tpu.parallel import make_mesh, use_mesh


@pytest.fixture()
def mesh(devices):
    m = make_mesh(data=8, model=1, devices=devices)
    with use_mesh(m):
        yield m


def test_from_array_collect_roundtrip(mesh, rng):
    x = rng.normal(size=(13, 5)).astype(np.float32)  # 13 not divisible by 8
    M = RowShardedMatrix.from_array(x, mesh)
    assert M.num_rows == 13 and M.num_cols == 5
    assert M.data.shape[0] % 8 == 0
    np.testing.assert_allclose(M.collect(), x, rtol=1e-6)


def test_gram_and_cross_term_match_dense(mesh, rng):
    x = rng.normal(size=(27, 6)).astype(np.float32)
    y = rng.normal(size=(27, 3)).astype(np.float32)
    A = RowShardedMatrix.from_array(x, mesh)
    B = RowShardedMatrix.from_array(y, mesh)
    np.testing.assert_allclose(np.asarray(A.gram()), x.T @ x, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(A.t_times(B)), x.T @ y, rtol=1e-4, atol=1e-4)


def test_times_add_column_means(mesh, rng):
    x = rng.normal(size=(16, 4)).astype(np.float32)
    w = rng.normal(size=(4, 2)).astype(np.float32)
    A = RowShardedMatrix.from_array(x, mesh)
    P = A.times(jnp.asarray(w))
    np.testing.assert_allclose(P.collect(), x @ w, rtol=1e-4, atol=1e-5)
    S = P + P
    np.testing.assert_allclose(S.collect(), 2 * (x @ w), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(A.column_means()), x.mean(axis=0), rtol=1e-5, atol=1e-6
    )


def test_create_random_shape_and_moments(mesh):
    M = RowShardedMatrix.create_random(jax.random.key(0), 1000, 8, mesh)
    assert M.num_rows == 1000 and M.num_cols == 8
    x = M.collect()
    assert abs(x.mean()) < 0.1 and abs(x.std() - 1.0) < 0.1


def test_solvers_accept_raw_unpadded_b(mesh, rng):
    # 13 rows pads to 16; a raw 13-row b must be co-padded internally.
    x = rng.normal(size=(13, 4)).astype(np.float32)
    w = rng.normal(size=(4, 2)).astype(np.float32)
    A = RowShardedMatrix.from_array(x, mesh)
    W = NormalEquations().solve_least_squares_with_l2(A, x @ w, lam=1e-6)
    np.testing.assert_allclose(np.asarray(W), w, rtol=1e-2, atol=1e-3)


def test_normal_equations_recover_planted_model(mesh, rng):
    # LinearMapperSuite.scala:11-34: OLS recovers a planted model.
    x = rng.normal(size=(200, 7)).astype(np.float32)
    w = rng.normal(size=(7, 3)).astype(np.float32)
    A = RowShardedMatrix.from_array(x, mesh)
    b = A.times(jnp.asarray(w))
    W = NormalEquations().solve_least_squares(A, b)
    np.testing.assert_allclose(np.asarray(W), w, rtol=1e-2, atol=1e-3)
    W2 = NormalEquations().solve_least_squares_with_l2(A, b, lam=1e-6)
    np.testing.assert_allclose(np.asarray(W2), w, rtol=1e-2, atol=1e-3)


def test_tsqr_r_and_solver(mesh, rng):
    x = rng.normal(size=(64, 5)).astype(np.float32)
    A = RowShardedMatrix.from_array(x, mesh)
    R = np.asarray(A.qr_r(mesh))
    np.testing.assert_allclose(R.T @ R, x.T @ x, rtol=1e-4, atol=1e-4)
    w = rng.normal(size=(5, 2)).astype(np.float32)
    # raw unpadded b: the solvers co-pad it to A's padded rows internally
    W = TSQR().solve_least_squares(A, x @ w)
    np.testing.assert_allclose(np.asarray(W), w, rtol=1e-3, atol=1e-4)


def test_bcd_multi_lambda_matches_normal_equations(mesh, rng):
    x = rng.normal(size=(120, 12)).astype(np.float32)
    w = rng.normal(size=(12, 2)).astype(np.float32)
    b = x @ w
    A = RowShardedMatrix.from_array(x, mesh)
    B = RowShardedMatrix.from_array(b, mesh)
    models = BlockCoordinateDescent().solve_least_squares_with_l2(
        A, B, lams=[0.1, 10.0], num_iter=8, block_size=4
    )
    assert len(models) == 2
    for lam, W in zip([0.1, 10.0], models):
        ref = NormalEquations().solve_least_squares_with_l2(A, B, lam=lam)
        np.testing.assert_allclose(np.asarray(W), np.asarray(ref), rtol=1e-3, atol=1e-3)


def test_label_extractors():
    from keystone_tpu.core.dataset import LabeledData
    from keystone_tpu.ops.images import (
        ImageExtractor,
        LabelExtractor,
        MultiLabelExtractor,
    )

    imgs = jnp.ones((4, 8, 8, 3))
    labels = jnp.arange(4)
    ld = LabeledData(data=imgs, labels=labels)
    assert ImageExtractor()(ld).shape == (4, 8, 8, 3)
    np.testing.assert_array_equal(np.asarray(LabelExtractor()(ld)), np.arange(4))
    multi = ld.replace(labels=jnp.eye(4))
    np.testing.assert_array_equal(np.asarray(MultiLabelExtractor()(multi)), np.eye(4))
