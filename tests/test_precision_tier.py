"""The KEYSTONE_PRECISION_TIER dtype tier (PR 11).

Four contracts, each pinned here:

1. **f32 tier == prior program** — with the knob unset (or explicitly
   "f32") every rerouted path lowers to a program containing no bf16 and
   returns bit-identical results to the pre-tier code (the tier's
   acceptance criterion: default is a byte-identical no-op).
2. **bf16 envelope** — the bf16-storage/f32-accumulate rungs land within
   the documented ~2⁻⁸-operand-rounding envelope of their f32 twins; the
   sketch solver specifically keeps its subspace-embedding quality and,
   thanks to the f32 CG cleanup, a final error an order of magnitude
   TIGHTER than the raw bf16 gram rounding.
3. **autotune isolation** — precision joins tile shape in the cache key: a
   bf16 winner never serves an f32 call (and vice versa), and unknown-tier
   bucket entries are pruned by the stale-entry sanitizer.
4. **A3 intent registry** — each audit entry point's declared
   (storage, accumulate) dtypes are enforced in BOTH directions: silent
   f32→bf16 drift and a bf16 tier that quietly serves f32 are findings.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.linalg.bcd import block_coordinate_descent_l2
from keystone_tpu.linalg.sketch import (
    sketch_matrix,
    sketch_rows,
    sketched_lstsq_solve,
)
from keystone_tpu.linalg.solvers import (
    hdot,
    normal_equations_solve,
    resolve_precision_tier,
    tsqr_solve,
    validate_precision,
)
from keystone_tpu.parallel import make_mesh


def _system(n=512, d=64, c=4, seed=0):
    A = jax.random.normal(jax.random.key(seed), (n, d), jnp.float32)
    b = jax.random.normal(jax.random.key(seed + 1), (n, c), jnp.float32)
    return A, b


def _rel(a, b):
    return float(jnp.linalg.norm(a - b) / jnp.linalg.norm(b))


# ---------------------------------------------------------------------------
# 1. f32 tier is the prior program, bit for bit
# ---------------------------------------------------------------------------


def test_f32_tier_lowers_with_no_bf16_and_matches_unset(monkeypatch):
    A, _ = _system()
    monkeypatch.delenv("KEYSTONE_PRECISION_TIER", raising=False)
    unset = jax.jit(lambda X: hdot(X.T, X, "high")).lower(A).as_text()
    explicit = (
        jax.jit(lambda X: hdot(X.T, X, "high", tier="f32"))
        .lower(A).as_text()
    )
    assert unset == explicit
    assert "bf16" not in unset


@pytest.mark.parametrize("entry", ["normal_equations", "bcd", "sketch"])
def test_f32_tier_results_bit_identical_to_unset(monkeypatch, entry):
    """Unset knob and explicit tier='f32' resolve to the SAME static
    arguments, therefore the same compiled program and bitwise-equal
    outputs — for every rerouted solver path."""
    A, b = _system()

    def run(**kw):
        if entry == "normal_equations":
            return normal_equations_solve(A, b, lam=1.0, **kw)
        if entry == "bcd":
            return block_coordinate_descent_l2(A, b, 1.0, 32, **kw)
        return sketched_lstsq_solve(A, b, lam=1.0, tol=0.0, max_iters=3, **kw)

    monkeypatch.delenv("KEYSTONE_PRECISION_TIER", raising=False)
    w_unset = run()
    monkeypatch.setenv("KEYSTONE_PRECISION_TIER", "f32")
    w_f32_env = run()
    monkeypatch.delenv("KEYSTONE_PRECISION_TIER", raising=False)
    w_explicit = run(tier="f32")
    assert bool(jnp.all(w_unset == w_f32_env))
    assert bool(jnp.all(w_unset == w_explicit))


def test_pallas_f32_tier_bit_identical(monkeypatch):
    """The bf16-input kernel variants' f32 form is the prior kernel: the
    in-kernel astype(f32) of an f32 ref is a no-op, pinned bitwise."""
    from keystone_tpu.ops.pallas.extraction import fv_moments, sift_oriented_bins

    monkeypatch.delenv("KEYSTONE_PRECISION_TIER", raising=False)
    mag = jax.random.uniform(jax.random.key(0), (2, 24, 32), jnp.float32)
    ang = jax.random.uniform(
        jax.random.key(1), (2, 24, 32), jnp.float32, -3.0, 3.0
    )
    sel = (np.random.default_rng(0).uniform(size=(32, 9)) < 0.3).astype(
        np.float32
    )
    o_unset = sift_oriented_bins(mag, ang, sel, tile_r=16, interpret=True)
    o_f32 = sift_oriented_bins(
        mag, ang, sel, tile_r=16, interpret=True, tier="f32"
    )
    assert bool(jnp.all(o_unset == o_f32))
    x = jax.random.normal(jax.random.key(2), (3, 40, 6), jnp.float32)
    means = jax.random.normal(jax.random.key(3), (8, 6), jnp.float32)
    var = jnp.abs(jax.random.normal(jax.random.key(4), (8, 6), jnp.float32)) + 0.5
    w = jnp.ones((8,), jnp.float32) / 8
    q_unset = fv_moments(x, means, var, w, tile_nd=16, interpret=True)
    q_f32 = fv_moments(x, means, var, w, tile_nd=16, interpret=True, tier="f32")
    for a, c in zip(q_unset, q_f32):
        assert bool(jnp.all(a == c))


def test_knob_routes_same_program_as_per_call_tier(monkeypatch):
    A, b = _system()
    w_call = normal_equations_solve(A, b, lam=1.0, tier="bf16")
    monkeypatch.setenv("KEYSTONE_PRECISION_TIER", "bf16")
    w_env = normal_equations_solve(A, b, lam=1.0)
    assert bool(jnp.all(w_call == w_env))


def test_resolve_precision_tier_validates():
    assert resolve_precision_tier(None) == "f32"
    assert resolve_precision_tier("bf16") == "bf16"
    with pytest.raises(ValueError, match="precision tier"):
        resolve_precision_tier("fp8")


def test_validate_precision_rejects_tier_strings():
    """The two precision vocabularies stay disjoint: a dtype-tier string
    passed as an MXU precision gets a hint naming the right knob."""
    for tier in ("bf16", "f32"):
        with pytest.raises(ValueError, match="KEYSTONE_PRECISION_TIER"):
            validate_precision(tier)
    with pytest.raises(ValueError, match="precision must be one of"):
        validate_precision("bogus")
    assert validate_precision("high") == "high"


# ---------------------------------------------------------------------------
# 2. bf16 envelope
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("entry", ["normal_equations", "bcd", "tsqr"])
def test_bf16_envelope_exact_rungs(entry):
    """bf16-tier solutions of the exact rungs land within 2% of the f32
    twins on a well-conditioned system — and the programs genuinely differ
    (the tier engaged)."""
    A, b = _system(n=1024, d=128)
    mesh = make_mesh()
    if entry == "normal_equations":
        w32 = normal_equations_solve(A, b, lam=1.0)
        w16 = normal_equations_solve(A, b, lam=1.0, tier="bf16")
    elif entry == "bcd":
        w32 = block_coordinate_descent_l2(A, b, 1.0, 32)
        w16 = block_coordinate_descent_l2(A, b, 1.0, 32, tier="bf16")
    else:
        w32 = tsqr_solve(A, b, lam=1.0, mesh=mesh)
        w16 = tsqr_solve(A, b, lam=1.0, mesh=mesh, tier="bf16")
    delta = _rel(w16, w32)
    assert 0.0 < delta < 0.02, delta


@pytest.mark.parametrize("kind", ["countsketch", "srht"])
def test_bf16_sketch_subspace_embedding_quality(kind):
    """The bf16 sketch stays a usable subspace embedding: the
    preconditioned system's conditioning k(A R^-1) — THE property the
    solver's iteration count rides on — stays small at the default
    oversampling, for both operators."""
    n, d = 2048, 32
    A, _ = _system(n=n, d=d)
    m = sketch_rows(n, d)
    SA, _ = sketch_matrix(A, m, seed=3, kind=kind, tier="bf16")
    assert SA.dtype == jnp.float32  # the sketch output is always f32
    R = np.linalg.qr(np.asarray(SA, np.float64), mode="r")
    AR = np.asarray(A, np.float64) @ np.linalg.inv(R)
    s = np.linalg.svd(AR, compute_uv=False)
    assert s[0] / s[-1] < 3.0, s[0] / s[-1]


def test_bf16_sketch_solver_residual_envelope():
    """The full composition: bf16 sketch -> f32 QR -> f32 CG. The final
    residual matches the f32 tier within 1% and the solution delta is at
    least 10x TIGHTER than the raw bf16 gram rounding — the CG-cleanup
    claim that makes this solver the tier's first adopter."""
    A, b = _system(n=1024, d=128)
    w32 = sketched_lstsq_solve(A, b, lam=1.0, tol=1e-6, max_iters=50)
    w16 = sketched_lstsq_solve(
        A, b, lam=1.0, tol=1e-6, max_iters=50, tier="bf16"
    )
    r32 = float(jnp.linalg.norm(A @ w32 - b))
    r16 = float(jnp.linalg.norm(A @ w16 - b))
    assert r16 <= 1.01 * r32, (r16, r32)
    gram_delta = _rel(hdot(A.T, A, tier="bf16"), hdot(A.T, A, "high"))
    assert _rel(w16, w32) < gram_delta / 10.0


def test_ring_gram_routes_tier_to_bidirectional_schedule(monkeypatch):
    """The production ring-gram router (ring.ring_gram) threads the tier
    into the bidirectional schedule: knob-engaged bf16 differs from f32
    within the envelope, and the f32 tier stays bit-identical to the
    unidirectional prior program."""
    from keystone_tpu.parallel.ring import ring_gram

    k = jax.device_count()
    if k < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = make_mesh(data=1, model=k)
    x = jax.random.normal(jax.random.key(0), (40, 16 * k), jnp.float32)
    monkeypatch.delenv("KEYSTONE_PRECISION_TIER", raising=False)
    g_uni = ring_gram(x, mesh, axis="model", bidirectional=False)
    g_f32 = ring_gram(x, mesh, axis="model", bidirectional=True)
    assert bool(jnp.all(g_uni == g_f32))  # f32 tier: bit-identical schedule
    monkeypatch.setenv("KEYSTONE_PRECISION_TIER", "bf16")
    g_bf16 = ring_gram(x, mesh, axis="model", bidirectional=True)
    assert 0.0 < _rel(g_bf16, g_f32) < 0.01


def test_moments_small_n_fallback_keeps_f32_input():
    """gmm_moments_sep's small-n XLA fallback must NOT pay the bf16
    rounding: the fallback streams nothing, so under tier='bf16' it still
    computes from the un-cast f32 descriptors (bit-identical to the f32
    tier)."""
    from keystone_tpu.ops.pallas.moments import _TILE_N_CANDIDATES, gmm_moments_sep

    n = min(_TILE_N_CANDIDATES) + 8  # past the tiny-n guard, under tile_n
    x = jax.random.normal(jax.random.key(0), (n, 6), jnp.float32)
    means = jax.random.normal(jax.random.key(1), (8, 6), jnp.float32)
    var = jnp.abs(jax.random.normal(jax.random.key(2), (8, 6), jnp.float32)) + 0.5
    w = jnp.ones((8,), jnp.float32) / 8
    m32 = gmm_moments_sep(x, means, var, w, tier="f32")
    m16 = gmm_moments_sep(x, means, var, w, tier="bf16")
    for a, b in zip(m32, m16):
        assert bool(jnp.all(a == b))


def test_intent_check_rejects_unknown_vocabulary():
    """A typo'd INTENDED_PRECISION entry must never silently disable the
    rule: unknown storage/accumulate strings raise from the library check
    and surface as an A3 finding through the rule."""
    from keystone_tpu.analysis.ir_rules import (
        AuditProgram,
        PrecisionRule,
        check_intended_precision,
    )

    x = jnp.ones((8, 8), jnp.float32)
    jx = _jaxpr(lambda a: a @ a, x)
    with pytest.raises(ValueError, match="unknown intended precision"):
        check_intended_precision(jx, "f16", "f32")
    with pytest.raises(ValueError, match="unknown intended precision"):
        check_intended_precision(jx, "bf16", "bf16")
    prog = AuditProgram(
        name="toy", path="p.py", line=1, jaxpr=jx, hlo_text="",
        memory_stats=None, expect={"intended_precision": ("fp32", "f32")},
    )
    found = PrecisionRule().run(prog)
    assert any("unknown intended precision" in f.message for f in found)


def test_bf16_collective_structure_survives():
    """The bf16 tiled gram keeps the pipelined collective shape (>= k
    per-tile reduce-scatters, no terminal all-reduce) — the tier must
    never cost the overlap schedule. Needs the 8-device sim."""
    from keystone_tpu.analysis.ir_rules import assert_pipelined_reduce_scatter
    from keystone_tpu.parallel.overlap import tiled_transpose_matmul

    mesh = make_mesh(data=jax.device_count(), model=1)
    k = mesh.shape["data"]
    if k < 2:
        pytest.skip("needs a multi-device mesh")
    x = jax.random.normal(jax.random.key(0), (16 * k, 16 * k), jnp.float32)
    hlo = (
        jax.jit(lambda a: tiled_transpose_matmul(a, mesh=mesh, tier="bf16"))
        .lower(x).compile().as_text()
    )
    assert_pipelined_reduce_scatter(hlo, k)
    assert "bf16" in hlo
    g16 = tiled_transpose_matmul(x, mesh=mesh, tier="bf16")
    g32 = tiled_transpose_matmul(x, mesh=mesh)
    assert _rel(g16, g32) < 0.01


# ---------------------------------------------------------------------------
# 3. autotune precision-key isolation
# ---------------------------------------------------------------------------


def test_precision_bucket_forms():
    from keystone_tpu.ops.pallas import autotune

    assert autotune.precision_bucket("64x8", "f32") == "64x8"
    assert autotune.precision_bucket("64x8", None) == "64x8"
    assert autotune.precision_bucket("64x8", "bf16") == "64x8@bf16"
    with pytest.raises(ValueError, match="precision tier"):
        autotune.precision_bucket("64x8", "fp8")


def test_autotune_precision_key_isolation(tmp_path, monkeypatch):
    """A bf16 winner never serves an f32 lookup and vice versa — the two
    tiers' entries coexist under one kernel without shadowing."""
    from keystone_tpu.ops.pallas import autotune

    monkeypatch.setenv(
        "KEYSTONE_AUTOTUNE_CACHE", str(tmp_path / "cache.json")
    )
    autotune.clear_memory_cache()
    bucket = autotune.shape_bucket(100, 8)
    autotune.record("k.test", autotune.precision_bucket(bucket, "f32"), 512)
    autotune.record("k.test", autotune.precision_bucket(bucket, "bf16"), 128)
    assert autotune.lookup(
        "k.test", autotune.precision_bucket(bucket, "f32")
    ) == 512
    assert autotune.lookup(
        "k.test", autotune.precision_bucket(bucket, "bf16")
    ) == 128
    # persisted isolation too (fresh load from disk)
    autotune.clear_memory_cache()
    assert autotune.lookup("k.test", bucket + "@bf16") == 128
    assert autotune.lookup("k.test", bucket) == 512


def test_autotune_sanitize_prunes_unknown_tier(tmp_path, monkeypatch):
    """Stale-entry sanitization extended: a bucket qualified with a tier
    this build does not speak is pruned on load, while same-kernel good
    entries keep serving."""
    from keystone_tpu.ops.pallas import autotune

    path = tmp_path / "cache.json"
    path.write_text(json.dumps({
        "version": 1,
        "devices": {
            autotune.device_key(): {
                "k.test": {
                    "64x8": {"value": 256},
                    "64x8@bf16": {"value": 64},
                    "64x8@fp8": {"value": 8},       # unknown tier: pruned
                    "64x8@": {"value": 9},          # malformed: pruned
                },
            },
        },
    }))
    monkeypatch.setenv("KEYSTONE_AUTOTUNE_CACHE", str(path))
    autotune.clear_memory_cache()
    assert autotune.lookup("k.test", "64x8") == 256
    assert autotune.lookup("k.test", "64x8@bf16") == 64
    assert autotune.lookup("k.test", "64x8@fp8") is None
    assert autotune.lookup("k.test", "64x8@") is None
    autotune.clear_memory_cache()


def test_pick_tiles_consumes_tier_keyed_winner(tmp_path, monkeypatch):
    """overlap.tiles resolution is tier-keyed end to end: the bf16 winner
    reshapes the bf16 schedule only."""
    from keystone_tpu.ops.pallas import autotune
    from keystone_tpu.parallel.overlap import _pick_tiles

    monkeypatch.setenv(
        "KEYSTONE_AUTOTUNE_CACHE", str(tmp_path / "cache.json")
    )
    monkeypatch.delenv("KEYSTONE_OVERLAP_TILES", raising=False)
    autotune.clear_memory_cache()
    k = 4
    bucket = autotune.shape_bucket(64, k)
    autotune.record("overlap.tiles", bucket + "@bf16", 2)
    assert _pick_tiles(64, k, tier="bf16") == 2
    # the f32 path must NOT see the bf16 winner: heuristic default (= k)
    assert _pick_tiles(64, k) == k
    autotune.clear_memory_cache()


# ---------------------------------------------------------------------------
# 4. A3 intent registry
# ---------------------------------------------------------------------------


def _jaxpr(fn, *args):
    return jax.make_jaxpr(fn)(*args)


def test_intent_check_flags_silent_downgrade():
    """A program doing bf16 dots while its declared storage is f32: the
    f32->bf16 drift direction."""
    from keystone_tpu.analysis.ir_rules import check_intended_precision

    x = jnp.ones((8, 8), jnp.float32)
    jx = _jaxpr(
        lambda a: jnp.matmul(
            a.astype(jnp.bfloat16), a.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        ),
        x,
    )
    problems = check_intended_precision(jx, "f32", "f32")
    assert problems and any("intended f32 storage" in p for p in problems)
    # the same program audited under its true bf16 intent is clean
    assert check_intended_precision(jx, "bf16", "f32") == []


def test_intent_check_flags_unengaged_bf16():
    """A pure-f32 program declared bf16: the bf16->f32 drift direction —
    the tier's perf claim would be hollow."""
    from keystone_tpu.analysis.ir_rules import check_intended_precision

    x = jnp.ones((8, 8), jnp.float32)
    jx = _jaxpr(lambda a: a @ a, x)
    problems = check_intended_precision(jx, "bf16", "f32")
    assert problems and any("not engaged" in p for p in problems)
    assert check_intended_precision(jx, "f32", "f32") == []


def test_intent_check_flags_narrow_accumulation():
    """bf16 dots whose output stays bf16 (preferred_element_type dropped):
    the accumulate contract."""
    from keystone_tpu.analysis.ir_rules import check_intended_precision

    x = jnp.ones((8, 8), jnp.bfloat16)
    jx = _jaxpr(lambda a: a @ a, x)  # bf16 x bf16 -> bf16 accumulate
    problems = check_intended_precision(jx, "bf16", "f32")
    assert problems and any("accumulate" in p for p in problems)


def test_intent_registry_covers_every_entry_point():
    """Every registered audit entry has an explicit intent declaration —
    nothing rides the implicit default silently."""
    from keystone_tpu.analysis.ir_audit import ENTRY_POINTS, INTENDED_PRECISION

    missing = set(ENTRY_POINTS) - set(INTENDED_PRECISION)
    assert not missing, missing
    # and the bf16-tier variants are declared bf16-storage/f32-accumulate
    assert INTENDED_PRECISION["solver.sketch_bf16"] == ("bf16", "f32")
    assert INTENDED_PRECISION["overlap.tiled_gram_bf16"] == ("bf16", "f32")


def test_audit_bf16_entries_clean_and_drift_detected(monkeypatch):
    """End to end through run_audit: the registered bf16 entries audit
    clean against their declared intent, and flipping an intent makes the
    SAME program a finding — in each direction."""
    from keystone_tpu.analysis import ir_audit

    res = ir_audit.run_audit(
        targets=["solver.sketch_bf16", "pallas.sift_bins_bf16"],
        baseline_path=None,
    )
    assert not res.errors, res.errors
    assert res.findings == [], [f.message for f in res.findings]
    # direction 1: declare the bf16 entry f32 -> its bf16 program drifts
    monkeypatch.setitem(
        ir_audit.INTENDED_PRECISION, "solver.sketch_bf16", ("f32", "f32")
    )
    res = ir_audit.run_audit(
        targets=["solver.sketch_bf16"], baseline_path=None
    )
    assert any("intended f32 storage" in f.message for f in res.findings)
    # direction 2: declare an f32 entry bf16 -> unengaged-tier finding
    monkeypatch.setitem(
        ir_audit.INTENDED_PRECISION, "pallas.sift_bins", ("bf16", "f32")
    )
    res = ir_audit.run_audit(targets=["pallas.sift_bins"], baseline_path=None)
    assert any("not engaged" in f.message for f in res.findings)


# ---------------------------------------------------------------------------
# C4 learns the tier
# ---------------------------------------------------------------------------


def test_c4_flags_bf16_under_f32_tier_only(monkeypatch):
    """A stage emitting bfloat16 is a C4 finding under the default f32
    tier and CLEAN under KEYSTONE_PRECISION_TIER=bf16 — checked pipelines
    stay clean when the tier is the declared program."""
    from keystone_tpu.analysis.check import pipeline_findings
    from keystone_tpu.analysis.contracts import StageRecord

    rec = StageRecord(
        index=0, node=object(), deps=(-1,), name="caster",
        in_aval=jax.ShapeDtypeStruct((4, 8), jnp.float32),
        out_aval=jax.ShapeDtypeStruct((4, 8), jnp.bfloat16),
    )
    monkeypatch.delenv("KEYSTONE_PRECISION_TIER", raising=False)
    found = pipeline_findings([rec], "toy", site=("toy.py", 1))
    assert [f for f in found if f.rule == "C4" and "bfloat16" in f.message]
    monkeypatch.setenv("KEYSTONE_PRECISION_TIER", "bf16")
    found = pipeline_findings([rec], "toy", site=("toy.py", 1))
    assert not [f for f in found if f.rule == "C4"]
    # report-once-at-source: a stage CARRYING bf16 through is not re-flagged
    monkeypatch.delenv("KEYSTONE_PRECISION_TIER", raising=False)
    carrier = StageRecord(
        index=0, node=object(), deps=(-1,), name="carrier",
        in_aval=jax.ShapeDtypeStruct((4, 8), jnp.bfloat16),
        out_aval=jax.ShapeDtypeStruct((4, 8), jnp.bfloat16),
    )
    found = pipeline_findings([carrier], "toy", site=("toy.py", 1))
    assert not [f for f in found if f.rule == "C4"]
