"""True multi-process distributed execution (the DCN / multi-host analog).

The reference's distributed backend is Spark's driver/executor runtime over a
cluster (SURVEY.md §2.13); the rebuild's is a JAX process group —
``jax.distributed.initialize`` (what ``run-pipeline --coordinator ...``
calls, ``cli.py``) + XLA collectives over the global mesh. The 8-device
single-process mesh used everywhere else in this suite exercises the
collectives but not the *multi-controller* path: global arrays assembled
from process-local shards, cross-process psum/all-gather (Gloo on CPU here,
ICI/DCN on real pods).

This test spawns TWO OS processes, each exposing 4 CPU devices, forms the
8-device global mesh across them, and drives the framework's distributed
linalg through it:

- a global array built with ``jax.make_array_from_process_local_data``
  (each process contributes only its rows),
- ``tsqr_solve`` (shard_map QR tree + psum'd Qᵀb) on the global mesh,
- a jitted global reduction (the gram/psum pattern under NormalEquations),
- ``ring_attention`` with the sequence axis spanning both processes (K/V
  blocks rotate the full 8-device ring across the process boundary),

asserting both processes agree with a local dense reference.
"""

import os
import subprocess
import sys

_WORKER = r"""
import sys

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 4)
except AttributeError:  # older jaxlib: pre-init XLA flag instead
    import os

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()
try:  # cross-process CPU collectives ride Gloo; older jaxlib needs the
    jax.config.update("jax_cpu_enable_gloo_collectives", True)  # explicit opt-in
except AttributeError:
    pass
pid = int(sys.argv[1])
port = sys.argv[2]
jax.distributed.initialize(f"localhost:{port}", num_processes=2, process_id=pid)

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from keystone_tpu.linalg.solvers import tsqr_solve

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4

mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
n, d, c = 128, 8, 3
rng = np.random.default_rng(0)  # same seed on both processes
A_full = rng.normal(size=(n, d)).astype(np.float32)
b_full = rng.normal(size=(n, c)).astype(np.float32)

rows = NamedSharding(mesh, P("data"))
half = n // 2
A = jax.make_array_from_process_local_data(
    rows, A_full[pid * half : (pid + 1) * half], A_full.shape
)
b = jax.make_array_from_process_local_data(
    rows, b_full[pid * half : (pid + 1) * half], b_full.shape
)

# 1. cross-process reduction (the gram/psum pattern): AtA over all rows
AtA = jax.jit(
    lambda x: x.T @ x, out_shardings=NamedSharding(mesh, P())
)(A)
np.testing.assert_allclose(
    np.asarray(AtA), A_full.T @ A_full, rtol=1e-4, atol=1e-4
)

# 2. TSQR least squares across the process group
lam = 0.1
with mesh:
    w = tsqr_solve(A, b, lam=lam)
jax.block_until_ready(w)
w_ref = np.linalg.solve(
    A_full.T @ A_full + lam * np.eye(d), A_full.T @ b_full
)
np.testing.assert_allclose(np.asarray(w), w_ref, rtol=1e-3, atol=1e-3)

# 3. ring attention with the sequence axis spanning BOTH processes: K/V
# blocks rotate the full 8-device ring, crossing the process boundary
# (Gloo here; DCN on real multi-host pods)
from keystone_tpu.parallel import use_mesh
from keystone_tpu.parallel.ring import attention_reference, ring_attention

seq, heads, dim = 64, 2, 8
q_full = rng.normal(size=(2, seq, heads, dim)).astype(np.float32)
seq_sh = NamedSharding(mesh, P(None, "data"))
half_seq = seq // 2
q_arr = jax.make_array_from_process_local_data(
    seq_sh, q_full[:, pid * half_seq : (pid + 1) * half_seq], q_full.shape
)
with use_mesh(mesh):
    out = ring_attention(q_arr, q_arr, q_arr, causal=True)
jax.block_until_ready(out)
ref = np.asarray(attention_reference(
    jnp.asarray(q_full), jnp.asarray(q_full), jnp.asarray(q_full), causal=True
))
# multi-controller arrays are only partially addressable: check this
# process's shards against the dense single-host reference
for shard in out.addressable_shards:
    sl = shard.index
    np.testing.assert_allclose(
        np.asarray(shard.data), ref[sl], rtol=2e-4, atol=2e-4
    )

# 4. streaming weighted BCD with rows spanning BOTH processes: per-block
# pop-stat grams/cross-terms psum across the group, class-bucketed solves
# gather rows of a globally-sharded X (the flagship solver's comm pattern,
# multi-controller edition)
from keystone_tpu.learning.block_weighted import (
    BlockWeightedLeastSquaresEstimator,
)

ns, bs_, cs = 64, 16, 4
x_full = rng.normal(size=(ns, 2 * bs_)).astype(np.float32)
lab_full = np.arange(ns) % cs
proto = rng.normal(size=(cs, 2 * bs_)).astype(np.float32)
x_full = x_full * 0.3 + proto[lab_full]  # separable: the fit must recover it
ind_full = -np.ones((ns, cs), np.float32)
ind_full[np.arange(ns), lab_full] = 1.0
half_n = ns // 2
xr = jax.make_array_from_process_local_data(
    rows, x_full[pid * half_n : (pid + 1) * half_n], x_full.shape
)
lr = jax.make_array_from_process_local_data(
    rows, ind_full[pid * half_n : (pid + 1) * half_n], ind_full.shape
)


class _Slice:
    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    def apply_batch(self, r):
        return r["x"][:, self.lo : self.hi]


est = BlockWeightedLeastSquaresEstimator(bs_, 1, 0.1, 0.25)
with use_mesh(mesh):
    m = est.fit_streaming(
        [_Slice(0, bs_), _Slice(bs_, 2 * bs_)], {"x": xr}, lr
    )
jax.block_until_ready((m.w, m.b))
scores = x_full @ np.asarray(m.w) + np.asarray(m.b)
train_acc = float((scores.argmax(1) == lab_full).mean())
assert train_acc > 0.95, train_acc  # separable prototypes must be recovered
# cross-controller consistency: the parent compares both processes' sums
print(f"WBCD_CKSUM {float(np.asarray(m.w).sum()):.6f}", flush=True)

print(f"MULTIHOST_OK proc={pid}", flush=True)
"""


def _spawn_workers(tmp_path):
    import socket

    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    # ephemeral free port: a fixed one collides across concurrent suite runs
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = str(s.getsockname()[1])
    env = dict(os.environ)
    # the workers pin their own platform/device count before distributed
    # init; drop any inherited platform pin (e.g. the axon TPU plugin owns
    # the real chip in the parent test process)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), port],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def test_two_process_distributed_tsqr(tmp_path):
    # Older jaxlib's Gloo TCP transport has a rare startup race
    # ("op.preamble.length <= op.nbytes", SIGABRT) whose probability spikes
    # under host load: the failure is in the transport layer, not the
    # framework code under test, so retry ONLY on that exact signature —
    # any other failure asserts immediately. Backoff between attempts lets
    # a transient load burst pass.
    import time

    for attempt in range(5):
        procs, outs = _spawn_workers(tmp_path)
        if not any(
            p.returncode != 0 and "gloo::EnforceNotMet" in out
            for p, out in zip(procs, outs)
        ):
            break
        time.sleep(1 + attempt)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc{i} failed:\n{out[-3000:]}"
        assert f"MULTIHOST_OK proc={i}" in out, out[-3000:]
    # cross-controller consistency: both processes ran the same global
    # weighted-BCD program and must report the SAME fitted-model checksum
    cksums = set()
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("WBCD_CKSUM")]
        assert line, out[-3000:]
        cksums.add(line[-1].split()[1])
    assert len(cksums) == 1, f"controllers disagree: {cksums}"
