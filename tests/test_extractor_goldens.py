"""Committed descriptor-statistics goldens for SIFT/HOG/DAISY/LCS on the
reference's own test photos (VERDICT round-1 item 4).

Tolerance policy, mirroring the reference's (``VLFeatSuite.scala:44-51`` —
≥99.5% of entries within 1 after 512× quantization against MATLAB
``vl_phow``): the vl_phow golden CSVs are absent from the reference checkout
and no vlfeat binary exists in this image, so bitwise parity is unprovable
here (gap statement in README "Known capability gaps"). What IS pinned,
exactly: keypoint geometry per scale (integer — must equal ``vl_dsift``'s
frame counts), total descriptor counts, the quantized-value histogram
(integer bins, small drift budget for backend rounding), the mass-threshold
zero fraction, and float summary moments with 1e-3 relative tolerance. If a
vlfeat golden file appears, ``test_vl_phow_policy_ready`` documents the
comparison to run.

Regenerate after an intentional extractor change:
``JAX_PLATFORMS=cpu python scripts/gen_extractor_goldens.py``.
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

_RES = "/root/reference/src/test/resources/images"
_GOLD = os.path.join(os.path.dirname(__file__), "goldens", "extractor_stats.json")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(_RES), reason="reference fixture images not mounted"
)


def _gold():
    with open(_GOLD) as f:
        return json.load(f)


def _gray(name):
    from PIL import Image

    return np.asarray(
        Image.open(os.path.join(_RES, name)).convert("L"), np.float32
    ) / 255.0


def _rgb(name):
    from PIL import Image

    return np.asarray(
        Image.open(os.path.join(_RES, name)).convert("RGB"), np.float32
    ) / 255.0


@pytest.mark.parametrize("name", ["000012.jpg", "gantrycrane.png"])
def test_sift_golden_stats(name):
    from keystone_tpu.ops.images.sift import SIFTExtractor, dsift_geometry

    g = _gold()[name]["sift"]
    gray = _gray(name)
    h, w = _gold()[name]["hw"]
    assert gray.shape == (h, w)

    sift = SIFTExtractor()
    # keypoint geometry per scale: integer, must match vl_dsift's frame
    # counts for (step+s, bin+2s, aligned bounds) exactly
    per_scale = []
    for s in range(sift.scales):
        ny, nx = dsift_geometry(
            w, h,
            sift.step_size + s * sift.scale_step,
            sift.bin_size + 2 * s,
            (1 + 2 * sift.scales) - 3 * s,
        )
        per_scale.append(ny * nx)
    assert per_scale == g["keypoints_per_scale"]

    descs = np.asarray(sift.apply(jnp.asarray(gray)))
    assert descs.shape == (g["num_descriptors"], 128)
    assert sum(per_scale) == g["num_descriptors"]

    # quantized-value histogram: integer bins; allow <=0.1% of mass to move
    # between bins (backend rounding at bin edges)
    edges = [0, 1, 2, 4, 8, 16, 32, 64, 128, 256]
    hist = np.histogram(descs, bins=edges)[0]
    drift = np.abs(hist - np.asarray(g["quant_histogram"])).sum()
    assert drift <= max(2, descs.size // 1000), (hist.tolist(), g["quant_histogram"])

    zero_frac = float(np.mean(np.all(descs == 0.0, axis=1)))
    assert zero_frac == pytest.approx(g["zero_descriptor_fraction"], abs=1e-3)
    assert float(descs.mean()) == pytest.approx(g["mean"], rel=1e-3)


@pytest.mark.parametrize("name", ["000012.jpg", "gantrycrane.png"])
def test_hog_daisy_lcs_golden_stats(name):
    from keystone_tpu.ops.images.daisy import DaisyExtractor
    from keystone_tpu.ops.images.hog import HogExtractor
    from keystone_tpu.ops.images.lcs import LCSExtractor

    g = _gold()[name]
    gray, rgb = _gray(name), _rgb(name)

    hog = np.asarray(HogExtractor(bin_size=8).apply(jnp.asarray(rgb)))
    assert list(hog.shape) == g["hog"]["shape"]
    assert float(hog.mean()) == pytest.approx(g["hog"]["mean"], rel=1e-3)
    assert float(hog.std()) == pytest.approx(g["hog"]["std"], rel=1e-3)
    assert float(np.mean(hog == 0.0)) == pytest.approx(
        g["hog"]["zero_fraction"], abs=1e-3
    )

    daisy = np.asarray(DaisyExtractor().apply(jnp.asarray(gray)))
    assert list(daisy.shape) == g["daisy"]["shape"]
    assert float(daisy.mean()) == pytest.approx(g["daisy"]["mean"], rel=1e-3)
    assert float(daisy.std()) == pytest.approx(g["daisy"]["std"], rel=1e-3)

    lcs = np.asarray(LCSExtractor(4, 16, 6).apply(jnp.asarray(rgb)))
    assert list(lcs.shape) == g["lcs"]["shape"]
    assert float(lcs.mean()) == pytest.approx(g["lcs"]["mean"], rel=1e-3)
    assert float(lcs.std()) == pytest.approx(g["lcs"]["std"], rel=1e-3)


def test_vl_phow_policy_ready():
    """The reference's tolerance policy, executable the moment a vl_phow
    golden appears: load (128, N) golden descriptors, extract with
    SIFTExtractor on the same image, and require >=99.5% of entries within
    1 after the 512x quantization (VLFeatSuite.scala:44-51). The golden
    (feats128.csv) is absent from the reference checkout; this test
    documents + skips rather than silently not existing."""
    golden = os.path.join(_RES, "feats128.csv")
    if not os.path.exists(golden):
        pytest.skip("vl_phow golden (feats128.csv) not in reference checkout")
    from keystone_tpu.ops.images.sift import SIFTExtractor

    ref = np.loadtxt(golden, delimiter=",")  # (128, N), already 512x-quantized
    descs = np.asarray(
        SIFTExtractor().apply(jnp.asarray(_gray("gantrycrane.png")))
    ).T
    assert descs.shape == ref.shape
    within_1 = np.mean(np.abs(descs - ref) <= 1.0)
    assert within_1 >= 0.995
