"""keystone-audit (keystone_tpu/analysis/ir_audit.py + ir_rules.py):
IR-level rules A1-A5 over lowered jaxpr + compiled HLO.

Every rule is proven by a deliberately-bad fixture program it must flag
(terminal all-reduce gram, unpaired one-directional ppermute ring, host
callback in a jitted path, f64 leak, padding-wasteful matmul, undersized
plan estimate) AND by the repo-audits-clean invariant over the committed
``ir_baseline.json`` — mirroring ``test_lint.py``'s structure one IR level
down.  The acceptance pins: >= 8 registered entry points spanning both
overlap schedulers, >= 2 solver rungs, >= 2 Pallas kernels with XLA
twins, and >= 1 fused DAG segment; and A5 asserting ``core/plan.py``'s
closed-form peak estimate bounds the compiled buffer-assignment peak on
the flagship solver block.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from keystone_tpu.analysis import ir_audit
from keystone_tpu.analysis.ir_audit import (
    ENTRY_POINTS,
    Built,
    EntryPoint,
    lower_entry,
    resolve_targets,
    run_audit,
)
from keystone_tpu.analysis.ir_rules import (
    AuditProgram,
    CollectiveShapeRule,
    HostTransferRule,
    MemoryRule,
    PaddingRule,
    PrecisionRule,
    unpaired_permute_count,
)
from keystone_tpu.linalg.solvers import hdot
from keystone_tpu.parallel import make_mesh

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _program(fn, args, **kw):
    """Lower a fixture into the rule input (the engine's own recipe)."""
    compiled = jax.jit(fn).lower(*args).compile()
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    return AuditProgram(
        name=kw.pop("name", "fixture"), path="fixture.py", line=1,
        jaxpr=jax.make_jaxpr(fn)(*args), hlo_text=compiled.as_text(),
        memory_stats=mem, **kw,
    )


@pytest.fixture()
def mesh(devices):
    return make_mesh(data=8, model=1, devices=devices)


# ---------------------------------------------------------------------------
# A1: collective shape
# ---------------------------------------------------------------------------


def test_a1_flags_terminal_all_reduce_gram(mesh, rng):
    """The canonical regression: a row-sharded gram whose reduction XLA
    lowered to ONE bulk all-reduce instead of per-tile reduce-scatters."""
    x = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))
    rows = NamedSharding(mesh, P("data", None))
    fn = jax.jit(lambda a: hdot(a.T, a), in_shardings=rows,
                 out_shardings=NamedSharding(mesh, P()))
    compiled = fn.lower(x).compile()
    prog = AuditProgram(
        name="bad.gram", path="fixture.py", line=1,
        jaxpr=jax.make_jaxpr(lambda a: hdot(a.T, a))(x),
        hlo_text=compiled.as_text(), memory_stats=None, k=8,
        expect=dict(reduce_scatter_min="k"),
    )
    findings = CollectiveShapeRule().run(prog)
    assert findings, "terminal all-reduce not flagged"
    assert any("all-reduce" in f.message for f in findings)
    assert all(f.rule == "A1" for f in findings)


def test_a1_flags_unpaired_ppermute_ring(mesh, rng):
    """A one-directional ring (every permute forward, no inverse) must
    fail the bidirectional-pairing check."""
    x = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))

    def one_dir(xj):
        perm = [(i, (i + 1) % 8) for i in range(8)]
        acc = xj
        for _ in range(7):
            xj = jax.lax.ppermute(xj, "data", perm)
            acc = acc + xj
        return acc

    f = jax.jit(jax.shard_map(
        one_dir, mesh=mesh, in_specs=P("data", None),
        out_specs=P("data", None), check_vma=False,
    ))
    hlo = f.lower(x).compile().as_text()
    assert unpaired_permute_count(hlo) == 7
    prog = _program(lambda a: a, (x,), k=8,
                    expect=dict(paired_permutes=True, permute_min=2))
    prog.hlo_text = hlo
    findings = CollectiveShapeRule().run(prog)
    assert any("matched inverse" in f.message for f in findings)


def test_a1_clean_on_the_real_overlap_schedulers(devices, rng):
    """The paired schedules themselves stay clean under the same rule —
    the auditor's expectations match what the schedulers actually emit."""
    from keystone_tpu.parallel.overlap import (
        bidirectional_ring_gram,
        tiled_transpose_matmul,
    )

    m = make_mesh(data=8, model=1, devices=devices)
    x = jnp.asarray(rng.normal(size=(128, 128)).astype(np.float32))
    hlo = jax.jit(
        lambda a: tiled_transpose_matmul(a, mesh=m)
    ).lower(x).compile().as_text()
    prog = _program(lambda a: a, (x,), k=8,
                    expect=dict(reduce_scatter_min="k", all_gather_max=1))
    prog.hlo_text = hlo
    assert CollectiveShapeRule().run(prog) == []

    m2 = make_mesh(data=1, model=8, devices=devices)
    x2 = jnp.asarray(rng.normal(size=(40, 128)).astype(np.float32))
    hlo2 = jax.jit(
        lambda a: bidirectional_ring_gram(a, m2, axis="model")
    ).lower(x2).compile().as_text()
    prog2 = _program(lambda a: a, (x2,), k=8,
                     expect=dict(zero_bulk=True, paired_permutes=True,
                                 permute_min=6))
    prog2.hlo_text = hlo2
    assert CollectiveShapeRule().run(prog2) == []


# ---------------------------------------------------------------------------
# A2: host transfers
# ---------------------------------------------------------------------------


def test_a2_flags_callback_in_hot_path(rng):
    x = jnp.asarray(rng.normal(size=(32, 8)).astype(np.float32))

    def bad(a):
        y = jax.pure_callback(
            lambda v: np.asarray(v) * 2.0,
            jax.ShapeDtypeStruct(a.shape, a.dtype), a,
        )
        return y + 1.0

    prog = _program(bad, (x,))
    findings = HostTransferRule().run(prog)
    assert findings, "pure_callback not flagged"
    assert any("pure_callback" in f.message for f in findings)
    assert all(f.rule == "A2" for f in findings)
    # the allowlist escape hatch
    prog.expect = dict(allow_host=True)
    assert HostTransferRule().run(prog) == []


def test_a2_silent_on_lapack_custom_calls(rng):
    """CPU linalg lowers to LAPACK custom-calls — those are on-device
    library calls, NOT host round-trips, and must not be flagged."""
    x = jnp.asarray(rng.normal(size=(32, 8)).astype(np.float32))
    prog = _program(lambda a: jnp.linalg.qr(a, mode="r"), (x,))
    assert "custom-call" in prog.hlo_text  # the lapack call IS there
    assert HostTransferRule().run(prog) == []


# ---------------------------------------------------------------------------
# A3: precision
# ---------------------------------------------------------------------------


def test_a3_flags_f64_leak(rng):
    x = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
    with jax.enable_x64():
        def leak(a):
            wide = a.astype(jnp.float64)
            return (wide @ wide.T).astype(jnp.float32)

        prog = _program(leak, (x,))
    findings = PrecisionRule().run(prog)
    assert findings, "f64 leak not flagged"
    assert any("float64" in f.message or "f64" in f.message
               for f in findings)
    # the silent weak-type upcast is named as such
    assert any("upcast" in f.message for f in findings)
    assert all(f.rule == "A3" for f in findings)
    # allowlisted entries (e.g. a deliberate f64 reference path) pass
    prog.expect = dict(allow_f64=True)
    assert PrecisionRule().run(prog) == []


def test_a3_clean_on_f32_solver(rng):
    x = jnp.asarray(rng.normal(size=(32, 8)).astype(np.float32))
    prog = _program(lambda a: hdot(a.T, a, "high"), (x,))
    assert PrecisionRule().run(prog) == []


# ---------------------------------------------------------------------------
# A4: padding/alignment
# ---------------------------------------------------------------------------


def test_a4_flags_padding_wasteful_matmul(rng):
    """A 130-wide contraction pads to 256 lanes: 49 % of every MXU pass
    wasted — flagged.  The same matmul at 128 is clean, and dims under
    the min (class counts etc.) are never flagged."""
    x = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))
    w1 = jnp.ones((64, 130), jnp.float32)
    w2 = jnp.ones((130, 8), jnp.float32)
    prog = _program(lambda a: a @ w1 @ w2, (x,),
                    expect=dict(check_padding=True))
    findings = PaddingRule().run(prog)
    assert findings, "padding waste not flagged"
    assert any("130" in f.message for f in findings)
    assert all(f.rule == "A4" for f in findings)
    # 8-wide output dim: below PAD_MIN_DIM, not flagged
    assert not any(" 8 pads" in f.message for f in findings)
    # aligned shapes are clean
    w_ok = jnp.ones((64, 128), jnp.float32)
    clean = _program(lambda a: a @ w_ok, (x,),
                     expect=dict(check_padding=True))
    assert PaddingRule().run(clean) == []
    # the rule is opt-in: without check_padding nothing fires
    prog.expect = {}
    assert PaddingRule().run(prog) == []


def test_a4_cross_checks_autotuned_tile(tmp_path, monkeypatch, rng):
    """A persisted autotune winner that no longer tiles the production
    row count without >25 % padding is stale tuning — flagged."""
    from keystone_tpu.ops.pallas import autotune

    cache = tmp_path / "autotune_cache.json"
    monkeypatch.setenv("KEYSTONE_AUTOTUNE_CACHE", str(cache))
    autotune.clear_memory_cache()
    bucket = autotune.shape_bucket(48)
    autotune.record("audit.test_kernel", bucket, 256)  # tiles 48 rows at 81% waste
    x = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
    prog = _program(
        lambda a: a + 1.0, (x,),
        expect=dict(check_padding=True,
                    tile_kernel=("audit.test_kernel", bucket, 48)),
    )
    findings = PaddingRule().run(prog)
    assert any("autotuned tile 256" in f.message for f in findings)
    autotune.clear_memory_cache()


# ---------------------------------------------------------------------------
# A5: memory (plan estimate bounds compiled peak)
# ---------------------------------------------------------------------------


def test_a5_flags_undersized_plan_estimate(rng):
    x = jnp.asarray(rng.normal(size=(256, 128)).astype(np.float32))
    prog = _program(lambda a: hdot(a.T, a, "high"), (x,),
                    peak_estimate=1024)  # absurdly small: must be flagged
    findings = MemoryRule().run(prog)
    assert findings, "undersized estimate not flagged"
    assert all(f.rule == "A5" for f in findings)
    assert "exceeds" in findings[0].message


def test_a5_estimate_bounds_flagship_solver_block(devices):
    """THE acceptance pin: ``plan.block_solve_peak_bytes`` bounds the
    compiled buffer-assignment peak of the flagship solver block step —
    the cost model the HBM-safe planner trusts has not drifted."""
    entry = ENTRY_POINTS["solver.block_step"]
    prog = lower_entry(entry, devices)
    compiled = MemoryRule.compiled_peak_bytes(prog.memory_stats)
    assert compiled is not None and compiled > 0
    assert prog.peak_estimate is not None
    assert prog.peak_estimate >= compiled, (
        f"plan estimate {prog.peak_estimate} B no longer bounds the "
        f"compiled peak {compiled} B"
    )
    assert MemoryRule().run(prog) == []


# ---------------------------------------------------------------------------
# Registry + engine
# ---------------------------------------------------------------------------


def test_registry_covers_the_acceptance_surface():
    """>= 8 entries spanning both overlap schedulers, >= 2 solver rungs,
    >= 2 Pallas kernels WITH their XLA twins, >= 1 fused DAG segment."""
    assert len(ENTRY_POINTS) >= 8
    assert "overlap.tiled_gram" in ENTRY_POINTS   # scheduler 1: tiled RS
    assert "overlap.ring_gram" in ENTRY_POINTS    # scheduler 2: ppermute ring
    solvers = [n for n, e in ENTRY_POINTS.items() if e.category == "solver"]
    assert len(solvers) >= 2
    pallas = [n for n, e in ENTRY_POINTS.items() if e.category == "pallas"]
    kernels = {n for n in pallas if not n.endswith("_xla")}
    twins = {n[: -len("_xla")] for n in pallas if n.endswith("_xla")}
    assert len(kernels & twins) >= 2, (kernels, twins)
    assert any(e.category == "pipeline" for e in ENTRY_POINTS.values())


def test_resolve_targets_names_prefixes_and_knob(monkeypatch):
    monkeypatch.delenv("KEYSTONE_AUDIT_TARGETS", raising=False)
    assert resolve_targets(None) == list(ENTRY_POINTS)
    assert resolve_targets(["overlap.tiled_gram"]) == ["overlap.tiled_gram"]
    by_prefix = resolve_targets(["overlap"])
    assert set(by_prefix) == {
        n for n, e in ENTRY_POINTS.items() if e.category == "overlap"
    }
    with pytest.raises(KeyError, match="unknown audit target"):
        resolve_targets(["nonsense"])
    monkeypatch.setenv("KEYSTONE_AUDIT_TARGETS", "pallas.sift_bins")
    assert resolve_targets(None) == ["pallas.sift_bins"]


def test_repo_audits_clean_against_committed_baseline(devices):
    """The acceptance invariant (mirrors test_lint's): every registered
    entry point lowers + audits with ZERO new findings on the clean
    repo against the committed ``ir_baseline.json``."""
    res = run_audit(
        baseline_path=os.path.join(REPO_ROOT, ir_audit.DEFAULT_IR_BASELINE),
    )
    assert res.errors == [], res.errors
    assert res.skipped == {}, res.skipped  # 8-device sim places everything
    assert len(res.targets) >= 8
    assert res.findings == [], "\n".join(f.format() for f in res.findings)


def test_engine_end_to_end_bad_entry_and_baseline_prune(
    devices, monkeypatch, tmp_path, rng, capsys
):
    """A bad entry registered into the engine flows all the way through:
    finding anchored at the registration line, failing CLI exit, then
    baselined — and --update-baseline prunes the fingerprint once the
    entry is gone."""
    x = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))
    m = make_mesh(data=8, model=1, devices=devices)
    rows = NamedSharding(m, P("data", None))

    def build_bad(devs):
        # committed row sharding: the jitted gram's contraction crosses
        # shards, so XLA emits the terminal all-reduce the rule bans
        xs = jax.device_put(x, rows)
        return Built(fn=lambda a: hdot(a.T, a), args=(xs,), k=8,
                     expect=dict(reduce_scatter_min="k"))

    bad = EntryPoint(
        name="fixture.bad_gram", category="solver", builder=build_bad,
        min_devices=8, line=1, doc="terminal all-reduce fixture",
    )
    monkeypatch.setitem(ENTRY_POINTS, "fixture.bad_gram", bad)
    baseline = tmp_path / "ir_baseline.json"

    res = run_audit(["fixture.bad_gram"], baseline_path=None)
    assert res.findings and all(f.rule == "A1" for f in res.findings)
    assert res.findings[0].path == ir_audit._SELF_RELPATH

    # baseline it -> clean
    from keystone_tpu.analysis.engine import load_baseline, save_baseline

    save_baseline(str(baseline), res.findings, tool="audit")
    bad_fp = res.findings[0].fingerprint
    res2 = run_audit(["fixture.bad_gram"], baseline_path=str(baseline))
    assert res2.findings == [] and res2.baselined

    # --update-baseline scoped to a DIFFERENT target must KEEP the bad
    # entry's debt (a subset run cannot silently prune out-of-scope
    # fingerprints)...
    rc = ir_audit.main([
        "--root", str(tmp_path), "--baseline", str(baseline),
        "--target", "overlap.tiled_gram", "--update-baseline",
    ])
    out = capsys.readouterr().out
    assert rc == 0 and "out-of-scope kept" in out
    assert bad_fp in load_baseline(str(baseline))

    # ...then FIXING the entry and updating ITS scope prunes the debt
    def build_fixed(devs):
        return Built(fn=lambda a: a + 1.0, args=(x,), k=8)

    monkeypatch.setitem(
        ENTRY_POINTS, "fixture.bad_gram",
        EntryPoint(name="fixture.bad_gram", category="solver",
                   builder=build_fixed, min_devices=8, line=1, doc=""),
    )
    rc = ir_audit.main([
        "--root", str(tmp_path), "--baseline", str(baseline),
        "--target", "fixture.bad_gram", "--update-baseline",
    ])
    out = capsys.readouterr().out
    # both of the bad gram's fingerprints (terminal all-reduce + missing
    # reduce-scatters) are now stale and pruned
    assert rc == 0 and "stale fingerprint(s) pruned" in out
    assert "0 stale" not in out
    assert load_baseline(str(baseline)) == {}


def test_cli_update_baseline_refuses_partial_runs(
    monkeypatch, tmp_path, capsys
):
    """A run with skipped entries must NEVER rewrite the ratchet: the
    skipped entries' debt would be silently pruned and resurface as
    'new' findings on the next fully-provisioned run."""
    giant = EntryPoint(
        name="fixture.needs_many", category="overlap",
        builder=lambda devs: Built(fn=lambda a: a, args=(jnp.zeros(1),)),
        min_devices=4096, line=1, doc="",
    )
    monkeypatch.setitem(ENTRY_POINTS, "fixture.needs_many", giant)
    baseline = tmp_path / "ir_baseline.json"
    baseline.write_text(json.dumps({"findings": {"x::A1::e::d": 1}}))
    rc = ir_audit.main([
        "--root", str(tmp_path), "--baseline", str(baseline),
        "--target", "fixture.needs_many", "--update-baseline",
    ])
    err = capsys.readouterr().err
    assert rc == 2 and "refusing --update-baseline" in err
    from keystone_tpu.analysis.engine import load_baseline

    assert load_baseline(str(baseline)) == {"x::A1::e::d": 1}  # untouched


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_list_json_and_exit_codes(devices, capsys):
    rc = ir_audit.main(["--list"])
    out = capsys.readouterr().out
    assert rc == 0
    for name in ("overlap.tiled_gram", "solver.tsqr", "pallas.fv_encode",
                 "dag.fused_segment"):
        assert name in out

    rc = ir_audit.main([
        "--root", REPO_ROOT, "--target", "pallas.fv_encode",
        "--format", "json",
    ])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    for key in ("new", "baselined", "stale", "stale_pragmas", "suppressed",
                "targets", "skipped", "errors", "total"):
        assert key in payload
    assert payload["targets"] == ["pallas.fv_encode"]
    assert payload["new"] == [] and payload["errors"] == []

    rc = ir_audit.main(["--target", "nonsense", "--root", REPO_ROOT])
    assert rc == 2
    capsys.readouterr()


def test_cli_skips_underprovisioned_entries_loudly(monkeypatch, capsys):
    """An entry the topology cannot place is SKIPPED and reported, never
    silently passed (the bench honesty key rides this)."""
    giant = EntryPoint(
        name="fixture.needs_many", category="overlap",
        builder=lambda devs: Built(fn=lambda a: a, args=(jnp.zeros(1),)),
        min_devices=4096, line=1, doc="",
    )
    monkeypatch.setitem(ENTRY_POINTS, "fixture.needs_many", giant)
    res = run_audit(["fixture.needs_many"], baseline_path=None)
    assert res.skipped == {
        "fixture.needs_many":
            f"needs >= 4096 devices, have {len(jax.devices())}"
    }
    assert res.findings == [] and res.files == 0
