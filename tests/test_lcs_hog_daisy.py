"""LCS / HOG / DAISY tests: naive-oracle comparisons for the conv2d contract
and LCS statistics, property tests for HOG/DAISY (the reference compared
against its original implementations' outputs; those binaries don't exist on
this platform — see tests/test_sift.py for the same policy)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops.images import (
    DaisyExtractor,
    HogExtractor,
    LCSExtractor,
    SIFTExtractor,
)
from keystone_tpu.ops.images.lcs import conv2d_same


def naive_conv2d_same(img, xf, yf):
    """Scalar reimplementation of ImageUtils.conv2D: zero-pad floor/ceil,
    true convolution per axis."""
    h, w = img.shape
    out = np.zeros_like(img)
    kx, ky = len(xf), len(yf)
    lox = (kx - 1) // 2
    loy = (ky - 1) // 2
    tmp = np.zeros_like(img)
    for y in range(h):
        for x in range(w):
            acc = 0.0
            for i in range(kx):
                src = x - lox + i
                if 0 <= src < w:
                    acc += img[y, src] * xf[kx - 1 - i]
            tmp[y, x] = acc
    for y in range(h):
        for x in range(w):
            acc = 0.0
            for i in range(ky):
                src = y - loy + i
                if 0 <= src < h:
                    acc += tmp[src, x] * yf[ky - 1 - i]
            out[y, x] = acc
    return out


def test_conv2d_same_matches_naive(rng):
    img = rng.random((9, 11)).astype(np.float32)
    for xf, yf in [
        ([1.0, 0.0, -1.0], [1.0, 2.0, 1.0]),
        ([1 / 6] * 6, [1 / 6] * 6),  # even-length box
    ]:
        got = np.asarray(conv2d_same(jnp.asarray(img), np.array(xf), np.array(yf)))
        expected = naive_conv2d_same(img.astype(np.float64), np.array(xf), np.array(yf))
        np.testing.assert_allclose(got, expected, atol=1e-4)


def test_lcs_statistics_match_naive(rng):
    img = rng.random((48, 48, 3)).astype(np.float32)
    node = LCSExtractor(stride=4, stride_start=16, sub_patch_size=6)
    out = np.asarray(node.serve(jnp.asarray(img)))
    assert out.shape == (node.num_keypoints(48, 48), 96)

    # check one keypoint/channel/offset against directly computed box stats:
    # keypoint (y=16, x=16), offset (-10, -10), channel 0: box mean over
    # rows/cols [y-10-2, y-10+3] (floor/ceil split of 6-wide box)
    y, x, off = 16, 16, -10
    py, px = y + off, x + off
    patch = img[py - 2 : py + 4, px - 2 : px + 4, 0].astype(np.float64)
    expected_mean = patch.mean()
    expected_std = np.sqrt(max((patch**2).mean() - expected_mean**2, 0.0))
    # descriptor layout: (c, ox, oy, 2); keypoint 0 is (y=16, x=16); offset
    # (-10, -10) is ox=0, oy=0 -> indices 0 (mean) and 1 (std)
    np.testing.assert_allclose(out[0, 0], expected_mean, atol=1e-4)
    np.testing.assert_allclose(out[0, 1], expected_std, atol=1e-4)


def test_lcs_constant_image_zero_std():
    img = jnp.full((48, 48, 3), 7.0)
    out = np.asarray(LCSExtractor(4, 16, 6).serve(img))
    means = out[:, 0::2]
    stds = out[:, 1::2]
    np.testing.assert_allclose(means, 7.0, atol=1e-4)
    np.testing.assert_allclose(stds, 0.0, atol=1e-4)


def test_hog_shape_and_range(rng):
    img = rng.random((40, 48, 3)).astype(np.float32)
    node = HogExtractor(bin_size=8)
    out = np.asarray(node.serve(jnp.asarray(img)))
    # 48/8=6 x-cells, 40/8=5 y-cells -> (6-2)*(5-2) = 12 interior cells
    assert out.shape == (12, 32)
    assert out.min() >= 0.0
    # clamped features bounded: sensitive/insensitive <= 0.5*4*0.2 = 0.4
    assert out[:, :27].max() <= 0.4 + 1e-6
    assert np.allclose(out[:, 31], 0.0)  # truncation feature


def test_hog_rounded_up_grid_does_not_crash(rng):
    # 44/8 = 5.5 -> 6 cells (round half up); visible region clamps to the
    # image instead of crashing
    img = jnp.asarray(rng.random((44, 44, 3)).astype(np.float32))
    out = np.asarray(HogExtractor(bin_size=8).serve(img))
    assert out.shape == ((6 - 2) * (6 - 2), 32)
    assert np.isfinite(out).all()


def test_hog_uniform_image_is_zero():
    out = np.asarray(HogExtractor(bin_size=8).serve(jnp.full((32, 32, 3), 0.5)))
    np.testing.assert_allclose(out, 0.0, atol=1e-6)


def test_hog_gradient_energy_shifts_orientation():
    # vertical stripes -> horizontal gradient -> contrast-sensitive energy
    # concentrated near orientation 0/9 (dx dominant)
    img = jnp.tile(jnp.arange(64.0)[None, :, None] % 2, (64, 1, 3))
    out = np.asarray(HogExtractor(bin_size=8).serve(img))
    sens = out[:, :18].reshape(-1, 18).sum(0)
    assert sens.argmax() in (0, 9)


def test_daisy_shape_layout_and_norms(rng):
    img = rng.random((64, 64)).astype(np.float32)
    node = DaisyExtractor()
    out = np.asarray(node.serve(jnp.asarray(img)))
    n_k = len(range(16, 48, 4)) ** 2
    assert out.shape == (n_k, 200)
    # every 8-dim histogram block is L2-normalized (or zero)
    blocks = out.reshape(n_k, 25, 8)
    norms = np.linalg.norm(blocks, axis=2)
    ok = np.isclose(norms, 1.0, atol=1e-3) | np.isclose(norms, 0.0, atol=1e-6)
    assert ok.all()


def test_daisy_constant_image_zero_interior():
    # zero-padded conv2D creates border gradients (reference behavior too);
    # keypoints far from the border see zero gradient -> zeroed histograms
    out = np.asarray(DaisyExtractor().serve(jnp.full((128, 128), 3.0)))
    n_side = len(range(16, 112, 4))
    center = out.reshape(n_side, n_side, 200)[n_side // 2, n_side // 2]
    np.testing.assert_allclose(center, 0.0, atol=1e-5)


def test_extractors_feed_fv_pipeline(rng):
    """Integration: extractor -> descriptors usable by PCA/GMM/FV."""
    from keystone_tpu.learning import GaussianMixtureModelEstimator, PCAEstimator
    from keystone_tpu.ops.images import FisherVector

    img = rng.random((48, 48)).astype(np.float32)
    descs = SIFTExtractor(scales=2).serve(jnp.asarray(img))
    pca = PCAEstimator(dims=16, method="svd").fit(descs)
    reduced = pca(descs)
    gmm = GaussianMixtureModelEstimator(k=4, num_iter=10).fit(reduced)
    fv = FisherVector(gmm=gmm).serve(reduced)
    assert fv.shape == (16, 8)
    assert np.isfinite(np.asarray(fv)).all()
