"""Hermetic multi-device test environment.

The reference simulated a cluster with Spark local mode
(``src/test/scala/pipelines/LocalSparkContext.scala``); here the analog is a
single-process 8-device CPU mesh via
``--xla_force_host_platform_device_count=8`` (SURVEY.md §4). Must run before
jax initializes a backend, hence the env mutation at import time.
"""

import os

# Belt and braces: env for fresh interpreters, jax.config for the case where
# site customization already imported jax before pytest ran.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jaxlib: the XLA_FLAGS path above already forces 8 host devices
    pass
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
