"""The bench artifact contract (VERDICT r05 headline: the blind ratchet).

``bench.py`` must leave a parseable record no matter how it dies: the full
dict goes to ``bench_full.json`` and a compact JSON line is re-printed after
EVERY section, so a driver SIGKILL/timeout at any point after the first
section still yields a last stdout line that parses (< 1500 chars) and a
current artifact — rc=124 can never again produce ``parsed: null``.

Both tests run the real ``bench.py`` in a subprocess under ``BENCH_SMOKE=1``
(tiny CPU shapes, heavy sections defaulted off — exactly what
``make bench-smoke`` runs); the kill test uses the BENCH_KILL_AFTER_SECTION
hook, which SIGKILLs the process immediately after the named section's
flush — the driver's kill, simulated at a deterministic point.
"""

import json
import os
import signal
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(tmp_path, extra_env):
    env = os.environ.copy()
    # a clean CPU environment for the child: the bench must not inherit this
    # test process's 8-device simulation flags (it sets up its own world)
    env.pop("XLA_FLAGS", None)
    env.update(
        JAX_PLATFORMS="cpu",
        BENCH_SMOKE="1",
        # 180 s: the smoke sections total ~55 s standalone, but inside a
        # loaded tier-1 suite every section runs ~2x slower and a 120 s
        # budget let the 60 s section floors skip pinned keys (the serve
        # regime subprocess pays a cold import the in-process section
        # never did) — the budget must cover the SLOWED full section list
        KEYSTONE_BENCH_BUDGET_S="180",
        BENCH_FULL_PATH=str(tmp_path / "bench_full.json"),
        BENCH_TELEMETRY_PATH=str(tmp_path / "bench_telemetry.json"),
        BENCH_XLA_CACHE=str(tmp_path / "xla_cache"),
        # isolate the secondary-section rotation from the repo's cursor
        # (and from other tests sharing this tmp_path)
        KEYSTONE_BENCH_CURSOR=str(tmp_path / "bench_cursor.json"),
    )
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py")],
        capture_output=True, text=True, timeout=540, env=env, cwd=_REPO,
    )


def _cf(v):
    """Compare a bench_full.json float the way the compact line stores it:
    bench.compact_round drops to 1 decimal at |v| >= 10, so a slow smoke
    run whose ingest fit lands at 13.195 s still mirrors as 13.2."""
    sys.path.insert(0, _REPO)
    import bench

    return bench.compact_round(v) if isinstance(v, float) else v


def _last_line(stdout: str) -> str:
    lines = [l for l in stdout.strip().splitlines() if l.strip()]
    assert lines, f"bench produced no stdout: {stdout!r}"
    return lines[-1]


def test_bench_smoke_compact_line_contract(tmp_path):
    """Clean smoke run: rc 0, last stdout line is the final (non-partial)
    compact summary, parseable and under the 1500-char tail-capture bound,
    and bench_full.json holds the full dict including the solver ladder."""
    proc = _run_bench(tmp_path, {})
    assert proc.returncode == 0, proc.stderr[-3000:]
    line = _last_line(proc.stdout)
    assert len(line) < 1500, len(line)
    compact = json.loads(line)
    assert compact["metric"] == "mnist_random_fft_fit_eval_wallclock"
    assert isinstance(compact["value"], (int, float))
    assert "partial" not in compact  # the FINAL line is not a partial flush
    full = json.loads((tmp_path / "bench_full.json").read_text())
    assert full["smoke"] is True
    # the parameterized precision/overlap ladder emitted its base cells
    # (now via the budget-derated solver_ladder subprocess regime)
    assert "solver_gflops_per_chip" in full
    assert "solver_gflops_per_chip_overlap" in full
    # ...including the randomized sketch rung and the equal-test-error
    # comparison vs the exact rung (linalg/sketch.py acceptance keys)
    assert "sketch_gflops_per_chip" in full
    assert "sketch_gflops_per_chip_overlap" in full
    assert "sketch_vs_exact_error_delta_d65536" in full
    assert "sketch_vs_exact_d" in full
    # precision-tier section (KEYSTONE_PRECISION_TIER): every bf16 speed
    # key PAIRED with its *_vs_f32_error_delta twin, plus the backend +
    # 16-bit-read-bandwidth honesty keys that contextualize the pair
    for key in (
        "gram_f32_gflops", "gram_bf16_gflops",
        "gram_bf16_vs_f32_error_delta",
        "sketch_f32_gflops", "sketch_bf16_gflops",
        "sketch_bf16_vs_f32_error_delta",
        "precision_backend", "precision_f32_read_gbs",
        "precision_bf16_read_gbs",
    ):
        assert key in full, key
    # the paired error deltas are small but REAL numbers (a None/absent
    # delta next to a ratcheting speed key is the dishonesty this pins)
    assert 0 <= full["gram_bf16_vs_f32_error_delta"] < 0.05
    assert 0 <= full["sketch_bf16_vs_f32_error_delta"] < 0.05
    assert compact["g_gram16"] == _cf(full["gram_bf16_gflops"])
    # fault-recovery pair (PR 12): a streaming fit killed mid-schedule by
    # an injected device error resumed through the production elastic
    # retry loop — the crash price, the retry count that paid it, and the
    # measured checkpoint save/load costs all on record
    assert full["resume_overhead_s"] >= 0
    assert full["retry_attempts_total"] >= 1
    assert full["checkpoint_save_s"] > 0
    assert full["checkpoint_load_s"] > 0
    assert compact["retry_n"] == full["retry_attempts_total"]
    # numerical-health pair (PR 13): a NaN block injected under
    # KEYSTONE_HEALTH=heal — the sentinels trip, the escalation ladder
    # re-runs the block, and the healed model stays inside the clean
    # twin's envelope (the error-delta honesty key next to the counters)
    assert full["health_escalations_total"] >= 1
    assert full["health_healed_total"] >= 1
    # the injected poison is transient (gone on the heal pass's fresh
    # re-featurize), so a WORKING ladder leaves nothing permanently
    # quarantined — a 1 here means heal regressed into quarantine
    assert full["health_quarantined_total"] == 0
    assert 0 <= full["health_heal_error_delta"] < 0.5
    assert compact["health_q"] == full["health_quarantined_total"]
    assert compact["health_esc"] == full["health_escalations_total"]
    # serving-gateway section (PR 14): the sustained-at-SLO row holds
    # real numbers and the saturation curve has its three points — the
    # graceful-degradation evidence next to the throughput claim
    assert full["serve_sustained_qps"] > 0
    assert full["serve_p50_ms"] > 0 and full["serve_p99_ms"] > 0
    assert 0.0 <= full["serve_shed_frac"] <= 1.0
    assert full["serve_slo_ms"] > 0
    curve = full["serve_saturation"]
    assert len(curve) == 3
    for pt in curve:
        assert set(pt) == {"offered_qps", "qps", "p50_ms", "p99_ms",
                           "shed_frac"}
        assert 0.0 <= pt["shed_frac"] <= 1.0
    # offered load sweeps upward (0.25x -> 1x -> 4x measured capacity)
    assert curve[0]["offered_qps"] < curve[1]["offered_qps"] \
        < curve[2]["offered_qps"]
    assert compact["sv_qps"] == _cf(full["serve_sustained_qps"])
    assert compact["sv_p99"] == _cf(full["serve_p99_ms"])
    assert compact["sv_shed"] == _cf(full["serve_shed_frac"])
    # streaming-ingest section (PR 15, core/ingest.py): sustained decode
    # GB/s, the overlap pair, and the never-resident flagship fit with
    # its raw-vs-peak honesty pair. The on<=off ORDERING is pinned by
    # make ingest-smoke on the calibrated workload, not here — at smoke
    # shapes the pair is a scheduler coin flip; this contract pins that
    # both numbers LAND together (a speed claim never ships without its
    # strict-sequential twin).
    assert full["ingest_gbs"] > 0
    assert full["ingest_overlap_on_s"] > 0
    assert full["ingest_overlap_off_s"] > 0
    # the never-resident evidence pair: the streamed fit completed at a
    # dataset scale whose raw footprint EXCEEDS the ring it held, and
    # its per-batch reduce program compiled exactly once
    assert full["ingest_never_resident"] is True
    assert full["ingest_raw_bytes"] > full["ingest_peak_host_bytes"] > 0
    assert full["ingest_reduce_compiles"] == 1
    assert full["ingest_fit_s"] > 0
    assert compact["in_gbs"] == _cf(full["ingest_gbs"])
    assert compact["in_ov_on"] == _cf(full["ingest_overlap_on_s"])
    assert compact["in_ov_off"] == _cf(full["ingest_overlap_off_s"])
    assert compact["in_fit"] == _cf(full["ingest_fit_s"])
    # whole-pipeline-optimizer rows (core/plan.py): the flagship plan's
    # decisions landed, and the repeat plan in the same process performed
    # ZERO re-plans (the content-fingerprinted memo served it)
    assert full["plan_block_size"] > 0
    assert full["plan_segments"] >= 1
    assert isinstance(full["plan_fits"], bool)
    assert full["plan_replans"] == 0
    assert full["plan_est_peak_hbm_gb"] >= 0
    assert compact["plan_replans"] == 0
    # pipeline-contract hygiene rows (keystone_tpu/analysis/check.py): all
    # registered targets checked, zero new findings, and the compact line
    # carries the series
    assert full["check_new"] == 0
    assert full["check_findings_total"] >= 0
    assert full["check_targets"] >= 5
    assert compact["check"] == full["check_findings_total"]
    # structured-telemetry contract: telemetry_* keys in the COMPACT line,
    # non-zero span/counter headcounts, and a loadable artifact whose
    # Chrome trace is Perfetto-shaped
    assert compact["telemetry_spans"] > 0
    assert compact["telemetry_counters"] > 0
    assert full["telemetry_timer_stages"] > 0
    bt = json.loads((tmp_path / "bench_telemetry.json").read_text())
    assert bt["metrics"]["counters"]
    events = bt["chrome_trace"]["traceEvents"]
    assert events and all(
        f in ev for ev in events for f in ("name", "ph", "ts", "dur")
    )
    # every line printed along the way parses too (the incremental flushes)
    for l in proc.stdout.strip().splitlines():
        json.loads(l)


def test_bench_survives_sigkill_after_first_section(tmp_path):
    """SIGKILL right after the first section's flush (the simulated driver
    timeout): the process dies hard, but the LAST stdout line still parses
    as a compact summary (marked partial) and bench_full.json is current."""
    proc = _run_bench(tmp_path, {"BENCH_KILL_AFTER_SECTION": "primary"})
    assert proc.returncode == -signal.SIGKILL, (
        proc.returncode, proc.stderr[-2000:]
    )
    line = _last_line(proc.stdout)
    assert len(line) < 1500
    compact = json.loads(line)
    assert compact.get("partial") is True
    assert compact["metric"] == "mnist_random_fft_fit_eval_wallclock"
    full = json.loads((tmp_path / "bench_full.json").read_text())
    assert full["metric"] == "mnist_random_fft_fit_eval_wallclock"


def test_bench_budget_skips_big_regimes(tmp_path):
    """A zero budget must not kill the run: every budget-gated section is
    skipped with an explicit marker and the final line still prints."""
    proc = _run_bench(
        tmp_path,
        {
            "KEYSTONE_BENCH_BUDGET_S": "0",
            # force subprocess regimes ON so the derate path (not just
            # the env gate) is what skips them
            "BENCH_FLAGSHIP": "1",
            "BENCH_FLEET": "1",
        },
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    compact = json.loads(_last_line(proc.stdout))
    assert "partial" not in compact
    full = json.loads((tmp_path / "bench_full.json").read_text())
    assert full.get("imagenet_refdim_streaming_warm_s_skipped") == "budget"
    # the planner section exhausts gracefully too (no plan rows, a marker)
    assert full.get("plan_skipped") == "budget"
    assert "plan_block_size" not in full
    # ... and the IR-audit section (PR 9): same reduced-floor contract
    assert full.get("audit_skipped") == "budget"
    assert "audit_findings_total" not in full
    # ... and the pipeline-contract section: same reduced-floor contract
    assert full.get("check_skipped") == "budget"
    assert "check_findings_total" not in full
    # ... and the lock-discipline section (PR 20): same reduced-floor
    # contract — no hygiene count may land without its budget story
    assert full.get("race_skipped") == "budget"
    assert "race_findings_total" not in full
    # ... and the precision-tier section (PR 11): same reduced-floor
    # contract — no speed key may land without its budget story
    assert full.get("precision_skipped") == "budget"
    assert "gram_bf16_gflops" not in full
    # ... and the fault-recovery section (PR 12): same reduced-floor
    # contract
    assert full.get("faults_skipped") == "budget"
    assert "resume_overhead_s" not in full
    # ... and the numerical-health section (PR 13): same reduced-floor
    # contract — no counter may land without its budget story
    assert full.get("health_skipped") == "budget"
    assert "health_quarantined_total" not in full
    # ... and the serving-gateway section (PR 14): same reduced-floor
    # contract — no QPS claim may land without its budget story
    assert full.get("serve_skipped") == "budget"
    assert "serve_sustained_qps" not in full
    # ... and the streaming-ingest section (PR 15): same reduced-floor
    # contract — no decode-GB/s claim may land without its budget story
    assert full.get("ingest_skipped") == "budget"
    assert "ingest_gbs" not in full
    # ... and the fleet regime: no scaling claim without its budget story
    assert full.get("fleet_qps_scale_skipped") == "budget"
    assert full.get("fleet_qps_scale") is None
    # the fleet observability keys ride the same regime — a skipped fleet
    # run must not land server-side shed/p99 claims either
    assert full.get("fleet_shed_frac") is None
    assert full.get("fleet_p99_ms") is None
    assert full.get("fleet_breaker_trips") is None
    assert full.get("telemetry_merge_procs") is None
    # the secondary sections starve too, but the rotation STILL advances
    # and is recorded — a fully-starved run must not freeze the cursor
    assert full["bench_secondary_cursor"] == 0
    assert full["bench_secondary_order"].startswith("extras,")
    cursor = json.loads((tmp_path / "bench_cursor.json").read_text())
    assert cursor["secondary"] == 1


def test_bench_secondary_cursor_rotates_across_runs(tmp_path):
    """The bench-budget rebalance (BENCH_r06–r08): the in-process secondary
    sections rotate their start index across runs via the persisted
    cursor, so a budget that exhausts partway down the list starves a
    DIFFERENT suffix each run — every section gets fresh coverage within
    len(sections) runs instead of the tail never running. Zero budget
    keeps both runs fast; the rotation must advance regardless."""
    runs = []
    for _ in range(2):
        proc = _run_bench(tmp_path, {"KEYSTONE_BENCH_BUDGET_S": "0"})
        assert proc.returncode == 0, proc.stderr[-3000:]
        runs.append(
            json.loads((tmp_path / "bench_full.json").read_text())
        )
    first, second = runs
    assert first["bench_secondary_cursor"] == 0
    assert second["bench_secondary_cursor"] == 1
    order1 = first["bench_secondary_order"].split(",")
    order2 = second["bench_secondary_order"].split(",")
    # same sections, rotated by one: run 2 starts where run 1's second
    # section was, and the full multiset is preserved
    assert sorted(order1) == sorted(order2)
    assert order1 != order2
    assert order2[0] == order1[1]
    assert order2 == order1[1:] + order1[:1]
    # every secondary section in run 2 still got its budget marker (zero
    # budget): rotation changes WHO starves first, never the contract
    for name in order2:
        assert second.get(f"{name}_skipped") == "budget"


def test_bench_cursor_concurrent_rotations_lose_no_increment(tmp_path):
    """Regression for the keystone-race T5 finding on ``_rotate_secondary``:
    the cursor read->increment->replace window now runs under the flock
    sidecar, so N bench processes sharing one cursor file each advance it
    by exactly one — a lost increment would replay the same prefix and
    starve the tail sections again.  Four concurrent rotations of a
    2-section list must use cursors 0,1,0,1 (each section twice), never a
    duplicated read."""
    script = (
        "import bench\n"
        "cursor, rotated = bench._rotate_secondary(['a', 'b'])\n"
        "assert rotated in (['a', 'b'], ['b', 'a'])\n"
        "print('CURSOR', cursor)\n"
    )
    env = os.environ.copy()
    env.pop("XLA_FLAGS", None)
    env.update(
        JAX_PLATFORMS="cpu",
        KEYSTONE_BENCH_CURSOR=str(tmp_path / "cursor.json"),
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=_REPO,
        )
        for _ in range(4)
    ]
    cursors = []
    for p in procs:
        out, err = p.communicate(timeout=120)
        assert p.returncode == 0, err[-2000:]
        cursors.append(int(out.split()[-1]))
    assert sorted(cursors) == [0, 0, 1, 1], cursors
    # flock-serialized: the last writer saw cursor 1 and persisted 2
    final = json.loads((tmp_path / "cursor.json").read_text())
    assert final["secondary"] == 2


def test_bench_section_floor_exhaustion_is_graceful(tmp_path):
    """The run-5 rc=124 class: budget exhaustion mid-run must yield
    explicit ``<key>_skipped`` markers and rc=0, never the harness timeout.
    A section floor no regime can meet forces the before-entry enforcement
    on EVERY derated subprocess section — including the solver ladder, the
    heavy section that used to run in-process with no enforceable bound —
    and the final compact line must still be the clean (non-partial) one."""
    proc = _run_bench(
        tmp_path,
        {
            "KEYSTONE_BENCH_SECTION_FLOOR_S": "999999",
            # force big regimes ON so the derate path (not the env
            # gate) is what skips them
            "BENCH_FLAGSHIP": "1",
            "BENCH_EXTRACTION": "1",
            # gate the ingest section OFF: checked BEFORE its budget
            # floor, so the section must emit neither rows nor a marker
            "BENCH_INGEST": "0",
        },
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    compact = json.loads(_last_line(proc.stdout))
    assert "partial" not in compact
    full = json.loads((tmp_path / "bench_full.json").read_text())
    # BENCH_INGEST=0: gated off entirely — no rows AND no budget marker
    assert "ingest_gbs" not in full
    assert "ingest_skipped" not in full
    assert full.get("solver_gflops_per_chip_skipped") == "budget"
    assert (
        full.get("sketch_vs_exact_error_delta_d65536_skipped") == "budget"
    )
    assert full.get("imagenet_refdim_streaming_warm_s_skipped") == "budget"
    # the PR-7 extraction-kernel regime honors the same contract
    assert full.get("sift_pallas_on_gflops_skipped") == "budget"
    # the primary metric itself still landed
    assert compact["metric"] == "mnist_random_fft_fit_eval_wallclock"


def test_fleet_obs_bench_keys(tmp_path, monkeypatch):
    """The BENCH_FLEET observability emissions are exact functions of the
    merged per-process shards: shed fraction and breaker trips equal the
    cross-shard counter sums, fleet_p99_ms comes from the UNIONED
    serve.latency_ms histograms, and telemetry_merge_procs honestly counts
    the process shards the merge saw (no subprocess needed — bench_keys is
    the same code path the fleet regime calls after its observed arm)."""
    from keystone_tpu.telemetry.fleet import bench_keys, export_process
    from keystone_tpu.telemetry.registry import (
        LATENCY_BUCKETS_MS,
        MetricsRegistry,
    )

    for role, lats in (("replica-0", (2.0, 4.0)), ("replica-1", (8.0, 400.0))):
        monkeypatch.setenv("KEYSTONE_TELEMETRY_ROLE", role)
        reg = MetricsRegistry()
        reg.inc("serve.responses", 2, code="ok")
        reg.inc("serve.responses", code="shed")
        reg.inc("serve.shed_total", reason="overload")
        reg.inc("serve.breaker", event="open")
        for lat in lats:
            reg.observe("serve.latency_ms", lat,
                        buckets=LATENCY_BUCKETS_MS, model="default")
        export_process(str(tmp_path), registry=reg)

    keys = bench_keys(str(tmp_path))
    assert keys["telemetry_merge_procs"] == 2
    assert keys["fleet_breaker_trips"] == 2
    assert keys["fleet_shed_frac"] == round(2 / 6, 4)
    # 4 merged observations (2, 4, 8, 400): the q=0.99 estimate must land
    # in the top histogram bucket, clamped by the recorded max
    assert 250.0 < keys["fleet_p99_ms"] <= 400.0
