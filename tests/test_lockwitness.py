"""The KEYSTONE_LOCK_WITNESS runtime sanitizer (utils/lockwitness.py):
the zero-overhead off path (identity, no wrapper — pinned), inversion
detection on an A->B / B->A interleave, the PR-15 ``_claim_slot``
deadlock replay flagged in seconds, telemetry counters, and the
preserved lock semantics of the wrapper itself.
"""

import threading
import time

import pytest

from keystone_tpu.utils import lockwitness
from keystone_tpu.utils.lockwitness import WitnessLock, register_lock


@pytest.fixture
def witness_on(monkeypatch):
    monkeypatch.setenv("KEYSTONE_LOCK_WITNESS", "1")
    lockwitness.reset()
    yield
    lockwitness.reset()


# ---------------------------------------------------------------------------
# The off path: identity, not a wrapper
# ---------------------------------------------------------------------------

def test_knob_off_returns_bare_lock_unchanged(monkeypatch):
    """The zero-overhead contract: with the knob unset (the default) and
    with an explicit 0, register_lock returns the SAME object — no
    wrapper type, no indirection, byte-identical lock behavior."""
    monkeypatch.delenv("KEYSTONE_LOCK_WITNESS", raising=False)
    bare = threading.Lock()
    assert register_lock(bare, "off.lock") is bare
    rlock = threading.RLock()
    assert register_lock(rlock, "off.rlock") is rlock

    monkeypatch.setenv("KEYSTONE_LOCK_WITNESS", "0")
    assert register_lock(bare, "off.lock") is bare
    assert not lockwitness.enabled()


def test_knob_on_wraps(witness_on):
    wrapped = register_lock(threading.Lock(), "on.lock")
    assert isinstance(wrapped, WitnessLock)
    assert wrapped.name == "on.lock"


# ---------------------------------------------------------------------------
# The wrapper preserves lock semantics
# ---------------------------------------------------------------------------

def test_wrapper_semantics_preserved(witness_on):
    lk = register_lock(threading.Lock(), "sem.lock")
    assert lk.acquire() is True
    assert lk.locked()
    assert lk.acquire(blocking=False) is False  # a Lock, not an RLock
    lk.release()
    assert not lk.locked()
    with lk:
        assert lk.locked()
    assert not lk.locked()
    # bounded acquire passes through
    assert lk.acquire(timeout=0.1) is True
    lk.release()


def test_rlock_reentry_is_not_an_order_edge(witness_on):
    rl = register_lock(threading.RLock(), "re.lock")
    with rl:
        with rl:
            pass
    assert lockwitness.events() == []


# ---------------------------------------------------------------------------
# Inversion: A->B somewhere, B->A anywhere = one event
# ---------------------------------------------------------------------------

def test_inversion_detected_without_deadlocking(witness_on):
    """The static T1, at runtime: the witness flags the ORDER on a clean
    sequential interleave — no actual deadlock required."""
    from keystone_tpu.telemetry import get_registry

    before = get_registry().get_counter("witness.inversion")
    a = register_lock(threading.Lock(), "inv.a")
    b = register_lock(threading.Lock(), "inv.b")
    with a:
        with b:
            pass
    assert lockwitness.events("inversion") == []
    with b:
        with a:
            pass
    events = lockwitness.events("inversion")
    assert len(events) == 1, events
    ev = events[0]
    assert ev["order"] == "inv.b->inv.a"
    assert ev["reverse"] == "inv.a->inv.b"
    assert get_registry().get_counter("witness.inversion") == before + 1

    # report-once: replaying the same pair stays one event
    with b:
        with a:
            pass
    assert len(lockwitness.events("inversion")) == 1


# ---------------------------------------------------------------------------
# Held-while-blocking: the PR-15 _claim_slot deadlock replay
# ---------------------------------------------------------------------------

def test_pr15_deadlock_replay_flagged_fast(witness_on):
    """The buffers=1/threads>=2 shape from PR 15's review: a worker
    blocks on the (held, never-draining) ring while holding the claim
    lock.  The witness must DIAGNOSE it — a held_blocking event naming
    both locks — well inside 5 s, instead of the process just hanging."""
    from keystone_tpu.telemetry import get_registry

    before = get_registry().get_counter("witness.held_blocking")
    ring = register_lock(threading.Lock(), "replay.ring")
    claim = register_lock(threading.Lock(), "replay.claim")
    ring.acquire()
    try:
        def worker():
            with claim:
                with ring:
                    pass

        t = threading.Thread(target=worker, daemon=True)
        t0 = time.monotonic()
        t.start()
        events = []
        while time.monotonic() - t0 < 5.0:
            events = lockwitness.events("held_blocking")
            if events:
                break
            time.sleep(0.05)
        flagged_s = time.monotonic() - t0
    finally:
        ring.release()
    t.join(5.0)
    assert not t.is_alive()
    assert events, f"no held_blocking event within {flagged_s:.1f}s"
    ev = events[0]
    assert ev["held"] == "replay.claim"
    assert ev["blocked_on"] == "replay.ring"
    assert ev["waited_s"] >= lockwitness.HELD_BLOCK_THRESHOLD_S
    assert flagged_s < 5.0
    assert get_registry().get_counter("witness.held_blocking") == before + 1


def test_bounded_wait_under_lock_not_flagged(witness_on):
    """A timeout= acquire is a bounded wait — the witness records no
    held_blocking event for it (mirrors the static T2 exemption)."""
    outer = register_lock(threading.Lock(), "bounded.outer")
    inner = register_lock(threading.Lock(), "bounded.inner")
    inner.acquire()
    try:
        with outer:
            assert inner.acquire(timeout=0.2) is False
    finally:
        inner.release()
    assert lockwitness.events("held_blocking") == []


def test_reset_clears_events_and_report_once_state(witness_on):
    a = register_lock(threading.Lock(), "rst.a")
    b = register_lock(threading.Lock(), "rst.b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert lockwitness.events("inversion")
    lockwitness.reset()
    assert lockwitness.events() == []
    # after reset the pair reports fresh again
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert len(lockwitness.events("inversion")) == 1
