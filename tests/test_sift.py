"""Dense SIFT tests: independent naive-numpy oracle of the same documented
vl_dsift flat-window algorithm, plus geometry/quantization/threshold
properties (the reference validated against MATLAB vl_phow with a
quantization tolerance, VLFeatSuite.scala:44-51; no vlfeat binary for this
platform exists here, so the oracle is a from-scratch scalar reimplementation)."""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops.images.sift import (
    CONTRAST_THRESHOLD,
    DESC_DIM,
    NUM_BIN_S,
    NUM_BIN_T,
    SIFTExtractor,
    _TRANSPOSE_PERM,
    dsift_geometry,
)


def naive_gaussian_blur(img, sigma):
    if sigma <= 0:
        return img
    radius = max(1, int(math.ceil(4.0 * sigma)))
    t = np.arange(-radius, radius + 1)
    k = np.exp(-0.5 * (t / sigma) ** 2)
    k /= k.sum()
    padded = np.pad(img, radius, mode="edge")
    tmp = np.zeros_like(padded)
    for i in range(padded.shape[0]):
        tmp[i] = np.convolve(padded[i], k, mode="same")
    out = np.zeros_like(padded)
    for j in range(padded.shape[1]):
        out[:, j] = np.convolve(tmp[:, j], k, mode="same")
    return out[radius:-radius, radius:-radius]


def naive_dsift_one_scale(img, step, bin_size, min_bound):
    """Scalar-loop dsift (flat window box bins), written independently of the
    XLA implementation."""
    h, w = img.shape
    gy, gx = np.gradient(img)
    mag = np.sqrt(gx**2 + gy**2)
    ang = np.arctan2(gy, gx)
    ft = np.mod(ang / (2 * np.pi) * NUM_BIN_T, NUM_BIN_T)

    energies = np.zeros((NUM_BIN_T, h, w))
    b0 = np.floor(ft).astype(int) % NUM_BIN_T
    r = ft - np.floor(ft)
    for y in range(h):
        for x in range(w):
            energies[b0[y, x], y, x] += (1 - r[y, x]) * mag[y, x]
            energies[(b0[y, x] + 1) % NUM_BIN_T, y, x] += r[y, x] * mag[y, x]

    ny, nx = dsift_geometry(w, h, step, bin_size, min_bound)
    descs = np.zeros((ny * nx, DESC_DIM))
    masses = np.zeros(ny * nx)
    idx = 0
    for fy in range(ny):
        for fx in range(nx):
            oy = min_bound + fy * step
            ox = min_bound + fx * step
            d = np.zeros(DESC_DIM)
            for by in range(NUM_BIN_S):
                for bx in range(NUM_BIN_S):
                    cy = oy + by * bin_size - bin_size // 2
                    cx = ox + bx * bin_size - bin_size // 2
                    cy = min(max(cy, 0), h - bin_size)
                    cx = min(max(cx, 0), w - bin_size)
                    window = energies[:, cy : cy + bin_size, cx : cx + bin_size]
                    for t in range(NUM_BIN_T):
                        # vl layout t + T*(x_vl + 4*y_vl) with vl-x = our axis 0
                        d[t + NUM_BIN_T * (by + NUM_BIN_S * bx)] = window[t].sum()
            mass = np.linalg.norm(d)
            masses[idx] = mass
            d = d / max(mass, 1e-10)
            d = np.minimum(d, 0.2)
            d = d / max(np.linalg.norm(d), 1e-10)
            descs[idx] = d
            idx += 1
    return descs, masses


def test_geometry_formula():
    # 32x32, step 3, bin 4, bound 9: range = (31-9) - 12 = 10 -> 10//3+1 = 4
    assert dsift_geometry(32, 32, 3, 4, 9) == (4, 4)
    # degenerate: bounds too tight
    assert dsift_geometry(10, 10, 3, 4, 9) == (0, 0)


def test_sift_matches_naive_oracle(rng):
    img = rng.random((24, 26)).astype(np.float32)
    step, bin_size, min_bound = 2, 4, 3
    # single scale with no smoothing: exercise the core dsift path
    node = SIFTExtractor(step_size=step, bin_size=bin_size, scales=1, scale_step=0)
    # scales=1 -> min_bound = (1+2*1) - 0 = 3, sigma = 4/6
    smoothed = naive_gaussian_blur(img.astype(np.float64), bin_size / 6.0)
    expected, masses = naive_dsift_one_scale(smoothed, step, bin_size, 3)
    expected = expected[:, _TRANSPOSE_PERM]
    expected = np.where(
        (masses > CONTRAST_THRESHOLD)[:, None],
        np.minimum(np.floor(512 * expected), 255),
        0.0,
    )
    got = np.asarray(node.serve(jnp.asarray(img)))
    assert got.shape == expected.shape
    # reference tolerance policy: ≥99.5% of entries within 1 after 512× quant
    close = np.abs(got - expected) <= 1.0
    assert close.mean() >= 0.995, f"only {close.mean():.4f} within 1"


def test_sift_multiscale_shape_and_range(rng):
    img = rng.random((32, 32)).astype(np.float32)
    node = SIFTExtractor()  # defaults: step 3, bin 4, scales 4, scale_step 1
    out = np.asarray(node.serve(jnp.asarray(img)))
    assert out.shape == (node.num_descriptors(32, 32), 128)
    assert out.shape[0] > 0
    assert out.min() >= 0 and out.max() <= 255


def test_sift_low_contrast_zeroed():
    img = jnp.full((32, 32), 0.5)  # constant image: zero gradient mass
    out = np.asarray(SIFTExtractor().serve(img))
    np.testing.assert_allclose(out, 0.0)


def test_sift_batch_matches_single(rng):
    imgs = rng.random((3, 32, 32)).astype(np.float32)
    node = SIFTExtractor(scales=2)
    batch = np.asarray(node(jnp.asarray(imgs)))
    single = np.asarray(node.serve(jnp.asarray(imgs[2])))
    np.testing.assert_allclose(batch[2], single, atol=1e-4)


def test_bin_aggregation_paths_agree(rng):
    """The TPU selection-matmul form and the reduce_window+gather form of
    the per-scale bin aggregation are the same sum in different fp orders —
    pin their agreement so the backend-gated dispatch can never hide a
    divergence (impl='auto' picks by backend; both forced here)."""
    from keystone_tpu.ops.images.sift import _dsift_single_scale

    img = jnp.asarray(rng.random((3, 48, 40)).astype(np.float32))
    a, _ = _dsift_single_scale(img, 3, 4, 9, 48, 40, impl="matmul")
    b, _ = _dsift_single_scale(img, 3, 4, 9, 48, 40, impl="window")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_conv1d_same_impls_agree(rng):
    """Banded-matmul vs lax.conv forms of the separable 'same' convolution
    (zero AND edge padding) — forced-path parity for the backend-gated
    dispatch in image_utils._conv1d_same."""
    from keystone_tpu.ops.images.image_utils import _conv1d_same

    x = jnp.asarray(rng.random((5, 31)).astype(np.float32))
    for k in (3, 6, 9):
        filt = rng.random(k).astype(np.float32)
        for mode in ("zero", "edge"):
            a = _conv1d_same(x, filt, -1, mode=mode, impl="matmul")
            b = _conv1d_same(x, filt, -1, mode=mode, impl="conv")
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5,
                err_msg=f"k={k} mode={mode}",
            )
