"""Solver correctness via math invariants, mirroring the reference suites
(``LinearMapperSuite``, ``BlockLinearMapperSuite``,
``BlockWeightedLeastSquaresSuite`` zero-gradient checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.core.dataset import pad_rows
from keystone_tpu.linalg import (
    block_coordinate_descent_l2,
    normal_equations_solve,
    tsqr_r,
    tsqr_solve,
)
from keystone_tpu.learning import (
    BlockLeastSquaresEstimator,
    BlockLinearMapper,
    LinearMapEstimator,
    LinearMapper,
)
from keystone_tpu.parallel import distribute, make_mesh, use_mesh


def _planted(rng, n=256, d=24, c=3, noise=0.0):
    A = rng.normal(size=(n, d)).astype(np.float32)
    W = rng.normal(size=(d, c)).astype(np.float32)
    b = A @ W + noise * rng.normal(size=(n, c)).astype(np.float32)
    return A, W, b


def test_normal_equations_recovers_planted_model(rng):
    A, W, b = _planted(rng)
    What = np.asarray(normal_equations_solve(A, b))
    np.testing.assert_allclose(What, W, atol=1e-2)


def test_normal_equations_ridge_gradient_zero(rng):
    """Ridge solution invariant: Aᵀ(AW-b) + λW = 0."""
    A, _, b = _planted(rng, noise=0.5)
    lam = 3.0
    W = np.asarray(normal_equations_solve(A, b, lam))
    grad = A.T @ (A @ W - b) + lam * W
    assert np.abs(grad).max() < 2e-2


def test_tsqr_r_matches_gram(rng, devices):
    mesh = make_mesh()
    A = rng.normal(size=(64, 8)).astype(np.float32)
    with use_mesh(mesh):
        R = np.asarray(tsqr_r(jnp.asarray(A), mesh))
    np.testing.assert_allclose(R.T @ R, A.T @ A, atol=1e-3)


def test_tsqr_solve_matches_normal_equations(rng, devices):
    A, _, b = _planted(rng, n=128, d=16, noise=0.3)
    lam = 1.5
    mesh = make_mesh()
    with use_mesh(mesh):
        W1 = np.asarray(tsqr_solve(jnp.asarray(A), jnp.asarray(b), lam, mesh=mesh))
    W2 = np.asarray(normal_equations_solve(A, b, lam))
    np.testing.assert_allclose(W1, W2, atol=1e-3)


def test_bcd_single_block_equals_normal_equations(rng):
    A, _, b = _planted(rng, d=16, noise=0.2)
    lam = 2.0
    W_bcd = np.asarray(block_coordinate_descent_l2(A, b, lam, block_size=16))
    W_ne = np.asarray(normal_equations_solve(A, b, lam))
    np.testing.assert_allclose(W_bcd, W_ne, atol=1e-4)


def test_bcd_converges_to_zero_gradient(rng):
    """Multi-block BCD after several passes: ridge gradient ≈ 0
    (the reference's independent-gradient check,
    BlockWeightedLeastSquaresSuite.scala:71)."""
    A, _, b = _planted(rng, n=200, d=30, noise=0.5)
    lam = 4.0
    W = np.asarray(block_coordinate_descent_l2(A, b, lam, block_size=8, num_iter=20))
    grad = A.T @ (A @ W - b) + lam * W
    assert np.abs(grad).max() < 1e-2


def test_bcd_feature_padding_weights_are_zero(rng):
    A, _, b = _planted(rng, d=10, noise=0.1)
    W = np.asarray(block_coordinate_descent_l2(A, b, 1.0, block_size=8, num_iter=3))
    assert W.shape == (10, 3)  # padded cols trimmed


def test_bcd_masked_rows_ignored(rng):
    A, _, b = _planted(rng, n=100, d=12, noise=0.2)
    lam = 1.0
    W_full = np.asarray(block_coordinate_descent_l2(A, b, lam, block_size=4, num_iter=5))
    Ap, mask = pad_rows(jnp.asarray(A), 16)
    bp, _ = pad_rows(jnp.asarray(b), 16)
    # poison the padding rows; mask must hide them
    Ap = Ap.at[100:].set(99.0)
    bp = bp.at[100:].set(-99.0)
    W_masked = np.asarray(
        block_coordinate_descent_l2(Ap, bp, lam, block_size=4, num_iter=5, mask=mask)
    )
    np.testing.assert_allclose(W_masked, W_full, atol=1e-4)


def test_linear_map_estimator_centers_and_recovers(rng):
    """OLS with intercept: recovers model on shifted data
    (LinearMapperSuite.scala:11-34)."""
    A, W, b = _planted(rng, noise=0.0)
    A_shift = A + 5.0
    b_shift = b + 2.0
    model = LinearMapEstimator().fit(jnp.asarray(A_shift), jnp.asarray(b_shift))
    pred = np.asarray(model(jnp.asarray(A_shift)))
    np.testing.assert_allclose(pred, b_shift, atol=5e-2)
    # single-item serving path agrees
    one = np.asarray(model.serve(jnp.asarray(A_shift[0])))
    np.testing.assert_allclose(one, pred[0], atol=1e-3)


def test_linear_map_estimator_tsqr(rng, devices):
    A, W, b = _planted(rng)
    mesh = make_mesh()
    with use_mesh(mesh):
        model = LinearMapEstimator(lam=0.01, solver="tsqr").fit(
            jnp.asarray(A), jnp.asarray(b)
        )
        pred = np.asarray(model(jnp.asarray(A)))
    np.testing.assert_allclose(pred, b, atol=5e-2)


def test_block_mapper_equals_dense_mapper(rng):
    """Block model ≡ dense model, incl. the streaming evaluate path
    (BlockLinearMapperSuite.scala:17-54)."""
    A, _, b = _planted(rng, n=128, d=32, noise=0.3)
    est = BlockLeastSquaresEstimator(block_size=8, num_iter=10, lam=2.0)
    block_model = est.fit(jnp.asarray(A), jnp.asarray(b))

    dense = LinearMapper(
        w=block_model.w, b=block_model.b,
        feature_scaler=None,
    )
    centered = jnp.asarray(A) - block_model.feature_means
    np.testing.assert_allclose(
        np.asarray(block_model(jnp.asarray(A))),
        np.asarray(dense(centered)),
        atol=1e-4,
    )

    # streaming path: last partial equals the full prediction
    partials = []
    block_model.apply_and_evaluate(jnp.asarray(A), lambda p: partials.append(np.asarray(p)))
    assert len(partials) == 4  # 32 / 8
    np.testing.assert_allclose(
        partials[-1], np.asarray(block_model(jnp.asarray(A))), atol=1e-4
    )


def test_block_estimator_on_sharded_dataset(rng, devices):
    A, _, b = _planted(rng, n=120, d=16, noise=0.2)
    mesh = make_mesh()
    with use_mesh(mesh):
        ds = distribute(jnp.asarray(A))
        labels, _ = pad_rows(jnp.asarray(b), 8)
        est = BlockLeastSquaresEstimator(block_size=8, num_iter=5, lam=1.0)
        model = est.fit(ds.data, labels, mask=ds.mask)
    W_local = np.asarray(
        block_coordinate_descent_l2(A - A.mean(0), b - b.mean(0), 1.0, block_size=8, num_iter=5)
    )
    np.testing.assert_allclose(np.asarray(model.w), W_local, atol=1e-3)


def test_block_estimator_accepts_block_sequence(rng):
    A, _, b = _planted(rng, n=64, d=16, noise=0.1)
    blocks = [jnp.asarray(A[:, :8]), jnp.asarray(A[:, 8:])]
    est = BlockLeastSquaresEstimator(block_size=8, num_iter=5, lam=1.0)
    m1 = est.fit(blocks, jnp.asarray(b))
    m2 = est.fit(jnp.asarray(A), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(m1.w), np.asarray(m2.w), atol=1e-5)


def test_bcd_feature_sharded_2d_mesh(rng, devices):
    """BCD with A sharded over BOTH mesh axes — rows over ``data``, feature
    columns over ``model`` (the 256k-dim FV regime, SURVEY.md §5): same
    solution as the replicated-columns solve."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(data=4, model=2)
    A, Wtrue, b = _planted(rng, n=256, d=64, noise=0.0)
    with use_mesh(mesh):
        Aj = jax.device_put(jnp.asarray(A), NamedSharding(mesh, P("data", "model")))
        bj = jax.device_put(jnp.asarray(b), NamedSharding(mesh, P("data", None)))
        W = np.asarray(
            block_coordinate_descent_l2(Aj, bj, 0.0, block_size=16, num_iter=30)
        )
    np.testing.assert_allclose(W, np.asarray(Wtrue), atol=1e-4)
