"""Independent cross-implementation oracles (round-4 VERDICT item 1).

The reference anchors extractor/learner correctness in *external* golden
implementations: vlfeat descriptors within a quantized tolerance
(``src/test/scala/utils/external/VLFeatSuite.scala:44-51``) and the enceval
C++ EM recovering planted Gaussians
(``src/test/scala/utils/external/EncEvalSuite.scala:42-64``). The analog
here uses the independent implementations actually present in this image —
none of them shares a line of code (or an author) with ``keystone_tpu``:

- **OpenCV** (``cv2.SIFT_create``) for SIFT descriptors on the reference's
  own test photos;
- **scikit-learn** for GMM-EM (planted mixtures AND real SIFT
  descriptors), PCA, LDA, and multinomial Naive Bayes;
- **scipy / torch** for convolution paths (Convolver vs
  ``torch.nn.functional.conv2d`` + an explicit im2col oracle, DAISY
  gradient maps vs ``scipy.signal.convolve2d``, PaddedFFT vs
  ``scipy.fft``).

Validated against: cv2 5.0.0, scikit-learn 1.9.0, scipy 1.17.0,
torch 2.13.0 (``test_oracle_versions_recorded`` pins the majors so a
silent downgrade can't hollow the suite out).

SIFT tolerance policy (stated like the reference's ≥99.5%-within-1 rule,
which applies only to *same-algorithm* vlfeat-vs-vlfeat comparison): exact
equality with OpenCV is impossible by construction — vl_phow-style dense
SIFT uses flat (box) spatial windows and per-scale Gaussian smoothing of
the input, while OpenCV SIFT uses Gaussian-weighted trilinear binning on
its own scale pyramid. What must hold is *structural agreement on the same
keypoints under the analytically-derived layout mapping*: our pre-transpose
element order is (x_bin, y_bin, t) with orientation measured from the
y-down gradient, OpenCV's is (y_bin, x_bin, o) with its y-gradient negated
— so the mapping is a spatial-axis swap plus orientation flip
t -> (8 - t) mod 8. Measured on the reference photos this mapping gives
median per-keypoint Pearson correlation 0.877-0.898 with ≥98.5% of
keypoints above 0.5, while the best *wrong* orientation mapping scores
≤ 0.38 — the thresholds below (0.8 / 0.97 / 0.55) sit between the measured
signal and the measured confounds.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

_RES = "/root/reference/src/test/resources/images"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(_RES), reason="reference fixture images not mounted"
)


def test_oracle_versions_recorded():
    """Pin the oracle majors this suite was validated against."""
    import cv2
    import scipy
    import sklearn
    import torch

    assert int(cv2.__version__.split(".")[0]) >= 4
    assert tuple(map(int, sklearn.__version__.split(".")[:2])) >= (1, 3)
    assert tuple(map(int, scipy.__version__.split(".")[:2])) >= (1, 10)
    assert int(torch.__version__.split(".")[0]) >= 2


def _gray_u8(name):
    from PIL import Image

    return np.asarray(Image.open(os.path.join(_RES, name)).convert("L"), np.uint8)


# ---------------------------------------------------------------------------
# (a) SIFT vs OpenCV
# ---------------------------------------------------------------------------


def _our_sift_with_grid(gray01):
    """Descriptors + the (x, y, bin_size) keypoint grid they were sampled on.

    Grid geometry mirrors ``SIFTExtractor._extract``: per scale the frame
    origin is min_bound + f·step and the 4x4 spatial bins of width bin_s
    are centered at origin + i·bin_s, so the descriptor center sits at
    origin + 1.5·bin_s on each axis.
    """
    from keystone_tpu.ops.images.sift import SIFTExtractor, dsift_geometry

    h, w = gray01.shape
    sift = SIFTExtractor()
    descs = np.asarray(sift.apply(jnp.asarray(gray01)))
    grid = []
    for s in range(sift.scales):
        bin_s = sift.bin_size + 2 * s
        step_s = sift.step_size + s * sift.scale_step
        mb = (1 + 2 * sift.scales) - 3 * s
        ny, nx = dsift_geometry(w, h, step_s, bin_s, mb)
        for fy in range(ny):
            for fx in range(nx):
                grid.append(
                    (mb + fx * step_s + 1.5 * bin_s,
                     mb + fy * step_s + 1.5 * bin_s,
                     bin_s)
                )
    assert len(grid) == descs.shape[0]
    return descs, grid


def _to_cv2_layout(descs, flip_orientation=True):
    """Map our output to OpenCV's (y_bin, x_bin, o) element order.

    Undo the vl transpose permutation, read the pre-transpose
    (x_bin, y_bin, t) tensor, swap the spatial axes, and flip the
    orientation index — OpenCV's angle is ``fastAtan2(-dy, dx)``, the
    negation of our ``arctan2(gy, gx)``, so its bin o is our t = (8-o)%8.
    ``flip_orientation=False`` is the specificity control: the deliberately
    wrong mapping that must NOT correlate.
    """
    from keystone_tpu.ops.images.sift import _TRANSPOSE_PERM

    pre = descs[:, np.argsort(_TRANSPOSE_PERM)].reshape(-1, 4, 4, 8)
    spatial = pre.transpose(0, 2, 1, 3)  # (n, y_bin, x_bin, t)
    if flip_orientation:
        spatial = spatial[..., (8 - np.arange(8)) % 8]
    return spatial.reshape(len(descs), 128)


def _rowwise_pearson(a, b):
    a = a.astype(np.float64) - a.mean(1, keepdims=True)
    b = b.astype(np.float64) - b.mean(1, keepdims=True)
    na, nb = np.linalg.norm(a, axis=1), np.linalg.norm(b, axis=1)
    ok = (na > 0) & (nb > 0)
    return np.sum(a[ok] * b[ok], axis=1) / (na[ok] * nb[ok])


@pytest.mark.parametrize("name", ["gantrycrane.png", "000012.jpg"])
def test_sift_vs_opencv(name):
    import cv2

    g8 = _gray_u8(name)
    descs, grid = _our_sift_with_grid(g8.astype(np.float32) / 255.0)

    # every 31st grid point with a surviving (non-mass-thresholded)
    # descriptor — several hundred keypoints across all four scales
    idx = np.arange(0, len(grid), 31)
    idx = idx[np.linalg.norm(descs[idx], axis=1) > 0]
    assert len(idx) >= 300

    # OpenCV keypoint size: its descriptor bin width is 3·(size/2) pixels
    # (SIFT_DESCR_SCL_FCTR), so size = 2·bin_s/3 aligns the windows
    kps = [
        cv2.KeyPoint(float(grid[i][0]), float(grid[i][1]),
                     2.0 * grid[i][2] / 3.0, 0.0)
        for i in idx
    ]
    _, cv_des = cv2.SIFT_create().compute(g8, kps)
    assert cv_des.shape == (len(idx), 128)

    ours = _to_cv2_layout(descs[idx])
    corr = _rowwise_pearson(ours, cv_des)
    assert np.median(corr) >= 0.80, np.median(corr)
    assert np.mean(corr > 0.5) >= 0.97, np.mean(corr > 0.5)

    # specificity control: the wrong orientation mapping (no flip, any
    # cyclic offset) must stay far below the true one — the agreement above
    # is orientation structure, not generic image smoothness
    wrong = _to_cv2_layout(descs[idx], flip_orientation=False)
    wrong_best = max(
        np.median(_rowwise_pearson(
            wrong.reshape(-1, 16, 8)[..., (np.arange(8) + o) % 8]
            .reshape(-1, 128), cv_des))
        for o in range(8)
    )
    assert wrong_best <= 0.55, wrong_best


# ---------------------------------------------------------------------------
# (b) GMM-EM vs scikit-learn
# ---------------------------------------------------------------------------


def _mean_loglik(model, X):
    ll = np.asarray(model.log_likelihoods(jnp.asarray(X)))
    mx = ll.max(1, keepdims=True)
    return float(np.mean(mx[:, 0] + np.log(np.exp(ll - mx).sum(1))))


def test_gmm_recovers_planted_mixture_like_sklearn():
    """EncEvalSuite.scala:42-64 analog with sklearn as the external EM."""
    from sklearn.mixture import GaussianMixture

    from keystone_tpu.learning.gmm import GaussianMixtureModelEstimator

    rng = np.random.default_rng(0)
    k, d, n = 5, 8, 4000
    true_mu = rng.normal(scale=6.0, size=(k, d))
    true_var = rng.uniform(0.5, 2.0, (k, d))
    true_w = rng.dirichlet(np.full(k, 5.0))
    comp = rng.choice(k, n, p=true_w)
    X = (true_mu[comp] + rng.normal(size=(n, d)) * np.sqrt(true_var[comp])
         ).astype(np.float32)

    ours = GaussianMixtureModelEstimator(k, num_iter=50, seed=0).fit(X)
    sk = GaussianMixture(k, covariance_type="diag", max_iter=200, n_init=3,
                         random_state=0).fit(X)

    # density parity: both EMs reach the same (global, planted) optimum
    ll_o, ll_s = _mean_loglik(ours, X), float(sk.score(X))
    assert abs(ll_o - ll_s) / abs(ll_s) < 1e-3, (ll_o, ll_s)

    # moment recovery, components matched by nearest sklearn mean
    om = np.asarray(ours.means)
    perm = [int(np.argmin(((sk.means_ - om[i]) ** 2).sum(1))) for i in range(k)]
    assert len(set(perm)) == k
    np.testing.assert_allclose(om, sk.means_[perm], atol=0.05)
    np.testing.assert_allclose(
        np.asarray(ours.weights), sk.weights_[perm], atol=0.02
    )
    np.testing.assert_allclose(
        np.asarray(ours.variances), sk.covariances_[perm], rtol=0.05
    )


def test_gmm_on_real_sift_descriptors_matches_sklearn_likelihood():
    """Cross-fit on real (PCA-reduced) SIFT descriptors from the reference
    photo: local optima may differ in detail, but our EM's density fit must
    not be worse than sklearn's best-of-3 beyond noise (measured signed gap
    7.7e-4; bound 5e-3)."""
    from sklearn.mixture import GaussianMixture

    from keystone_tpu.learning.gmm import GaussianMixtureModelEstimator
    from keystone_tpu.learning.pca import PCAEstimator

    g = _gray_u8("gantrycrane.png").astype(np.float32) / 255.0
    from keystone_tpu.ops.images.sift import SIFTExtractor

    descs = np.asarray(SIFTExtractor().apply(jnp.asarray(g)))
    descs = descs[np.linalg.norm(descs, axis=1) > 0]
    rng = np.random.default_rng(7)
    sub = descs[rng.choice(len(descs), 8000, replace=False)].astype(np.float32)

    Z = np.asarray(PCAEstimator(16).fit(sub).apply(jnp.asarray(sub)))
    ours = GaussianMixtureModelEstimator(8, num_iter=60, seed=0).fit(Z)
    sk = GaussianMixture(8, covariance_type="diag", max_iter=300, n_init=3,
                         random_state=0).fit(Z)
    ll_o, ll_s = _mean_loglik(ours, Z), float(sk.score(Z))
    assert ll_o >= ll_s - 5e-3 * abs(ll_s), (ll_o, ll_s)


# ---------------------------------------------------------------------------
# (c) PCA / ZCA / LDA / NaiveBayes vs scikit-learn (+ scipy)
# ---------------------------------------------------------------------------


def test_pca_matches_sklearn_on_sift_descriptors():
    from sklearn.decomposition import PCA as SKPCA

    from keystone_tpu.learning.pca import PCAEstimator
    from keystone_tpu.ops.images.sift import SIFTExtractor

    g = _gray_u8("gantrycrane.png").astype(np.float32) / 255.0
    descs = np.asarray(SIFTExtractor().apply(jnp.asarray(g)))
    descs = descs[np.linalg.norm(descs, axis=1) > 0]
    rng = np.random.default_rng(3)
    sub = descs[rng.choice(len(descs), 6000, replace=False)].astype(np.float32)

    ours = np.asarray(PCAEstimator(16, method="svd").fit(sub).pca_mat)  # (d,16)
    gram = np.asarray(PCAEstimator(16, method="gram").fit(sub).pca_mat)
    sk = SKPCA(16, svd_solver="full").fit(sub)

    # per-component alignment up to sign (spectrum is well separated here)
    for mat in (ours, gram):
        dots = np.abs(np.sum(mat * sk.components_.T, axis=0))
        assert dots.min() >= 0.99, dots

    # identical captured variance: reconstruction-error parity
    Xc = sub - sub.mean(0)
    nrm = np.linalg.norm(Xc)

    def recon(V):
        return float(np.linalg.norm(Xc - Xc @ (V @ V.T)) / nrm)

    assert abs(recon(ours) - recon(sk.components_.T)) < 1e-4


def test_zca_matches_scipy_oracle():
    import scipy.linalg

    from keystone_tpu.learning.zca import ZCAWhitenerEstimator

    rng = np.random.default_rng(5)
    X = (rng.normal(size=(500, 20)) @ rng.normal(size=(20, 20))).astype(np.float32)
    eps = 0.1
    ours = ZCAWhitenerEstimator(eps=eps).fit_single(X)

    # independent construction: scipy LAPACK SVD, float64
    Xc = X.astype(np.float64) - X.mean(0, dtype=np.float64)
    _, s, vt = scipy.linalg.svd(Xc, full_matrices=False)
    wh = (vt.T * (s * s / (len(X) - 1.0) + eps) ** -0.5) @ vt
    np.testing.assert_allclose(np.asarray(ours.whitener), wh, atol=5e-4)

    # and the defining property: with eps << spectrum the whitened sample
    # covariance is the identity (for large eps it is V·diag(λ/(λ+eps))·Vᵀ,
    # symmetric but NOT diagonal — so the property is only checkable here)
    tiny = ZCAWhitenerEstimator(eps=1e-6).fit_single(X)
    Z = np.asarray(tiny.apply(jnp.asarray(X))).astype(np.float64)
    cov = (Z.T @ Z) / (len(Z) - 1.0)
    assert np.abs(cov - np.eye(cov.shape[0])).max() < 5e-2


def test_lda_matches_sklearn_eigen_solver():
    from sklearn.discriminant_analysis import (
        LinearDiscriminantAnalysis as SKLDA,
    )

    from keystone_tpu.learning.lda import LinearDiscriminantAnalysis

    rng = np.random.default_rng(1)
    C, n, d, k = 5, 2000, 20, 3
    mu_c = rng.normal(scale=3.0, size=(C, d))
    lab = rng.choice(C, n)
    X = (mu_c[lab] + rng.normal(size=(n, d))).astype(np.float32)

    W = np.asarray(
        LinearDiscriminantAnalysis(k).fit(jnp.asarray(X), jnp.asarray(lab)).w
    )
    sk = SKLDA(solver="eigen", n_components=k).fit(X, lab)

    # same discriminant subspace: all principal-angle cosines ~ 1
    Qo, _ = np.linalg.qr(W)
    Qs, _ = np.linalg.qr(sk.scalings_[:, :k])
    cosines = np.linalg.svd(Qo.T @ Qs, compute_uv=False)
    assert cosines.min() >= 0.999, cosines

    # identical class separation (Fisher criterion) on the projections
    def fisher(P):
        Z = X @ P
        gm = Z.mean(0)
        sb = sw = 0.0
        for c in range(C):
            Zc = Z[lab == c]
            sb += len(Zc) * np.sum((Zc.mean(0) - gm) ** 2)
            sw += np.sum((Zc - Zc.mean(0)) ** 2)
        return sb / sw

    assert fisher(Qo) == pytest.approx(fisher(Qs), rel=1e-3)


def test_naive_bayes_matches_sklearn_multinomial():
    from sklearn.naive_bayes import MultinomialNB

    from keystone_tpu.learning.naive_bayes import NaiveBayesEstimator
    from keystone_tpu.ops.util.sparse import SparseBatch

    rng = np.random.default_rng(11)
    n, V, C, lam = 400, 50, 4, 1.0
    X = rng.poisson(0.8, (n, V)).astype(np.float32)
    lab = rng.choice(C, n)

    dense_model = NaiveBayesEstimator(C, lam=lam).fit(X, lab)
    # padded-COO device path must produce the same tables
    max_nnz = int((X > 0).sum(1).max())
    idx = np.full((n, max_nnz), -1, np.int32)
    val = np.zeros((n, max_nnz), np.float32)
    for i in range(n):
        nz = np.nonzero(X[i])[0]
        idx[i, : len(nz)] = nz
        val[i, : len(nz)] = X[i, nz]
    sparse_model = NaiveBayesEstimator(C, lam=lam).fit(
        SparseBatch(jnp.asarray(idx), jnp.asarray(val), V), lab
    )

    sk = MultinomialNB(alpha=lam).fit(X, lab)
    for model in (dense_model, sparse_model):
        # the smoothed log-likelihood matrix is formula-identical
        np.testing.assert_allclose(
            np.asarray(model.theta), sk.feature_log_prob_, rtol=1e-5, atol=1e-5
        )
        # priors differ only by MLlib's Laplace smoothing of pi (the
        # reference's contract, NaiveBayesModel.scala:58-70) — predictions
        # must still agree
        Xt = rng.poisson(0.8, (200, V)).astype(np.float32)
        ours_pred = np.argmax(np.asarray(model.apply_batch(jnp.asarray(Xt))), 1)
        assert (ours_pred == sk.predict(Xt)).mean() == 1.0


# ---------------------------------------------------------------------------
# (d) Convolution paths vs torch / scipy
# ---------------------------------------------------------------------------


def test_convolver_matches_torch_conv2d():
    import torch
    import torch.nn.functional as F

    from keystone_tpu.ops.images.convolver import Convolver

    rng = np.random.default_rng(2)
    imgs = rng.normal(size=(2, 16, 16, 3)).astype(np.float32)
    k, nf = 5, 4
    filters = rng.normal(size=(nf, k * k * 3)).astype(np.float32)

    ours = np.asarray(
        Convolver(filters=jnp.asarray(filters), normalize_patches=False)
        .apply_batch(jnp.asarray(imgs))
    )
    tw = torch.from_numpy(
        filters.reshape(nf, k, k, 3).transpose(0, 3, 1, 2).copy()
    )
    tout = F.conv2d(torch.from_numpy(imgs.transpose(0, 3, 1, 2).copy()), tw)
    np.testing.assert_allclose(
        ours, tout.numpy().transpose(0, 2, 3, 1), rtol=1e-4, atol=1e-4
    )


def test_convolver_normalized_matches_im2col_oracle():
    """The normalized path's closed-form decomposition vs an explicit numpy
    im2col oracle doing what the reference's makePatches+normalizeRows does
    (``Convolver.scala:19-154``) patch by patch."""
    from keystone_tpu.learning.zca import ZCAWhitener
    from keystone_tpu.ops.images.convolver import Convolver

    rng = np.random.default_rng(4)
    img = rng.normal(size=(12, 14, 3)).astype(np.float32)
    k, nf, vc = 3, 5, 10.0
    filters = rng.normal(size=(nf, k * k * 3)).astype(np.float32)
    wmeans = rng.normal(size=(k * k * 3,)).astype(np.float32)
    whitener = ZCAWhitener(
        whitener=jnp.eye(k * k * 3), means=jnp.asarray(wmeans)
    )

    ours = np.asarray(
        Convolver(filters=jnp.asarray(filters), whitener=whitener,
                  var_constant=vc).apply(jnp.asarray(img))
    )

    oh, ow = 12 - k + 1, 14 - k + 1
    want = np.zeros((oh, ow, nf), np.float32)
    n = k * k * 3
    for y in range(oh):
        for x in range(ow):
            p = img[y:y + k, x:x + k, :].reshape(-1).astype(np.float64)
            p = (p - p.mean()) / np.sqrt(p.var(ddof=1) + vc)
            want[y, x] = (p - wmeans) @ filters.T.astype(np.float64)
    np.testing.assert_allclose(ours, want, rtol=2e-3, atol=2e-3)


def test_daisy_gradient_maps_match_scipy():
    """The DAISY front half — separable [1,0,-1]/[1,2,1] gradient convs
    (``DaisyExtractor.scala:110-111``) — against scipy's full-2D true
    convolution with zero padding."""
    import scipy.signal

    from keystone_tpu.ops.images.image_utils import conv2d_same

    rng = np.random.default_rng(6)
    img = rng.normal(size=(24, 31)).astype(np.float32)
    f1 = np.array([1.0, 0.0, -1.0], np.float32)
    f2 = np.array([1.0, 2.0, 1.0], np.float32)

    # ref ix = conv2D(in, f1, f2): xFilter f1 along ref-x = our axis 0
    ix = np.asarray(conv2d_same(jnp.asarray(img), f2, f1))
    iy = np.asarray(conv2d_same(jnp.asarray(img), f1, f2))

    kx = np.outer(f1, f2)  # rows (axis 0) = f1, cols (axis 1) = f2
    ky = np.outer(f2, f1)
    np.testing.assert_allclose(
        ix, scipy.signal.convolve2d(img, kx, mode="same"), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        iy, scipy.signal.convolve2d(img, ky, mode="same"), rtol=1e-4, atol=1e-5
    )


def test_padded_fft_matches_scipy():
    import scipy.fft

    from keystone_tpu.ops.stats.nodes import PaddedFFT

    rng = np.random.default_rng(8)
    for n in (784, 512, 100):
        x = rng.normal(size=(n,)).astype(np.float32)
        ours = np.asarray(PaddedFFT().apply(jnp.asarray(x)))
        npad = 1 << max(0, (n - 1).bit_length())
        want = scipy.fft.rfft(x.astype(np.float64), n=npad).real[: npad // 2]
        np.testing.assert_allclose(ours, want, rtol=1e-4, atol=1e-3)
