"""Native keyed aggregation (native/ngram.cpp): parity with the numpy
fallback, weight merging, and the big-input threaded path."""

import numpy as np
import pytest

from keystone_tpu.native.ngram import (
    _count_by_key_np,
    count_by_key,
    native_available,
)


def test_count_by_key_small(rng):
    keys = np.array([5, 3, 5, 5, 3, 9], np.int64)
    uniq, totals = count_by_key(keys)
    np.testing.assert_array_equal(uniq, [3, 5, 9])
    np.testing.assert_array_equal(totals, [2.0, 3.0, 1.0])


def test_count_by_key_weights():
    keys = np.array([1, 2, 1], np.int64)
    w = np.array([0.5, 2.0, 1.5])
    uniq, totals = count_by_key(keys, w)
    np.testing.assert_array_equal(uniq, [1, 2])
    np.testing.assert_allclose(totals, [2.0, 2.0])


def test_count_by_key_empty():
    uniq, totals = count_by_key(np.zeros((0,), np.int64))
    assert uniq.size == 0 and totals.size == 0


def test_count_by_key_matches_numpy_large(rng):
    # > threading threshold, skewed key distribution (Zipf-ish n-gram counts)
    keys = rng.integers(0, 5000, size=200_000).astype(np.int64) ** 2
    w = rng.random(200_000)
    ref_u, ref_t = _count_by_key_np(keys, w)
    # num_threads=4 forces the hash-partitioned threaded path even on 1-core
    # CI boxes (the default would pick T=1 there).
    for threads in (1, 4):
        uniq, totals = count_by_key(keys, w, num_threads=threads)
        np.testing.assert_array_equal(uniq, ref_u)
        np.testing.assert_allclose(totals, ref_t, rtol=1e-9)
        assert np.all(np.diff(uniq) > 0)  # key-sorted, distinct


def test_native_library_builds():
    # The image ships g++, so the native path (not the fallback) must be live.
    assert native_available()


def test_stupid_backoff_uses_aggregated_tables():
    from keystone_tpu.ops.nlp.stupid_backoff import StupidBackoffEstimator

    # duplicate bigram entries (NoAdd-mode partials) must be summed
    counts = [((0, 1), 2), ((0, 1), 3), ((1, 2), 1)]
    model = StupidBackoffEstimator({0: 5, 1: 6, 2: 1}).fit(counts)
    assert model.apply([0, 1]) == pytest.approx(5.0 / 5.0)
