"""Latency-hiding collectives (``parallel/overlap.py``) on the 8-device CPU
mesh: the pipelined programs must (a) match the dense oracle / monolithic
path to f32 tolerance, and (b) PROVE their pipelined structure in the
compiled HLO — ≥ k per-tile reduce-scatters and NO terminal all-reduce on
the overlap path, paired collective-permutes on the bidirectional ring.
Numeric equivalence alone cannot catch a silent fall-back to the serialized
collective (correct numbers, unhidden latency), so every overlap feature
here carries both pins.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from keystone_tpu.learning import BlockLeastSquaresEstimator
from keystone_tpu.learning.block_weighted import BlockWeightedLeastSquaresEstimator
from keystone_tpu.linalg import (
    RowShardedMatrix,
    block_coordinate_descent_l2,
    normal_equations_solve,
    tsqr_solve,
)
from keystone_tpu.linalg.solvers import hdot
from keystone_tpu.parallel import make_mesh, use_mesh
from keystone_tpu.parallel.overlap import (
    _pick_tiles,
    bidirectional_ring_gram,
    maybe_tiled_transpose_matmul,
    overlap_enabled,
    overlap_mesh,
    tiled_psum_dot,
    tiled_transpose_matmul,
    use_overlap,
)


def _collectives(hlo_text: str):
    return {
        name: len(re.findall(name + r"\(|" + name + r"-start\(", hlo_text))
        for name in (
            "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
        )
    }


@pytest.fixture()
def mesh(devices):
    m = make_mesh(data=8, model=1, devices=devices)
    with use_mesh(m):
        yield m


# -- knob resolution --------------------------------------------------------


def test_overlap_knob_resolution(monkeypatch, devices):
    monkeypatch.delenv("KEYSTONE_OVERLAP", raising=False)
    assert not overlap_enabled()
    monkeypatch.setenv("KEYSTONE_OVERLAP", "1")
    assert overlap_enabled()
    with use_overlap(False):  # context beats env
        assert not overlap_enabled()
        assert overlap_enabled(True)  # per-call beats context
    monkeypatch.setenv("KEYSTONE_OVERLAP", "0")
    assert overlap_enabled(True)  # per-call beats env


def test_overlap_mesh_trivial_axis_disables(devices):
    # a single-device axis has no collective to hide: knob on, mesh None
    m1 = make_mesh(data=1, model=1, devices=devices[:1])
    with use_mesh(m1):
        assert overlap_mesh(True) is None
    m8 = make_mesh(data=8, model=1, devices=devices)
    with use_mesh(m8):
        assert overlap_mesh(True) is m8
        assert overlap_mesh(False) is None  # per-call off wins


# -- tiled reduce-scatter collective matmul ---------------------------------


def test_tiled_gram_matches_dense(mesh, rng):
    x = rng.normal(size=(128, 64)).astype(np.float32)
    g = tiled_transpose_matmul(jnp.asarray(x), mesh=mesh)
    np.testing.assert_allclose(np.asarray(g), x.T @ x, rtol=1e-4, atol=1e-4)


def test_tiled_cross_term_matches_dense(mesh, rng):
    x = rng.normal(size=(128, 64)).astype(np.float32)
    y = rng.normal(size=(128, 10)).astype(np.float32)
    c = tiled_transpose_matmul(jnp.asarray(x), jnp.asarray(y), mesh=mesh)
    np.testing.assert_allclose(np.asarray(c), x.T @ y, rtol=1e-4, atol=1e-4)


def test_tiled_gram_hlo_is_pipelined(mesh, rng):
    """THE structure pin: k per-tile reduce-scatters (one per feature tile,
    overlappable with the next tile's matmul), ONE trailing all-gather, and
    NO all-reduce — the monolithic program's terminal collective must not
    exist on the overlap path."""
    k = mesh.shape["data"]
    x = jnp.asarray(rng.normal(size=(128, 16 * k)).astype(np.float32))
    f = jax.jit(lambda a: tiled_transpose_matmul(a, mesh=mesh))
    cols = _collectives(f.lower(x).compile().as_text())
    assert cols["reduce-scatter"] >= k, cols
    assert cols["all-reduce"] == 0, (
        f"overlap path still carries a bulk all-reduce: {cols}"
    )
    assert cols["all-gather"] == 1, cols


def test_monolithic_gram_hlo_has_terminal_all_reduce(mesh, rng):
    """The contrast pin documenting what overlap removes: the plain sharded
    gram lowers to matmul + ONE bulk all-reduce and no reduce-scatter."""
    x = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))
    rows = NamedSharding(mesh, P("data", None))
    f = jax.jit(lambda a: hdot(a.T, a), in_shardings=rows,
                out_shardings=NamedSharding(mesh, P()))
    cols = _collectives(f.lower(x).compile().as_text())
    assert cols["all-reduce"] >= 1, cols
    assert cols["reduce-scatter"] == 0, cols


def test_tiled_errors_on_indivisible_shapes(mesh, rng):
    x = jnp.asarray(rng.normal(size=(130, 64)).astype(np.float32))
    with pytest.raises(ValueError, match="row count"):
        tiled_transpose_matmul(x, mesh=mesh)  # 130 % 8 != 0
    x = jnp.asarray(rng.normal(size=(128, 60)).astype(np.float32))
    with pytest.raises(ValueError, match="tiled"):
        tiled_transpose_matmul(x, mesh=mesh)  # 60 % 8 != 0
    y = jnp.asarray(rng.normal(size=(64, 3)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))
    with pytest.raises(ValueError, match="row mismatch"):
        tiled_transpose_matmul(x, y, mesh=mesh)


def test_maybe_tiled_falls_back_on_indivisible_shapes(mesh, rng):
    # 60 features cannot tile over 8 shards -> silently the monolithic hdot
    x = rng.normal(size=(128, 60)).astype(np.float32)
    g = maybe_tiled_transpose_matmul(jnp.asarray(x), None, mesh)
    np.testing.assert_allclose(np.asarray(g), x.T @ x, rtol=1e-4, atol=1e-4)
    # and with no mesh at all
    g = maybe_tiled_transpose_matmul(jnp.asarray(x), None, None)
    np.testing.assert_allclose(np.asarray(g), x.T @ x, rtol=1e-4, atol=1e-4)


def test_pick_tiles():
    assert _pick_tiles(64, 8) == 8       # 64 = 8 tiles x 8 rows
    assert _pick_tiles(16, 8) == 2       # at most dim/k tiles
    assert _pick_tiles(8, 8) == 1        # degenerate single tile
    assert _pick_tiles(60, 8) == 0       # not divisible by k
    assert _pick_tiles(64, 8, target=4) == 4


def test_tiled_psum_dot_matches_psum(mesh, rng):
    """The in-shard_map tiling (the TSQR Qᵀb reduction): tiled vs monolithic
    psum of per-shard partial products."""
    a = rng.normal(size=(8, 64, 32)).astype(np.float32)  # per-shard factors
    b = rng.normal(size=(8, 32, 5)).astype(np.float32)

    def tiled(ai, bi):
        return tiled_psum_dot(ai[0], bi[0], "data")[None]

    def mono(ai, bi):
        return jax.lax.psum(hdot(ai[0], bi[0]), "data")[None]

    spec = P("data", None, None)
    outs = []
    for fn in (tiled, mono):
        f = jax.shard_map(
            fn, mesh=mesh, in_specs=(spec, spec), out_specs=spec,
            check_vma=False,
        )
        outs.append(np.asarray(f(jnp.asarray(a), jnp.asarray(b)))[0])
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        outs[0], np.einsum("kij,kjc->ic", a, b), rtol=1e-4, atol=1e-4
    )


# -- bidirectional ring gram ------------------------------------------------


def test_bidirectional_ring_hlo_paired_permutes(devices, rng):
    """Structure pin: the unrolled bidirectional schedule carries paired
    collective-permutes — 2 per round plus the even-k middle hop (7 for
    k=8) — and no other collective."""
    m = make_mesh(data=1, model=8, devices=devices)
    x = jnp.asarray(rng.normal(size=(40, 32)).astype(np.float32))
    with use_mesh(m):
        f = jax.jit(lambda a: bidirectional_ring_gram(a, m, axis="model"))
        cols = _collectives(f.lower(x).compile().as_text())
    k = 8
    assert cols["collective-permute"] == 2 * ((k - 1) // 2) + 1, cols
    assert cols["all-reduce"] == 0 and cols["all-gather"] == 0, cols


# -- solver entry points: overlap on == overlap off -------------------------


def test_normal_equations_overlap_matches(mesh, rng):
    A = rng.normal(size=(256, 64)).astype(np.float32)
    b = rng.normal(size=(256, 8)).astype(np.float32)
    w0 = np.asarray(normal_equations_solve(A, b, lam=1.0))
    w1 = np.asarray(normal_equations_solve(A, b, lam=1.0, overlap=True))
    np.testing.assert_allclose(w1, w0, rtol=1e-4, atol=1e-5)
    # unregularized (lstsq) path too
    w0 = np.asarray(normal_equations_solve(A, b))
    w1 = np.asarray(normal_equations_solve(A, b, overlap=True))
    np.testing.assert_allclose(w1, w0, rtol=1e-4, atol=1e-5)


def test_normal_equations_overlap_hlo_is_pipelined(mesh, rng):
    """Acceptance pin on a REAL solver program: the jitted overlap-path
    normal equations carry ≥ k per-tile reduce-scatters (gram + cross
    term) and no single terminal all-reduce."""
    from keystone_tpu.linalg.solvers import _normal_equations

    k = mesh.shape["data"]
    A = jnp.asarray(rng.normal(size=(256, 8 * k)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(256, 8)).astype(np.float32))
    lowered = _normal_equations.lower(
        A, b, jnp.float32(1.0), None, precision="high", omesh=mesh
    )
    cols = _collectives(lowered.compile().as_text())
    assert cols["reduce-scatter"] >= k, cols
    assert cols["all-reduce"] == 0, cols


def test_tsqr_overlap_matches(mesh, rng):
    A = rng.normal(size=(256, 16)).astype(np.float32)
    b = rng.normal(size=(256, 3)).astype(np.float32)
    w0 = np.asarray(tsqr_solve(A, b, lam=0.5, mesh=mesh))
    w1 = np.asarray(tsqr_solve(A, b, lam=0.5, mesh=mesh, overlap=True))
    np.testing.assert_allclose(w1, w0, rtol=1e-5, atol=1e-6)


def test_bcd_overlap_matches(mesh, rng):
    A = rng.normal(size=(256, 64)).astype(np.float32)
    b = rng.normal(size=(256, 8)).astype(np.float32)
    for num_iter in (1, 3):  # pass-0 grams AND the cached-gram scan path
        w0 = np.asarray(
            block_coordinate_descent_l2(A, b, 1.0, 16, num_iter=num_iter)
        )
        w1 = np.asarray(
            block_coordinate_descent_l2(
                A, b, 1.0, 16, num_iter=num_iter, overlap=True
            )
        )
        np.testing.assert_allclose(w1, w0, rtol=1e-4, atol=1e-5)


def test_row_sharded_matrix_overlap_matches(mesh, rng):
    x = rng.normal(size=(250, 64)).astype(np.float32)  # padded rows masked
    y = rng.normal(size=(250, 8)).astype(np.float32)
    M = RowShardedMatrix.from_array(x, mesh)
    np.testing.assert_allclose(
        np.asarray(M.gram(overlap=True)), np.asarray(M.gram()),
        rtol=1e-4, atol=1e-4,
    )
    Y = RowShardedMatrix.from_array(y, mesh)
    np.testing.assert_allclose(
        np.asarray(M.t_times(Y, overlap=True)), np.asarray(M.t_times(Y)),
        rtol=1e-4, atol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(M.gram(overlap=True)), x.T @ x, rtol=1e-3, atol=1e-3
    )


# -- learning-layer plumbing (composes with the streamed block passes) ------


def _feature_nodes(rng, d=12, b=16, nblocks=2):
    from keystone_tpu.core.pipeline import chain
    from keystone_tpu.ops.stats import CosineRandomFeatures

    keys = jax.random.split(jax.random.key(3), nblocks)
    return [
        chain(CosineRandomFeatures.create(d, b, 0.1, keys[i]))
        for i in range(nblocks)
    ]


def test_block_ls_streaming_overlap_matches(mesh, rng):
    nodes = _feature_nodes(rng)
    x = jnp.asarray(rng.normal(size=(128, 12)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(128, 5)).astype(np.float32))
    ref = BlockLeastSquaresEstimator(16, num_iter=2, lam=0.5).fit_streaming(
        nodes, x, y
    )
    got = BlockLeastSquaresEstimator(
        16, num_iter=2, lam=0.5, overlap=True
    ).fit_streaming(nodes, x, y)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=1e-4, atol=1e-5
    )


def test_block_weighted_streaming_overlap_matches(mesh, rng):
    n, ds, cs = 128, 16, 4
    raw = jnp.asarray(rng.normal(size=(n, 2 * ds)).astype(np.float32))
    # real pytree nodes: one cosine-RF block per column half
    nodes = _feature_nodes(rng, d=2 * ds, b=ds, nblocks=2)
    labels = jnp.asarray(
        (np.eye(cs)[np.arange(n) % cs] * 2.0 - 1.0).astype(np.float32)
    )
    ref = BlockWeightedLeastSquaresEstimator(ds, 1, 0.1, 0.25).fit_streaming(
        nodes, raw, labels
    )
    got = BlockWeightedLeastSquaresEstimator(
        ds, 1, 0.1, 0.25, overlap=True
    ).fit_streaming(nodes, raw, labels)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=1e-4, atol=1e-5
    )


def test_env_knob_routes_solvers(mesh, rng, monkeypatch):
    """KEYSTONE_OVERLAP=1 with no per-call arg must route through the tiled
    path (pin: the env-resolved program contains reduce-scatters)."""
    from keystone_tpu.linalg.solvers import _normal_equations

    monkeypatch.setenv("KEYSTONE_OVERLAP", "1")
    A = rng.normal(size=(256, 64)).astype(np.float32)
    b = rng.normal(size=(256, 8)).astype(np.float32)
    omesh = overlap_mesh()
    assert omesh is mesh
    w0 = np.asarray(normal_equations_solve(A, b, lam=1.0))
    monkeypatch.setenv("KEYSTONE_OVERLAP", "0")
    w1 = np.asarray(normal_equations_solve(A, b, lam=1.0))
    np.testing.assert_allclose(w0, w1, rtol=1e-4, atol=1e-5)
