"""Latency-hiding collectives (``parallel/overlap.py``) on the 8-device CPU
mesh: the pipelined programs must (a) match the dense oracle / monolithic
path to f32 tolerance, and (b) PROVE their pipelined structure in the
compiled HLO — ≥ k per-tile reduce-scatters and NO terminal all-reduce on
the overlap path, paired collective-permutes on the bidirectional ring.
Numeric equivalence alone cannot catch a silent fall-back to the serialized
collective (correct numbers, unhidden latency), so every overlap feature
here carries both pins.

The structural pins are the A1 assertion helpers from
``keystone_tpu/analysis/ir_rules.py`` — the SAME functions the
``keystone-tpu audit`` pass runs over the registered entry points, so
these tests and the auditor can never disagree about what "pipelined"
means (PR 9 migrated the hand-written string pins onto them).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from keystone_tpu.analysis.ir_rules import (
    assert_no_all_reduce,
    assert_no_bulk_collectives,
    assert_paired_permutes,
    assert_permute_count,
    assert_pipelined_reduce_scatter,
    assert_two_tier_replica_groups,
    collective_counts,
)

from keystone_tpu.learning import BlockLeastSquaresEstimator
from keystone_tpu.learning.block_weighted import BlockWeightedLeastSquaresEstimator
from keystone_tpu.linalg import (
    RowShardedMatrix,
    block_coordinate_descent_l2,
    normal_equations_solve,
    tsqr_solve,
)
from keystone_tpu.linalg.solvers import hdot, tsqr_r
from keystone_tpu.parallel import make_mesh, use_mesh
from keystone_tpu.parallel.overlap import (
    _pick_tiles,
    bidirectional_ring_gram,
    maybe_tiled_transpose_matmul,
    mesh_tiers,
    model_overlap_spec,
    model_tiled_transpose_matmul,
    overlap_enabled,
    overlap_mesh,
    tiled_psum_dot,
    tiled_transpose_matmul,
    use_overlap,
)


# the one collective-counting implementation (ir_rules.py) — the contrast
# tests (monolithic path HAS the all-reduce) read counts directly
_collectives = collective_counts


@pytest.fixture()
def mesh(devices):
    m = make_mesh(data=8, model=1, devices=devices)
    with use_mesh(m):
        yield m


# -- knob resolution --------------------------------------------------------


def test_overlap_knob_resolution(monkeypatch, devices):
    monkeypatch.delenv("KEYSTONE_OVERLAP", raising=False)
    assert not overlap_enabled()
    monkeypatch.setenv("KEYSTONE_OVERLAP", "1")
    assert overlap_enabled()
    with use_overlap(False):  # context beats env
        assert not overlap_enabled()
        assert overlap_enabled(True)  # per-call beats context
    monkeypatch.setenv("KEYSTONE_OVERLAP", "0")
    assert overlap_enabled(True)  # per-call beats env


def test_overlap_mesh_trivial_axis_disables(devices):
    # a single-device axis has no collective to hide: knob on, mesh None
    m1 = make_mesh(data=1, model=1, devices=devices[:1])
    with use_mesh(m1):
        assert overlap_mesh(True) is None
    m8 = make_mesh(data=8, model=1, devices=devices)
    with use_mesh(m8):
        assert overlap_mesh(True) is m8
        assert overlap_mesh(False) is None  # per-call off wins


# -- tiled reduce-scatter collective matmul ---------------------------------


def test_tiled_gram_matches_dense(mesh, rng):
    x = rng.normal(size=(128, 64)).astype(np.float32)
    g = tiled_transpose_matmul(jnp.asarray(x), mesh=mesh)
    np.testing.assert_allclose(np.asarray(g), x.T @ x, rtol=1e-4, atol=1e-4)


def test_tiled_cross_term_matches_dense(mesh, rng):
    x = rng.normal(size=(128, 64)).astype(np.float32)
    y = rng.normal(size=(128, 10)).astype(np.float32)
    c = tiled_transpose_matmul(jnp.asarray(x), jnp.asarray(y), mesh=mesh)
    np.testing.assert_allclose(np.asarray(c), x.T @ y, rtol=1e-4, atol=1e-4)


def test_tiled_gram_hlo_is_pipelined(mesh, rng):
    """THE structure pin: k per-tile reduce-scatters (one per feature tile,
    overlappable with the next tile's matmul), ONE trailing all-gather, and
    NO all-reduce — the monolithic program's terminal collective must not
    exist on the overlap path."""
    k = mesh.shape["data"]
    x = jnp.asarray(rng.normal(size=(128, 16 * k)).astype(np.float32))
    f = jax.jit(lambda a: tiled_transpose_matmul(a, mesh=mesh))
    # the auditor's A1 check verbatim (ir_rules.py)
    assert_pipelined_reduce_scatter(f.lower(x).compile().as_text(), k)


def test_monolithic_gram_hlo_has_terminal_all_reduce(mesh, rng):
    """The contrast pin documenting what overlap removes: the plain sharded
    gram lowers to matmul + ONE bulk all-reduce and no reduce-scatter."""
    x = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))
    rows = NamedSharding(mesh, P("data", None))
    f = jax.jit(lambda a: hdot(a.T, a), in_shardings=rows,
                out_shardings=NamedSharding(mesh, P()))
    cols = _collectives(f.lower(x).compile().as_text())
    assert cols["all-reduce"] >= 1, cols
    assert cols["reduce-scatter"] == 0, cols


def test_tiled_errors_on_indivisible_shapes(mesh, rng):
    x = jnp.asarray(rng.normal(size=(130, 64)).astype(np.float32))
    with pytest.raises(ValueError, match="row count"):
        tiled_transpose_matmul(x, mesh=mesh)  # 130 % 8 != 0
    x = jnp.asarray(rng.normal(size=(128, 60)).astype(np.float32))
    with pytest.raises(ValueError, match="tiled"):
        tiled_transpose_matmul(x, mesh=mesh)  # 60 % 8 != 0
    y = jnp.asarray(rng.normal(size=(64, 3)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))
    with pytest.raises(ValueError, match="row mismatch"):
        tiled_transpose_matmul(x, y, mesh=mesh)


def test_maybe_tiled_falls_back_on_indivisible_shapes(mesh, rng):
    # 60 features cannot tile over 8 shards -> silently the monolithic hdot
    x = rng.normal(size=(128, 60)).astype(np.float32)
    g = maybe_tiled_transpose_matmul(jnp.asarray(x), None, mesh)
    np.testing.assert_allclose(np.asarray(g), x.T @ x, rtol=1e-4, atol=1e-4)
    # and with no mesh at all
    g = maybe_tiled_transpose_matmul(jnp.asarray(x), None, None)
    np.testing.assert_allclose(np.asarray(g), x.T @ x, rtol=1e-4, atol=1e-4)


def test_pick_tiles():
    assert _pick_tiles(64, 8) == 8       # 64 = 8 tiles x 8 rows
    assert _pick_tiles(16, 8) == 2       # at most dim/k tiles
    assert _pick_tiles(8, 8) == 1        # degenerate single tile
    assert _pick_tiles(60, 8) == 0       # not divisible by k
    assert _pick_tiles(64, 8, target=4) == 4


def test_tiled_psum_dot_matches_psum(mesh, rng):
    """The in-shard_map tiling (the TSQR Qᵀb reduction): tiled vs monolithic
    psum of per-shard partial products."""
    a = rng.normal(size=(8, 64, 32)).astype(np.float32)  # per-shard factors
    b = rng.normal(size=(8, 32, 5)).astype(np.float32)

    def tiled(ai, bi):
        return tiled_psum_dot(ai[0], bi[0], "data")[None]

    def mono(ai, bi):
        return jax.lax.psum(hdot(ai[0], bi[0]), "data")[None]

    spec = P("data", None, None)
    outs = []
    for fn in (tiled, mono):
        f = jax.shard_map(
            fn, mesh=mesh, in_specs=(spec, spec), out_specs=spec,
            check_vma=False,
        )
        outs.append(np.asarray(f(jnp.asarray(a), jnp.asarray(b)))[0])
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        outs[0], np.einsum("kij,kjc->ic", a, b), rtol=1e-4, atol=1e-4
    )


# -- bidirectional ring gram ------------------------------------------------


def test_bidirectional_ring_hlo_paired_permutes(devices, rng):
    """Structure pin: the unrolled bidirectional schedule carries paired
    collective-permutes — 2 per round plus the even-k middle hop (7 for
    k=8) — and no other collective."""
    m = make_mesh(data=1, model=8, devices=devices)
    x = jnp.asarray(rng.normal(size=(40, 32)).astype(np.float32))
    with use_mesh(m):
        f = jax.jit(lambda a: bidirectional_ring_gram(a, m, axis="model"))
        hlo = f.lower(x).compile().as_text()
    k = 8
    # the auditor's checks verbatim (ir_rules.py): the exact bidirectional
    # round count, every permute table matched by its inverse (one
    # unpaired even-k middle hop), zero bulk collectives
    assert_permute_count(hlo, exact=2 * ((k - 1) // 2) + 1)
    assert_paired_permutes(hlo, min_permutes=2 * ((k - 1) // 2))
    assert_no_bulk_collectives(hlo)


# -- solver entry points: overlap on == overlap off -------------------------


def test_normal_equations_overlap_matches(mesh, rng):
    A = rng.normal(size=(256, 64)).astype(np.float32)
    b = rng.normal(size=(256, 8)).astype(np.float32)
    w0 = np.asarray(normal_equations_solve(A, b, lam=1.0))
    w1 = np.asarray(normal_equations_solve(A, b, lam=1.0, overlap=True))
    np.testing.assert_allclose(w1, w0, rtol=1e-4, atol=1e-5)
    # unregularized (lstsq) path too
    w0 = np.asarray(normal_equations_solve(A, b))
    w1 = np.asarray(normal_equations_solve(A, b, overlap=True))
    np.testing.assert_allclose(w1, w0, rtol=1e-4, atol=1e-5)


def test_normal_equations_overlap_hlo_is_pipelined(mesh, rng):
    """Acceptance pin on a REAL solver program: the jitted overlap-path
    normal equations carry ≥ k per-tile reduce-scatters (gram + cross
    term) and no single terminal all-reduce."""
    from keystone_tpu.linalg.solvers import _normal_equations

    k = mesh.shape["data"]
    A = jnp.asarray(rng.normal(size=(256, 8 * k)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(256, 8)).astype(np.float32))
    lowered = _normal_equations.lower(
        A, b, jnp.float32(1.0), None, precision="high", omesh=mesh
    )
    # gram + cross term: two trailing all-gathers are legitimate
    assert_pipelined_reduce_scatter(
        lowered.compile().as_text(), k, all_gather_max=2
    )


def test_tsqr_overlap_matches(mesh, rng):
    A = rng.normal(size=(256, 16)).astype(np.float32)
    b = rng.normal(size=(256, 3)).astype(np.float32)
    w0 = np.asarray(tsqr_solve(A, b, lam=0.5, mesh=mesh))
    w1 = np.asarray(tsqr_solve(A, b, lam=0.5, mesh=mesh, overlap=True))
    np.testing.assert_allclose(w1, w0, rtol=1e-5, atol=1e-6)


def test_bcd_overlap_matches(mesh, rng):
    A = rng.normal(size=(256, 64)).astype(np.float32)
    b = rng.normal(size=(256, 8)).astype(np.float32)
    for num_iter in (1, 3):  # pass-0 grams AND the cached-gram scan path
        w0 = np.asarray(
            block_coordinate_descent_l2(A, b, 1.0, 16, num_iter=num_iter)
        )
        w1 = np.asarray(
            block_coordinate_descent_l2(
                A, b, 1.0, 16, num_iter=num_iter, overlap=True
            )
        )
        np.testing.assert_allclose(w1, w0, rtol=1e-4, atol=1e-5)


def test_row_sharded_matrix_overlap_matches(mesh, rng):
    x = rng.normal(size=(250, 64)).astype(np.float32)  # padded rows masked
    y = rng.normal(size=(250, 8)).astype(np.float32)
    M = RowShardedMatrix.from_array(x, mesh)
    np.testing.assert_allclose(
        np.asarray(M.gram(overlap=True)), np.asarray(M.gram()),
        rtol=1e-4, atol=1e-4,
    )
    Y = RowShardedMatrix.from_array(y, mesh)
    np.testing.assert_allclose(
        np.asarray(M.t_times(Y, overlap=True)), np.asarray(M.t_times(Y)),
        rtol=1e-4, atol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(M.gram(overlap=True)), x.T @ x, rtol=1e-3, atol=1e-3
    )


# -- learning-layer plumbing (composes with the streamed block passes) ------


def _feature_nodes(rng, d=12, b=16, nblocks=2):
    from keystone_tpu.core.pipeline import chain
    from keystone_tpu.ops.stats import CosineRandomFeatures

    keys = jax.random.split(jax.random.key(3), nblocks)
    return [
        chain(CosineRandomFeatures.create(d, b, 0.1, keys[i]))
        for i in range(nblocks)
    ]


def test_block_ls_streaming_overlap_matches(mesh, rng):
    nodes = _feature_nodes(rng)
    x = jnp.asarray(rng.normal(size=(128, 12)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(128, 5)).astype(np.float32))
    ref = BlockLeastSquaresEstimator(16, num_iter=2, lam=0.5).fit_streaming(
        nodes, x, y
    )
    got = BlockLeastSquaresEstimator(
        16, num_iter=2, lam=0.5, overlap=True
    ).fit_streaming(nodes, x, y)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=1e-4, atol=1e-5
    )


def test_block_weighted_streaming_overlap_matches(mesh, rng):
    n, ds, cs = 128, 16, 4
    raw = jnp.asarray(rng.normal(size=(n, 2 * ds)).astype(np.float32))
    # real pytree nodes: one cosine-RF block per column half
    nodes = _feature_nodes(rng, d=2 * ds, b=ds, nblocks=2)
    labels = jnp.asarray(
        (np.eye(cs)[np.arange(n) % cs] * 2.0 - 1.0).astype(np.float32)
    )
    ref = BlockWeightedLeastSquaresEstimator(ds, 1, 0.1, 0.25).fit_streaming(
        nodes, raw, labels
    )
    got = BlockWeightedLeastSquaresEstimator(
        ds, 1, 0.1, 0.25, overlap=True
    ).fit_streaming(nodes, raw, labels)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=1e-4, atol=1e-5
    )


def test_env_knob_routes_solvers(mesh, rng, monkeypatch):
    """KEYSTONE_OVERLAP=1 with no per-call arg must route through the tiled
    path (pin: the env-resolved program contains reduce-scatters)."""
    from keystone_tpu.linalg.solvers import _normal_equations

    monkeypatch.setenv("KEYSTONE_OVERLAP", "1")
    A = rng.normal(size=(256, 64)).astype(np.float32)
    b = rng.normal(size=(256, 8)).astype(np.float32)
    omesh = overlap_mesh()
    assert omesh is mesh
    w0 = np.asarray(normal_equations_solve(A, b, lam=1.0))
    monkeypatch.setenv("KEYSTONE_OVERLAP", "0")
    w1 = np.asarray(normal_equations_solve(A, b, lam=1.0))
    np.testing.assert_allclose(w0, w1, rtol=1e-4, atol=1e-5)


# -- KEYSTONE_OVERLAP_TILES (per-topology tile override) --------------------


def test_overlap_tiles_env_override(monkeypatch):
    monkeypatch.delenv("KEYSTONE_OVERLAP_TILES", raising=False)
    assert _pick_tiles(64, 8) == 8  # default target: the axis size
    monkeypatch.setenv("KEYSTONE_OVERLAP_TILES", "4")
    assert _pick_tiles(64, 8) == 4
    monkeypatch.setenv("KEYSTONE_OVERLAP_TILES", "2,1")  # inner,outer form
    assert _pick_tiles(64, 8) == 2
    # explicit target still beats the env (per-call beats env, as always)
    assert _pick_tiles(64, 8, target=8) == 8


def test_overlap_tiles_env_rejects_nonsense(monkeypatch):
    for bad in ("0", "-3", "banana", "2,0", "1,2,3", "2.5", ","):
        monkeypatch.setenv("KEYSTONE_OVERLAP_TILES", bad)
        with pytest.raises(ValueError, match="KEYSTONE_OVERLAP_TILES"):
            _pick_tiles(64, 8)


# -- two-tier ICI/DCN reduce-scatter ----------------------------------------


def test_mesh_tiers_probe_and_env(mesh, monkeypatch):
    monkeypatch.delenv("KEYSTONE_MESH_TIERS", raising=False)
    # CPU sim: every device shares one process -> single tier
    assert mesh_tiers(mesh) == (1, 8)
    monkeypatch.setenv("KEYSTONE_MESH_TIERS", "2")
    assert mesh_tiers(mesh) == (2, 4)
    monkeypatch.setenv("KEYSTONE_MESH_TIERS", "8")
    assert mesh_tiers(mesh) == (8, 1)
    for bad in ("3", "0", "-2", "x", "2x4"):
        monkeypatch.setenv("KEYSTONE_MESH_TIERS", bad)
        with pytest.raises(ValueError, match="KEYSTONE_MESH_TIERS"):
            mesh_tiers(mesh)


def test_two_tier_matches_single_tier(mesh, rng, monkeypatch):
    """The fake two-slice tier map over the CPU mesh must reproduce the
    single-tier result. Not bit-identical by construction — the two-tier
    schedule sums slice partials before crossing slices, a different f32
    addition order — so the pin is dense-oracle equivalence at the tiling
    tests' tolerance plus exact agreement between the env-declared and
    explicitly-passed tier maps (identical schedules -> identical bits)."""
    monkeypatch.delenv("KEYSTONE_MESH_TIERS", raising=False)
    x = rng.normal(size=(128, 64)).astype(np.float32)
    g1 = np.asarray(tiled_transpose_matmul(jnp.asarray(x), mesh=mesh))
    g_exp = np.asarray(
        tiled_transpose_matmul(jnp.asarray(x), mesh=mesh, tiers=(2, 4))
    )
    monkeypatch.setenv("KEYSTONE_MESH_TIERS", "2")
    g_env = np.asarray(tiled_transpose_matmul(jnp.asarray(x), mesh=mesh))
    np.testing.assert_array_equal(g_env, g_exp)
    np.testing.assert_allclose(g_exp, g1, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(g_exp, x.T @ x, rtol=1e-4, atol=1e-4)
    # cross term through the same two-tier schedule
    y = rng.normal(size=(128, 8)).astype(np.float32)
    c = np.asarray(
        tiled_transpose_matmul(jnp.asarray(x), jnp.asarray(y), mesh=mesh)
    )
    np.testing.assert_allclose(c, x.T @ y, rtol=1e-4, atol=1e-4)


def test_two_tier_inner_never_crosses_slice_boundary(mesh, rng):
    """HLO pin for the tier map: with 2 declared slices over the 8-device
    axis, EVERY reduce-scatter is either within one slice ({0-3} / {4-7},
    the inner ICI tier) or one-member-per-slice ({j, 4+j}, the outer
    exchange shipping only slice partials) — no monolithic 8-wide
    reduction, no all-reduce, and >= T within-slice scatters (one per
    tile)."""
    k = mesh.shape["data"]
    x = jnp.asarray(rng.normal(size=(128, 16 * k)).astype(np.float32))
    f = jax.jit(lambda a: tiled_transpose_matmul(a, mesh=mesh, tiers=(2, 4)))
    hlo = f.lower(x).compile().as_text()
    # the auditor's two-tier boundary check verbatim (ir_rules.py): every
    # reduce-scatter within one slice or one-member-per-slice, >= T
    # within-slice scatters (one per tile), >= 1 cross-slice exchange,
    # no all-reduce anywhere
    T = _pick_tiles(x.shape[1], k)
    assert_two_tier_replica_groups(hlo, 2, 4, min_inner=T)
    assert_no_all_reduce(hlo)


def test_two_tier_tiled_psum_dot_matches(mesh, rng):
    """The in-shard_map form with an explicit tier map (the TSQR/gram inner
    loop) against the monolithic psum."""
    a = rng.normal(size=(8, 64, 32)).astype(np.float32)
    b = rng.normal(size=(8, 32, 5)).astype(np.float32)

    def tiered(ai, bi):
        return tiled_psum_dot(ai[0], bi[0], "data", tiers=(2, 4))[None]

    spec = P("data", None, None)
    f = jax.shard_map(
        tiered, mesh=mesh, in_specs=(spec, spec), out_specs=spec,
        check_vma=False,
    )
    out = np.asarray(f(jnp.asarray(a), jnp.asarray(b)))[0]
    np.testing.assert_allclose(
        out, np.einsum("kij,kjc->ic", a, b), rtol=1e-4, atol=1e-4
    )


# -- overlapped TSQR tree ---------------------------------------------------


def test_tsqr_ring_fold_matches_dense_oracle(devices, rng):
    """Dense-oracle equivalence for the ring R-tree at odd shard counts and
    non-tile-divisible d (d=10 has no tiling over either axis size): the
    regimes the tiled paths cannot touch, which the fold handles because it
    has no divisibility requirement at all."""
    for nk in (5, 8):
        mesh = make_mesh(data=nk, model=1, devices=devices[:nk])
        d, c = 10, 3
        n = 24 * nk
        A = rng.normal(size=(n, d)).astype(np.float32)
        b = rng.normal(size=(n, c)).astype(np.float32)
        with use_mesh(mesh):
            w_off = np.asarray(tsqr_solve(A, b, lam=0.5, mesh=mesh))
            w_on = np.asarray(
                tsqr_solve(A, b, lam=0.5, mesh=mesh, overlap=True)
            )
            w_on0 = np.asarray(
                tsqr_solve(A, b, lam=0.0, mesh=mesh, overlap=True)
            )
            R = np.asarray(tsqr_r(jnp.asarray(A), mesh, overlap=True))
        np.testing.assert_allclose(w_on, w_off, rtol=1e-4, atol=1e-5)
        # unregularized path: the exact least-squares oracle
        w_ref = np.linalg.lstsq(A, b, rcond=None)[0]
        np.testing.assert_allclose(w_on0, w_ref, rtol=1e-4, atol=1e-4)
        # tsqr_r contract: RtR = AtA (row signs are QR's freedom)
        np.testing.assert_allclose(
            R.T @ R, A.T @ A, rtol=1e-4,
            atol=1e-3 * np.abs(A.T @ A).max(),
        )


def test_tsqr_overlap_hlo_ring_tree(mesh, rng):
    """THE structure pin for the overlapped TSQR tree: paired
    collective-permutes (2 per bidirectional round) and ZERO bulk
    all-gather / all-reduce — the monolithic R-stack gather and the
    trailing Qtb psum must both be gone from the overlap path."""
    from keystone_tpu.linalg.solvers import _tsqr_solve

    k = mesh.shape["data"]
    A = jnp.asarray(rng.normal(size=(256, 16)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(256, 3)).astype(np.float32))
    lowered = _tsqr_solve.lower(
        A, b, jnp.float32(0.5), None, mesh, True, "highest", True
    )
    hlo = lowered.compile().as_text()
    # the auditor's A1 checks verbatim (ir_rules.py): paired permutes
    # carrying the (R, Qᵀb) pair — the even-k middle hop ships the pair,
    # so up to TWO unmatched HLO permutes are the schedule, not a bug —
    # and zero bulk all-gather/all-reduce
    assert_paired_permutes(
        hlo, min_permutes=2 * ((k - 1) // 2), unpaired_max=2
    )
    assert_no_bulk_collectives(hlo)
    # contrast: the monolithic tree keeps the bulk gather
    lowered = _tsqr_solve.lower(
        A, b, jnp.float32(0.5), None, mesh, True, "highest", False
    )
    cols = _collectives(lowered.compile().as_text())
    assert cols["all-gather"] >= 1, cols


# -- model-axis (column-sharded) BCD overlap --------------------------------


@pytest.fixture()
def mesh2d(devices):
    m = make_mesh(data=4, model=2, devices=devices)
    with use_mesh(m):
        yield m


def test_model_tiled_matmul_matches_dense(mesh2d, rng):
    x = rng.normal(size=(64, 32)).astype(np.float32)
    y = rng.normal(size=(64, 5)).astype(np.float32)
    xs = jax.device_put(
        jnp.asarray(x), NamedSharding(mesh2d, P("data", "model"))
    )
    ys = jax.device_put(jnp.asarray(y), NamedSharding(mesh2d, P("data", None)))
    g = np.asarray(model_tiled_transpose_matmul(xs, None, mesh2d))
    c = np.asarray(model_tiled_transpose_matmul(xs, ys, mesh2d))
    np.testing.assert_allclose(g, x.T @ x, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(c, x.T @ y, rtol=1e-4, atol=1e-4)


def test_model_tiled_gram_hlo_composes_rotation_and_tiles(mesh2d, rng):
    """Structure pin: the column-sharded gram carries the model-axis block
    rotation (collective-permutes) AND per-rotation tiled data-axis
    reduce-scatters, with no all-reduce anywhere."""
    x = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32))
    f = jax.jit(
        lambda a: model_tiled_transpose_matmul(a, None, mesh2d),
        in_shardings=NamedSharding(mesh2d, P("data", "model")),
    )
    hlo = f.lower(x).compile().as_text()
    km, kd = mesh2d.shape["model"], mesh2d.shape["data"]
    T = _pick_tiles(x.shape[1] // km, kd)
    # the block rotation rides >= 1 collective-permute, and tiles x
    # rotations reduce-scatters with no terminal all-reduce — both pins
    # are the auditor's own helpers (ir_rules.py)
    assert_permute_count(hlo, min_count=1)
    assert_pipelined_reduce_scatter(
        hlo, kd, min_scatter=km * T, all_gather_max=None
    )


def test_model_overlap_spec_gate(mesh2d, rng):
    x = jax.device_put(
        jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32)),
        NamedSharding(mesh2d, P("data", "model")),
    )
    assert model_overlap_spec(x, mesh2d, 16)
    assert not model_overlap_spec(x, mesh2d, 15)  # block % model != 0
    assert not model_overlap_spec(x, None, 16)  # knob off
    x_rows = jax.device_put(
        jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32)),
        NamedSharding(mesh2d, P("data", None)),
    )
    assert not model_overlap_spec(x_rows, mesh2d, 16)  # not column-sharded


def test_bcd_model_axis_overlap_matches(mesh2d, rng):
    """The column-sharded P('data','model') regime: overlap on == off, for
    single-pass and cached-gram multi-pass solves."""
    A = rng.normal(size=(64, 64)).astype(np.float32)
    b = rng.normal(size=(64, 5)).astype(np.float32)
    Acs = jax.device_put(
        jnp.asarray(A), NamedSharding(mesh2d, P("data", "model"))
    )
    bs = jax.device_put(jnp.asarray(b), NamedSharding(mesh2d, P("data", None)))
    for num_iter in (1, 3):
        w0 = np.asarray(
            block_coordinate_descent_l2(Acs, bs, 1.0, 16, num_iter=num_iter)
        )
        w1 = np.asarray(
            block_coordinate_descent_l2(
                Acs, bs, 1.0, 16, num_iter=num_iter, overlap=True
            )
        )
        np.testing.assert_allclose(w1, w0, rtol=1e-4, atol=1e-5)


def test_weighted_model_axis_overlap_matches(mesh2d, rng):
    """In-core weighted BCD (the flagship FV solver) over column-sharded
    data: the per-block pop-cov/XtR reductions take the model-axis path."""
    from keystone_tpu.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )

    n, d, cs = 64, 32, 4
    X = rng.normal(size=(n, d)).astype(np.float32)
    lbl = (np.eye(cs)[np.arange(n) % cs] * 2.0 - 1.0).astype(np.float32)
    Xcs = jax.device_put(
        jnp.asarray(X), NamedSharding(mesh2d, P("data", "model"))
    )
    lblr = jax.device_put(
        jnp.asarray(lbl), NamedSharding(mesh2d, P("data", None))
    )
    ref = BlockWeightedLeastSquaresEstimator(16, 2, 0.1, 0.25).fit(Xcs, lblr)
    got = BlockWeightedLeastSquaresEstimator(
        16, 2, 0.1, 0.25, overlap=True
    ).fit(Xcs, lblr)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=1e-4, atol=1e-5
    )


# -- fallback logging (a silent fallback must not look like overlap) --------


def test_overlap_fallback_logs_once(mesh, rng, caplog):
    import logging

    from keystone_tpu import telemetry
    from keystone_tpu.parallel import overlap as _ov

    _ov._FALLBACK_LOGGED.clear()
    telemetry.reset()
    reg = telemetry.get_registry()
    x = jnp.asarray(rng.normal(size=(128, 60)).astype(np.float32))  # 60 % 8
    with caplog.at_level(
        logging.WARNING, logger="keystone_tpu.parallel.overlap"
    ):
        maybe_tiled_transpose_matmul(x, None, mesh)
        maybe_tiled_transpose_matmul(x, None, mesh)  # same shape: no re-log
    recs = [
        r for r in caplog.records if "overlap fallback" in r.getMessage()
    ]
    assert len(recs) == 1, [r.getMessage() for r in recs]
    # ...but the telemetry counter is NOT rate-limited: both fallback
    # decisions are countable straight off the registry (no log scraping)
    assert reg.get_counter(
        "overlap.fallback", site="maybe_tiled_transpose_matmul"
    ) == 2
    # a DIFFERENT failing shape logs its own line
    y = jnp.asarray(rng.normal(size=(130, 64)).astype(np.float32))  # rows % 8
    with caplog.at_level(
        logging.WARNING, logger="keystone_tpu.parallel.overlap"
    ):
        maybe_tiled_transpose_matmul(y, None, mesh)
    recs = [
        r for r in caplog.records if "overlap fallback" in r.getMessage()
    ]
    assert len(recs) == 2
    assert reg.get_counter(
        "overlap.fallback", site="maybe_tiled_transpose_matmul"
    ) == 3
    # and an ENGAGED shape increments the engagement series, zero fallbacks
    telemetry.reset()
    z = jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))
    maybe_tiled_transpose_matmul(z, None, mesh)
    assert reg.get_counter(
        "overlap.engaged", site="tiled_transpose_matmul",
        schedule="single_tier",
    ) == 1
    assert reg.sum_counters("overlap.fallback") == 0


# -- tier-aware TSQR fold order ---------------------------------------------


def test_tsqr_ring_fold_two_tier_matches(mesh, rng, monkeypatch):
    """Tiered fold-order equivalence on a simulated 2-slice mesh
    (KEYSTONE_MESH_TIERS=2 over the 8-device axis): within-slice factors
    fold first, only per-slice results cross the 'DCN' boundary — and the
    solution still matches the untiered tree and the dense oracle."""
    from keystone_tpu import telemetry

    monkeypatch.setenv("KEYSTONE_MESH_TIERS", "2")
    telemetry.reset()
    A = rng.normal(size=(192, 12)).astype(np.float32)
    b = rng.normal(size=(192, 3)).astype(np.float32)
    w_off = np.asarray(tsqr_solve(A, b, lam=0.5, mesh=mesh))
    w_on = np.asarray(tsqr_solve(A, b, lam=0.5, mesh=mesh, overlap=True))
    np.testing.assert_allclose(w_on, w_off, rtol=1e-4, atol=1e-5)
    w_on0 = np.asarray(tsqr_solve(A, b, lam=0.0, mesh=mesh, overlap=True))
    w_ref = np.linalg.lstsq(A, b, rcond=None)[0]
    np.testing.assert_allclose(w_on0, w_ref, rtol=1e-4, atol=1e-4)
    R = np.asarray(tsqr_r(jnp.asarray(A), mesh, overlap=True))
    np.testing.assert_allclose(
        R.T @ R, A.T @ A, rtol=1e-4, atol=1e-3 * np.abs(A.T @ A).max()
    )
    # the two-tier schedule engaged — ONE engaged count per fold (the
    # untagged series), the schedule on tier_schedule, per-tier hop
    # counters: the inner stage folds 4-device slices, the outer stage
    # rings 2 slice results
    reg = telemetry.get_registry()
    assert reg.get_counter("overlap.engaged", site="ring_tsqr_fold") >= 1
    assert reg.get_counter(
        "overlap.tier_schedule", schedule="2x4"
    ) >= 1, reg.as_dict()["counters"]
    assert reg.get_counter(
        "overlap.ppermute_rounds", site="ring_tsqr_fold", tier="inner"
    ) >= 1
    assert reg.get_counter(
        "overlap.ppermute_rounds", site="ring_tsqr_fold", tier="outer"
    ) >= 1
    telemetry.reset()


def test_tsqr_two_tier_hlo_fewer_permutes_no_bulk(mesh, rng, monkeypatch):
    """THE structure pin for the tiered fold: the two-stage schedule keeps
    ZERO bulk all-gather/all-reduce AND lowers to FEWER collective-permutes
    than the flat 8-ring (4 hop-slots — 3 within-slice + 1 cross-slice —
    vs the flat ring's 7), i.e. the cross-slice traffic really dropped to
    the outer-1 slice-result hops."""
    from keystone_tpu.linalg.solvers import _tsqr_solve

    A = jnp.asarray(rng.normal(size=(256, 16)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(256, 3)).astype(np.float32))

    def permute_count(tiered: bool):
        # tiers rides through the jit as a STATIC argument (resolved from
        # KEYSTONE_MESH_TIERS eagerly in tsqr_solve) — passed explicitly
        # here so the two lowerings are distinct compiled programs
        lowered = _tsqr_solve.lower(
            A, b, jnp.float32(0.5), None, mesh, True, "highest", True,
            (2, 4) if tiered else None,
        )
        return _collectives(lowered.compile().as_text())

    flat = permute_count(False)
    tiered = permute_count(True)
    assert tiered["all-gather"] == 0 and tiered["all-reduce"] == 0, tiered
    assert tiered["collective-permute"] >= 1
    assert tiered["collective-permute"] < flat["collective-permute"], (
        tiered, flat,
    )


def test_ring_fold_bad_tiers_degrade_single_tier(mesh, rng):
    """A tier map that does not factor the axis must degrade to the flat
    fold (logged), not silently half-run: results stay correct."""
    from keystone_tpu.parallel import overlap as _ov
    from keystone_tpu.parallel.overlap import ring_tsqr_fold

    _ov._FALLBACK_LOGGED.clear()
    A = rng.normal(size=(128, 8)).astype(np.float32)

    def local(Ai):
        Ri = jnp.linalg.qr(Ai, mode="r")
        R, _ = ring_tsqr_fold(Ri, None, "data", tiers=(3, 2))  # 3*2 != 8
        s = jnp.where(jnp.diagonal(R) < 0, -1.0, 1.0).astype(R.dtype)
        return R * s[:, None]

    f = jax.shard_map(
        local, mesh=mesh, in_specs=P("data", None), out_specs=P(),
        check_vma=False,
    )
    R = np.asarray(f(jnp.asarray(A)))
    np.testing.assert_allclose(
        R.T @ R, A.T @ A, rtol=1e-4, atol=1e-3 * np.abs(A.T @ A).max()
    )


# -- tiled_psum (the sketch reduction's schedule) ---------------------------


def test_tiled_psum_matches_psum(mesh, rng):
    """The standalone tiled reduction (used by the CountSketch partials,
    linalg/sketch.py): equivalence with the monolithic psum plus the
    reduce-scatter/no-all-reduce HLO pin."""
    from keystone_tpu.parallel.overlap import tiled_psum

    k = mesh.shape["data"]
    x = rng.normal(size=(8, 16 * k, 5)).astype(np.float32)

    def tiled(xi):
        return tiled_psum(xi[0], "data")[None]

    spec = P("data", None, None)
    f = jax.shard_map(
        tiled, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
    )
    out = np.asarray(f(jnp.asarray(x)))[0]
    np.testing.assert_allclose(out, x.sum(0), rtol=1e-4, atol=1e-4)
    jf = jax.jit(f)
    assert_pipelined_reduce_scatter(
        jf.lower(jnp.asarray(x)).compile().as_text(), k
    )


def test_tiled_psum_falls_back_on_indivisible_rows(mesh, rng):
    from keystone_tpu.parallel.overlap import tiled_psum

    x = rng.normal(size=(8, 10, 3)).astype(np.float32)  # 10 % 8 != 0

    def tiled(xi):
        return tiled_psum(xi[0], "data")[None]

    spec = P("data", None, None)
    out = np.asarray(jax.shard_map(
        tiled, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
    )(jnp.asarray(x)))[0]
    np.testing.assert_allclose(out, x.sum(0), rtol=1e-4, atol=1e-4)
