"""End-to-end mini runs of the VOC and ImageNet pipelines + loader tests."""

import io
import tarfile

import numpy as np
import pytest

from keystone_tpu.loaders.imagenet import load_imagenet, synthetic_imagenet
from keystone_tpu.loaders.voc import load_voc_labels, synthetic_voc
from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
    ImageNetSiftLcsFVConfig,
    run as run_imagenet,
)
from keystone_tpu.pipelines.voc_sift_fisher import VOCSIFTFisherConfig, run as run_voc


def _make_tar(path, entries):
    from PIL import Image

    with tarfile.open(path, "w") as tf:
        for name, arr in entries:
            b = io.BytesIO()
            Image.fromarray(arr).save(b, "JPEG", quality=95)
            data = b.getvalue()
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            tf.addfile(ti, io.BytesIO(data))


def test_imagenet_loader_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    entries = [
        (f"n01/img_{i}.JPEG", (rng.random((40, 50, 3)) * 255).astype(np.uint8))
        for i in range(3)
    ] + [
        (f"n02/img_{i}.JPEG", (rng.random((64, 64, 3)) * 255).astype(np.uint8))
        for i in range(2)
    ]
    _make_tar(tmp_path / "data.tar", entries)
    (tmp_path / "labels.txt").write_text("n01 0\nn02 1\n")
    imgs, labels = load_imagenet(
        str(tmp_path), str(tmp_path / "labels.txt"), target_hw=(48, 48)
    )
    assert imgs.shape == (5, 48, 48, 3)
    assert sorted(labels.tolist()) == [0, 0, 0, 1, 1]


def test_voc_labels_csv(tmp_path):
    csv = 'header\n1,3,x,y,"img1.jpg"\n2,5,x,y,"img1.jpg"\n3,1,x,y,"img2.jpg"\n'
    (tmp_path / "labels.csv").write_text(csv)
    m = load_voc_labels(str(tmp_path / "labels.csv"))
    assert m == {"img1.jpg": [2, 4], "img2.jpg": [0]}


def test_synthetic_voc_multilabel():
    imgs, labels = synthetic_voc(10, num_classes=5, hw=(48, 48))
    assert imgs.shape == (10, 48, 48, 3)
    assert labels.shape[1] == 2
    assert (labels[:, 0] >= 0).all()  # at least one label each


def test_voc_sift_fisher_end_to_end():
    res = run_voc(
        VOCSIFTFisherConfig(
            desc_dim=16,
            vocab_size=4,
            num_pca_samples=3000,
            num_gmm_samples=3000,
            sift_scales=2,
            lam=0.5,
            synthetic_train=24,
            synthetic_test=12,
            synthetic_classes=4,
            synthetic_hw=64,
        )
    )
    # synthetic prototypes are separable: mAP far above chance (~0.3)
    assert res["test_map"] > 0.6


def test_imagenet_sift_lcs_fv_end_to_end():
    res = run_imagenet(
        ImageNetSiftLcsFVConfig(
            sift_pca_dim=16,
            lcs_pca_dim=16,
            vocab_size=4,
            num_pca_samples=3000,
            num_gmm_samples=3000,
            lam=1e-3,
            block_size=512,
            synthetic_train=32,
            synthetic_test=16,
            synthetic_classes=4,
            synthetic_hw=64,
        )
    )
    assert res["test_top5_error"] <= res["test_top1_error"]
    assert res["test_top1_error"] < 30.0


def test_imagenet_streaming_end_to_end():
    """Flagship out-of-core mode at test scale: chunked synthetic ingest →
    PCA/GMM on a sample → FV block nodes → fit_streaming → streaming eval.
    The (n, d) feature matrix never materializes (VERDICT round-1 item 1)."""
    res = run_imagenet(
        ImageNetSiftLcsFVConfig(
            sift_pca_dim=8,
            lcs_pca_dim=8,
            vocab_size=4,
            num_pca_samples=3000,
            num_gmm_samples=3000,
            lam=1e-3,
            block_size=16,
            synthetic_train=96,
            synthetic_test=32,
            synthetic_classes=4,
            synthetic_hw=48,
            streaming=True,
            extract_chunk=32,
            sample_images=96,
            fv_row_chunk=40,  # ragged: 96 = 2×40 + 16 tail
            desc_dtype="float32",
        )
    )
    assert res["feature_dim"] == 2 * (8 + 8) * 4
    assert res["test_top5_error"] <= res["test_top1_error"]
    assert res["test_top1_error"] < 30.0


def test_imagenet_loader_skips_empty_entry_and_non_tars(tmp_path):
    """A 0-byte entry mid-archive must not truncate ingestion, and stray
    non-tar files in data_dir must be ignored (ingest.cpp ks_tar_next
    end-of-archive vs empty-file disambiguation)."""
    rng = np.random.default_rng(1)
    good = [
        (f"n01/img_{i}.JPEG", (rng.random((48, 48, 3)) * 255).astype(np.uint8))
        for i in range(2)
    ]
    path = tmp_path / "data.tar"
    with tarfile.open(path, "w") as tf:
        from PIL import Image

        def add(name, data):
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            tf.addfile(ti, io.BytesIO(data))

        b = io.BytesIO()
        Image.fromarray(good[0][1]).save(b, "JPEG", quality=95)
        add(good[0][0], b.getvalue())
        add("n01/placeholder.JPEG", b"")  # zero-byte entry in the middle
        b = io.BytesIO()
        Image.fromarray(good[1][1]).save(b, "JPEG", quality=95)
        add(good[1][0], b.getvalue())
    (tmp_path / "labels.txt").write_text("n01 0\n")
    (tmp_path / "README").write_text("not a tar\n")
    imgs, labels = load_imagenet(
        str(tmp_path), str(tmp_path / "labels.txt"), target_hw=(48, 48)
    )
    assert imgs.shape[0] == 2  # both real images survive the empty entry


def test_bucketed_loader_mixed_sizes(tmp_path):
    """Variable-size ingest (VERDICT round-1 item 6): mixed-size JPEGs land
    in the smallest containing bucket (pad, no crop) or the largest (crop),
    and per-bucket SIFT descriptor counts match dsift_geometry for the
    bucket's static shape."""
    import jax.numpy as jnp

    from keystone_tpu.native import BucketedImageLoader
    from keystone_tpu.ops.images import GrayScaler
    from keystone_tpu.ops.images.sift import SIFTExtractor

    rng = np.random.default_rng(7)
    entries = [
        ("a/small_0.JPEG", (rng.random((40, 50, 3)) * 255).astype(np.uint8)),
        ("a/small_1.JPEG", (rng.random((60, 64, 3)) * 255).astype(np.uint8)),
        ("a/mid_0.JPEG", (rng.random((80, 100, 3)) * 255).astype(np.uint8)),
        ("a/huge_0.JPEG", (rng.random((200, 260, 3)) * 255).astype(np.uint8)),
    ]
    _make_tar(tmp_path / "mixed.tar", entries)
    loader = BucketedImageLoader(
        [str(tmp_path / "mixed.tar")], buckets=[(64, 64), (128, 128)],
        num_threads=2,
    )
    sift = SIFTExtractor(scales=2)
    by_bucket = {}
    for hw, imgs, names in loader.batches(batch_size=8):
        assert imgs.shape[1:] == (*hw, 3)
        by_bucket.setdefault(hw, []).extend(names)
        gray = GrayScaler()(jnp.asarray(imgs))[..., 0]
        descs = sift(gray)
        assert descs.shape[1] == sift.num_descriptors(*hw)  # dsift_geometry
    # 40x50 and 60x64 fit (64,64); 80x100 fits (128,128); 200x260 crops
    # into the largest bucket (128,128).
    small = {n.split("/")[-1] for n in by_bucket[(64, 64)]}
    big = {n.split("/")[-1] for n in by_bucket[(128, 128)]}
    assert small == {"small_0.JPEG", "small_1.JPEG"}
    assert big == {"mid_0.JPEG", "huge_0.JPEG"}


def test_bucketed_loader_abandoned_generator_cleans_up(tmp_path):
    """Early break out of batches() must not leave worker threads blocked on
    a full queue (decoded images pinned for the process lifetime)."""
    import threading

    from keystone_tpu.native import BucketedImageLoader

    rng = np.random.default_rng(3)
    entries = [
        (f"a/i{k}.JPEG", (rng.random((48, 48, 3)) * 255).astype(np.uint8))
        for k in range(12)
    ]
    _make_tar(tmp_path / "m.tar", entries)
    before = threading.active_count()
    loader = BucketedImageLoader([str(tmp_path / "m.tar")], [(64, 64)], num_threads=2)
    for hw, imgs, names in loader.batches(batch_size=2):
        break  # abandon the generator mid-stream
    import gc

    gc.collect()  # finalize the abandoned generator (runs its finally)
    deadline = 50
    while threading.active_count() > before and deadline:
        import time

        time.sleep(0.1)
        deadline -= 1
    assert threading.active_count() <= before


def test_streaming_quality_signal_with_shuffled_label_control():
    """Flagship quality protocol at test scale (VERDICT r2 weak #3): at the
    non-vacuous noise (0.6, the flagship default) the streaming fit must
    carry real class signal — top-1 error well below chance — and the
    shuffled-label control (train labels independent of images) must
    collapse toward chance, proving the signal comes from the images, not
    from a leak in the pipeline."""
    base = dict(
        sift_pca_dim=8,
        lcs_pca_dim=8,
        vocab_size=4,
        num_pca_samples=3000,
        num_gmm_samples=3000,
        lam=1e-3,
        block_size=16,
        synthetic_train=256,
        synthetic_test=64,
        synthetic_classes=8,
        synthetic_hw=48,
        synthetic_noise=0.6,
        streaming=True,
        extract_chunk=64,
        sample_images=128,
        fv_row_chunk=64,
        desc_dtype="float32",
    )
    res = run_imagenet(ImageNetSiftLcsFVConfig(**base))
    ctrl = run_imagenet(ImageNetSiftLcsFVConfig(**base, shuffle_labels=True))
    chance_top1 = 100.0 * (1.0 - 1.0 / 8)  # 87.5%
    # real labels: clear signal (non-trivial bound, far from both 0 and chance)
    assert res["test_top1_error"] < 0.6 * chance_top1, res
    # QUALITY FLOOR (VERDICT r3 weak #1, tightened r5 per VERDICT r4 #4):
    # fixed-seed flagship-shape run at the default noise. Two-sided pin:
    # (a) ≤ 5% at THIS seed — the measured value is 0.0% (chance top-5 =
    # 37.5%), so a structural regression from 0% to 10-15% at test scale
    # now fails instead of hiding under the old 20% bound; (b) the 20%
    # band-blowout bound stays as a separately-worded assertion so a
    # platform-numerics drift that nudges the draw shows up as a distinct
    # failure message from a band blowout.
    assert res["test_top5_error"] <= 20.0, ("quality band blowout", res)
    assert res["test_top5_error"] <= 5.0, (
        "fixed-seed quality floor regressed (expected ~0%)", res)
    # shuffled labels: no signal — error near chance
    assert ctrl["test_top1_error"] > 0.75 * chance_top1, ctrl
    assert ctrl["test_top1_error"] > res["test_top1_error"]
