"""Device-keyed tile autotuner (``ops/pallas/autotune.py``).

Counters (``autotune.{sweep,cache_hit,cache_miss,default}``) are asserted
via the telemetry registry as DELTAS — never absolute totals and never by
resetting the process-global registry (other tests share it). The headline
contract: a sweep happens at most once per (kernel, device, bucket); a
repeat resolution — including after dropping the in-memory mirror, i.e. a
fresh process against the persisted file — performs ZERO re-sweeps.
"""

import json
import os

import jax
import numpy as np
import pytest

from keystone_tpu.ops.pallas import autotune
from keystone_tpu.telemetry import get_registry


def _count(name: str) -> float:
    return sum(get_registry().counters(name).values())


@pytest.fixture()
def tuner_cache(tmp_path, monkeypatch):
    """Repoint the cache at a tmp file and drop the in-memory mirror so
    every test starts from an empty, isolated cache."""
    path = tmp_path / "autotune_cache.json"
    monkeypatch.setenv("KEYSTONE_AUTOTUNE_CACHE", str(path))
    autotune.clear_memory_cache()
    yield path
    autotune.clear_memory_cache()


def test_shape_bucket_pow2_bands():
    assert autotune.shape_bucket(1000, 128) == "1024x128"
    assert autotune.shape_bucket(1024) == "1024"
    assert autotune.shape_bucket(1025) == "2048"
    assert autotune.shape_bucket(1) == "1"
    assert autotune.shape_bucket(0) == "0"
    # shapes within one band share an entry; across bands they don't
    assert autotune.shape_bucket(700, 37) == autotune.shape_bucket(513, 64) == "1024x64"
    assert autotune.shape_bucket(700) == autotune.shape_bucket(1024)
    assert autotune.shape_bucket(700) != autotune.shape_bucket(1025)


def test_device_key_names_backend_and_generation():
    key = autotune.device_key()
    backend, _, kind = key.partition(":")
    assert backend == jax.default_backend()
    assert kind and all(c.islower() or c.isdigit() or c == "_" for c in kind)


def test_resolve_sweeps_once_then_hits_persisted_cache(tuner_cache, monkeypatch):
    monkeypatch.setenv("KEYSTONE_AUTOTUNE", "1")
    calls = []

    def measure(cand, reps):
        calls.append(cand)
        return {8: 0.05, 16: 0.01, 32: 0.09}[cand] * reps

    s0, h0 = _count("autotune.sweep"), _count("autotune.cache_hit")
    won = autotune.resolve("test.kernel", "64x64", (8, 16, 32), 8,
                           measure=measure)
    assert won == 16  # fastest latency-cancelled candidate
    assert calls, "sweep never measured"
    assert _count("autotune.sweep") == s0 + 1
    # persisted, device-keyed
    data = json.loads(tuner_cache.read_text())
    entry = data["devices"][autotune.device_key()]["test.kernel"]["64x64"]
    assert entry["value"] == 16 and entry["swept"] == 3

    # repeat resolution: zero re-sweeps, pure cache hit — including after
    # dropping the in-memory mirror (the fresh-process case)
    calls.clear()
    assert autotune.resolve("test.kernel", "64x64", (8, 16, 32), 8,
                            measure=measure) == 16
    autotune.clear_memory_cache()
    assert autotune.resolve("test.kernel", "64x64", (8, 16, 32), 8,
                            measure=measure) == 16
    assert not calls, "a persisted winner was re-swept"
    assert _count("autotune.sweep") == s0 + 1
    assert _count("autotune.cache_hit") >= h0 + 2


def test_resolve_without_knob_serves_default_and_never_sweeps(
    tuner_cache, monkeypatch
):
    monkeypatch.delenv("KEYSTONE_AUTOTUNE", raising=False)
    d0 = _count("autotune.default")

    def boom(cand, reps):
        raise AssertionError("swept with KEYSTONE_AUTOTUNE unset")

    assert autotune.resolve("test.off", "any", (8, 16), 12, measure=boom) == 12
    assert _count("autotune.default") == d0 + 1
    assert not tuner_cache.exists()


def test_sweep_skips_failing_candidates_and_bounds_grid(
    tuner_cache, monkeypatch
):
    monkeypatch.setenv("KEYSTONE_AUTOTUNE", "1")
    monkeypatch.setenv("KEYSTONE_AUTOTUNE_GRID", "2")
    seen = []

    def measure(cand, reps):
        seen.append(cand)
        if cand == 8:
            raise ValueError("shape cannot support this tile")
        return 0.01 * reps

    won = autotune.resolve("test.bounded", "b", (8, 16, 32), 8,
                           measure=measure)
    assert won == 16  # 8 failed, 32 fell past the bounded grid
    assert 32 not in seen


def test_corrupt_cache_degrades_to_default(tuner_cache, monkeypatch):
    tuner_cache.write_text("{not json")
    assert autotune.lookup("test.kernel", "64x64") is None
    # and recording over it repairs the file
    autotune.record("test.kernel", "64x64", 4, swept=1)
    autotune.clear_memory_cache()
    assert autotune.lookup("test.kernel", "64x64") == 4


def test_malformed_nesting_is_pruned_not_fatal(tuner_cache):
    """A schema-passing file with malformed NESTING (hand edit, foreign
    writer) must degrade branch-by-branch, never crash a lookup or a
    record — tuning is not a correctness dependency."""
    tuner_cache.write_text(json.dumps({
        "version": 1,
        "devices": {
            autotune.device_key(): {
                "bad.kernel": 5,                      # not a bucket dict
                "half.kernel": {"b": 7, "ok": {"value": 3}},
                "good.kernel": {"64x64": {"value": 9}},
            },
            "other:dev": "junk",
        },
    }))
    assert autotune.lookup("bad.kernel", "any") is None
    assert autotune.lookup("half.kernel", "b") is None
    assert autotune.lookup("half.kernel", "ok") == 3
    assert autotune.lookup("good.kernel", "64x64") == 9
    # record() survives merging over the pruned structure
    autotune.record("bad.kernel", "any", 1, swept=1)
    autotune.clear_memory_cache()
    assert autotune.lookup("bad.kernel", "any") == 1
    assert autotune.lookup("good.kernel", "64x64") == 9


def test_all_candidates_failing_counts_default_only(tuner_cache, monkeypatch):
    monkeypatch.setenv("KEYSTONE_AUTOTUNE", "1")
    s0, d0 = _count("autotune.sweep"), _count("autotune.default")

    def boom(cand, reps):
        raise ValueError("no tile fits")

    assert autotune.resolve("test.allfail", "b", (8, 16), 12,
                            measure=boom) == 12
    # exactly ONE outcome counter fired: default (the sweep yielded nothing)
    assert _count("autotune.sweep") == s0
    assert _count("autotune.default") == d0 + 1


def test_pick_tiles_consumes_tuned_default_env_still_wins(
    tuner_cache, monkeypatch
):
    from keystone_tpu.parallel.overlap import _pick_tiles

    dim, k = 96, 4
    # no entry: heuristic target (axis size) — 96/(4*4)=6 tiles at target 4
    assert _pick_tiles(dim, k) == 4
    autotune.record("overlap.tiles", autotune.shape_bucket(dim, k), 3,
                    swept=1)
    assert _pick_tiles(dim, k) == 3
    # explicit target argument and env override both beat the tuner
    assert _pick_tiles(dim, k, target=6) == 6
    monkeypatch.setenv("KEYSTONE_OVERLAP_TILES", "2")
    assert _pick_tiles(dim, k) == 2
    monkeypatch.delenv("KEYSTONE_OVERLAP_TILES")
    # a tuned value the shape cannot honor degrades like any target
    autotune.record("overlap.tiles", autotune.shape_bucket(dim, k), 5,
                    swept=1)
    assert _pick_tiles(dim, k) == 4  # largest valid count <= 5


def test_moments_tile_resolves_through_autotuner(tuner_cache):
    """The satellite: ``moments._TILE_N`` is gone — the kernel resolves its
    row tile through the shared path, and a persisted winner changes the
    padding/grid while keeping results exact."""
    from keystone_tpu.ops.pallas import moments as M

    assert M._tile_n() == M._TILE_N_DEFAULT
    autotune.record("moments.tile_n", "any", 256, swept=1)
    assert M._tile_n() == 256

    rng = np.random.default_rng(0)
    x = rng.normal(size=(700, 24)).astype(np.float32)
    means = rng.normal(size=(6, 24)).astype(np.float32)
    variances = rng.uniform(0.5, 2.0, (6, 24)).astype(np.float32)
    weights = rng.dirichlet(np.ones(6)).astype(np.float32)
    ref = M.gmm_moments_xla(x, means, variances, weights)
    out = M.gmm_moments(x, means, variances, weights)  # tile 256 padding
    for a, b in zip(out, ref):
        denom = float(np.max(np.abs(np.asarray(b)))) + 1e-9
        np.testing.assert_allclose(
            np.asarray(a) / denom, np.asarray(b) / denom, atol=2e-3
        )
    # a stale larger tile against a sample padded at 256 re-fits the grid
    assert M._fit_tile(768, 1024) == 256


def test_unwritable_cache_dir_serves_in_memory(tmp_path, monkeypatch):
    target = tmp_path / "no_such_dir" / "autotune_cache.json"
    monkeypatch.setenv("KEYSTONE_AUTOTUNE_CACHE", str(target))
    autotune.clear_memory_cache()
    autotune.record("test.mem", "b", 7, swept=1)
    assert autotune.lookup("test.mem", "b") == 7  # mirror still serves
    assert not target.exists()
    autotune.clear_memory_cache()
