"""Fleet serving tier (keystone_tpu/serve/{pool,front,fleet}.py): the
multi-tenant pool's declared policies (HBM-envelope admission, LRU/priority
eviction over the cache tiers, per-tenant fair shedding), the socket
front's cross-process coalescing parity, and the replicated fleet's chaos
contract (kill one replica under load -> traffic rebalances, no wedge).

The pool tests run against UNSTARTED gateways where the policy under test
is a submit-path gate (deterministic: no worker races), and against
started ones only where dispatch itself is the subject (eviction).  The
chaos test spawns real replica worker processes and rides the existing
``KEYSTONE_FAULTS`` serve.dispatch site — the same plan grammar every
other fault drill uses.
"""

import threading
import time

import jax
import numpy as np
import pytest

import keystone_tpu._compat  # noqa: F401
from keystone_tpu.core.pipeline import Transformer, chain
from keystone_tpu.serve import BatchingFront, Fleet, FrontClient, pool
from keystone_tpu.serve.pool import ladder_peak_bytes
from keystone_tpu.telemetry import get_registry


class Doubler(Transformer):
    def apply(self, x):
        return x * 2


D = 4


def _spec(d=D):
    return jax.ShapeDtypeStruct((d,), np.float32)


def _item(i=0.0, d=D):
    return np.arange(d, dtype=np.float32) + np.float32(i)


# ---------------------------------------------------------------------------
# ladder_peak_bytes (the A5 bound the admission gate enforces)
# ---------------------------------------------------------------------------


def test_ladder_peak_bytes_counts_model_and_widest_rung():
    node = chain(Doubler())
    small = ladder_peak_bytes(node, _spec(), (1,))
    big = ladder_peak_bytes(node, _spec(), (1, 64))
    # elementwise chain: boundary = rung * (in + out) item bytes
    assert small >= 2 * D * 4
    assert big >= 64 * 2 * D * 4
    assert big > small  # monotone in the largest rung


# ---------------------------------------------------------------------------
# HBM-envelope admission (overflow rejects PRE-dispatch, never OOM-retry)
# ---------------------------------------------------------------------------


def test_over_envelope_tenant_rejects_pre_dispatch():
    reg = get_registry()
    before = reg.get_counter("serve.rejected", kind="hbm")
    # 16-byte envelope: no ladder fits; the model must register cold
    p = pool(chain(Doubler()), item_spec=_spec(),
             hbm_mb=16 / (1 << 20), warm=False, start=False)
    try:
        ts = p.tenant_stats("default")
        assert ts["over_envelope"] is True
        assert ts["peak_bytes"] > p.hbm_bytes
        r = p.submit(_item()).result(1)
        # the declared-envelope gate decision: a structured rejection at
        # the gate, not a shed and NOT an OOM dug out of a dispatch retry
        assert r.ok is False
        assert r.code == "rejected"
        assert r.kind == "hbm"
        assert "envelope" in (r.error or "")
        assert reg.get_counter("serve.rejected", kind="hbm") == before + 1
        assert p.tenant_stats("default")["rejected"] == 1
    finally:
        p.close(drain=False)


def test_envelope_zero_is_unbounded():
    p = pool(chain(Doubler()), item_spec=_spec(), hbm_mb=0.0,
             warm=False, start=False)
    try:
        assert p.tenant_stats("default")["over_envelope"] is False
    finally:
        p.close(drain=False)


# ---------------------------------------------------------------------------
# per-tenant fair shedding (asymmetric load cannot starve the cold tenant)
# ---------------------------------------------------------------------------


def test_fair_share_sheds_hot_tenant_not_cold():
    p = pool(chain(Doubler()), item_spec=_spec(), name="hot",
             queue_depth=8, fair_frac=0.25, warm=False, start=False)
    try:
        p.add_model("cold", chain(Doubler()), _spec())
        cap = max(1, int(p.queue_depth * p.fair_frac))  # = 2
        pend = [p.submit(_item(i), model="hot") for i in range(6)]
        # first `cap` admit; the rest shed at the tenant gate
        assert sum(1 for q in pend if not q.done()) == cap
        sheds = [q.result(0.1) for q in pend if q.done()]
        assert all(r.code == "shed" for r in sheds)
        assert all("share" in (r.error or "") for r in sheds)
        assert all((r.retry_after_s or 0) > 0 for r in sheds)
        # the cold tenant's request still admits through its own share
        q = p.submit(_item(), model="cold")
        assert not q.done()
        stats = p.tenant_stats()
        assert stats["hot"]["shed"] == 6 - cap
        assert stats["hot"]["shed_frac"] > 0
        assert stats["cold"]["shed"] == 0
        assert stats["cold"]["shed_frac"] == 0.0
    finally:
        p.close(drain=False)


# ---------------------------------------------------------------------------
# LRU/priority eviction over the cache tiers (declared, not a sweep)
# ---------------------------------------------------------------------------


def test_envelope_pressure_demotes_lru_tenant():
    reg = get_registry()
    before = reg.get_counter("serve.model_demotions")
    node = chain(Doubler())
    peak = ladder_peak_bytes(node, _spec(), (1, 2))
    # envelope fits ONE tenant's ladder, not two
    p = pool(node, item_spec=_spec(), name="a", shapes=(1, 2),
             hbm_mb=1.5 * peak / (1 << 20), coalesce_ms=0.0)
    try:
        p.add_model("b", chain(Doubler()), _spec())
        assert p.predict(_item(), model="a", deadline_ms=5000) is not None
        assert p.predict(_item(), model="b", deadline_ms=5000) is not None
        stats = p.tenant_stats()
        # dispatching "b" had to demote "a" (the LRU victim) to host
        assert stats["b"]["tier"] == "device"
        assert stats["a"]["tier"] == "host"
        assert reg.get_counter("serve.model_demotions") > before
        # a later request PROMOTES "a" back — tier mechanics unchanged
        assert p.predict(_item(), model="a", deadline_ms=5000) is not None
        assert p.tenant_stats("a")["tier"] == "device"
    finally:
        p.close(drain=False)


# ---------------------------------------------------------------------------
# socket front: cross-process parity + cross-connection coalescing
# ---------------------------------------------------------------------------


def test_front_parity_and_cross_connection_coalescing(tmp_path):
    reg = get_registry()
    pipe = chain(Doubler())
    g = pool(pipe, item_spec=_spec(), shapes=(1, 4), coalesce_ms=0.0,
             start=False)
    front = BatchingFront(g, path=str(tmp_path / "front.sock"))
    try:
        results = {}

        def one(i):
            c = FrontClient(front.path)
            try:
                results[i] = c.predict(_item(float(i)))
            finally:
                c.close()

        threads = [threading.Thread(target=one, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5.0
        while len(g._queue) < 4 and time.monotonic() < deadline:
            time.sleep(0.005)  # let every connection's request enqueue
        d0 = reg.counter_family_total("serve.dispatch_total")
        g.start()
        for t in threads:
            t.join(10)
        d1 = reg.counter_family_total("serve.dispatch_total")
        assert len(results) == 4
        for i, r in results.items():
            assert r["ok"] is True
            np.testing.assert_allclose(
                np.asarray(r["value"]),
                np.asarray(pipe.serve(_item(float(i)))),
            )
        # 4 requests from 4 CONNECTIONS coalesced into one padded rung
        assert d1 - d0 == 1
    finally:
        front.close()
        g.close(drain=False)


# ---------------------------------------------------------------------------
# chaos: SIGKILL one replica under load -> rebalance, no wedge
# ---------------------------------------------------------------------------


def test_kill_one_replica_rebalances_no_wedge():
    x = np.zeros(64, np.float32)
    # replica 0 carries a fault plan on the EXISTING serve.dispatch site:
    # its 3rd dispatch SIGKILLs the process mid-flight
    with Fleet("cosine", replicas=2, shapes="1,2", coalesce_ms=0.0,
               faults={0: "serve.dispatch@2:kill"}) as f:
        assert f.live_count() == 2
        outcomes = []
        for _ in range(12):
            r = f.predict(x, deadline_ms=5000)
            outcomes.append(r)
            assert isinstance(r, dict)  # structured, never a raw error
            if f.live_count() == 1:
                break
        deadline = time.monotonic() + 10.0
        while f.live_count() == 2 and time.monotonic() < deadline:
            f.predict(x, deadline_ms=5000)
        assert f.live_count() == 1  # the kill landed and was detected
        # traffic rebalances onto the survivor: served, not wedged
        for _ in range(3):
            r = f.predict(x, deadline_ms=5000)
            assert r["ok"] is True
        s = f.stats()
        assert s["live"] == 1
        assert s["replicas"]["0"] == {"dead": True}
        tenants = s["replicas"]["1"]["stats"]["tenants"]
        assert tenants["default"]["served"] > 0
        # no survivors left -> structured fleet_down, still no wedge
        f.kill(1)
        r = f.predict(x)
        assert r["ok"] is False
        assert r["code"] == "fleet_down"


if __name__ == "__main__":
    pytest.main([__file__, "-v"])
