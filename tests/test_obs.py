"""Fleet-wide observability plane (keystone_tpu/telemetry/fleet.py +
trace.py): pid+role-unique crash-atomic shard export, exact-sum merge
under concurrent writers, stale-shard pruning, request-scoped trace-id
propagation through a REAL BatchingFront -> gateway round trip stitched
into one multi-process Perfetto trace, the zero-overhead-when-off pin
(no span records, stable compile cache, byte-identical lowered HLO), and
the ``signals()`` schema the planner consumes.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

import keystone_tpu._compat  # noqa: F401
from keystone_tpu.core.pipeline import Transformer, chain
from keystone_tpu.serve import serve
from keystone_tpu.serve.front import BatchingFront, FrontClient, mint_trace_id
from keystone_tpu.telemetry import reset as telemetry_reset
from keystone_tpu.telemetry.fleet import (
    bench_keys,
    export_process,
    merge_shards,
    merge_traces,
    obs_main,
    signals,
)
from keystone_tpu.telemetry.registry import LATENCY_BUCKETS_MS, MetricsRegistry
from keystone_tpu.telemetry.spans import get_tracer
from keystone_tpu.utils import knobs

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Doubler(Transformer):
    def apply(self, x):
        return x * 2


def _spec(d=4):
    return jax.ShapeDtypeStruct((d,), np.float32)


def _item(d=4):
    return np.arange(d, dtype=np.float32)


def _clean_env(**extra):
    env = os.environ.copy()
    env.pop("XLA_FLAGS", None)
    env.pop("KEYSTONE_TELEMETRY", None)
    env.pop("KEYSTONE_TELEMETRY_DIR", None)
    env.update(JAX_PLATFORMS="cpu", **extra)
    return env


# ---------------------------------------------------------------------------
# Shard export + merge
# ---------------------------------------------------------------------------


def test_shard_names_are_pid_and_role_unique(tmp_path, monkeypatch):
    """Two roles in one process -> two shard files; re-exporting the same
    role overwrites ITS OWN shard (idempotent), never another's — the fix
    for the fixed-filename atexit clobber."""
    reg = MetricsRegistry()
    reg.inc("x.count", 3)
    monkeypatch.setenv("KEYSTONE_TELEMETRY_ROLE", "alpha")
    paths_a = export_process(str(tmp_path), registry=reg)
    monkeypatch.setenv("KEYSTONE_TELEMETRY_ROLE", "beta")
    paths_b = export_process(str(tmp_path), registry=reg)
    assert paths_a["metrics"] != paths_b["metrics"]
    assert str(os.getpid()) in os.path.basename(paths_a["metrics"])
    n_before = len(list(tmp_path.iterdir()))
    export_process(str(tmp_path), registry=reg)  # same role+pid: overwrite
    assert len(list(tmp_path.iterdir())) == n_before
    view = merge_shards(str(tmp_path), prune=False)
    assert view["merged"]["counters"]["x.count"] == 6  # alpha + beta
    assert not view["pruned"]
    # no temp droppings: the atomic write cleaned up after itself
    assert not [p for p in tmp_path.iterdir() if p.suffix == ".tmp"]


def test_merge_exact_sums_under_concurrent_process_writers(tmp_path):
    """N real OS processes exporting concurrently into one dir: the merged
    counters equal the exact per-process sums, gauges stay per-process
    under the added proc label, histograms union bucket-wise."""
    code = (
        "import sys\n"
        "from keystone_tpu.telemetry.fleet import export_process\n"
        "from keystone_tpu.telemetry.registry import (\n"
        "    LATENCY_BUCKETS_MS, MetricsRegistry)\n"
        "i = int(sys.argv[1])\n"
        "reg = MetricsRegistry()\n"
        "reg.inc('w.count', i + 1)\n"
        "reg.inc('w.labeled', 2, kind='a')\n"
        "reg.set_gauge('w.depth', float(i))\n"
        "reg.observe('w.lat_ms', 5.0 * (i + 1),\n"
        "            buckets=LATENCY_BUCKETS_MS)\n"
        "export_process(sys.argv[2], registry=reg)\n"
    )
    n = 4
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code, str(i), str(tmp_path)],
            cwd=_REPO,
            env=_clean_env(KEYSTONE_TELEMETRY_ROLE=f"writer-{i}"),
        )
        for i in range(n)
    ]
    for p in procs:
        assert p.wait(timeout=60) == 0
    view = merge_shards(str(tmp_path), prune=False)
    assert len(view["procs"]) == n
    assert {p["role"] for p in view["procs"]} == {
        f"writer-{i}" for i in range(n)
    }
    merged = view["merged"]
    assert merged["counters"]["w.count"] == sum(i + 1 for i in range(n))
    assert merged["counters"]["w.labeled{kind=a}"] == 2 * n
    # gauges NOT summed: one proc-labeled series per writer
    depth_keys = [k for k in merged["gauges"] if k.startswith("w.depth{")]
    assert len(depth_keys) == n
    assert sorted(merged["gauges"][k] for k in depth_keys) == [
        float(i) for i in range(n)
    ]
    h = merged["histograms"]["w.lat_ms"]
    assert h["count"] == n
    assert h["sum"] == pytest.approx(sum(5.0 * (i + 1) for i in range(n)))
    assert h["min"] == 5.0 and h["max"] == 5.0 * n


def test_stale_shards_pruned_fresh_dead_pid_kept(tmp_path, monkeypatch):
    """A DEAD pid's shard past the staleness horizon is pruned (and never
    summed); a fresh shard from a dead pid — the normal atexit export of
    an exited worker — still merges.  Unparseable shards are pruned too."""
    import time as _time

    dead_pid = 2 ** 22 + 12345  # beyond pid_max defaults: never alive
    stale = {
        "schema": 1, "pid": dead_pid, "role": "old", "host": "h",
        "exported_at": _time.time() - 86400.0,
        "metrics": {"counters": {"x.count": 100}, "gauges": {},
                    "histograms": {}},
    }
    fresh_dead = dict(stale, role="worker", exported_at=_time.time(),
                      metrics={"counters": {"x.count": 7}, "gauges": {},
                               "histograms": {}})
    (tmp_path / f"telemetry_shard-old-{dead_pid}.json").write_text(
        json.dumps(stale)
    )
    (tmp_path / f"telemetry_trace_shard-old-{dead_pid}.json").write_text(
        json.dumps({"schema": 1, "pid": dead_pid, "role": "old",
                    "exported_at": stale["exported_at"],
                    "epoch_offset_us": 0.0,
                    "trace": {"traceEvents": []}})
    )
    (tmp_path / f"telemetry_shard-worker-{dead_pid}.json").write_text(
        json.dumps(fresh_dead)
    )
    (tmp_path / "telemetry_shard-torn-1.json").write_text("{not json")
    view = merge_shards(str(tmp_path))
    assert view["merged"]["counters"]["x.count"] == 7  # stale NOT summed
    assert f"telemetry_shard-old-{dead_pid}.json" in view["pruned"]
    assert "telemetry_shard-torn-1.json" in view["pruned"]
    # pruning removed the stale metric shard AND its trace twin
    assert not (tmp_path / f"telemetry_shard-old-{dead_pid}.json").exists()
    assert not (
        tmp_path / f"telemetry_trace_shard-old-{dead_pid}.json"
    ).exists()
    assert (tmp_path / f"telemetry_shard-worker-{dead_pid}.json").exists()


# ---------------------------------------------------------------------------
# Distributed tracing
# ---------------------------------------------------------------------------


def test_trace_id_rides_front_frame_and_stitches_one_trace(
        tmp_path, monkeypatch):
    """A client-minted trace id rides the unix-socket frame through a REAL
    BatchingFront -> gateway round trip: the response echoes it, every
    serve-path span carries it, and merge_traces stitches spans from TWO
    OS processes into ONE Perfetto trace with flow arrows on the id."""
    monkeypatch.setenv("KEYSTONE_TELEMETRY", "1")
    telemetry_reset()
    g = serve(chain(Doubler()), item_spec=_spec(), slo_ms=10_000.0)
    front = BatchingFront(g)
    client = FrontClient(front.path)
    tid = mint_trace_id()
    try:
        resp = client.predict(_item(), trace_id=tid)
        assert resp["ok"], resp
        assert resp["trace"] == tid
        np.testing.assert_allclose(np.asarray(resp["value"]), _item() * 2)
        # an untraced request stays untraced (no ambient id leaks in)
        resp2 = client.predict(_item())
        assert resp2["ok"] and resp2["trace"] is None
    finally:
        client.close()
        front.close()
        g.close()
    spans = [
        (e["name"], (e.get("args") or {}).get("trace_id"))
        for e in get_tracer().chrome_trace()["traceEvents"]
        if e.get("ph") == "X"
    ]
    traced_names = {name for name, t in spans if t == tid}
    for want in ("front.enqueue", "serve.admit", "serve.coalesce",
                 "serve.rung", "serve.dispatch", "serve.reply"):
        assert want in traced_names, (want, spans)
    monkeypatch.setenv("KEYSTONE_TELEMETRY_ROLE", "gateway")
    export_process(str(tmp_path))
    # a second OS process records its half of the SAME request trace
    code = (
        "import os, sys\n"
        "from keystone_tpu.telemetry.fleet import export_process\n"
        "from keystone_tpu.telemetry.trace import request_span\n"
        "with request_span('client.send', sys.argv[1]):\n"
        "    pass\n"
        "export_process(sys.argv[2])\n"
    )
    rc = subprocess.run(
        [sys.executable, "-c", code, tid, str(tmp_path)],
        cwd=_REPO,
        env=_clean_env(KEYSTONE_TELEMETRY="1",
                       KEYSTONE_TELEMETRY_ROLE="client"),
        timeout=60,
    ).returncode
    assert rc == 0
    merged = merge_traces(str(tmp_path),
                          out_path=str(tmp_path / "trace.json"))
    evs = merged["traceEvents"]
    traced = [e for e in evs if e.get("ph") == "X"
              and (e.get("args") or {}).get("trace_id") == tid]
    assert len({e["pid"] for e in traced}) >= 2  # spans from BOTH processes
    flows = [e for e in evs if e.get("ph") in ("s", "t", "f")
             and e.get("id") == tid]
    assert [e for e in flows if e["ph"] == "s"]
    assert [e for e in flows if e["ph"] == "f" and e.get("bp") == "e"]
    # the written artifact is the same Perfetto-loadable JSON
    on_disk = json.loads((tmp_path / "trace.json").read_text())
    assert on_disk["traceEvents"]
    # every event has the Chrome-trace required fields
    for e in on_disk["traceEvents"]:
        assert "ph" in e and "pid" in e
        if e["ph"] == "X":
            assert "ts" in e and "dur" in e and "name" in e


def test_tracing_off_zero_spans_no_recompile_identical_hlo(monkeypatch):
    """KEYSTONE_TRACE_SAMPLE=0 and telemetry off: serving records ZERO
    spans, the jit cache never grows past warmup, and the dispatch
    program lowers to byte-identical HLO with tracing active vs not —
    trace ids are host metadata, never program inputs."""
    from keystone_tpu.serve.gateway import _jit_apply_batch, _serve_apply
    from keystone_tpu.telemetry.spans import use_tracing
    from keystone_tpu.telemetry.trace import maybe_mint, request_span, \
        use_trace

    monkeypatch.delenv("KEYSTONE_TELEMETRY", raising=False)
    monkeypatch.delenv("KEYSTONE_TELEMETRY_DIR", raising=False)
    monkeypatch.setenv("KEYSTONE_TRACE_SAMPLE", "0.0")
    telemetry_reset()
    assert maybe_mint() is None  # sampling off: the edge mints nothing
    g = serve(chain(Doubler()), item_spec=_spec(), slo_ms=10_000.0)
    try:
        g.predict(_item())
        size0 = g.compile_cache_size()
        for i in range(5):
            g.predict(_item())
        assert g.compile_cache_size() == size0
        assert _jit_apply_batch._cache_size() == size0
    finally:
        g.close()
    evs = get_tracer().chrome_trace()["traceEvents"]
    assert [e for e in evs if e.get("ph") == "X"] == []
    # byte-identical lowered programs, traced vs untraced
    node = chain(Doubler())
    xs = np.zeros((4, 4), np.float32)
    plain = jax.jit(lambda x: _serve_apply(node, x)).lower(xs).as_text()
    with use_tracing(True), use_trace("deadbeefdeadbeef"):
        with request_span("serve.rung", "deadbeefdeadbeef", n=4):
            traced = jax.jit(
                lambda x: _serve_apply(node, x)
            ).lower(xs).as_text()
    assert plain == traced
    telemetry_reset()


def test_sample_rate_mints_when_selected(monkeypatch):
    """KEYSTONE_TRACE_SAMPLE=1.0 mints an id at the admission edge even
    when the caller passed none (and the knob validates as a fraction)."""
    from keystone_tpu.telemetry.trace import maybe_mint

    monkeypatch.setenv("KEYSTONE_TRACE_SAMPLE", "1.0")
    tid = maybe_mint()
    assert tid is not None and len(tid) == 16
    monkeypatch.setenv("KEYSTONE_TRACE_SAMPLE", "2.0")
    with pytest.raises(ValueError):
        knobs.validate_environment()


# ---------------------------------------------------------------------------
# Signals + CLI
# ---------------------------------------------------------------------------

_SERVE_KEYS = {
    "requests", "responses", "shed_total", "shed_frac", "breaker_trips",
    "sentinel_trips", "demotions", "p50_ms", "p99_ms",
}
_TENANT_KEYS = {
    "responses", "served", "shed", "slo_violations", "slo_violation_frac",
    "p50_ms", "p99_ms",
}
_INGEST_KEYS = {"prefetch_stalls", "prefetch_ready", "ingest_batches"}


def test_signals_schema_is_stable_process_and_fleet_scope(tmp_path,
                                                          monkeypatch):
    """The planner-facing dict: same pinned schema over the local registry
    and over a fleet-merged snapshot, fractions consistent with the raw
    counters."""
    reg = MetricsRegistry()
    reg.inc("serve.requests", 4, model="m")
    reg.inc("serve.responses", 3, code="ok")
    reg.inc("serve.responses", code="shed")
    reg.inc("serve.shed_total", reason="overload")
    reg.inc("serve.breaker", event="open")
    reg.inc("serve.tenant_responses", 4, model="m")
    reg.inc("serve.tenant_served", 3, model="m")
    reg.inc("serve.tenant_shed", 1, model="m")
    reg.inc("serve.tenant_slo_violations", 2, model="m")
    for lat in (1.0, 2.0, 40.0):
        reg.observe("serve.latency_ms", lat, buckets=LATENCY_BUCKETS_MS,
                    model="m")
    monkeypatch.setenv("KEYSTONE_TELEMETRY_ROLE", "sig")
    export_process(str(tmp_path), registry=reg)

    for sig in (signals(reg.as_dict()),
                signals(merge_shards(str(tmp_path), prune=False))):
        assert set(sig) == {"schema", "scope", "serve", "tenants",
                            "memory", "ingest"}
        assert sig["schema"] == 1
        assert set(sig["serve"]) == _SERVE_KEYS
        assert sig["serve"]["requests"] == 4
        assert sig["serve"]["shed_frac"] == round(1 / 4, 4)
        assert sig["serve"]["breaker_trips"] == 1
        assert sig["serve"]["p99_ms"] is not None
        assert set(sig["tenants"]) == {"m"}
        assert set(sig["tenants"]["m"]) == _TENANT_KEYS
        assert sig["tenants"]["m"]["slo_violation_frac"] == 0.5
        assert set(sig["ingest"]) == _INGEST_KEYS
    assert signals(reg.as_dict())["scope"] == "fleet"  # explicit snapshot
    local = signals()
    assert local["scope"] == "process" and set(local["serve"]) == _SERVE_KEYS


def test_tenant_stats_and_signals_agree_on_slo_burn(monkeypatch):
    """ModelPool per-tenant SLO accounting: a shed burns SLO budget, and
    tenant_stats / the registry counters / signals() tell one story."""
    from keystone_tpu.serve.pool import pool

    telemetry_reset()
    g = pool(chain(Doubler()), item_spec=_spec(), name="t0",
             slo_ms=10_000.0, queue_depth=64)
    try:
        for _ in range(3):
            g.predict(_item())
        ts = g.tenant_stats("t0")
        assert ts["slo_violations"] == 0
        assert ts["slo_violation_frac"] == 0.0
        assert {"slo_violations", "slo_violation_frac"} <= set(ts)
        sig = signals()
        assert sig["tenants"]["t0"]["served"] == 3
        assert sig["tenants"]["t0"]["slo_violation_frac"] == 0.0
    finally:
        g.close()


def test_obs_cli_text_json_prometheus(tmp_path, monkeypatch, capsys):
    """``keystone-tpu obs``: rc=0 with a shard dir (rc=2 without), totals
    in every format equal the shard sums exactly."""
    reg = MetricsRegistry()
    reg.inc("serve.requests", 5, model="default")
    reg.observe("serve.latency_ms", 3.0, buckets=LATENCY_BUCKETS_MS,
                model="default")
    monkeypatch.setenv("KEYSTONE_TELEMETRY_ROLE", "cli-a")
    export_process(str(tmp_path), registry=reg)
    monkeypatch.setenv("KEYSTONE_TELEMETRY_ROLE", "cli-b")
    export_process(str(tmp_path), registry=reg)

    monkeypatch.delenv("KEYSTONE_TELEMETRY_DIR", raising=False)
    assert obs_main([]) == 2  # no dir anywhere
    assert obs_main([str(tmp_path / "nope")]) == 2

    assert obs_main([str(tmp_path), "--format", "json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["merged"]["counters"]["serve.requests{model=default}"] == 10
    assert len(out["procs"]) == 2
    assert out["signals"]["serve"]["requests"] == 10

    assert obs_main([str(tmp_path)]) == 0
    text = capsys.readouterr().out
    assert "2 merged" in text and "serve.requests{model=default}" in text

    assert obs_main([str(tmp_path), "--format", "prometheus"]) == 0
    prom = capsys.readouterr().out
    assert 'keystone_serve_requests{model="default"} 10' in prom
    assert "keystone_serve_latency_ms_bucket" in prom

    trace_out = tmp_path / "stitched.json"
    assert obs_main([str(tmp_path), "--traces", str(trace_out)]) == 0
    assert json.loads(trace_out.read_text())["traceEvents"] is not None
