"""Communication-pattern pins for the sharded streaming weighted solve.

SURVEY §2.13/§7: the multi-chip design is *psum over ICI* — per-block gram
and cross-term reductions lower to all-reduces, and neither the feature
block nor the raw descriptors are ever all-gathered (a silent all-gather of
a (n, 4096) block is the classic sharding regression: correct numerics,
cluster-killing traffic). These tests compile the actual solver step and
the grouped Fisher featurization under the 8-device mesh with row-sharded
inputs and assert the collective mix in the optimized HLO text — catching
regressions that the numeric mesh tests (``test_block_weighted.py``) cannot
see.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import keystone_tpu.learning.block_weighted as bw


def _collectives(hlo_text: str):
    return {
        "all-reduce": len(re.findall(r"all-reduce\(|all-reduce-start\(", hlo_text)),
        "all-gather": len(re.findall(r"all-gather\(|all-gather-start\(", hlo_text)),
        "all-to-all": len(re.findall(r"all-to-all\(", hlo_text)),
    }


@pytest.fixture()
def mesh():
    return Mesh(np.array(jax.devices()[:8]), ("data",))


def test_weighted_block_step_all_reduces_never_gathers(mesh, rng):
    """One full streaming-solver block step (pop stats + Woodbury-eligible
    bucketed class solves + residual update) with row-sharded X/R: the HLO
    must contain all-reduces (the psum-over-ICI reductions) and NO
    all-gather / all-to-all — X stays sharded end to end."""
    n, bs, C = 512, 64, 128  # nc = 4 exactly -> Woodbury (threshold bs//4=16)
    X = rng.normal(size=(n, bs)).astype(np.float32)
    lab = np.arange(n) % C  # balanced so every bucket stays under threshold
    rng.shuffle(lab)
    ind = -np.ones((n, C), np.float32)
    ind[np.arange(n), lab] = 1.0

    rows = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())

    labels = jnp.asarray(ind)
    class_idx, counts, valid = bw._prepare(labels, None, C)
    n_eff = jnp.sum(counts).astype(jnp.float32)
    R = (labels - 0.1) * valid[:, None]
    buckets, inv_perm = bw._class_buckets(
        np.asarray(counts), np.asarray(class_idx)
    )
    max_nc = int(np.asarray(counts).max())
    assert bw._use_woodbury(max_nc, bs), "test must exercise the Woodbury path"
    w, lam, prec = jnp.float32(0.25), jnp.float32(0.05), "high"
    model0 = jnp.zeros((bs, C), jnp.float32)
    _, residual_mean = bw._class_col_means(R, class_idx, counts)
    class_sums = bw._class_sums(jnp.asarray(X), class_idx, C)

    def step(Xb, R, valid, counts, inv_perm, residual_mean, model):
        pop_mean, pop_cov, pop_xtr = bw._pop_stats(
            Xb, R, valid, n_eff, precision=prec
        )
        base_inv = (
            bw._base_inverse(pop_cov, lam, w, prec)[0]
            if bw._needs_base_inverse(buckets, bs)
            else None
        )
        class_means = class_sums / jnp.maximum(
            counts[:, None].astype(jnp.float32), 1.0
        )
        joint_means_b = w * class_means + (1.0 - w) * pop_mean
        dW = bw._bucketed_class_solves(
            Xb, R, counts, pop_cov, pop_mean, pop_xtr, joint_means_b,
            residual_mean, model, lam, w, buckets, inv_perm, base_inv,
            precision=prec,
        )
        R2 = bw._apply_update(R, Xb, dW, valid, precision=prec)
        return dW, R2

    jitted = jax.jit(
        step,
        in_shardings=(rows, rows, rows, rep, rep, rep, rep),
        out_shardings=(rep, rows),
    )
    args = (
        jnp.asarray(X), R, valid, counts, inv_perm, residual_mean, model0,
    )
    txt = jitted.lower(*args).compile().as_text()
    cols = _collectives(txt)
    # per-block reductions ride all-reduce (psum) — XLA merges adjacent
    # reductions, so the count floor is deliberately loose (observed: 2 with
    # the Woodbury path, 8 with dense solves); the hard pin is gather==0
    assert cols["all-reduce"] >= 1, cols
    assert cols["all-gather"] == 0, (
        f"sharded solver step all-gathers (X or R replicated!): {cols}"
    )
    assert cols["all-to-all"] == 0, cols
    # and the numbers must still be right: sharded step == replicated step
    dW_sh, _ = jitted(*args)
    dW_ref, _ = jax.jit(step)(*args)
    np.testing.assert_allclose(
        np.asarray(dW_sh), np.asarray(dW_ref), atol=2e-4
    )


def test_grouped_fisher_block_featurization_never_gathers_descriptors(
    mesh, rng
):
    """The grouped FV block featurization (what fit_streaming calls per
    cache group) on row-sharded bf16 descriptors: per-row work only — the
    HLO must contain no collective at all (descriptors never leave their
    shard; the only cross-shard traffic of the streaming fit is the solver's
    all-reduces, pinned above)."""
    from keystone_tpu.learning.gmm import GaussianMixtureModelEstimator
    from keystone_tpu.ops.images.fisher_vector import (
        fisher_l1_norms,
        make_fisher_block_nodes,
    )

    k, d, n = 4, 16, 256
    gmm = GaussianMixtureModelEstimator(k=k, num_iter=5).fit(
        jnp.asarray(rng.normal(size=(200, d)).astype(np.float32))
    )
    bs = 2 * d  # 2k*d = 128 branch width -> 4 blocks of 32
    nodes = make_fisher_block_nodes(gmm, block_size=bs, cache_blocks=2)
    descs = jnp.asarray(rng.normal(size=(n, 6, d)), jnp.bfloat16)
    l1 = fisher_l1_norms(descs.astype(jnp.float32), gmm, chunk=64)
    rows = NamedSharding(mesh, P("data"))

    node = nodes[0]
    assert node.cache_group is not None  # grouping active
    gnode = node.group_node()

    def featurize(descs, l1):
        return gnode({"descs": descs, "l1": l1})

    jitted = jax.jit(featurize, in_shardings=(rows, rows), out_shardings=rows)
    txt = jitted.lower(descs, l1).compile().as_text()
    cols = _collectives(txt)
    assert cols["all-gather"] == 0 and cols["all-to-all"] == 0, cols
