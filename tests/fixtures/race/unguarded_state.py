"""Bad fixture: T3 unguarded shared state.

The module-level lock marks this module as concurrent; ``publish``
mutates the module-level container WITHOUT taking it.  Scanned by
tests/test_race.py and scripts/race_smoke.py — never imported.
"""

import threading

state_lock = threading.Lock()
RESULTS = []


def publish(value):
    RESULTS.append(value)


def read_all():
    with state_lock:
        return list(RESULTS)
