"""Bad fixture: T1 lock-order inversion.

``forward`` nests a_lock -> b_lock; ``backward`` nests b_lock -> a_lock.
Two threads interleaving these deadlock.  Scanned by tests/test_race.py
and scripts/race_smoke.py — never imported, never executed.
"""

import threading

a_lock = threading.Lock()
b_lock = threading.Lock()


def forward():
    with a_lock:
        with b_lock:
            return True


def backward():
    with b_lock:
        with a_lock:
            return True
