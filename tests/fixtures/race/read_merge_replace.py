"""Bad fixture: T5 unlocked read-merge-replace.

``bump_counter`` reads persisted JSON, merges, and ``os.replace``s it
back with no ``fcntl.flock`` sidecar window — two processes
interleaving lose one writer's increment.  Scanned by
tests/test_race.py and scripts/race_smoke.py — never imported.
"""

import json
import os


def bump_counter(path):
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        data = {}
    data["n"] = int(data.get("n", 0)) + 1
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f)
    os.replace(tmp, path)
    return data["n"]
