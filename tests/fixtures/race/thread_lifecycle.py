"""Bad fixture: T4 thread lifecycle — both shapes.

``launch`` spawns an OS process while holding a lock (the child
inherits the locked mutex state), and starts a non-daemon thread it
never joins (interpreter shutdown blocks on it).  Scanned by
tests/test_race.py and scripts/race_smoke.py — never imported.
"""

import subprocess
import threading

spawn_lock = threading.Lock()


def launch():
    t = threading.Thread(target=print)
    t.start()
    with spawn_lock:
        subprocess.run(["true"])
    return t
