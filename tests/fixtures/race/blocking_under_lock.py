"""Bad fixture: T2 blocking call while holding a lock.

``queue.Queue.get()`` with no timeout inside the ``with work_lock:``
span — the PR-15 ``_claim_slot`` deadlock class.  Scanned by
tests/test_race.py and scripts/race_smoke.py — never imported.
"""

import queue
import threading

work_lock = threading.Lock()
work_q: "queue.Queue" = queue.Queue()


def drain_one():
    with work_lock:
        item = work_q.get()
        return item
