"""LDA tests (reference: nodes/learning/LinearDiscriminantAnalysisSuite -
iris-style class separation)."""

import numpy as np

from keystone_tpu.learning import LinearDiscriminantAnalysis


def _synthetic_classes(rng, n_per=60, d=4):
    means = np.array(
        [[0, 0, 0, 0], [4, 1, 0, 0], [0, 3, 3, 0]], dtype=np.float64
    )
    xs, ys = [], []
    for c, mu in enumerate(means):
        xs.append(rng.normal(size=(n_per, d)) * 0.7 + mu)
        ys.append(np.full(n_per, c))
    return np.concatenate(xs).astype(np.float32), np.concatenate(ys).astype(np.int32)


def test_lda_matches_generalized_eig(rng):
    x, y = _synthetic_classes(rng)
    mapper = LinearDiscriminantAnalysis(num_dims=2).fit(x, y)
    w = np.asarray(mapper.w, np.float64)  # (d, 2)

    # independent numpy solution of eig(inv(Sw) Sb)
    d = x.shape[1]
    sw = np.zeros((d, d))
    sb = np.zeros((d, d))
    gm = x.mean(0)
    for c in range(3):
        xc = x[y == c].astype(np.float64)
        mu = xc.mean(0)
        sw += (xc - mu).T @ (xc - mu)
        sb += len(xc) * np.outer(mu - gm, mu - gm)
    evals, evecs = np.linalg.eig(np.linalg.solve(sw, sb))
    order = np.argsort(-evals.real)
    ref = evecs[:, order[:2]].real

    # same 2-d subspace: principal angles ~ 0
    qa, _ = np.linalg.qr(w)
    qb, _ = np.linalg.qr(ref)
    sv = np.linalg.svd(qa.T @ qb, compute_uv=False)
    np.testing.assert_allclose(sv, 1.0, atol=1e-3)


def test_lda_projection_separates_classes(rng):
    x, y = _synthetic_classes(rng)
    mapper = LinearDiscriminantAnalysis(num_dims=2).fit(x, y)
    z = np.asarray(mapper(x))
    # between-class variance dominates within-class variance after projection
    gm = z.mean(0)
    within = sum(((z[y == c] - z[y == c].mean(0)) ** 2).sum() for c in range(3))
    between = sum(len(z[y == c]) * ((z[y == c].mean(0) - gm) ** 2).sum() for c in range(3))
    assert between / within > 3.0


def test_lda_respects_mask(rng):
    x, y = _synthetic_classes(rng)
    # poison rows, then mask them out: result must match the clean fit
    x_aug = np.concatenate([x, rng.normal(size=(20, 4)).astype(np.float32) * 50])
    y_aug = np.concatenate([y, np.zeros(20, np.int32)])
    mask = np.concatenate([np.ones(len(x)), np.zeros(20)]).astype(np.float32)
    clean = np.asarray(LinearDiscriminantAnalysis(2).fit(x, y).w)
    masked = np.asarray(LinearDiscriminantAnalysis(2).fit(x_aug, y_aug, mask=mask).w)
    qa, _ = np.linalg.qr(clean.astype(np.float64))
    qb, _ = np.linalg.qr(masked.astype(np.float64))
    sv = np.linalg.svd(qa.T @ qb, compute_uv=False)
    np.testing.assert_allclose(sv, 1.0, atol=1e-3)
