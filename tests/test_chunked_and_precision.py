"""Tests for ChunkedMap row-chunked execution, the solver MXU precision
knob, and the on-device synthetic generators / samplers.

These are the memory- and link-bandwidth features of the data plane: the
reference got partition streaming and driver-side sampling from Spark for
free (SURVEY.md §2.12-2.13); here they are explicit nodes and their
semantics (equivalence with unchunked execution, determinism, masking)
must hold exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.core.pipeline import Chain, ChunkedMap, Transformer, chain
from keystone_tpu.linalg import (
    block_coordinate_descent_l2,
    get_solver_precision,
    set_solver_precision,
)
from keystone_tpu.ops.stats import ColumnSampler, Sampler
from keystone_tpu.parallel import distribute, make_mesh, use_mesh


class _Square(Transformer):
    def apply(self, x):
        return x * x


class _RowSum(Transformer):
    def apply(self, x):
        return jnp.sum(x, keepdims=True)

    def apply_batch(self, xs):
        return jnp.sum(xs, axis=1, keepdims=True)


def test_chunked_map_equals_unchunked():
    xs = jnp.arange(48.0).reshape(12, 4)
    node = chain(_Square(), _RowSum())
    expected = node(xs)
    for c in (1, 2, 3, 4, 6, 12):
        out = ChunkedMap(node=node, num_chunks=c)(xs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)


def test_chunked_map_non_divisible_rows():
    xs = jnp.arange(47.0)[:, None]
    out = ChunkedMap(node=_Square(), num_chunks=5)(xs)
    assert out.shape == (47, 1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(xs) ** 2)


def test_chunked_map_more_chunks_than_rows():
    xs = jnp.arange(3.0)[:, None]
    out = ChunkedMap(node=_Square(), num_chunks=8)(xs)
    assert out.shape == (3, 1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(xs) ** 2)


def test_chunked_map_serve_path():
    one = ChunkedMap(node=_Square(), num_chunks=4).serve(jnp.float32(3.0))
    assert float(one) == 9.0


def test_chunked_map_keeps_row_sharding(devices):
    xs = np.arange(64.0, dtype=np.float32).reshape(16, 4)
    with use_mesh(make_mesh()):
        ds = distribute(xs)
        out = ChunkedMap(node=_Square(), num_chunks=4)(ds)
        assert out.data.sharding.spec[0] == "data"  # rows stay sharded
        np.testing.assert_allclose(np.asarray(out.data), xs * xs, rtol=1e-6)


def test_chunked_map_preserved_under_chain_composition():
    node = ChunkedMap(node=_Square(), num_chunks=2) >> _RowSum()
    assert isinstance(node, Chain)
    xs = jnp.ones((6, 3))
    np.testing.assert_allclose(np.asarray(node(xs)), 3.0 * np.ones((6, 1)))


# -- solver precision knob --------------------------------------------------


def test_precision_knob_roundtrip():
    assert get_solver_precision() == "high"  # documented default
    try:
        for p in ("default", "highest", "high"):
            set_solver_precision(p)
            assert get_solver_precision() == p
    finally:
        set_solver_precision("high")


def test_precision_knob_rejects_unknown():
    with pytest.raises(ValueError, match="precision"):
        set_solver_precision("bf16")


def test_bcd_precision_arg_validated():
    A = jnp.ones((16, 4))
    b = jnp.ones((16, 2))
    with pytest.raises(ValueError, match="precision"):
        block_coordinate_descent_l2(A, b, 1.0, 4, precision="hi")


def test_bcd_same_result_across_precisions_on_cpu():
    # On CPU all precision levels are true f32, so results must agree
    # exactly; this pins the static-arg threading (each precision value is a
    # separate compile, same math).
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.normal(size=(64, 12)).astype(np.float32))
    Wt = rng.normal(size=(12, 3)).astype(np.float32)
    b = A @ jnp.asarray(Wt)
    sols = [
        np.asarray(
            block_coordinate_descent_l2(A, b, 1e-8, 4, num_iter=6, precision=p)
        )
        for p in ("default", "high", "highest")
    ]
    np.testing.assert_allclose(sols[0], sols[1], atol=1e-6)
    np.testing.assert_allclose(sols[1], sols[2], atol=1e-6)
    np.testing.assert_allclose(sols[2], Wt, atol=5e-3)


# -- device samplers / generators -------------------------------------------


def test_sampler_device_path_deterministic_no_replacement():
    xs = jnp.arange(500.0)[:, None] * jnp.ones((1, 2))
    a = Sampler(size=64, seed=9).apply_batch(xs)
    b = Sampler(size=64, seed=9).apply_batch(xs)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert len(np.unique(np.asarray(a)[:, 0])) == 64


def test_sampler_caps_at_population():
    xs = jnp.arange(10.0)[:, None]
    out = Sampler(size=100, seed=1).apply_batch(xs)
    assert out.shape == (10, 1)


def test_column_sampler_device_shape():
    descs = jax.random.normal(jax.random.key(0), (6, 40, 8))
    out = ColumnSampler(100, seed=2).apply_batch(descs)
    assert out.shape == (100, 8)


def test_synthetic_device_generators_match_host_structure():
    from keystone_tpu.loaders.cifar import synthetic_cifar_device
    from keystone_tpu.loaders.imagenet import synthetic_imagenet_device
    from keystone_tpu.loaders.timit import TIMIT_DIMENSION, synthetic_timit_device
    from keystone_tpu.loaders.voc import synthetic_voc_device

    imgs, y = synthetic_cifar_device(20, seed=1)
    assert imgs.shape == (20, 32, 32, 3) and float(imgs.min()) >= 0.0
    assert float(imgs.max()) <= 255.0 and int(np.asarray(y).max()) < 10

    x, y = synthetic_timit_device(30, seed=2)
    assert x.shape == (30, TIMIT_DIMENSION) and int(np.asarray(y).max()) < 147

    imgs, y = synthetic_imagenet_device(10, 4, (32, 32))
    assert imgs.shape == (10, 32, 32, 3) and int(np.asarray(y).max()) < 4

    imgs, labels = synthetic_voc_device(25, 20, (32, 32), max_labels=3, seed=3)
    labels = np.asarray(labels)
    assert imgs.shape == (25, 32, 32, 3) and labels.shape == (25, 3)
    counts = (labels >= 0).sum(axis=1)
    assert counts.min() >= 1 and counts.max() <= 3
    for row in labels:
        v = row[row >= 0]
        assert sorted(set(v.tolist())) == sorted(v.tolist())  # distinct, sorted

    # train/test splits with different seeds share class structure
    a, _ = synthetic_cifar_device(4, seed=1)
    b, _ = synthetic_cifar_device(4, seed=2)
    assert not np.allclose(np.asarray(a), np.asarray(b))
