"""Intermediate cache (core/cache.py) + prefetch (core/prefetch.py) tests:
fingerprint semantics, tier mechanics (hit/miss/demotion/eviction/disk
round-trip), chain-level memoization with zero-recompute proof, golden
bit-identical cached-vs-uncached pipelines, and prefetch ordering/gating.
"""

import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import flax.struct as struct

from keystone_tpu.core.cache import (
    IntermediateCache,
    cache_from_env,
    fingerprint,
    get_cache,
    set_cache,
    stage_key,
    use_cache,
)
from keystone_tpu.core.pipeline import Cacher, Transformer, chain
from keystone_tpu.core.prefetch import prefetch_map


@pytest.fixture(autouse=True)
def _no_ambient_cache():
    """Tests own the active cache; nothing may leak between them."""
    prev = set_cache(None)
    yield
    set_cache(prev)


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------


class ScaleNode(Transformer):
    w: jax.Array

    def apply_batch(self, xs):
        return xs * self.w

    apply = apply_batch


class _CountingFeaturizer(Transformer):
    """Eager (non-jittable) featurizer that counts its bulk invocations —
    the recompute counter hook for the zero-recompute pipeline tests."""

    scale: float = struct.field(pytree_node=False, default=2.0)

    jittable = False
    calls = []  # class-level (unannotated: not a dataclass field)

    def apply_batch(self, xs):
        _CountingFeaturizer.calls.append(1)
        return xs * self.scale

    apply = apply_batch


def test_fingerprint_identical_content_matches():
    a = jnp.arange(12.0).reshape(3, 4)
    b = jnp.arange(12.0).reshape(3, 4)
    assert fingerprint(a) == fingerprint(b)
    assert fingerprint({"x": a, "y": 1}) == fingerprint({"x": b, "y": 1})


def test_fingerprint_content_and_structure_sensitivity():
    a = jnp.arange(12.0).reshape(3, 4)
    assert fingerprint(a) != fingerprint(a + 1)  # content
    assert fingerprint(a) != fingerprint(a.reshape(4, 3))  # shape
    assert fingerprint(a) != fingerprint(a.astype(jnp.bfloat16))  # dtype
    assert fingerprint([a]) != fingerprint((a,))  # treedef


def test_fingerprint_refit_same_treedef_new_leaves_is_miss():
    """A re-fitted node keeps its structure but changes its leaves — the
    content key MUST change (stale reuse would be silent corruption)."""
    n1 = ScaleNode(w=jnp.float32(2.0))
    n2 = ScaleNode(w=jnp.float32(3.0))  # same treedef, new leaves
    assert fingerprint(n1) != fingerprint(n2)
    x_fp = fingerprint(jnp.ones((4,)))
    assert stage_key((n1,), x_fp) != stage_key((n2,), x_fp)
    # identical refit -> identical key (bitwise reuse is safe)
    assert stage_key((n1,), x_fp) == stage_key(
        (ScaleNode(w=jnp.float32(2.0)),), x_fp
    )


def test_fingerprintable_refuses_opaque_callables():
    """Two distinct closures repr identically once addresses strip. A node
    carrying a static callable field (memoizable left True — the Pooler /
    TermFrequency shape, NOT a LambdaTransformer) must be refused by the
    memoization gate, or the second node would be served the first's cached
    output."""
    from keystone_tpu.core.cache import fingerprint, fingerprintable

    class ThresholdNode(Transformer):
        fn: object = struct.field(pytree_node=False, default=None)

        def apply_batch(self, xs):
            return self.fn(xs)

        apply = apply_batch

    def make(t):
        return ThresholdNode(fn=lambda x: (x > t).astype(jnp.float32))

    a, b = make(0.0), make(99.0)
    # the hazard this guard exists for: different closures, same fingerprint
    assert fingerprint(a) == fingerprint(b)
    assert not fingerprintable(a)
    assert fingerprintable(ScaleNode(w=jnp.ones(3)))
    x = jnp.ones((4, 4))
    with use_cache(IntermediateCache()) as c:
        ra = a(x)
        rb = b(x)  # must NOT be served a's cached output
        assert c.stats.puts == 0  # nothing memoized through opaque nodes
        np.testing.assert_array_equal(np.asarray(ra), np.ones((4, 4)))
        np.testing.assert_array_equal(np.asarray(rb), np.zeros((4, 4)))


def test_fingerprint_large_array_uses_device_checksum():
    """Arrays past the host-hash bound still fingerprint by content."""
    from keystone_tpu.core import cache as cache_mod

    big = jnp.ones((cache_mod._HOST_HASH_MAX_BYTES // 4 + 16,), jnp.float32)
    assert fingerprint(big) == fingerprint(big + 0.0)
    bumped = big.at[17].set(2.0)
    assert fingerprint(big) != fingerprint(bumped)


# ---------------------------------------------------------------------------
# tier mechanics
# ---------------------------------------------------------------------------


def test_memoize_hit_miss_and_bit_identical_values():
    cache = IntermediateCache()
    x = jnp.arange(8.0)
    calls = []

    def compute():
        calls.append(1)
        return jnp.sin(x)

    v1 = cache.memoize("k1", compute)
    v2 = cache.memoize("k1", compute)
    assert len(calls) == 1
    assert cache.stats.hits == 1 and cache.stats.misses == 1
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))

    cache.memoize("k2", compute)
    assert len(calls) == 2  # different key -> recompute


def test_demotion_to_host_and_promotion_back():
    """Over-budget device tier demotes the lowest recompute-density entry
    to host numpy; a later hit promotes it back to device."""
    cache = IntermediateCache(device_bytes=1 << 12, host_bytes=1 << 20)
    a = jnp.ones((256,), jnp.float32)  # 1 KiB
    b = jnp.ones((512,), jnp.float32)  # 2 KiB
    c = jnp.ones((768,), jnp.float32)  # 3 KiB
    cache.put("a", a, cost_s=10.0)  # high density: stays on device
    cache.put("b", b, cost_s=0.001)  # low density: first demotion victim
    cache.put("c", c, cost_s=5.0)
    assert cache.stats.demotions >= 1
    tiers = {e.key: e.tier for e in cache._entries.values()}
    assert tiers["b"] == "host"
    # host-tier value is exact, and the hit promotes it deviceward
    hit, vb = cache.lookup("b")
    assert hit
    np.testing.assert_array_equal(np.asarray(vb), np.asarray(b))
    assert isinstance(vb, jax.Array)
    assert cache.stats.promotions == 1
    assert cache.stats.host_hits == 1


def test_eviction_when_no_lower_tier():
    """host-budget 0 and no disk dir: device overflow evicts outright."""
    cache = IntermediateCache(device_bytes=1 << 11, host_bytes=0)
    for i in range(8):
        cache.put(f"k{i}", jnp.ones((256,), jnp.float32), cost_s=float(i))
    assert cache.stats.evictions >= 1
    total = sum(e.nbytes for e in cache._entries.values())
    assert total <= 1 << 11


def test_disk_tier_round_trip(tmp_path):
    """Demotion through host to disk, then a disk hit restores the exact
    value and promotes; clear() removes the files."""
    d = str(tmp_path / "kcache")
    cache = IntermediateCache(
        device_bytes=1 << 10, host_bytes=0, disk_bytes=1 << 20, cache_dir=d
    )
    val = {"w": jnp.arange(512.0), "meta": jnp.int32(7)}
    cache.put("deep", val, cost_s=3.0)
    # force overflow so "deep" demotes to disk
    cache.put("hot", jnp.ones((200,), jnp.float32), cost_s=100.0)
    cache.put("hot2", jnp.ones((200,), jnp.float32), cost_s=90.0)
    tiers = {e.key: e.tier for e in cache._entries.values()}
    assert "disk" in tiers.values(), tiers
    disk_key = next(k for k, t in tiers.items() if t == "disk")
    files = os.listdir(d)
    assert any(f.startswith(disk_key) for f in files)
    hit, got = cache.lookup(disk_key)
    assert hit and cache.stats.disk_hits == 1
    if disk_key == "deep":
        np.testing.assert_array_equal(np.asarray(got["w"]),
                                      np.arange(512.0, dtype=np.float32))
    cache.clear()
    assert not [f for f in os.listdir(d) if f.endswith(".kcache")]


def test_disk_tier_cross_process_adoption(tmp_path):
    """A fresh cache over an existing cache_dir serves the files written by
    a previous cache (process) — lazy metadata adoption."""
    d = str(tmp_path / "kcache")
    c1 = IntermediateCache(
        device_bytes=1 << 8, host_bytes=0, disk_bytes=1 << 20, cache_dir=d
    )
    c1.put("x", jnp.arange(256.0), cost_s=1.0)
    c1.put("y", jnp.arange(256.0) * 2, cost_s=2.0)  # overflows device -> disk
    assert any(f.endswith(".kcache") for f in os.listdir(d))
    disk_keys = [e.key for e in c1._entries.values() if e.tier == "disk"]

    c2 = IntermediateCache(
        device_bytes=1 << 20, host_bytes=1 << 20, disk_bytes=1 << 20,
        cache_dir=d,
    )
    for k in disk_keys:
        hit, v = c2.lookup(k)
        assert hit, f"adopted disk entry {k} missed"


def test_put_same_key_replaces():
    cache = IntermediateCache()
    cache.put("k", jnp.ones((4,)), cost_s=1.0)
    cache.put("k", jnp.zeros((4,)), cost_s=1.0)
    hit, v = cache.lookup("k")
    assert hit
    np.testing.assert_array_equal(np.asarray(v), np.zeros(4, np.float32))
    assert len(cache._entries) == 1


def test_cache_from_env(monkeypatch, tmp_path):
    monkeypatch.delenv("KEYSTONE_CACHE", raising=False)
    assert cache_from_env() is None
    monkeypatch.setenv("KEYSTONE_CACHE", "1")
    monkeypatch.setenv("KEYSTONE_CACHE_DEVICE_MB", "1")
    monkeypatch.setenv("KEYSTONE_CACHE_DIR", str(tmp_path / "c"))
    c = cache_from_env()
    assert c is not None
    assert c.budgets["device"] == 1 << 20
    assert c.cache_dir == str(tmp_path / "c")


def test_env_cache_survives_suppression_scope(monkeypatch):
    """A transient ``use_cache(None)`` scope (pipelines suppress the cache
    around self-managed buffers) must not disable the KEYSTONE_CACHE=1
    env-configured cache for the rest of the process."""
    import keystone_tpu.core.cache as cache_mod

    monkeypatch.setenv("KEYSTONE_CACHE", "1")
    monkeypatch.setattr(
        cache_mod, "_override",
        cache_mod.contextvars.ContextVar("t", default=cache_mod._UNSET),
    )
    monkeypatch.setattr(cache_mod, "_env_cache", None)
    monkeypatch.setattr(cache_mod, "_env_checked", False)
    # the suppression scope is the FIRST cache-API touch (the streaming
    # pipelines hit exactly this ordering)
    with use_cache(None):
        assert get_cache() is None
    env_cache = get_cache()
    assert isinstance(env_cache, IntermediateCache)
    assert get_cache() is env_cache  # resolved once, stable thereafter
    with use_cache(None):
        assert get_cache() is None
    assert get_cache() is env_cache


def test_thread_safety_under_concurrent_memoize():
    cache = IntermediateCache(device_bytes=1 << 16, host_bytes=1 << 20)
    errs = []

    def worker(tid):
        try:
            for i in range(30):
                k = f"k{(tid + i) % 10}"
                v = cache.memoize(k, lambda: jnp.full((64,), float(tid)))
                assert v.shape == (64,)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs


# ---------------------------------------------------------------------------
# pipeline-level memoization
# ---------------------------------------------------------------------------


def test_second_apply_batch_zero_featurization_recomputes():
    """THE KeystoneML ``.cache()`` contract: a second bulk apply over
    identical features re-runs NO featurization (counter hook on an eager
    featurizer node)."""
    _CountingFeaturizer.calls = []
    p = chain(_CountingFeaturizer(), Cacher(), ScaleNode(w=jnp.float32(3.0)))
    x = jnp.arange(16.0).reshape(4, 4)
    with use_cache(IntermediateCache()):
        out1 = p(x)
        n_after_first = len(_CountingFeaturizer.calls)
        out2 = p(x)
        assert len(_CountingFeaturizer.calls) == n_after_first, (
            "second apply_batch re-featurized"
        )
        assert n_after_first == 1
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_cacher_prefix_reused_across_chain_suffixes():
    """Fit-time featurization through ``f >> Cacher()`` must be a prefix
    hit when the same features flow through a LONGER fitted chain — the
    cross-chain reuse stage_key guarantees."""
    _CountingFeaturizer.calls = []
    feat = _CountingFeaturizer()
    x = jnp.arange(16.0).reshape(4, 4)
    with use_cache(IntermediateCache()):
        descs = chain(feat, Cacher())(x)  # "fit-time" featurization
        assert len(_CountingFeaturizer.calls) == 1
        fitted = chain(feat, Cacher(), ScaleNode(w=jnp.float32(2.0)))
        out = fitted(x)  # prefix hit -> only the scale stage runs
        assert len(_CountingFeaturizer.calls) == 1
    np.testing.assert_array_equal(np.asarray(out), np.asarray(descs) * 2.0)


def test_refit_chain_is_cache_miss_not_stale_hit():
    """Same chain structure with a re-fitted (different-leaves) stage must
    recompute — and produce the re-fitted answer, not the stale one."""
    _CountingFeaturizer.calls = []
    x = jnp.arange(8.0).reshape(2, 4)
    with use_cache(IntermediateCache()):
        p2 = chain(_CountingFeaturizer(), Cacher(), ScaleNode(w=jnp.float32(2.0)))
        p3 = chain(_CountingFeaturizer(), Cacher(), ScaleNode(w=jnp.float32(3.0)))
        out2 = p2(x)
        out3 = p3(x)
    np.testing.assert_array_equal(np.asarray(out3), np.asarray(out2) * 1.5)
    # the shared featurizer prefix hit; only the scale suffix recomputed
    assert len(_CountingFeaturizer.calls) == 1


def test_cached_pipeline_bit_identical_to_uncached():
    """Golden comparison: cached run == uncached run, bit for bit, and a
    second cached run returns the stored bits."""
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(6, 8)).astype(np.float32)
    )
    w = jnp.asarray(
        np.random.default_rng(1).normal(size=(8,)).astype(np.float32)
    )
    p = chain(ScaleNode(w=w), Cacher(), ScaleNode(w=w * 0.5))
    baseline = np.asarray(p(x))  # no cache active
    with use_cache(IntermediateCache()) as cache:
        first = np.asarray(p(x))
        second = np.asarray(p(x))
        assert cache.stats.hits >= 1
    assert baseline.tobytes() == first.tobytes()
    assert baseline.tobytes() == second.tobytes()


def test_lambda_transformer_never_memoized():
    """Closure state is invisible to content fingerprinting: two from_fn
    nodes built from the SAME source location with different captured
    values would collide on an address-stripped fingerprint — so they must
    bypass the cache entirely."""

    def make(k):
        return Transformer.from_fn(lambda x: x * k, name="closure")

    n2, n3 = make(2.0), make(3.0)
    assert not n2.memoizable
    x = jnp.arange(4.0)
    with use_cache(IntermediateCache()) as cache:
        out2 = np.asarray(n2(x))
        out3 = np.asarray(n3(x))
        assert cache.stats.puts == 0  # nothing stored, nothing to collide
        # chains containing one inherit the bypass
        assert not chain(ScaleNode(w=jnp.float32(1.0)), n2).memoizable
    np.testing.assert_array_equal(out3, out2 * 1.5)


def test_cache_bypassed_inside_jit_traces():
    """Tracers must never be fingerprinted or stored."""
    n = ScaleNode(w=jnp.float32(2.0))
    with use_cache(IntermediateCache()) as cache:
        out = jax.jit(lambda v: n(v) + 1.0)(jnp.arange(4.0))
        assert cache.stats.puts == 0
    np.testing.assert_array_equal(np.asarray(out),
                                  np.arange(4.0, dtype=np.float32) * 2 + 1)


def test_streaming_predict_memoized_zero_refeaturize():
    """Warm out-of-core predict returns stored scores without touching the
    feature nodes (the flagship eval.predict elimination)."""
    from keystone_tpu.learning.block_linear import (
        BlockLinearMapper,
        streaming_predict,
    )

    _CountingFeaturizer.calls = []
    nodes = [_CountingFeaturizer(scale=1.0), _CountingFeaturizer(scale=2.0)]
    raw = jnp.asarray(
        np.random.default_rng(2).normal(size=(5, 4)).astype(np.float32)
    )
    model = BlockLinearMapper(
        w=jnp.asarray(
            np.random.default_rng(3).normal(size=(8, 3)).astype(np.float32)
        ),
        b=None, feature_means=None, block_size=4,
    )
    cold = np.asarray(streaming_predict(model, nodes, raw))  # uncached
    calls_uncached = len(_CountingFeaturizer.calls)
    with use_cache(IntermediateCache()):
        first = np.asarray(streaming_predict(model, nodes, raw))
        calls_after_first = len(_CountingFeaturizer.calls)
        warm = np.asarray(streaming_predict(model, nodes, raw))
        assert len(_CountingFeaturizer.calls) == calls_after_first, (
            "warm streaming_predict re-featurized"
        )
    assert cold.tobytes() == first.tobytes() == warm.tobytes()
    assert calls_uncached == 2  # sanity: both nodes actually run per predict


# ---------------------------------------------------------------------------
# prefetch
# ---------------------------------------------------------------------------


def test_prefetch_map_order_and_results():
    items = list(range(20))
    out = list(prefetch_map(lambda i: i * i, items, depth=3))
    assert out == [i * i for i in items]


def test_prefetch_map_runs_producer_single_threaded_in_order():
    seen = []

    def produce(i):
        seen.append(i)
        return i

    assert list(prefetch_map(produce, range(10), depth=4)) == list(range(10))
    assert seen == list(range(10))


def test_prefetch_map_gate_blocks_lookahead():
    """gate(prev, nxt) False defers the next group's production until the
    boundary item has been YIELDED (the two-group-buffers guard)."""
    produced = []
    yielded = []
    items = [("a", 0), ("a", 1), ("b", 2), ("b", 3)]

    def produce(it):
        produced.append(it)
        return it

    gen = prefetch_map(
        produce, items, depth=2, gate=lambda p, n: p[0] == n[0]
    )
    first = next(gen)
    yielded.append(first)
    # group b must not have been produced while only ("a", ...) was yielded
    assert all(g == "a" for g, _ in produced)
    assert [x for x in gen] == items[1:]


def test_prefetch_map_depth_zero_is_sequential():
    calls = []
    out = list(prefetch_map(lambda i: calls.append(i) or i, range(5), depth=0))
    assert out == list(range(5)) and calls == list(range(5))


def test_prefetch_map_exception_surfaces_at_right_item():
    def produce(i):
        if i == 3:
            raise ValueError("boom")
        return i

    gen = prefetch_map(produce, range(6), depth=2)
    got = []
    with pytest.raises(ValueError, match="boom"):
        for v in gen:
            got.append(v)
    assert got == [0, 1, 2]


def test_prefetch_env_kill_switch(monkeypatch):
    from keystone_tpu.core.prefetch import prefetch_depth

    monkeypatch.setenv("KEYSTONE_PREFETCH", "0")
    assert prefetch_depth() == 0
    monkeypatch.setenv("KEYSTONE_PREFETCH", "4")
    assert prefetch_depth() == 4
    monkeypatch.setenv("KEYSTONE_PREFETCH", "junk")
    assert prefetch_depth(2) == 2


def test_weighted_fit_prefetch_on_off_bit_identical(monkeypatch):
    """The solver's double-buffered block feed must be a pure overlap: the
    fitted model with KEYSTONE_PREFETCH=2 equals =0 bitwise."""
    from keystone_tpu.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )
    from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels

    rng = np.random.default_rng(4)
    X = jnp.asarray(rng.normal(size=(40, 12)).astype(np.float32))
    y = ClassLabelIndicatorsFromIntLabels(3)(
        jnp.asarray(rng.integers(0, 3, 40))
    )

    def fit():
        return BlockWeightedLeastSquaresEstimator(4, 2, 0.1, 0.25).fit(X, y)

    monkeypatch.setenv("KEYSTONE_PREFETCH", "2")
    m_on = fit()
    monkeypatch.setenv("KEYSTONE_PREFETCH", "0")
    m_off = fit()
    assert np.asarray(m_on.w).tobytes() == np.asarray(m_off.w).tobytes()
    assert np.asarray(m_on.b).tobytes() == np.asarray(m_off.b).tobytes()
