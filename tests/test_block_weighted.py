"""BlockWeightedLeastSquares tests, mirroring the reference suite's
independently-recomputed-solution checks
(BlockWeightedLeastSquaresSuite.scala:18-97)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.core.dataset import pad_rows
from keystone_tpu.learning import BlockLeastSquaresEstimator
from keystone_tpu.learning.block_weighted import BlockWeightedLeastSquaresEstimator
from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels


def _toy(rng, n=120, d=10, c=3, balanced=True):
    if balanced:
        labels = np.repeat(np.arange(c), n // c).astype(np.int32)
    else:
        labels = rng.choice(c, size=n, p=[0.6, 0.3, 0.1]).astype(np.int32)
    protos = rng.normal(size=(c, d)).astype(np.float32)
    x = protos[labels] + 0.5 * rng.normal(size=(n, d)).astype(np.float32)
    rng.shuffle(labels)  # decouple row order from class order
    x = protos[labels] + 0.5 * rng.normal(size=(n, d)).astype(np.float32)
    ind = np.asarray(ClassLabelIndicatorsFromIntLabels(c)(jnp.asarray(labels)))
    return x, labels, ind


def _weighted_oracle_single_block(x, ind, lam, w):
    """Numpy recomputation of the single-block, single-pass solution from the
    mixture-of-empiricals definitions (weighted distribution D_c =
    (1-w)·All + w·Class_c per class column)."""
    n, d = x.shape
    c = ind.shape[1]
    labels = ind.argmax(1)
    counts = np.bincount(labels, minlength=c)
    jlm = 2 * w + 2 * (1 - w) * counts / n - 1
    R = ind - jlm
    mu = x.mean(0)
    pop_cov = x.T @ x / n - np.outer(mu, mu)
    pop_xtr = x.T @ R / n
    class_means = np.stack([x[labels == k].mean(0) for k in range(c)])
    res_class_means = np.stack([R[labels == k].mean(0) for k in range(c)])
    residual_mean = res_class_means.mean(0)
    W = np.zeros((d, c))
    for k in range(c):
        xc = x[labels == k]
        mc = class_means[k]
        cc = (xc - mc).T @ (xc - mc) / counts[k]
        cxtr = xc.T @ R[labels == k, k] / counts[k]
        md = mc - mu
        jxtx = (1 - w) * pop_cov + w * cc + (1 - w) * w * np.outer(md, md)
        jm = w * mc + (1 - w) * mu
        mmw = (1 - w) * residual_mean[k] + w * R[labels == k, k].mean()
        jxtr = (1 - w) * pop_xtr[:, k] + w * cxtr - jm * mmw
        W[:, k] = np.linalg.solve(jxtx + lam * np.eye(d), jxtr)
    joint_means = w * class_means + (1 - w) * mu
    b = jlm - np.einsum("cd,dc->c", joint_means, W)
    return W, b


def test_weighted_single_block_matches_numpy_oracle(rng):
    x, labels, ind = _toy(rng, balanced=False)
    lam, w = 0.5, 0.25
    est = BlockWeightedLeastSquaresEstimator(
        block_size=x.shape[1], num_iter=1, lam=lam, mixture_weight=w
    )
    model = est.fit(jnp.asarray(x), jnp.asarray(ind))
    W_exp, b_exp = _weighted_oracle_single_block(x.astype(np.float64), ind, lam, w)
    np.testing.assert_allclose(np.asarray(model.w), W_exp, atol=2e-3)
    np.testing.assert_allclose(np.asarray(model.b), b_exp, atol=2e-3)


def test_weighted_w0_balanced_equals_plain_bcd(rng):
    """With mixture_weight→0 and balanced classes the weighted solver reduces
    to centered BCD with lam scaled by n (normalized grams)."""
    x, labels, ind = _toy(rng, n=120, c=3, balanced=True)
    n = x.shape[0]
    lam = 0.3
    wls = BlockWeightedLeastSquaresEstimator(
        block_size=5, num_iter=2, lam=lam, mixture_weight=0.0
    ).fit(jnp.asarray(x), jnp.asarray(ind))
    bcd = BlockLeastSquaresEstimator(block_size=5, num_iter=2, lam=lam * n).fit(
        jnp.asarray(x), jnp.asarray(ind)
    )
    pred_w = np.asarray(wls(jnp.asarray(x)))
    pred_b = np.asarray(bcd(jnp.asarray(x)))
    np.testing.assert_allclose(pred_w, pred_b, atol=5e-3)


def test_weighted_masked_rows_ignored(rng):
    x, labels, ind = _toy(rng, n=90, balanced=False)
    est = BlockWeightedLeastSquaresEstimator(5, 1, 0.5, 0.25)
    m1 = est.fit(jnp.asarray(x), jnp.asarray(ind))
    xp, mask = pad_rows(jnp.asarray(x), 16)
    indp, _ = pad_rows(jnp.asarray(ind), 16)
    xp = xp.at[90:].set(123.0)
    indp = indp.at[90:].set(1.0)
    m2 = est.fit(xp, indp, mask=mask)
    np.testing.assert_allclose(np.asarray(m1.w), np.asarray(m2.w), atol=1e-3)
    np.testing.assert_allclose(np.asarray(m1.b), np.asarray(m2.b), atol=1e-3)


def _many_class_toy(rng, n, c, d, alpha=1.2):
    """Heavy-tailed class sizes (every class nonempty) + separable features."""
    extra = rng.choice(c, size=n - c, p=(np.arange(1, c + 1.0) ** -alpha)
                       / np.sum(np.arange(1, c + 1.0) ** -alpha))
    labels = np.concatenate([np.arange(c), extra]).astype(np.int32)
    rng.shuffle(labels)
    protos = rng.normal(size=(c, d)).astype(np.float32)
    x = protos[labels] + 0.3 * rng.normal(size=(n, d)).astype(np.float32)
    ind = np.asarray(ClassLabelIndicatorsFromIntLabels(c)(jnp.asarray(labels)))
    return x, labels, ind


def test_weighted_147_classes_timit_scale(rng):
    """TIMIT's class axis (147 phone classes) through the bucketed scan
    (VERDICT round-1 item 5; reference C at TimitFeaturesDataLoader.scala:17)."""
    x, labels, ind = _many_class_toy(rng, n=1470, c=147, d=24)
    est = BlockWeightedLeastSquaresEstimator(
        block_size=8, num_iter=2, lam=0.05, mixture_weight=0.25
    )
    model = est.fit(jnp.asarray(x), jnp.asarray(ind))
    preds = np.asarray(model(jnp.asarray(x))).argmax(1)
    assert (preds == labels).mean() > 0.9


def test_weighted_1000_classes_imbalanced_matches_oracle(rng):
    """ImageNet's class axis: 1000 classes, zipf-imbalanced counts (largest
    ~30× the smallest bucket). Single block + single pass so the numpy
    mixture-of-empiricals oracle applies exactly; the bucketed scan must
    reproduce it per class."""
    c, d = 1000, 12
    x, labels, ind = _many_class_toy(rng, n=6000, c=c, d=d)
    lam, w = 0.3, 0.25
    est = BlockWeightedLeastSquaresEstimator(
        block_size=d, num_iter=1, lam=lam, mixture_weight=w
    )
    model = est.fit(jnp.asarray(x), jnp.asarray(ind))
    W_exp, b_exp = _weighted_oracle_single_block(x.astype(np.float64), ind, lam, w)
    np.testing.assert_allclose(np.asarray(model.w), W_exp, atol=5e-3)
    np.testing.assert_allclose(np.asarray(model.b), b_exp, atol=5e-3)


class _SliceNode:
    """Feature node for fit_streaming tests: emits one column block of
    raw['x'] (stands in for re-featurization from raw inputs)."""

    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    def apply_batch(self, raw):
        return raw["x"][:, self.lo : self.hi]


@pytest.mark.parametrize("num_iter,cache_stats", [(1, True), (3, True), (3, False)])
def test_weighted_streaming_matches_incore(rng, num_iter, cache_stats):
    """fit_streaming (re-featurize per block, nothing materialized) must
    reproduce the in-core fit exactly — same loop, different block source
    (VERDICT round-1 item 1)."""
    x, labels, ind = _toy(rng, n=200, d=24, balanced=False)
    bs = 8
    est = BlockWeightedLeastSquaresEstimator(
        block_size=bs, num_iter=num_iter, lam=0.1, mixture_weight=0.25,
        cache_stats=cache_stats,
    )
    m_incore = est.fit(jnp.asarray(x), jnp.asarray(ind))
    nodes = [_SliceNode(k * bs, (k + 1) * bs) for k in range(x.shape[1] // bs)]
    m_stream = est.fit_streaming(nodes, {"x": jnp.asarray(x)}, jnp.asarray(ind))
    np.testing.assert_allclose(
        np.asarray(m_stream.w), np.asarray(m_incore.w), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(m_stream.b), np.asarray(m_incore.b), atol=1e-5
    )


def test_weighted_streaming_masked_and_sharded(rng, devices):
    """Streaming weighted fit on an 8-device mesh with padded (masked) rows:
    the scaled-down sharded version of the flagship out-of-core solve."""
    from keystone_tpu.parallel import distribute, make_mesh, use_mesh

    x, labels, ind = _toy(rng, n=90, d=16, balanced=False)
    est = BlockWeightedLeastSquaresEstimator(8, 2, 0.1, 0.25)
    m_ref = est.fit(jnp.asarray(x), jnp.asarray(ind))
    with use_mesh(make_mesh()):
        ds = distribute(jnp.asarray(x))  # pads to /8, row-shards, masks
        lds, _ = pad_rows(jnp.asarray(ind), ds.data.shape[0])
        nodes = [_SliceNode(k * 8, (k + 1) * 8) for k in range(2)]
        m_stream = est.fit_streaming(
            nodes, {"x": ds.data}, lds, mask=ds.mask
        )
    np.testing.assert_allclose(
        np.asarray(m_stream.w), np.asarray(m_ref.w), atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(m_stream.b), np.asarray(m_ref.b), atol=1e-4
    )


def test_weighted_multiblock_classifies_imbalanced(rng):
    x, labels, ind = _toy(rng, n=200, d=16, balanced=False)
    est = BlockWeightedLeastSquaresEstimator(
        block_size=8, num_iter=3, lam=0.1, mixture_weight=0.25
    )
    model = est.fit(jnp.asarray(x), jnp.asarray(ind))
    preds = np.asarray(model(jnp.asarray(x))).argmax(1)
    assert (preds == labels).mean() > 0.95


def test_weighted_feature_sharded_2d_mesh(rng, devices):
    """Weighted BCD with the feature matrix sharded over BOTH mesh axes —
    rows over ``data``, feature columns over ``model`` (the column-sharded
    alternative to streaming for the flagship dims, SURVEY.md §5): same
    model as the unsharded fit."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.parallel import make_mesh, use_mesh

    x, labels, ind = _toy(rng, n=160, d=32, balanced=False)
    est = BlockWeightedLeastSquaresEstimator(8, 2, 0.1, 0.25)
    m_ref = est.fit(jnp.asarray(x), jnp.asarray(ind))
    mesh = make_mesh(data=4, model=2)
    with use_mesh(mesh):
        xj = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("data", "model")))
        lj = jax.device_put(jnp.asarray(ind), NamedSharding(mesh, P("data", None)))
        m_sh = est.fit(xj, lj)
    np.testing.assert_allclose(np.asarray(m_sh.w), np.asarray(m_ref.w), atol=1e-4)
    np.testing.assert_allclose(np.asarray(m_sh.b), np.asarray(m_ref.b), atol=1e-4)


def test_weighted_streaming_grouped_fisher_matches_ungrouped(rng):
    """fit_streaming with cache-grouped Fisher nodes (shared-posterior group
    featurization, f32 cache) must solve identically to per-block nodes, and
    bf16 cache must stay close — the flagship HBM configuration."""
    from keystone_tpu.learning.gmm import GaussianMixtureModelEstimator
    from keystone_tpu.ops.images.fisher_vector import (
        fisher_l1_norms,
        make_fisher_block_nodes,
    )

    k, d = 4, 8
    gmm = GaussianMixtureModelEstimator(k=k, num_iter=10).fit(
        jnp.asarray(rng.normal(size=(300, d)).astype(np.float32))
    )
    n = 96
    descs = jnp.asarray(rng.normal(size=(n, 12, d)).astype(np.float32))
    raw = {"descs": descs, "l1": fisher_l1_norms(descs, gmm, chunk=32)}
    labels = rng.integers(0, 5, n)
    ind = np.full((n, 5), -1.0, np.float32)
    ind[np.arange(n), labels] = 1.0

    est = BlockWeightedLeastSquaresEstimator(2 * d, 1, 0.1, 0.25)
    plain = make_fisher_block_nodes(gmm, block_size=2 * d)
    m_ref = est.fit_streaming(plain, raw, jnp.asarray(ind))
    grouped = make_fisher_block_nodes(gmm, block_size=2 * d, cache_blocks=2)
    m_f32 = est.fit_streaming(grouped, raw, jnp.asarray(ind))
    np.testing.assert_allclose(np.asarray(m_f32.w), np.asarray(m_ref.w), atol=1e-5)
    np.testing.assert_allclose(np.asarray(m_f32.b), np.asarray(m_ref.b), atol=1e-5)

    m_bf16 = est.fit_streaming(
        grouped, raw, jnp.asarray(ind), cache_dtype=jnp.bfloat16
    )
    # bf16 feature storage: ~3 decimal digits; weights stay within a relative
    # envelope of the f32 solution
    ref_w = np.asarray(m_ref.w)
    np.testing.assert_allclose(
        np.asarray(m_bf16.w), ref_w, atol=0.02 * np.abs(ref_w).max() + 1e-4
    )

    # streaming prediction: grouped == ungrouped
    from keystone_tpu.learning.block_linear import streaming_predict

    p_ref = np.asarray(streaming_predict(m_ref, plain, raw))
    p_grp = np.asarray(streaming_predict(m_ref, grouped, raw))
    np.testing.assert_allclose(p_grp, p_ref, atol=1e-4)


def test_weighted_streaming_leaves_raw_untouched(rng):
    """No global class sort exists anywhere in the solver: the caller's raw
    pytree must come back bit-identical (per-class row access is by index
    gather inside the solves)."""
    from keystone_tpu.learning.gmm import GaussianMixtureModelEstimator
    from keystone_tpu.ops.images.fisher_vector import (
        fisher_l1_norms,
        make_fisher_block_nodes,
    )

    k, d = 4, 8
    gmm = GaussianMixtureModelEstimator(k=k, num_iter=10).fit(
        jnp.asarray(rng.normal(size=(300, d)).astype(np.float32))
    )
    n = 64
    descs = jnp.asarray(rng.normal(size=(n, 12, d)).astype(np.float32))
    l1 = fisher_l1_norms(descs, gmm, chunk=32)
    labels = rng.integers(0, 5, n)
    ind = np.full((n, 5), -1.0, np.float32)
    ind[np.arange(n), labels] = 1.0
    descs_before = np.asarray(descs).copy()

    est = BlockWeightedLeastSquaresEstimator(2 * d, 1, 0.1, 0.25)
    nodes = make_fisher_block_nodes(gmm, block_size=2 * d, cache_blocks=2)
    raw = {"descs": descs, "l1": l1}
    est.fit_streaming(nodes, raw, jnp.asarray(ind), cache_dtype=jnp.bfloat16)
    assert raw["descs"] is descs and raw["l1"] is l1
    np.testing.assert_array_equal(np.asarray(raw["descs"]), descs_before)


def test_woodbury_class_solves_match_dense(rng, monkeypatch):
    """Small-class solves via the shared-base Woodbury identity (rank-n_c
    updates against one B=(1-w)popCov+lam*I inverse per block) must match
    the dense per-class Cholesky to float tolerance. bs=128 with ~8-row
    classes crosses the max_nc+1 <= bs//8 threshold, so the default path IS
    Woodbury here; the dense reference is obtained by forcing the
    crossover off."""
    import keystone_tpu.learning.block_weighted as bw

    c, d, n = 40, 128, 320
    labels = np.concatenate([np.arange(c), rng.choice(c, size=n - c)]).astype(np.int32)
    rng.shuffle(labels)
    protos = rng.normal(size=(c, d)).astype(np.float32)
    x = protos[labels] + 0.3 * rng.normal(size=(n, d)).astype(np.float32)
    ind = np.asarray(ClassLabelIndicatorsFromIntLabels(c)(jnp.asarray(labels)))

    est = BlockWeightedLeastSquaresEstimator(
        block_size=d, num_iter=1, lam=0.05, mixture_weight=0.25
    )
    assert bw._use_woodbury(8, d)  # the small-class buckets take this path
    m_wood = est.fit(jnp.asarray(x), jnp.asarray(ind))
    monkeypatch.setattr(bw, "_use_woodbury", lambda max_nc, bs: False)
    m_dense = est.fit(jnp.asarray(x), jnp.asarray(ind))
    np.testing.assert_allclose(
        np.asarray(m_wood.w), np.asarray(m_dense.w), atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(m_wood.b), np.asarray(m_dense.b), atol=2e-4
    )


def test_weighted_streaming_grouped_fisher_sharded_mesh(rng, devices):
    """The full flagship configuration shape on the 8-device mesh:
    row-sharded bf16 descriptors + cache-grouped Fisher block nodes +
    bf16 group cache + Woodbury-eligible class buckets, through
    fit_streaming and streaming_predict, vs the unsharded f32 reference."""
    from keystone_tpu.learning.block_linear import streaming_predict
    from keystone_tpu.learning.gmm import GaussianMixtureModelEstimator
    from keystone_tpu.ops.images.fisher_vector import (
        fisher_l1_norms,
        make_fisher_block_nodes,
    )
    from keystone_tpu.parallel import distribute, make_mesh, use_mesh

    import keystone_tpu.learning.block_weighted as bw

    k, d = 4, 32
    gmm = GaussianMixtureModelEstimator(k=k, num_iter=10).fit(
        jnp.asarray(rng.normal(size=(300, d)).astype(np.float32))
    )
    # n NOT divisible by 8: distribute() really pads, so masked rows flow
    # through the grouped featurization, solves, and predict paths
    n, c = 100, 24
    descs = jnp.asarray(rng.normal(size=(n, 10, d)).astype(np.float32))
    labels = np.concatenate([np.arange(c), rng.choice(c, size=n - c)]).astype(np.int32)
    rng.shuffle(labels)
    ind = np.asarray(ClassLabelIndicatorsFromIntLabels(c)(jnp.asarray(labels)))
    # bs=128: the ~4-row classes land in min-chunk-8 buckets, and
    # 8 + 1 <= 128//8 crosses the Woodbury threshold — the flagship
    # combination (Woodbury + sharding + bf16 cache) genuinely runs
    bs = 4 * d  # 2 blocks over the 2k*d = 256 branch width
    assert bw._use_woodbury(8, bs)
    nodes = make_fisher_block_nodes(gmm, block_size=bs, cache_blocks=2)
    assert nodes[0].cache_group is not None  # grouping active too
    l1 = fisher_l1_norms(descs, gmm, chunk=32)

    est = BlockWeightedLeastSquaresEstimator(bs, 1, 0.05, 0.25)
    m_ref = est.fit_streaming(nodes, {"descs": descs, "l1": l1}, jnp.asarray(ind))

    with use_mesh(make_mesh()):
        ds = distribute(descs)  # pads to /8, row-shards, masks
        n_pad = ds.data.shape[0]
        l1_p, _ = pad_rows(l1[:, None], n_pad)
        ind_p, _ = pad_rows(jnp.asarray(ind), n_pad)
        raw = {
            "descs": jnp.asarray(ds.data, jnp.bfloat16),
            # pad l1 with 1s: padded rows divide by it before masking
            "l1": jnp.where(ds.mask > 0, l1_p[:, 0], 1.0),
        }
        m_sh = est.fit_streaming(
            nodes, raw, ind_p, mask=ds.mask, cache_dtype=jnp.bfloat16
        )
        preds = streaming_predict(m_sh, nodes, raw, jnp.bfloat16)
    # bf16 descriptors + bf16 group cache: expect ~3-digit agreement
    ref_w = np.asarray(m_ref.w)
    np.testing.assert_allclose(
        np.asarray(m_sh.w), ref_w, atol=0.05 * np.abs(ref_w).max() + 1e-3
    )
    p_ref = np.asarray(streaming_predict(m_ref, nodes, {"descs": descs, "l1": l1}))
    np.testing.assert_allclose(
        np.asarray(preds)[:n], p_ref, atol=0.05 * np.abs(p_ref).max() + 1e-3
    )


class _FailingSliceNode(_SliceNode):
    """Raises on the k-th apply call — the mid-fit crash injector."""

    calls = 0

    def __init__(self, lo, hi, fail_at):
        super().__init__(lo, hi)
        self.fail_at = fail_at

    def apply_batch(self, raw):
        _FailingSliceNode.calls += 1
        if _FailingSliceNode.calls == self.fail_at:
            raise RuntimeError("injected mid-fit crash")
        return super().apply_batch(raw)


@pytest.mark.parametrize("num_iter", [1, 2])
def test_streaming_checkpoint_kill_and_resume_bit_exact(rng, tmp_path, num_iter):
    """Mid-fit checkpoint/resume (VERDICT r2 next #6): kill the streaming
    fit partway (a feature node raises), resume from the checkpoint, and
    the resumed fit must equal the uninterrupted fit BIT-exactly — the
    saved state (residual, models, joint means, cursor) plus deterministic
    recomputation of the pass-0 caches is the whole loop state."""
    x, labels, ind = _toy(rng, n=160, d=32, balanced=False)
    bs = 8
    nblocks = x.shape[1] // bs
    est = BlockWeightedLeastSquaresEstimator(
        block_size=bs, num_iter=num_iter, lam=0.1, mixture_weight=0.25
    )
    raw = {"x": jnp.asarray(x)}
    nodes = [_SliceNode(k * bs, (k + 1) * bs) for k in range(nblocks)]
    m_ref = est.fit_streaming(nodes, raw, jnp.asarray(ind))

    ckpt = str(tmp_path / "midfit.ckpt")
    # crash on the 3rd block visit of the LAST iteration, after two
    # checkpoints have been written in that iteration
    fail_at = (num_iter - 1) * nblocks + 3
    _FailingSliceNode.calls = 0
    failing = [
        _FailingSliceNode(k * bs, (k + 1) * bs, fail_at) for k in range(nblocks)
    ]
    with pytest.raises(RuntimeError, match="injected"):
        est.fit_streaming(
            failing, raw, jnp.asarray(ind),
            checkpoint_path=ckpt, checkpoint_every=1,
        )
    assert (tmp_path / "midfit.ckpt").exists()

    # resume with healthy nodes from the same path
    m_res = est.fit_streaming(
        nodes, raw, jnp.asarray(ind),
        checkpoint_path=ckpt, checkpoint_every=1,
    )
    np.testing.assert_array_equal(np.asarray(m_res.w), np.asarray(m_ref.w))
    np.testing.assert_array_equal(np.asarray(m_res.b), np.asarray(m_ref.b))


def test_streaming_checkpoint_rejects_mismatched_shape(rng, tmp_path):
    x, labels, ind = _toy(rng, n=80, d=16, balanced=False)
    est = BlockWeightedLeastSquaresEstimator(8, 1, 0.1, 0.25)
    ckpt = str(tmp_path / "c.ckpt")
    # interrupt after block 1 so a checkpoint survives (a COMPLETED fit
    # removes its checkpoint — pinned below)
    _FailingSliceNode.calls = 0
    failing = [_FailingSliceNode(k * 8, (k + 1) * 8, 2) for k in range(2)]
    with pytest.raises(RuntimeError, match="injected"):
        est.fit_streaming(failing, {"x": jnp.asarray(x)}, jnp.asarray(ind),
                          checkpoint_path=ckpt, checkpoint_every=1)
    assert (tmp_path / "c.ckpt").exists()
    est4 = BlockWeightedLeastSquaresEstimator(4, 1, 0.1, 0.25)
    nodes4 = [_SliceNode(k * 4, (k + 1) * 4) for k in range(4)]
    with pytest.raises(ValueError, match="checkpoint"):
        est4.fit_streaming(nodes4, {"x": jnp.asarray(x)}, jnp.asarray(ind),
                           checkpoint_path=ckpt, checkpoint_every=1)


def test_streaming_checkpoint_removed_after_completed_fit(rng, tmp_path):
    """A completed fit deletes its checkpoint: a rerun with the same path on
    different same-shape data must FIT, not silently resume a stale cursor
    (code-review r3 finding)."""
    x, labels, ind = _toy(rng, n=80, d=16, balanced=False)
    est = BlockWeightedLeastSquaresEstimator(8, 1, 0.1, 0.25)
    nodes = [_SliceNode(k * 8, (k + 1) * 8) for k in range(2)]
    ckpt = str(tmp_path / "done.ckpt")
    est.fit_streaming(nodes, {"x": jnp.asarray(x)}, jnp.asarray(ind),
                      checkpoint_path=ckpt, checkpoint_every=1)
    assert not (tmp_path / "done.ckpt").exists()
    # rerun on different data: must produce that data's own solution
    x2 = x[::-1].copy()
    m2 = est.fit_streaming(nodes, {"x": jnp.asarray(x2)}, jnp.asarray(ind),
                           checkpoint_path=ckpt, checkpoint_every=1)
    m2_ref = est.fit_streaming(nodes, {"x": jnp.asarray(x2)}, jnp.asarray(ind))
    np.testing.assert_array_equal(np.asarray(m2.w), np.asarray(m2_ref.w))


def test_woodbury_matches_dense_at_flagship_conditioning(rng, monkeypatch):
    """ADVICE r2: the Woodbury path forms B^-1 = ((1-w)popCov + lam*I)^-1
    explicitly, and the r2 equivalence evidence ran at lam=0.05 / bs=128 —
    far better conditioned than the flagship (lam=6e-5, correlated FV-like
    features). This pins Woodbury == dense under flagship-like conditioning:
    low-rank-dominated covariance (features = loadings @ factors + small
    noise, condition number >> 1e4) and the flagship lambda."""
    import keystone_tpu.learning.block_weighted as bw

    n, d, c, rank = 512, 128, 32, 12
    # strongly correlated features: 12 latent factors + 1e-3 noise floor
    loadings = rng.normal(size=(n, rank)).astype(np.float32)
    factors = rng.normal(size=(rank, d)).astype(np.float32)
    x = loadings @ factors + 1e-3 * rng.normal(size=(n, d)).astype(np.float32)
    cov = np.cov(x.T)
    evals = np.linalg.eigvalsh(cov)
    assert evals.max() / max(evals.min(), 1e-30) > 1e4  # genuinely ill-posed
    labels = (np.arange(n) % c).astype(np.int32)
    rng.shuffle(labels)
    ind = np.asarray(ClassLabelIndicatorsFromIntLabels(c)(jnp.asarray(labels)))

    bs = d  # one block
    m_wood = BlockWeightedLeastSquaresEstimator(
        bs, 1, 6e-5, 0.25, woodbury="always"
    ).fit(jnp.asarray(x), jnp.asarray(ind))
    m_dense = BlockWeightedLeastSquaresEstimator(
        bs, 1, 6e-5, 0.25, woodbury="never"
    ).fit(jnp.asarray(x), jnp.asarray(ind))
    # At this conditioning f32 WEIGHTS are not comparable (the objective is
    # flat along the near-null space and the two algorithms pick different
    # near-minimizers; vs an f64 oracle BOTH carry O(0.1) weight error).
    # The meaningful solver contract is the OBJECTIVE: both must reach the
    # same residual to well under 1%.
    pred_w = np.asarray(x @ np.asarray(m_wood.w)) + np.asarray(m_wood.b)
    pred_d = np.asarray(x @ np.asarray(m_dense.w)) + np.asarray(m_dense.b)
    res_w = np.linalg.norm(pred_w - ind)
    res_d = np.linalg.norm(pred_d - ind)
    assert abs(res_w - res_d) / res_d < 0.01, (res_w, res_d)
    # and the dense escape hatch (woodbury="never") must exist and agree
    # with the f64 oracle's predictions much more tightly than Woodbury —
    # the documented envelope in BlockWeightedLeastSquaresEstimator.__init__
    W64, _ = _weighted_oracle_single_block(
        x.astype(np.float64), ind.astype(np.float64), 6e-5, 0.25
    )
    po = x @ W64
    err_d = np.abs(x @ np.asarray(m_dense.w) - po).max()
    err_w = np.abs(x @ np.asarray(m_wood.w) - po).max()
    assert err_d < 0.1 * np.abs(po).max()
    assert err_d < err_w  # dense is the accuracy-side choice here


def test_woodbury_threshold_boundary_both_ways(rng, monkeypatch):
    """The boundary bucket (max_nc straddling bs//4) must produce the same
    solution whichever side of the crossover it lands on — the threshold is
    a performance choice, never a correctness one. Measured basis for the
    bs//4 value: scripts/woodbury_crossover.py (quoted in _use_woodbury)."""
    import keystone_tpu.learning.block_weighted as bw

    bs = 64
    # exactly AT the threshold: max_nc + 1 == bs // 4
    nc = bs // 4 - 1
    assert bw._use_woodbury(nc, bs) and not bw._use_woodbury(nc + 1, bs)
    c = 8
    n = nc * c
    x, labels = _toy(rng, n=n, d=bs, c=c, balanced=True)[:2]
    ind = np.asarray(ClassLabelIndicatorsFromIntLabels(c)(jnp.asarray(labels)))
    est = BlockWeightedLeastSquaresEstimator(bs, 1, 0.05, 0.25)
    m_auto = est.fit(jnp.asarray(x), jnp.asarray(ind))  # Woodbury side
    monkeypatch.setattr(bw, "_use_woodbury", lambda max_nc, bs: False)
    m_dense = est.fit(jnp.asarray(x), jnp.asarray(ind))
    np.testing.assert_allclose(
        np.asarray(m_auto.w), np.asarray(m_dense.w), atol=2e-4
    )


def _ill_conditioned_fixture(rng, n=512, d=128, c=32, rank=12, noise=1e-3):
    """Low-rank-dominated features (cond(cov) >> 1e6 with the flagship
    lambda) — the operating point where the explicit f32 Woodbury base
    inverse measurably drifts (estimator docstring envelope)."""
    loadings = rng.normal(size=(n, rank)).astype(np.float32)
    factors = rng.normal(size=(rank, d)).astype(np.float32)
    x = loadings @ factors + noise * rng.normal(size=(n, d)).astype(np.float32)
    labels = (np.arange(n) % c).astype(np.int32)
    rng.shuffle(labels)
    ind = np.asarray(ClassLabelIndicatorsFromIntLabels(c)(jnp.asarray(labels)))
    return x, ind


def test_woodbury_cond_guard_refits_dense(rng, caplog):
    """Runtime conditioning guard (VERDICT r3 weak #7): past the measured
    drift onset an 'auto' fit must WARN and fall back to dense solves — the
    result is bit-identical to woodbury='never' because the refit IS that
    path."""
    import logging

    x, ind = _ill_conditioned_fixture(rng)
    bs = x.shape[1]
    with caplog.at_level(
        logging.WARNING, logger="keystone_tpu.learning.block_weighted"
    ):
        m_auto = BlockWeightedLeastSquaresEstimator(bs, 1, 6e-5, 0.25).fit(
            jnp.asarray(x), jnp.asarray(ind)
        )
    assert any("conditioning" in r.message for r in caplog.records)
    m_dense = BlockWeightedLeastSquaresEstimator(
        bs, 1, 6e-5, 0.25, woodbury="never"
    ).fit(jnp.asarray(x), jnp.asarray(ind))
    np.testing.assert_array_equal(np.asarray(m_auto.w), np.asarray(m_dense.w))

    # woodbury='always' keeps the rank-update result but still warns
    caplog.clear()
    with caplog.at_level(
        logging.WARNING, logger="keystone_tpu.learning.block_weighted"
    ):
        BlockWeightedLeastSquaresEstimator(
            bs, 1, 6e-5, 0.25, woodbury="always"
        ).fit(jnp.asarray(x), jnp.asarray(ind))
    assert any("always" in r.message for r in caplog.records)


def test_woodbury_cond_guard_quiet_when_well_conditioned(rng, caplog):
    """The guard must not fire (and must not refit) at healthy conditioning
    — the common case pays one scalar sync and nothing else."""
    import logging

    x, labels, ind = _toy(rng, n=240, d=64, balanced=True)
    with caplog.at_level(
        logging.WARNING, logger="keystone_tpu.learning.block_weighted"
    ):
        BlockWeightedLeastSquaresEstimator(64, 1, 0.05, 0.25).fit(
            jnp.asarray(x), jnp.asarray(ind)
        )
    assert not any("conditioning" in r.message for r in caplog.records)


def test_woodbury_cond_guard_survives_resume(rng, tmp_path, caplog):
    """The guard's evidence rides the checkpoint: block 0 is the
    ill-conditioned one; a crash AFTER block 0 and a resume that only runs
    block 1 must still fire the guard (the restored cond estimate, not the
    resumed blocks', carries the signal)."""
    import logging

    import keystone_tpu.learning.block_weighted as bw

    bs, c = 128, 32
    x_ill, ind = _ill_conditioned_fixture(rng, d=bs, c=c)
    n = x_ill.shape[0]
    x_ok = rng.normal(size=(n, bs)).astype(np.float32)  # healthy block 1
    blocks = [jnp.asarray(x_ill), jnp.asarray(x_ok)]
    est = BlockWeightedLeastSquaresEstimator(bs, 1, 6e-5, 0.25)
    ck = str(tmp_path / "ck")

    calls = {"n": 0}

    def poisoned(b):
        if b == 1 and calls["n"] == 0:
            calls["n"] += 1
            raise RuntimeError("boom")
        return blocks[b]

    with pytest.raises(RuntimeError, match="boom"):
        est._run(poisoned, 2, jnp.asarray(ind), None, "high",
                 checkpoint_path=ck, checkpoint_every=1)
    assert os.path.exists(ck)
    with caplog.at_level(
        logging.WARNING, logger="keystone_tpu.learning.block_weighted"
    ):
        est._run(lambda b: blocks[b], 2, jnp.asarray(ind), None, "high",
                 checkpoint_path=ck, checkpoint_every=1)
    assert any("conditioning" in r.message for r in caplog.records)


def test_dense_refit_checkpoint_not_resumed_as_woodbury(rng, tmp_path):
    """A crash inside the guard's dense refit leaves a force_dense-marked
    checkpoint; a later plain run must adopt the dense path end to end
    (bit-identical to an uninterrupted dense run), never mixing solve
    paths."""
    import keystone_tpu.learning.block_weighted as bw

    bs, c = 128, 32
    x, ind = _ill_conditioned_fixture(rng, d=2 * bs, c=c)
    blocks = [jnp.asarray(x[:, :bs]), jnp.asarray(x[:, bs:])]
    est = BlockWeightedLeastSquaresEstimator(bs, 1, 6e-5, 0.25)
    ck = str(tmp_path / "ck")

    calls = {"n": 0}

    def poisoned(b):
        if b == 1 and calls["n"] == 0:
            calls["n"] += 1
            raise RuntimeError("boom")
        return blocks[b]

    with pytest.raises(RuntimeError, match="boom"):
        est._run(poisoned, 2, jnp.asarray(ind), None, "high",
                 checkpoint_path=ck, checkpoint_every=1, _force_dense=True)
    assert os.path.exists(ck)
    W_resumed, *_ = est._run(
        lambda b: blocks[b], 2, jnp.asarray(ind), None, "high",
        checkpoint_path=ck, checkpoint_every=1,
    )
    W_dense, *_ = est._run(
        lambda b: blocks[b], 2, jnp.asarray(ind), None, "high",
        _force_dense=True,
    )
    np.testing.assert_array_equal(np.asarray(W_resumed), np.asarray(W_dense))


def test_streaming_checkpoint_resumes_on_reshaped_mesh(rng, tmp_path, devices):
    """Mesh portability (PR 12): a checkpoint written under an 8-device
    row-sharded mesh resumes on a 4-device mesh — the PR-6 loud
    mismatch-on-resume became reshard-and-continue (counted as
    checkpoint.reshard), loud only on genuine shape mismatch. The resumed
    model must match the uninterrupted twin within reduction-order
    rounding (same math, different collective geometry)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.core.checkpoint import load_manifest
    from keystone_tpu.parallel import make_mesh
    from keystone_tpu.telemetry import get_registry

    x, labels, ind = _toy(rng, n=160, d=32, balanced=False)
    bs = 8
    nblocks = x.shape[1] // bs
    est = BlockWeightedLeastSquaresEstimator(bs, 2, 0.1, 0.25)
    mesh8 = make_mesh(data=8, model=1, devices=devices[:8])
    mesh4 = make_mesh(data=4, model=1, devices=devices[:4])

    def put(mesh, a):
        return jax.device_put(
            jnp.asarray(a), NamedSharding(mesh, P("data", None))
        )

    nodes = [_SliceNode(k * bs, (k + 1) * bs) for k in range(nblocks)]
    m_ref = est.fit_streaming(nodes, {"x": put(mesh8, x)}, put(mesh8, ind))

    ckpt = str(tmp_path / "reshard.ckpt")
    fail_at = nblocks + 2  # mid-schedule, in the second pass
    _FailingSliceNode.calls = 0
    failing = [
        _FailingSliceNode(k * bs, (k + 1) * bs, fail_at)
        for k in range(nblocks)
    ]
    with pytest.raises(RuntimeError, match="injected"):
        est.fit_streaming(
            failing, {"x": put(mesh8, x)}, put(mesh8, ind),
            checkpoint_path=ckpt, checkpoint_every=1,
        )
    manifest = load_manifest(ckpt)
    assert manifest["mesh_shape"] == {"data": 8, "model": 1}

    reg = get_registry()
    r0 = reg.get_counter("checkpoint.reshard")
    m_res = est.fit_streaming(
        nodes, {"x": put(mesh4, x)}, put(mesh4, ind),
        checkpoint_path=ckpt, checkpoint_every=1,
    )
    assert reg.get_counter("checkpoint.reshard") > r0
    assert not (tmp_path / "reshard.ckpt").exists()
    np.testing.assert_allclose(
        np.asarray(m_res.w), np.asarray(m_ref.w), rtol=2e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(m_res.b), np.asarray(m_ref.b), rtol=2e-4, atol=1e-6
    )


def test_streaming_checkpoint_manifest_schedule_skew_is_loud(rng, tmp_path):
    """A manifest whose schedule fingerprint disagrees with the state's own
    saved schedule (manifest/state skew — a corruption class the per-field
    checks cannot see) must fail with the named mismatch error."""
    from keystone_tpu.core.checkpoint import (
        CheckpointMismatchError,
        load_checkpoint,
        load_manifest,
        save_node,
    )

    x, labels, ind = _toy(rng, n=80, d=16, balanced=False)
    est = BlockWeightedLeastSquaresEstimator(8, 1, 0.1, 0.25)
    ckpt = str(tmp_path / "skew.ckpt")
    _FailingSliceNode.calls = 0
    failing = [_FailingSliceNode(k * 8, (k + 1) * 8, 2) for k in range(2)]
    with pytest.raises(RuntimeError, match="injected"):
        est.fit_streaming(failing, {"x": jnp.asarray(x)}, jnp.asarray(ind),
                          checkpoint_path=ckpt, checkpoint_every=1)
    state, manifest = load_checkpoint(ckpt)
    manifest["schedule_fingerprint"] = "0" * 32  # forge the skew
    save_node(state, ckpt, manifest=manifest)
    assert load_manifest(ckpt)["schedule_fingerprint"] == "0" * 32
    nodes = [_SliceNode(k * 8, (k + 1) * 8) for k in range(2)]
    with pytest.raises(CheckpointMismatchError, match="skew"):
        est.fit_streaming(nodes, {"x": jnp.asarray(x)}, jnp.asarray(ind),
                          checkpoint_path=ckpt, checkpoint_every=1)
