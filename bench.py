"""Benchmark entry point: MnistRandomFFT fit+eval wall-clock on TPU.

Prints ONE compact JSON line as the LAST line of stdout
(``{"metric": ..., "value": N, "unit": "s", "vs_baseline": N, ...}`` —
short keys, see ``_COMPACT_KEYS``; asserted < 1500 chars so it always
fits the driver's 2,000-char tail capture) and writes the full result
dict to ``bench_full.json`` next to this file.

The ratchet can no longer be blinded by a timeout (VERDICT r05 headline):
after EVERY section the full dict is re-written to ``bench_full.json`` and a
compact line (with ``"partial": true``) is re-printed, so a SIGKILL/rc=124
at ANY point after the first section still leaves a parseable last line and
a current artifact. A total wall-clock budget (``KEYSTONE_BENCH_BUDGET_S``,
default 840 s) gates every section after the primary metric: when the
remaining budget cannot cover a big regime, the regime is recorded as an
explicit ``<key>_skipped`` entry instead of eating the driver's timeout,
and subprocess regimes get their timeout derated from the remaining budget
rather than a flat 3600 s. ``BENCH_SMOKE=1`` shrinks every shape to a
CPU-friendly smoke configuration (the ``make bench-smoke`` loop; heavy
sections default off but explicit env settings still win).

The flagship workload is the reference's own headline config
(``--numFFTs 4 --blockSize 2048``, ``README.md:14-22``): 60k×784 train /
10k×784 test, 4×(sign-flip → 1024-pt FFT → ReLU) featurization to 2048
features, one-pass block least squares, streaming block evaluation.

The reference publishes no numbers (BASELINE.md) — and the 64-core Spark
cluster of the north star cannot run in this image (no JVM). The measured
anchor is ``cpu_baseline.json``: the SAME pipeline math on jax-CPU on this
host (1 core — produced by ``scripts/cpu_baseline.py``, methodology in
BASELINE.md). ``vs_baseline`` = cpu_warm_s / tpu_warm_s against that anchor;
the JSON also restates the anchor's core count so the number can't be
misread as a cluster comparison. We report the steady-state run (second
invocation, compile cached) as the headline value and the cold run
separately.
"""

import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp

from keystone_tpu.utils import knobs

# Fail fast on a typo'd knob: every section gate now reads through the
# strict registry, and a ValueError surfacing mid-run at whichever section
# reads the bad value first would forfeit the partial-results contract.
# Validating everything up front moves that failure to t=0, before any
# result exists to lose.
knobs.validate_environment()

# Persistent XLA compilation cache: the extras cover seven pipelines whose
# first-compile cost (~10 min total) would otherwise recur on every bench
# invocation; with the cache only the first run on a machine pays it. The
# reported cold_wallclock_s measures THIS process's first run, which on a
# pre-populated cache is mostly cache-deserialize time — the JSON states
# the cache state (``xla_cache_prewarmed``) so cold numbers can't be
# misread across runs.
_CACHE_DIR = knobs.get("BENCH_XLA_CACHE")
_CACHE_PREWARMED = os.path.isdir(_CACHE_DIR) and bool(os.listdir(_CACHE_DIR))
try:
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception as e:  # never let cache config block the benchmark
    print(f"compilation cache unavailable: {e}", file=sys.stderr)

# Smoke mode: tiny shapes for a fast CPU-runnable end-to-end pass that
# still exercises the emit/budget/section machinery (make bench-smoke, the
# bench-contract tier-1 test). Heavy sections default OFF — but only
# default: an explicit BENCH_<X>=1 in the environment still runs them.
_SMOKE = knobs.get("BENCH_SMOKE")
if _SMOKE:
    for _gate in ("BENCH_EXTRAS", "BENCH_FLAGSHIP", "BENCH_VOC_REFDIM",
                  "BENCH_TIMIT_FULL", "BENCH_CACHED", "BENCH_PREFETCH",
                  "BENCH_MOMENTS", "BENCH_CONSTANTS", "BENCH_SERVE_LATENCY",
                  "BENCH_STAGES", "BENCH_SOLVER_OVERLAP",
                  "BENCH_EXTRACTION", "BENCH_FLEET"):
        os.environ.setdefault(_gate, "0")

# Total wall-clock budget for the whole bench run. The driver kills at
# ~900 s (rc=124); finishing under the budget means the FINAL compact line
# is printed before that. Sections checked against the remaining budget are
# skipped (with explicit *_skipped entries) rather than started.
_BUDGET_S = knobs.get("KEYSTONE_BENCH_BUDGET_S")
_BUDGET_T0 = time.monotonic()  # re-anchored at main() entry
# Minimum seconds a big section must have left to start, and the reserve
# kept for the final flush + ratio bookkeeping.
_SECTION_FLOOR_S = knobs.get("KEYSTONE_BENCH_SECTION_FLOOR_S")
_FINALIZE_RESERVE_S = 15.0


def _budget_remaining() -> float:
    return _BUDGET_S - (time.monotonic() - _BUDGET_T0)


def _flush(out: dict, section: str) -> None:
    """Incremental ratchet flush: re-write bench_full.json and re-print the
    compact line (marked partial) after ``section`` completes, so a kill at
    any later point still leaves a parseable last line and a current
    artifact. BENCH_KILL_AFTER_SECTION is the test hook that simulates the
    driver's SIGKILL right after a named section's flush."""
    _emit(out, partial=True)
    if knobs.get_raw("BENCH_KILL_AFTER_SECTION") == section:
        import signal

        sys.stdout.flush()
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)
    # occurrence-indexed generalization of the named-section hook above:
    # a KEYSTONE_FAULTS 'bench_section@N[:kill]' entry SIGKILLs (or
    # raises) right after the Nth section flush (utils/faults.py; no-op
    # when the knob is unset)
    from keystone_tpu.utils import faults

    faults.check("bench_section")


def _cursor_path() -> str:
    """The persisted round-robin cursor for the in-process secondary
    sections (``KEYSTONE_BENCH_CURSOR``; default: ``.bench_cursor.json``
    at the repo root — local artifact, gitignored)."""
    p = knobs.get("KEYSTONE_BENCH_CURSOR")
    if p:
        return p
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench_cursor.json")


def _rotate_secondary(sections):
    """Round-robin start-index rotation of the secondary section list,
    persisted across runs: run N starts at section ``N % len``, so a
    budget that exhausts partway down the list (the BENCH_r06–r08 failure
    mode: the tail sections NEVER ran) still gives every section fresh
    coverage within ``len(sections)`` runs. The cursor advances even when
    every section budget-skips — a run that starves the whole list must
    not freeze the rotation. Returns ``(cursor_used, rotated_list)``; an
    unreadable/unwritable cursor file degrades to cursor 0 (the exact
    pre-cursor order) rather than failing the bench.

    The read→increment→replace window runs under an exclusive ``flock``
    on a ``<path>.lock`` sidecar (the ``autotune.record`` shape): two
    bench processes sharing a cursor file must each advance it by one, or
    a lost increment replays the same prefix and the tail sections starve
    again. Filesystems without flock degrade to best-effort."""
    path = _cursor_path()
    lockf = None
    try:
        import fcntl

        lockf = open(f"{path}.lock", "w")
        fcntl.flock(lockf, fcntl.LOCK_EX)
    except Exception:
        if lockf is not None:
            lockf.close()
            lockf = None
    cursor = 0
    try:
        with open(path) as f:
            cursor = int(json.load(f).get("secondary", 0))
    except (OSError, ValueError, TypeError, AttributeError):
        pass
    cursor %= len(sections)
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"secondary": cursor + 1}, f)
        os.replace(tmp, path)
    except OSError as e:
        print(f"bench cursor not persisted: {e}", file=sys.stderr)
    finally:
        if lockf is not None:
            lockf.close()  # drops the flock
    return cursor, sections[cursor:] + sections[:cursor]


def _load_cpu_baseline():
    """The measured CPU anchor (scripts/cpu_baseline.py); None if absent."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cpu_baseline.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        print(f"cpu_baseline.json unavailable: {e}", file=sys.stderr)
        return None


def solver_gflops(n: int = None, d: int = None, c: int = 10, block: int = None,
                  iters: int = None, precision: str = None,
                  overlap: bool = False) -> float:
    """BlockLeastSquares solver GFLOPS/chip (BASELINE.json's second metric):
    sustained rate of the block-coordinate-descent solve at the MNIST
    flagship shape (f32 inputs; MXU pass count set by ``precision`` —
    default is the framework's solver precision, bf16x3). ``overlap``
    routes the per-block gram/cross reductions through the tiled
    reduce-scatter collective matmul (``parallel/overlap.py``) — on a
    single chip it falls back to the monolithic path, so the on/off pair
    only separates on a real mesh.

    Measured as (time of K chained solves) − (time of 1 solve), each timed to
    a single scalar host transfer: device calls execute serially, so the
    difference is pure device time and the host↔device round-trip latency
    (~100 ms on a tunneled runtime) cancels out of the per-solve rate.
    """
    from keystone_tpu.linalg.bcd import block_coordinate_descent_l2

    # smoke shapes keep the ladder CPU-runnable in a few seconds
    n = n or (4096 if _SMOKE else 60000)
    d = d or (512 if _SMOKE else 2048)
    block = block or (512 if _SMOKE else 2048)
    iters = iters or (2 if _SMOKE else 16)

    key = jax.random.key(0)
    A = jax.random.normal(key, (n, d), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (n, c), jnp.float32)
    float(A[0, 0])  # materialize inputs

    def timed(k: int) -> float:
        ws = [block_coordinate_descent_l2(A, b, 1.0 + i, block,
                                          precision=precision, overlap=overlap)
              for i in range(k)]
        float(ws[-1][0, 0])  # warm compile + drain the whole warm-up chain
        t0 = time.perf_counter()
        ws = [block_coordinate_descent_l2(A, b, 2.0 + i, block,
                                          precision=precision, overlap=overlap)
              for i in range(k)]
        w_last = float(ws[-1][0, 0])  # one transfer after the chain
        if w_last != w_last:
            raise FloatingPointError("solver produced NaN")
        return time.perf_counter() - t0

    dt = (timed(1 + iters) - timed(1)) / iters
    if dt <= 0:
        raise RuntimeError(f"non-positive solver timing difference: {dt}")
    nblocks = -(-d // block)
    flops = nblocks * (2 * n * block * block + 4 * n * block * c
                       + 2 * block * block * c) + (2 / 3) * nblocks * block**3
    return flops / dt / 1e9


def sketch_gflops(n: int = None, d: int = None, c: int = 10,
                  overlap: bool = False) -> float:
    """Sketch-and-precondition solver GFLOPs/chip — the randomized rung of
    the ladder (``linalg/sketch.py``) at the same flagship shape as the
    exact BCD rung, so the two rows compare directly. ``tol=0`` pins the
    CG to exactly ``cg_iters`` iterations (fixed, countable work); FLOPs
    are the solver's analytic phase formulas (sketch pass + m·d² QR +
    per-iteration matvec pair). Same latency-cancelled timing scheme as
    :func:`solver_gflops`."""
    from keystone_tpu.linalg.sketch import sketch_rows, sketched_lstsq_solve

    n = n or (4096 if _SMOKE else 60000)
    d = d or (512 if _SMOKE else 2048)
    cg_iters = 2 if _SMOKE else 8
    iters = 2 if _SMOKE else 8

    key = jax.random.key(0)
    A = jax.random.normal(key, (n, d), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (n, c), jnp.float32)
    float(A[0, 0])  # materialize inputs

    def timed(k: int) -> float:
        ws = [sketched_lstsq_solve(A, b, lam=1.0 + i, tol=0.0,
                                   max_iters=cg_iters, overlap=overlap)
              for i in range(k)]
        float(ws[-1][0, 0])  # warm compile + drain the whole warm-up chain
        t0 = time.perf_counter()
        ws = [sketched_lstsq_solve(A, b, lam=2.0 + i, tol=0.0,
                                   max_iters=cg_iters, overlap=overlap)
              for i in range(k)]
        w_last = float(ws[-1][0, 0])  # one transfer after the chain
        if w_last != w_last:
            raise FloatingPointError("sketched solver produced NaN")
        return time.perf_counter() - t0

    dt = (timed(1 + iters) - timed(1)) / iters
    if dt <= 0:
        raise RuntimeError(f"non-positive sketch timing difference: {dt}")
    m = sketch_rows(n, d)
    flops = (n * (d + c) + 2.0 * (m + d) * d * d
             + cg_iters * (4.0 * n * d * c + 2.0 * d * d * c))
    return flops / dt / 1e9


def _try_metric(name: str, fn):
    """Retry-once wrapper shared by the ladder cells; never let a secondary
    metric block the primary JSON line. One retry absorbs transient timing
    noise (dt<=0 on a contended chip); genuine failures (e.g. the NaN
    guard) are logged to stderr before retrying so they are distinguishable
    from noise in the driver log."""
    for attempt in range(2):
        try:
            return round(fn(), 1)
        except Exception as e:
            print(
                f"{name} attempt {attempt + 1} "
                f"failed: {type(e).__name__}: {e}",
                file=sys.stderr,
            )
    return None


def _try_solver_gflops(precision=None, overlap: bool = False):
    return _try_metric(
        f"solver_gflops(precision={precision}, overlap={overlap})",
        lambda: solver_gflops(precision=precision, overlap=overlap),
    )


def _try_solver_gflops_ladder() -> dict:
    """The solver ladder in ONE place: GFLOPs/chip for the ``"high"``
    (bf16x3, the framework default) and ``"highest"`` (6-pass ≈ f32) MXU
    modes of the exact BCD rung, plus the randomized sketch rung — each
    with the overlap knob off and on. The ``"highest"`` column rides the
    BENCH_EXTRAS gate (it doubles the ladder's device time); the overlap
    columns are cheap on a single chip (same program after fallback) and
    document the on/off pairs whenever a mesh is present.

    Since the sketch rung landed this runs as a budget-derated SUBPROCESS
    regime (``scripts/bench_regime.py solver_ladder``): in-process it was
    the one heavy section with no enforceable timeout — the rc=124 hole
    run 5 fell into."""
    rows = {
        "solver_gflops_per_chip": _try_solver_gflops("high"),
        "solver_gflops_per_chip_overlap": _try_solver_gflops(
            "high", overlap=True
        ),
        # the randomized rung (linalg/sketch.py): same shape, sub-quadratic
        # work — the d≳65536 regime's escape from the exact grams
        "sketch_gflops_per_chip": _try_metric(
            "sketch_gflops", lambda: sketch_gflops()
        ),
        "sketch_gflops_per_chip_overlap": _try_metric(
            "sketch_gflops(overlap)", lambda: sketch_gflops(overlap=True)
        ),
    }
    if knobs.get("BENCH_EXTRAS"):
        rows["solver_gflops_per_chip_f32_highest"] = _try_solver_gflops(
            "highest"
        )
        rows["solver_gflops_per_chip_f32_highest_overlap"] = _try_solver_gflops(
            "highest", overlap=True
        )
    return rows


# (key, pipeline module, config class name, config kwargs) — each runs
# twice, reports the warm wall-clock, and never blocks the primary metric.
_EXTRA_PIPELINES = (
    ("timit_100k_50x4096_5ep_warm_s", "keystone_tpu.pipelines.timit",
     "TimitConfig", dict(synthetic_train=100000, synthetic_test=20000)),
    ("random_patch_cifar_50k_warm_s",
     "keystone_tpu.pipelines.random_patch_cifar", "RandomPatchCifarConfig",
     dict(synthetic_train=50000, synthetic_test=10000)),
    ("newsgroups_20k_warm_s", "keystone_tpu.pipelines.newsgroups",
     "NewsgroupsConfig",
     dict(synthetic_train=20000, synthetic_test=4000, synthetic_classes=20,
          common_features=100000)),
    ("stupid_backoff_20k_warm_s", "keystone_tpu.pipelines.stupid_backoff",
     "StupidBackoffConfig", dict(synthetic_docs=20000)),
    # the small-config image rows use the pipelines' shared small_config()
    # factories — the CPU anchor (scripts/cpu_baseline.py) measures the
    # exact same construction, so the vs-CPU ratios cannot drift
    ("voc_small_warm_s", "keystone_tpu.pipelines.voc_sift_fisher",
     "small_config", {}),
    ("imagenet_small_warm_s", "keystone_tpu.pipelines.imagenet_sift_lcs_fv",
     "small_config", {}),
)


WARM_REPS = knobs.get("BENCH_WARM_REPS")

# A warm distribution whose max strays this far above its median was
# measurably contended (chip shared with another tenant): BASELINE.md's
# observed swings are ~1.5-1.9x, quiet-chip spreads are <1.2x.
_CONTENTION_RATIO = 1.3


def _warm_stats(fn, reps: int = None):
    """Run ``fn`` ``reps`` times; return (median, min, max, contended).

    The tunneled chip is contended, so single-shot warm numbers drift ~1.5x
    run to run (BASELINE.md); the JSON carries the spread, not prose. When
    max/median exceeds the contention ratio the sample auto-reruns ONCE
    (the extra rep usually restores a clean median) and the final
    ``contended`` bool is recorded per metric — no more silent 1.9x spreads
    inside one artifact (VERDICT r3 weak #5)."""
    import statistics

    reps = WARM_REPS if reps is None else reps
    times = []
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    if len(times) > 1 and max(times) / statistics.median(times) > _CONTENTION_RATIO:
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    med = statistics.median(times)
    return (
        round(med, 3),
        round(min(times), 3),
        round(max(times), 3),
        bool(max(times) / med > _CONTENTION_RATIO),
    )


def _try_extras():
    """Secondary whole-pipeline wall-clocks (warm median of WARM_REPS, with
    min/max spread), never fatal. Disable with BENCH_EXTRAS=0 to keep the
    run to the primary metric only.

    Budget-enforced per PIPELINE, not just at section entry: six pipelines
    run here back to back, so a single entry gate could admit the section
    with 61 s left and then run for minutes past the driver's kill — the
    same hole class as the old in-process ladder. Each pipeline re-checks
    the remaining budget and the rest skip with explicit markers."""
    if not knobs.get("BENCH_EXTRAS"):
        return {}
    import importlib

    extras = {}
    for key, module, config_name, kwargs in _EXTRA_PIPELINES:
        if _budget_remaining() - _FINALIZE_RESERVE_S < _SECTION_FLOOR_S:
            extras[key] = None
            extras[key + "_skipped"] = "budget"
            print(f"extras[{key}] skipped: budget exhausted", file=sys.stderr)
            continue
        try:
            mod = importlib.import_module(module)
            cfg = getattr(mod, config_name)(**kwargs)
            mod.run(cfg)  # cold (compile)
            med, lo, hi, contended = _warm_stats(lambda: mod.run(cfg))
            extras[key] = med
            extras[key + "_min"] = lo
            extras[key + "_max"] = hi
            extras[key + "_contended"] = contended
        except Exception as e:
            print(f"extras[{key}] failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            extras[key] = None
    return extras


def _try_device_count_constants():
    """Re-measure the two on-chip microbenchmarks the device-count design
    rests on (``device_count.py``/``device_text.py`` docstrings: int32 keys
    sort ~2x faster than int64; ``searchsorted method='sort'`` ~19x faster
    than ``'scan'`` for int32): a jaxlib upgrade that inverted either would
    otherwise silently strand the design on the slow side (VERDICT r3 weak
    #6). Latency-cancelled timing — (K chained ops) − (1 op) — so the
    ~100 ms tunnel round trip drops out. BENCH_CONSTANTS=0 skips."""
    if not knobs.get("BENCH_CONSTANTS"):
        return {}
    try:
        n = 1 << 20  # ~the 20k-doc StupidBackoff window-key count
        k_reps = 8

        def lat_cancelled(fn, sync):
            def timed(k):
                sync(fn(0))  # compile
                t0 = time.perf_counter()
                o = None
                for i in range(k):
                    o = fn(i + 1)
                sync(o)
                return time.perf_counter() - t0

            # contention can make the short run slower than the long one
            # (negative difference -> garbage ratios); retry, then give up
            for _ in range(3):
                dt = (timed(1 + k_reps) - timed(1)) / k_reps
                if dt > 0:
                    return dt
            raise RuntimeError("non-positive latency-cancelled timing")

        out = {}
        with jax.enable_x64():
            keys32 = jax.random.randint(
                jax.random.key(0), (n,), 0, 1 << 30, jnp.int32
            )
            keys64 = keys32.astype(jnp.int64) << 20

            def sort_t(keys):
                f = jax.jit(lambda s: jnp.sort(keys + s))
                return lat_cancelled(f, lambda o: int(o[0]))

            t32, t64 = sort_t(keys32), sort_t(keys64)
            out["key_sort_int32_s"] = round(t32, 4)
            out["key_sort_int64_s"] = round(t64, 4)
            out["key_sort_int64_over_int32"] = round(t64 / t32, 2)

            table = jnp.sort(jax.random.randint(
                jax.random.key(1), (200_000,), 0, 1 << 30, jnp.int32
            ))
            q = jax.random.randint(jax.random.key(2), (n,), 0, 1 << 30,
                                   jnp.int32)

            def ss_t(method):
                f = jax.jit(functools.partial(
                    lambda s, m: jnp.searchsorted(table, q + s, method=m),
                    m=method,
                ))
                return lat_cancelled(f, lambda o: int(o[0]))

            ts, tc = ss_t("sort"), ss_t("scan")
            out["searchsorted_sort_int32_s"] = round(ts, 4)
            out["searchsorted_scan_int32_s"] = round(tc, 4)
            out["searchsorted_scan_over_sort_int32"] = round(tc / ts, 1)
        return out
    except Exception as e:
        print(f"device-count constants bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def _try_serving_latency():
    """Single-item ``serve`` latency on fitted pipelines (VERDICT r3 missing
    #4 — the dual bulk/single-item contract, ``Transformer.scala:16-30``,
    had correctness tests but zero perf evidence). Two numbers per pipeline:

    - ``*_serve_p50_ms`` / ``*_serve_p95_ms``: 100 calls, each synced to the
      host — over a tunneled runtime this INCLUDES the transport round trip,
      i.e. what a caller would actually observe (~100 ms RTT floor here).
    - ``*_serve_device_ms``: the framework's own per-call cost with transport
      subtracted — k calls enqueued async (device executes them serially)
      with ONE final sync, minus the 1-call time, divided by k. The same
      latency-cancellation scheme as ``solver_gflops``; the tunnel RTT and
      the single sync cancel in the difference.

    BENCH_SERVE_LATENCY=0 skips."""
    if not knobs.get("BENCH_SERVE_LATENCY"):
        return {}
    import statistics

    out = {}

    def p50_p95(call):
        call()  # compile
        times = []
        for _ in range(100):
            t0 = time.perf_counter()
            call()
            times.append((time.perf_counter() - t0) * 1e3)
        times.sort()
        return round(statistics.median(times), 2), round(times[94], 2)

    def device_ms(call_dev, k=30):
        """Per-call device+dispatch ms of ``call_dev`` (returns a device
        array, no host sync) via latency cancellation; one retry absorbs a
        contended-chip negative difference."""
        jax.block_until_ready(call_dev())  # compile + warm

        def timed(n):
            t0 = time.perf_counter()
            rs = [call_dev() for _ in range(n)]
            jax.block_until_ready(rs[-1])
            return time.perf_counter() - t0

        for _ in range(2):
            dt = (timed(1 + k) - timed(1)) / k
            if dt > 0:
                return round(dt * 1e3, 2)
        return None

    try:
        from keystone_tpu.learning import BlockLeastSquaresEstimator
        from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels
        from keystone_tpu.pipelines.mnist_random_fft import (
            MnistRandomFFTConfig,
            build_featurizer,
        )
        from keystone_tpu.loaders.mnist import synthetic_mnist_device

        cfg = MnistRandomFFTConfig(num_ffts=4, block_size=2048, lam=10.0)
        feats = build_featurizer(cfg)
        x, y = synthetic_mnist_device(4096, seed=7)
        train_feats = jnp.concatenate([f(x) for f in feats], axis=1)
        labels = ClassLabelIndicatorsFromIntLabels(10)(y)
        model = BlockLeastSquaresEstimator(2048, num_iter=1, lam=10.0).fit(
            train_feats, labels
        )
        item = x[0]

        def mnist_dev():
            f = jnp.concatenate([f_.serve(item) for f_ in feats])
            return model.serve(f)

        def serve_mnist():
            return float(jnp.sum(mnist_dev()))

        p50, p95 = p50_p95(serve_mnist)
        out["mnist_serve_p50_ms"] = p50
        out["mnist_serve_p95_ms"] = p95
        out["mnist_serve_device_ms"] = device_ms(mnist_dev)
    except Exception as e:
        print(f"mnist serve bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)

    try:
        import numpy as np

        from keystone_tpu.learning.naive_bayes import NaiveBayesEstimator
        from keystone_tpu.ops.nlp.device_text import DeviceCommonSparseFeatures

        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 5000, (2000, 64)), jnp.int32)
        lens = jnp.asarray(rng.integers(8, 65, 2000), jnp.int32)
        lab = jnp.asarray(rng.integers(0, 20, 2000), jnp.int32)
        vec = DeviceCommonSparseFeatures(
            base=5001, orders=(1, 2), num_features=4096
        ).fit(ids, lens)
        nb = NaiveBayesEstimator(20).fit(vec.apply_encoded(ids, lens), lab)
        one_ids, one_len = ids[:1], lens[:1]

        def news_dev():
            return nb.apply_batch(vec.apply_encoded(one_ids, one_len))

        def serve_news():
            return float(jnp.sum(news_dev()))

        p50, p95 = p50_p95(serve_news)
        out["newsgroups_serve_p50_ms"] = p50
        out["newsgroups_serve_p95_ms"] = p95
        out["newsgroups_serve_device_ms"] = device_ms(news_dev)
    except Exception as e:
        print(f"newsgroups serve bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)

    try:
        # The image-track serving story (the reference's VOC pipeline,
        # ``VOCSIFTFisher.scala:36-66`` fit → ``Transformer.scala:16-30``
        # per-item apply): one 96² image through grayscale → SIFT → PCA →
        # FV → normalize → linear scores per call. The featurizer/model are
        # fitted at the BASELINE small-config dims (vocab 16, descDim 80);
        # the fit set is 128 images — serve cost depends only on the dims.
        from keystone_tpu.learning import BlockLeastSquaresEstimator
        from keystone_tpu.loaders.voc import synthetic_voc_device
        from keystone_tpu.ops.images import GrayScaler, SIFTExtractor
        from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntArrayLabels
        from keystone_tpu.pipelines._fisher import fit_fisher_branch

        imgs, labs = synthetic_voc_device(128, 8, (96, 96), seed=1)
        gray_node = GrayScaler()
        gray = gray_node(jnp.asarray(imgs))[..., 0]
        featurizer, train_feats = fit_fisher_branch(
            SIFTExtractor(scales=4), gray, 80, 16, 1000000, 1000000, seed=42
        )
        vlabels = ClassLabelIndicatorsFromIntArrayLabels(8)(jnp.asarray(labs))
        vmodel = BlockLeastSquaresEstimator(4096, num_iter=1, lam=0.5).fit(
            train_feats, vlabels
        )
        one_img = jnp.asarray(imgs)[0]

        def voc_dev():
            g = gray_node.serve(one_img)[..., 0]
            return vmodel.serve(featurizer.serve(g))

        def serve_voc():
            return float(jnp.sum(voc_dev()))

        p50, p95 = p50_p95(serve_voc)
        out["voc_serve_p50_ms"] = p50
        out["voc_serve_p95_ms"] = p95
        out["voc_serve_device_ms"] = device_ms(voc_dev)
    except Exception as e:
        print(f"voc serve bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    return out


def _try_moments_design_point():
    """GMM/FV moments at the Pallas kernel's design point (1e7×256, d=64 —
    the reference's 1e7-sample GMM regime): both the kernel and the
    chunked-XLA path, single-sync timings (VERDICT r2 weak #6: demonstrate
    the regime or stop maintaining two paths — demonstrated; the auto path
    picks the measured winner). Never fatal; BENCH_MOMENTS=0 skips."""
    if not knobs.get("BENCH_MOMENTS"):
        return {}
    try:
        from keystone_tpu.ops.pallas.moments import (
            gmm_moments_sep,
            gmm_moments_xla,
        )

        n, d, k = 10_000_000, 64, 256
        x = jax.random.normal(jax.random.key(0), (n, d), jnp.float32)
        means = jax.random.normal(jax.random.key(1), (k, d), jnp.float32)
        var = jnp.ones((k, d), jnp.float32) * 0.5
        w = jnp.ones((k,), jnp.float32) / k

        def timed(f):
            def sync(o):
                return float(o[0].sum())

            sync(f(x, means, var, w))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                sync(f(x, means, var, w))
                best = min(best, time.perf_counter() - t0)
            return round(best, 3)

        out = {"moments_design_point_pallas_s": timed(jax.jit(gmm_moments_sep))}

        def xla_scan(x, m, v, w):
            # chunked accumulation identical to gmm_moments_auto's off-TPU
            # arm, INCLUDING the ragged tail chunk
            from keystone_tpu.ops.pallas.moments import _CHUNK_ROWS

            center = jnp.mean(x, axis=0)
            num_full = x.shape[0] // _CHUNK_ROWS

            def step(acc, i):
                xi = jax.lax.dynamic_slice_in_dim(x, i * _CHUNK_ROWS, _CHUNK_ROWS, 0)
                qs, qx, qx2 = gmm_moments_xla(xi, m, v, w, None, center)
                return (acc[0] + qs, acc[1] + qx, acc[2] + qx2), None

            init = (jnp.zeros((k,)), jnp.zeros((k, d)), jnp.zeros((k, d)))
            acc, _ = jax.lax.scan(step, init, jnp.arange(num_full))
            tail = x.shape[0] - num_full * _CHUNK_ROWS
            if tail:
                qs, qx, qx2 = gmm_moments_xla(
                    x[num_full * _CHUNK_ROWS :], m, v, w, None, center
                )
                acc = (acc[0] + qs, acc[1] + qx, acc[2] + qx2)
            return acc

        out["moments_design_point_xla_scan_s"] = timed(jax.jit(xla_scan))
        return out
    except Exception as e:
        print(f"moments design-point bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def _try_flagship_stage_breakdown():
    """Per-stage device seconds + achieved GFLOPs for the flagship regime
    (VERDICT r3 weak #2: 'you cannot push what you don't attribute').

    One extra flagship run under ``KEYSTONE_SYNC_TIMERS=1`` (hard device
    barriers at every Timer exit — honest per-stage device time, NOT part
    of the headline async measurement, whose row stays separate). FLOP
    counts are the analytic per-stage formulas at the flagship dims;
    'achieved' = formula / barriered seconds, so cross-stage overlap that
    the async run enjoys is deliberately absent here. BENCH_STAGES=0 skips.
    """
    if not knobs.get("BENCH_STAGES"):
        return {}
    try:
        prev = knobs.get_raw("KEYSTONE_SYNC_TIMERS")
        os.environ["KEYSTONE_SYNC_TIMERS"] = "1"
        try:
            from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
                flagship_config,
                run as run_flagship,
            )
            from keystone_tpu.utils import Timer

            cfg = flagship_config()
            run_flagship(cfg)  # warm the caches under this process
            Timer.reset()
            run_flagship(cfg)
            reg = {k: s["total"] for k, s in Timer.summary().items()}
        finally:
            if prev is None:
                os.environ.pop("KEYSTONE_SYNC_TIMERS", None)
            else:
                os.environ["KEYSTONE_SYNC_TIMERS"] = prev

        # flagship dims (flagship_config/BASELINE.md)
        n, nd_s, nd_l, d, k = 102400, 425, 64, 64, 256
        bs, C, blocks, groups_s, groups_l = 4096, 1000, 16, 4, 4
        nc1 = n // C + 1
        n_test = 5120

        # posteriors: 2 matmuls (x, x²) of (n·nd, d)@(d, k); moments: 2
        # einsums over the group's 128 centers — per group, per branch
        fv_group = lambda nd: 2 * 2 * n * nd * d * k + 2 * 2 * n * nd * 128 * d
        flops = {
            "solve.featurize": groups_s * fv_group(nd_s) + groups_l * fv_group(nd_l),
            # gram + cross term, per block
            "solve.pop_stats": blocks * (2 * n * bs * bs + 2 * n * bs * C),
            # Woodbury: T = V@B⁻¹ dominates (2·nc1·bs² per class)
            "solve.class_solves": blocks * C * 2 * nc1 * bs * bs,
            # R update: Xb@dW per block
            "solve.residual": blocks * 2 * n * bs * C,
        }
        keys = {
            "solve.featurize": "weighted_bcd.featurize",
            "solve.pop_stats": "weighted_bcd.pop_stats",
            "solve.class_solves": "weighted_bcd.class_solves",
            "solve.residual": "weighted_bcd.residual_update",
        }
        out = {}
        for stage, t_key in keys.items():
            secs = reg.get(t_key)
            if not secs:
                continue
            out[f"stage_{stage}_s"] = round(secs, 2)
            out[f"stage_{stage}_gflops"] = round(flops[stage] / secs / 1e9, 1)
        for extra, t_key in (
            ("stage_extract_chunks_s", "streaming.reduce.extract_chunks"),
            ("stage_l1_norms_s", "streaming.reduce.l1_norms"),
            ("stage_base_inverse_s", "weighted_bcd.base_inverse"),
            ("stage_fit_pca_gmm_s", "streaming.fit_pca_gmm"),
            # seconds only: eval.predict is test-side re-featurization +
            # the final gemm — a gemm-only FLOP count would misstate its
            # achieved rate by >10x (the featurize posterior pass dominates)
            ("stage_eval.predict_s", "eval.predict"),
        ):
            if reg.get(t_key):
                out[extra] = round(reg[t_key], 2)
        # extraction throughput: bytes of reduced descriptors produced
        # (both branches, train+test) per extract second — the HBM-side
        # rate of the phase (images are generated on device)
        ext = reg.get("streaming.reduce.extract_chunks")
        if ext:
            desc_bytes = (n + n_test) * (nd_s + nd_l) * d * 2  # bf16 out
            out["stage_extract_descriptor_gb_s"] = round(
                desc_bytes / ext / 1e9, 2
            )
        return out
    except Exception as e:
        print(f"flagship stage breakdown failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def _try_cache_rows():
    """Cached-vs-cold whole-pipeline evidence for the intermediate cache
    (``core.cache``): the imagenet small in-core pipeline runs twice under
    one content-addressed cache — the first run populates it (featurization
    + FV chains memoize per stage prefix), the second hits everywhere, so
    the delta IS the re-featurization the cache eliminates. Compile warmth
    is established by an uncached run first, so the cold row measures
    compute, not XLA. Never fatal; BENCH_CACHED=0 skips."""
    if not knobs.get("BENCH_CACHED"):
        return {}
    prev_flag = knobs.get_raw("KEYSTONE_EVAL_CACHED_TIMING")
    try:
        from keystone_tpu.core.cache import IntermediateCache, use_cache
        from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
            run as run_inet,
            small_config,
        )

        cfg = small_config()
        run_inet(cfg)  # compile-warm, uncached
        out = {}
        # the cold/cached eval double-predict is bench-only instrumentation;
        # the pipelines gate it on this flag so ordinary cache-enabled runs
        # never pay a second predict
        os.environ["KEYSTONE_EVAL_CACHED_TIMING"] = "1"
        with use_cache(IntermediateCache(
            device_bytes=2 << 30, host_bytes=6 << 30
        )) as cache:
            t0 = time.perf_counter()
            r_cold = run_inet(cfg)
            out["imagenet_small_cache_cold_s"] = round(
                time.perf_counter() - t0, 3
            )
            t0 = time.perf_counter()
            r_warm = run_inet(cfg)
            out["imagenet_small_cache_warm_s"] = round(
                time.perf_counter() - t0, 3
            )
            # correctness rides the row: a cache hit must be bit-identical
            if r_warm["test_top5_error"] != r_cold["test_top5_error"]:
                raise RuntimeError(
                    f"cached rerun changed quality: "
                    f"{r_cold['test_top5_error']} -> "
                    f"{r_warm['test_top5_error']}"
                )
            out["imagenet_small_cache_speedup"] = round(
                out["imagenet_small_cache_cold_s"]
                / max(out["imagenet_small_cache_warm_s"], 1e-9), 2,
            )
            s = cache.stats
            out["imagenet_small_cache_hits"] = s.hits
            out["imagenet_small_cache_computes"] = s.computes
        return out
    except Exception as e:
        print(f"cache rows failed: {type(e).__name__}: {e}", file=sys.stderr)
        return {}
    finally:
        if prev_flag is None:
            os.environ.pop("KEYSTONE_EVAL_CACHED_TIMING", None)
        else:
            os.environ["KEYSTONE_EVAL_CACHED_TIMING"] = prev_flag


def _try_prefetch_rows():
    """Prefetch-on/off evidence for the double-buffered block feed
    (``core.prefetch``): the imagenet small STREAMING pipeline (block
    solver + grouped FV featurization — the paths that consume
    ``prefetch_map``) warm-timed with KEYSTONE_PREFETCH=1 vs 0. Results
    are bit-identical by construction; only the overlap differs. Never
    fatal; BENCH_PREFETCH=0 skips."""
    if not knobs.get("BENCH_PREFETCH"):
        return {}
    prev = knobs.get_raw("KEYSTONE_PREFETCH")
    try:
        from keystone_tpu.core.cache import use_cache
        from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
            run as run_inet,
            small_config,
        )

        # block_size 1024 gives each branch 2 FV blocks (vocab 16 × 64-dim
        # PCA) so the streaming solver actually loops; the default 4096
        # would round the branch to a single block and hide the feed.
        cfg = small_config(
            streaming=True, block_size=1024, extract_chunk=512,
            sample_images=1024, fv_row_chunk=512,
        )
        out = {}
        # suppress any ambient KEYSTONE_CACHE env cache: with memoization
        # active every timed rep would return stored featurizations and the
        # prefetch on/off delta would measure cache hits, not overlap
        with use_cache(None):
            for flag, key in (("1", "imagenet_small_streaming_prefetch_on_s"),
                              ("0", "imagenet_small_streaming_prefetch_off_s")):
                os.environ["KEYSTONE_PREFETCH"] = flag
                run_inet(cfg)  # compile-warm under this flag
                med, lo, hi, contended = _warm_stats(lambda: run_inet(cfg))
                out[key] = med
                out[key + "_min"] = lo
                out[key + "_max"] = hi
                out[key + "_contended"] = contended
        return out
    except Exception as e:
        print(f"prefetch rows failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}
    finally:
        if prev is None:
            os.environ.pop("KEYSTONE_PREFETCH", None)
        else:
            os.environ["KEYSTONE_PREFETCH"] = prev


def _try_telemetry_rows(config) -> dict:
    """Structured-telemetry evidence (``keystone_tpu/telemetry``): ONE extra
    primary-pipeline run under the span tracer, then the full registry +
    span dump + Chrome trace goes to ``bench_telemetry.json``
    (``BENCH_TELEMETRY_PATH`` overrides; ``keystone-tpu telemetry-report``
    renders it) and the compact line carries ``telemetry_*`` headcounts —
    so a bench artifact now SHOWS which overlap paths engaged vs fell back,
    per-tier cache traffic, prefetch stalls, and per-stage spans, instead
    of implying them. Traced runs sync per span, so this row is diagnostics,
    never the headline timing. BENCH_TELEMETRY=0 skips."""
    if not knobs.get("BENCH_TELEMETRY"):
        return {}
    try:
        from keystone_tpu import telemetry
        from keystone_tpu.pipelines.mnist_random_fft import run

        telemetry.reset()
        # The overlap/schedule counters fire at TRACE time (inside
        # shard_map/jit bodies); the primary section already compiled every
        # program, so without dropping the in-memory jit cache the traced
        # rerun would be a cache hit and the artifact would report zero
        # engagement for schedules that really ran. The persistent XLA
        # cache (BENCH_XLA_CACHE) keeps the re-lowering cheap.
        jax.clear_caches()
        with telemetry.use_tracing(True):
            run(config)
        reg = telemetry.get_registry()
        metrics = reg.as_dict()
        spans = telemetry.get_tracer().spans_as_dicts()
        artifact = {
            "metrics": metrics,
            "spans": spans,
            "chrome_trace": telemetry.get_tracer().chrome_trace(),
        }
        path = knobs.get_raw("BENCH_TELEMETRY_PATH") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "bench_telemetry.json"
        )
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(artifact, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return {
            "telemetry_file": os.path.basename(path),
            "telemetry_spans": len(spans),
            "telemetry_counters": len(metrics["counters"]),
            "telemetry_timer_stages": sum(
                1 for k in metrics["histograms"] if k.startswith("timer.")
            ),
            "telemetry_overlap_engaged": int(
                reg.sum_counters("overlap.engaged")
            ),
            "telemetry_overlap_fallbacks": int(
                reg.sum_counters("overlap.fallback")
            ),
            "telemetry_prefetch_stall_s": round(
                reg.get_counter("prefetch.stall_s"), 3
            ),
        }
    except Exception as e:
        print(f"telemetry rows failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def _try_lint_rows() -> dict:
    """Static-analysis hygiene row (``keystone_tpu/analysis``): run the
    R1-R5 pass over the package + bench + scripts and record the finding
    counts, so the bench trail shows hygiene over time next to the perf
    numbers. ``lint_findings_total`` counts everything surfaced (new +
    baselined — the debt), ``lint_new`` what would fail ``make lint``.
    Pure-AST, no device work: milliseconds. BENCH_LINT=0 skips."""
    if not knobs.get("BENCH_LINT"):
        return {}
    try:
        from keystone_tpu.analysis import run_lint
        from keystone_tpu.analysis.cli import DEFAULT_BASELINE, default_paths

        root = os.path.dirname(os.path.abspath(__file__))
        baseline = os.path.join(root, DEFAULT_BASELINE)
        result = run_lint(
            root, default_paths(root),
            baseline_path=baseline if os.path.exists(baseline) else None,
        )
        return {
            "lint_findings_total": result.total,
            "lint_new": len(result.findings),
            "lint_suppressed": result.suppressed,
            "lint_files": result.files,
        }
    except Exception as e:
        print(f"lint rows failed: {type(e).__name__}: {e}", file=sys.stderr)
        return {}


def _try_check_rows() -> dict:
    """Pipeline-contract hygiene row (``keystone_tpu/analysis/check.py``):
    propagate (shape, dtype, PartitionSpec) through the registered
    pipeline graphs and record the C1-C5 finding counts — the graph-level
    complement of the lint (source) and audit (HLO) rows.
    ``check_findings_total`` counts everything surfaced (new + baselined),
    ``check_new`` what would fail ``make check``. Abstract eval only — no
    data, no compiles: a couple of seconds. BENCH_CHECK=0 skips."""
    if not knobs.get("BENCH_CHECK"):
        return {}
    try:
        from keystone_tpu.analysis.check import (
            DEFAULT_CHECK_BASELINE,
            run_check,
        )

        root = os.path.dirname(os.path.abspath(__file__))
        baseline = os.path.join(root, DEFAULT_CHECK_BASELINE)
        result = run_check(
            baseline_path=baseline if os.path.exists(baseline) else None,
            root=root,
        )
        return {
            "check_findings_total": result.total,
            "check_new": len(result.findings),
            "check_suppressed": result.suppressed,
            "check_targets": len(result.targets),
            "check_errors": len(result.errors) or None,
        }
    except Exception as e:
        print(f"check rows failed: {type(e).__name__}: {e}", file=sys.stderr)
        return {"check_findings_total": None}


def _try_race_rows() -> dict:
    """Lock-discipline hygiene row (``keystone_tpu/analysis/
    concurrency.py``): sweep the package with rules T1-T5 over the
    lockgraph model and record the finding counts — the concurrency
    complement of the lint (source) and check (graph) rows.
    ``race_findings_total`` counts everything surfaced (new + baselined),
    ``race_new`` what would fail ``make race``. Pure AST walk — no
    backend, no execution: ~2 s. BENCH_RACE=0 skips."""
    if not knobs.get("BENCH_RACE"):
        return {}
    try:
        from keystone_tpu.analysis.concurrency import (
            DEFAULT_RACE_BASELINE,
            default_paths,
            run_race,
        )

        root = os.path.dirname(os.path.abspath(__file__))
        baseline = os.path.join(root, DEFAULT_RACE_BASELINE)
        result = run_race(
            root,
            default_paths(root),
            baseline_path=baseline if os.path.exists(baseline) else None,
        )
        return {
            "race_findings_total": result.total,
            "race_new": len(result.findings),
            "race_suppressed": result.suppressed,
            "race_files": result.files,
            "race_errors": len(result.errors) or None,
        }
    except Exception as e:
        print(f"race rows failed: {type(e).__name__}: {e}", file=sys.stderr)
        return {"race_findings_total": None}


def _try_audit_rows() -> dict:
    """IR-audit hygiene row (``keystone_tpu/analysis/ir_audit.py``): lower
    the registered entry points the live topology can place and record the
    A1-A5 finding counts next to the perf numbers — the compiled-program
    complement of the lint row. ``audit_findings_total`` counts everything
    surfaced (new + baselined), ``audit_new`` what would fail ``make
    audit``. A few lowers + compiles (no execution): seconds.
    BENCH_AUDIT=0 skips."""
    if not knobs.get("BENCH_AUDIT"):
        return {}
    try:
        from keystone_tpu.analysis.ir_audit import (
            DEFAULT_IR_BASELINE,
            run_audit,
        )

        root = os.path.dirname(os.path.abspath(__file__))
        baseline = os.path.join(root, DEFAULT_IR_BASELINE)
        result = run_audit(
            baseline_path=baseline if os.path.exists(baseline) else None,
        )
        return {
            "audit_findings_total": result.total,
            "audit_new": len(result.findings),
            "audit_suppressed": result.suppressed,
            "audit_targets": len(result.targets) - len(result.skipped),
            # entries the topology could not place (e.g. collective
            # entries on a 1-device backend) — honesty key: a clean audit
            # that skipped half its targets is not a clean audit
            "audit_targets_skipped": len(result.skipped) or None,
            "audit_errors": len(result.errors) or None,
        }
    except Exception as e:
        print(f"audit rows failed: {type(e).__name__}: {e}", file=sys.stderr)
        return {"audit_findings_total": None}


def _try_plan_rows() -> dict:
    """Whole-pipeline-optimizer evidence rows (``core/plan.py``): plan the
    flagship descriptor-reduction DAG + weighted-solver block site in
    estimate mode under the HBM budget and record the decisions — chosen
    block size, segment/cache counts, estimated peak vs the budget, and
    the repeat-plan count (MUST be zero: the content-fingerprinted plan
    memo serves the second call). Pre-dispatch shape analysis + one
    lowering — no pipeline runs. BENCH_PLAN=0 skips."""
    if not knobs.get("BENCH_PLAN"):
        return {}
    try:
        from keystone_tpu.core import plan
        from keystone_tpu.telemetry import get_registry

        pipe, sample, sites = plan._TARGETS["imagenet"](_SMOKE)
        budget = plan.hbm_budget_bytes() or (16 << 30)  # v5e-class default
        reg = get_registry()

        def build():
            return plan.plan_pipeline(
                pipe, sample, mode="estimate", budget_bytes=budget,
                block_sites=sites,
            )

        p = build()
        computed_before = reg.get_counter("plan.computed")
        p = build()  # repeat: must be served from the plan memo
        replans = reg.get_counter("plan.computed") - computed_before
        out = {
            "plan_block_size": p.block_sizes.get("imagenet.weighted_solver"),
            "plan_segments": p.num_segments,
            "plan_cached_stages": len(p.cached_stages),
            "plan_cache_tiers": sorted(
                {s.cache_tier for s in p.cached_stages}
            ),
            "plan_sharding_boundary": next(
                (s.name for s in p.stages if s.sharding == "model"), None
            ),
            "plan_est_peak_hbm_gb": round(
                p.est_peak_hbm_bytes / (1 << 30), 3
            ),
            "plan_hbm_budget_gb": round(budget / (1 << 30), 3),
            "plan_fits": p.fits,
            "plan_bounded": p.bounded,
            "plan_replans": int(replans),
        }
        # NOTE deliberately absent: a plan_measured_peak_hbm row. The
        # process-wide peak_bytes_in_use here would reflect every earlier
        # in-process bench section, not the planned configuration (which
        # this section never runs) — the estimated-vs-measured comparison
        # belongs to a dedicated fresh-process flagship run (ROADMAP).
        return out
    except Exception as e:
        print(f"plan rows failed: {type(e).__name__}: {e}", file=sys.stderr)
        return {"plan_block_size": None}


def _try_precision_rows() -> dict:
    """Precision-tier evidence rows (``KEYSTONE_PRECISION_TIER``, PR 11):
    the bf16-storage/f32-accumulate gram and sketch rungs against their f32
    twins, at the SAME shape under the SAME latency-cancelled protocol —
    and every speed key PAIRED with a ``*_vs_f32_error_delta`` key, so a
    tier win can never ratchet without its accuracy cost on record.

    Honesty keys: ``precision_backend`` names the backend the pair ran on,
    and ``precision_{f32,bf16}_read_gbs`` record the measured streaming
    read bandwidth of each storage dtype on this host — the bf16 rung's
    entire value proposition is halved memory traffic, so whether 16-bit
    loads are fast here (native on TPU; scalarized on some CPU stacks) is
    THE context the pair must carry. A host whose bf16 read path is slower
    than f32 will honestly show the bf16 rung losing; the TPU pod run is
    where the ratchet bites (ROADMAP pod ladder). BENCH_PRECISION=0
    skips."""
    if not knobs.get("BENCH_PRECISION"):
        return {}
    try:
        from keystone_tpu.linalg.sketch import sketch_rows, sketched_lstsq_solve
        from keystone_tpu.linalg.solvers import hdot

        n = 4096 if _SMOKE else 16384
        d = 256 if _SMOKE else 1024
        c = 10
        reps = 2 if _SMOKE else 4
        cg_iters = 2 if _SMOKE else 8
        key = jax.random.key(0)
        A = jax.random.normal(key, (n, d), jnp.float32)
        b = jax.random.normal(jax.random.key(1), (n, c), jnp.float32)
        A16 = A.astype(jnp.bfloat16)  # the bf16-STORED operand
        jax.block_until_ready((A, b, A16))

        gram_f32 = jax.jit(lambda X: hdot(X.T, X, "high"))
        gram_bf16 = jax.jit(lambda X: hdot(X.T, X, tier="bf16"))

        def lat_cancelled(fn, arg, flops):
            def chain(k):
                outs = [fn(arg) for _ in range(k)]
                jax.block_until_ready(outs[-1])

            chain(1)  # warm the compile
            t0 = time.perf_counter()
            chain(1)
            t1 = time.perf_counter()
            chain(1 + reps)
            t2 = time.perf_counter()
            dt = ((t2 - t1) - (t1 - t0)) / reps
            if dt <= 0:
                dt = (t2 - t1) / (1 + reps)
            return flops / dt / 1e9

        gram_flops = 2.0 * n * d * d
        out = {
            "precision_backend": jax.default_backend(),
            "gram_f32_gflops": round(lat_cancelled(gram_f32, A, gram_flops), 1),
            "gram_bf16_gflops": round(
                lat_cancelled(gram_bf16, A16, gram_flops), 1
            ),
        }
        import numpy as np

        G32 = np.asarray(gram_f32(A), np.float64)
        G16 = np.asarray(gram_bf16(A16), np.float64)
        out["gram_bf16_vs_f32_error_delta"] = float(
            np.linalg.norm(G16 - G32) / max(np.linalg.norm(G32), 1e-30)
        )

        # streaming-read bandwidth of each storage dtype (the honesty probe)
        probe = jax.random.normal(jax.random.key(2), (1 << 24,), jnp.float32)
        probe16 = probe.astype(jnp.bfloat16)
        jax.block_until_ready((probe, probe16))
        rsum = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))
        for label, arr, bytes_per in (("f32", probe, 4), ("bf16", probe16, 2)):
            jax.block_until_ready(rsum(arr))
            t0 = time.perf_counter()
            jax.block_until_ready(rsum(arr))
            dt = time.perf_counter() - t0
            out[f"precision_{label}_read_gbs"] = round(
                arr.shape[0] * bytes_per / max(dt, 1e-9) / 1e9, 2
            )

        # sketch rung: tier pair of the randomized solver (fixed CG work)
        m = sketch_rows(n, d)
        sk_flops = (n * (d + c) + 2.0 * (m + d) * d * d
                    + cg_iters * (4.0 * n * d * c + 2.0 * d * d * c))

        def sk(tier):
            def run(k):
                ws = [sketched_lstsq_solve(A, b, lam=1.0 + i, tol=0.0,
                                           max_iters=cg_iters, tier=tier)
                      for i in range(k)]
                jax.block_until_ready(ws[-1])
                return ws[-1]

            run(1)
            t0 = time.perf_counter()
            run(1)
            t1 = time.perf_counter()
            w = run(1 + reps)
            t2 = time.perf_counter()
            dt = ((t2 - t1) - (t1 - t0)) / reps
            if dt <= 0:
                dt = (t2 - t1) / (1 + reps)
            return sk_flops / dt / 1e9, np.asarray(w, np.float64)

        g32, w32 = sk("f32")
        g16, w16 = sk("bf16")
        out["sketch_f32_gflops"] = round(g32, 1)
        out["sketch_bf16_gflops"] = round(g16, 1)
        # solution delta, not sketch delta: what the f32 CG cleanup leaves
        # behind — the number the error-envelope tests bound
        out["sketch_bf16_vs_f32_error_delta"] = float(
            np.linalg.norm(w16 - w32) / max(np.linalg.norm(w32), 1e-30)
        )
        return out
    except Exception as e:
        print(f"precision rows failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {"gram_bf16_gflops": None}


class _BenchSlice:
    """Streaming feature node for the fault-recovery section: one column
    block of the raw features (module-level so the section's setup mirrors
    the production fit_streaming call shape)."""

    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    def apply_batch(self, raw):
        return raw["x"][:, self.lo : self.hi]


def _try_fault_rows() -> dict:
    """Fault-recovery evidence rows (``utils/faults.py`` + the mesh-portable
    checkpoint path, PR 12): one streaming weighted fit run clean, then the
    SAME fit killed mid-schedule by a deterministic injected device error
    and resumed from its mid-fit checkpoint through the production
    ``fit_streaming_elastic`` retry loop. Emits ``resume_overhead_s`` (the
    price of the crash: kill-and-resume wall clock minus the uninterrupted
    fit), ``retry_attempts_total``, and the measured
    ``checkpoint_save_s`` / ``checkpoint_load_s`` (from the telemetry
    histograms the checkpoint writer/reader feed). BENCH_FAULTS=0 skips."""
    if not knobs.get("BENCH_FAULTS"):
        return {}
    try:
        import tempfile

        import numpy as np

        from keystone_tpu.learning.block_weighted import (
            BlockWeightedLeastSquaresEstimator,
        )
        from keystone_tpu.telemetry import get_registry
        from keystone_tpu.utils import faults, fit_streaming_elastic

        n = 512 if _SMOKE else 8192
        d = 64 if _SMOKE else 1024
        c = 8
        bs = d // 8  # 8 blocks: room for a mid-schedule kill
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        lbl = jnp.asarray(
            np.eye(c, dtype=np.float32)[np.arange(n) % c] * 2.0 - 1.0
        )
        nodes = [_BenchSlice(k * bs, (k + 1) * bs) for k in range(d // bs)]
        est = BlockWeightedLeastSquaresEstimator(bs, 1, 0.1, 0.25)
        raw = {"x": x}

        def run_clean():
            m = est.fit_streaming(nodes, raw, lbl)
            jax.block_until_ready(m.w)

        run_clean()  # warm the compile so both timed runs are steady-state
        t0 = time.perf_counter()
        run_clean()
        base_s = time.perf_counter() - t0

        reg = get_registry()
        attempts0 = reg.get_counter("retry.attempt")

        def hist_sum(name):
            h = reg.get_histogram(name)
            return (h or {}).get("sum") or 0.0

        save0, load0 = hist_sum("checkpoint.save_s"), hist_sum(
            "checkpoint.load_s"
        )
        ckpt = os.path.join(
            tempfile.mkdtemp(prefix="bench_faults_"), "fit.ckpt"
        )
        faults.reset()
        os.environ["KEYSTONE_FAULTS"] = f"block@{len(nodes) // 2}:xla"
        try:
            t0 = time.perf_counter()
            m = fit_streaming_elastic(
                est, nodes, raw, lbl,
                checkpoint_path=ckpt, checkpoint_every=1,
                retries=2, backoff_s=0.0,
            )
            jax.block_until_ready(m.w)
            resumed_s = time.perf_counter() - t0
        finally:
            os.environ.pop("KEYSTONE_FAULTS", None)
            faults.reset()
        return {
            "resume_overhead_s": round(max(resumed_s - base_s, 0.0), 3),
            "fault_fit_base_s": round(base_s, 3),
            "fault_fit_resumed_s": round(resumed_s, 3),
            "retry_attempts_total": int(
                reg.get_counter("retry.attempt") - attempts0
            ),
            # 6 digits: a smoke-size checkpoint loads in tens of
            # microseconds — 4 digits would round it to 0.0 and flake the
            # contract test's > 0 pin
            "checkpoint_save_s": round(
                hist_sum("checkpoint.save_s") - save0, 6
            ),
            "checkpoint_load_s": round(
                hist_sum("checkpoint.load_s") - load0, 6
            ),
        }
    except Exception as e:
        print(f"fault rows failed: {type(e).__name__}: {e}", file=sys.stderr)
        return {"resume_overhead_s": None}


def _try_health_rows() -> dict:
    """Numerical-health evidence rows (``utils/health.py``, PR 13): one
    streaming weighted fit run clean, then the SAME fit with a NaN block
    injected mid-schedule (``KEYSTONE_FAULTS`` numeric kind) under
    ``KEYSTONE_HEALTH=heal`` — the sentinels must trip, quarantine the
    poisoned block on device, and the escalation ladder must re-run it.
    Emits ``health_quarantined_total`` / ``health_escalations_total`` /
    ``health_healed_total`` (counter deltas over the injected fit) and
    ``health_heal_error_delta`` — the healed model's relative distance
    from the clean twin (the within-envelope acceptance evidence).
    BENCH_HEALTH=0 skips."""
    if not knobs.get("BENCH_HEALTH"):
        return {}
    try:
        import numpy as np

        from keystone_tpu.learning.block_weighted import (
            BlockWeightedLeastSquaresEstimator,
        )
        from keystone_tpu.telemetry import get_registry
        from keystone_tpu.utils import faults

        n = 512 if _SMOKE else 8192
        d = 64 if _SMOKE else 1024
        c = 8
        bs = d // 8  # 8 blocks: room for a mid-schedule poisoning
        rng = np.random.default_rng(11)
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        lbl = jnp.asarray(
            np.eye(c, dtype=np.float32)[np.arange(n) % c] * 2.0 - 1.0
        )
        nodes = [_BenchSlice(k * bs, (k + 1) * bs) for k in range(d // bs)]
        est = BlockWeightedLeastSquaresEstimator(bs, 1, 0.1, 0.25)
        raw = {"x": x}

        clean = est.fit_streaming(nodes, raw, lbl)
        jax.block_until_ready(clean.w)

        reg = get_registry()
        counter_sum = reg.counter_family_total

        os.environ["KEYSTONE_FAULTS"] = f"block@{len(nodes) // 2}:nan"
        os.environ["KEYSTONE_HEALTH"] = "heal"
        try:
            # untimed warm run: the guarded program variants + the heal
            # re-run path trace and compile here, so the timed row below
            # measures heal OVERHEAD, not jit (the same reason
            # _try_fault_rows warms its fit before timing)
            faults.reset()
            warm = est.fit_streaming(nodes, raw, lbl)
            jax.block_until_ready(warm.w)
            # counter baseline AFTER the warm run: the published deltas
            # cover exactly the timed fit
            base = {
                name: counter_sum(name)
                for name in (
                    "health.quarantined", "health.escalations",
                    "health.healed",
                )
            }
            faults.reset()
            t0 = time.perf_counter()
            healed = est.fit_streaming(nodes, raw, lbl)
            jax.block_until_ready(healed.w)
            healed_s = time.perf_counter() - t0
        finally:
            os.environ.pop("KEYSTONE_FAULTS", None)
            os.environ.pop("KEYSTONE_HEALTH", None)
            faults.reset()
        w_ref = np.asarray(clean.w, np.float64)
        w_heal = np.asarray(healed.w, np.float64)
        delta = float(
            np.linalg.norm(w_heal - w_ref)
            / max(np.linalg.norm(w_ref), 1e-30)
        )
        return {
            "health_quarantined_total": int(
                counter_sum("health.quarantined")
                - base["health.quarantined"]
            ),
            "health_escalations_total": int(
                counter_sum("health.escalations")
                - base["health.escalations"]
            ),
            "health_healed_total": int(
                counter_sum("health.healed") - base["health.healed"]
            ),
            "health_heal_error_delta": round(delta, 6),
            "health_heal_fit_s": round(healed_s, 3),
        }
    except Exception as e:
        print(f"health rows failed: {type(e).__name__}: {e}", file=sys.stderr)
        return {"health_quarantined_total": None}


def _make_ingest_tarset(root: str, num_tars: int, per_tar: int, hw: int,
                        num_classes: int = 4, progressive: bool = False
                        ) -> tuple:
    """Synthetic JPEG tar set + labels file under ``root`` (class-dir entry
    names, the ImageNet layout) — the workload for the ingest rows.
    ``progressive`` JPEGs decode with ~4x the compute per byte (multi-pass),
    the shape the overlap pair needs so the worker pool has CPU-bound work
    to hide behind the consumer's bandwidth-bound transfer+extract."""
    import io
    import tarfile

    import numpy as np
    from PIL import Image

    rng = np.random.default_rng(12)
    os.makedirs(root, exist_ok=True)
    protos = rng.uniform(0.2, 0.8, size=(num_classes, hw, hw, 3))
    for t in range(num_tars):
        with tarfile.open(os.path.join(root, f"part{t}.tar"), "w") as tf:
            for i in range(per_tar):
                c = (t * per_tar + i) % num_classes
                arr = np.clip(
                    protos[c] + 0.08 * rng.normal(size=(hw, hw, 3)), 0, 1
                )
                buf = io.BytesIO()
                Image.fromarray((arr * 255).astype(np.uint8)).save(
                    buf, "JPEG", quality=90, progressive=progressive
                )
                ti = tarfile.TarInfo(f"cls{c}/im_{t}_{i}.jpg")
                ti.size = buf.getbuffer().nbytes
                buf.seek(0)
                tf.addfile(ti, buf)
    labels = os.path.join(root, "labels.txt")
    with open(labels, "w") as f:
        for c in range(num_classes):
            f.write(f"cls{c} {c}\n")
    return root, labels


def _try_ingest_rows() -> dict:
    """Streaming-ingest evidence rows (``core/ingest.py``, the out-of-core
    tier): ``ingest_gbs`` (sustained decode GB/s of the worker pool into
    the buffer ring), the overlap pair ``ingest_overlap_{on,off}_s`` (the
    same synthetic tar set decoded+extracted overlapped vs strictly
    sequentially — on <= off is the latency-hiding claim), and the
    never-resident flagship fit (``fit_streaming_ingest`` over tar
    archives) with its honesty pair: ``ingest_raw_bytes`` (what the
    in-core path would have materialized) vs ``ingest_peak_host_bytes``
    (the ring this path actually held) plus the zero-recompile pin
    ``ingest_reduce_compiles``. BENCH_INGEST=0 skips."""
    if not knobs.get("BENCH_INGEST"):
        return {}
    try:
        import shutil
        import tempfile

        from keystone_tpu.core.ingest import StreamingTarIngest, stream_batches
        from keystone_tpu.telemetry import get_registry

        hw = 64 if _SMOKE else 96
        per_tar = 24 if _SMOKE else 128
        num_tars = 4
        batch = 16 if _SMOKE else 64
        # the overlap pair runs its own calibrated workload: progressive
        # 256^2 JPEGs whose multi-pass decode is COMPUTE-bound, so the
        # 2-worker pool genuinely parallelizes against the consumer's
        # bandwidth-bound transfer+extract (at baseline-JPEG decode speeds
        # the pair is a scheduler-noise coin flip on a 2-core host)
        ov_hw = 64 if _SMOKE else 256
        ov_per_tar = 24 if _SMOKE else 128
        ov_batch = 16 if _SMOKE else 64
        root = tempfile.mkdtemp(prefix="bench_ingest_")
        reg = get_registry()
        out: dict = {}
        try:
            data_dir, labels_path = _make_ingest_tarset(
                root, num_tars, per_tar, hw
            )
            ov_dir, _ = _make_ingest_tarset(
                os.path.join(root, "overlap"), num_tars, ov_per_tar, ov_hw,
                progressive=True,
            )
            ov_tars = sorted(
                os.path.join(ov_dir, f) for f in os.listdir(ov_dir)
                if f.endswith(".tar")
            )

            # sustained decode GB/s: stream everything, no consumer compute
            b0 = reg.get_counter("ingest.bytes")
            t0 = time.perf_counter()
            n_imgs = sum(
                n for _, _, n in stream_batches(
                    StreamingTarIngest(ov_tars, (ov_hw, ov_hw), ov_batch)
                )
            )
            dt = time.perf_counter() - t0
            out["ingest_gbs"] = round(
                (reg.get_counter("ingest.bytes") - b0) / dt / 1e9, 3
            )
            out["ingest_gbs_images"] = n_imgs

            # overlap pair: identical decode + extract work; ON overlaps
            # decode of batch t+1 (2-worker pool + run-ahead transfer)
            # with extract of batch t, OFF is strictly sequential (one
            # worker, one buffer, lease held across the extract so decode
            # cannot run ahead). The extract is deliberately LIGHT — the
            # overlap under test is worker decode vs consumer transfer,
            # and a heavy extract would fight the workers for cores.
            @jax.jit
            def _extract(x):
                y = x.reshape(x.shape[0], -1)
                w = jnp.ones((y.shape[1], 64), jnp.float32) / y.shape[1]
                return jnp.tanh(y @ w).sum()

            def overlapped() -> float:
                t0 = time.perf_counter()
                for arr, _, n in stream_batches(
                    StreamingTarIngest(ov_tars, (ov_hw, ov_hw), ov_batch,
                                       num_threads=2, num_buffers=3),
                    depth=1,
                ):
                    float(_extract(arr))
                return time.perf_counter() - t0

            def sequential() -> float:
                t0 = time.perf_counter()
                ing = StreamingTarIngest(
                    ov_tars, (ov_hw, ov_hw), ov_batch,
                    num_threads=1, num_buffers=1,
                )
                for b in ing.batches():
                    # the same copying transfer stream_batches performs
                    # (asarray can zero-copy and skew the pair)
                    arr = jnp.array(b.images)
                    float(_extract(arr))
                    b.release()
                return time.perf_counter() - t0

            overlapped()  # warm the extract compile out of both timings
            out["ingest_overlap_on_s"] = round(min(
                overlapped(), overlapped(), overlapped()
            ), 3)
            out["ingest_overlap_off_s"] = round(min(
                sequential(), sequential(), sequential()
            ), 3)

            # never-resident fit: dataset raw footprint must EXCEED the
            # ring this path holds (2 buffers pinned via the knob)
            from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
                ImageNetSiftLcsFVConfig,
                fit_streaming_ingest,
            )

            test_root = os.path.join(root, "test")
            test_dir, _ = _make_ingest_tarset(
                test_root, 1, per_tar, hw
            )
            os.environ["KEYSTONE_INGEST_BUFFERS"] = "2"
            try:
                t0 = time.perf_counter()
                res = fit_streaming_ingest(ImageNetSiftLcsFVConfig(
                    train_location=data_dir, train_labels=labels_path,
                    test_location=test_dir, test_labels=labels_path,
                    streaming=True, ingest=True, ingest_batch=batch,
                    image_hw=hw, vocab_size=4,
                    sift_pca_dim=16, lcs_pca_dim=16,
                    num_pca_samples=100000, num_gmm_samples=100000,
                    sample_images=2 * batch, fv_row_chunk=batch,
                    block_size=64, fv_cache_blocks=1,
                ))
                out["ingest_fit_s"] = round(time.perf_counter() - t0, 3)
            finally:
                os.environ.pop("KEYSTONE_INGEST_BUFFERS", None)
            out["ingest_raw_bytes"] = res["ingest_raw_bytes"]
            out["ingest_peak_host_bytes"] = res["ingest_peak_host_bytes"]
            out["ingest_never_resident"] = (
                res["ingest_raw_bytes"] > res["ingest_peak_host_bytes"]
            )
            out["ingest_reduce_compiles"] = res["ingest_reduce_compiles"]
            out["ingest_fit_top5_error"] = round(res["test_top5_error"], 2)
            return out
        finally:
            shutil.rmtree(root, ignore_errors=True)
    except Exception as e:
        print(f"ingest rows failed: {type(e).__name__}: {e}", file=sys.stderr)
        return {"ingest_gbs": None}


def _try_serve_rows() -> dict:
    """Serving-gateway evidence rows (``keystone_tpu/serve``, PR 14):
    sustained open-loop load on the flagship (MNIST random-FFT) predict
    path through the REAL gateway — compiled fixed-shape ladder, padded
    dispatch, admission + shed + breaker machinery all armed.  Emits the
    sustained row (``serve_sustained_qps`` / ``serve_p50_ms`` /
    ``serve_p99_ms`` / ``serve_shed_frac`` at an offered rate the SLO can
    hold) and a 3-point saturation curve (``serve_saturation``: offered
    QPS swept 0.25x/1x/4x the measured dispatch capacity — the knee where
    p99 blows through the SLO and shedding takes over is the graceful-
    degradation evidence).  The SLO is the ``KEYSTONE_SERVE_SLO_MS`` knob
    floored at 8x the measured single-item dispatch (``serve_slo_ms`` in
    the artifact), so the row stays meaningful on slow backends.
    BENCH_SERVE=0 skips."""
    if not knobs.get("BENCH_SERVE"):
        return {}
    gw = None
    try:
        import numpy as np

        from keystone_tpu.learning import BlockLeastSquaresEstimator
        from keystone_tpu.loaders.mnist import synthetic_mnist_device
        from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels
        from keystone_tpu.pipelines.mnist_random_fft import (
            MnistRandomFFTConfig,
            build_featurizer,
        )
        from keystone_tpu.serve import serve as serve_gateway

        rows = 512 if _SMOKE else 4096
        ladder = (1, 4) if _SMOKE else (1, 8, 32)
        dur_s = 0.5 if _SMOKE else 2.0

        cfg = MnistRandomFFTConfig(num_ffts=1, block_size=512, lam=10.0)
        feat = build_featurizer(cfg)[0]
        x, y = synthetic_mnist_device(rows, seed=7)
        model = BlockLeastSquaresEstimator(512, num_iter=1, lam=10.0).fit(
            feat(x), ClassLabelIndicatorsFromIntLabels(10)(y)
        )
        pipe = feat >> model
        spec = jax.ShapeDtypeStruct((int(x.shape[1]),), jnp.float32)
        items = np.asarray(x)

        # SLO: the knob, floored at 8x the measured single-item dispatch
        # so the row stays meaningful on slow backends
        probe = serve_gateway(pipe, item_spec=spec, shapes=ladder,
                              start=False)
        est_one = probe._estimate_ms(probe.default_model, 1)
        probe.close()
        slo_ms = max(float(knobs.get("KEYSTONE_SERVE_SLO_MS")),
                     8.0 * est_one)

        gw = serve_gateway(pipe, item_spec=spec, shapes=ladder,
                           slo_ms=slo_ms, queue_depth=64)
        size0 = gw.compile_cache_size()

        def drive(offered_qps: float) -> dict:
            interval = 1.0 / max(offered_qps, 1.0)
            pend, i = [], 0
            t0 = time.perf_counter()
            next_t = t0
            while True:
                now = time.perf_counter()
                if now - t0 >= dur_s:
                    break
                if now >= next_t:
                    pend.append(gw.submit(items[i % rows]))
                    i += 1
                    next_t += interval
                else:
                    time.sleep(min(next_t - now, 0.002))
            rs = [p.result(30) for p in pend]
            wall = time.perf_counter() - t0  # includes the drain
            lats = sorted(r.latency_ms for r in rs if r.ok)
            n_ok = len(lats)
            n_shed = sum(r.code == "shed" for r in rs)
            assert all(r.code in ("ok", "shed") for r in rs), (
                [r.code for r in rs if r.code not in ("ok", "shed")]
            )
            return {
                "offered_qps": round(offered_qps, 1),
                "qps": round(n_ok / wall, 1),
                "p50_ms": round(lats[n_ok // 2], 2) if lats else None,
                "p99_ms": round(
                    lats[min(n_ok - 1, int(0.99 * n_ok))], 2
                ) if lats else None,
                "shed_frac": round(n_shed / max(len(rs), 1), 3),
            }

        # EMPIRICAL capacity: an unpaced burst phase's achieved QPS is the
        # gateway's real coalesced throughput (per-shape dispatch
        # estimates ignore the coalesce window + submission overhead and
        # over-promise by orders of magnitude)
        capacity_qps = max(drive(1e6)["qps"], 1.0)
        sustained = drive(0.5 * capacity_qps)
        curve = [drive(f * capacity_qps) for f in (0.25, 1.0, 4.0)]
        assert gw.compile_cache_size() == size0, (
            "serve bench recompiled mid-load"
        )
        def _cr(v):
            # the compact emitter re-rounds floats (3 decimals under 10,
            # 1 above); store the pinned keys pre-rounded to the same rule
            # so compact == full holds exactly
            return None if v is None else round(v, 3 if abs(v) < 10 else 1)

        return {
            "serve_slo_ms": round(slo_ms, 1),
            "serve_sustained_qps": _cr(sustained["qps"]),
            "serve_p50_ms": _cr(sustained["p50_ms"]),
            "serve_p99_ms": _cr(sustained["p99_ms"]),
            "serve_shed_frac": _cr(sustained["shed_frac"]),
            "serve_saturation": curve,
        }
    except Exception as e:
        print(f"serve rows failed: {type(e).__name__}: {e}", file=sys.stderr)
        return {"serve_sustained_qps": None}
    finally:
        if gw is not None:
            gw.close(drain=False)


def _run_regime_subprocess(regime: str, fail_key: str,
                           timeout_s: int = None) -> dict:
    """One big-regime row via ``scripts/bench_regime.py`` in a fresh OS
    process (ordering-independence contract — see the call sites). Returns
    the regime's result dict, or ``{fail_key: None}`` so a crashed regime
    stays visible in the artifact instead of silently absent.

    ``timeout_s=None`` derates the subprocess timeout from the REMAINING
    bench budget (minus the finalize reserve) instead of a flat 3600 s per
    regime — three regimes at 3600 s each could otherwise eat 3 driver
    timeouts' worth of wall clock. A regime whose remaining budget is under
    the section floor is not started at all and recorded as an explicit
    ``<key>_skipped`` entry."""
    import subprocess

    if timeout_s is None:
        remaining = _budget_remaining() - _FINALIZE_RESERVE_S
        if remaining < _SECTION_FLOOR_S:
            print(
                f"{regime} regime skipped: {remaining:.0f}s of bench budget "
                f"left < floor {_SECTION_FLOOR_S:.0f}s",
                file=sys.stderr,
            )
            return {fail_key: None, f"{fail_key}_skipped": "budget"}
        timeout_s = min(3600.0, remaining)
    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts",
        "bench_regime.py",
    )
    try:
        proc = subprocess.run(
            [sys.executable, script, regime],
            capture_output=True, text=True, timeout=timeout_s,
        )
        if proc.stderr:
            sys.stderr.write(proc.stderr)
        lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
        # Forward every non-final stdout line to stderr: a hung or slow
        # regime's progress (pipeline timers, warnings) must be diagnosable
        # from the driver log instead of silently discarded. The LAST line
        # stays the JSON contract.
        for line in lines[:-1]:
            print(f"[{regime}] {line}", file=sys.stderr)
        if proc.returncode != 0 or not lines:
            raise RuntimeError(
                f"exit {proc.returncode}, "
                f"stdout tail: {proc.stdout[-300:]!r}"
            )
        return json.loads(lines[-1])
    except Exception as e:
        # a timed-out regime still surfaces whatever it printed before the
        # kill (TimeoutExpired carries the captured streams)
        for stream in (getattr(e, "stdout", None), getattr(e, "stderr", None)):
            if stream:
                if isinstance(stream, bytes):
                    stream = stream.decode(errors="replace")
                for line in stream.strip().splitlines():
                    print(f"[{regime}] {line}", file=sys.stderr)
        print(f"{regime} regime subprocess failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        res = {fail_key: None}
        if isinstance(e, subprocess.TimeoutExpired):
            # distinguishable from a crash: the derated timeout fired
            res[f"{fail_key}_skipped"] = "timeout"
        return res


def main():
    global _BUDGET_T0
    _BUDGET_T0 = time.monotonic()
    from keystone_tpu.pipelines.mnist_random_fft import MnistRandomFFTConfig, run

    config = MnistRandomFFTConfig(
        num_ffts=2 if _SMOKE else 4,
        block_size=512 if _SMOKE else 2048,
        lam=10.0,
        synthetic_train=2048 if _SMOKE else 60000,
        synthetic_test=512 if _SMOKE else 10000,
    )
    t0 = time.perf_counter()
    run(config)  # cold (compile)
    cold_s = time.perf_counter() - t0
    last: dict = {}
    med, lo, hi, contended = _warm_stats(lambda: last.update(run(config)))
    warm = last

    value = med
    anchor = _load_cpu_baseline()
    anchor_s = (anchor or {}).get("mnist_random_fft_cpu_warm_s")
    out = {
        "metric": "mnist_random_fft_fit_eval_wallclock",
        "value": round(value, 3),
        "unit": "s",
        # Speedup of 1 TPU v5e chip over the same pipeline on jax-CPU
        # (host_cores below — NOT the 64-core Spark north-star baseline).
        # Smoke runs use tiny shapes, so their ratio would be meaningless.
        "vs_baseline": round(anchor_s / value, 2)
        if anchor_s and not _SMOKE else None,
        "baseline_anchor": None if anchor is None else {
            "source": "scripts/cpu_baseline.py (same pipeline, jax-CPU)",
            "host_cores": anchor.get("host_cores"),
            "mnist_cpu_warm_s": anchor_s,
        },
        "value_min": lo,
        "value_max": hi,
        "contended": contended,
        "warm_reps": WARM_REPS,
        "cold_wallclock_s": round(cold_s, 3),
        "xla_cache_prewarmed": _CACHE_PREWARMED,
        "smoke": _SMOKE or None,
        "bench_budget_s": _BUDGET_S,
        "train_error_pct": round(warm["train_error"], 3),
        "test_error_pct": round(warm["test_error"], 3),
        "device": str(jax.devices()[0]),
    }
    _flush(out, "primary")
    # Telemetry evidence rides directly after the primary (one more run of
    # the SAME config under the span tracer): it must land even on runs
    # whose budget dies before the heavy regimes, so it gets a reduced
    # floor (a traced primary rerun, not a flagship section).
    if _budget_remaining() - _FINALIZE_RESERVE_S < 20.0:
        out["telemetry_skipped"] = "budget"
        print("bench section telemetry skipped: budget exhausted",
              file=sys.stderr)
    else:
        out.update(_try_telemetry_rows(config))
    _flush(out, "telemetry")
    # Static-analysis hygiene (milliseconds, no budget gate): the compact
    # line records lint_findings_total so a hygiene regression is visible
    # in the same trail as a perf regression.
    out.update(_try_lint_rows())
    _flush(out, "lint")
    # Pipeline-contract hygiene (abstract shape propagation over the
    # registered pipeline graphs — no data, no compiles): ~2 s of
    # eval_shape tracing, so the 20 s reduced floor is generous headroom,
    # not a heavy-section derate; the explicit budget-skip marker is the
    # section contract the tests pin.
    if _budget_remaining() - _FINALIZE_RESERVE_S < 20.0:
        out["check_skipped"] = "budget"
        print("bench section check skipped: budget exhausted",
              file=sys.stderr)
    else:
        out.update(_try_check_rows())
    _flush(out, "check")
    # Lock-discipline hygiene (AST sweep of the concurrent tier, rules
    # T1-T5): ~2 s of parsing, so the 20 s reduced floor is generous
    # headroom; the explicit budget-skip marker is the section contract
    # the tests pin.
    if _budget_remaining() - _FINALIZE_RESERVE_S < 20.0:
        out["race_skipped"] = "budget"
        print("bench section race skipped: budget exhausted",
              file=sys.stderr)
    else:
        out.update(_try_race_rows())
    _flush(out, "race")
    # IR-audit hygiene (lower + compile the registered entry points; no
    # execution): seconds, but not milliseconds — a reduced floor like
    # telemetry's, with the explicit budget-skip marker the section
    # contract pins.
    if _budget_remaining() - _FINALIZE_RESERVE_S < 20.0:
        out["audit_skipped"] = "budget"
        print("bench section audit skipped: budget exhausted",
              file=sys.stderr)
    else:
        out.update(_try_audit_rows())
    _flush(out, "audit")
    # Whole-pipeline-optimizer evidence (core/plan.py): shape analysis +
    # one lowering, but SIFT lowering on a cold process is not free — a
    # reduced floor like telemetry's, with the explicit budget-skip marker.
    if _budget_remaining() - _FINALIZE_RESERVE_S < 20.0:
        out["plan_skipped"] = "budget"
        print("bench section plan skipped: budget exhausted",
              file=sys.stderr)
    else:
        out.update(_try_plan_rows())
    _flush(out, "plan")
    # Precision-tier pair (bf16-storage/f32-accumulate vs f32 twins, each
    # speed key paired with its error delta): in-process, small shapes — a
    # reduced floor like telemetry's, with the explicit budget-skip marker
    # the section contract pins.
    if _budget_remaining() - _FINALIZE_RESERVE_S < 20.0:
        out["precision_skipped"] = "budget"
        print("bench section precision skipped: budget exhausted",
              file=sys.stderr)
    else:
        out.update(_try_precision_rows())
    _flush(out, "precision")
    # Fault-recovery pair (inject -> crash -> checkpoint-resume through the
    # production retry loop): in-process, small shapes — a reduced floor
    # like telemetry's, with the explicit budget-skip marker the section
    # contract pins.
    if _budget_remaining() - _FINALIZE_RESERVE_S < 20.0:
        out["faults_skipped"] = "budget"
        print("bench section faults skipped: budget exhausted",
              file=sys.stderr)
    else:
        out.update(_try_fault_rows())
    _flush(out, "faults")
    # Numerical-health pair (inject a NaN block -> sentinels trip ->
    # quarantine + heal through the escalation ladder): in-process, small
    # shapes — a reduced floor like telemetry's, with the explicit
    # budget-skip marker the section contract pins.
    if _budget_remaining() - _FINALIZE_RESERVE_S < 20.0:
        out["health_skipped"] = "budget"
        print("bench section health skipped: budget exhausted",
              file=sys.stderr)
    else:
        out.update(_try_health_rows())
    _flush(out, "health")
    # Streaming-ingest section (core/ingest.py): sustained decode GB/s,
    # the overlap on/off pair, and the never-resident fit with its
    # raw-vs-peak honesty pair — in-process, small tar set, the same
    # reduced floor + explicit budget-skip marker the section contract
    # pins. The BENCH_INGEST=0 gate is checked BEFORE the floor so a
    # gated-off section emits neither rows nor a budget marker.
    if not knobs.get("BENCH_INGEST"):
        pass
    elif _budget_remaining() - _FINALIZE_RESERVE_S < 20.0:
        out["ingest_skipped"] = "budget"
        print("bench section ingest skipped: budget exhausted",
              file=sys.stderr)
    else:
        out.update(_try_ingest_rows())
    _flush(out, "ingest")
    # Solver GFLOPs ladder (exact BCD + randomized sketch rungs, overlap
    # on/off): a budget-derated SUBPROCESS regime since the sketch rung
    # landed. In-process it was the one heavy section whose runtime the
    # budget could not bound — the gate only checked the entry floor, so a
    # ladder that outran the remaining budget ate the driver's timeout
    # (run 5's rc=124). As a subprocess it inherits the same derated
    # timeout/skip treatment as every other big regime.
    out.update(
        _run_regime_subprocess(
            "solver_ladder", fail_key="solver_gflops_per_chip"
        )
    )
    _flush(out, "solver_gflops")
    # Sketch-vs-exact equal-test-error comparison (the acceptance row for
    # the randomized rung): configured at d=65536, derated to what the
    # backend's memory can actually hold (the artifact records the actual
    # d); subprocess + derated timeout like every big regime.
    if knobs.get("BENCH_SKETCH"):
        out.update(
            _run_regime_subprocess(
                "sketch_compare",
                fail_key="sketch_vs_exact_error_delta_d65536",
            )
        )
        _flush(out, "sketch_compare")
    # Serving-gateway section (keystone_tpu/serve): sustained QPS at the
    # SLO + the 3-point saturation curve through the real admission/shed/
    # breaker machinery. A budget-derated SUBPROCESS regime since the
    # fleet tier landed: the sweep's runtime scales with how hard the
    # shed/breaker machinery works on a contended host, and in-process
    # the budget could not bound it. The section keeps its REDUCED entry
    # floor (it is seconds-scale in smoke, where the default 60 s
    # subprocess floor would starve it under the contract test's budget —
    # which is also why it runs AFTER the solver ladder: a cold serve
    # subprocess costs an import+compile the in-process section never
    # paid, and the solver regimes' 60 s floor must not eat it), so the
    # gate lives here and the subprocess gets the remaining budget as an
    # explicit derated timeout. fail_key="serve" keeps the budget-skip
    # marker name (`serve_skipped`) the section contract pins; the stray
    # None row on failure is dropped by the emitters.
    _serve_budget = _budget_remaining() - _FINALIZE_RESERVE_S
    if _serve_budget < 20.0:
        out["serve_skipped"] = "budget"
        print("bench section serve skipped: budget exhausted",
              file=sys.stderr)
    else:
        out.update(_run_regime_subprocess(
            "serve", fail_key="serve", timeout_s=_serve_budget
        ))
    _flush(out, "serve")
    # Fleet section (pool -> front -> replicas): aggregate-QPS scaling
    # across replicated gateways at pinned p99 with zero steady-state
    # recompiles, plus the batched-front vs unbatched-baseline pair —
    # cross-PROCESS clients against per-replica sockets, so it only ever
    # runs as a subprocess regime (standard derated floor: replica
    # startup alone needs real headroom). BENCH_FLEET=0 skips (smoke
    # default).
    if knobs.get("BENCH_FLEET"):
        out.update(
            _run_regime_subprocess("fleet", fail_key="fleet_qps_scale")
        )
        _flush(out, "fleet")
    # Topology-aware overlap ladder (scripts/bench_regime.py solver_overlap):
    # tsqr_overlap_{on,off}_gflops + bcd_model_overlap_{on,off}_gflops in a
    # fresh process, timeout derated from the remaining budget like every
    # other regime. On the single driver chip the knobs fall back (parity
    # documents it); a >=4-chip run ratchets the measured delta.
    if knobs.get("BENCH_SOLVER_OVERLAP"):
        out.update(
            _run_regime_subprocess(
                "solver_overlap", fail_key="tsqr_overlap_on_gflops"
            )
        )
        _flush(out, "solver_overlap")
    # Extraction-kernel family (ops/pallas/extraction.py): Pallas-vs-XLA
    # GFLOPs for the fused SIFT binning and FV encode kernels, latency-
    # cancelled in a fresh process with the same derated-timeout/skip
    # treatment (PR-6 contract: exhaustion -> <key>_skipped, rc stays 0).
    if knobs.get("BENCH_EXTRACTION"):
        out.update(
            _run_regime_subprocess(
                "extraction_kernels", fail_key="sift_pallas_on_gflops"
            )
        )
        _flush(out, "extraction_kernels")
    # Big regimes (flagship / VOC-refdim / full-TIMIT) each run in a FRESH
    # OS process (scripts/bench_regime.py): round 4 measured the in-bench
    # flagship ~1.4x slower than the same code in a fresh process (20.1 s
    # vs 14.4-14.6 s, contended=False — process-lifetime allocator state,
    # not chip contention), and ordering the bench around it only dodged
    # the effect until the next reordering. Subprocess isolation makes the
    # rows ordering-independent by construction; the persistent XLA cache
    # keeps each fresh process's cold run cheap (BENCH_FLAGSHIP=0 etc. opt
    # out on cache-cold machines where the first-ever compile is ~6 min).
    # Timeouts are derated from the remaining bench budget; a regime that
    # no longer fits is recorded as <key>_skipped instead of started.
    if knobs.get("BENCH_FLAGSHIP"):
        out.update(
            _run_regime_subprocess(
                "flagship", fail_key="imagenet_refdim_streaming_warm_s"
            )
        )
        _flush(out, "flagship")
    if knobs.get("BENCH_VOC_REFDIM"):
        out.update(
            _run_regime_subprocess("voc_refdim", fail_key="voc_refdim_warm_s")
        )
        _flush(out, "voc_refdim")
    # in-process secondary sections: each gated on the remaining budget and
    # flushed on completion, so a driver kill mid-run costs at most ONE
    # section's rows — never the artifact. The start index round-robins
    # across runs (persisted cursor), so budget exhaustion partway down
    # the list rotates WHICH sections starve instead of always the tail.
    cursor, secondary = _rotate_secondary([
        ("extras", _try_extras),
        ("cache", _try_cache_rows),
        ("prefetch", _try_prefetch_rows),
        ("moments", _try_moments_design_point),
        ("constants", _try_device_count_constants),
        ("serve_latency", _try_serving_latency),
    ])
    out["bench_secondary_cursor"] = cursor
    out["bench_secondary_order"] = ",".join(n for n, _ in secondary)
    for name, fn in secondary:
        if _budget_remaining() - _FINALIZE_RESERVE_S < _SECTION_FLOOR_S:
            out[f"{name}_skipped"] = "budget"
            print(f"bench section {name} skipped: budget exhausted",
                  file=sys.stderr)
            _flush(out, name)
            continue
        out.update(fn())
        _flush(out, name)
    if knobs.get("BENCH_TIMIT_FULL"):
        out.update(
            _run_regime_subprocess(
                "timit_full", fail_key="timit_full_2p2m_warm_s"
            )
        )
        _flush(out, "timit_full")
        timit_full_cpu = (anchor or {}).get("timit_cpu_warm_extrapolated_s")
        if timit_full_cpu and out.get("timit_full_2p2m_warm_s"):
            # per-block-epoch costs scale linearly in rows (22x)
            out["timit_full_vs_cpu_baseline"] = round(
                timit_full_cpu * 22.0 / out["timit_full_2p2m_warm_s"], 1
            )
    flagship_cpu = (anchor or {}).get("imagenet_flagship_cpu_warm_extrapolated_s")
    flagship_tpu = out.get("imagenet_refdim_streaming_warm_s")
    if flagship_cpu and flagship_tpu:
        # CPU side is the published 4-point bilinear extrapolation
        # (scripts/cpu_baseline.py, imagenet_flagship_extrapolation)
        out["imagenet_flagship_vs_cpu_baseline"] = round(
            flagship_cpu / flagship_tpu, 1
        )
    timit_cpu = (anchor or {}).get("timit_cpu_warm_extrapolated_s")
    timit_tpu = out.get("timit_100k_50x4096_5ep_warm_s")
    if timit_cpu and timit_tpu:
        out["timit_vs_cpu_baseline"] = round(timit_cpu / timit_tpu, 1)
    for cpu_key, tpu_key, ratio_key in (
        ("newsgroups_cpu_warm_s", "newsgroups_20k_warm_s",
         "newsgroups_vs_cpu_baseline"),
        ("stupid_backoff_cpu_warm_s", "stupid_backoff_20k_warm_s",
         "stupid_backoff_vs_cpu_baseline"),
        ("voc_small_cpu_warm_s", "voc_small_warm_s",
         "voc_small_vs_cpu_baseline"),
        ("imagenet_small_cpu_warm_s", "imagenet_small_warm_s",
         "imagenet_small_vs_cpu_baseline"),
    ):
        cpu_s, tpu_s = (anchor or {}).get(cpu_key), out.get(tpu_key)
        if cpu_s and tpu_s:
            out[ratio_key] = round(cpu_s / tpu_s, 1)
    _emit(out)


# Compact-line key -> full-dict key. The driver captures only the trailing
# ~2,000 chars of stdout (BENCH_r04 came back "parsed": null because the
# single full-dict line outgrew that window and truncated from the FRONT,
# losing metric/value/flagship). Contract since r5: the FULL dict goes to
# bench_full.json (committed, human- and judge-readable); the LAST stdout
# line is this compact summary, asserted < 1500 chars so growth fails
# loudly instead of silently blinding the ratchet.
_COMPACT_KEYS = (
    # headline (names kept verbatim — the driver's schema)
    ("metric", "metric"), ("value", "value"), ("unit", "unit"),
    ("vs_baseline", "vs_baseline"),
    ("contended", "contended"),
    # structured-telemetry headcounts (full dump: bench_telemetry.json)
    ("telemetry_spans", "telemetry_spans"),
    ("telemetry_counters", "telemetry_counters"),
    ("telemetry_fallbacks", "telemetry_overlap_fallbacks"),
    # static-analysis hygiene (keystone_tpu/analysis; full counts in
    # bench_full.json)
    ("lint", "lint_findings_total"),
    # pipeline-contract hygiene (keystone_tpu/analysis/check.py; full
    # counts in bench_full.json)
    ("check", "check_findings_total"),
    # IR-audit hygiene (keystone_tpu/analysis/ir_audit.py; full counts in
    # bench_full.json)
    ("audit", "audit_findings_total"),
    # whole-pipeline optimizer decisions (core/plan.py; full table via
    # `keystone-tpu plan imagenet`)
    ("plan_bs", "plan_block_size"),
    ("plan_hbm", "plan_est_peak_hbm_gb"),
    ("plan_fits", "plan_fits"),
    ("plan_replans", "plan_replans"),
    # flagship regime
    ("fs", "imagenet_refdim_streaming_warm_s"),
    ("fs_cont", "imagenet_refdim_streaming_warm_s_contended"),
    ("fs_top5", "imagenet_refdim_top5_error_pct"),
    ("fs_ov", "imagenet_refdim_streaming_overlap_on_s"),
    # other proven regimes (warm seconds + contended flags)
    ("voc_ref", "voc_refdim_warm_s"),
    ("voc_ref_cont", "voc_refdim_warm_s_contended"),
    ("timit_full", "timit_full_2p2m_warm_s"),
    ("timit_full_cont", "timit_full_2p2m_warm_s_contended"),
    ("timit100k", "timit_100k_50x4096_5ep_warm_s"),
    ("cifar", "random_patch_cifar_50k_warm_s"),
    ("news", "newsgroups_20k_warm_s"),
    ("sbo", "stupid_backoff_20k_warm_s"),
    ("voc_sm", "voc_small_warm_s"),
    ("inet_sm", "imagenet_small_warm_s"),
    # intermediate-cache + prefetch evidence (core/cache.py, core/prefetch.py)
    ("cache_cold", "imagenet_small_cache_cold_s"),
    ("cache_warm", "imagenet_small_cache_warm_s"),
    ("cache_x", "imagenet_small_cache_speedup"),
    ("pf_on", "imagenet_small_streaming_prefetch_on_s"),
    ("pf_off", "imagenet_small_streaming_prefetch_off_s"),
    ("fs_pred_cold", "imagenet_refdim_predict_cold_s"),
    ("fs_pred_cached", "imagenet_refdim_predict_cached_s"),
    ("fs_pf_off", "imagenet_refdim_streaming_prefetch_off_s"),
    # flagship stage attribution (GFLOPs where a formula exists, else s)
    ("g_solver", "solver_gflops_per_chip"),
    ("g_solver_ov", "solver_gflops_per_chip_overlap"),
    # precision-tier pair (KEYSTONE_PRECISION_TIER): bf16 rungs + their
    # paired error deltas vs the f32 twins (honesty keys in bench_full)
    ("g_gram32", "gram_f32_gflops"),
    ("g_gram16", "gram_bf16_gflops"),
    ("gram16_err", "gram_bf16_vs_f32_error_delta"),
    ("g_sk16", "sketch_bf16_gflops"),
    ("sk16_err", "sketch_bf16_vs_f32_error_delta"),
    # fault-recovery evidence (utils/faults.py + mesh-portable
    # checkpoints): the price of a mid-schedule crash and the retry count
    # that paid it (full rows incl. checkpoint save/load in bench_full)
    ("resume_ovh", "resume_overhead_s"),
    ("retry_n", "retry_attempts_total"),
    # numerical-health evidence (utils/health.py): quarantine/escalation
    # counts from the injected-NaN heal run + the healed model's distance
    # from its clean twin (full rows in bench_full)
    ("health_q", "health_quarantined_total"),
    ("health_esc", "health_escalations_total"),
    ("health_err", "health_heal_error_delta"),
    # randomized sketch rung (linalg/sketch.py) + equal-test-error delta
    # vs the exact rung (configured d=65536; actual d in bench_full.json)
    ("g_sketch", "sketch_gflops_per_chip"),
    ("g_sketch_ov", "sketch_gflops_per_chip_overlap"),
    ("sk_err_d", "sketch_vs_exact_error_delta_d65536"),
    # topology-aware overlap ladder (scripts/bench_regime.py solver_overlap)
    ("g_tsqr", "tsqr_overlap_off_gflops"),
    ("g_tsqr_ov", "tsqr_overlap_on_gflops"),
    ("g_bcdm", "bcd_model_overlap_off_gflops"),
    ("g_bcdm_ov", "bcd_model_overlap_on_gflops"),
    # extraction-kernel family: fused Pallas vs XLA twin
    # (scripts/bench_regime.py extraction_kernels)
    ("g_sift_pl", "sift_pallas_on_gflops"),
    ("g_sift_xla", "sift_pallas_off_gflops"),
    ("g_fv_pl", "fv_encode_pallas_on_gflops"),
    ("g_fv_xla", "fv_encode_pallas_off_gflops"),
    ("s_feat", "stage_solve.featurize_s"),
    ("g_feat", "stage_solve.featurize_gflops"),
    ("g_pop", "stage_solve.pop_stats_gflops"),
    ("g_cls", "stage_solve.class_solves_gflops"),
    ("s_ext", "stage_extract_chunks_s"),
    ("ext_gbs", "stage_extract_descriptor_gb_s"),
    # streaming ingest (core/ingest.py): sustained decode GB/s + the
    # overlap pair + the never-resident fit; raw-vs-peak honesty bytes
    # live in bench_full.json
    ("in_gbs", "ingest_gbs"),
    ("in_ov_on", "ingest_overlap_on_s"),
    ("in_ov_off", "ingest_overlap_off_s"),
    ("in_fit", "ingest_fit_s"),
    # serving gateway (keystone_tpu/serve): sustained-at-SLO row; the
    # saturation curve + slo live in bench_full.json
    ("sv_qps", "serve_sustained_qps"),
    ("sv_p99", "serve_p99_ms"),
    ("sv_shed", "serve_shed_frac"),
    # per-item serve latency (tunneled p50 + device-only component)
    # fleet tier (pool -> front -> replicas): the aggregate-QPS scaling
    # ratchet at pinned p99 + the coalesced-front gain; per-replica
    # honesty keys and the recompile pin live in bench_full.json
    ("fleet_x", "fleet_qps_scale"),
    ("fleet_q1", "fleet_qps_1"),
    ("fleet_coal", "fleet_coalesce_gain"),
    # fleet observability plane (telemetry shards merged across replica
    # processes): server-side shed fraction / breaker trips / p99 from
    # the merged serve.latency_ms histograms; telemetry_merge_procs is
    # the honesty key (how many process shards the merge saw)
    ("fleet_shed", "fleet_shed_frac"),
    ("fleet_brk", "fleet_breaker_trips"),
    ("fleet_p99", "fleet_p99_ms"),
    ("obs_procs", "telemetry_merge_procs"),
    # per-item serve latency (tunneled p50 + device-only component)
    ("sv_mnist", "mnist_serve_p50_ms"),
    ("sv_mnist_dev", "mnist_serve_device_ms"),
    ("sv_news", "newsgroups_serve_p50_ms"),
    ("sv_news_dev", "newsgroups_serve_device_ms"),
    ("sv_voc", "voc_serve_p50_ms"),
    ("sv_voc_dev", "voc_serve_device_ms"),
    # headline speedup ratios vs the measured CPU anchor
    ("r_fs", "imagenet_flagship_vs_cpu_baseline"),
    ("r_timit_full", "timit_full_vs_cpu_baseline"),
    ("r_timit", "timit_vs_cpu_baseline"),
    ("r_news", "newsgroups_vs_cpu_baseline"),
    ("r_sbo", "stupid_backoff_vs_cpu_baseline"),
    ("r_voc", "voc_small_vs_cpu_baseline"),
    ("r_inet", "imagenet_small_vs_cpu_baseline"),
    # design-constant ratchet (a jaxlib upgrade inverting a design choice
    # must be visible in the parsed artifact — VERDICT r3 item 8 / r4 item 9)
    ("c_i64sort", "key_sort_int64_over_int32"),
    ("c_scansort", "searchsorted_scan_over_sort_int32"),
    ("c_mom_pl", "moments_design_point_pallas_s"),
    ("c_mom_xla", "moments_design_point_xla_scan_s"),
)


def compact_round(v: float) -> float:
    """The compact-line float truncation: 3 decimals under |10|, 1 decimal
    above (keeps the tail-captured line inside the driver's 2000-char
    window).  Named so tests/test_bench_contract.py compares compact
    values against bench_full.json under the SAME rule — the full
    artifact keeps more decimals, and a slow run pushing a smoke timing
    past 10 s (13.195 -> 13.2) must not read as a mirroring failure."""
    return round(v, 3 if abs(v) < 10 else 1)


def _emit(out: dict, partial: bool = False) -> None:
    """Write the full dict to bench_full.json; print the compact summary as
    the LAST stdout line (driver tail-capture contract, see _COMPACT_KEYS).

    ``partial=True`` is the incremental-flush form (called after every
    section): the same full-dict write and the same compact line with a
    ``"partial": true`` marker — still valid JSON, so if the process is
    killed before the final emit the LAST stdout line remains parseable
    (rc=124 can no longer produce ``parsed: null``). ``BENCH_FULL_PATH``
    overrides the artifact location (tests point it at a tmp dir)."""
    full_path = knobs.get_raw("BENCH_FULL_PATH") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_full.json"
    )
    compact = {}
    try:
        tmp_path = full_path + ".tmp"
        with open(tmp_path, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp_path, full_path)  # atomic: a kill mid-write cannot
        compact["full"] = os.path.basename(full_path)  # truncate the artifact
    except OSError as e:
        # do NOT advertise the (stale, committed) file in the compact line
        print(f"bench_full.json write failed: {e}", file=sys.stderr)
        compact["full_write_failed"] = True
    if partial:
        compact["partial"] = True
    for short, key in _COMPACT_KEYS:
        v = out.get(key)
        if v is None:
            continue
        if isinstance(v, float):
            v = compact_round(v)
        compact[short] = v
    line = json.dumps(compact)
    if len(line) >= 1500:  # explicit raise: a bare assert dies under -O
        raise AssertionError(
            f"compact bench line {len(line)} chars >= 1500: trim "
            f"_COMPACT_KEYS (driver tail capture is 2000 chars; BENCH_r04 "
            f"went unparsed)"
        )
    print(line, flush=True)


if __name__ == "__main__":
    main()
