"""Benchmark entry point: MnistRandomFFT fit+eval wall-clock on TPU.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "s", "vs_baseline": N}``.

The flagship workload is the reference's own headline config
(``--numFFTs 4 --blockSize 2048``, ``README.md:14-22``): 60k×784 train /
10k×784 test, 4×(sign-flip → 1024-pt FFT → ReLU) featurization to 2048
features, one-pass block least squares, streaming block evaluation.

The reference publishes no numbers (BASELINE.md) — the Spark baseline must be
measured on a 64-core cluster we don't have here, so ``vs_baseline`` reports
against ``baseline_s`` below once BASELINE.md gains a measured value; until
then it is null. We report the steady-state run (second invocation, compile
cached) as the headline value and the cold run separately.
"""

import json
import time

import jax
import jax.numpy as jnp

# Measured reference wall-clock (Spark, 64-core), to be filled in BASELINE.md.
BASELINE_S = None


def solver_gflops(n: int = 60000, d: int = 2048, c: int = 10, block: int = 2048,
                  iters: int = 16, precision: str = None) -> float:
    """BlockLeastSquares solver GFLOPS/chip (BASELINE.json's second metric):
    sustained rate of the block-coordinate-descent solve at the MNIST
    flagship shape (f32 inputs; MXU pass count set by ``precision`` —
    default is the framework's solver precision, bf16x3).

    Measured as (time of K chained solves) − (time of 1 solve), each timed to
    a single scalar host transfer: device calls execute serially, so the
    difference is pure device time and the host↔device round-trip latency
    (~100 ms on a tunneled runtime) cancels out of the per-solve rate.
    """
    from keystone_tpu.linalg.bcd import block_coordinate_descent_l2

    key = jax.random.key(0)
    A = jax.random.normal(key, (n, d), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (n, c), jnp.float32)
    float(A[0, 0])  # materialize inputs

    def timed(k: int) -> float:
        ws = [block_coordinate_descent_l2(A, b, 1.0 + i, block, precision=precision)
              for i in range(k)]
        float(ws[-1][0, 0])  # warm compile + drain the whole warm-up chain
        t0 = time.perf_counter()
        ws = [block_coordinate_descent_l2(A, b, 2.0 + i, block, precision=precision)
              for i in range(k)]
        w_last = float(ws[-1][0, 0])  # one transfer after the chain
        if w_last != w_last:
            raise FloatingPointError("solver produced NaN")
        return time.perf_counter() - t0

    dt = (timed(1 + iters) - timed(1)) / iters
    if dt <= 0:
        raise RuntimeError(f"non-positive solver timing difference: {dt}")
    nblocks = -(-d // block)
    flops = nblocks * (2 * n * block * block + 4 * n * block * c
                       + 2 * block * block * c) + (2 / 3) * nblocks * block**3
    return flops / dt / 1e9


def _try_solver_gflops(precision=None):
    """Secondary metric; never let it block the primary JSON line. One retry
    absorbs transient timing noise (dt<=0 on a contended chip)."""
    for _ in range(2):
        try:
            return round(solver_gflops(precision=precision), 1)
        except Exception:
            continue
    return None


def _try_extras():
    """Secondary whole-pipeline wall-clocks (warm), never fatal. Disable with
    BENCH_EXTRAS=0 to keep the run to the primary metric only."""
    import os

    if os.environ.get("BENCH_EXTRAS", "1") == "0":
        return {}
    extras = {}
    try:
        from keystone_tpu.pipelines.timit import TimitConfig, run as run_timit

        cfg = TimitConfig(synthetic_train=100000, synthetic_test=20000)
        run_timit(cfg)
        extras["timit_100k_50x4096_5ep_warm_s"] = round(
            run_timit(cfg)["wallclock_s"], 3
        )
    except Exception:
        extras["timit_100k_50x4096_5ep_warm_s"] = None
    try:
        from keystone_tpu.pipelines.random_patch_cifar import (
            RandomPatchCifarConfig,
            run as run_rpc,
        )

        cfg = RandomPatchCifarConfig(synthetic_train=50000, synthetic_test=10000)
        run_rpc(cfg)
        extras["random_patch_cifar_50k_warm_s"] = round(
            run_rpc(cfg)["wallclock_s"], 3
        )
    except Exception:
        extras["random_patch_cifar_50k_warm_s"] = None
    return extras


def main():
    from keystone_tpu.pipelines.mnist_random_fft import MnistRandomFFTConfig, run

    config = MnistRandomFFTConfig(
        num_ffts=4,
        block_size=2048,
        lam=10.0,
        synthetic_train=60000,
        synthetic_test=10000,
    )
    t0 = time.perf_counter()
    cold = run(config)
    cold_s = time.perf_counter() - t0
    warm = run(config)

    value = warm["wallclock_s"]
    out = {
        "metric": "mnist_random_fft_fit_eval_wallclock",
        "value": round(value, 3),
        "unit": "s",
        "vs_baseline": round(BASELINE_S / value, 2) if BASELINE_S else None,
        "cold_wallclock_s": round(cold_s, 3),
        "train_error_pct": round(warm["train_error"], 3),
        "test_error_pct": round(warm["test_error"], 3),
        "solver_gflops_per_chip": _try_solver_gflops(),
        "device": str(jax.devices()[0]),
    }
    import os

    if os.environ.get("BENCH_EXTRAS", "1") != "0":
        out["solver_gflops_per_chip_f32_highest"] = _try_solver_gflops("highest")
    out.update(_try_extras())
    print(json.dumps(out))


if __name__ == "__main__":
    main()
