"""Lock-discipline smoke (< 20 s): the contract `make verify-fast` rides.

Asserts, end to end through the REAL CLI code path:

1. the committed bad fixtures (tests/fixtures/race/) fire EVERY rule
   T1-T5 — the detectors cannot silently rot;
2. the real tree sweeps CLEAN against the committed (empty)
   ``race_baseline.json`` — zero new findings, zero parse errors, rc=0 —
   and the JSON output schema holds (the keys bench.py and the tests
   read);
3. ``KEYSTONE_LOCK_WITNESS=1`` catches a replay of the PR-15
   ``_claim_slot`` deadlock shape (blocking on the ring while holding
   the claim lock) within seconds, with the held/blocked locks named;
4. with the knob unset, :func:`register_lock` returns the bare lock
   UNCHANGED — the zero-overhead off path is identity, not a wrapper;
5. the whole pass stays under the 20 s budget.
"""

from __future__ import annotations

import io
import json
import os
import sys
import threading
import time
from contextlib import redirect_stdout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BUDGET_S = 20.0
DEADLOCK_FLAG_BUDGET_S = 5.0


def main() -> int:
    t0 = time.monotonic()
    os.chdir(REPO)

    from keystone_tpu.analysis.concurrency import ALL_RACE_RULES, RaceEngine
    from keystone_tpu.analysis.concurrency import main as race_main

    # 1: every T rule fires on its committed bad fixture
    bad = RaceEngine(REPO, ["tests/fixtures/race"]).run()
    assert not bad.errors, bad.errors
    fired = {f.rule for f in bad.findings}
    assert fired == set(ALL_RACE_RULES), (
        f"fixtures fired {sorted(fired)}, want {list(ALL_RACE_RULES)}"
    )

    # 2: the real tree is clean vs the committed baseline + JSON schema
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = race_main(["--format", "json", "--root", REPO])
    payload = json.loads(buf.getvalue())
    assert rc == 0, f"keystone-tpu race rc={rc}: {payload['new']}"
    for key in ("new", "baselined", "stale", "suppressed", "files",
                "errors", "total"):
        assert key in payload, f"missing JSON key {key}"
    assert payload["new"] == [], payload["new"]
    assert payload["errors"] == [], payload["errors"]
    assert payload["files"] > 100, payload["files"]

    # 4 (before flipping the knob): off path is identity, no wrapper
    os.environ.pop("KEYSTONE_LOCK_WITNESS", None)
    from keystone_tpu.utils import lockwitness
    from keystone_tpu.utils.lockwitness import register_lock

    bare = threading.Lock()
    assert register_lock(bare, "smoke.off") is bare, (
        "KEYSTONE_LOCK_WITNESS unset must return the lock unchanged"
    )

    # 3: the PR-15 deadlock shape, replayed and DIAGNOSED in seconds.
    # Main holds the ring (a full buffer ring that will never drain);
    # the worker blocks acquiring it while holding the claim lock —
    # exactly `_claim_slot` before the fix.
    os.environ["KEYSTONE_LOCK_WITNESS"] = "1"
    try:
        lockwitness.reset()
        ring = register_lock(threading.Lock(), "replay.ring")
        claim = register_lock(threading.Lock(), "replay.claim")
        assert isinstance(ring, lockwitness.WitnessLock)

        ring.acquire()

        def worker():
            with claim:
                with ring:
                    pass

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        deadline = time.monotonic() + DEADLOCK_FLAG_BUDGET_S
        events = []
        while time.monotonic() < deadline:
            events = lockwitness.events("held_blocking")
            if events:
                break
            time.sleep(0.05)
        ring.release()
        t.join(5.0)
        assert events, (
            f"witness failed to flag the replayed deadlock within "
            f"{DEADLOCK_FLAG_BUDGET_S}s"
        )
        ev = events[0]
        assert ev["held"] == "replay.claim", ev
        assert ev["blocked_on"] == "replay.ring", ev
        assert not t.is_alive(), "replay worker did not finish"
    finally:
        os.environ.pop("KEYSTONE_LOCK_WITNESS", None)
        lockwitness.reset()

    elapsed = time.monotonic() - t0
    assert elapsed < BUDGET_S, (
        f"race smoke took {elapsed:.1f}s (budget {BUDGET_S}s)"
    )
    print(
        f"race-smoke OK: {len(bad.findings)} fixture findings across "
        f"{len(fired)} rules, tree clean over {payload['files']} files, "
        f"witness flagged the PR-15 replay, {elapsed:.1f}s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
