"""Numerical-health smoke (<20 s, CPU): the `make health-smoke` rung of
`verify-fast` — sentinel trips, quarantine, self-healing escalation, and
the off-mode byte-identity pin, end to end through the REAL entry points.

Pins:

1. ``KEYSTONE_HEALTH=0`` (and unset, and ``warn`` with no trip) produce
   BIT-IDENTICAL models — the sentinels are a pure program add-on whose
   gate never perturbs a healthy fit, and the default mode is the prior
   program.
2. The hazard is real: the same NaN injection under ``KEYSTONE_HEALTH=0``
   silently poisons the whole model (non-finite weights).
3. ``warn``: the sentinel trips on the injected NaN block, the block is
   quarantined ON DEVICE (``health.quarantined`` counted), and the fit
   completes with a finite model.
4. ``heal``: the escalation ladder re-runs the poisoned block
   (``health.escalations``/``health.healed`` counted) and the healed
   model's test error lands within the clean twin's envelope.
5. Malformed ``KEYSTONE_FAULTS`` plans — including a numeric kind at a
   non-data site — fail EAGERLY at ``knobs.validate_environment()``, not
   mid-fit.
"""

from __future__ import annotations

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
for knob in ("KEYSTONE_FAULTS", "KEYSTONE_HEALTH"):
    os.environ.pop(knob, None)

t_start = time.monotonic()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

BUDGET_S = 20.0


class _Slice:
    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    def apply_batch(self, raw):
        return raw["x"][:, self.lo : self.hi]


def main() -> int:
    from keystone_tpu.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )
    from keystone_tpu.telemetry import get_registry
    from keystone_tpu.utils import faults, knobs

    reg = get_registry()
    counter_sum = reg.counter_family_total

    # synthetic task WITH signal, so test error is meaningful: labels from
    # a ground-truth linear model over the features
    n, d, c, bs = 256, 48, 4, 8
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=(d, c)).astype(np.float32)
    cls = np.argmax(x @ w_true, axis=1)
    lbl = np.eye(c, dtype=np.float32)[cls] * 2.0 - 1.0
    nodes = [_Slice(k * bs, (k + 1) * bs) for k in range(d // bs)]
    raw = {"x": jnp.asarray(x)}
    est = BlockWeightedLeastSquaresEstimator(bs, 2, 0.1, 0.25)

    def fit():
        m = est.fit_streaming(nodes, raw, jnp.asarray(lbl))
        jax.block_until_ready(m.w)
        return m

    def err_pct(m):
        pred = np.argmax(np.asarray(x @ np.asarray(m.w) + np.asarray(m.b)), 1)
        return 100.0 * float(np.mean(pred != cls))

    def poisoned(env_mode):
        faults.reset()
        os.environ["KEYSTONE_FAULTS"] = "block@2:nan"
        if env_mode is None:
            os.environ.pop("KEYSTONE_HEALTH", None)
        else:
            os.environ["KEYSTONE_HEALTH"] = env_mode
        try:
            return fit()
        finally:
            os.environ.pop("KEYSTONE_FAULTS", None)
            os.environ.pop("KEYSTONE_HEALTH", None)
            faults.reset()

    # 1. byte-identity: unset == "0" == warn-with-no-trip, bitwise
    ref = fit()
    os.environ["KEYSTONE_HEALTH"] = "0"
    m0 = fit()
    os.environ["KEYSTONE_HEALTH"] = "warn"
    mw = fit()
    os.environ.pop("KEYSTONE_HEALTH", None)
    assert np.array_equal(np.asarray(ref.w), np.asarray(m0.w)), (
        "KEYSTONE_HEALTH=0 is not byte-identical to unset"
    )
    assert np.array_equal(np.asarray(ref.w), np.asarray(mw.w)), (
        "a no-trip warn-mode fit perturbed the model (the gate must be "
        "a bit-exact pass-through on healthy blocks)"
    )
    clean_err = err_pct(ref)

    # 2. the hazard: unguarded NaN injection poisons the whole model
    m_bad = poisoned(None)
    assert not bool(np.all(np.isfinite(np.asarray(m_bad.w)))), (
        "unguarded NaN block did NOT poison the model — the injection "
        "is not reaching the solver"
    )

    # 3. warn: trip -> on-device quarantine, fit completes finite
    q0, t0 = counter_sum("health.quarantined"), counter_sum("health.tripped")
    m_warn = poisoned("warn")
    assert counter_sum("health.tripped") > t0, "sentinel did not trip"
    assert counter_sum("health.quarantined") > q0, "no quarantine counted"
    assert bool(np.all(np.isfinite(np.asarray(m_warn.w)))), (
        "warn-mode model is not finite — quarantine gate leaked"
    )

    # 4. heal: escalation re-runs the block; test error within envelope
    e0, h0 = counter_sum("health.escalations"), counter_sum("health.healed")
    m_heal = poisoned("heal")
    assert counter_sum("health.escalations") > e0, "no escalation counted"
    assert counter_sum("health.healed") > h0, "heal did not complete"
    heal_err = err_pct(m_heal)
    assert heal_err <= clean_err + 2.0, (
        f"healed test error {heal_err:.2f}% outside the clean twin's "
        f"envelope ({clean_err:.2f}% + 2%)"
    )

    # 5. malformed plans fail EAGERLY at validate_environment
    for bad in ("block@x", "segment@1:nan", "bench_section@0:saturate"):
        os.environ["KEYSTONE_FAULTS"] = bad
        try:
            knobs.validate_environment()
        except ValueError:
            pass
        else:
            raise AssertionError(
                f"malformed plan {bad!r} validated without error"
            )
        finally:
            os.environ.pop("KEYSTONE_FAULTS", None)

    elapsed = time.monotonic() - t_start
    print(
        f"health-smoke OK in {elapsed:.1f}s: off-mode byte-identical, "
        f"unguarded NaN poisons, warn quarantines, heal escalates "
        f"(clean {clean_err:.2f}% vs healed {heal_err:.2f}%), malformed "
        "plans rejected eagerly"
    )
    assert elapsed < BUDGET_S, f"smoke took {elapsed:.1f}s (>{BUDGET_S}s)"
    return 0


if __name__ == "__main__":
    sys.exit(main())
