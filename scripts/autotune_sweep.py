"""KEYSTONE_AUTOTUNE=1 sweep of the tunable kernel family at BOTH precision
tiers, persisting winners into the repo-root ``autotune_cache.json``.

The ROADMAP pod-ladder item (d) rung that needs no hardware: on the CPU
backend (8-device sim for the overlap schedulers, interpret-mode Pallas for
the extraction kernels) sweep

- ``overlap.tiles``  — the tiled reduce-scatter gram's tile-count target at
  the flagship (d=2048, k=8) bucket; candidates are multiples of k so every
  winner preserves the >=k per-tile-collective structure the A1 audit pins;
- ``sift.bins`` / ``fv.encode`` — the extraction kernels' row tiles;
- ``moments.tile_n`` — the shared moments row tile (bucket "any");

each at tier f32 AND tier bf16, so the committed cache demonstrates
precision-keyed entries coexisting: ``"<bucket>"`` (f32) next to
``"<bucket>@bf16"``, resolved independently by ``autotune.precision_bucket``
consumers. CPU winners are keyed ``cpu:cpu`` — they serve CPU runs (tests,
the bench host) and never leak to TPU keys.

Run from the repo root: ``python scripts/autotune_sweep.py``; the refreshed
``autotune_cache.json`` is meant to be committed (the zero-re-sweeps
contract: every later process on this device generation hits the cache).
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["KEYSTONE_AUTOTUNE"] = "1"
# bounded but roomy: interpret-mode Pallas candidates are slow on CPU
os.environ.setdefault("KEYSTONE_AUTOTUNE_BUDGET_S", "60")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

TIERS = ("f32", "bf16")


def sweep_overlap_tiles() -> None:
    from keystone_tpu.ops.pallas import autotune
    from keystone_tpu.parallel import make_mesh
    from keystone_tpu.parallel.overlap import tiled_transpose_matmul

    mesh = make_mesh(data=8, model=1)
    k = mesh.shape["data"]
    n, d = 1024, 2048  # the flagship feature dim's (dim, k) bucket
    from jax.sharding import NamedSharding, PartitionSpec as P

    x32 = jax.device_put(
        jax.random.normal(jax.random.key(0), (n, d), jnp.float32),
        NamedSharding(mesh, P("data", None)),
    )
    bucket = autotune.shape_bucket(d, k)
    # candidates are multiples of k: every winner keeps >= k per-tile
    # reduce-scatters (the A1 audit structure; _pick_tiles' heuristic
    # default is exactly k)
    candidates = [k, 2 * k, 4 * k]
    for tier in TIERS:
        key = autotune.precision_bucket(bucket, tier)

        def build(tiles):
            return lambda i: tiled_transpose_matmul(
                x32, mesh=mesh, tiles=int(tiles), tier=tier
            )

        won = autotune.sweep(
            "overlap.tiles", key, candidates,
            autotune.chained_measure(build), reps=2,
        )
        print(f"overlap.tiles[{key}] -> {won}")


def sweep_extraction() -> None:
    """Sweep the generated-variant spaces, not just tiles: each plan call
    resolves the default variant's tile at the bare bucket (pre-variant
    entries stay valid), then validates + sweeps every non-default variant
    at its ``#``-qualified bucket and arbitrates the measured winner."""
    from keystone_tpu.ops.pallas.extraction import (
        conv_norm_plan,
        conv_pool_plan,
        fv_encode_plan,
        pool_sum_plan,
        sift_bins_plan,
    )

    # representative extraction shapes: a 2048-row/64-wide SIFT chunk, a
    # 512-descriptor/64-dim/16-center FV encode, and the CIFAR-scale
    # conv/pool geometry (32² RGB, 5² patches, 256 filters)
    for tier in TIERS:
        v, t = sift_bins_plan(2048, 64, 36, allow_sweep=True, tier=tier)
        print(f"sift.bins tier={tier} -> {v}/{t}")
    for tier in TIERS:
        v, t = fv_encode_plan(512, 64, 16, allow_sweep=True, tier=tier)
        print(f"fv.encode tier={tier} -> {v}/{t}")
    for tier in TIERS:
        v, t = conv_norm_plan(32, 32, 3, 5, 256, allow_sweep=True, tier=tier)
        print(f"conv.norm tier={tier} -> {v}/{t}")
    for tier in TIERS:
        v, t = pool_sum_plan(28, 28, 256, stride=2, pool_size=3,
                             allow_sweep=True, tier=tier)
        print(f"pool.sum tier={tier} -> {v}/{t}")
    for tier in TIERS:
        v, t = conv_pool_plan(32, 32, 3, 5, 256, stride=2, pool_size=3,
                              allow_sweep=True, tier=tier)
        print(f"conv.pool tier={tier} -> {v}/{t}")


def sweep_moments() -> None:
    from keystone_tpu.ops.pallas.moments import gmm_moments_sep

    x = jax.random.normal(jax.random.key(3), (4096, 16), jnp.float32)
    means = jax.random.normal(jax.random.key(4), (8, 16), jnp.float32)
    variances = jnp.abs(
        jax.random.normal(jax.random.key(5), (8, 16), jnp.float32)
    ) + 0.5
    weights = jnp.ones((8,), jnp.float32) / 8.0
    for tier in TIERS:
        gmm_moments_sep(x, means, variances, weights, tier=tier)
        print(f"moments.tile_n tier={tier} swept")


def main() -> int:
    t0 = time.monotonic()
    sweep_extraction()
    sweep_moments()
    sweep_overlap_tiles()
    from keystone_tpu.ops.pallas import autotune

    path = autotune.cache_path()
    print(f"swept in {time.monotonic() - t0:.1f}s -> {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
