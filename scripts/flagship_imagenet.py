"""Run the flagship-regime streaming ImageNet config on the TPU, twice in
one process, and print cold + warm wall-clocks (warm = jit + XLA caches
hot). The BASELINE.md reference-dim row comes from this script. A
persistent XLA compilation cache (``--cache-dir``) additionally makes the
"cold" run of later invocations compile-warm; delete the directory for a
true first-compile measurement.

Usage: ``python scripts/flagship_imagenet.py [--warm] [--train N]``.
"""

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser()
    ap.add_argument("--warm", action="store_true",
                    help="run twice; also report the second (cache-hot) run")
    ap.add_argument("--train", type=int, default=102400)
    ap.add_argument("--test", type=int, default=5120)
    ap.add_argument("--noise", type=float, default=0.6,
                    help="0.6 = the non-vacuous quality regime (flagship "
                         "default); 0.08 = separable prototypes, 0%% error "
                         "plumbing check")
    ap.add_argument("--control-shuffled-labels", action="store_true",
                    help="also run the shuffled-label control: train labels "
                         "drawn independently of images; top-5 error must "
                         "collapse to ~chance (1 - 5/classes)")
    ap.add_argument("--cache-dir", default="/tmp/keystone_xla_cache")
    ap.add_argument("--cache-blocks", type=int, default=None,
                    help="override fv_cache_blocks (posterior cache-group "
                         "width; HBM experiment knob)")
    return ap


def main() -> None:
    args = build_parser().parse_args()
    import jax

    jax.config.update("jax_compilation_cache_dir", args.cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
        flagship_config,
        run,
    )

    overrides = {}
    if args.cache_blocks is not None:
        overrides["fv_cache_blocks"] = args.cache_blocks
    cfg = flagship_config(
        synthetic_train=args.train,
        synthetic_test=args.test,
        synthetic_noise=args.noise,
        **overrides,
    )
    out = {"cold": run(cfg)}
    if args.warm:
        out["warm"] = run(cfg)
    if args.control_shuffled_labels:
        ctrl = flagship_config(
            synthetic_train=args.train,
            synthetic_test=args.test,
            synthetic_noise=args.noise,
            shuffle_labels=True,
        )
        res = run(ctrl)
        chance = 100.0 * (1.0 - 5.0 / ctrl.synthetic_classes)
        res["chance_top5_error"] = chance
        res["collapsed_to_chance"] = bool(res["test_top5_error"] > 0.9 * chance)
        out["shuffled_label_control"] = res
    print(json.dumps(out))


if __name__ == "__main__":
    main()
