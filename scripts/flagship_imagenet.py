"""Run the flagship-regime streaming ImageNet config on the TPU, twice in
one process, and print cold + warm wall-clocks (warm = XLA compile cache
hot). The BASELINE.md reference-dim row comes from this script.

Usage: ``python scripts/flagship_imagenet.py [--warm] [--train N]``.
"""

import argparse
import json

from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
    ImageNetSiftLcsFVConfig,
    run,
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--warm", action="store_true",
                    help="run twice; report the second (compile-cached) run")
    ap.add_argument("--train", type=int, default=102400)
    ap.add_argument("--test", type=int, default=5120)
    args = ap.parse_args()

    cfg = ImageNetSiftLcsFVConfig(
        sift_pca_dim=64,
        lcs_pca_dim=64,
        vocab_size=256,
        num_pca_samples=2000000,
        num_gmm_samples=2000000,
        lam=6e-5,
        mixture_weight=0.25,
        block_size=4096,
        synthetic_train=args.train,
        synthetic_test=args.test,
        synthetic_classes=1000,
        synthetic_hw=64,
        streaming=True,
        extract_chunk=2048,
        sample_images=8192,
        fv_row_chunk=1024,
    )
    cold = run(cfg)
    out = {"cold": cold}
    if args.warm:
        out["warm"] = run(cfg)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
