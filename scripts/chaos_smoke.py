"""Chaos-ladder smoke (<20 s, CPU): the `make chaos-smoke` rung of
`verify-fast` — inject → crash → resume-on-a-RESHAPED-mesh, end to end.

Pins, through the REAL entry points on the 8-device CPU sim:

1. A streaming weighted fit sharded over an 8-device mesh is killed
   mid-schedule by a deterministic injected device error
   (``KEYSTONE_FAULTS=block@K:xla`` — utils/faults.py), leaving its
   mid-fit checkpoint behind.
2. The SAME checkpoint resumes the fit on a 4-device mesh — the
   preempted-pod-comes-back-smaller scenario: the manifest records the
   mesh the state was written under, the resume reshards onto the live
   one (``checkpoint.reshard`` counted), and the fit completes with zero
   manual intervention.
3. The resumed model matches the uninterrupted twin within the
   documented envelope (identical math; only the collective reduction
   geometry changed, so the delta is reduction-order rounding).
4. The completed fit removes its checkpoint, and a deliberately
   truncated checkpoint raises the NAMED CheckpointCorruptError — never
   half-loaded garbage.
5. NUMERIC chaos (PR 13): a fit whose block 2 is NaN-poisoned
   (``KEYSTONE_FAULTS`` numeric kind) under ``KEYSTONE_HEALTH=heal`` is
   killed mid-schedule; the checkpoint manifest records the tripped
   position + mode, a mode-flipped resume is REJECTED loudly, and the
   same-mode resume completes, heals the quarantined block through the
   escalation ladder, and lands inside the clean twin's residual
   envelope.
6. ELASTIC retry (PR 14 fix): ``fit_streaming_elastic`` with the
   checkpoint path DERIVED from ``KEYSTONE_CHECKPOINT_DIR`` (no explicit
   path — the derivation was previously only exercised by batch-fit unit
   tests) survives a transient injected device error inside its own
   retry loop: the retried attempt resumes from the mid-fit checkpoint,
   ``retry.attempt`` and ``retry.resumed`` are both counted >= 1 (the
   resumed counter was written but never pinned end to end), the result
   matches the uninterrupted twin, and the completed fit cleans the
   derived file out of the directory.
"""

from __future__ import annotations

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# 8-device CPU sim, set BEFORE jax initializes a backend (conftest pattern)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.pop("KEYSTONE_FAULTS", None)
os.environ.pop("KEYSTONE_HEALTH", None)

t_start = time.monotonic()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

BUDGET_S = 20.0


class _Slice:
    """Streaming feature node: one column block of the raw features."""

    def __init__(self, lo, hi):
        self.lo, self.hi = lo, hi

    def apply_batch(self, raw):
        return raw["x"][:, self.lo : self.hi]


def _put_rows(mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P("data", None)))


def main() -> int:
    import tempfile

    from keystone_tpu.core.checkpoint import (
        CheckpointCorruptError,
        load_manifest,
    )
    from keystone_tpu.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )
    from keystone_tpu.parallel import make_mesh
    from keystone_tpu.telemetry import get_registry
    from keystone_tpu.utils import faults

    devices = jax.devices()
    assert len(devices) >= 8, f"need the 8-device CPU sim, got {len(devices)}"
    reg = get_registry()

    n, d, c, bs = 128, 32, 4, 8
    nblocks = d // bs
    num_iter = 2  # schedule length 8: room for a mid-schedule kill
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    lbl = (np.eye(c, dtype=np.float32)[np.arange(n) % c] * 2.0 - 1.0)
    nodes = [_Slice(k * bs, (k + 1) * bs) for k in range(nblocks)]

    mesh8 = make_mesh(data=8, model=1, devices=devices[:8])
    mesh4 = make_mesh(data=4, model=1, devices=devices[:4])

    def fit(mesh, est, **kw):
        raw = {"x": _put_rows(mesh, jnp.asarray(x))}
        labels = _put_rows(mesh, jnp.asarray(lbl))
        m = est.fit_streaming(nodes, raw, labels, **kw)
        jax.block_until_ready(m.w)
        return m

    est = BlockWeightedLeastSquaresEstimator(bs, num_iter, 0.1, 0.25)

    # uninterrupted twin on the full 8-device mesh
    ref = fit(mesh8, est)

    # 1. inject: deterministic device error at schedule position 5 (pass 1,
    #    second block) — mid-schedule, past the first full pass
    ckpt = os.path.join(tempfile.mkdtemp(prefix="chaos_smoke_"), "fit.ckpt")
    kill_pos = 5
    faults.reset()
    os.environ["KEYSTONE_FAULTS"] = f"block@{kill_pos}:xla"
    try:
        try:
            fit(mesh8, est, checkpoint_path=ckpt, checkpoint_every=1)
        except Exception as e:
            assert "injected fault" in str(e), f"unexpected failure: {e}"
        else:
            raise AssertionError("injected fault did not fire")
    finally:
        os.environ.pop("KEYSTONE_FAULTS", None)
        faults.reset()
    assert os.path.exists(ckpt), "crash left no checkpoint behind"
    manifest = load_manifest(ckpt)
    assert manifest and manifest["mesh_shape"] == {"data": 8, "model": 1}, (
        f"manifest did not record the writing mesh: {manifest}"
    )
    assert manifest["pos"] == kill_pos, manifest["pos"]

    # 2. resume the SAME checkpoint on the RESHAPED (8 -> 4 device) mesh
    reshards0 = reg.get_counter("checkpoint.reshard")
    resumed = fit(mesh4, est, checkpoint_path=ckpt, checkpoint_every=1)
    assert reg.get_counter("checkpoint.reshard") > reshards0, (
        "resume on the reshaped mesh did not count checkpoint.reshard"
    )
    assert not os.path.exists(ckpt), "completed fit left its checkpoint"

    # 3. envelope: same math, different reduction geometry — the delta is
    #    collective reduction-order rounding, orders below model scale
    w_ref = np.asarray(ref.w, np.float64)
    w_res = np.asarray(resumed.w, np.float64)
    delta = float(
        np.linalg.norm(w_res - w_ref) / max(np.linalg.norm(w_ref), 1e-30)
    )
    assert delta < 1e-4, f"reshaped resume diverged from the twin: {delta}"
    b_delta = float(np.max(np.abs(np.asarray(resumed.b) - np.asarray(ref.b))))
    assert b_delta < 1e-4, f"intercept diverged: {b_delta}"

    # 4. a truncated checkpoint is a NAMED error, never half-loaded
    from keystone_tpu.core.checkpoint import save_node

    trunc = ckpt + ".trunc"
    save_node({"w": np.arange(1024, dtype=np.float32)}, trunc)
    blob = open(trunc, "rb").read()
    with open(trunc, "wb") as f:
        f.write(blob[: len(blob) // 2])
    try:
        load_manifest(trunc)
    except CheckpointCorruptError:
        pass
    else:
        raise AssertionError("truncated checkpoint loaded without error")

    # 5. poisoned-block kill-and-resume (PR 13): NaN block at pos 2, kill
    #    at pos 5, resume under the SAME health mode -> the restored
    #    sentinel records replay the quarantine and the heal pass re-runs
    #    the block; a mode-flipped resume is loudly rejected
    from keystone_tpu.core.checkpoint import CheckpointMismatchError

    def obj(m):
        r = x @ np.asarray(m.w, np.float64) + np.asarray(m.b, np.float64)
        return float(np.linalg.norm(r - lbl))

    ckpt2 = ckpt + ".health"
    faults.reset()
    os.environ["KEYSTONE_HEALTH"] = "heal"
    os.environ["KEYSTONE_FAULTS"] = "block@2:nan,block@5:xla"
    try:
        try:
            fit(mesh8, est, checkpoint_path=ckpt2, checkpoint_every=1)
        except Exception as e:
            assert "injected fault" in str(e), f"unexpected failure: {e}"
        else:
            raise AssertionError("injected kill did not fire")
    finally:
        os.environ.pop("KEYSTONE_FAULTS", None)
        faults.reset()
    man2 = load_manifest(ckpt2)
    assert man2.get("health_mode") == "heal", man2.get("health_mode")
    assert 2 in man2.get("health_tripped", []), (
        f"manifest did not record the tripped position: {man2}"
    )
    # mode flip across the kill = different quarantine/heal decisions:
    # loud, never silent
    os.environ["KEYSTONE_HEALTH"] = "0"
    try:
        fit(mesh8, est, checkpoint_path=ckpt2, checkpoint_every=1)
    except CheckpointMismatchError:
        pass
    else:
        raise AssertionError("mode-flipped resume was not rejected")
    os.environ["KEYSTONE_HEALTH"] = "heal"
    healed0 = reg.get_counter("health.healed", site="block")
    healed = fit(mesh8, est, checkpoint_path=ckpt2, checkpoint_every=1)
    os.environ.pop("KEYSTONE_HEALTH", None)
    assert reg.get_counter("health.healed", site="block") > healed0, (
        "resume did not heal the quarantined block"
    )
    assert not os.path.exists(ckpt2), "healed fit left its checkpoint"
    assert np.all(np.isfinite(np.asarray(healed.w))), "healed model NaN"
    obj_ref, obj_heal = obj(ref), obj(healed)
    assert obj_heal <= obj_ref * 1.10 + 1e-6, (
        f"healed fit outside the clean twin's residual envelope: "
        f"{obj_heal:.4f} vs {obj_ref:.4f}"
    )

    # 6. elastic retry with the DERIVED checkpoint path: a transient
    #    device error at schedule position 3 is absorbed by the retry
    #    loop in-process (the long-lived-gateway restart path) — the
    #    second attempt resumes from the mid-fit checkpoint and
    #    retry.resumed is finally pinned where it is produced
    from keystone_tpu.utils.retry import fit_streaming_elastic

    ckdir = tempfile.mkdtemp(prefix="chaos_elastic_")
    attempts0 = reg.get_counter("retry.attempt")
    resumed0 = reg.get_counter("retry.resumed")
    faults.reset()
    os.environ["KEYSTONE_CHECKPOINT_DIR"] = ckdir
    os.environ["KEYSTONE_FAULTS"] = "block@3:xla"
    try:
        raw = {"x": _put_rows(mesh8, jnp.asarray(x))}
        labels = _put_rows(mesh8, jnp.asarray(lbl))
        elastic = fit_streaming_elastic(
            est, nodes, raw, labels, checkpoint_every=1, backoff_s=0.01,
        )
        jax.block_until_ready(elastic.w)
    finally:
        os.environ.pop("KEYSTONE_FAULTS", None)
        os.environ.pop("KEYSTONE_CHECKPOINT_DIR", None)
        faults.reset()
    assert reg.get_counter("retry.attempt") > attempts0, (
        "the injected transient fault never entered the retry loop"
    )
    assert reg.get_counter("retry.resumed") > resumed0, (
        "retry.resumed was not counted for the resumed elastic fit"
    )
    w_el = np.asarray(elastic.w, np.float64)
    el_delta = float(
        np.linalg.norm(w_el - w_ref) / max(np.linalg.norm(w_ref), 1e-30)
    )
    assert el_delta < 1e-6, (
        f"elastic resumed fit diverged from the twin: {el_delta}"
    )
    leftovers = os.listdir(ckdir)
    assert not leftovers, f"elastic fit left derived checkpoints: {leftovers}"

    elapsed = time.monotonic() - t_start
    print(
        f"chaos-smoke OK in {elapsed:.1f}s: injected fault at pos "
        f"{kill_pos}, resumed 8->4 devices (reshard counted), "
        f"w_delta={delta:.2e}, truncated file -> CheckpointCorruptError; "
        f"poisoned-block kill-and-resume healed "
        f"(obj {obj_heal:.3f} vs clean {obj_ref:.3f}); elastic retry "
        f"resumed in-process (retry.resumed pinned, delta={el_delta:.1e})"
    )
    assert elapsed < BUDGET_S, f"smoke took {elapsed:.1f}s (>{BUDGET_S}s)"
    return 0


if __name__ == "__main__":
    sys.exit(main())
