"""Flagship quality-band attribution (VERDICT r4 next #3).

The flagship's top-5 across seeds {42, 7, 123} spans 6.8-29.7% (BASELINE.md)
with the native EM. Two arms decide whether that band is the framework's EM
or the task's:

- ``sklearn``: external codebooks — sklearn GaussianMixture (diag,
  k-means++ init) fitted on a subsample of the SAME descriptor feed,
  plugged into the UNCHANGED FV+solver path (``gmm_backend="sklearn"``).
  If the band persists under an external EM, the instability is the
  task's, not ``learning/gmm.py``'s.
- ``ensemble``: FV ensembling over 4 independently-seeded 64-center
  codebooks per branch, concatenated (``gmm_ensemble=4``; total feature
  dim unchanged) — the one untried cheap stabilizer.

``seed`` varies the PCA/GMM *sampler* draws over identical synthetic data
(the native EM seed is fixed at 42), exactly the protocol that produced
the published band. Optionally re-measures the native arm in-session
(``--with-native``) instead of relying on the published numbers.

Writes one JSON line per completed run (resumable evidence) to
``codebook_control.jsonl`` and a final summary line; quality only — the
in-process allocator effect on *timing* (bench_regime.py docstring) does
not touch the error metric.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

SEEDS = (42, 7, 123)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--arms", default="sklearn,ensemble",
                    help="comma list: native,sklearn,ensemble")
    ap.add_argument("--seeds", default=",".join(map(str, SEEDS)))
    ap.add_argument("--out", default="codebook_control.jsonl")
    ap.add_argument("--ensemble-k", type=int, default=4)
    args = ap.parse_args()

    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
        flagship_config,
        run,
    )

    arms = {
        "native": {},
        "sklearn": {"gmm_backend": "sklearn"},
        "ensemble": {"gmm_ensemble": args.ensemble_k},
    }
    done = set()
    if os.path.exists(args.out):
        with open(args.out) as f:
            for line in f:
                try:
                    r = json.loads(line)
                    done.add((r["arm"], r["seed"]))
                except Exception:
                    pass
    summary = {}
    for arm in args.arms.split(","):
        for seed in (int(s) for s in args.seeds.split(",")):
            if (arm, seed) in done:
                print(f"skip {arm}/{seed} (already in {args.out})",
                      flush=True)
                continue
            cfg = flagship_config(seed=seed, **arms[arm])
            t0 = time.perf_counter()
            res = run(cfg)
            rec = {
                "arm": arm, "seed": seed,
                "top5": round(res["test_top5_error"], 2),
                "top1": round(res["test_top1_error"], 2),
                "wallclock_s": round(time.perf_counter() - t0, 1),
            }
            with open(args.out, "a") as f:
                f.write(json.dumps(rec) + "\n")
            print(json.dumps(rec), flush=True)
            summary.setdefault(arm, {})[seed] = rec["top5"]
    print("SUMMARY " + json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
