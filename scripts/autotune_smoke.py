"""End-to-end smoke of the Pallas tile autotuner (seconds, CPU).

Exercises the full sweep → persist → reload → zero-re-sweep contract on a
tiny interpret-mode grid — exactly what ``tests/test_autotune.py`` pins,
but visible in the terminal and runnable on its own
(``make autotune-smoke``; folded into ``verify-fast``):

1. With ``KEYSTONE_AUTOTUNE=1`` and a temp cache, resolving the sift/fv
   kernel tiles sweeps once per (kernel, bucket) and persists winners.
2. The in-memory mirror is dropped; re-resolution must reload the
   persisted file and perform ZERO new sweeps (pure ``autotune.cache_hit``).
3. An ``overlap.tiles`` winner recorded through the public API must be
   consumed by ``parallel/overlap.py::_pick_tiles`` — and an explicit
   ``KEYSTONE_OVERLAP_TILES`` override must still beat it.
"""

import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

_TMP = tempfile.mkdtemp(prefix="keystone_autotune_smoke_")
_CACHE = os.path.join(_TMP, "autotune_cache.json")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["KEYSTONE_AUTOTUNE"] = "1"
os.environ["KEYSTONE_AUTOTUNE_CACHE"] = _CACHE
os.environ["KEYSTONE_AUTOTUNE_GRID"] = "2"  # tiny grid: 2 candidates/kernel

import keystone_tpu  # noqa: E402  (compat shims first)
from keystone_tpu.ops.pallas import autotune  # noqa: E402
from keystone_tpu.ops.pallas.extraction import (  # noqa: E402
    fv_encode_tile,
    sift_bins_tile,
)
from keystone_tpu.telemetry import get_registry  # noqa: E402


def _counts():
    reg = get_registry()
    return (
        sum(reg.counters("autotune.sweep").values()),
        sum(reg.counters("autotune.cache_hit").values()),
    )


def main() -> int:
    reg = get_registry()
    reg.reset()

    t_sift = sift_bins_tile(96, 48, 52)
    t_fv = fv_encode_tile(64, 16, 8)
    sweeps, hits = _counts()
    assert sweeps == 2, f"expected 2 sweeps (one per kernel), got {sweeps}"
    assert os.path.exists(_CACHE), "winners were not persisted"
    print(f"autotune-smoke: swept sift.bins->{t_sift} fv.encode->{t_fv} "
          f"({sweeps} sweeps), cache at {_CACHE}")

    # Fresh-process simulation: drop the mirror, re-resolve — the persisted
    # file must serve both winners with zero new sweeps.
    autotune.clear_memory_cache()
    assert sift_bins_tile(96, 48, 52) == t_sift
    assert fv_encode_tile(64, 16, 8) == t_fv
    sweeps2, hits2 = _counts()
    assert sweeps2 == sweeps, (
        f"repeat resolution re-swept: {sweeps2} != {sweeps}"
    )
    assert hits2 >= hits + 2, "repeat resolution did not hit the cache"
    print(f"autotune-smoke: reload hit the persisted cache "
          f"({hits2 - hits} hits, 0 re-sweeps)")

    # Overlap consumption: a recorded winner becomes _pick_tiles' default,
    # and the env override still beats it.
    from keystone_tpu.parallel.overlap import _pick_tiles

    dim, k = 96, 4
    autotune.record(
        "overlap.tiles", autotune.shape_bucket(dim, k), 3, swept=1
    )
    assert _pick_tiles(dim, k) == 3, "_pick_tiles ignored the tuned winner"
    os.environ["KEYSTONE_OVERLAP_TILES"] = "2"
    try:
        assert _pick_tiles(dim, k) == 2, "env override lost to the tuner"
    finally:
        del os.environ["KEYSTONE_OVERLAP_TILES"]
    print("autotune-smoke: _pick_tiles consumes tuned default, "
          "KEYSTONE_OVERLAP_TILES still wins — ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
