"""Plan-contract smoke (``make plan-smoke``; folded into verify-fast).

End-to-end pin of the whole-pipeline-optimizer contract on a tiny DAG, in
seconds on CPU:

1. plan under a deliberately small HBM budget -> the plan FITS and the
   budget is a BINDING constraint (the chosen block size is below the
   hand-tuned default — the computed answer differs from the hand answer);
2. repeat plan in the same process -> served from the in-memory memo,
   ZERO re-plans;
3. repeat plan with the in-memory memo cleared (the fresh-process
   simulation) -> served from the persisted ``KEYSTONE_PLAN_CACHE``
   artifact, still ZERO re-plans;
4. run the planned pipeline twice -> bit-identical outputs and ZERO
   recompiles on the repeat (the shared jit entry's cache size is flat).
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# knob production for the child checks (the bench's subprocess-control
# idiom): a small budget that binds, optimizer on
os.environ["KEYSTONE_OPTIMIZER"] = "estimate"
os.environ["KEYSTONE_HBM_BUDGET"] = "16"

import numpy as np  # noqa: E402


def fail(msg: str) -> None:
    print(f"plan-smoke FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from keystone_tpu.core import plan
    from keystone_tpu.core.pipeline import _jit_apply_batch
    from keystone_tpu.telemetry import get_registry

    tmp = tempfile.mkdtemp(prefix="plan_smoke_")
    cache_path = os.path.join(tmp, "plan_cache.json")
    os.environ["KEYSTONE_PLAN_CACHE"] = cache_path

    pipe, sample, sites = plan._TARGETS["toy"](True)
    budget = plan.hbm_budget_bytes()
    if budget != 16 << 20:
        fail(f"KEYSTONE_HBM_BUDGET not honored: {budget}")
    reg = get_registry()

    def build():
        return plan.plan_pipeline(
            pipe, sample, budget_bytes=budget, block_sites=sites
        )

    p = build()
    if not p.fits:
        fail(f"plan does not fit the {budget >> 20} MiB budget:\n"
             + p.summary())
    block = p.block_sizes["toy.solver"]
    default = sites[0]["default"]
    if not (0 < block < default):
        fail(f"budget is not a binding constraint: block {block} vs "
             f"hand default {default} (expected planned < default)")
    peak = plan.block_solve_peak_bytes(
        block, n_rows=sites[0]["n_rows"], num_classes=sites[0]["num_classes"]
    )
    if peak > budget:
        fail(f"chosen block {block} peak {peak} exceeds budget {budget}")
    print(f"plan-smoke: fits budget, binding block size {block} < {default}")

    # 2: in-process repeat -> memo hit, zero re-plans
    computed = reg.get_counter("plan.computed")
    build()
    if reg.get_counter("plan.computed") != computed:
        fail("repeat plan_pipeline re-planned (memo miss)")
    # 3: fresh-process simulation -> persisted cache hit, zero re-plans
    with plan._PLAN_LOCK:
        plan._PLAN_MEMO.clear()
    if not os.path.exists(cache_path):
        fail("KEYSTONE_PLAN_CACHE artifact was not written")
    build()
    if reg.get_counter("plan.computed") != computed:
        fail("cold repeat re-planned despite the persisted plan cache")
    if not reg.get_counter("plan.cache_hit", tier="disk"):
        fail("cold repeat did not hit the persisted plan cache")
    print("plan-smoke: zero re-plans (memo + persisted cache)")

    # 4: run the planned pipeline twice -> identical outputs, no recompile
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=sample.shape).astype("float32")
    )
    planned = plan.apply_plan(pipe, p)
    out1 = jax.block_until_ready(planned(x))
    size1 = _jit_apply_batch._cache_size()
    out2 = jax.block_until_ready(planned(x))
    size2 = _jit_apply_batch._cache_size()
    if size2 != size1:
        fail(f"repeat run recompiled: jit cache {size1} -> {size2}")
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    print("plan-smoke: repeat run zero recompiles, outputs bit-identical")
    print("plan-smoke PASS")


if __name__ == "__main__":
    main()
