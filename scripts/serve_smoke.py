"""Serving-gateway smoke over the MNIST chain (<20 s, CPU): the
`make serve-smoke` rung of `verify-fast`.

Pins, through the REAL pipeline (``pipelines/mnist_random_fft.py``
featurizer >> a fitted block-least-squares model) served by
``keystone_tpu/serve/gateway.py``:

1. Gateway predictions MATCH the batch apply path — the padded
   fixed-shape dispatch serves the same model the fit produced.
2. Steady-state serving performs ZERO recompiles (the compiled shape
   ladder + padded dispatch contract).
3. Overload against the bounded queue sheds with a structured
   retry-after response (ONE shed asserted) while admitted work still
   serves.
4. A NaN-poisoned dispatch (``KEYSTONE_FAULTS serve.dispatch`` numeric
   kind) trips the sentinel/breaker (ONE breaker trip asserted), the
   half-open probe re-admits the model, and serving resumes.
5. ``close(drain=True)`` serves the whole admitted backlog before
   stopping — the graceful-drain contract (no request left hanging).
"""

from __future__ import annotations

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("KEYSTONE_FAULTS", None)

t_start = time.monotonic()

BUDGET_S = 20.0


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.learning import BlockLeastSquaresEstimator
    from keystone_tpu.loaders.mnist import synthetic_mnist_device
    from keystone_tpu.ops.util import ClassLabelIndicatorsFromIntLabels
    from keystone_tpu.pipelines.mnist_random_fft import (
        MnistRandomFFTConfig,
        build_featurizer,
    )
    from keystone_tpu.serve import serve
    from keystone_tpu.telemetry import get_registry
    from keystone_tpu.utils import faults

    reg = get_registry()

    # tiny fitted MNIST chain: one random-FFT featurizer >> block LS model
    cfg = MnistRandomFFTConfig(num_ffts=1, block_size=512, lam=10.0)
    feat = build_featurizer(cfg)[0]
    x, y = synthetic_mnist_device(512, seed=7)
    model = BlockLeastSquaresEstimator(512, num_iter=1, lam=10.0).fit(
        feat(x), ClassLabelIndicatorsFromIntLabels(10)(y)
    )
    pipe = feat >> model
    spec = jax.ShapeDtypeStruct((x.shape[1],), np.float32)

    # 1+2: parity with the batch apply path, zero steady-state recompiles
    gw = serve(pipe, item_spec=spec, shapes=(1, 4), slo_ms=10_000.0,
               queue_depth=32, breaker_threshold=1,
               breaker_cooldown_s=0.1)
    size0 = gw.compile_cache_size()
    ref = np.asarray(pipe.apply_batch(x[:8]))
    pend = [gw.submit(np.asarray(x[i])) for i in range(8)]
    rs = [p.result(20) for p in pend]
    assert all(r.ok for r in rs), [r.code for r in rs]
    got = np.stack([np.asarray(r.value) for r in rs])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    assert np.array_equal(np.argmax(got, 1), np.argmax(ref, 1))
    assert gw.compile_cache_size() == size0, "steady-state recompile"
    print("serve-smoke 1-2/5: gateway matches the batch apply "
          "(8/8 argmax), zero steady-state recompiles")

    # 3: bounded-queue shed with the gateway paused (deterministic burst)
    gw.close()
    gw = serve(pipe, item_spec=spec, shapes=(1, 4), slo_ms=10_000.0,
               queue_depth=8, breaker_threshold=1,
               breaker_cooldown_s=0.1, warm=False, start=False)
    burst = [gw.submit(np.asarray(x[i])) for i in range(10)]
    shed = [p.result(0.5) for p in burst[8:]]
    assert all(r.code == "shed" and r.retry_after_s for r in shed), shed
    gw.start()
    assert all(p.result(20).ok for p in burst[:8]), "admitted work lost"
    assert int(reg.counter_family_total("serve.shed_total")) >= 2
    print("serve-smoke 3/5: overload shed structured (retry-after set), "
          "admitted backlog still served")

    # 4: NaN-poisoned dispatch -> breaker trip -> half-open recovery
    trips0 = reg.get_counter("serve.sentinel_trips", model="default")
    os.environ["KEYSTONE_FAULTS"] = "serve.dispatch@0:nan"
    faults.reset()
    r = gw.submit(np.asarray(x[0])).result(20)
    os.environ.pop("KEYSTONE_FAULTS", None)
    faults.reset()
    assert r.code == "sentinel", r
    assert reg.get_counter(
        "serve.sentinel_trips", model="default") > trips0
    assert gw.breaker_state() == "open", gw.breaker_state()
    time.sleep(0.12)
    assert gw.submit(np.asarray(x[1])).result(20).ok, "probe failed"
    assert gw.breaker_state() == "closed"
    print("serve-smoke 4/5: poisoned dispatch tripped the breaker, "
          "half-open probe recovered it")

    # 5: graceful drain — everything admitted before close() serves
    backlog = [gw.submit(np.asarray(x[i])) for i in range(6)]
    gw.close(drain=True)
    drained = [p.result(5) for p in backlog]
    assert all(r.ok for r in drained), [r.code for r in drained]
    assert gw.submit(np.asarray(x[0])).result(1).code == "shutdown"
    print("serve-smoke 5/5: graceful drain served 6/6, post-close "
          "submissions get structured shutdown")

    elapsed = time.monotonic() - t_start
    print(f"serve-smoke OK in {elapsed:.1f}s")
    assert elapsed < BUDGET_S, f"smoke took {elapsed:.1f}s (>{BUDGET_S}s)"
    return 0


if __name__ == "__main__":
    sys.exit(main())
