"""Fleet observability smoke (<20 s, CPU): the `make obs-smoke` rung of
`verify-fast`.

Pins, through REAL replica worker processes (``keystone_tpu/serve/
fleet.py`` with ``KEYSTONE_TELEMETRY_DIR`` exported to every worker):

1. Each replica writes its OWN pid+role-unique telemetry shard at exit
   (no atexit clobber), and the merged counter totals EXACTLY equal the
   per-shard sums — `keystone-tpu obs` totals are exact, not sampled.
2. A client-minted trace id rides the unix-socket frame into a replica:
   the stitched Perfetto file contains spans from >= 2 OS processes
   (driver + replica) sharing that id, connected by flow arrows.
3. The ``keystone-tpu obs`` CLI renders the merged dir with rc=0.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("KEYSTONE_FAULTS", None)
os.environ.pop("KEYSTONE_TELEMETRY_DIR", None)

t_start = time.monotonic()

BUDGET_S = 20.0


def main() -> int:
    import subprocess

    import numpy as np

    from keystone_tpu.serve.builders import two_tenant
    from keystone_tpu.serve.fleet import Fleet
    from keystone_tpu.serve.front import mint_trace_id
    from keystone_tpu.telemetry import (
        export_process,
        get_tracer,
        merge_shards,
        merge_traces,
        use_tracing,
    )
    from keystone_tpu.telemetry.trace import request_span

    tdir = tempfile.mkdtemp(prefix="keystone-obs-smoke-")
    tid = mint_trace_id()
    with Fleet("two_tenant", replicas=2, shapes="1,4",
               coalesce_ms=0.0, queue_depth=32, slo_ms=10_000.0,
               env={"KEYSTONE_TELEMETRY_DIR": tdir}) as f:
        assert f.live_count() == 2, f.stats()
        items = {
            s.name: np.linspace(-1.0, 1.0, int(s.item_spec.shape[0]),
                                dtype=np.float32)
            for s in two_tenant()
        }
        models = sorted(items)
        model = models[0]
        # the driver's half of the distributed trace: a client-side span
        # carrying the same id the replica's serve-path spans will carry
        with use_tracing(True):
            with request_span("client.predict", tid, model=model):
                r = f.predict(items[model], model=model,
                              deadline_ms=10_000, trace_id=tid)
        assert r["ok"] is True, r
        assert r["trace"] == tid, r
        n_req = 6
        for i in range(n_req - 1):
            m = models[i % len(models)]
            r = f.predict(items[m], model=m, deadline_ms=10_000)
            assert r["ok"] is True, r
    # fleet closed: every worker's atexit wrote its shard. The driver's
    # half of the trace (the client-side span) exports alongside them.
    os.environ["KEYSTONE_TELEMETRY_ROLE"] = "driver"
    export_process(tdir, tracer=get_tracer())

    # 1: unique shards, merged totals == exact per-shard sums
    shard_files = sorted(n for n in os.listdir(tdir)
                         if n.startswith("telemetry_shard-"))
    assert len(shard_files) == 3, shard_files  # 2 replicas + driver
    per_shard = 0.0
    for name in shard_files:
        with open(os.path.join(tdir, name)) as fh:
            metrics = json.load(fh)["metrics"]
        for key, value in (metrics.get("counters") or {}).items():
            if key.startswith("serve.requests"):
                per_shard += value
    view = merge_shards(tdir, prune=False)
    merged_total = sum(
        v for k, v in view["merged"]["counters"].items()
        if k.startswith("serve.requests")
    )
    assert merged_total == per_shard == n_req, (merged_total, per_shard)
    roles = sorted(p["role"] for p in view["procs"])
    assert roles == ["driver", "replica-0", "replica-1"], roles
    print(f"obs-smoke 1/3: {len(shard_files)} pid+role-unique shards, "
          f"merged serve.requests == exact shard sum == {n_req}")

    # 2: one stitched Perfetto trace spanning >= 2 OS processes
    trace_path = os.path.join(tdir, "stitched_trace.json")
    merged = merge_traces(tdir, out_path=trace_path, prune=False)
    traced = [e for e in merged["traceEvents"] if e.get("ph") == "X"
              and (e.get("args") or {}).get("trace_id") == tid]
    pids = {e["pid"] for e in traced}
    assert len(pids) >= 2, (pids, [e["name"] for e in traced])
    flows = [e for e in merged["traceEvents"]
             if e.get("ph") in ("s", "t", "f") and e.get("id") == tid]
    assert flows, "no flow arrows for the request trace"
    names = {e["name"] for e in traced}
    assert "serve.admit" in names and "serve.reply" in names, names
    print(f"obs-smoke 2/3: trace {tid} stitched across {len(pids)} OS "
          f"processes ({len(traced)} spans, {len(flows)} flow arrows)")

    # 3: the obs CLI renders the dir, rc=0
    proc = subprocess.run(
        [sys.executable, "-m", "keystone_tpu.cli", "obs", tdir,
         "--format", "json"],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout)
    assert out["signals"]["serve"]["requests"] == n_req, out["signals"]
    print("obs-smoke 3/3: `keystone-tpu obs` rc=0, signals.serve."
          f"requests == {n_req}")

    dt = time.monotonic() - t_start
    print(f"obs-smoke PASS in {dt:.1f}s")
    if dt > BUDGET_S:
        print(f"obs-smoke OVER BUDGET ({dt:.1f}s > {BUDGET_S}s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
