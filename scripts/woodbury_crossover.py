"""Measure the dense-vs-Woodbury class-solve crossover on the real chip.

VERDICT r2 weak #8: ``_use_woodbury``'s threshold (``max_nc + 1 <= bs // 8``)
was set conservatively without on-chip evidence. This script times
``_bucketed_class_solves`` at the flagship block size (bs=4096) with the
Woodbury path forced ON and OFF at several max_nc/bs ratios and prints one
JSON line per point — the measured basis for the threshold (quoted in the
``_use_woodbury`` docstring).

Run on the TPU: ``python scripts/woodbury_crossover.py``.
Timing is latency-cancelled: each measurement chains K solves and subtracts
a 1-solve run, so the tunnel round-trip (~100 ms) drops out.
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

import keystone_tpu.learning.block_weighted as bw

# Constructed once at module scope: wrapping inside build_case would mint a
# fresh jit object (and XLA compile) per case (lint R2).
_pop_stats_jit = jax.jit(bw._pop_stats, static_argnames=("precision",))


def build_case(bs: int, nc: int, num_classes: int, seed: int = 0):
    n = nc * num_classes
    rng = np.random.default_rng(seed)
    X = jnp.asarray(rng.normal(size=(n, bs)).astype(np.float32))
    lab = np.arange(n) % num_classes
    rng.shuffle(lab)
    ind = -np.ones((n, num_classes), np.float32)
    ind[np.arange(n), lab] = 1.0
    labels = jnp.asarray(ind)
    class_idx, counts, valid = bw._prepare(labels, None, num_classes)
    n_eff = jnp.sum(counts).astype(jnp.float32)
    R = (labels - 0.1) * valid[:, None]
    buckets, inv_perm = bw._class_buckets(
        np.asarray(counts), np.asarray(class_idx)
    )
    prec = "high"
    pop_mean, pop_cov, pop_xtr = _pop_stats_jit(
        X, R, valid, n_eff, precision=prec
    )
    w, lam = jnp.float32(0.25), jnp.float32(6e-5)
    base_inv = bw._base_inverse(pop_cov, lam, w, prec)[0]
    class_sums = bw._class_sums(X, class_idx, num_classes)
    class_means = class_sums / jnp.maximum(
        counts[:, None].astype(jnp.float32), 1.0
    )
    joint_means_b = w * class_means + (1.0 - w) * pop_mean
    _, residual_mean = bw._class_col_means(R, class_idx, counts)
    model0 = jnp.zeros((bs, num_classes), jnp.float32)
    return dict(
        Xb=X, R=R, counts=counts, pop_cov=pop_cov, pop_mean=pop_mean,
        pop_xtr=pop_xtr, joint_means_b=joint_means_b,
        residual_mean=residual_mean, model_b=model0, lam=lam, w=w,
        buckets=buckets, inv_perm=inv_perm, base_inv=base_inv,
        precision=prec,
    )


def timed_solves(case, woodbury: bool, iters: int = 3) -> float:
    orig = bw._use_woodbury
    bw._use_woodbury = lambda max_nc, bs: woodbury
    try:
        def once(shift):
            return bw._bucketed_class_solves(
                case["Xb"], case["R"] + shift, case["counts"], case["pop_cov"],
                case["pop_mean"], case["pop_xtr"], case["joint_means_b"],
                case["residual_mean"], case["model_b"], case["lam"], case["w"],
                case["buckets"], case["inv_perm"], case["base_inv"],
                precision=case["precision"],
            )

        def chain(k):
            outs = [once(1e-6 * i) for i in range(k)]
            float(outs[-1].sum())  # warm + drain
            t0 = time.perf_counter()
            outs = [once(1e-5 * i) for i in range(k)]
            float(outs[-1].sum())
            return time.perf_counter() - t0

        return (chain(1 + iters) - chain(1)) / iters
    finally:
        bw._use_woodbury = orig


def main():
    bs = 4096
    for ratio_name, nc, C in (("1/16", 256, 32), ("1/8", 512, 16),
                              ("1/4", 1024, 8), ("1/2", 2048, 4)):
        case = build_case(bs, nc, C)
        t_w = timed_solves(case, True)
        t_d = timed_solves(case, False)
        print(json.dumps({
            "bs": bs, "max_nc_over_bs": ratio_name, "nc": nc, "classes": C,
            "woodbury_s": round(t_w, 4), "dense_s": round(t_d, 4),
            "woodbury_speedup": round(t_d / t_w, 2),
        }))


if __name__ == "__main__":
    main()
