"""End-to-end smoke of the kernel variant search (<20 s, CPU).

The contract ``make verify-fast`` rides, visible in the terminal instead
of buried in a fixture: against a THROWAWAY cache (never the committed
``autotune_cache.json``), a tiny interpret-mode sweep of the fused-span
kernel's full variant space (``conv.pool``: split | fused.yx | fused.xy)

1. validates every challenger (parity + ir_rules gate: ``variants.
   rejected`` stays zero on the clean repo), sweeps each variant's tile
   grid once, and persists bare + ``#variant`` entries side by side;
2. RELOADED (in-memory mirror dropped = the fresh-process case) serves
   the measured cross-variant winner with ZERO re-sweeps — the
   ``autotune.sweep`` counter is flat across the reload;
3. the fused variants stay bit-envelope equivalent to the split pair
   (the conv intermediate leaving VMEM must never change the answer).

``make kernel-search-smoke``; folded into ``verify-fast``.
"""

import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_TMP = tempfile.mkdtemp(prefix="kernel_search_smoke_")
os.environ["KEYSTONE_AUTOTUNE_CACHE"] = os.path.join(
    _TMP, "autotune_cache.json"
)
os.environ["KEYSTONE_AUTOTUNE"] = "1"
os.environ["KEYSTONE_AUTOTUNE_BUDGET_S"] = "10"
# one tile candidate per variant: the smoke pins the SEARCH protocol
# (validate -> sweep -> persist -> reload -> zero re-sweeps), not the grid
os.environ["KEYSTONE_AUTOTUNE_GRID"] = "1"

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from keystone_tpu.ops.pallas import autotune, variants  # noqa: E402
from keystone_tpu.ops.pallas.extraction import (  # noqa: E402
    conv_norm_pool,
    conv_pool_plan,
)
from keystone_tpu.telemetry import get_registry  # noqa: E402

_BUDGET_S = 20.0
# tiny CIFAR-shaped geometry: every tile candidate fits, sweeps are ms
_H, _W, _C, _KSZ, _NF = 14, 14, 3, 5, 32
_STRIDE, _POOL = 2, 3


def _count(name: str) -> float:
    return sum(get_registry().counters(name).values())


def main() -> int:
    t0 = time.monotonic()

    s0 = _count("autotune.sweep")
    r0 = _count("variants.rejected")
    variant, tile = conv_pool_plan(
        _H, _W, _C, _KSZ, _NF, stride=_STRIDE, pool_size=_POOL,
    )
    swept = _count("autotune.sweep") - s0
    assert tile is not None, "no tile fit the smoke geometry"
    assert variant in variants.known_variants("conv.pool"), variant
    assert swept >= 2, f"expected a full variant sweep, got {swept} sweeps"
    assert _count("variants.rejected") == r0, (
        "a variant failed the parity/ir_rules gate on the clean repo"
    )
    # bare + #variant entries persisted side by side
    bucket = autotune.shape_bucket(_H, _W, _NF)
    assert autotune.peek_entry("conv.pool", bucket) is not None
    for name in variants.known_variants("conv.pool")[1:]:
        assert autotune.peek_entry("conv.pool", f"{bucket}#{name}"), name

    # the fresh-process case: reload -> same winner, ZERO re-sweeps
    autotune.clear_memory_cache()
    s1 = _count("autotune.sweep")
    again = conv_pool_plan(
        _H, _W, _C, _KSZ, _NF, stride=_STRIDE, pool_size=_POOL,
    )
    assert again == (variant, tile), (again, variant, tile)
    assert _count("autotune.sweep") == s1, "a persisted winner was re-swept"

    # fused parity vs the split pair on the served tile
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(
        rng.uniform(0, 1, (2, _H, _W, _C)).astype(np.float32)
    )
    filters = jnp.asarray(
        rng.normal(size=(_NF, _KSZ * _KSZ * _C)).astype(np.float32)
    )
    kw = dict(num_channels=_C, normalize=True, var_constant=10.0,
              stride=_STRIDE, pool_size=_POOL, tile_f=tile, interpret=True)
    split = np.asarray(conv_norm_pool(imgs, filters, variant="split", **kw))
    denom = float(np.max(np.abs(split))) + 1e-9
    for name in ("fused.yx", "fused.xy"):
        fused = np.asarray(conv_norm_pool(imgs, filters, variant=name, **kw))
        err = float(np.max(np.abs(fused - split))) / denom
        assert err <= 2e-5, f"{name} diverged from split: rel err {err:.2e}"

    dt = time.monotonic() - t0
    assert dt < _BUDGET_S, f"kernel-search smoke too slow: {dt:.1f}s"
    print(
        f"kernel-search smoke OK in {dt:.1f}s: winner {variant}/{tile} "
        f"after {swept:.0f} sweeps, reload re-swept 0, fused==split"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
