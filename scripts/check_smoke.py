"""Pipeline-contract checker smoke (< 20 s): the contract `make
verify-fast` rides.

Asserts, end to end through the REAL CLI code path:

1. every registered pipeline target builds and checks CLEAN against the
   committed (empty) ``check_baseline.json`` — zero new findings, zero
   build errors, rc=0;
2. the JSON output schema holds (the keys bench.py and the tests read);
3. a deliberately mis-chained pipeline (rank mismatch between SIFT
   extraction and FV encode) is REJECTED at construction time — zero data
   loaded, zero compiles — with both stages named;
4. the whole pass stays under the 20 s budget (pre-dispatch abstract
   evaluation must stay cheap enough to run on every CI loop).
"""

from __future__ import annotations

import io
import json
import os
import sys
import time
from contextlib import redirect_stdout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BUDGET_S = 20.0


def main() -> int:
    t0 = time.monotonic()
    os.chdir(REPO)

    from keystone_tpu.analysis.check import CHECK_TARGETS, main as check_main

    # 1 + 2: all registered targets, JSON schema, rc=0 vs the committed
    # baseline
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = check_main(["--format", "json", "--root", REPO])
    payload = json.loads(buf.getvalue())
    assert rc == 0, f"keystone-tpu check rc={rc}: {payload}"
    for key in ("new", "baselined", "suppressed", "targets", "errors",
                "total"):
        assert key in payload, f"missing JSON key {key}"
    assert payload["new"] == [], payload["new"]
    assert payload["errors"] == [], payload["errors"]
    expected = {"mnist", "cifar", "timit", "voc", "imagenet"}
    assert expected <= set(payload["targets"]), (
        f"registry lost a pipeline: {payload['targets']}"
    )
    assert expected <= set(CHECK_TARGETS)

    # 3: the acceptance scenario — a rank mismatch inserted between SIFT
    # extraction and FV encode must be rejected AT CONSTRUCTION
    import jax.numpy as jnp

    from keystone_tpu.analysis.contracts import ContractViolation
    from keystone_tpu.core.pipeline import chain
    from keystone_tpu.learning.gmm import GaussianMixtureModel
    from keystone_tpu.ops.images import SIFTExtractor
    from keystone_tpu.ops.images.fisher_vector import FisherVector
    from keystone_tpu.ops.util import MatrixVectorizer

    gmm = GaussianMixtureModel(
        means=jnp.zeros((4, 16), jnp.float32),
        variances=jnp.ones((4, 16), jnp.float32),
        weights=jnp.ones((4,), jnp.float32) / 4,
    )
    try:
        chain(SIFTExtractor(), MatrixVectorizer(), FisherVector(gmm=gmm))
    except ContractViolation as e:
        msg = str(e)
        assert "MatrixVectorizer" in msg and "FisherVector" in msg, msg
        assert e.findings and e.findings[0].rule == "C1"
    else:
        raise AssertionError(
            "mis-chained SIFT->vectorize->FV was NOT rejected at "
            "construction"
        )

    elapsed = time.monotonic() - t0
    assert elapsed < BUDGET_S, (
        f"check smoke took {elapsed:.1f}s (budget {BUDGET_S}s)"
    )
    print(
        f"check-smoke OK: {len(payload['targets'])} targets clean, "
        f"mis-chain rejected at construction, {elapsed:.1f}s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
