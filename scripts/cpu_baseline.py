"""Measure the CPU anchor for ``vs_baseline`` (VERDICT round-1 item 2).

The north star (BASELINE.json) compares against a 64-core Spark cluster we
cannot run here (no JVM); the honest measurable anchor is the SAME pipeline
math executed by jax-CPU on this host (state the core count — this image
exposes 1 core). Run with::

    JAX_PLATFORMS=cpu python scripts/cpu_baseline.py

Prints one JSON object and writes it to ``cpu_baseline.json`` at the repo
root; ``bench.py`` reads that file and reports
``vs_baseline = cpu_wallclock / tpu_warm_wallclock``.

MNIST runs the full flagship config (60k×784, numFFTs=4, blockSize=2048 —
``README.md:14-22`` of the reference). TIMIT's full config (100k frames,
50×4096 cosine features, 5 epochs) is ~8.4e13 solver FLOPs — hours on one
core — so it is measured at ``--timit-scale 1/25`` (2 epochs × 10 blocks)
and extrapolated linearly in block-passes; the scaling is stated in the
output and in BASELINE.md. Both numbers are the warm (second) invocation,
matching how bench.py times the TPU.
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)

import argparse
import json
import multiprocessing
import os
import platform
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-timit", action="store_true")
    ap.add_argument("--skip-mnist", action="store_true")
    ap.add_argument("--skip-text", action="store_true")
    ap.add_argument("--skip-images", action="store_true")
    ap.add_argument("--skip-flagship", action="store_true")
    args = ap.parse_args()

    import jax

    # sitecustomize imports jax with the axon (TPU) platform at interpreter
    # startup; env vars are too late. Re-pin to CPU before backend init.
    jax.config.update("jax_platforms", "cpu")
    assert jax.default_backend() == "cpu", (
        "could not select jax-cpu (got %s)" % jax.default_backend()
    )
    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "cpu_baseline.json")
    # merge into any existing anchor file so sections can be re-measured
    # independently (each --skip-* leaves the old entry intact) — but only
    # when the old entries come from THIS host; mixing hosts would silently
    # misattribute timings to the recorded host_cores/platform
    host = {
        "host_cores": multiprocessing.cpu_count(),
        "platform": platform.platform(),
        "backend": "jax-cpu",
    }
    out = {}
    if os.path.exists(path):
        with open(path) as f:
            prev = json.load(f)
        if all(prev.get(k) == v for k, v in host.items()):
            out = prev
        else:
            print(
                "cpu_baseline.json is from a different host "
                f"({prev.get('platform')}, {prev.get('host_cores')} cores); "
                "discarding its entries", file=sys.stderr,
            )
    out.update(host)

    if not args.skip_mnist:
        from keystone_tpu.pipelines.mnist_random_fft import (
            MnistRandomFFTConfig,
            run as run_mnist,
        )

        cfg = MnistRandomFFTConfig(
            num_ffts=4, block_size=2048, lam=10.0,
            synthetic_train=60000, synthetic_test=10000,
        )
        run_mnist(cfg)  # cold (compile)
        t0 = time.perf_counter()
        res = run_mnist(cfg)
        out["mnist_random_fft_cpu_warm_s"] = round(time.perf_counter() - t0, 3)
        out["mnist_train_error_pct"] = round(res["train_error"], 3)

    if not args.skip_text:
        from keystone_tpu.pipelines.newsgroups import (
            NewsgroupsConfig,
            run as run_news,
        )
        from keystone_tpu.pipelines.stupid_backoff import (
            StupidBackoffConfig,
            run as run_sb,
        )

        # The CPU anchor runs each text pipeline in its BEST CPU
        # configuration: device_path=False selects the fused host
        # featurization (numpy + native C++ count_by_key), which on one
        # jax-CPU core is ~10-20x faster than forcing the TPU-shaped XLA
        # sort/segment programs through a single core. The TPU side of the
        # ratio uses its own best path (device counting) — both sides
        # best-vs-best, stated in BASELINE.md.
        ncfg = NewsgroupsConfig(synthetic_train=20000, synthetic_test=4000,
                                synthetic_classes=20, common_features=100000,
                                device_path=False)
        run_news(ncfg)  # cold
        t0 = time.perf_counter()
        run_news(ncfg)
        out["newsgroups_cpu_warm_s"] = round(time.perf_counter() - t0, 3)

        scfg = StupidBackoffConfig(synthetic_docs=20000, device_path=False)
        run_sb(scfg)  # cold
        t0 = time.perf_counter()
        run_sb(scfg)
        out["stupid_backoff_cpu_warm_s"] = round(time.perf_counter() - t0, 3)

    if not args.skip_images:
        # the image track's anchors: VOC small-config (1024/256 imgs 96²,
        # vocab 16) and ImageNet small-config (2048/512 imgs 96², SIFT+LCS
        # branches) — full extract→PCA→GMM→FV→solve→eval on jax-CPU. The
        # reference-dim configs (vocab 256, 1000 classes) extrapolate
        # linearly in images and ~16× in FV/GMM width; stated, not run
        # (hours on one core).
        from keystone_tpu.pipelines.voc_sift_fisher import (
            small_config as voc_small_config,
            run as run_voc,
        )

        vcfg = voc_small_config()  # the SAME construction bench.py times
        run_voc(vcfg)  # cold
        t0 = time.perf_counter()
        run_voc(vcfg)
        out["voc_small_cpu_warm_s"] = round(time.perf_counter() - t0, 3)

        from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
            small_config as imagenet_small_config,
            run as run_imagenet,
        )

        icfg = imagenet_small_config()
        run_imagenet(icfg)  # cold
        t0 = time.perf_counter()
        run_imagenet(icfg)
        out["imagenet_small_cpu_warm_s"] = round(time.perf_counter() - t0, 3)

    if not args.skip_flagship:
        # Flagship (reference-dim streaming ImageNet) anchor, TIMIT-style:
        # the full config (n=102 400 rows, d=65 536 -> B=16 feature blocks)
        # is days on one core, so measure four scaled configs of the SAME
        # streaming construction (fit_streaming + FV cache groups + Woodbury
        # class solves) and fit t(n, B) = c0 + c1*n + c2*B + c3*n*B — the
        # bilinear model of the two axes the flagship actually scales
        # (featurization + gram work are ~n*B; per-block solve overhead ~B;
        # per-row extraction ~n). B is set by vocab: d = 2*(64+64)*vocab,
        # B = d/4096 = vocab/16. Class count scales with n at the flagship's
        # rows-per-class ratio (n/102) so the per-class solve population is
        # represented, not degenerate. All four points + the fit constants
        # are published here; the extrapolation factor is large (200-400x in
        # n) and stated — same protocol as the TIMIT row.
        from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
            flagship_config,
            run as run_flagship,
        )

        def timed_flagship(n: int, vocab: int) -> float:
            cfg = flagship_config(
                synthetic_train=n,
                synthetic_test=max(64, n // 8),
                synthetic_classes=max(2, n // 102),
                vocab_size=vocab,
                num_pca_samples=100000,
                num_gmm_samples=100000,
                sample_images=min(n, 512),
                extract_chunk=256,
                fv_row_chunk=256,
            )
            run_flagship(cfg)  # cold (compile)
            best = float("inf")
            for _ in range(2):  # best-of-2: robust to background host load
                t0 = time.perf_counter()
                run_flagship(cfg)
                best = min(best, time.perf_counter() - t0)
            return best

        # vocab sets B = 2*(64+64)*vocab / 4096 = vocab/16; vocab >= 32 so a
        # branch's FV (2*vocab*64) spans at least one 4096 solver block (the
        # sliced-FV layout constraint) — so B in {2, 4}, same bs as flagship
        n1, n2, b1, b2 = 512, 1024, 2, 4
        t11 = timed_flagship(n1, 16 * b1)
        t21 = timed_flagship(n2, 16 * b1)
        t12 = timed_flagship(n1, 16 * b2)
        t22 = timed_flagship(n2, 16 * b2)
        c3 = (t22 - t21 - t12 + t11) / ((n2 - n1) * (b2 - b1))
        c1 = (t21 - t11) / (n2 - n1) - c3 * b1
        c2 = (t12 - t11) / (b2 - b1) - c3 * n1
        c0 = t11 - c1 * n1 - c2 * b1 - c3 * n1 * b1
        n_full, b_full = 102400, 16
        full = c0 + c1 * n_full + c2 * b_full + c3 * n_full * b_full
        out["imagenet_flagship_cpu_warm_measured_s"] = {
            f"{n1}n_{b1}B": round(t11, 2), f"{n2}n_{b1}B": round(t21, 2),
            f"{n1}n_{b2}B": round(t12, 2), f"{n2}n_{b2}B": round(t22, 2),
        }
        out["imagenet_flagship_cpu_warm_extrapolated_s"] = round(full, 1)
        out["imagenet_flagship_extrapolation"] = (
            f"t(n,B) = c0 + c1*n + c2*B + c3*n*B fitted on ({n1},{b1}), "
            f"({n2},{b1}), ({n1},{b2}), ({n2},{b2}) rows x feature-blocks "
            f"(best-of-2 warm runs each); c0={c0:.1f}s "
            f"c1={c1*1000:.2f}ms/row c2={c2:.1f}s/blk c3={c3*1000:.3f}ms/(row*blk); "
            f"evaluated at n={n_full}, B={b_full} (d=65536). Classes scale "
            "with n at the flagship rows-per-class ratio; hw=64 as flagship."
        )

    if not args.skip_timit:
        from keystone_tpu.pipelines.timit import TimitConfig, run as run_timit

        full_epochs, full_blocks = 5, 50

        def timed(epochs: int, blocks: int) -> float:
            tcfg = TimitConfig(
                synthetic_train=100000,
                synthetic_test=20000,
                num_epochs=epochs,
                num_cosines=blocks,
            )
            run_timit(tcfg)  # cold
            t0 = time.perf_counter()
            run_timit(tcfg)
            return time.perf_counter() - t0

        # Cost model t(e, b) = c0 + c1·b + c2·e·b: c0 = fixed overhead +
        # evaluation, c1 = per-block featurization (one pass), c2 = per-
        # epoch-block solver work (gram + cross-terms + solve). Three
        # measurements identify all three; no term is scaled by a factor it
        # does not actually grow with (a flat e·b scaling would inflate the
        # featurization and eval components). Configs kept small — each
        # block-epoch is ~3.4e12 solver FLOPs, minutes on one core.
        t_1_2 = timed(1, 2)
        t_1_4 = timed(1, 4)
        t_2_4 = timed(2, 4)
        c2 = (t_2_4 - t_1_4) / 4.0
        c1 = (t_1_4 - t_1_2) / 2.0 - c2
        c0 = t_1_2 - 2.0 * (c1 + c2)
        full = c0 + c1 * full_blocks + c2 * full_epochs * full_blocks
        out["timit_cpu_warm_measured_s"] = {
            "1ep_2blk": round(t_1_2, 3),
            "1ep_4blk": round(t_1_4, 3),
            "2ep_4blk": round(t_2_4, 3),
        }
        out["timit_cpu_warm_extrapolated_s"] = round(full, 1)
        out["timit_extrapolation"] = (
            "t(e,b) = c0 + c1*b + c2*e*b fitted on (1ep,2blk), (1ep,4blk), "
            f"(2ep,4blk); c0={c0:.1f}s c1={c1:.2f}s/blk c2={c2:.2f}s/(ep*blk); "
            f"evaluated at {full_epochs}ep*{full_blocks}blk"
        )

    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
