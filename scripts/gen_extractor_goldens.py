"""Generate committed descriptor-statistics goldens for the extractor nodes
on the reference's own test photos (VERDICT round-1 item 4).

The reference pins SIFT bitwise against MATLAB ``vl_phow`` output
(``VLFeatSuite.scala:44-51``); its golden CSVs are absent from the checkout
and no vlfeat binary exists in this image, so the strongest committable
anchor is a set of descriptor statistics on the same images the reference
tests with (``src/test/resources/images/000012.jpg``, ``gantrycrane.png``):
per-scale keypoint counts (pure geometry — must match ``vl_dsift`` exactly),
the quantized-value histogram, the mass-threshold zero fraction, and
summary moments for HOG/DAISY/LCS. Regenerate with::

    JAX_PLATFORMS=cpu python scripts/gen_extractor_goldens.py

Run on the CPU backend — the test env (tests/conftest.py) is CPU, and
integer statistics (counts, quantized histograms) are backend-exact while
float moments carry tolerances in the test.
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
)

import json
import os

import numpy as np


def _load_gray(path: str) -> np.ndarray:
    from PIL import Image

    img = np.asarray(Image.open(path).convert("L"), np.float32) / 255.0
    return img


def _load_rgb(path: str) -> np.ndarray:
    from PIL import Image

    return np.asarray(Image.open(path).convert("RGB"), np.float32) / 255.0


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from keystone_tpu.ops.images.daisy import DaisyExtractor
    from keystone_tpu.ops.images.hog import HogExtractor
    from keystone_tpu.ops.images.lcs import LCSExtractor
    from keystone_tpu.ops.images.sift import SIFTExtractor, dsift_geometry

    res = "/root/reference/src/test/resources/images"
    out: dict = {}
    edges = [0, 1, 2, 4, 8, 16, 32, 64, 128, 256]
    for name in ("000012.jpg", "gantrycrane.png"):
        gray = _load_gray(os.path.join(res, name))
        rgb = _load_rgb(os.path.join(res, name))
        h, w = gray.shape
        entry: dict = {"hw": [h, w]}

        sift = SIFTExtractor()
        descs = np.asarray(sift.apply(jnp.asarray(gray)))
        per_scale = []
        for s in range(sift.scales):
            ny, nx = dsift_geometry(
                w, h,
                sift.step_size + s * sift.scale_step,
                sift.bin_size + 2 * s,
                (1 + 2 * sift.scales) - 3 * s,
            )
            per_scale.append(int(ny * nx))
        entry["sift"] = {
            "num_descriptors": int(descs.shape[0]),
            "keypoints_per_scale": per_scale,
            "quant_histogram": np.histogram(descs, bins=edges)[0].tolist(),
            "zero_descriptor_fraction": float(
                np.mean(np.all(descs == 0.0, axis=1))
            ),
            "mean": float(descs.mean()),
        }

        hog = np.asarray(HogExtractor(bin_size=8).apply(jnp.asarray(rgb)))
        entry["hog"] = {
            "shape": list(hog.shape),
            "mean": float(hog.mean()),
            "std": float(hog.std()),
            "zero_fraction": float(np.mean(hog == 0.0)),
        }

        daisy = np.asarray(DaisyExtractor().apply(jnp.asarray(gray)))
        entry["daisy"] = {
            "shape": list(daisy.shape),
            "mean": float(daisy.mean()),
            "std": float(daisy.std()),
        }

        lcs = np.asarray(LCSExtractor(4, 16, 6).apply(jnp.asarray(rgb)))
        entry["lcs"] = {
            "shape": list(lcs.shape),
            "mean": float(lcs.mean()),
            "std": float(lcs.std()),
        }
        out[name] = entry

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests", "goldens", "extractor_stats.json",
    )
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
    print(json.dumps(out, indent=1, sort_keys=True))


if __name__ == "__main__":
    main()
