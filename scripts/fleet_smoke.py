"""Fleet-serving smoke (<20 s, CPU): the `make fleet-smoke` rung of
`verify-fast`.

Pins, through REAL replica worker processes (``keystone_tpu/serve/
fleet.py`` spawning ``ModelPool`` + ``BatchingFront`` per replica over
the deterministic ``two_tenant`` builder):

1. Every fleet prediction MATCHES a locally built deterministic twin of
   the same builder — the coalesced cross-process batch path returns
   bit-for-bit what the single-request apply produces, for BOTH tenants.
2. A concurrent multi-tenant burst (two threads per tenant) is served
   with ZERO steady-state recompiles across every replica (the warmed
   shape-ladder contract, summed over the fleet).
3. Both tenants' requests land (per-tenant served counts over the
   fleet's shared stats view), and the routed load reaches both
   replicas' sockets.
"""

from __future__ import annotations

import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("KEYSTONE_FAULTS", None)

t_start = time.monotonic()

BUDGET_S = 20.0


def _ccs(fleet) -> int:
    return sum(
        r.get("compile_cache_size", 0)
        for r in fleet.stats()["replicas"].values()
        if not r.get("dead")
    )


def main() -> int:
    import numpy as np

    from keystone_tpu.serve.builders import two_tenant
    from keystone_tpu.serve.fleet import Fleet

    # the deterministic local twin: same builder, same seeds, no fleet
    twins = {s.name: s for s in two_tenant()}
    items = {
        name: np.linspace(-1.0, 1.0, int(s.item_spec.shape[0]),
                          dtype=np.float32)
        for name, s in twins.items()
    }
    want = {
        name: np.asarray(twins[name].pipe.serve(items[name]))
        for name in twins
    }

    with Fleet("two_tenant", replicas=2, shapes="1,4",
               coalesce_ms=0.0, queue_depth=32, slo_ms=10_000.0) as f:
        assert f.live_count() == 2, f.stats()

        # 1: parity vs the local twin, each tenant, single requests
        for name in twins:
            r = f.predict(items[name], model=name, deadline_ms=10_000)
            assert r["ok"] is True, r
            np.testing.assert_allclose(
                np.asarray(r["value"]), want[name], rtol=1e-6, atol=1e-6
            )
        print("fleet-smoke 1/3: fleet predictions match the local "
              "deterministic twin for both tenants")

        # 2: concurrent burst -> coalesced batches, zero recompiles
        ccs0 = _ccs(f)
        results: list = []
        lock = threading.Lock()

        def worker(name):
            for _ in range(8):
                r = f.predict(items[name], model=name, deadline_ms=10_000)
                with lock:
                    results.append((name, r))

        threads = [threading.Thread(target=worker, args=(n,))
                   for n in twins for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert len(results) == 8 * len(threads), len(results)
        for name, r in results:
            assert r["ok"] is True, (name, r)
            np.testing.assert_allclose(
                np.asarray(r["value"]), want[name], rtol=1e-6, atol=1e-6
            )
        recompiles = _ccs(f) - ccs0
        assert recompiles == 0, f"{recompiles} steady-state recompiles"
        print(f"fleet-smoke 2/3: {len(results)} coalesced responses "
              "match the single-request path, zero steady-state "
              "recompiles across the fleet")

        # 3: both tenants served, on live shared stats
        s = f.stats()
        served = {name: 0 for name in twins}
        for rep in s["replicas"].values():
            for name, ts in rep.get("stats", {}).get("tenants", {}).items():
                served[name] += ts["served"]
        assert all(v > 0 for v in served.values()), served
        assert s["live"] == 2, s
        print(f"fleet-smoke 3/3: both tenants served across the fleet "
              f"({served}), 2/2 replicas live")

    dt = time.monotonic() - t_start
    print(f"fleet-smoke PASS in {dt:.1f}s")
    if dt > BUDGET_S:
        print(f"fleet-smoke OVER BUDGET ({dt:.1f}s > {BUDGET_S}s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
