"""Streaming-ingest smoke (<20 s, CPU): the `make ingest-smoke` rung of
`verify-fast` — the out-of-core ingest tier (core/ingest.py) end to end.

Pins, through the REAL entry points:

1. OVERLAP: the same synthetic tar set decoded + extracted through the
   overlapped pipeline (worker pool + run-ahead device transfer) finishes
   no slower than the strictly-sequential decode-then-extract twin
   (min-of-3 each; the archives are PROGRESSIVE JPEGs — multi-pass decode
   is compute-bound, so the worker pool genuinely parallelizes against
   the consumer's bandwidth-bound transfer+extract even on a 2-core CI
   host, a calibrated ~20%+ structural margin with disjoint trial
   distributions — not a scheduler-noise coin flip).
2. BOUNDED MEMORY: the ``ingest.buffers_live_peak`` gauge never exceeds
   the ring size (KEYSTONE_INGEST_BUFFERS provably bounds live decoded
   batches), and every buffer is recycled by stream end (live == 0).
3. FALLBACK PARITY: the pure-Python (tarfile + PIL) path yields the same
   entry names and image count as the native path, with pixel parity
   within JPEG-decoder tolerance.
4. FAULTS: an injected bad-JPEG fault (``KEYSTONE_FAULTS=ingest.decode``)
   costs exactly one image and a warning — the stream completes, never
   wedges; an injected worker death re-queues its in-flight archive so
   the surviving workers lose nothing.
5. ZERO RECOMPILES: the per-batch jitted extract sees one fixed ring
   shape — jit cache size 1 after the full stream.
"""

from __future__ import annotations

import io
import os
import sys
import tarfile
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

T0 = time.monotonic()
# Sizing (calibrated on the 2-core CI host): 768 progressive 256^2 JPEGs
# cost ~1.3 s of compute-bound worker decode single-threaded, against
# ~0.7 s of consumer transfer+extract — sequential pays the sum (~1.9 s),
# the 2-worker overlapped pipeline pays ~max (~1.6 s): disjoint min-of-3
# distributions, not a coin flip.
HW = 256
BATCH = 64
NUM_TARS = 6
PER_TAR = 128


def check(ok, msg):
    status = "ok" if ok else "FAIL"
    print(f"[{status}] {msg} ({time.monotonic() - T0:.1f}s)")
    if not ok:
        sys.exit(1)


def make_tarset(root):
    from PIL import Image

    rng = np.random.default_rng(5)
    paths = []
    for t in range(NUM_TARS):
        path = os.path.join(root, f"part{t}.tar")
        with tarfile.open(path, "w") as tf:
            for i in range(PER_TAR):
                arr = (rng.uniform(0, 1, size=(HW, HW, 3)) * 255).astype(
                    np.uint8
                )
                buf = io.BytesIO()
                # progressive: multi-pass decode is COMPUTE-bound, so the
                # worker pool has real work to hide behind the consumer
                Image.fromarray(arr).save(
                    buf, "JPEG", quality=90, progressive=True
                )
                ti = tarfile.TarInfo(f"cls{i % 4}/im_{t}_{i}.jpg")
                ti.size = buf.getbuffer().nbytes
                buf.seek(0)
                tf.addfile(ti, buf)
        paths.append(path)
    return paths


def main():
    from keystone_tpu.core.ingest import StreamingTarIngest, stream_batches
    from keystone_tpu.telemetry import get_registry
    from keystone_tpu.utils import faults

    reg = get_registry()
    root = tempfile.mkdtemp(prefix="ingest_smoke_")
    tars = make_tarset(root)
    total = NUM_TARS * PER_TAR

    # per-batch extract: light on purpose — the overlap under test is the
    # worker pool's decode against the consumer's transfer, and a heavy
    # extract would just fight the workers for the 2 CI cores
    @jax.jit
    def extract(x):
        y = x.reshape(x.shape[0], -1)
        w = jnp.ones((y.shape[1], 64), jnp.float32) / y.shape[1]
        return jnp.tanh(y @ w).sum()

    def overlapped() -> float:
        t0 = time.perf_counter()
        n_tot = 0
        for arr, _, n in stream_batches(
            StreamingTarIngest(tars, (HW, HW), BATCH, num_threads=2,
                               num_buffers=3),
            depth=1,
        ):
            float(extract(arr))
            n_tot += n
        assert n_tot == total, (n_tot, total)
        return time.perf_counter() - t0

    def sequential() -> float:
        t0 = time.perf_counter()
        n_tot = 0
        ing = StreamingTarIngest(tars, (HW, HW), BATCH, num_threads=1,
                                 num_buffers=1)
        for b in ing.batches():  # lease held across extract: no run-ahead
            # jnp.array, not asarray: the SAME copying transfer the
            # overlapped arm's stream_batches performs, so the pair
            # differs only in overlap
            float(extract(jnp.array(b.images)))
            n_tot += b.n_valid
            b.release()
        assert n_tot == total, (n_tot, total)
        return time.perf_counter() - t0

    overlapped()  # compile warmup out of both timings
    on_s = min(overlapped() for _ in range(3))
    off_s = min(sequential() for _ in range(3))
    check(
        on_s <= off_s,
        f"overlap-on {on_s:.3f}s <= overlap-off {off_s:.3f}s",
    )
    check(extract._cache_size() == 1,
          "one fixed ring shape -> jit cache size 1")

    peak = reg.get_gauge("ingest.buffers_live_peak")
    live = reg.get_gauge("ingest.buffers_live")
    check(peak is not None and peak <= 3,
          f"buffers_live_peak {peak} bounded by the ring")
    check(live == 0, "every ring buffer recycled at stream end")

    # fallback parity: force the pure-Python tar walk + PIL decode
    def collect(paths):
        got = {}
        for arr, names, n in stream_batches(
            StreamingTarIngest(paths, (HW, HW), BATCH, num_threads=2,
                               num_buffers=2)
        ):
            arr = np.asarray(arr)
            for i in range(n):
                got[names[i]] = arr[i].copy()
        return got

    from keystone_tpu.native import ingest as native_ingest

    native = collect(tars[:1])
    saved = (native_ingest._lib, native_ingest._build_attempted)
    native_ingest._lib, native_ingest._build_attempted = None, True
    try:
        fallback = collect(tars[:1])
    finally:
        native_ingest._lib, native_ingest._build_attempted = saved
    check(set(native) == set(fallback) and len(native) == PER_TAR,
          f"fallback parity: same {len(native)} entries")
    worst = max(
        float(np.abs(native[k] - fallback[k]).mean()) for k in native
    )
    check(worst <= 2.0 / 255.0,
          f"fallback pixel parity (mean |delta| {worst:.5f} <= 2/255)")

    # injected bad JPEG: one image lost, a warning, no wedge
    bad0 = reg.get_counter("ingest.bad_images")
    os.environ["KEYSTONE_FAULTS"] = "ingest.decode@2:xla"
    faults.reset()
    try:
        n_tot = sum(
            n for _, _, n in stream_batches(
                StreamingTarIngest(tars[:1], (HW, HW), BATCH)
            )
        )
    finally:
        os.environ.pop("KEYSTONE_FAULTS", None)
        faults.reset()
    check(
        n_tot == PER_TAR - 1
        and reg.get_counter("ingest.bad_images") - bad0 == 1,
        "injected bad JPEG: one image skipped with a warning, stream done",
    )

    # injected worker death: in-flight archive re-queued, nothing lost
    os.environ["KEYSTONE_FAULTS"] = "ingest.worker@1:xla"
    faults.reset()
    try:
        n_tot = sum(
            n for _, _, n in stream_batches(
                StreamingTarIngest(tars, (HW, HW), BATCH, num_threads=2,
                                   num_buffers=2)
            )
        )
    finally:
        os.environ.pop("KEYSTONE_FAULTS", None)
        faults.reset()
    check(
        n_tot == total
        and reg.get_counter("ingest.worker_deaths") >= 1,
        "worker death: survivors re-ran its archive, zero images lost",
    )

    elapsed = time.monotonic() - T0
    check(elapsed < 120.0, f"smoke completed in {elapsed:.1f}s")
    print("ingest smoke: PASS")


if __name__ == "__main__":
    main()
