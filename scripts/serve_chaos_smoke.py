"""Serving-gateway chaos smoke (<20 s, CPU): the acceptance scenario for
the hardened serving tier (``keystone_tpu/serve/gateway.py``).

Under sustained synthetic load with ``KEYSTONE_FAULTS`` firing at all
three serve sites plus one mid-run SIGKILL/restart, the gateway never
wedges:

1. ``serve.admit`` fault -> the request still terminates, as a STRUCTURED
   ``error`` response (never a hang); the next request serves normally.
2. Sustained overload against a bounded queue -> every submitted request
   terminates as served-or-structured-shed (sheds counted, retry-after
   set), and the latency/qps gauges populate.
3. ``serve.respond`` fault -> structured ``error``, next request fine.
4. ``serve.dispatch`` NaN poison x breaker threshold -> consecutive
   sentinel trips round-trip the per-model circuit breaker
   open -> half-open -> closed (fast-fails counted while open, the
   half-open probe re-certifies the model).
5. A worker process serving sustained load is SIGKILLed MID-RUN by an
   injected ``serve.dispatch`` kill fault (the preemption case); the
   "restarted" worker (a fresh process over the same pipeline) reaches
   steady state and serves its whole load with ZERO recompiles after
   warmup — the compiled-ladder contract survives restarts.

``make serve-chaos-smoke``; the gateway-over-MNIST rung lives in
``scripts/serve_smoke.py`` (``make serve-smoke``).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("KEYSTONE_FAULTS", None)

t_start = time.monotonic()

BUDGET_S = 20.0
D = 4  # item width of the synthetic serve chain


def _build_gateway(**kw):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.core.pipeline import Transformer, chain
    from keystone_tpu.serve import serve

    class Affine(Transformer):
        def apply(self, x):
            return x * 2.0 + 1.0

    kw.setdefault("item_spec", jax.ShapeDtypeStruct((D,), np.float32))
    return serve(chain(Affine()), **kw)


def _item(i=0.0):
    import numpy as np

    return np.arange(D, dtype=np.float32) + np.float32(i)


def _worker(mode: str) -> int:
    """Child process: sustained synthetic load. ``kill`` mode arms a
    mid-run SIGKILL at the dispatch boundary (the parent asserts the -9);
    ``steady`` mode is the restarted gateway — it must serve everything
    with zero recompiles after warmup."""
    from keystone_tpu.utils import faults

    if mode == "kill":
        os.environ["KEYSTONE_FAULTS"] = "serve.dispatch@5:kill"
    faults.reset()
    # generous SLO: CPU-sim dispatch is ~100 ms, and THIS phase pins the
    # no-wedge/zero-recompile contract, not shedding (phase 2 does that)
    gw = _build_gateway(slo_ms=10_000.0)
    size0 = gw.compile_cache_size()
    served = 0
    for burst in range(12):
        pend = [gw.submit(_item(burst * 4 + j)) for j in range(4)]
        rs = [p.result(10) for p in pend]
        assert all(r.ok for r in rs), [r.code for r in rs]
        served += len(rs)
        print(f"worker[{mode}]: burst {burst} served (total {served})",
              flush=True)
    assert gw.compile_cache_size() == size0, (
        f"steady-state recompile: {gw.compile_cache_size()} != {size0}"
    )
    gw.close()
    print(f"worker[{mode}]: DONE served={served} recompiles=0", flush=True)
    return 0


def main() -> int:
    from keystone_tpu.telemetry import get_registry
    from keystone_tpu.utils import faults

    reg = get_registry()

    # -- 1. admission fault: structured error, never a hang -------------
    gw = _build_gateway(queue_depth=8, breaker_threshold=2,
                        breaker_cooldown_s=0.1)
    os.environ["KEYSTONE_FAULTS"] = "serve.admit@0:xla"
    faults.reset()
    r = gw.submit(_item()).result(5)
    os.environ.pop("KEYSTONE_FAULTS", None)
    faults.reset()
    assert r.code == "error" and "injected fault" in r.error, r
    assert gw.submit(_item()).result(10).ok, "gateway wedged after fault"
    print("serve-chaos 1/5: admit fault -> structured error, recovered")

    # -- 2. sustained overload: served-or-shed, nothing hangs ------------
    gw.close()
    gw = _build_gateway(queue_depth=8, breaker_threshold=2,
                        breaker_cooldown_s=0.1, start=False)
    pend = [gw.submit(_item(i)) for i in range(40)]
    gw.start()
    codes = [p.result(15).code for p in pend]
    assert len(codes) == 40 and all(c is not None for c in codes)
    n_ok = sum(c == "ok" for c in codes)
    n_shed = sum(c == "shed" for c in codes)
    assert n_ok + n_shed == 40, f"unexpected codes under overload: {codes}"
    assert n_ok >= 8 and n_shed >= 1, (n_ok, n_shed)
    assert int(reg.counter_family_total("serve.shed_total")) >= n_shed
    print(f"serve-chaos 2/5: overload degraded to partial availability "
          f"({n_ok} served, {n_shed} shed, zero wedged)")

    # -- 3. respond fault: structured error, next request fine -----------
    os.environ["KEYSTONE_FAULTS"] = "serve.respond@0:xla"
    faults.reset()
    r = gw.submit(_item()).result(10)
    os.environ.pop("KEYSTONE_FAULTS", None)
    faults.reset()
    assert r.code == "error" and "respond failure" in r.error, r
    assert gw.submit(_item()).result(10).ok
    print("serve-chaos 3/5: respond fault -> structured error, recovered")

    # -- 4. dispatch NaN x2 -> breaker open -> half-open -> closed -------
    os.environ["KEYSTONE_FAULTS"] = "serve.dispatch@0:nan*2"
    faults.reset()
    states = [gw.breaker_state()]
    s1 = gw.submit(_item()).result(10)
    s2 = gw.submit(_item()).result(10)
    os.environ.pop("KEYSTONE_FAULTS", None)
    faults.reset()
    assert (s1.code, s2.code) == ("sentinel", "sentinel"), (s1, s2)
    states.append(gw.breaker_state())
    assert states[-1] == "open", states
    ff = gw.submit(_item()).result(5)
    assert ff.code == "breaker_open" and ff.retry_after_s is not None, ff
    time.sleep(0.12)  # past the cooldown: next request is the probe
    probe = gw.submit(_item()).result(10)
    assert probe.ok, probe
    states.append(gw.breaker_state())
    assert states[-1] == "closed", states
    for event in ("open", "half_open", "close"):
        assert reg.get_counter("serve.breaker", event=event) >= 1, event
    assert gw.submit(_item()).result(10).ok
    gw.close()
    print(f"serve-chaos 4/5: breaker round-trip {' -> '.join(states)} "
          "(fast-fail while open, probe re-admitted)")

    # -- 5. mid-run SIGKILL under load, then a zero-recompile restart ----
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    kill = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker", "kill"],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert kill.returncode == -signal.SIGKILL, (
        kill.returncode, kill.stdout[-500:], kill.stderr[-500:]
    )
    assert "burst 0 served" in kill.stdout, kill.stdout  # died MID-run
    assert "DONE" not in kill.stdout
    steady = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker", "steady"],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert steady.returncode == 0, (
        steady.returncode, steady.stdout[-800:], steady.stderr[-800:]
    )
    assert "DONE served=48 recompiles=0" in steady.stdout, steady.stdout
    print("serve-chaos 5/5: SIGKILLed mid-run under load; restarted "
          "gateway served 48/48 with zero steady-state recompiles")

    elapsed = time.monotonic() - t_start
    print(f"serve-chaos-smoke OK in {elapsed:.1f}s")
    assert elapsed < BUDGET_S, f"smoke took {elapsed:.1f}s (>{BUDGET_S}s)"
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        sys.exit(_worker(sys.argv[2]))
    sys.exit(main())
