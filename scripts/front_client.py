"""Jax-free closed-loop client driver for a serving front socket.

The fleet bench regime (``scripts/bench_regime.py fleet``) spawns N of
these per replica to generate genuinely cross-PROCESS single-request
traffic.  ``keystone_tpu/serve/front.py`` is loaded standalone (by file
path, not through the package) so the driver never imports jax — client
processes start in ~0.2 s and cost numpy, not a backend.

Usage: ``python scripts/front_client.py --drive /path/to.sock
[--seconds 2] [--model name] [--deadline-ms F] [--seed N]`` — prints ONE
JSON line of client-side results (see ``front.drive_main``).
"""

import importlib.util
import os
import sys

_FRONT_PY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "keystone_tpu", "serve", "front.py",
)


def _load_front():
    spec = importlib.util.spec_from_file_location("_keystone_front",
                                                  _FRONT_PY)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


if __name__ == "__main__":
    sys.exit(_load_front().drive_main(sys.argv[1:]))
