"""Telemetry smoke: tiny pipeline under the tracer -> counters non-zero,
Chrome trace well-formed, report renders. The ``make telemetry-smoke``
target (folded into ``make verify-fast``) — the end-to-end contract in one
command, CPU-runnable in seconds.

Exit 0 on success; prints the failing check and exits 1 otherwise.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg: str) -> int:
    print(f"TELEMETRY SMOKE FAILED: {msg}", file=sys.stderr)
    return 1


def main() -> int:
    from keystone_tpu import telemetry
    from keystone_tpu.pipelines.mnist_random_fft import (
        MnistRandomFFTConfig,
        run,
    )

    telemetry.reset()
    cfg = MnistRandomFFTConfig(
        num_ffts=2, block_size=256, lam=10.0,
        synthetic_train=512, synthetic_test=128,
    )
    # KEYSTONE_GUARD=1 additionally arms the transfer/recompile sentinel
    # (keystone_tpu/analysis/guard.py) around the traced run; violations
    # land as guard.* counters in the same registry this smoke asserts on.
    from keystone_tpu.analysis.guard import maybe_guard

    with maybe_guard():
        with telemetry.use_tracing(True):
            run(cfg)

    reg = telemetry.get_registry()
    metrics = reg.as_dict()
    spans = telemetry.get_tracer().spans_as_dicts()

    if not spans:
        return fail("no spans recorded under use_tracing(True)")
    if not metrics["counters"]:
        return fail("no counters recorded")
    if reg.get_counter("solver.calls", solver="bcd") < 1:
        return fail("solver.calls{solver=bcd} counter is zero")
    timer_hists = [k for k in metrics["histograms"] if k.startswith("timer.")]
    if not timer_hists:
        return fail("no timer.* histograms (Timer -> registry routing)")

    with tempfile.TemporaryDirectory() as tmp:
        paths = telemetry.export_dir(tmp)
        with open(paths["trace"]) as f:
            trace = json.load(f)
        events = trace.get("traceEvents")
        if not events:
            return fail("exported Chrome trace has no traceEvents")
        for ev in events:
            for field in ("name", "ph", "ts", "dur", "pid", "tid"):
                if field not in ev:
                    return fail(f"trace event missing {field!r}: {ev}")
        # the report must render from the bench-artifact schema too
        artifact_path = os.path.join(tmp, "bench_telemetry.json")
        with open(artifact_path, "w") as f:
            json.dump({"metrics": metrics, "spans": spans}, f)
        from keystone_tpu.cli import main as cli_main

        rc = cli_main(["telemetry-report", artifact_path])
        if rc != 0:
            return fail(f"telemetry-report exited {rc}")

    print(
        f"telemetry smoke OK: {len(spans)} spans, "
        f"{len(metrics['counters'])} counter series, "
        f"{len(timer_hists)} timer stages"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
