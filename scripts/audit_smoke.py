"""End-to-end smoke of the IR audit pass (seconds, CPU).

Audits two registered entry points — one overlap scheduler and one solver
rung, the pair that exercises the A1 collective checks plus A2/A3/A4 —
and asserts the contract ``make verify-fast`` rides:

1. Zero NEW findings against the committed ``ir_baseline.json`` (the
   repo-audits-clean invariant, visible in the terminal).
2. The ``--format json`` output schema: the keys the bench section and CI
   consumers parse (``new``/``baselined``/``targets``/``skipped``/
   ``errors``/``total``).
3. Wall clock under 20 s — the audit must stay cheap enough to fold into
   every pre-merge loop.

``make audit-smoke``; folded into ``verify-fast``.
"""

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import keystone_tpu  # noqa: E402  (compat shims first)
from keystone_tpu.analysis.ir_audit import (  # noqa: E402
    DEFAULT_IR_BASELINE,
    ensure_cpu_devices,
    main as audit_main,
    render_audit_json,
    run_audit,
)

_TARGETS = ["overlap.tiled_gram", "solver.normal_equations"]
_BUDGET_S = 20.0


def main() -> int:
    t0 = time.monotonic()
    ensure_cpu_devices()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    baseline = os.path.join(root, DEFAULT_IR_BASELINE)

    result = run_audit(
        _TARGETS,
        baseline_path=baseline if os.path.exists(baseline) else None,
    )
    assert not result.errors, f"audit errors: {result.errors}"
    assert not result.skipped, (
        f"smoke targets skipped (device bootstrap broke?): {result.skipped}"
    )
    assert len(result.targets) == 2, result.targets
    assert not result.findings, (
        "NEW audit findings on the clean repo:\n"
        + "\n".join(f.format() for f in result.findings)
    )

    # JSON schema: what the CI/bench consumers parse
    payload = json.loads(render_audit_json(result))
    for key in (
        "new", "baselined", "stale", "stale_pragmas", "suppressed",
        "targets", "skipped", "errors", "total",
    ):
        assert key in payload, f"audit JSON missing {key!r}"
    assert isinstance(payload["new"], list)
    assert payload["targets"] == _TARGETS

    # the CLI form agrees (exit 0 = no new findings)
    rc = audit_main(["--target", _TARGETS[0], "--root", root])
    assert rc == 0, f"audit CLI exited {rc}"

    elapsed = time.monotonic() - t0
    assert elapsed < _BUDGET_S, (
        f"audit smoke took {elapsed:.1f}s (> {_BUDGET_S:.0f}s budget)"
    )
    print(
        f"audit-smoke: {len(result.targets)} targets audited clean "
        f"({payload['total']} total findings, {result.suppressed} "
        f"suppressed) in {elapsed:.1f}s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
