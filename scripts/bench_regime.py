"""Run ONE big-regime benchmark in a fresh OS process; print ONE JSON line.

``bench.py`` shells out here for the flagship / VOC-refdim / full-TIMIT
rows. Why a subprocess: round 4 measured the in-bench flagship ~1.4x
slower than the same code in a fresh or early process (20.1 s vs 14.4 s,
``contended=False`` — process-lifetime allocator state after ~20 min of
other pipelines, not chip contention), and "run the big regimes first" only
dodges the effect until the next reordering. A fresh process per regime
makes each row ordering-independent by construction; the persistent XLA
compile cache (configured on ``import bench``) keeps the fresh-process
cold run cheap. VERDICT r4 weak #6 / next #7.

Usage: ``python scripts/bench_regime.py {flagship|voc_refdim|timit_full}``
— the LAST stdout line is the regime's result dict (full-dict key names,
exactly what bench.py's in-process blocks used to produce).
"""

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _flagship() -> dict:
    import bench
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
        flagship_config,
        run,
    )

    cfg = flagship_config()
    run(cfg)  # cold / cache-deserialize
    last: dict = {}
    med, lo, hi, cont = bench._warm_stats(lambda: last.update(run(cfg)))
    out = {
        "imagenet_refdim_streaming_warm_s": med,
        "imagenet_refdim_streaming_warm_s_min": lo,
        "imagenet_refdim_streaming_warm_s_max": hi,
        "imagenet_refdim_streaming_warm_s_contended": cont,
    }
    try:
        # quality rides the artifact: a draw from the measured band
        # (BASELINE.md flagship row), floored in CI by
        # tests/test_voc_imagenet_pipelines.py
        out["imagenet_refdim_top5_error_pct"] = round(
            last["test_top5_error"], 2
        )
    except Exception as e:
        print(f"flagship quality readout failed: {e}", file=sys.stderr)
    # stage attribution AFTER the headline rows (extra barriered runs must
    # not precede — and so perturb — the async warm measurement)
    out.update(bench._try_flagship_stage_breakdown())
    return out


def _voc_refdim() -> dict:
    import bench
    from keystone_tpu.pipelines.voc_sift_fisher import (
        VOCSIFTFisherConfig,
        run,
    )

    cfg = VOCSIFTFisherConfig(
        synthetic_train=5120, synthetic_test=4096, desc_dim=80,
        vocab_size=256, block_size=4096, row_chunks=16,
    )
    run(cfg)  # cold / cache-deserialize
    med, lo, hi, cont = bench._warm_stats(lambda: run(cfg), reps=2)
    return {
        "voc_refdim_warm_s": med,
        "voc_refdim_warm_s_min": lo,
        "voc_refdim_warm_s_max": hi,
        "voc_refdim_warm_s_contended": cont,
    }


def _timit_full() -> dict:
    import bench
    from keystone_tpu.pipelines.timit import TimitConfig, run

    cfg = TimitConfig(
        synthetic_train=2_200_000, synthetic_test=100_000,
        num_epochs=5, row_chunk=131072,
    )
    run(cfg)  # cold
    med, lo, hi, cont = bench._warm_stats(lambda: run(cfg), reps=2)
    return {
        "timit_full_2p2m_warm_s": round(med, 1),
        "timit_full_2p2m_warm_s_min": round(lo, 1),
        "timit_full_2p2m_warm_s_max": round(hi, 1),
        "timit_full_2p2m_warm_s_contended": cont,
    }


_REGIMES = {
    "flagship": _flagship,
    "voc_refdim": _voc_refdim,
    "timit_full": _timit_full,
}


def main():
    if len(sys.argv) != 2 or sys.argv[1] not in _REGIMES:
        print(f"usage: bench_regime.py {{{'|'.join(_REGIMES)}}}",
              file=sys.stderr)
        return 2
    out = _REGIMES[sys.argv[1]]()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
