"""Run ONE big-regime benchmark in a fresh OS process; print ONE JSON line.

``bench.py`` shells out here for the flagship / VOC-refdim / full-TIMIT
rows. Why a subprocess: round 4 measured the in-bench flagship ~1.4x
slower than the same code in a fresh or early process (20.1 s vs 14.4 s,
``contended=False`` — process-lifetime allocator state after ~20 min of
other pipelines, not chip contention), and "run the big regimes first" only
dodges the effect until the next reordering. A fresh process per regime
makes each row ordering-independent by construction; the persistent XLA
compile cache (configured on ``import bench``) keeps the fresh-process
cold run cheap. VERDICT r4 weak #6 / next #7.

Usage: ``python scripts/bench_regime.py {flagship|voc_refdim|timit_full}``
— the LAST stdout line is the regime's result dict (full-dict key names,
exactly what bench.py's in-process blocks used to produce).
"""

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _flagship() -> dict:
    import bench
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
        flagship_config,
        run,
    )

    cfg = flagship_config()
    run(cfg)  # cold / cache-deserialize
    last: dict = {}
    med, lo, hi, cont = bench._warm_stats(lambda: last.update(run(cfg)))
    out = {
        "imagenet_refdim_streaming_warm_s": med,
        "imagenet_refdim_streaming_warm_s_min": lo,
        "imagenet_refdim_streaming_warm_s_max": hi,
        "imagenet_refdim_streaming_warm_s_contended": cont,
    }
    try:
        # quality rides the artifact: a draw from the measured band
        # (BASELINE.md flagship row), floored in CI by
        # tests/test_voc_imagenet_pipelines.py
        out["imagenet_refdim_top5_error_pct"] = round(
            last["test_top5_error"], 2
        )
    except Exception as e:
        print(f"flagship quality readout failed: {e}", file=sys.stderr)
    # cached-vs-cold predict: one extra run under a content-addressed
    # intermediate cache (core/cache.py). Inside it the eval section times
    # the first (computing, memoizing) and second (stored-scores, zero
    # re-featurization) predict with explicit syncs — the flagship's
    # "eval.predict is test-side re-featurization" cost, measured against
    # its elimination. AFTER the headline rows: the cache run must not
    # perturb the async warm measurement. BENCH_CACHED=0 skips.
    if os.environ.get("BENCH_CACHED", "1") == "1":
        prev_flag = os.environ.get("KEYSTONE_EVAL_CACHED_TIMING")
        # bench-only: the pipelines gate the cold/cached eval double-predict
        # on this flag so ordinary cache-enabled runs never pay for it
        os.environ["KEYSTONE_EVAL_CACHED_TIMING"] = "1"
        try:
            from keystone_tpu.core.cache import IntermediateCache, use_cache

            with use_cache(IntermediateCache(
                device_bytes=2 << 30, host_bytes=8 << 30
            )):
                r = run(cfg)
            out["imagenet_refdim_predict_cold_s"] = r.get("predict_cold_s")
            out["imagenet_refdim_predict_cached_s"] = r.get(
                "predict_cached_s"
            )
        except Exception as e:
            print(f"flagship cached-predict row failed: {e}",
                  file=sys.stderr)
        finally:
            if prev_flag is None:
                os.environ.pop("KEYSTONE_EVAL_CACHED_TIMING", None)
            else:
                os.environ["KEYSTONE_EVAL_CACHED_TIMING"] = prev_flag
    # prefetch-off control for the double-buffered block feed
    # (core/prefetch.py): the headline warm row above runs with prefetch ON
    # (the default); this one warm run with KEYSTONE_PREFETCH=0 is the
    # overlap's measured value. BENCH_PREFETCH=0 skips.
    if os.environ.get("BENCH_PREFETCH", "1") == "1":
        prev = os.environ.get("KEYSTONE_PREFETCH")
        os.environ["KEYSTONE_PREFETCH"] = "0"
        try:
            import time as _time

            from keystone_tpu.core.cache import use_cache

            t0 = _time.perf_counter()
            # ambient-env-cache suppressed: the row must measure the lost
            # overlap, not memoized featurization hits
            with use_cache(None):
                run(cfg)
            out["imagenet_refdim_streaming_prefetch_off_s"] = round(
                _time.perf_counter() - t0, 3
            )
        except Exception as e:
            print(f"flagship prefetch-off row failed: {e}", file=sys.stderr)
        finally:
            if prev is None:
                os.environ.pop("KEYSTONE_PREFETCH", None)
            else:
                os.environ["KEYSTONE_PREFETCH"] = prev
    # overlap-on control for the latency-hiding collectives
    # (parallel/overlap.py): the headline warm row runs with the knob OFF
    # (the default); this one warm run under KEYSTONE_OVERLAP=1 measures
    # the tiled reduce-scatter solver path — on a single chip it falls
    # back to the monolithic programs, so on/off only separates on a mesh
    # (the row still documents that). One compile-warm run first: the
    # pipelined programs are new compilations. BENCH_OVERLAP=0 skips.
    if os.environ.get("BENCH_OVERLAP", "1") == "1":
        prev = os.environ.get("KEYSTONE_OVERLAP")
        os.environ["KEYSTONE_OVERLAP"] = "1"
        try:
            import time as _time

            from keystone_tpu.core.cache import use_cache

            with use_cache(None):  # measure overlap, not memoization hits
                run(cfg)  # compile-warm under the flag
                t0 = _time.perf_counter()
                run(cfg)
            out["imagenet_refdim_streaming_overlap_on_s"] = round(
                _time.perf_counter() - t0, 3
            )
        except Exception as e:
            print(f"flagship overlap-on row failed: {e}", file=sys.stderr)
        finally:
            if prev is None:
                os.environ.pop("KEYSTONE_OVERLAP", None)
            else:
                os.environ["KEYSTONE_OVERLAP"] = prev
    # stage attribution AFTER the extra rows (extra barriered runs must
    # not precede — and so perturb — the async warm measurement)
    out.update(bench._try_flagship_stage_breakdown())
    return out


def _voc_refdim() -> dict:
    import bench
    from keystone_tpu.pipelines.voc_sift_fisher import (
        VOCSIFTFisherConfig,
        run,
    )

    cfg = VOCSIFTFisherConfig(
        synthetic_train=5120, synthetic_test=4096, desc_dim=80,
        vocab_size=256, block_size=4096, row_chunks=16,
    )
    run(cfg)  # cold / cache-deserialize
    med, lo, hi, cont = bench._warm_stats(lambda: run(cfg), reps=2)
    return {
        "voc_refdim_warm_s": med,
        "voc_refdim_warm_s_min": lo,
        "voc_refdim_warm_s_max": hi,
        "voc_refdim_warm_s_contended": cont,
    }


def _timit_full() -> dict:
    import bench
    from keystone_tpu.pipelines.timit import TimitConfig, run

    cfg = TimitConfig(
        synthetic_train=2_200_000, synthetic_test=100_000,
        num_epochs=5, row_chunk=131072,
    )
    run(cfg)  # cold
    med, lo, hi, cont = bench._warm_stats(lambda: run(cfg), reps=2)
    return {
        "timit_full_2p2m_warm_s": round(med, 1),
        "timit_full_2p2m_warm_s_min": round(lo, 1),
        "timit_full_2p2m_warm_s_max": round(hi, 1),
        "timit_full_2p2m_warm_s_contended": cont,
    }


_REGIMES = {
    "flagship": _flagship,
    "voc_refdim": _voc_refdim,
    "timit_full": _timit_full,
}


def main():
    if len(sys.argv) != 2 or sys.argv[1] not in _REGIMES:
        print(f"usage: bench_regime.py {{{'|'.join(_REGIMES)}}}",
              file=sys.stderr)
        return 2
    out = _REGIMES[sys.argv[1]]()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
