"""Run ONE big-regime benchmark in a fresh OS process; print ONE JSON line.

``bench.py`` shells out here for the flagship / VOC-refdim / full-TIMIT
rows. Why a subprocess: round 4 measured the in-bench flagship ~1.4x
slower than the same code in a fresh or early process (20.1 s vs 14.4 s,
``contended=False`` — process-lifetime allocator state after ~20 min of
other pipelines, not chip contention), and "run the big regimes first" only
dodges the effect until the next reordering. A fresh process per regime
makes each row ordering-independent by construction; the persistent XLA
compile cache (configured on ``import bench``) keeps the fresh-process
cold run cheap. VERDICT r4 weak #6 / next #7.

Usage: ``python scripts/bench_regime.py
{flagship|voc_refdim|timit_full|solver_overlap}`` — the LAST stdout line is
the regime's result dict (full-dict key names, exactly what bench.py's
in-process blocks used to produce). ``solver_overlap`` emits the
topology-aware overlap ladder (``tsqr_overlap_{on,off}_gflops`` +
``bcd_model_overlap_{on,off}_gflops``) for the ≥4-chip on/off ratchet.
"""

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from keystone_tpu.utils import knobs  # noqa: E402


def _flagship() -> dict:
    import bench
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import (
        flagship_config,
        run,
    )

    cfg = flagship_config()
    run(cfg)  # cold / cache-deserialize
    last: dict = {}
    med, lo, hi, cont = bench._warm_stats(lambda: last.update(run(cfg)))
    out = {
        "imagenet_refdim_streaming_warm_s": med,
        "imagenet_refdim_streaming_warm_s_min": lo,
        "imagenet_refdim_streaming_warm_s_max": hi,
        "imagenet_refdim_streaming_warm_s_contended": cont,
    }
    try:
        # quality rides the artifact: a draw from the measured band
        # (BASELINE.md flagship row), floored in CI by
        # tests/test_voc_imagenet_pipelines.py
        out["imagenet_refdim_top5_error_pct"] = round(
            last["test_top5_error"], 2
        )
    except Exception as e:
        print(f"flagship quality readout failed: {e}", file=sys.stderr)
    # cached-vs-cold predict: one extra run under a content-addressed
    # intermediate cache (core/cache.py). Inside it the eval section times
    # the first (computing, memoizing) and second (stored-scores, zero
    # re-featurization) predict with explicit syncs — the flagship's
    # "eval.predict is test-side re-featurization" cost, measured against
    # its elimination. AFTER the headline rows: the cache run must not
    # perturb the async warm measurement. BENCH_CACHED=0 skips.
    if knobs.get("BENCH_CACHED"):
        prev_flag = knobs.get_raw("KEYSTONE_EVAL_CACHED_TIMING")
        # bench-only: the pipelines gate the cold/cached eval double-predict
        # on this flag so ordinary cache-enabled runs never pay for it
        os.environ["KEYSTONE_EVAL_CACHED_TIMING"] = "1"
        try:
            from keystone_tpu.core.cache import IntermediateCache, use_cache

            with use_cache(IntermediateCache(
                device_bytes=2 << 30, host_bytes=8 << 30
            )):
                r = run(cfg)
            out["imagenet_refdim_predict_cold_s"] = r.get("predict_cold_s")
            out["imagenet_refdim_predict_cached_s"] = r.get(
                "predict_cached_s"
            )
        except Exception as e:
            print(f"flagship cached-predict row failed: {e}",
                  file=sys.stderr)
        finally:
            if prev_flag is None:
                os.environ.pop("KEYSTONE_EVAL_CACHED_TIMING", None)
            else:
                os.environ["KEYSTONE_EVAL_CACHED_TIMING"] = prev_flag
    # prefetch-off control for the double-buffered block feed
    # (core/prefetch.py): the headline warm row above runs with prefetch ON
    # (the default); this one warm run with KEYSTONE_PREFETCH=0 is the
    # overlap's measured value. BENCH_PREFETCH=0 skips.
    if knobs.get("BENCH_PREFETCH"):
        prev = knobs.get_raw("KEYSTONE_PREFETCH")
        os.environ["KEYSTONE_PREFETCH"] = "0"
        try:
            import time as _time

            from keystone_tpu.core.cache import use_cache

            t0 = _time.perf_counter()
            # ambient-env-cache suppressed: the row must measure the lost
            # overlap, not memoized featurization hits
            with use_cache(None):
                run(cfg)
            out["imagenet_refdim_streaming_prefetch_off_s"] = round(
                _time.perf_counter() - t0, 3
            )
        except Exception as e:
            print(f"flagship prefetch-off row failed: {e}", file=sys.stderr)
        finally:
            if prev is None:
                os.environ.pop("KEYSTONE_PREFETCH", None)
            else:
                os.environ["KEYSTONE_PREFETCH"] = prev
    # overlap-on control for the latency-hiding collectives
    # (parallel/overlap.py): the headline warm row runs with the knob OFF
    # (the default); this one warm run under KEYSTONE_OVERLAP=1 measures
    # the tiled reduce-scatter solver path — on a single chip it falls
    # back to the monolithic programs, so on/off only separates on a mesh
    # (the row still documents that). One compile-warm run first: the
    # pipelined programs are new compilations. BENCH_OVERLAP=0 skips.
    if knobs.get("BENCH_OVERLAP"):
        prev = knobs.get_raw("KEYSTONE_OVERLAP")
        os.environ["KEYSTONE_OVERLAP"] = "1"
        try:
            import time as _time

            from keystone_tpu.core.cache import use_cache

            with use_cache(None):  # measure overlap, not memoization hits
                run(cfg)  # compile-warm under the flag
                t0 = _time.perf_counter()
                run(cfg)
            out["imagenet_refdim_streaming_overlap_on_s"] = round(
                _time.perf_counter() - t0, 3
            )
        except Exception as e:
            print(f"flagship overlap-on row failed: {e}", file=sys.stderr)
        finally:
            if prev is None:
                os.environ.pop("KEYSTONE_OVERLAP", None)
            else:
                os.environ["KEYSTONE_OVERLAP"] = prev
    # stage attribution AFTER the extra rows (extra barriered runs must
    # not precede — and so perturb — the async warm measurement)
    out.update(bench._try_flagship_stage_breakdown())
    return out


def _voc_refdim() -> dict:
    import bench
    from keystone_tpu.pipelines.voc_sift_fisher import (
        VOCSIFTFisherConfig,
        run,
    )

    cfg = VOCSIFTFisherConfig(
        synthetic_train=5120, synthetic_test=4096, desc_dim=80,
        vocab_size=256, block_size=4096, row_chunks=16,
    )
    run(cfg)  # cold / cache-deserialize
    med, lo, hi, cont = bench._warm_stats(lambda: run(cfg), reps=2)
    return {
        "voc_refdim_warm_s": med,
        "voc_refdim_warm_s_min": lo,
        "voc_refdim_warm_s_max": hi,
        "voc_refdim_warm_s_contended": cont,
    }


def _timit_full() -> dict:
    import bench
    from keystone_tpu.pipelines.timit import TimitConfig, run

    cfg = TimitConfig(
        synthetic_train=2_200_000, synthetic_test=100_000,
        num_epochs=5, row_chunk=131072,
    )
    run(cfg)  # cold
    med, lo, hi, cont = bench._warm_stats(lambda: run(cfg), reps=2)
    return {
        "timit_full_2p2m_warm_s": round(med, 1),
        "timit_full_2p2m_warm_s_min": round(lo, 1),
        "timit_full_2p2m_warm_s_max": round(hi, 1),
        "timit_full_2p2m_warm_s_contended": cont,
    }


def _latency_cancelled_gflops(solve, flops: float, iters: int) -> float:
    """(time of 1+iters chained solves) − (time of 1), like
    ``bench.solver_gflops``: device dispatches execute serially, so the
    difference is pure device time and the host↔device round-trip cancels."""
    import time

    def timed(k: int) -> float:
        ws = [solve(i) for i in range(k)]
        last = float(ws[-1].ravel()[0])  # warm compile + drain the chain
        t0 = time.perf_counter()
        ws = [solve(100 + i) for i in range(k)]
        last = float(ws[-1].ravel()[0])
        if last != last:
            raise FloatingPointError("solver produced NaN")
        return time.perf_counter() - t0

    dt = (timed(1 + iters) - timed(1)) / iters
    if dt <= 0:
        raise RuntimeError(f"non-positive timing difference: {dt}")
    return flops / dt / 1e9


def _try_gflops(key_name: str, solve, flops: float, iters: int):
    """One retry absorbs transient timing noise (dt<=0 on a contended
    chip), mirroring ``bench._try_solver_gflops``; genuine failures are
    logged to stderr and the row stays None (visible, never blocking)."""
    for attempt in range(2):
        try:
            return round(_latency_cancelled_gflops(solve, flops, iters), 1)
        except Exception as e:
            print(
                f"{key_name} attempt {attempt + 1} failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )
    return None


def _solver_overlap() -> dict:
    """The topology-aware overlap ladder: ``tsqr_overlap_{on,off}_gflops``
    (the bidirectional ring R-tree vs the bulk all-gather tree) and
    ``bcd_model_overlap_{on,off}_gflops`` (the column-sharded
    ``P('data','model')`` block solve with the model-axis rotation composed
    with the tiled data reductions, vs the monolithic path).

    On the single driver chip every overlap knob falls back to the
    monolithic program (no collective to hide / no model axis), so on/off
    parity here documents the fallback; the rows exist so the next ≥4-chip
    run can ratchet the measured delta (ROADMAP "measured on/off deltas on
    a real pod"). Budget derating rides the subprocess timeout bench.py
    hands this regime."""
    import bench  # configures the XLA compile cache; holds _SMOKE
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.linalg.bcd import block_coordinate_descent_l2
    from keystone_tpu.linalg.solvers import tsqr_solve
    from keystone_tpu.parallel import make_mesh, use_mesh

    smoke = bench._SMOKE
    ndev = len(jax.devices())
    out: dict = {}

    # --- overlapped TSQR tree ------------------------------------------
    # d=512 keeps the per-solve Householder QR (not MXU-shaped — measured
    # ~0.5 s warm at 65536x512 even on the CPU host) small enough that the
    # whole ladder fits the derated subprocess timeout on any backend.
    n = (2048 if smoke else 65536) // ndev * ndev
    d, c = 128 if smoke else 512, 10
    iters = 2 if smoke else 4
    mesh = make_mesh(data=ndev, model=1)
    with use_mesh(mesh):
        key = jax.random.key(0)
        A = jax.device_put(
            jax.random.normal(key, (n, d), jnp.float32),
            NamedSharding(mesh, P("data", None)),
        )
        b = jax.device_put(
            jax.random.normal(jax.random.key(1), (n, c), jnp.float32),
            NamedSharding(mesh, P("data", None)),
        )
        flops = 2.0 * n * d * d + 2.0 * n * d * c
        for on in (False, True):
            key_name = f"tsqr_overlap_{'on' if on else 'off'}_gflops"
            out[key_name] = _try_gflops(
                key_name,
                lambda i: tsqr_solve(A, b, lam=1.0 + i, mesh=mesh, overlap=on),
                flops, iters,
            )

    # --- model-axis (column-sharded) BCD -------------------------------
    model_ax = 2 if ndev % 2 == 0 and ndev >= 2 else 1
    mesh2 = make_mesh(data=max(ndev // model_ax, 1), model=model_ax)
    n2 = (4096 if smoke else 60000) // mesh2.shape["data"] * mesh2.shape["data"]
    d2 = 512 if smoke else 2048
    block = 256 if smoke else 2048
    iters2 = 2 if smoke else 4
    with use_mesh(mesh2):
        A2 = jax.device_put(
            jax.random.normal(jax.random.key(2), (n2, d2), jnp.float32),
            NamedSharding(mesh2, P("data", "model")),
        )
        b2 = jax.device_put(
            jax.random.normal(jax.random.key(3), (n2, c), jnp.float32),
            NamedSharding(mesh2, P("data", None)),
        )
        nblocks = -(-d2 // block)
        flops2 = nblocks * (
            2.0 * n2 * block * block + 4.0 * n2 * block * c
            + 2.0 * block * block * c
        ) + (2.0 / 3.0) * nblocks * block ** 3
        for on in (False, True):
            key_name = f"bcd_model_overlap_{'on' if on else 'off'}_gflops"
            out[key_name] = _try_gflops(
                key_name,
                lambda i: block_coordinate_descent_l2(
                    A2, b2, 1.0 + i, block, overlap=on
                ),
                flops2, iters2,
            )
    out["solver_overlap_mesh"] = (
        f"tsqr data={ndev}; bcd data={mesh2.shape['data']}"
        f" model={mesh2.shape['model']}"
    )
    # ``overlap.tiles`` recorder: sweep the tile-count target of the tiled
    # reduce-scatter gram at the ladder's feature width and persist the
    # winner in the device-keyed autotune cache — this is the production
    # path that feeds ``_pick_tiles``' autotuned default. Honors the
    # KEYSTONE_AUTOTUNE opt-in like every other sweep (off = lookup-only,
    # and the bench must not mutate the checkout as a side effect); a
    # single chip has no collective to tile, so it also needs a mesh.
    if ndev > 1 and knobs.get("KEYSTONE_AUTOTUNE"):
        try:
            from keystone_tpu.ops.pallas import autotune
            from keystone_tpu.parallel.overlap import (
                _pick_tiles,
                tiled_transpose_matmul,
            )

            cands = sorted({
                t for target in (2, 4, 8, 16, ndev)
                for t in (_pick_tiles(d, ndev, target),) if t > 0
            })
            if cands:
                bucket = autotune.shape_bucket(d, ndev)

                def build(tile):
                    return lambda i: tiled_transpose_matmul(
                        A, mesh=mesh, tiles=tile
                    )

                won = autotune.sweep(
                    "overlap.tiles", bucket, cands,
                    autotune.chained_measure(build),
                    reps=2 if smoke else 3,
                )
                out["overlap_tiles_swept"] = won
        except Exception as e:
            print(f"overlap.tiles sweep failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
    return out


def _solver_ladder() -> dict:
    """The solver GFLOPs ladder (exact BCD precision cells + the randomized
    sketch rung, overlap off/on) in a fresh OS process. Moved out of
    bench.py's in-process flow because the ladder was the one heavy section
    whose RUNTIME the bench budget could not bound: the in-process gate
    checked only the entry floor, so a ladder outrunning the remaining
    budget rode straight into the driver's rc=124 (run 5). As a subprocess
    it gets the same derated timeout/skip treatment as every other big
    regime — budget exhaustion now yields a ``<key>_skipped`` marker, never
    a harness kill."""
    import bench

    return bench._try_solver_gflops_ladder()


def _sketch_compare() -> dict:
    """Equal-test-error comparison of the sketch rung vs the exact rung
    (TSQR) on a planted least-squares problem — the acceptance row for the
    randomized tier, CONFIGURED at the d=65536 flagship feature width.

    d=65536 at the sketch's n≳6d working set is ~44·d² f32 — hundreds of
    GB, beyond any single chip — so the regime derates d by halving until
    the estimated working set fits a conservative 8 GiB and RECORDS the
    actual d (``sketch_vs_exact_d``) next to the configured-regime key,
    exactly like the timit_full key names its configured rows. Smoke mode
    shrinks to seconds-scale dims."""
    import time

    import bench
    import jax
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.linalg.sketch import sketched_lstsq_solve
    from keystone_tpu.linalg.solvers import tsqr_solve
    from keystone_tpu.parallel import make_mesh, use_mesh

    smoke = bench._SMOKE
    configured_d = 65536
    ndev = len(jax.devices())
    # the exact twin is TSQR: every data shard must hold >= d rows, so the
    # row count scales with the device count (n/ndev >= d), never below
    # the 6d that keeps the sketch a real 1.5x row compression at m=4d
    n_factor = max(6, ndev)
    if smoke:
        d = 256
    else:
        d = configured_d
        # working set ≈ (n rows + m=4d sketch rows + d² R + test rows)
        # f32; halve until it fits next to XLA temporaries
        while d > 1024 and (n_factor + 4.5) * d * d * 4 > 8 * (1 << 30):
            d //= 2
    n = (n_factor * d) // ndev * ndev
    n_test, c, lam = max(d // 2, 64), 10, 1e-2
    mesh = make_mesh(data=ndev, model=1)
    rngk = jax.random.key(7)
    kA, kW, kN, kT, kTN = jax.random.split(rngk, 5)
    with use_mesh(mesh):
        A = jax.random.normal(kA, (n, d), jnp.float32)
        Wtrue = jax.random.normal(kW, (d, c), jnp.float32)
        b = A @ Wtrue + 0.1 * jax.random.normal(kN, (n, c), jnp.float32)
        A_test = jax.random.normal(kT, (n_test, d), jnp.float32)
        b_test = A_test @ Wtrue + 0.1 * jax.random.normal(
            kTN, (n_test, c), jnp.float32
        )
        jax.block_until_ready((A, b, A_test, b_test))

        def test_error(W):
            return float(
                jnp.linalg.norm(A_test @ W - b_test) / jnp.linalg.norm(b_test)
            )

        out = {"sketch_vs_exact_d": d, "sketch_vs_exact_n": n}
        t0 = time.perf_counter()
        W_exact = tsqr_solve(A, b, lam=lam, mesh=mesh)
        jax.block_until_ready(W_exact)
        out["exact_solve_s"] = round(time.perf_counter() - t0, 3)
        t0 = time.perf_counter()
        W_sketch = sketched_lstsq_solve(A, b, lam=lam, mesh=mesh, tol=1e-6)
        jax.block_until_ready(W_sketch)
        out["sketch_solve_s"] = round(time.perf_counter() - t0, 3)
        ex_err, sk_err = test_error(W_exact), test_error(W_sketch)
        out["exact_test_error"] = round(ex_err, 6)
        out["sketch_test_error"] = round(sk_err, 6)
        # the contract key: ~0 when the preconditioned iteration converged
        # to the exact rung's test error (the equal-test-error claim)
        out["sketch_vs_exact_error_delta_d65536"] = round(
            sk_err - ex_err, 6
        )
    return out


def _extraction_kernels() -> dict:
    """Pallas-vs-XLA GFLOPs for the extraction kernel family
    (``ops/pallas/extraction.py``): ``sift_pallas_{on,off}_gflops`` (the
    fused orientation-binning × selection matmul vs the backend-best XLA
    form) and ``fv_encode_pallas_{on,off}_gflops`` (the fused posterior ×
    moment kernel vs the XLA batch encoder). Latency-cancelled like the
    solver ladder; each arm forces its implementation explicitly
    (``impl=`` / tile args), so the rows measure the kernels, not the knob
    plumbing. Off-TPU the Pallas arm runs in interpret mode — orders of
    magnitude slow, so shapes shrink to keep the row seconds-scale and the
    artifact records the backend next to the numbers (a CPU on/off pair
    documents interpret overhead, not a kernel regression). Budget
    derating rides the subprocess timeout bench.py hands this regime."""
    import bench  # configures the XLA compile cache; holds _SMOKE
    import jax
    import jax.numpy as jnp

    from keystone_tpu.ops.images.sift import (
        NUM_BIN_S,
        _dsift_single_scale,
        dsift_geometry,
    )
    from keystone_tpu.ops.images import fisher_vector as FV
    from keystone_tpu.ops.images.fisher_vector import _fv_cols_batch_pallas
    from keystone_tpu.learning.gmm import GaussianMixtureModel
    from keystone_tpu.ops.pallas.extraction import (
        conv_norm_pool,
        conv_pool_plan,
        fv_encode_plan,
        sift_bins_plan,
    )

    smoke = bench._SMOKE
    tpu = jax.default_backend() == "tpu"
    small = smoke or not tpu
    out: dict = {"extraction_backend": jax.default_backend()}
    key = jax.random.key(0)

    # --- SIFT binning: fused kernel vs backend-best XLA form -----------
    b, hw = (2, 48) if small else (256, 96)
    step, bin_size, min_bound = 3, 4, 9
    imgs = jax.random.uniform(key, (b, hw, hw), jnp.float32)
    ny, nx = dsift_geometry(hw, hw, step, bin_size, min_bound)
    q = nx * NUM_BIN_S
    # both arms share the selection-matmul flop model: binned energies @
    # Mx then the H-axis contraction with My
    flops = 2.0 * b * 8 * hw * hw * q + 2.0 * b * 8 * q * hw * ny * NUM_BIN_S
    # variant honesty: the row times whatever form the search serves, and
    # the artifact names it — a reader can tell a generated-variant win
    # from the hand-written default without opening the cache
    sift_variant, tile = sift_bins_plan(b * hw, hw, q)
    out["sift_bins_variant_winner"] = sift_variant
    iters = 2 if small else 4
    for arm, impl in (("on", "pallas"), ("off", "auto")):
        key_name = f"sift_pallas_{arm}_gflops"
        out[key_name] = _try_gflops(
            key_name,
            lambda i, impl=impl: _dsift_single_scale(
                imgs + (i * 1e-4), step, bin_size, min_bound, hw, hw,
                impl, tile, "f32", sift_variant,
            )[0],
            flops, iters,
        )

    # --- FV encode: fused kernel vs the XLA batch encoder --------------
    n_img, nd, d, k = (8, 64, 16, 8) if small else (256, 512, 64, 256)
    kk = jax.random.split(key, 4)
    x = jax.random.normal(kk[0], (n_img, nd, d), jnp.float32)
    gmm = GaussianMixtureModel(
        means=jax.random.normal(kk[1], (k, d), jnp.float32),
        variances=1.0 + jax.random.uniform(kk[2], (k, d), jnp.float32),
        weights=jnp.full((k,), 1.0 / k, jnp.float32),
    )
    # posterior gemms (2d-wide affine form) + the two moment contractions
    fv_flops = n_img * nd * (2.0 * 2 * d * k + 2.0 * 2 * k * 2 * d)
    # resolve (and possibly sweep) OUTSIDE timing; record the served form
    out["fv_encode_variant_winner"] = fv_encode_plan(nd, d, k)[0]
    xla_twin = FV._fv_cols_batch_mxu if tpu else FV._fv_cols_batch_f32
    for arm, fn in (("on", _fv_cols_batch_pallas), ("off", xla_twin)):
        key_name = f"fv_encode_pallas_{arm}_gflops"
        out[key_name] = _try_gflops(
            key_name,
            lambda i, fn=fn: fn(x + (i * 1e-4), gmm, 0, 2 * k),
            fv_flops, iters,
        )

    # --- conv.norm → pool.sum fusion span: fused kernel vs split pair ---
    cb, ch, cw, cc = (2, 20, 20, 3) if small else (16, 32, 32, 3)
    ksz, nf, stride, pool_size = 5, 64 if small else 256, 2, 3
    cimgs = jax.random.uniform(key, (cb, ch, cw, cc), jnp.float32)
    cfilt = jax.random.normal(key, (nf, ksz * ksz * cc), jnp.float32)
    res_h, res_w = ch - ksz + 1, cw - ksz + 1
    # conv matmuls dominate; pooling's two selection matmuls ride along
    conv_flops = 2.0 * cb * res_h * res_w * ksz * ksz * cc * nf
    cp_variant, cp_tile = conv_pool_plan(
        ch, cw, cc, ksz, nf, stride=stride, pool_size=pool_size
    )
    out["conv_pool_variant_winner"] = cp_variant
    if cp_tile is not None:
        fused_variant = (
            cp_variant if cp_variant.startswith("fused.") else "fused.yx"
        )
        for key_name, variant in (
            ("conv_pool_fused_gflops", fused_variant),
            ("conv_pool_split_gflops", "split"),
        ):
            out[key_name] = _try_gflops(
                key_name,
                lambda i, v=variant: conv_norm_pool(
                    cimgs + (i * 1e-4), cfilt, num_channels=cc,
                    normalize=True, var_constant=10.0, stride=stride,
                    pool_size=pool_size, tile_f=cp_tile, variant=v,
                ),
                conv_flops, iters,
            )
        fused = out.get("conv_pool_fused_gflops")
        split = out.get("conv_pool_split_gflops")
        if fused and split:
            out["conv_pool_fused_vs_split_gflops"] = round(fused / split, 3)
    else:
        out["conv_pool_fused_gflops_skipped"] = "vmem"
    return out


def _serve() -> dict:
    """The serving-gateway saturation sweep (sustained QPS at the SLO +
    the 3-point saturation curve) in a fresh OS process. Moved out of
    bench.py's in-process flow for the same reason as ``solver_ladder``:
    the sweep's RUNTIME scales with how hard the shed/breaker machinery
    has to work on a contended host, and the in-process gate checked only
    the entry floor. As a subprocess it gets the derated timeout/skip
    treatment; the admission-path compile caches start cold here, which
    is also the honest regime (a serving process warms its OWN ladder)."""
    import bench

    return bench._try_serve_rows()


def _drive_fleet(routes, seconds, per_route, window=8, seed0=0):
    """Closed-loop cross-PROCESS load: ``per_route`` jax-free client
    subprocesses (``scripts/front_client.py``) per replica socket, each
    keeping ``window`` requests outstanding (pipelined; shed slots back
    off by the server's retry hint) and printing one JSON result line.
    Returns ``[(socket_path, result)]``."""
    import subprocess

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "front_client.py"
    )
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # clients are numpy-only; no sim devices
    procs = []
    for ci in range(per_route):  # route-major: clients spread evenly
        for path in routes:
            procs.append((path, subprocess.Popen(
                [sys.executable, script, "--drive", path,
                 "--seconds", str(seconds),
                 "--window", str(window),
                 "--seed", str(seed0 + len(procs))],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, env=env,
            )))
    results = []
    for path, proc in procs:
        stdout, _ = proc.communicate(timeout=60 + seconds * 10)
        line = stdout.strip().splitlines()[-1] if stdout.strip() else "{}"
        results.append((path, json.loads(line)))
    return results


def _fleet() -> dict:
    """The fleet regime: aggregate-QPS scaling across replicated gateways
    at pinned p99 with zero steady-state recompiles.

    Three fleet configurations, all driven by cross-process jax-free
    clients (``scripts/front_client.py``) against each replica's
    :class:`~keystone_tpu.serve.front.BatchingFront` socket:

    - 1 replica, full micro-batch ladder, 2 pipelined clients ->
      ``fleet_front_batched_qps`` (many client PROCESSES coalesced into
      one gateway's ladder, top rung sized below the offered window so
      the server never answers the whole window in one burst);
    - same load, ladder pinned to batch=1 -> ``fleet_front_unbatched_qps``
      (the N-clients-no-batching baseline; ``fleet_coalesce_gain`` is the
      ratio);
    - 1 replica vs ``KEYSTONE_SERVE_REPLICAS`` replicas at the SAME total
      offered load (2 clients per would-be replica) -> ``fleet_qps_1``,
      ``fleet_qps_N`` and the scaling ratchet ``fleet_qps_scale``.

    Honesty keys: ``fleet_replica_qps`` (per-replica breakdown — a
    1-replica-does-everything "fleet" can't hide), ``fleet_recompiles``
    (sum of per-replica compile-cache growth across every measured drive;
    the zero-steady-state-recompile pin), ``fleet_p99_ms_{1,N}`` client-
    side with ``fleet_p99_pinned`` checked on the arms that are SUPPOSED
    to hold the ``fleet_p99_pin_ms`` SLO (the saturated single-gateway
    arm is allowed to blow it — that it does while the replicated arm
    holds it is the point), and ``fleet_cpu_count``: replica scaling is
    bounded by cores, so a 1-core host reads scale ~1x honestly rather
    than faking a ratio. Budget derating rides the subprocess timeout."""
    import bench
    from keystone_tpu.serve.fleet import Fleet

    smoke = bench._SMOKE
    # drives shorter than ~2s are dominated by the window-fill transient
    # on a contended host; smoke keeps the warm pass short instead
    seconds = 2.0 if smoke else 3.0
    warm_s = 0.4 if smoke else 1.0
    window = 8
    replicas = int(knobs.get("KEYSTONE_SERVE_REPLICAS"))
    # the declared pin: replicas shed at this SLO, so client-side p99 of
    # OK responses is bounded by queue-wait + dispatch under it
    pin_ms = float(knobs.get("KEYSTONE_SERVE_SLO_MS"))
    # empirically validated single-core config: coalescing from the
    # natural queue (window=0ms — a timed wait is a scheduler round-trip
    # under contention), depth above the offered window so steady-state
    # load is not shed, top ladder rung ~half the total outstanding
    # window so server bursts interleave with client turnaround
    base = dict(coalesce_ms=0.0, queue_depth=64, shapes="1,4,8")
    out: dict = {
        "fleet_replicas": replicas,
        "fleet_p99_pin_ms": pin_ms,
        "fleet_cpu_count": os.cpu_count(),
        "fleet_window": window,
    }

    def measure(n_replicas, total_clients, seed0, **overrides):
        kw = dict(base)
        kw.update(overrides)
        per_route = max(1, total_clients // n_replicas)
        with Fleet("cosine", replicas=n_replicas, slo_ms=pin_ms, **kw) as f:
            _drive_fleet(f.routes(), warm_s, 1, window=4,
                         seed0=seed0)  # warm est_ms + ladder
            ccs0 = sum(
                r.get("compile_cache_size", 0)
                for r in f.stats()["replicas"].values() if not r.get("dead")
            )
            # best-of-2 drives against the SAME warm fleet: a 1-core
            # host's scheduler noise swings a 2 s drive by ~2x, and the
            # best pass is the honest capacity reading (the recompile
            # pin still sums over BOTH drives)
            best = None
            for rep in range(2):
                res = _drive_fleet(f.routes(), seconds, per_route,
                                   window=window,
                                   seed0=seed0 + 100 * (rep + 1))
                by_route: dict = {}
                for path, r in res:
                    by_route.setdefault(path, []).append(r)
                per_replica = [
                    round(sum(r.get("qps", 0.0) for r in rs), 1)
                    for _, rs in sorted(by_route.items())
                ]
                qps = sum(per_replica)
                p99 = max(
                    (r.get("p99_ms") or 0.0 for _, r in res), default=0.0)
                n_ok = sum(r.get("n_ok", 0) for _, r in res)
                if best is None or qps > best[0]:
                    best = (qps, p99, per_replica, n_ok)
            ccs1 = sum(
                r.get("compile_cache_size", 0)
                for r in f.stats()["replicas"].values() if not r.get("dead")
            )
            qps, p99, per_replica, n_ok = best
            return qps, p99, per_replica, ccs1 - ccs0, n_ok

    recompiles = 0
    # --- coalesce gain: 2 clients on one gateway, ladder vs batch=1 ---
    qps_b, p99_b, _, rec, ok_b = measure(1, 2, seed0=0)
    recompiles += rec
    out["fleet_front_batched_qps"] = round(qps_b, 1)
    out["fleet_front_p99_ms"] = round(p99_b, 3)
    qps_unb, _, _, rec, _ = measure(1, 2, seed0=300, shapes="1")
    recompiles += rec
    out["fleet_front_unbatched_qps"] = round(qps_unb, 1)
    if qps_unb > 0:
        out["fleet_coalesce_gain"] = round(qps_b / qps_unb, 2)
    # --- replica scaling: same total offered load, 1 vs N replicas ---
    total_clients = 2 * replicas
    out["fleet_clients_total"] = total_clients
    qps1, p99_1, _, rec, ok1 = measure(1, total_clients, seed0=600)
    recompiles += rec
    out["fleet_qps_1"] = round(qps1, 1)
    out["fleet_p99_ms_1"] = round(p99_1, 3)
    qpsN, p99_N, per_replica, rec, okN = measure(
        replicas, total_clients, seed0=900)
    recompiles += rec
    out[f"fleet_qps_{replicas}"] = round(qpsN, 1)
    out[f"fleet_p99_ms_{replicas}"] = round(p99_N, 3)
    out["fleet_replica_qps"] = per_replica
    out["fleet_recompiles"] = recompiles
    out["fleet_p99_pinned"] = bool(
        p99_b <= pin_ms and p99_N <= pin_ms and ok_b > 0 and okN > 0
    )
    if qps1 > 0:
        out["fleet_qps_scale"] = round(qpsN / qps1, 2)
    # --- fleet-wide observability plane: the N-replica config once more
    # with KEYSTONE_TELEMETRY_DIR exported to every worker, so each
    # replica writes its pid+role-unique telemetry shard at exit and the
    # merged view yields SERVER-side keys (fleet_p99_ms is the gateways'
    # own serve.latency_ms histogram quantile — the client-side p99
    # above includes socket turnaround).  Its OWN arm, so span recording
    # never rides the capacity arms; telemetry_merge_procs is the
    # honesty key (how many process shards the merge actually saw).
    import shutil
    import tempfile

    from keystone_tpu.telemetry.fleet import bench_keys
    tdir = tempfile.mkdtemp(prefix="keystone-bench-obs-")
    try:
        measure(replicas, total_clients, seed0=1200,
                env={"KEYSTONE_TELEMETRY_DIR": tdir})
        out.update(bench_keys(tdir))
    finally:
        shutil.rmtree(tdir, ignore_errors=True)
    return out


_REGIMES = {
    "flagship": _flagship,
    "voc_refdim": _voc_refdim,
    "timit_full": _timit_full,
    "solver_overlap": _solver_overlap,
    "solver_ladder": _solver_ladder,
    "sketch_compare": _sketch_compare,
    "extraction_kernels": _extraction_kernels,
    "serve": _serve,
    "fleet": _fleet,
}


def main():
    # Fail-fast env validation (the bench.py contract): a typo'd
    # KEYSTONE_*/BENCH_* value dies here with the knob-named message
    # instead of being silently ignored (or exploding) mid-regime.
    try:
        knobs.validate_environment()
    except ValueError as e:
        print(f"invalid environment: {e}", file=sys.stderr)
        return 2
    if len(sys.argv) != 2 or sys.argv[1] not in _REGIMES:
        print(f"usage: bench_regime.py {{{'|'.join(_REGIMES)}}}",
              file=sys.stderr)
        return 2
    out = _REGIMES[sys.argv[1]]()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
