"""Precision-tier contract smoke (<20 s, CPU): the `make precision-smoke`
rung of `verify-fast`.

Pins, end to end through the REAL entry points:

1. f32-tier byte-identity — with KEYSTONE_PRECISION_TIER unset, the lowered
   normal-equations/BCD programs contain no bf16 and are text-identical to
   an explicit tier="f32" call (the acceptance contract: the default tier
   is the prior program).
2. bf16 parity envelope — the bf16-tier normal-equations/BCD solutions land
   within the documented ~2⁻⁸-operand-rounding envelope of their f32 twins,
   and the bf16 program actually holds bf16 (the tier engaged — the silent
   bf16→f32 drift the A3 intent registry polices).
3. The sketch composition — the bf16 sketch → f32 QR → f32 CG solve's
   error delta vs the f32 tier is an order of magnitude TIGHTER than the
   raw gram delta (the CG-cleanup claim the tier's first-adopter choice
   rests on).
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("KEYSTONE_PRECISION_TIER", None)

t_start = time.monotonic()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> int:
    from keystone_tpu.linalg.bcd import block_coordinate_descent_l2
    from keystone_tpu.linalg.sketch import sketched_lstsq_solve
    from keystone_tpu.linalg.solvers import (
        hdot,
        normal_equations_solve,
        validate_precision,
    )

    A = jax.random.normal(jax.random.key(0), (1024, 128), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (1024, 8), jnp.float32)

    # 1. f32 byte-identity (unset knob == explicit "f32"; no bf16 anywhere)
    lowered_unset = jax.jit(lambda X: hdot(X.T, X, "high")).lower(A).as_text()
    lowered_f32 = (
        jax.jit(lambda X: hdot(X.T, X, "high", tier="f32")).lower(A).as_text()
    )
    assert lowered_unset == lowered_f32, "f32 tier is not the prior program"
    assert "bf16" not in lowered_unset, "bf16 leaked into the f32 tier"

    # 2. bf16 parity envelope + engagement
    lowered_bf16 = (
        jax.jit(lambda X: hdot(X.T, X, tier="bf16")).lower(A).as_text()
    )
    assert "bf16" in lowered_bf16, "bf16 tier did not engage"
    w32 = normal_equations_solve(A, b, lam=1.0)
    w16 = normal_equations_solve(A, b, lam=1.0, tier="bf16")
    ne_delta = float(jnp.linalg.norm(w16 - w32) / jnp.linalg.norm(w32))
    assert ne_delta < 0.02, f"normal-equations bf16 delta {ne_delta}"
    wb32 = block_coordinate_descent_l2(A, b, 1.0, 32)
    wb16 = block_coordinate_descent_l2(A, b, 1.0, 32, tier="bf16")
    bcd_delta = float(jnp.linalg.norm(wb16 - wb32) / jnp.linalg.norm(wb32))
    assert bcd_delta < 0.02, f"BCD bf16 delta {bcd_delta}"

    # 3. sketch composition: CG cleanup tightens the bf16 rounding by >=10x
    gram_delta = float(np.linalg.norm(
        np.asarray(hdot(A.T, A, tier="bf16"), np.float64)
        - np.asarray(hdot(A.T, A, "high"), np.float64)
    ) / np.linalg.norm(np.asarray(hdot(A.T, A, "high"), np.float64)))
    ws32 = sketched_lstsq_solve(A, b, lam=1.0, tol=1e-6, max_iters=50)
    ws16 = sketched_lstsq_solve(
        A, b, lam=1.0, tol=1e-6, max_iters=50, tier="bf16"
    )
    sk_delta = float(jnp.linalg.norm(ws16 - ws32) / jnp.linalg.norm(ws32))
    assert sk_delta < gram_delta / 10.0, (
        f"CG cleanup did not restore accuracy: sketch delta {sk_delta} vs "
        f"gram delta {gram_delta}"
    )

    # the two precision vocabularies stay disjoint (the disambiguation)
    try:
        validate_precision("bf16")
    except ValueError as e:
        assert "KEYSTONE_PRECISION_TIER" in str(e)
    else:
        raise AssertionError("validate_precision accepted a tier string")

    elapsed = time.monotonic() - t_start
    print(
        f"precision-smoke OK in {elapsed:.1f}s: f32 byte-identical; "
        f"ne_delta={ne_delta:.2e} bcd_delta={bcd_delta:.2e} "
        f"gram_delta={gram_delta:.2e} sketch_delta={sk_delta:.2e} "
        f"(cleanup {gram_delta / max(sk_delta, 1e-12):.0f}x tighter)"
    )
    assert elapsed < 20.0, f"smoke took {elapsed:.1f}s (>20s contract)"
    return 0


if __name__ == "__main__":
    sys.exit(main())
